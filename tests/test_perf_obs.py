"""Performance flight recorder + trend gate (fedml_tpu/obs/perf.py,
fedml_tpu/obs/trend.py) — the ISSUE 6 acceptance pins:

* ledger schema: every ``perf.jsonl`` line carries round / phases /
  wire deltas / RSS watermark / recompile verdict, written as ONE
  append so readers tolerate at most a torn tail;
* RSS sampler: start/stop idempotent, no thread leaks, per-round
  watermark protocol;
* recompile sentry: silent across clean rounds, fires on a forced
  re-jit, hard-fails under strict mode BEFORE a misleading clean
  ledger line can be written;
* trend gate: passes on identical ledgers, fails (named phase,
  non-zero exit) on a seeded +50% regression, and the mfu <= 1.0 lint
  refuses unretracted impossible values — the exact contract
  ``bench._max_mfu`` delegates to;
* SLO evaluator: breach counters + the serve frontend's
  ``/healthz?deep=1`` path (200 holding, 503 + verdict on breach).
"""

import http.client
import json
import os
import threading

import numpy as np
import pytest

from fedml_tpu.obs import telemetry, trend
from fedml_tpu.obs.perf import (DEFAULT_SLOS, PerfRecorder, RecompileError,
                                RecompileSentry, RssSampler, SloEvaluator,
                                histogram_quantile, parse_slo_spec,
                                read_rss_bytes)


class _FakeJit:
    """A hot function whose jit cache the test grows at will."""

    def __init__(self, n=1):
        self.n = n

    def _cache_size(self):
        return self.n


def _reg():
    return telemetry.TelemetryRegistry()


# ---------------------------------------------------------------------------
# ledger schema + atomic writes
# ---------------------------------------------------------------------------

def test_ledger_schema_and_per_round_lines(tmp_path):
    reg = _reg()
    out = reg.counter("fedml_comm_send_bytes_total", link="0->1")
    inn = reg.counter("fedml_comm_wire_bytes_total", link="1->0")
    rec = PerfRecorder(str(tmp_path / "perf.jsonl"), node="server",
                       registry=reg)
    try:
        for r in range(2):
            rec.round_start(r)
            out.inc(100)
            inn.inc(40)
            with rec.phase("broadcast_serialize"):
                pass
            # re-entering a phase ACCUMULATES (admission runs per upload)
            rec.add_phase("admission", 0.01)
            rec.add_phase("admission", 0.02)
            line = rec.round_end(r, quorum=3)
            assert line["quorum"] == 3
    finally:
        rec.close()

    with open(rec.path) as f:
        rows = [json.loads(l) for l in f]          # every line parses
    assert [r["round"] for r in rows] == [0, 1]
    assert trend.validate_ledger(rows) == []       # full schema
    for row in rows:
        assert row["node"] == "server"
        assert row["round_s"] > 0
        assert row["phases"]["admission"] == pytest.approx(0.03)
        assert "broadcast_serialize" in row["phases"]
        # wire deltas are PER ROUND, not cumulative
        assert row["wire"] == {"bytes_out": 100, "bytes_in": 40}
        assert row["recompiles"] == 0
        if read_rss_bytes() is not None:           # Linux: watermark real
            assert row["rss"]["peak_bytes"] > 0
    # phase histograms + round counter exported
    snap = reg.snapshot()
    assert snap["counters"]["fedml_perf_rounds_total"] == 2
    assert any(k.startswith("fedml_perf_phase_seconds")
               for k in snap["histograms"])


def test_ledger_round_end_without_start_is_noop(tmp_path):
    rec = PerfRecorder(str(tmp_path / "perf.jsonl"), registry=_reg())
    try:
        assert rec.round_end(0) is None
        assert not os.path.exists(rec.path)
    finally:
        rec.close()


def test_ledger_reader_tolerates_torn_tail_only(tmp_path):
    rec = PerfRecorder(str(tmp_path / "perf.jsonl"), registry=_reg())
    try:
        for r in range(3):
            rec.round_start(r)
            rec.round_end(r)
    finally:
        rec.close()
    with open(rec.path, "a") as f:
        f.write('{"round": 3, "pha')          # crash mid-write
    rows = trend.load_ledger(rec.path)
    assert [r["round"] for r in rows] == [0, 1, 2]
    # a torn line ANYWHERE ELSE is corruption, not a crash artifact
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"round": 0}\n{"torn\n{"round": 2}\n')
    with pytest.raises(ValueError, match="malformed"):
        trend.load_ledger(str(bad))


# ---------------------------------------------------------------------------
# RSS sampler
# ---------------------------------------------------------------------------

def test_rss_sampler_lifecycle_no_thread_leak():
    def sampler_threads():
        return [t for t in threading.enumerate()
                if t.name == "perf-rss-sampler"]

    n0 = len(sampler_threads())
    s = RssSampler(interval_s=0.005)
    s.start()
    s.start()                              # idempotent
    if read_rss_bytes() is None:
        pytest.skip("no /proc on this platform")
    assert len(sampler_threads()) == n0 + 1
    s.sample()
    assert s.peak_bytes > 0
    first = s.reset_peak()
    assert first > 0
    # after a reset the watermark restarts from a FRESH sample, not 0
    s.sample()
    assert s.peak_bytes > 0
    s.stop()
    s.stop()                               # idempotent
    assert len(sampler_threads()) == n0    # joined, not leaked


def test_recorder_close_stops_sampler(tmp_path):
    rec = PerfRecorder(str(tmp_path / "p.jsonl"), registry=_reg())
    rec.round_start(0)                     # starts the sampler thread
    rec.round_end(0)
    rec.close()
    rec.close()                            # safe to call twice
    assert not any(t.name == "perf-rss-sampler"
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# recompile sentry
# ---------------------------------------------------------------------------

def test_sentry_silent_on_clean_rounds_counts_growth():
    reg = _reg()
    sentry = RecompileSentry(registry=reg)
    fn = _FakeJit(1)
    assert sentry.register("agg", fn)
    assert sentry.check(0) == {}           # baseline round
    for r in (1, 2, 3):
        assert sentry.check(r) == {}       # 3 clean rounds: silent
    fn.n = 3
    assert sentry.check(4) == {"agg": 2}
    assert reg.snapshot()["counters"]["fedml_perf_recompiles_total"] == 2
    # a shrunk cache (explicit clear) re-baselines silently
    fn.n = 1
    assert sentry.check(5) == {}
    fn.n = 2
    assert sentry.check(6) == {"agg": 1}


def test_sentry_fires_on_forced_rejit():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2.0)
    f(jnp.ones((4,)))
    sentry = RecompileSentry(registry=_reg())
    if not sentry.register("f", f):
        pytest.skip("this jax version exposes no _cache_size probe")
    assert sentry.check(0) == {}
    for r in (1, 2, 3):
        f(jnp.ones((4,)))                  # cache hit
        assert sentry.check(r) == {}
    f(jnp.ones((8,)))                      # new shape → retrace
    assert sentry.check(4) == {"f": 1}


def test_sentry_skips_functions_without_probe():
    sentry = RecompileSentry(registry=_reg())
    assert not sentry.register("plain", lambda x: x)
    assert sentry.names() == []
    assert sentry.check(0) == {}


def test_strict_sentry_raises_before_ledger_line(tmp_path):
    """The strict verdict must fire BEFORE the round's ledger line is
    written — a recompiling round must never ledger as clean."""
    rec = PerfRecorder(str(tmp_path / "perf.jsonl"), registry=_reg(),
                       strict_recompiles=True)
    fn = _FakeJit(1)
    assert rec.register_jit("agg", fn)
    try:
        rec.round_start(0)
        assert rec.round_end(0)["recompiles"] == 0   # baseline: fine
        rec.round_start(1)
        fn.n = 2
        with pytest.raises(RecompileError, match="retracing"):
            rec.round_end(1)
    finally:
        rec.close()
    rows = trend.load_ledger(rec.path)
    assert [r["round"] for r in rows] == [0]         # no misleading line


# ---------------------------------------------------------------------------
# trend gate
# ---------------------------------------------------------------------------

def _write_ledger(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return str(path)


def _rows(agg_s=0.2, n=4, recompiles=0):
    return [{"round": i, "round_s": agg_s + 0.1,
             "phases": {"defended_aggregate": agg_s,
                        "broadcast_serialize": 0.05},
             "wire": {"bytes_out": 10, "bytes_in": 10},
             "rss": {"peak_bytes": 1 << 20},
             "recompiles": recompiles if i else 0}
            for i in range(n)]


def test_trend_gate_passes_identical_fails_seeded_regression(tmp_path,
                                                             capsys):
    base = _write_ledger(tmp_path / "base.jsonl", _rows(0.2))
    same = _write_ledger(tmp_path / "same.jsonl", _rows(0.2))
    slow = _write_ledger(tmp_path / "slow.jsonl", _rows(0.3))  # +50%

    assert trend.main(["--ledger", same, "--baseline", base]) == 0
    assert trend.main(["--ledger", slow, "--baseline", base]) == 1
    out = capsys.readouterr().out
    assert "phase regression: defended_aggregate" in out
    assert "1.50x" in out


def test_trend_gate_noise_band_and_abs_floor(tmp_path):
    base = _rows(0.2)
    # +20% stays inside the default +25% band
    within = _write_ledger(tmp_path / "w.jsonl", _rows(0.24))
    basep = _write_ledger(tmp_path / "b.jsonl", base)
    assert trend.main(["--ledger", within, "--baseline", basep]) == 0
    # a 2ms phase doubling trips the relative band but not the absolute
    # floor — noise, not a regression
    tiny_b = _write_ledger(tmp_path / "tb.jsonl", [
        {**r, "phases": {"publish": 0.002}} for r in base])
    tiny_c = _write_ledger(tmp_path / "tc.jsonl", [
        {**r, "phases": {"publish": 0.004}} for r in base])
    assert trend.main(["--ledger", tiny_c, "--baseline", tiny_b]) == 0


def test_trend_gate_recompile_after_round0_fails(tmp_path, capsys):
    led = _write_ledger(tmp_path / "r.jsonl", _rows(0.2, recompiles=1))
    assert trend.main(["--ledger", led]) == 1
    assert "recompile gate" in capsys.readouterr().out
    assert trend.main(["--ledger", led, "--no_recompile_gate"]) == 0


def test_trend_gate_missing_inputs_exit_2(tmp_path, capsys):
    assert trend.main(["--ledger", str(tmp_path / "absent.jsonl")]) == 2
    assert trend.main([]) == 2
    capsys.readouterr()


def test_trend_schema_validation_names_missing_keys(tmp_path):
    rows = [{"round": 0, "phases": {}}]            # no recompiles/wire
    problems = trend.validate_ledger(rows)
    assert any("recompiles" in p for p in problems)
    assert any("wire" in p for p in problems)
    assert trend.validate_ledger([]) == ["ledger is empty"]


# ---------------------------------------------------------------------------
# mfu lint (+ the bench delegation contract)
# ---------------------------------------------------------------------------

def test_mfu_lint_refuses_unretracted_over_one(tmp_path, capsys):
    bad = tmp_path / "BENCH_bad.json"
    bad.write_text(json.dumps(
        {"configs": {"a": {"mfu": 1.57}, "b": {"mfu": 0.3}}}))
    violations = trend.lint_mfu_artifacts([str(bad)])
    assert len(violations) == 1 and "1.57" in violations[0]
    assert trend.main(["--lint_mfu", str(bad)]) == 1
    assert "mfu lint" in capsys.readouterr().out


def test_mfu_lint_retraction_markers_are_sticky_downward(tmp_path):
    ok = tmp_path / "BENCH_ok.json"
    ok.write_text(json.dumps({
        "cohort_scaling": {"128": {
            "mfu": 1.57,
            "mfu_retracted": "timing retracted, see ROUND_NOTES"}},
        "quarantined": {"timing_untrusted": "broken timer",
                        "nested": [{"mfu": 3.08}]},
        "configs": {"a": {"mfu": 0.9}}}))
    assert trend.lint_mfu_artifacts([str(ok)]) == []
    assert trend.main(["--lint_mfu", str(ok)]) == 0


def test_mfu_lint_unreadable_artifact_is_a_violation(tmp_path):
    missing = str(tmp_path / "nope.json")
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    violations = trend.lint_mfu_artifacts([missing, str(garbage)])
    assert len(violations) == 2
    assert all("unreadable" in v for v in violations)


def test_max_mfu_recursive_and_ignores_retraction():
    art = {"configs": {"a": {"mfu": 0.3}},
           "cohort_scaling": {"128": {"mfu": 1.57, "mfu_retracted": "yes"}},
           "deep": [{"nested": {"mfu": 0.7}}]}
    # retraction markers make the LINT green but never hide the value
    # from max_mfu — a refused artifact stays refused
    assert trend.max_mfu(art) == pytest.approx(1.57)
    assert trend.max_mfu({}) == 0.0


def test_bench_max_mfu_delegates_to_trend():
    """bench's promotion refusal and the CI lint must share one scan —
    a nested cell counts in both or neither."""
    import bench
    art = {"configs": {"a": {"mfu": 0.3}},
           "cohort_scaling": {"64": {"mfu": 0.9}},
           "scaling_curve_v2": [{"mfu": 1.2}]}     # nested, non-canonical
    assert bench._max_mfu(art) == trend.max_mfu(art) == pytest.approx(1.2)


# ---------------------------------------------------------------------------
# SLO evaluator + deep health
# ---------------------------------------------------------------------------

def test_histogram_quantile():
    assert histogram_quantile({}, 0.95) is None
    stats = {"count": 100, "max": 9.0,
             "buckets": {"0.1": 50, "0.5": 45, "1.0": 0, "+Inf": 5}}
    assert histogram_quantile(stats, 0.5) == pytest.approx(0.1)
    assert histogram_quantile(stats, 0.95) == pytest.approx(0.5)
    # the +Inf tail falls back to the observed max
    assert histogram_quantile(stats, 0.999) == pytest.approx(9.0)


def test_parse_slo_spec():
    assert parse_slo_spec("") == {}
    spec = parse_slo_spec("serve_shed_rate=0.01, quarantine_rate=2")
    assert spec == {"serve_shed_rate": 0.01, "quarantine_rate": 2.0}
    with pytest.raises(ValueError, match="unknown SLO"):
        parse_slo_spec("tpyo_rate=1")
    with pytest.raises(ValueError, match="name=value"):
        parse_slo_spec("just_a_name")


def test_slo_evaluator_breach_counters_and_overrides():
    reg = _reg()
    reg.counter("fedml_serve_requests_total").inc(100)
    reg.counter("fedml_serve_shed_total").inc(50)
    ev = SloEvaluator(registry=reg)
    verdict = ev.evaluate()
    assert set(verdict) == set(DEFAULT_SLOS)
    assert verdict["serve_shed_rate"]["value"] == pytest.approx(0.5)
    assert not verdict["serve_shed_rate"]["ok"]
    assert verdict["torn_frame_rate"]["ok"]       # no traffic: vacuous
    assert not ev.healthy()
    snap = reg.snapshot()
    assert snap["gauges"]["fedml_slo_serve_shed_ratio"] \
        == pytest.approx(0.5)
    breaches = [v for k, v in snap["counters"].items()
                if k.startswith("fedml_slo_breaches_total")
                and "serve_shed_rate" in k]
    assert breaches and breaches[0] >= 1
    # a deployment that tolerates 60% shed passes the same registry
    lax = SloEvaluator(registry=reg, thresholds={"serve_shed_rate": 0.6})
    assert lax.healthy()
    with pytest.raises(ValueError, match="unknown SLO"):
        SloEvaluator(registry=reg, thresholds={"nope": 1.0})


def test_slo_round_duration_p95_from_histograms():
    reg = _reg()
    h = reg.histogram("fedml_round_duration_seconds")
    for _ in range(20):
        h.observe(0.2)
    ev = SloEvaluator(registry=reg,
                      thresholds={"round_duration_p95_seconds": 0.1})
    verdict = ev.evaluate()
    assert verdict["round_duration_p95_seconds"]["value"] >= 0.2
    assert not verdict["round_duration_p95_seconds"]["ok"]


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, json.loads(body) if body.startswith(b"{") else body


def test_deep_healthz_http_path():
    from fedml_tpu.serve import MicroBatcher, ModelRegistry, ServeFrontend

    reg = _reg()
    slo = SloEvaluator(registry=reg)
    registry = ModelRegistry(lambda p, x: x, history=8)
    batcher = MicroBatcher(registry, buckets=(1,))
    frontend = ServeFrontend(registry, batcher, port=0, slo=slo).start()
    try:
        port = frontend.port
        registry.publish({"w": np.ones(2, np.float32)}, 0)
        # shallow stays shallow; deep evaluates and holds
        status, body = _get(port, "/healthz")
        assert status == 200 and "slo" not in body
        status, body = _get(port, "/healthz?deep=1")
        assert status == 200 and body["status"] == "ok"
        assert body["slo"]["serve_shed_rate"]["ok"]
        # breach the shed SLO → deep probes 503 with the verdict, so an
        # LB rotates out an instance that is up but violating objectives
        reg.counter("fedml_serve_requests_total").inc(100)
        reg.counter("fedml_serve_shed_total").inc(50)
        status, body = _get(port, "/healthz?deep=1")
        assert status == 503 and body["status"] == "slo_breach"
        assert not body["slo"]["serve_shed_rate"]["ok"]
        # shallow probes still answer 200 — liveness is not SLO health
        status, _ = _get(port, "/healthz")
        assert status == 200
    finally:
        frontend.stop(drain=False)


def test_deep_healthz_unconfigured():
    from fedml_tpu.serve import MicroBatcher, ModelRegistry, ServeFrontend

    registry = ModelRegistry(lambda p, x: x, history=8)
    frontend = ServeFrontend(registry, MicroBatcher(registry, buckets=(1,)),
                             port=0).start()
    try:
        registry.publish({"w": np.ones(2, np.float32)}, 0)
        status, body = _get(frontend.port, "/healthz?deep=1")
        assert status == 200 and body["deep"] == "unconfigured"
    finally:
        frontend.stop(drain=False)


# ---------------------------------------------------------------------------
# telemetry HTTP endpoint hardening (satellite: bind failure + /healthz)
# ---------------------------------------------------------------------------

def test_start_http_server_bind_failure_returns_none():
    reg = _reg()
    first = telemetry.start_http_server(0, reg, host="127.0.0.1")
    assert first is not None
    try:
        port = first.server_address[1]
        # same port again: warn-and-None, never an exception that would
        # kill a training run over its scrape endpoint
        assert telemetry.start_http_server(port, reg,
                                           host="127.0.0.1") is None
        # and the surviving server answers /healthz beside /metrics
        reg.counter("fedml_comm_send_total").inc(3)
        status, body = _get(port, "/healthz")
        assert status == 200 and body["status"] == "ok"
        status, body = _get(port, "/metrics")
        assert status == 200 and b"fedml_comm_send_total 3" in body
    finally:
        first.shutdown()
        first.server_close()


# ---------------------------------------------------------------------------
# report merger hardening (satellite: --merge_trace clean no-op)
# ---------------------------------------------------------------------------

def test_merge_trace_missing_or_empty_dir_is_clean_noop(tmp_path, capsys):
    from fedml_tpu.obs import report

    out = tmp_path / "merged.json"
    # missing dir: no output file, message instead of an error
    assert report.merge_traces(str(tmp_path / "absent"), str(out)) is None
    assert not out.exists()
    # empty dir: same
    empty = tmp_path / "empty"
    empty.mkdir()
    assert report.merge_traces(str(empty), str(out)) is None
    assert not out.exists()
    # the CLI stays exit-0 and says so
    assert report.main(["--merge_trace", str(out),
                        "--trace_dir", str(empty)]) == 0
    assert "nothing written" in capsys.readouterr().out
    assert report.main(["--merge_trace", str(out)]) == 0
    assert "nothing to merge" in capsys.readouterr().out


def test_ledger_rotates_previous_run_instead_of_appending(tmp_path):
    """Two runs at the same path must not splice into one ledger — the
    second run's compile-paying round 0 would land mid-file and poison
    the trend gate's skip-first-round medians."""
    path = str(tmp_path / "perf.jsonl")
    first = PerfRecorder(path, registry=_reg())
    try:
        first.round_start(0)
        first.round_end(0)
    finally:
        first.close()
    second = PerfRecorder(path, registry=_reg())
    try:
        second.round_start(0)
        second.round_end(0)
        second.round_start(1)
        second.round_end(1)
    finally:
        second.close()
    rows = trend.load_ledger(path)
    assert [r["round"] for r in rows] == [0, 1]    # second run only
    prev = trend.load_ledger(path + ".prev")       # first run preserved
    assert [r["round"] for r in prev] == [0]


def test_probe_paths_do_not_count_breaches():
    """Breach counting belongs to the round cadence: `healthy()` and
    `evaluate(count_breaches=False)` (the /healthz?deep=1 path) must
    read the objectives without ticking `fedml_slo_breaches_total` —
    otherwise one sustained breach counts once per LB probe instead of
    once per round and every "breaches > N" alert threshold breaks."""
    reg = _reg()
    reg.counter("fedml_serve_requests_total").inc(100)
    reg.counter("fedml_serve_shed_total").inc(50)
    ev = SloEvaluator(registry=reg)

    def breaches():
        return sum(v for k, v in reg.snapshot()["counters"].items()
                   if k.startswith("fedml_slo_breaches_total"))

    assert not ev.healthy()                        # query: no tick
    ev.evaluate(count_breaches=False)              # probe: no tick
    assert breaches() == 0
    ev.evaluate()                                  # round cadence: ticks
    assert breaches() == 1


def _live_round_phases(tmp_path, aggregate_fn, name):
    """One live 2-silo round through FedAvgServerActor with a recorder;
    returns the single ledger line's phase dict."""
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor)
    from fedml_tpu.comm.local import LocalHub

    hub = LocalHub()
    init = {"w": np.ones(4, np.float32)}
    rec = PerfRecorder(str(tmp_path / name), registry=_reg())
    server = FedAvgServerActor(hub.transport(0), init, 2, 2, 1,
                               aggregate_fn=aggregate_fn, perf=rec)
    server.register_handlers()
    silos = [FedAvgClientActor(i, hub.transport(i),
                               lambda p, c, r: (p, 5)) for i in (1, 2)]
    for s in silos:
        s.register_handlers()
    try:
        server.start()
        hub.pump()
    finally:
        rec.close()
    rows = trend.load_ledger(rec.path)
    assert len(rows) == 1
    return rows[0]["phases"]


def test_aggregate_phase_named_by_what_ran(tmp_path):
    """The ledger names the aggregate span by the code path that ran:
    plain `aggregate` without a defense, `defended_aggregate` only when
    a make_defended_aggregate product is wired — a defended run must
    never trend-compare against an undefended baseline under one
    label."""
    from fedml_tpu.robust.defense import make_defended_aggregate

    phases = _live_round_phases(tmp_path, None, "plain.jsonl")
    assert "aggregate" in phases
    assert "defended_aggregate" not in phases
    defended = make_defended_aggregate("mean", norm_clip=5.0)
    phases = _live_round_phases(tmp_path, defended, "defended.jsonl")
    assert "defended_aggregate" in phases
    assert "aggregate" not in phases


def test_trend_gate_single_round_ledger_is_not_a_regression(tmp_path,
                                                            capsys):
    """A one-round ledger's only line pays the jit compiles; gated
    against a steady-state baseline it must NOT read as a regression —
    the gate says there is nothing steady-state to compare and passes
    (the recompile/schema checks still ran)."""
    base = _write_ledger(tmp_path / "base.jsonl", _rows(0.2))
    smoke = _write_ledger(tmp_path / "smoke.jsonl", _rows(5.0, n=1))
    assert trend.main(["--ledger", smoke, "--baseline", base]) == 0
    assert "no steady-state rounds" in capsys.readouterr().out


def test_report_renders_explicit_perf_ledger_path(tmp_path):
    """`--perf_ledger` points the report at a ledger written outside
    run_dir; an explicitly named ledger with no rows must say so instead
    of silently rendering the run as uninstrumented."""
    from fedml_tpu.obs import report

    led = _write_ledger(tmp_path / "elsewhere.jsonl", _rows(0.2, n=2))
    text = report.render_report(str(tmp_path), None, perf_ledger=led)
    assert "perf ledger" in text
    assert "defended_aggregate"[:14] in text  # phase columns clip to 14
    missing = str(tmp_path / "nope.jsonl")
    text = report.render_report(str(tmp_path), None, perf_ledger=missing)
    assert f"no rows at {missing}" in text
