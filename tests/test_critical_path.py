"""Round critical-path observatory (fedml_tpu/obs/critical_path.py,
ISSUE 17): the attribution sweep partitions a round's wall clock across
the constraint vocabulary, the binding constraint is named correctly
under seeded straggler / slow-fold shapes, the disabled mode stays
zero-allocation, the trend gate accepts both pre- and post-observatory
ledger shapes, and the config gates fail loud.
"""

import gc
import json
import tracemalloc

import numpy as np
import pytest

from fedml_tpu.comm.actors import NodeManager, ServerManager
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.obs import critical_path as cpath
from fedml_tpu.obs import telemetry, trace, trend
from fedml_tpu.obs.perf import PerfRecorder


def _cp():
    """Accumulator with a pinned origin; samples pass explicit t1."""
    return cpath.RoundCriticalPath(t0=0.0, clock=lambda: 0.0)


# ---------------------------------------------------------------------------
# the attribution sweep
# ---------------------------------------------------------------------------

def test_attribution_partitions_wall_clock():
    """Every second of the round lands in exactly one constraint:
    sum(attribution) == round_s, coverage == 1.0 — the >= 0.95 bench
    gate holds by construction, not by luck."""
    cp = _cp()
    cp.note_arrival(t=1.0)
    cp.note("decode", 1.0, t1=2.0)
    cp.note_arrival(t=4.0)
    cp.note("fold", 3.0, t1=5.0)
    rec = cp.finalize(duration=10.0)
    assert sum(rec["attribution"].values()) == pytest.approx(10.0)
    assert rec["coverage"] == pytest.approx(1.0)
    assert rec["round_s"] == pytest.approx(10.0)
    assert rec["uploads"] == 2
    # [0,1) pre-first-arrival idle -> network; [1,2) decode; [2,5) fold;
    # [5,10) post-last-arrival idle -> barrier_wait
    assert rec["attribution"]["network"] == pytest.approx(1.0)
    assert rec["attribution"]["decode"] == pytest.approx(1.0)
    assert rec["attribution"]["fold"] == pytest.approx(3.0)
    assert rec["attribution"]["barrier_wait"] == pytest.approx(5.0)
    assert rec["binding"] == "barrier_wait"
    assert cpath.validate_record(rec) == []


def test_straggler_binding_under_seeded_slow_silo():
    """A quorum trickling in (first upload early, last upload late, the
    host idle in between) must name ``straggler``, not network."""
    cp = _cp()
    cp.note_arrival(t=1.0)
    cp.note("fold", 0.5, t1=1.5)
    cp.note_arrival(t=9.0)
    cp.note("fold", 0.5, t1=9.5)
    rec = cp.finalize(duration=10.0)
    assert rec["binding"] == "straggler"
    assert rec["attribution"]["straggler"] == pytest.approx(7.5)
    assert cpath.validate_record(rec) == []


def test_fold_binding_under_seeded_slow_fold():
    """A host that serializes a long fold after the last upload must
    name ``fold`` — and its fold-overlap ratio exposes that none of the
    fold hid behind the network."""
    cp = _cp()
    cp.note_arrival(t=0.5)
    cp.note_arrival(t=1.0)
    cp.note("fold", 7.9, t1=9.0)
    rec = cp.finalize(duration=9.5)
    assert rec["binding"] == "fold"
    assert rec["fold_overlap_ratio"] == pytest.approx(0.0)
    assert cpath.validate_record(rec) == []


def test_fold_overlap_ratio_full_when_fold_hides_behind_wire():
    """Fold busy time entirely inside the arrival window reads 1.0 —
    the aggregation-hidden-behind-the-network number."""
    cp = _cp()
    cp.note_arrival(t=1.0)
    cp.note("fold", 1.0, t1=2.0)
    cp.note_arrival(t=5.0)
    rec = cp.finalize(duration=6.0)
    assert rec["fold_overlap_ratio"] == pytest.approx(1.0)


def test_compile_carved_out_preserves_the_partition():
    """Known compile wall time relabels fold/decode work as ``compile``
    without changing the total."""
    cp = _cp()
    cp.note("fold", 4.0, t1=4.0)
    cp.note_arrival(t=4.0)
    rec = cp.finalize(duration=5.0, compile_s=1.5)
    assert rec["attribution"]["compile"] == pytest.approx(1.5)
    assert rec["attribution"]["fold"] == pytest.approx(2.5)
    assert sum(rec["attribution"].values()) == pytest.approx(5.0)
    assert cpath.validate_record(rec) == []


def test_overlapping_work_segments_take_priority_bucket():
    """Concurrent receive threads: a fold∩decode segment goes to fold
    (the work-priority order), and is never counted twice."""
    cp = _cp()
    cp.note("decode", 2.0, t1=2.0)
    cp.note("fold", 2.0, t1=3.0)     # [1,3) overlaps decode on [1,2)
    cp.note_arrival(t=3.0)
    rec = cp.finalize(duration=3.0)
    assert rec["attribution"]["decode"] == pytest.approx(1.0)
    assert rec["attribution"]["fold"] == pytest.approx(2.0)
    assert sum(rec["attribution"].values()) == pytest.approx(3.0)


def test_phase_vocabulary_mapping():
    """straggler_wait (an idle measurement) is excluded; unknown phase
    names land in fold (host-side round work); the mapped names agree
    with the constraint vocabulary."""
    assert cpath.phase_bucket("straggler_wait") is None
    assert cpath.phase_bucket("some_future_phase") == "fold"
    assert cpath.phase_bucket("decode") == "decode"
    assert cpath.phase_bucket("broadcast_serialize") == "network"
    assert cpath.phase_bucket("admission") == "admission"
    # a cross-device "wave" span is the server *producing* an upload —
    # it plays the network's role in the round (the fold either hides
    # behind it, pipelined, or doesn't), so it buckets as network
    assert cpath.phase_bucket("wave") == "network"
    for name in ("fold", "journal", "unmask", "shard_finalize"):
        assert cpath.phase_bucket(name) == "fold"
    cp = _cp()
    cp.note("straggler_wait", 5.0, t1=5.0)
    rec = cp.finalize(duration=5.0)
    assert "fold" not in rec["attribution"]


def test_validate_record_rejects_malformed_records():
    assert cpath.validate_record("nope") == ["critical_path: not a dict"]
    bad_binding = {"binding": "vibes", "attribution": {}, "coverage": 1.0,
                   "round_s": 1.0}
    assert any("binding" in p for p in cpath.validate_record(bad_binding))
    lying_coverage = {"binding": "fold", "attribution": {"fold": 0.2},
                      "coverage": 1.0, "round_s": 1.0}
    assert any("coverage" in p
               for p in cpath.validate_record(lying_coverage))
    unknown_key = {"binding": "fold", "attribution": {"gremlins": 0.5},
                   "coverage": 0.5, "round_s": 1.0}
    assert any("gremlins" in p for p in cpath.validate_record(unknown_key))


# ---------------------------------------------------------------------------
# telemetry export
# ---------------------------------------------------------------------------

def test_ingest_gauges_export():
    reg = telemetry.TelemetryRegistry()
    gauges = cpath.IngestGauges(reg)
    rec = {"binding": "fold", "round_s": 2.0, "uploads": 3,
           "fold_overlap_ratio": 0.75,
           "attribution": {"fold": 1.0, "network": 1.0}, "coverage": 1.0}
    gauges.export(rec, wire_bytes_in=4000)
    snap = reg.snapshot()
    assert snap["gauges"][
        "fedml_ingest_bytes_per_second_value"] == pytest.approx(2000.0)
    assert snap["gauges"][
        "fedml_ingest_fold_overlap_ratio"] == pytest.approx(0.75)
    assert snap["gauges"][
        'fedml_ingest_phase_utilization_ratio{constraint="fold"}'] == \
        pytest.approx(0.5)
    assert snap["gauges"][
        'fedml_ingest_phase_utilization_ratio{constraint="decode"}'] == 0.0
    assert snap["counters"]["fedml_ingest_uploads_total"] == 3


def test_perf_recorder_emits_critical_path_on_every_line(tmp_path):
    """The analyzer rides PerfRecorder: every round_end line carries a
    valid critical_path record, and the ingest gauges land in the SAME
    registry the recorder exports."""
    reg = telemetry.TelemetryRegistry()
    rec = PerfRecorder(str(tmp_path / "perf.jsonl"), registry=reg)
    try:
        for r in range(2):
            rec.round_start(r)
            rec.add_phase("decode", 0.002)
            rec.note_arrival()
            rec.add_phase("fold", 0.003)
            rec.round_end(r)
    finally:
        rec.close()
    with open(rec.path) as f:
        rows = [json.loads(line) for line in f]
    assert len(rows) == 2
    for row in rows:
        cp = row["critical_path"]
        assert cpath.validate_record(cp) == []
        assert cp["coverage"] >= 0.95
        assert cp["uploads"] == 1
        assert cp["binding"] in cpath.CONSTRAINTS
    assert trend.validate_ledger(rows) == []
    assert "fedml_ingest_uploads_total" in reg.snapshot()["counters"]


def test_live_federation_rounds_carry_critical_path(tmp_path):
    """End to end on the actor path: a local 2-silo federation with the
    flight recorder writes a critical_path record on every ledger line,
    with one arrival per upload and >= 95% coverage."""
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor)
    reg = telemetry.TelemetryRegistry()
    perf = PerfRecorder(str(tmp_path / "perf.jsonl"), registry=reg)
    hub = LocalHub(codec_roundtrip=True)
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(3, 2).astype(np.float32)}
    server = FedAvgServerActor(hub.transport(0), params,
                               client_num_in_total=2,
                               client_num_per_round=2,
                               num_rounds=2, perf=perf)
    server.register_handlers()

    def train_fn(p, client_idx, round_idx):
        import jax
        return jax.tree.map(lambda v: v + 1.0, p), 10

    silos = [FedAvgClientActor(i, hub.transport(i), train_fn)
             for i in (1, 2)]
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    perf.close()
    rows = trend.load_ledger(perf.path)
    assert len(rows) == 2
    assert trend.validate_ledger(rows) == []
    for row in rows:
        cp = row["critical_path"]
        assert cp["uploads"] == 2
        assert cp["coverage"] >= 0.95
        assert cp["binding"] in cpath.CONSTRAINTS


# ---------------------------------------------------------------------------
# the cost contract: disabled mode
# ---------------------------------------------------------------------------

def test_disabled_span_helpers_reuse_the_shared_null_context():
    """With tracing and perf off, the instrumented helpers return the
    ONE module-level null context — identity, not equality."""
    assert trace.get_tracer() is None

    class Probe(ServerManager):
        def register_handlers(self):
            pass

    hub = LocalHub()
    mgr = Probe(0, hub.transport(0))
    assert mgr._span("ingest:fold", deterministic=True) \
        is trace.NULL_CONTEXT
    assert mgr._root_span("round") is trace.NULL_CONTEXT
    assert mgr._perf_phase("fold") is trace.NULL_CONTEXT


def test_disabled_mode_is_zero_allocation():
    """The pin behind the bench's disabled-overhead gate: exercising the
    ingest span + arrival helpers with observability off retains NOTHING
    (transients may spike; retained delta must be zero)."""
    assert trace.get_tracer() is None

    class Probe(ServerManager):
        def register_handlers(self):
            pass

    hub = LocalHub()
    mgr = Probe(0, hub.transport(0))

    def hot_path():
        for _ in range(200):
            with mgr._span("ingest:decode", deterministic=True):
                pass
            with mgr._perf_phase("decode"):
                pass
            mgr._note_arrival()

    # two warm-up passes: the second crosses the interpreter's adaptive
    # specialization threshold, so the measured pass is steady-state
    hot_path()
    hot_path()
    tracemalloc.start()
    gc.collect()
    before = tracemalloc.take_snapshot()
    hot_path()
    gc.collect()   # collectible cycles are transients, not retention
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    # attribute retained bytes to the observatory's own code — the pin
    # is about what the disabled helpers keep, not interpreter noise
    # elsewhere in a busy pytest process
    flt = [tracemalloc.Filter(True, "*fedml_tpu*")]
    stats = after.filter_traces(flt).compare_to(
        before.filter_traces(flt), "lineno")
    retained = sum(s.size_diff for s in stats)
    assert retained <= 0, \
        f"disabled observability retained {retained} bytes: {stats[:5]}"


# ---------------------------------------------------------------------------
# trend gate: old and new ledger shapes
# ---------------------------------------------------------------------------

def _row(r, critical_path=None):
    row = {"round": r, "round_s": 0.2, "phases": {"fold": 0.1},
           "recompiles": 0, "wire": {"bytes_out": 10, "bytes_in": 10}}
    if critical_path is not None:
        row["critical_path"] = critical_path
    return row


def test_trend_gate_accepts_old_and_new_ledger_shapes():
    old = [_row(0), _row(1)]                      # pre-observatory
    assert trend.validate_ledger(old) == []
    good = {"binding": "fold", "attribution": {"fold": 0.2},
            "coverage": 1.0, "round_s": 0.2, "uploads": 2,
            "fold_overlap_ratio": 0.0}
    new = [_row(0, good), _row(1, good)]
    assert trend.validate_ledger(new) == []


def test_trend_gate_rejects_malformed_critical_path():
    bad = {"binding": "vibes", "attribution": {"fold": 0.2},
           "coverage": 1.0, "round_s": 0.2}
    problems = trend.validate_ledger([_row(0, bad)])
    assert problems and all("critical_path" in p for p in problems)


# ---------------------------------------------------------------------------
# BENCH_ingest schema gate
# ---------------------------------------------------------------------------

def _ingest_bench(**over):
    rec = {"binding": "fold", "attribution": {"fold": 0.2},
           "coverage": 1.0, "round_s": 0.2, "uploads": 2,
           "fold_overlap_ratio": 0.5}
    arm = {"backend": "cpu", "rounds": [dict(rec), dict(rec)],
           "recompiles_after_warmup": 0,
           "gates": {"coverage": {"ok": True, "min": 1.0}}}
    obj = {"bench": "ingest", "version": 1, "smoke": False,
           "arms": {"cross_silo": dict(arm), "cross_device": dict(arm),
                    "sharded": dict(arm), "secagg": dict(arm),
                    "disabled_pin": {"backend": "cpu", "gates":
                                     {"overhead": {"ok": True}}}},
           "pipeline": {"twins": {n: _pipeline_twin(n) for n in
                                  ("waves", "replicated", "sharded")}}}
    obj.update(over)
    return obj


def _pipeline_twin(name):
    """Minimal green `--ingest_pipeline` twin: bit-equal crc sequences,
    0 recompiles, rows that re-derive the waves overlap/wall-clock and
    replicated wire-drain gates, one arena+screen ledger entry each."""
    def _row(r):
        return {"round": r, "global_crc": 7 + r,
                "fold_overlap_ratio": 0.995, "last_arrival_s": 0.1,
                "round_s": 0.1, "bytes_in": 1000, "recompiles": 0}
    twin = {"gates": {"bit_equal_finals": {"ok": True}},
            "inline": {"rows": [_row(0), _row(1)]},
            "pipelined": {"rows": [_row(0), _row(1)]}}
    if name == "sharded":
        twin["pipelined"]["jit_cache_sizes"] = {
            f"ingest_s{s}_{kind}": 1
            for s in range(4) for kind in ("arena", "screen")}
    elif name == "replicated":
        twin["pipelined"]["jit_cache_sizes"] = {"ingest_arena": 1,
                                                "ingest_screen": 1}
    return twin


def test_validate_ingest_bench_accepts_committed_shape():
    assert trend.validate_ingest_bench(_ingest_bench()) == []


def test_validate_ingest_bench_rejects_failures():
    # a failed gate verdict is never excused, even on a smoke artifact
    obj = _ingest_bench(smoke=True)
    obj["arms"]["cross_silo"]["gates"]["coverage"] = {"ok": False}
    assert any("FAILED" in p for p in trend.validate_ingest_bench(obj))
    # a smoke label is refused on the committed trend line
    assert any("smoke" in p for p in trend.validate_ingest_bench(
        _ingest_bench(smoke=True), allow_smoke=False))
    # a dropped arm is a schema failure
    obj = _ingest_bench()
    del obj["arms"]["secagg"]
    assert any("secagg" in p for p in trend.validate_ingest_bench(obj))
    # low coverage is re-derived from the records, not trusted to gates
    obj = _ingest_bench()
    obj["arms"]["sharded"]["rounds"][0]["coverage"] = 0.5
    obj["arms"]["sharded"]["rounds"][0]["attribution"] = {"fold": 0.1}
    assert any("covers" in p for p in trend.validate_ingest_bench(obj))
    # recompiles after warmup with tracing on break the cost contract
    obj = _ingest_bench()
    obj["arms"]["cross_device"]["recompiles_after_warmup"] = 1
    assert any("recompiles" in p for p in trend.validate_ingest_bench(obj))
    # the --ingest_pipeline twins are required, and their bit-parity is
    # re-derived from the crc rows — a green verdict cannot survive
    # rows that contradict it
    obj = _ingest_bench()
    del obj["pipeline"]
    assert any("pipeline" in p for p in trend.validate_ingest_bench(obj))
    obj = _ingest_bench()
    obj["pipeline"]["twins"]["waves"]["pipelined"]["rows"][1][
        "global_crc"] = 999
    assert any("bit-parity" in p for p in trend.validate_ingest_bench(obj))
    obj = _ingest_bench()
    obj["pipeline"]["twins"]["waves"]["pipelined"]["rows"][1][
        "fold_overlap_ratio"] = 0.5
    assert any("fold_overlap" in p
               for p in trend.validate_ingest_bench(obj))
    obj = _ingest_bench()
    obj["pipeline"]["twins"]["replicated"]["pipelined"][
        "jit_cache_sizes"]["ingest_arena"] = 2
    assert any("ledger" in p for p in trend.validate_ingest_bench(obj))


# ---------------------------------------------------------------------------
# config gates
# ---------------------------------------------------------------------------

class TestMetricsPortConfigGates:
    def test_metrics_port_prom_port_disagreement_fails_loud(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="metrics_port"):
            main(["--algo", "cross_silo", "--metrics_port", "9001",
                  "--prom_port", "9002"])

    def test_metrics_endpoint_requires_live_registry(self):
        assert isinstance(telemetry.get_registry(), telemetry.NullRegistry)
        with pytest.raises(ValueError, match="telemetry is disabled"):
            telemetry.start_http_server(0, host="127.0.0.1")
