"""Device-mesh construction — the TPU replacement for mpirun + hostfile +
gpu_mapping.yaml (fedml_api/distributed/utils/gpu_mapping.py:8-37).

The reference assigns one OS process per FL participant and places each on a
GPU via a YAML table.  Here, placement is a `jax.sharding.Mesh`: the
``clients`` axis shards the cohort across chips; an optional ``model`` axis
gives intra-client model sharding (pjit tensor-parallel "for free" — a config
knob, not an algorithm, per SURVEY.md §2.5).  Multi-host pods initialize with
`jax.distributed.initialize` and the same code runs unchanged; hierarchical
FL maps its group tier onto ICI within a slice and its global tier onto DCN
across slices (two-level mesh axes)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(client_axis: Optional[int] = None, model_axis: int = 1,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_names=("clients", "model")) -> Mesh:
    """Mesh over all (or given) devices: [clients, model].

    Defaults: every device on the clients axis, no model sharding."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_axis < 1:
        raise ValueError(
            f"cannot build a mesh with model_axis={model_axis}: every mesh "
            f"axis must be >= 1 (got {n} devices)")
    if client_axis is None:
        client_axis = n // model_axis
    # a loud, assert-free factorization check: this used to be a bare
    # ``assert`` that vanishes under ``python -O`` and named no remedy —
    # a mis-factored launch must fail the same way in every interpreter
    # mode (the repo's fail-loudly convention)
    if client_axis < 1 or client_axis * model_axis != n:
        raise ValueError(
            f"cannot build a [{client_axis}, {model_axis}] "
            f"({axis_names[0]} x {axis_names[1]}) mesh from {n} devices: "
            f"the axes must be >= 1 and their product must equal the "
            f"device count — pass axis sizes that factor {n}, or a "
            f"matching devices= subset")
    arr = np.asarray(devices).reshape(client_axis, model_axis)
    return Mesh(arr, axis_names)


def make_two_level_mesh(group_axis: int, client_axis: Optional[int] = None,
                        devices: Optional[Sequence[jax.Device]] = None
                        ) -> Mesh:
    """[groups, clients] mesh for hierarchical FL (SURVEY.md §2.5): the
    group tier aggregates over the ``clients`` axis (ICI within a slice),
    the global tier over the ``groups`` axis (DCN across slices).  On a real
    multi-slice pod pass ``devices`` ordered slice-major so the groups axis
    falls on the DCN boundary."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if group_axis < 1:
        # guard BEFORE the derived division: group_axis=0 used to die as
        # a bare ZeroDivisionError instead of a named config error
        raise ValueError(
            f"cannot build a two-level mesh with group_axis={group_axis}: "
            f"the groups axis must be >= 1 (got {n} devices)")
    if client_axis is None:
        client_axis = n // group_axis
    if client_axis < 1 or group_axis * client_axis != n:
        raise ValueError(
            f"cannot build a [{group_axis}, {client_axis}] two-level mesh "
            f"from {n} devices: the axes must be >= 1 and their product "
            f"must equal the device count — the groups axis must divide "
            f"{n} (pass a client_axis that factors it, or a matching "
            f"devices= subset)")
    arr = np.asarray(devices).reshape(group_axis, client_axis)
    return Mesh(arr, ("groups", "clients"))


def make_model_mesh(num_shards: int,
                    devices: Optional[Sequence[jax.Device]] = None
                    ) -> Optional[Mesh]:
    """A ``[1, num_shards]`` (clients x model) mesh for the sharded
    global-model spine (`fedml_tpu.shard_spine`): every shard of the
    round state lives on its own device of the ``model`` axis.  Returns
    None when fewer than ``num_shards`` devices exist — the spine then
    runs placement-free on the default device (same math, no per-device
    memory split), which is the honest posture on a 1-chip host."""
    devices = list(devices if devices is not None else jax.devices())
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if len(devices) < num_shards:
        return None
    return make_mesh(client_axis=1, model_axis=num_shards,
                     devices=devices[:num_shards])


def tp_shard_params(params: Any, mesh: Mesh, axis: str = "model",
                    min_size: int = 4096) -> Any:
    """GSPMD tensor-parallel placement: put each large kernel's output
    dim on the ``axis`` mesh axis (replicate everything else) and let XLA
    insert the collectives when the (vmapped) training step is jitted over
    the same mesh — dp over ``clients`` x tp over ``axis`` with no manual
    shard_map (SURVEY.md §2.5: tensor parallel is "a config knob, not an
    algorithm").  Works with the PLAIN make_cohort_step (mesh=None form).

    2-D Dense kernels shard the output dim; 3-D DenseGeneral kernels
    (the transformer's [d_model, heads, d_head] q/k/v projections) shard
    the heads dim — the classic Megatron head-parallel split."""
    n = mesh.shape[axis]

    def place(x):
        nd = getattr(x, "ndim", 0)
        if nd == 2 and x.shape[-1] % n == 0 and x.size >= min_size:
            return jax.device_put(x, NamedSharding(mesh, P(None, axis)))
        if nd == 3 and x.size >= min_size:
            # Megatron head-parallel split for DenseGeneral kernels: the
            # in-projections are [d_model, H, dh] (large dim FIRST — shard
            # H at dim 1, column-parallel) and the out-projection is
            # [H, dh, d_model] (large dim LAST — shard H at dim 0,
            # row-parallel).  Discriminating by large-dim position keeps
            # the q/k/v and out splits consistent so XLA needs one psum
            # per attention block, not a reshard.  GSPMD guarantees
            # correctness either way — the spec is a layout hint.
            # Gate on the Megatron shape signature — one STRICTLY large
            # d_model dim at position 0 or -1, two small head dims — so
            # e.g. a Conv1D kernel [k, c_in, c_out] (two comparable large
            # dims) stays replicated instead of sharding a spatial/channel
            # dim, which GSPMD would accept but pay resharding for.
            d0, d1, d2 = x.shape
            if d0 > max(d1, d2):          # [d_model, H, dh] in-projection
                dim = 1
            elif d2 > max(d0, d1):        # [H, dh, d_model] out-projection
                dim = 0
            else:
                dim = None
            if dim is not None and x.shape[dim] % n == 0:
                spec = [None, None, None]
                spec[dim] = axis
                return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree.map(place, params)


def client_axis_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh.shape["clients"]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: int = 1, process_id: int = 0) -> bool:
    """Multi-host bootstrap — the TPU replacement for ``mpirun -np N
    -hostfile mpi_host_file`` (run_fedavg_distributed_pytorch.sh:17-21).

    Each host runs the SAME program with its own ``process_id``;
    `jax.distributed.initialize` wires the pod so `jax.devices()` spans all
    hosts and collectives ride ICI/DCN.  Returns True when distributed mode
    was actually initialized (no-op for single-process runs, so the same
    entry point serves laptop simulation and pod launches)."""
    if coordinator_address is None or num_processes <= 1:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def stage_global(tree: Any, mesh: Optional[Mesh], spec: Optional[P] = None):
    """Make host data feedable to a jit over a (possibly multi-process) mesh.

    Single-process: identity — jit accepts host numpy directly.  Multi-
    process (after `init_distributed`): a device on another host is not
    addressable, so process-local arrays cannot enter a global-mesh jit;
    each leaf is rebuilt as a global ``jax.Array`` via
    ``make_array_from_callback``.  The data-staging contract matches the
    rest of the framework: every process holds the SAME host-side dataset
    (the reference ships all data to every MPI rank too, FedAvgAPI.py:60-75)
    and the callback slices out just the shards this process addresses.

    ``spec=None`` replicates (params / rng keys); ``P("clients")`` shards
    the leading cohort axis.

    IDEMPOTENT: a leaf that is already a global (not fully addressable)
    jax.Array — e.g. the previous round's output fed back in, or an
    argument a caller staged earlier — passes through untouched, so
    layered staging (FedAvg.run stages params/cohort/rng; the stateful
    mesh wrap re-stages every positional arg) is safe.
    """
    if mesh is None or jax.process_count() == 1:
        return tree
    sharding = NamedSharding(mesh, spec if spec is not None else P())

    def mk(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x  # already global (idempotent staging)
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(
                x.dtype, jax.dtypes.prng_key):
            # typed PRNG keys can't round-trip through numpy; globalize the
            # underlying uint32 data and re-wrap
            data = mk(np.asarray(jax.random.key_data(x)))
            return jax.random.wrap_key_data(data)
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    return jax.tree.map(mk, tree)
