"""Streaming O(1)-memory aggregation (core/stream_agg.py) and the live
multi-level aggregator topology (hierarchical.EdgeAggregatorActor).

The load-bearing pins:

* ``mean`` stream-vs-stack BIT-IDENTITY — the stream fold and the stack
  path's `lax.scan` mean are the same sequential reduction, so the two
  `--agg_mode`s agree bit for bit, including dropped-straggler refill
  and quarantined weight-0 slots;
* reservoir regime: exact (up to slot order) when the cohort fits the
  reservoir, bounded O(K * model) beyond it, result inside the honest
  envelope;
* the fold jit compiles ONCE across rounds (`_cache_size() == 1`);
* stream mode never allocates the ``[cohort, ...]`` staging buffer, and
  stack mode RELEASES it at round close;
* edge→root topology over the real transport: flat parity clean, and a
  chaos-dropped edge degrades to the root's straggler policy instead of
  wedging the federation.
"""

import json
import threading

import numpy as np
import pytest

import jax

from fedml_tpu.algorithms.async_fl import AsyncFedServerActor, delta_encoder
from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor, MsgType)
from fedml_tpu.algorithms.hierarchical import EdgeAggregatorActor
from fedml_tpu.comm.chaos import ChaosPlan, ChaosTransport, LinkChaos
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message
from fedml_tpu.core.stream_agg import StreamingAggregator
from fedml_tpu.robust import (AdmissionPipeline, Attack, TrustTracker,
                              make_defended_aggregate,
                              make_malicious_train_fn)


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


def _uploads(n, seed=7):
    rng = np.random.RandomState(seed)
    ups, ws = [], []
    for i in range(n):
        ups.append(jax.tree.map(
            lambda v: np.asarray(v) + rng.randn(*np.shape(v)).astype(
                np.float32), _params()))
        ws.append(float(10 * (i + 1)))
    return ups, ws


def _stack(trees):
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *trees)


def _assert_trees_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# the fold itself: stream == stack, bit for bit
# ---------------------------------------------------------------------------

class TestMeanFold:
    @pytest.mark.parametrize("norm_clip,noise_std", [(0.0, 0.0),
                                                     (5.0, 0.0),
                                                     (5.0, 0.01)])
    def test_fold_matches_stack_scan_bitwise(self, norm_clip, noise_std):
        tmpl = _params()
        ups, ws = _uploads(6)
        agg = StreamingAggregator(tmpl, method="mean", norm_clip=norm_clip,
                                  noise_std=noise_std, seed=3)
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        streamed = agg.finalize(2)
        fn = make_defended_aggregate("mean", norm_clip=norm_clip,
                                     noise_std=noise_std, seed=3)
        stacked = fn(tmpl, _stack(ups), np.asarray(ws, np.float32), 2)
        _assert_trees_equal(streamed, stacked)

    def test_weight_zero_slots_are_exactly_absent(self):
        """A stack whose slot holds the reference at weight 0 (dropped /
        quarantined / rejected) contributes an exact +0.0 to the scan —
        bit-identical to never folding that slot at all."""
        tmpl = _params()
        ups, ws = _uploads(5)
        agg = StreamingAggregator(tmpl, method="mean", norm_clip=5.0)
        agg.reset(tmpl)
        for i, (u, w) in enumerate(zip(ups, ws)):
            if i != 2:  # slot 2 never arrives
                agg.fold(u, w)
        streamed = agg.finalize(0)
        fn = make_defended_aggregate("mean", norm_clip=5.0)
        padded = list(ups)
        padded[2] = tmpl  # the refill the stack path does at round close
        w = np.asarray(ws, np.float32)
        w[2] = 0.0
        _assert_trees_equal(streamed, fn(tmpl, _stack(padded), w, 0))

    def test_int_leaves_accumulate_exactly(self):
        """acc_dtype contract: int leaves (step counters) ride an f32
        accumulator in BOTH modes — same helper, same result."""
        tmpl = {"w": np.ones(3, np.float32), "step": np.int32(4)}
        ups = [{"w": np.full(3, i, np.float32), "step": np.int32(i)}
               for i in range(1, 4)]
        ws = [10.0, 20.0, 30.0]
        agg = StreamingAggregator(tmpl, method="mean")
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        fn = make_defended_aggregate("mean")
        _assert_trees_equal(agg.finalize(0),
                            fn(tmpl, _stack(ups),
                               np.asarray(ws, np.float32), 0))

    def test_validation_and_lifecycle_errors(self):
        tmpl = _params()
        with pytest.raises(ValueError, match="unknown streaming"):
            StreamingAggregator(tmpl, method="majority_vote")
        with pytest.raises(ValueError, match="kind"):
            StreamingAggregator(tmpl, kind="gradients")
        with pytest.raises(ValueError, match="reservoir_k"):
            StreamingAggregator(tmpl, method="krum", reservoir_k=0)
        agg = StreamingAggregator(tmpl, method="mean")
        with pytest.raises(RuntimeError, match="fold\\(\\) before reset"):
            agg.fold(tmpl, 1.0)
        agg.reset(tmpl)
        with pytest.raises(RuntimeError, match="no folded uploads"):
            agg.finalize(0)

    def test_fold_jit_compiles_once_across_rounds(self):
        tmpl = _params()
        agg = StreamingAggregator(tmpl, method="mean", norm_clip=5.0)
        for r in range(4):
            agg.reset(tmpl if r == 0 else out)  # noqa: F821 — prior round
            ups, ws = _uploads(3, seed=r)
            for u, w in zip(ups, ws):
                agg.fold(u, w)
            out = agg.finalize(r)
        assert agg._cache_size() == 1


# ---------------------------------------------------------------------------
# reservoir regime (robust rules)
# ---------------------------------------------------------------------------

class TestReservoir:
    def test_exact_when_cohort_fits(self):
        """cohort <= K: the rule sees every upload (pad slots carry the
        reference at weight 0 — the zero diff every rule masks out), so
        the reservoir result equals the stack-mode defended result."""
        tmpl = _params()
        ups, ws = _uploads(5)
        agg = StreamingAggregator(tmpl, method="coordinate_median",
                                  reservoir_k=8, seed=1)
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        got = agg.finalize(0)
        fn = make_defended_aggregate("coordinate_median")
        want = fn(tmpl, _stack(ups), np.asarray(ws, np.float32), 0)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6), got, want)

    @pytest.mark.parametrize("method", ["coordinate_median", "trimmed_mean",
                                        "krum", "geometric_median"])
    def test_bounded_beyond_k_and_inside_honest_envelope(self, method):
        """cohort > K: standing memory stays [K, ...] no matter how many
        uploads fold, and the rule's output lies inside the elementwise
        envelope of the honest uploads (a uniform subsample of honest
        points cannot leave their hull under any of these rules)."""
        tmpl = _params()
        ups, ws = _uploads(12)
        agg = StreamingAggregator(tmpl, method=method, reservoir_k=4,
                                  seed=2, trim_frac=0.25, byz_f=1)
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        assert agg.count == 12 and agg._seen == 12
        # the memory bound: K slots, every one holding a real upload now
        for leaf in agg._res_leaves:
            assert leaf.shape[0] == 4
        assert (agg._res_weights > 0).all()
        out = jax.tree.map(np.asarray, agg.finalize(0))
        lo = jax.tree.map(lambda *xs: np.min(np.stack(xs), 0) - 1e-5, *ups)
        hi = jax.tree.map(lambda *xs: np.max(np.stack(xs), 0) + 1e-5, *ups)
        jax.tree.map(lambda o, a, b: np.testing.assert_array_less(a, o)
                     or np.testing.assert_array_less(o, b), out, lo, hi)

    def test_reservoir_finalize_compiles_once_across_rounds(self):
        tmpl = _params()
        agg = StreamingAggregator(tmpl, method="trimmed_mean",
                                  reservoir_k=4, trim_frac=0.25)
        out = tmpl
        for r in range(3):
            agg.reset(out)
            ups, ws = _uploads(6, seed=r)
            for u, w in zip(ups, ws):
                agg.fold(u, w)
            out = agg.finalize(r)
        assert agg._cache_size() == 1

    def test_reservoir_rejects_treedef_mismatch(self):
        tmpl = _params()
        agg = StreamingAggregator(tmpl, method="krum", reservoir_k=4)
        agg.reset(tmpl)
        with pytest.raises(ValueError, match="treedef"):
            agg.fold({"alien": np.zeros(2, np.float32)}, 1.0)
        # fail-loud must not depend on winning an Algorithm-R slot: past
        # the K bound a malformed upload still raises on EVERY arrival
        # and is never absorbed into the fold count
        ups, ws = _uploads(8)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        count_before = agg.count
        for _ in range(6):  # several draws — losing ones must raise too
            with pytest.raises(ValueError, match="treedef"):
                agg.fold({"alien": np.zeros(2, np.float32)}, 1.0)
        assert agg.count == count_before


# ---------------------------------------------------------------------------
# the live sync server: --agg_mode stream vs stack, bit for bit
# ---------------------------------------------------------------------------

def _drift_train_fn(scale=0.01):
    def fn(params, client_idx, round_idx):
        return (jax.tree.map(
            lambda v: np.asarray(v)
            + np.float32(scale * (client_idx + 1)), params),
            10 * (client_idx + 1))
    return fn


def _run_sync(mode, n_silos=4, n_rounds=3, admission=None, attack=None,
              attacker=2, deaf=(), norm_clip=5.0, perf=None):
    """One pump-mode federation; ``deaf`` silos never answer a sync, and
    the caller-injected ROUND_TIMEOUT closes over them deterministically
    (arrival order stays slot order, so stream folds == stack scan)."""
    hub = LocalHub(codec_roundtrip=True)
    init = _params()
    kw = {}
    if mode == "stream":
        kw["stream_agg"] = StreamingAggregator(init, method="mean",
                                               norm_clip=norm_clip)
    else:
        kw["aggregate_fn"] = make_defended_aggregate("mean",
                                                     norm_clip=norm_clip)
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=n_silos,
        client_num_per_round=n_silos, num_rounds=n_rounds,
        admission=admission, perf=perf,
        straggler_policy="drop" if deaf else "wait",
        round_timeout_s=3600 if deaf else None, min_silo_frac=0.5, **kw)
    server.register_handlers()
    silos = []
    for i in range(1, n_silos + 1):
        fn = _drift_train_fn()
        if attack is not None and i == attacker:
            fn = make_malicious_train_fn(attack, fn, silo=i, seed=0)
        if i in deaf:
            class Deaf(FedAvgClientActor):
                def register_handlers(self):
                    self.register_handler(MsgType.S2C_FINISH,
                                          lambda m: self.finish())
            silos.append(Deaf(i, hub.transport(i), fn))
        else:
            silos.append(FedAvgClientActor(i, hub.transport(i), fn))
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    while deaf and server.round_idx < n_rounds:
        # the deterministic straggler close: every honest upload already
        # arrived (in slot order), the barrier waits only on the deaf
        # silos — fire the timeout by hand instead of sleeping on the
        # wall-clock timer
        server.send(MsgType.ROUND_TIMEOUT, 0,
                    **{Message.ARG_ROUND: server.round_idx})
        hub.pump()
    return server, init


class TestLiveSyncEquivalence:
    def test_stream_matches_stack_bitwise(self):
        stack, _ = _run_sync("stack")
        stream, _ = _run_sync("stream")
        assert stream.round_idx == stack.round_idx == 3
        _assert_trees_equal(stack.params, stream.params)
        # the O(1)-memory point: stream mode never allocated the
        # [cohort, ...] staging buffer at all
        assert stream._staging is None and stream._staged_seen == 0
        assert stack._staged_seen == 3 * 4
        # ... and stack mode RELEASED it at round close
        assert stack._staging is None

    def test_stream_matches_stack_with_dropped_straggler(self):
        stack, _ = _run_sync("stack", deaf=(4,))
        stream, _ = _run_sync("stream", deaf=(4,))
        assert stack.dropped_silos == stream.dropped_silos
        assert any(4 in v for v in stack.dropped_silos.values())
        _assert_trees_equal(stack.params, stream.params)

    def test_stream_matches_stack_with_quarantined_attacker(self):
        def adm():
            return AdmissionPipeline(
                _params(), norm_min_history=3,
                trust=TrustTracker(strikes_to_quarantine=2,
                                   quarantine_rounds=10))
        a1, a2 = adm(), adm()
        stack, init = _run_sync("stack", n_rounds=6, admission=a1,
                                attack=Attack("scale", 100.0))
        stream, _ = _run_sync("stream", n_rounds=6, admission=a2,
                              attack=Attack("scale", 100.0))
        # both arms saw the same screen verdicts and the same quarantine
        assert a1.rejected == a2.rejected
        assert a1.trust.state(2, 6) == a2.trust.state(2, 6) \
            == TrustTracker.QUARANTINED
        _assert_trees_equal(stack.params, stream.params)

    def test_stream_fold_jit_once_on_the_live_path(self):
        stream, _ = _run_sync("stream", n_rounds=4)
        assert stream.stream_agg._cache_size() == 1

    def test_perf_ledger_gains_the_fold_phase(self, tmp_path):
        from fedml_tpu.obs.perf import PerfRecorder
        rec = PerfRecorder(str(tmp_path / "perf.jsonl"))
        server, _ = _run_sync("stream", perf=rec)
        rec.close()
        rounds = [json.loads(l) for l in
                  (tmp_path / "perf.jsonl").read_text().splitlines()]
        assert len(rounds) == 3
        for line in rounds:
            assert line["phases"].get("fold", 0) > 0
            # every admitted upload folded at arrival — nothing staged
            assert "staging" not in line["phases"]


# ---------------------------------------------------------------------------
# the live async server: stream vs defended-stack, bit for bit
# ---------------------------------------------------------------------------

def _run_async(mode, n_silos=4, versions=3, goal=2):
    hub = LocalHub(codec_roundtrip=True)
    init = _params()
    kw = {}
    if mode == "stream":
        kw["stream_agg"] = StreamingAggregator(init, method="mean",
                                               kind="delta")
    else:
        kw["defended_aggregate"] = make_defended_aggregate("mean")
    server = AsyncFedServerActor(
        hub.transport(0), init, client_num_in_total=n_silos,
        n_silos=n_silos, num_versions=versions, aggregation_goal=goal,
        **kw)
    server.register_handlers()
    silos = [FedAvgClientActor(i, hub.transport(i), _drift_train_fn(),
                               encode_upload=delta_encoder)
             for i in range(1, n_silos + 1)]
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    return server


class TestLiveAsyncEquivalence:
    def test_stream_matches_defended_stack_bitwise(self):
        stack = _run_async("stack")
        stream = _run_async("stream")
        assert stack.version == stream.version >= 3
        assert list(stack.staleness_seen) == list(stream.staleness_seen)
        _assert_trees_equal(stack.params, stream.params)

    def test_stream_buffer_holds_no_deltas(self):
        """The async O(1) point: the buffer keeps metadata tuples only —
        the delta bytes fold at arrival and are dropped."""
        hub = LocalHub(codec_roundtrip=True)
        init = _params()
        server = AsyncFedServerActor(
            hub.transport(0), init, client_num_in_total=2, n_silos=2,
            num_versions=2, aggregation_goal=2,
            stream_agg=StreamingAggregator(init, method="mean",
                                           kind="delta"))
        seen = []
        orig = server._apply_buffer

        def spy():
            seen.extend(d for d, _, _, _, _ in server._buffer)
            orig()
        server._apply_buffer = spy
        server.register_handlers()
        silos = [FedAvgClientActor(i, hub.transport(i), _drift_train_fn(),
                                   encode_upload=delta_encoder)
                 for i in (1, 2)]
        for s in silos:
            s.register_handlers()
        server.start()
        hub.pump()
        assert seen and all(d is None for d in seen)


# ---------------------------------------------------------------------------
# the multi-level aggregator topology over the real transport
# ---------------------------------------------------------------------------

def _edge_federation(n_edges=2, n_silos=4, n_rounds=3, wrap=lambda i, t: t,
                     timeout_s=None, root_timeout_s=None,
                     straggler_policy="wait"):
    """root 0; edges 1..E; silos at E+g for global slot g (blocks of
    contiguous slots per edge) — the same address plan experiments/main.py
    deploys."""
    hub = LocalHub(codec_roundtrip=True)
    init = _params()
    server = FedAvgServerActor(
        wrap(0, hub.transport(0)), init, client_num_in_total=n_silos,
        client_num_per_round=n_edges, num_rounds=n_rounds,
        stream_agg=StreamingAggregator(init, method="mean"),
        straggler_policy=straggler_policy, round_timeout_s=root_timeout_s,
        min_silo_frac=0.5)
    server.register_handlers()
    blocks = np.array_split(np.arange(1, n_silos + 1), n_edges)
    edges = []
    for e, block in enumerate(blocks, start=1):
        edges.append(EdgeAggregatorActor(
            e, wrap(e, hub.transport(e)),
            {n_edges + int(g): int(g) for g in block},
            cohort_total=n_silos, client_num_in_total=n_silos,
            stream_agg=StreamingAggregator(init, method="mean"),
            timeout_s=timeout_s))
    edge_of = {int(g): e for e, block in enumerate(blocks, start=1)
               for g in block}
    silos = [FedAvgClientActor(n_edges + g, wrap(n_edges + g,
                                                 hub.transport(n_edges + g)),
                               _drift_train_fn(), server_id=edge_of[g])
             for g in range(1, n_silos + 1)]
    return hub, init, server, edges, silos


class TestEdgeTopology:
    def test_edge_root_matches_flat_stream(self):
        """mean(edge means, edge weights) == mean(all uploads) — the
        2-tier run lands where the flat run lands (fp association
        differs across the tiers, so allclose, not bitwise)."""
        hub, init, server, edges, silos = _edge_federation()
        for a in edges + silos:
            a.register_handlers()
        server.start()
        hub.pump()
        assert server.round_idx == 3
        flat, _ = _run_sync("stream", norm_clip=0.0)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
            server.params, flat.params)
        # each edge folded exactly its block every round
        for e in edges:
            assert e.stream_agg.count == 2

    def test_edge_ships_one_prereduced_frame(self):
        """The wire contract: the root receives E model-sized frames per
        round — the folded weight total as num_samples, the fold count
        as edge_count — no matter how many silos fed each edge."""
        hub, init, server, edges, silos = _edge_federation(n_rounds=1)
        got = []
        orig = server._on_model

        def spy(msg):
            got.append((msg.sender_id, msg.get(Message.ARG_NUM_SAMPLES),
                        msg.get(Message.ARG_EDGE_COUNT)))
            orig(msg)
        server.register_handler(MsgType.C2S_MODEL, spy)
        for a in edges + silos:
            a.register_handlers()
        server.start()
        hub.pump()
        assert sorted(s for s, _, _ in got) == [1, 2]
        for _, num_samples, edge_count in got:
            assert edge_count == 2          # silos folded into the edge
            assert num_samples > 0          # the folded weight total

    def test_chaos_dropped_edge_degrades_to_straggler_policy(self):
        """Every edge-1 → root frame is chaos-dropped: the root's drop
        policy closes each round on edge 2 alone (min_silo_frac 0.5)
        and the global still tracks edge 2's honest drift — a lost edge
        is a straggler, never a wedge."""
        plan = ChaosPlan(seed=3, links={(1, 0): LinkChaos(drop_prob=1.0)},
                         immune_types=(MsgType.S2C_FINISH,
                                       MsgType.ROUND_TIMEOUT))
        hub, init, server, edges, silos = _edge_federation(
            n_rounds=2, straggler_policy="drop", root_timeout_s=0.5,
            wrap=lambda i, t: ChaosTransport(t, plan) if i == 1 else t)
        threads = [threading.Thread(target=a.run, daemon=True,
                                    name=f"node-{a.node_id}")
                   for a in edges + silos]
        for th in threads:
            th.start()
        server.start()
        server.transport.run()
        for th in threads:
            th.join(timeout=10)
        assert server.round_idx == 2
        # edge 1 was dropped every round; edge 2's fold landed
        assert all(1 in v for v in server.dropped_silos.values())
        assert all(np.isfinite(l).all()
                   for l in jax.tree.leaves(server.params))
        drift = (np.asarray(server.params["dense"]["bias"])
                 - np.asarray(init["dense"]["bias"]))
        assert np.abs(drift).max() > 0  # edge 2's silos moved the global

    def test_foreign_and_stale_uploads_are_discarded(self):
        hub = LocalHub(codec_roundtrip=True)
        init = _params()
        edge = EdgeAggregatorActor(
            1, hub.transport(1), {3: 1, 4: 2}, cohort_total=2,
            client_num_in_total=2,
            stream_agg=StreamingAggregator(init, method="mean"))
        edge.register_handlers()
        hub.transport(3), hub.transport(4)  # endpoints for the re-broadcast
        # sync the edge into round 0 by hand
        msg = Message(MsgType.S2C_SYNC, 0, 1)
        msg.add(Message.ARG_MODEL_PARAMS, init)
        msg.add(Message.ARG_ROUND, 0)
        edge._on_sync(msg)
        up = Message(MsgType.C2S_MODEL, 9, 1)  # not one of its silos
        up.add(Message.ARG_MODEL_PARAMS, _params(1))
        up.add(Message.ARG_NUM_SAMPLES, 10)
        up.add(Message.ARG_ROUND, 0)
        edge._on_upload(up)
        assert edge.stream_agg.count == 0
        stale = Message(MsgType.C2S_MODEL, 3, 1)
        stale.add(Message.ARG_MODEL_PARAMS, _params(1))
        stale.add(Message.ARG_NUM_SAMPLES, 10)
        stale.add(Message.ARG_ROUND, 7)  # wrong round
        edge._on_upload(stale)
        assert edge.stream_agg.count == 0
        edge.finish()
