#!/usr/bin/env bash
# End-to-end serving demo (ISSUE 3 acceptance; multi-worker + v2 bench
# per ISSUE 15): serve-while-train, then the gated load benchmark —
# asserting the full loop actually closes:
#
#   * a cross-silo federation trains with --serve_port AND
#     --serve_workers 2: the multi-worker pool comes up (SO_REUSEPORT,
#     one registry), /healthz goes healthy and names the answering
#     worker, live /predict answers mid-training, and /version ADVANCES
#     as rounds publish new globals,
#   * checkpoint retention (--checkpoint_keep_last_n) keeps the watched
#     directory bounded,
#   * scripts/serve_bench.py --smoke runs the v2 arm set (replay/http/
#     decode) green — the CI-sized twin of the committed BENCH_serve.json,
#   * scripts/perf_trend.py --serve_bench validates the COMMITTED
#     artifact: arms present, honest backend labels, every recorded gate
#     verdict passing (the serve path rides the same trend line as every
#     other hot path).
#
# Usage: scripts/run_serve_demo.sh [workdir]  (default: a fresh mktemp dir)
set -euo pipefail
cd "$(dirname "$0")/.."

DIR="${1:-$(mktemp -d /tmp/fedml_serve_demo.XXXXXX)}"
PORT="${SERVE_PORT:-8351}"
CK="$DIR/ck"
echo "== serve demo: artifacts under $DIR"

env JAX_PLATFORMS=cpu python -m fedml_tpu \
    --algo cross_silo --model lr --dataset mnist \
    --client_num_in_total 8 --client_num_per_round 4 --comm_round 24 \
    --epochs 2 --batch_size 10 --frequency_of_the_test 100 \
    --log_stdout false --run_dir "$DIR/run" --telemetry true \
    --checkpoint_dir "$CK" --checkpoint_every 1 \
    --checkpoint_keep_last_n 3 \
    --serve_port "$PORT" --serve_workers 2 --serve_deadline_ms 100 &
TRAIN_PID=$!
trap 'kill $TRAIN_PID 2>/dev/null || true' EXIT

echo "== polling the live frontend while training runs"
python - "$PORT" "$TRAIN_PID" <<'EOF'
import http.client, json, os, sys, time
port, pid = int(sys.argv[1]), int(sys.argv[2])

def alive():
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False

def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    conn.request("GET", path)
    r = conn.getresponse()
    body = json.loads(r.read())
    conn.close()
    return r.status, body

# wait for the frontend to come up (training process must still be alive)
deadline = time.time() + 120
while True:
    assert alive(), "training process died before the frontend came up"
    assert time.time() < deadline, "frontend never came up"
    try:
        status, body = get("/healthz")
        if status == 200:
            break
    except OSError:
        pass
    time.sleep(0.05)
print(f"healthz up: {body}")
assert body.get("workers") == 2, f"pool did not report 2 workers: {body}"
assert "worker" in body, f"healthz lost the answering-worker id: {body}"

versions, predicted = set(), 0
x = [0.0] * 784
while alive():
    try:
        status, body = get("/version")
    except OSError:
        break  # frontend closed at training end
    if status == 200 and body["version"] is not None:
        versions.add(body["version"])
    if predicted < 3:  # live predictions mid-training
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("POST", "/predict", json.dumps({"x": x}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            resp = json.loads(r.read())
            conn.close()
            if r.status == 200:
                predicted += 1
                print(f"live /predict ok at version {resp['version']}")
        except OSError:
            pass
    time.sleep(0.05)

print(f"versions observed while training: {sorted(versions)}")
assert len(versions) >= 2, \
    f"/version never advanced during training: {sorted(versions)}"
assert predicted > 0, "no live /predict succeeded mid-training"
EOF
wait "$TRAIN_PID"
trap - EXIT

echo "== asserting checkpoint retention GC"
KEPT=$(ls "$CK" | grep -c '^[0-9][0-9]*$')
[ "$KEPT" -le 3 ] || { echo "retention kept $KEPT > 3 rounds"; exit 1; }

echo "== serve bench v2 smoke arms (replay / http / decode, gated)"
env JAX_PLATFORMS=cpu python scripts/serve_bench.py --smoke \
    --out "$DIR/BENCH_serve_smoke.json"

python - "$DIR/BENCH_serve_smoke.json" <<'EOF'
import json, sys
b = json.load(open(sys.argv[1]))
assert b["version"] == 2 and b["smoke"] is True, b
r = b["arms"]["replay"]; d = b["arms"]["decode"]
assert r["torn_responses"] == 0, r
assert r["latency_ms"]["p99"] <= r["deadline_ms"], r
assert d["occupancy_ratio"] >= 2.0, d
assert d["recompiles_after_warmup"] == 0, d
print(f"smoke OK: replay {r['throughput_rps']} req/s "
      f"p99={r['latency_ms']['p99']}ms, decode occupancy "
      f"{d['continuous']['occupancy_mean']} vs {d['drain']['occupancy_mean']} "
      f"({d['occupancy_ratio']}x), ledger={d['compile_ledger']}")
EOF

echo "== trend gate over the COMMITTED BENCH_serve.json"
env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --serve_bench BENCH_serve.json
echo "== serve demo OK ($DIR)"
