"""Differential-privacy accounting: Rényi-DP (RDP) moments accountant.

The reference ships "weak DP" — per-update Gaussian noise with NO privacy
accounting (``fedml_core/robustness/robust_aggregation.py:51-55``; the
stddev is a bare config knob and no (ε, δ) is ever computed or reported).
This module provides the real thing for ``--algo dp_fedavg``
(algorithms/dp_fedavg.py): the subsampled-Gaussian RDP bound composed
over rounds and converted to (ε, δ), so every run reports the privacy it
actually spent.

Math (host-side numpy — accounting is not a TPU workload):

* Gaussian mechanism with L2 sensitivity 1 and noise multiplier z has
  RDP ``ε(α) = α / (2 z²)`` (Mironov 2017, arXiv:1702.07476).
* Under Poisson subsampling with rate q, the integer-order bound
  (Mironov, Talwar & Zhang 2019, arXiv:1908.10530 — the tf-privacy
  accountant formula) is

      ε(α) = 1/(α−1) · log Σ_{j=0..α} C(α,j)(1−q)^{α−j} q^j e^{j(j−1)/(2z²)}

  computed in log space (lgamma binomials + logaddexp) so large orders
  don't overflow.
* RDP composes additively over rounds; conversion to (ε, δ) takes
  ``min_α [ ε(α) + log(1/δ)/(α−1) ]``.

Two sampling analyses are provided (``RdpAccountant(sampling=)``):

* ``"poisson"`` — the subsampled-Gaussian bound above.  EXACT only if
  each client joins each round independently with probability q; when
  the sampler is fixed-size, this is the approximation every production
  DP-FL accountant makes (documented, comparable with the literature).
* ``"fixed_size_wor"`` — the subsampling-WITHOUT-replacement bound
  (Wang, Balle & Kasiviswanathan 2019, arXiv:1808.00087, Thm 27), which
  matches the fixed-size cohort sampler dp_fedavg actually uses
  (``jax.random.choice(replace=False)``), under the replace-one
  adjacency that analysis is stated in.  A rigorous UPPER BOUND that
  applies to the real sampler (the Poisson analysis does not),
  conservative relative to Poisson (replace-one doubles the
  sensitivity) — the honest default for ``--algo dp_fedavg``.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence, Tuple

import numpy as np

# α=2..63 densely (small ε regimes resolve there) plus sparse large
# orders for tiny q / large z
DEFAULT_ORDERS: Tuple[int, ...] = tuple(range(2, 64)) + (
    80, 96, 128, 192, 256, 512)


def _subsample_prologue(q, noise_multiplier, orders):
    """Shared input contract of both subsampled-Gaussian bounds:
    validates (q, orders) and returns ``(orders_array, early_out)`` —
    ``early_out`` is the answer for the z<=0 (non-private: inf) and q=0
    (spends nothing: 0) edges, else None and the caller computes."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate q must be in [0, 1], got {q}")
    orders = np.asarray(list(orders))
    if orders.ndim != 1 or np.any(orders < 2) or \
            np.any(orders != orders.astype(int)):
        raise ValueError("orders must be integers >= 2")
    if noise_multiplier <= 0.0:
        return orders, np.full(orders.shape, np.inf)
    if q == 0.0:
        return orders, np.zeros(orders.shape)
    return orders, None


def rdp_subsampled_gaussian(q: float, noise_multiplier: float,
                            orders: Sequence[int] = DEFAULT_ORDERS
                            ) -> np.ndarray:
    """Per-step RDP ε(α) of the Poisson-subsampled Gaussian mechanism.

    ``q=1`` reduces exactly to the unsubsampled Gaussian ``α/(2z²)``
    (unit-tested); ``q=0`` spends nothing; ``z=0`` is non-private (inf).
    Orders must be integers ≥ 2 (the integer-order bound).
    """
    orders, early = _subsample_prologue(q, noise_multiplier, orders)
    if early is not None:
        return early
    z2 = float(noise_multiplier) ** 2
    if q == 1.0:
        return orders / (2.0 * z2)
    out = np.empty(len(orders))
    log_q, log_1q = math.log(q), math.log1p(-q)
    for i, a in enumerate(int(o) for o in orders):
        # log-space sum of C(a,j)(1-q)^(a-j) q^j exp(j(j-1)/(2 z²))
        terms = [math.lgamma(a + 1) - math.lgamma(j + 1)
                 - math.lgamma(a - j + 1)
                 + (a - j) * log_1q + j * log_q
                 + j * (j - 1) / (2.0 * z2)
                 for j in range(a + 1)]
        out[i] = float(np.logaddexp.reduce(terms)) / (a - 1)
    return out


def rdp_fixed_size_wor(q: float, noise_multiplier: float,
                       orders: Sequence[int] = DEFAULT_ORDERS
                       ) -> np.ndarray:
    """Per-step RDP ε'(α) of the FIXED-SIZE without-replacement
    subsampled Gaussian — the sampler dp_fedavg actually uses.

    Wang, Balle & Kasiviswanathan 2019 (arXiv:1808.00087) Theorem 27,
    integer orders, specialized to the Gaussian mechanism (ε(∞) = ∞, so
    the ``min[2, (e^{ε(∞)}−1)^j]`` factors are 2):

        ε'(α) = 1/(α−1) · log(1
                  + C(α,2) γ² · min{4(e^{ε(2)}−1), 2e^{ε(2)}}
                  + Σ_{j=3..α} 2 C(α,j) γ^j e^{(j−1)·ε(j)})

    with γ = m/N the sampling fraction and ε(j) = j/(2·z_ro²) the base
    Gaussian RDP under the REPLACE-ONE adjacency this analysis is stated
    in: swapping one user moves the clipped cohort sum by up to 2S (one
    update out, another in), not S — so the effective noise multiplier
    is z_ro = z/2.  That doubling is why this bound reads higher ε than
    the Poisson approximation at the same z: it is a valid (possibly
    loose) upper bound for the real sampler, where the Poisson analysis
    simply does not apply (pinned in tests/test_privacy.py).

    Subsampling never hurts (WBK19 §3), so the result is clamped to the
    unsubsampled replace-one Gaussian ``α/(2 z_ro²)`` — which is also
    the exact γ=1 (full participation) value.
    """
    orders, early = _subsample_prologue(q, noise_multiplier, orders)
    if early is not None:
        return early
    z_ro = float(noise_multiplier) / 2.0   # replace-one sensitivity 2S
    z2 = z_ro ** 2
    base = orders / (2.0 * z2)             # unsubsampled replace-one RDP
    if q == 1.0:
        return base.astype(np.float64)
    log_q = math.log(q)
    eps2 = 2.0 / (2.0 * z2)                # ε(2) of the base Gaussian
    out = np.empty(len(orders))
    for i, a in enumerate(int(o) for o in orders):
        # j=2 term: C(a,2) γ² min{4(e^{ε(2)}−1), 2e^{ε(2)}}, in log space
        log_min2 = min(math.log(4.0) + _log_expm1(eps2),
                       math.log(2.0) + eps2)
        terms = [0.0,                                   # the leading 1
                 math.lgamma(a + 1) - math.lgamma(3) - math.lgamma(a - 1)
                 + 2 * log_q + log_min2]
        for j in range(3, a + 1):
            terms.append(math.log(2.0)
                         + math.lgamma(a + 1) - math.lgamma(j + 1)
                         - math.lgamma(a - j + 1)
                         + j * log_q
                         + (j - 1) * j / (2.0 * z2))
        out[i] = float(np.logaddexp.reduce(terms)) / (a - 1)
    return np.minimum(out, base)


def _log_expm1(x: float) -> float:
    """log(e^x − 1), stable for large x (≈ x) and small x (≈ log x)."""
    if x > 30.0:
        return x
    return math.log(math.expm1(x))


def eps_from_rdp(rdp: np.ndarray, orders: Sequence[int],
                 delta: float) -> float:
    """(ε, δ) from composed RDP: ``min_α [ε(α) + log(1/δ)/(α−1)]``
    (Mironov 2017 Prop. 3)."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    orders = np.asarray(list(orders), dtype=np.float64)
    eps = np.asarray(rdp) + math.log(1.0 / delta) / (orders - 1.0)
    return float(np.min(eps))


class RdpAccountant:
    """Tracks privacy spent by repeated subsampled-Gaussian rounds.

    One instance per training run: ``step(n)`` after n rounds,
    ``epsilon()`` any time (cheap — the per-step RDP vector is computed
    once and composition is a scalar multiply)."""

    def __init__(self, q: float, noise_multiplier: float, delta: float,
                 orders: Iterable[int] = DEFAULT_ORDERS,
                 sampling: str = "poisson"):
        self.q = float(q)
        self.noise_multiplier = float(noise_multiplier)
        self.delta = float(delta)
        self.orders = tuple(int(o) for o in orders)
        self.sampling = sampling
        if sampling == "poisson":
            self._per_step = rdp_subsampled_gaussian(
                self.q, self.noise_multiplier, self.orders)
        elif sampling == "fixed_size_wor":
            self._per_step = rdp_fixed_size_wor(
                self.q, self.noise_multiplier, self.orders)
        else:
            raise ValueError(
                f"unknown sampling analysis {sampling!r}; use 'poisson' "
                "or 'fixed_size_wor'")
        self.steps = 0

    def step(self, n: int = 1) -> None:
        self.steps += int(n)

    def epsilon(self) -> float:
        if self.steps == 0:
            return 0.0
        return eps_from_rdp(self._per_step * self.steps, self.orders,
                            self.delta)
