"""Pallas TPU kernel: fused robust aggregation (clip + weak-DP + weighted mean).

SURVEY.md §7 step 6 marks the defended aggregation as the framework's Pallas
candidate, and this is it.  The XLA path (the cohort engine's
``transform_update`` hook, fedml_tpu/algorithms/fedavg_robust.py) vmaps
`clip_update` + `add_gaussian_noise` over the cohort, which materialises a
full transformed copy of every client's parameters in HBM ([N, D] written,
then re-read by the weighted mean) — O(3·N·D) HBM traffic.  This kernel
reads each stacked client block ONCE and writes only the [D] aggregate:

    out = Σ_i r_i · (g + s_i · (x_i − g) + σ · n_i)

with r_i the normalized sample weights, s_i the per-client norm-diff clip
scale (min(1, bound/‖x_i−g‖), robust_aggregation.py:38-49), and n_i a
per-client Gaussian stream (weak DP, :51-55) generated in-kernel by a
counter-based PRG (murmur3 finalizer + Box–Muller) — no HBM noise
temporaries.  One VMEM pass per block: O(N·D) reads, O(D) writes.

Clip scales need the GLOBAL update norm across all leaves, so they are a
cheap XLA reduction before the kernel launch (two-phase, like every fused
norm-clip implementation).

Semantics parity: with σ=0 the result equals the XLA compose
``tree_weighted_mean(vmap(clip_update))`` to float tolerance
(tests/test_pallas_agg.py); with σ>0 the noise distribution matches but the
stream differs (murmur counter PRG vs threefry), exactly like the SecAgg
pallas backend (secure/pallas_mask.py).

CPU/test fallback: ``interpret=True`` runs the same kernel through the
Pallas interpreter.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.pytree import tree_sub
from fedml_tpu.core.robust import _masked_global_norm, default_is_weight_param

Pytree = Any

_LANES = 128
_MAX_BLOCK_ELEMS = 4096 * 128   # x-block budget: N*rows*128 f32 <= 2 MiB


def _rows_per_block(num_clients: int) -> int:
    rows = max(8, (_MAX_BLOCK_ELEMS // _LANES) // max(num_clients, 1))
    return min(256, rows - rows % 8)


def _murmur_fmix(x: jax.Array) -> jax.Array:
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _gaussian_from_index(idx_h: jax.Array, salt: jax.Array) -> jax.Array:
    """Box–Muller over two counter-PRG uniform streams → N(0,1) f32."""
    bits1 = _murmur_fmix(idx_h ^ salt)
    bits2 = _murmur_fmix(bits1 ^ jnp.uint32(0x27D4EB2F))
    # 24-bit mantissa uniforms in (0,1): never 0, so log is finite.  The
    # shifted values fit in 24 bits, so the uint32->int32 hop is exact
    # (Mosaic has no direct uint32->f32 cast)
    u1 = ((bits1 >> 8).astype(jnp.int32).astype(jnp.float32)
          * (2.0 ** -24) + (2.0 ** -25))
    u2 = (bits2 >> 8).astype(jnp.int32).astype(jnp.float32) * (2.0 ** -24)
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(
        (2.0 * np.pi) * u2)


def _agg_kernel(scales_ref, ratios_ref, seed_ref, x_ref, g_ref, o_ref, *,
                num_clients, noise_std, rows):
    """One [rows, 128] block of one leaf: Σ_i r_i (g + s_i(x_i−g) + σ n_i)."""
    from jax.experimental import pallas as pl

    g = g_ref[:].astype(jnp.float32)
    acc = jnp.zeros_like(g)
    if noise_std:
        block = pl.program_id(0).astype(jnp.uint32)
        r_iota = jax.lax.broadcasted_iota(jnp.uint32, g.shape, 0)
        c_iota = jax.lax.broadcasted_iota(jnp.uint32, g.shape, 1)
        idx = (block * jnp.uint32(rows) + r_iota) * jnp.uint32(_LANES) + c_iota
        idx_h = _murmur_fmix(idx * jnp.uint32(0x9E3779B9) + jnp.uint32(1))
        s0 = _murmur_fmix(seed_ref[0].astype(jnp.uint32))
        s1 = _murmur_fmix(seed_ref[1].astype(jnp.uint32)
                          ^ jnp.uint32(0x5BD1E995))

    def body(i, acc):
        xi = x_ref[i].astype(jnp.float32)
        term = g + scales_ref[i] * (xi - g)
        if noise_std:
            # per-client stream: fold the client index into the round seed
            salt = _murmur_fmix(s0 ^ (s1 + i.astype(jnp.uint32)
                                      * jnp.uint32(0x85EBCA6B)))
            term = term + noise_std * _gaussian_from_index(idx_h, salt)
        return acc + ratios_ref[i] * term

    acc = jax.lax.fori_loop(0, num_clients, body, acc)
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("num_clients", "noise_std",
                                             "rows", "interpret"))
def _agg_leaf(x3d, g2d, scales, ratios, seed, *, num_clients, noise_std,
              rows, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    total_rows = x3d.shape[1]
    grid = total_rows // rows
    kernel = functools.partial(_agg_kernel, num_clients=num_clients,
                               noise_std=noise_std, rows=rows)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # scales[N]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # ratios[N]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # seed[2]
            pl.BlockSpec((num_clients, rows, _LANES), lambda r: (0, r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, _LANES), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, _LANES), lambda r: (r, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(g2d.shape, g2d.dtype),
        interpret=interpret,
    )(scales, ratios, seed, x3d, g2d)


def _finalize_kernel(wsum_ref, seed_ref, x_ref, o_ref, *, noise_std, rows):
    """One [rows, 128] block of a shard's flattened fold accumulator:
    ``out = acc / wsum (+ sigma * n)`` — the streamed defended-mean
    finalize as ONE fused pass (division + weak-DP noise, no HBM noise
    temporaries; the clip already happened at fold time, per arrival)."""
    from jax.experimental import pallas as pl

    out = x_ref[:].astype(jnp.float32) / wsum_ref[0]
    if noise_std:
        block = pl.program_id(0).astype(jnp.uint32)
        r_iota = jax.lax.broadcasted_iota(jnp.uint32, out.shape, 0)
        c_iota = jax.lax.broadcasted_iota(jnp.uint32, out.shape, 1)
        idx = (block * jnp.uint32(rows) + r_iota) * jnp.uint32(_LANES) + c_iota
        idx_h = _murmur_fmix(idx * jnp.uint32(0x9E3779B9) + jnp.uint32(1))
        s0 = _murmur_fmix(seed_ref[0].astype(jnp.uint32))
        s1 = _murmur_fmix(seed_ref[1].astype(jnp.uint32)
                          ^ jnp.uint32(0x5BD1E995))
        out = out + noise_std * _gaussian_from_index(idx_h,
                                                     _murmur_fmix(s0 ^ s1))
    o_ref[:] = out.astype(o_ref.dtype)


def make_fused_shard_finalize(*, noise_std: float = 0.0, seed: int = 0,
                              shard_salt: int = 0, interpret: bool = False):
    """Build the fused per-shard finalize of the sharded streaming spine
    (`fedml_tpu.shard_spine.agg`): ``fn(acc_pieces, wsum, ref_pieces,
    step) -> out_pieces`` where the pieces are one shard's slice of the
    fold accumulator, keyed like its wire slice body.

    All float-destined pieces are flattened into ONE padded [rows, 128]
    f32 buffer and ``clip-at-fold + weighted-sum + noise`` completes as a
    single `pallas_call` per shard — the one-kernel-launch-per-shard
    finalize ROADMAP item 2 names.  Integer-destined pieces (step
    counters) take a scalar XLA epilogue inside the same jit (the plain
    path never noises them either).  With ``noise_std=0`` the division
    is elementwise f32 — bit-identical to the XLA compose for f32
    models; sigma>0 matches the noise distribution with a different
    stream (the module's counter PRG vs threefry), exactly like
    `make_fused_robust_aggregate`.

    ``shard_salt`` decorrelates the per-shard noise streams (the fused
    twin of `add_gaussian_noise`'s per-leaf key split);
    ``interpret=True`` runs the same kernel through the Pallas
    interpreter — the CPU/test fallback.

    The returned callable is a fresh ``jax.jit`` (per-instance cache, so
    the jit-once-per-shard pin and the recompile sentry see this
    aggregator's compiles only) with ``_cache_size`` forwarded.
    """
    seed_word = ((int(seed) & 0xFFFFFFFF)
                 ^ (((int(shard_salt) & 0xFFFFFFFF) * 0x9E3779B9)
                    & 0xFFFFFFFF))

    def _finalize(acc_pieces, wsum, ref_pieces, step):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        keys = sorted(acc_pieces)
        fkeys = [k for k in keys if jnp.issubdtype(
            jnp.asarray(ref_pieces[k]).dtype, jnp.floating)]
        out: dict = {}
        # integer-destined pieces: divide + truncate in XLA (tiny; the
        # plain finalize's exact math, noise-free by contract)
        w32 = jnp.asarray(wsum, jnp.float32)
        for k in keys:
            if k not in fkeys:
                a = acc_pieces[k]
                out[k] = (a / w32.astype(a.dtype)).astype(
                    jnp.asarray(ref_pieces[k]).dtype)
        if fkeys:
            sizes = [int(np.prod(acc_pieces[k].shape or (1,)))
                     for k in fkeys]
            flat = jnp.concatenate(
                [acc_pieces[k].astype(jnp.float32).reshape(-1)
                 for k in fkeys])
            total = int(flat.shape[0])
            leaf_rows = -(-total // _LANES)
            rows = max(8, min(256, leaf_rows + (-leaf_rows) % 8))
            pad = (-total) % (rows * _LANES)
            x2d = jnp.pad(flat, (0, pad)).reshape(-1, _LANES)
            seed32 = jnp.stack([jnp.int32(np.int32(np.uint32(seed_word))),
                                jnp.asarray(step, jnp.int32)])
            kernel = functools.partial(_finalize_kernel,
                                       noise_std=float(noise_std),
                                       rows=rows)
            flat_out = pl.pallas_call(
                kernel,
                grid=(x2d.shape[0] // rows,),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),   # wsum[1]
                    pl.BlockSpec(memory_space=pltpu.SMEM),   # seed[2]
                    pl.BlockSpec((rows, _LANES), lambda r: (r, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((rows, _LANES), lambda r: (r, 0),
                                       memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
                interpret=interpret,
            )(w32.reshape(1), seed32, x2d).reshape(-1)
            off = 0
            for k, size in zip(fkeys, sizes):
                piece = flat_out[off:off + size].reshape(
                    acc_pieces[k].shape)
                out[k] = piece.astype(jnp.asarray(ref_pieces[k]).dtype)
                off += size
        return out

    return jax.jit(_finalize)


def _clip_scales(stacked: Pytree, global_params: Pytree, norm_bound: float,
                 is_weight) -> jax.Array:
    """Per-client min(1, bound/‖x_i−g‖) over weight leaves — the cheap XLA
    reduction phase (phase 1 of 2).  Reuses the same norm helper as the XLA
    clip path (core/robust.py), so 'which leaves count' can never drift
    between the two backends."""
    norms = jax.vmap(
        lambda x: _masked_global_norm(tree_sub(x, global_params), is_weight)
    )(stacked)
    return jnp.minimum(1.0, norm_bound / jnp.maximum(norms, 1e-12))


def make_fused_robust_aggregate(norm_bound: Optional[float] = None,
                                noise_std: float = 0.0,
                                is_weight=default_is_weight_param,
                                interpret: bool = False):
    """Build the fused aggregate for the cohort engine.

    Returns ``aggregate(stacked, weights, global_params, rng)`` (the
    engine passes the extra args when ``aggregate.needs_global`` is set).
    ``norm_bound=None`` disables clipping (s_i = 1); ``noise_std=0``
    disables the in-kernel noise — both defenses off reduces to the plain
    weighted mean.
    """

    def aggregate(stacked, weights, global_params, rng):
        w = jnp.asarray(weights, jnp.float32)
        ratios = w / jnp.maximum(jnp.sum(w), 1e-12)
        n = int(w.shape[0])
        max_clients = (_MAX_BLOCK_ELEMS // _LANES) // 8
        if n > max_clients:
            raise ValueError(
                f"cohort of {n} clients exceeds the fused kernel's VMEM "
                f"budget (max {max_clients}); use the xla defense backend "
                f"for cohorts this large")
        if norm_bound is not None:
            scales = _clip_scales(stacked, global_params, norm_bound,
                                  is_weight)
        else:
            scales = jnp.ones((n,), jnp.float32)
        seed = jax.random.key_data(rng).astype(jnp.uint32)[:2].astype(
            jnp.int32)
        ones = jnp.ones((n,), jnp.float32)

        s_leaves = jax.tree_util.tree_leaves_with_path(stacked)
        g_flat, treedef = jax.tree.flatten(global_params)
        out = []
        for li, ((path, x), g) in enumerate(zip(s_leaves, g_flat)):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                # int leaves (step counters): plain weighted mean, cast back
                acc = jnp.sum(x.astype(jnp.float32)
                              * ratios.reshape((-1,) + (1,) * (x.ndim - 1)),
                              axis=0)
                out.append(acc.astype(x.dtype))
                continue
            # running stats are never clipped (robust_aggregation.py:28-30)
            leaf_scales = scales if is_weight(path) else ones
            flat = x.reshape(n, -1)
            # block rows: the VMEM budget cap, shrunk for small leaves so a
            # 62-element bias pads to one 8x128 tile, not 256x128
            leaf_rows = -(-flat.shape[1] // _LANES)       # ceil(size/128)
            rows = min(_rows_per_block(n), leaf_rows + (-leaf_rows) % 8)
            pad = (-flat.shape[1]) % (rows * _LANES)
            x3d = jnp.pad(flat, ((0, 0), (0, pad))).reshape(n, -1, _LANES)
            g2d = jnp.pad(g.reshape(-1), (0, pad)).reshape(-1, _LANES)
            agg = _agg_leaf(x3d, g2d, leaf_scales, ratios,
                            seed + jnp.int32(li * 31337),
                            num_clients=n, noise_std=float(noise_std),
                            rows=rows, interpret=interpret)
            out.append(agg.reshape(-1)[:g.size].reshape(g.shape))
        return jax.tree.unflatten(treedef, out)

    aggregate.needs_global = True
    return aggregate
