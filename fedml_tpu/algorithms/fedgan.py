"""Federated GANs: FedGan (FedAvg over G+D) and AsDGan (split G/D).

Reference choreography:

* **FedGan** (``fedml_api/distributed/fedgan/``): every client runs local
  adversarial training (alternating D and G steps on its own data); the
  server sample-weight-averages the COMBINED G+D parameters exactly like
  FedAvg (FedGanAggregator.aggregate:72-100).
* **AsDGan** (``fedml_api/distributed/asdgan/``): asymmetric split — the
  SERVER owns the generator; each CLIENT owns a private discriminator and
  its private real data.  Per iteration the server generates fake images
  from conditioning inputs and routes each fake to the client whose real
  sample conditioned it (AsDGanAggregator.forward_G:124-157); clients train
  D on (real, fake) and return ∂L_G/∂fake (AsDGanClientManager /
  add_local_grad:190-196); the server scatters the sample-weighted grads
  back into the batch and applies them to G
  (AsDGanAggregator.backward_G:159-187).

TPU-native design: AsDGan's grad round-trip is the chain rule split at
``fake_B`` — on-chip it is ONE jit program: G forward, per-client D losses
via vmap over stacked private D params, and ``jax.grad`` w.r.t. G params
computes exactly the scatter-aggregated gradient the wire protocol builds by
hand.  D updates stay per-client (vmapped, never averaged), preserving the
privacy topology.  FedGan reuses the cohort machinery: local adversarial
scan, weighted pytree mean of (G, D).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.pytree import tree_weighted_mean

Pytree = Any


def bce_logits(logits: jnp.ndarray, target: float) -> jnp.ndarray:
    """GAN BCE against a constant real/fake target."""
    t = jnp.full_like(logits, target)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, t))


@dataclasses.dataclass
class FedGanConfig:
    rounds: int = 5
    local_epochs: int = 1
    lr_g: float = 2e-4
    lr_d: float = 2e-4
    seed: int = 0


class FedGan:
    """FedAvg over the (G, D) pair; local loop = alternating D/G steps."""

    def __init__(self, generator, discriminator, cfg: FedGanConfig):
        self.G = generator
        self.D = discriminator
        self.cfg = cfg
        self.g_opt = optax.adam(cfg.lr_g, b1=0.5)
        self.d_opt = optax.adam(cfg.lr_d, b1=0.5)
        self._build()

    def _build(self):
        cfg = self.cfg

        def d_loss_fn(dp, gp, real, rng):
            z = jax.random.normal(rng, (real.shape[0], self.G.z_dim))
            fake = self.G.apply({"params": gp}, z)
            d_real = self.D.apply({"params": dp}, real)
            d_fake = self.D.apply({"params": dp}, fake)
            return bce_logits(d_real, 1.0) + bce_logits(d_fake, 0.0)

        def g_loss_fn(gp, dp, batch_size, rng):
            z = jax.random.normal(rng, (batch_size, self.G.z_dim))
            fake = self.G.apply({"params": gp}, z)
            return bce_logits(self.D.apply({"params": dp}, fake), 1.0)

        def local_train(params, data, rng):
            """One client's adversarial epoch(s); params = {"g","d"}."""
            gp, dp = params["g"], params["d"]
            g_state = self.g_opt.init(gp)
            d_state = self.d_opt.init(dp)

            def step(carry, xs):
                gp, dp, gs, ds = carry
                batch, step_rng = xs
                r1, r2 = jax.random.split(step_rng)
                dl, g_d = jax.value_and_grad(d_loss_fn)(dp, gp, batch["x"], r1)
                du, ds = self.d_opt.update(g_d, ds, dp)
                dp = optax.apply_updates(dp, du)
                gl, g_g = jax.value_and_grad(g_loss_fn)(
                    gp, dp, batch["x"].shape[0], r2)
                gu, gs = self.g_opt.update(g_g, gs, gp)
                gp = optax.apply_updates(gp, gu)
                return (gp, dp, gs, ds), {"d_loss": dl, "g_loss": gl}

            S = data["x"].shape[0]
            carry = (gp, dp, g_state, d_state)
            for _ in range(cfg.local_epochs):
                rng, ep_rng = jax.random.split(rng)
                carry, ms = jax.lax.scan(
                    step, carry, ({"x": data["x"]},
                                  jax.random.split(ep_rng, S)))
            gp, dp, _, _ = carry
            return {"g": gp, "d": dp}, ms

        self._cohort_train = jax.jit(jax.vmap(
            local_train, in_axes=(None, 0, 0)))

    def init(self, rng: jax.Array, sample_x: jnp.ndarray) -> Dict[str, Pytree]:
        rg, rd = jax.random.split(rng)
        z = jnp.zeros((1, self.G.z_dim))
        return {"g": self.G.init(rg, z)["params"],
                "d": self.D.init(rd, sample_x[:1])["params"]}

    def run(self, cohort: Dict[str, jnp.ndarray],
            rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        """cohort: {"x": [C, S, B, H, W, ch], "num_samples": [C]}."""
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        rng, init_rng = jax.random.split(rng)
        params = self.init(init_rng, cohort["x"][0, 0])
        C = cohort["x"].shape[0]
        weights = cohort.get("num_samples",
                             jnp.ones((C,), jnp.float32))
        history: List[Dict[str, float]] = []
        for rnd in range(cfg.rounds):
            rng, r = jax.random.split(rng)
            client_params, ms = self._cohort_train(
                params, {"x": cohort["x"]}, jax.random.split(r, C))
            params = tree_weighted_mean(client_params, weights)
            history.append({"round": rnd,
                            "d_loss": float(jnp.mean(ms["d_loss"])),
                            "g_loss": float(jnp.mean(ms["g_loss"]))})
        return {"params": params, "history": history}

    def sample(self, params: Dict[str, Pytree], rng: jax.Array, n: int):
        z = jax.random.normal(rng, (n, self.G.z_dim))
        return self.G.apply({"params": params["g"]}, z)


@dataclasses.dataclass
class AsDGanConfig:
    epochs: int = 5
    lr_g: float = 2e-4
    lr_d: float = 2e-4
    sample_method: str = "balance"   # 'balance' weights grads by n_c
    # reference G objective extras (client-side terms whose grads flow back
    # to the server G, AsDGanAggregator train loss bookkeeping :40-69):
    # L_G = GAN + lambda_l1 * L1(fake, b) + lambda_perceptual * VGG-feat MSE
    lambda_l1: float = 0.0
    lambda_perceptual: float = 0.0
    seed: int = 0


class AsDGan:
    """Server generator vs. per-client private discriminators."""

    def __init__(self, generator, discriminator, cfg: AsDGanConfig,
                 feat_params=None, feat_model=None):
        """``feat_params/feat_model``: optional pre-trained VGG16Features
        for the perceptual term (imported via utils.checkpoint from the
        torchvision weights the reference downloads); random-init is used
        when lambda_perceptual > 0 and none are given."""
        self.G = generator
        self.D = discriminator
        self.cfg = cfg
        self.g_opt = optax.adam(cfg.lr_g, b1=0.5)
        self.d_opt = optax.adam(cfg.lr_d, b1=0.5)
        if feat_params is not None and feat_model is None:
            raise ValueError(
                "feat_params were provided without feat_model; pass both "
                "(params must match the feature architecture)")
        self._feat_params = feat_params
        self._feat_model = feat_model
        self._build()

    def _build(self):
        cfg = self.cfg

        def d_step(dp, ds, gp, a, real):
            """One client's D update on (real, G(a)) — client-side."""
            fake = jax.lax.stop_gradient(self.G.apply({"params": gp}, a))

            def loss(dp):
                return (bce_logits(self.D.apply({"params": dp}, real), 1.0)
                        + bce_logits(self.D.apply({"params": dp}, fake), 0.0))

            dl, g = jax.value_and_grad(loss)(dp)
            du, ds = self.d_opt.update(g, ds, dp)
            return optax.apply_updates(dp, du), ds, dl

        def g_step(gp, gs, dps, a, b, weights):
            """Server G update: the weighted per-client ∂L_G/∂fake grads,
            aggregated through the chain rule in one jax.grad
            (= backward_G's hand-built scatter, AsDGanAggregator.py:159-187).
            The L1/perceptual reconstruction terms are CLIENT-side (computed
            against the client's private b; only their gradients reach the
            server G — same privacy topology as the reference).
            a, b: [C, B, H, W, ch]; dps: stacked per-client D params."""

            def loss(gp):
                fake = self.G.apply({"params": gp},
                                    a.reshape((-1,) + a.shape[2:]))
                fake = fake.reshape(a.shape[:2] + fake.shape[1:])

                def per_client(dp, f, real):
                    l = bce_logits(self.D.apply({"params": dp}, f), 1.0)
                    if cfg.lambda_l1:
                        l = l + cfg.lambda_l1 * jnp.mean(jnp.abs(f - real))
                    if cfg.lambda_perceptual:
                        from fedml_tpu.models import perceptual_loss
                        l = l + cfg.lambda_perceptual * perceptual_loss(
                            self._feat_params, self._feat_model, f, real)
                    return l

                losses = jax.vmap(per_client)(dps, fake, b)
                w = weights / jnp.maximum(jnp.sum(weights), 1e-8)
                return jnp.sum(losses * w)

            gl, g = jax.value_and_grad(loss)(gp)
            gu, gs = self.g_opt.update(g, gs, gp)
            return optax.apply_updates(gp, gu), gs, gl

        self._d_steps = jax.jit(jax.vmap(d_step,
                                         in_axes=(0, 0, None, 0, 0)))
        self._g_step = jax.jit(g_step)

    def run(self, data: Dict[str, jnp.ndarray],
            rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        """data: {"a": [C, S, B, H, W, ca] conditioning, "b": [C, S, B, H,
        W, cb] private real images, "num_samples": [C]}."""
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        C, S = data["a"].shape[:2]
        rg, rd = jax.random.split(rng)
        if cfg.lambda_perceptual and (self._feat_params is None
                                      or self._feat_model is None):
            from fedml_tpu.models import VGG16Features
            if self._feat_model is None:
                self._feat_model = VGG16Features()
            if self._feat_params is None:
                x0 = data["b"][0, 0]
                x0 = jnp.repeat(x0, 3, -1) if x0.shape[-1] == 1 else x0
                self._feat_params = self._feat_model.init(
                    jax.random.fold_in(rng, 77), x0)["params"]
        gp = self.G.init(rg, data["a"][0, 0])["params"]
        dp0 = self.D.init(rd, data["b"][0, 0])["params"]
        dps = jax.tree.map(lambda v: jnp.broadcast_to(v, (C,) + v.shape), dp0)
        gs = self.g_opt.init(gp)
        dss = jax.vmap(self.d_opt.init)(dps)
        weights = (data.get("num_samples", jnp.ones((C,), jnp.float32))
                   if cfg.sample_method == "balance"
                   else jnp.ones((C,), jnp.float32))
        history: List[Dict[str, float]] = []
        for epoch in range(cfg.epochs):
            d_losses, g_losses = [], []
            for s in range(S):
                a, b = data["a"][:, s], data["b"][:, s]
                dps, dss, dl = self._d_steps(dps, dss, gp, a, b)
                gp, gs, gl = self._g_step(gp, gs, dps, a, b, weights)
                # keep device scalars async; host-sync once per epoch
                d_losses.append(jnp.mean(dl))
                g_losses.append(gl)
            history.append({"epoch": epoch,
                            "d_loss": float(np.mean(jax.device_get(d_losses))),
                            "g_loss": float(np.mean(jax.device_get(g_losses)))})
        return {"g_params": gp, "d_params": dps, "history": history}

    def generate(self, g_params, a: jnp.ndarray) -> jnp.ndarray:
        return self.G.apply({"params": g_params}, a)
