"""Algorithm-family correctness:

* FedOpt with server sgd lr=1.0, momentum=0  ==  plain FedAvg (the pseudo-
  gradient step w - 1.0*(w - w_avg) = w_avg);
* FedProx mu=0  ==  FedAvg; mu>0 keeps client updates closer to global;
* FedNova with E=1, 1 batch, no momentum  ==  FedAvg (tau_eff degenerates);
* FedAvgRobust clip bound ~0 pins params to global; huge bound == FedAvg;
* DecentralizedGossip converges to consensus under full mixing; ring
  ppermute mesh version matches dense ring mixing;
* HierarchicalFedAvg with 1 group and group_comm_round=1 == FedAvg.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import (
    FedAvg, FedAvgConfig, FedOpt, FedOptConfig, FedProx, FedProxConfig,
    FedNova, FedNovaConfig, FedAvgRobust, FedAvgRobustConfig,
    DecentralizedGossip, DecentralizedConfig,
    HierarchicalFedAvg, HierarchicalConfig,
)
from fedml_tpu.data.stacking import stack_client_data, FederatedData
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _data(n_clients=6, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    W = rng.randn(dim, classes)
    xs, ys = [], []
    for _ in range(n_clients):
        n = rng.randint(10, 25)
        x = rng.randn(n, dim).astype(np.float32)
        y = np.argmax(x @ W, axis=1).astype(np.int32)
        xs.append(x); ys.append(y)
    train = stack_client_data(xs, ys, batch_size=30)  # 1 full batch each
    return FederatedData(client_num=n_clients, class_num=classes,
                         train=train, test=train)


@pytest.fixture(scope="module")
def workload():
    return ClassificationWorkload(LogisticRegression(8, 3), num_classes=3,
                                  grad_clip_norm=None)


def _tree_close(a, b, **kw):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(x, y, **kw), a, b)


def _run(algo_cls, cfg, workload, data, seed=11):
    algo = algo_cls(workload, data, cfg)
    p0 = algo.init_params(jax.random.key(seed))
    return algo.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(seed + 1)), p0


BASE = dict(comm_round=3, client_num_per_round=6, epochs=1, batch_size=30,
            lr=0.2, frequency_of_the_test=100)


def test_fedopt_sgd_lr1_equals_fedavg(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fo, _ = _run(FedOpt, FedOptConfig(**BASE, server_optimizer="sgd",
                                      server_lr=1.0, server_momentum=0.0),
                 workload, data)
    _tree_close(fa, fo, rtol=1e-5, atol=1e-6)


def test_fedopt_adam_runs_and_differs(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fo, _ = _run(FedOpt, FedOptConfig(**BASE, server_optimizer="adam",
                                      server_lr=0.01), workload, data)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), fa, fo))
    assert max(diffs) > 1e-4


def test_fedopt_unknown_optimizer(workload):
    with pytest.raises(ValueError, match="unknown server optimizer"):
        FedOpt(workload, _data(), FedOptConfig(**BASE, server_optimizer="nope"))


def test_fedprox_mu0_equals_fedavg(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fp, _ = _run(FedProx, FedProxConfig(**BASE, mu=0.0), workload, data)
    _tree_close(fa, fp, rtol=1e-6, atol=1e-7)


def test_fedprox_fedopt_fednova_ride_device_fast_path(workload, monkeypatch):
    """FedProx (local_train seam), FedOpt (_server_update hook), and
    FedNova (_device_round_override) are all served from the HBM-resident
    device round — and the device round lands on the SAME parameters as
    the host-gather path (identical sampling and rng, so bit-comparable)."""
    data = _data()
    for cls, cfg in ((FedProx, FedProxConfig(**BASE, mu=0.1)),
                     (FedOpt, FedOptConfig(**BASE, server_optimizer="adam",
                                           server_lr=0.01)),
                     (FedNova, FedNovaConfig(**BASE, gmf=0.9))):
        algo = cls(workload, data, cfg)
        dev = algo.run(params=algo.init_params(jax.random.key(0)))
        assert algo._train_dev is not None, (
            f"{cls.__name__} fell back to the host-gather path")
        # force the host path (device budget 0) and compare trajectories
        monkeypatch.setenv("FEDML_TPU_DEVICE_DATA_BYTES", "0")
        host_algo = cls(workload, data, cfg)
        host = host_algo.run(params=host_algo.init_params(jax.random.key(0)))
        monkeypatch.delenv("FEDML_TPU_DEVICE_DATA_BYTES")
        assert host_algo._train_dev is None
        _tree_close(dev, host, rtol=1e-6, atol=1e-6)


def test_fedprox_mu_pulls_towards_global(workload):
    data = _data()
    cfg = dict(BASE, epochs=5)
    fa, p0 = _run(FedAvg, FedAvgConfig(**cfg), workload, data)
    fp, _ = _run(FedProx, FedProxConfig(**cfg, mu=10.0), workload, data)
    from fedml_tpu.core.pytree import tree_vector_norm
    assert float(tree_vector_norm(fp, p0)) < float(tree_vector_norm(fa, p0))


def test_fednova_degenerate_equals_fedavg(workload):
    """E=1 with a single full batch: every client takes exactly one SGD step,
    a_i = 1, tau_eff = 1 => FedNova update == FedAvg weighted average."""
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fn, _ = _run(FedNova, FedNovaConfig(**BASE), workload, data)
    _tree_close(fa, fn, rtol=1e-4, atol=1e-5)


def test_fednova_momentum_runs(workload):
    data = _data()
    fn, p0 = _run(FedNova, FedNovaConfig(**dict(BASE, epochs=3),
                                         momentum=0.9, gmf=0.5), workload, data)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), fn, p0))
    assert max(diffs) > 1e-3
    assert all(np.isfinite(x) for leaf in jax.tree.leaves(fn)
               for x in np.asarray(leaf).ravel())


def test_robust_clip_zero_bound_freezes(workload):
    data = _data()
    cfg = FedAvgRobustConfig(**BASE, defense="norm_diff_clipping",
                             norm_bound=1e-9)
    fr, p0 = _run(FedAvgRobust, cfg, workload, data)
    _tree_close(fr, p0, rtol=0, atol=1e-6)


def test_robust_huge_bound_equals_fedavg(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fr, _ = _run(FedAvgRobust, FedAvgRobustConfig(
        **BASE, defense="norm_diff_clipping", norm_bound=1e9), workload, data)
    _tree_close(fa, fr, rtol=1e-5, atol=1e-6)


def test_robust_weak_dp_noise_moves_params(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fr, _ = _run(FedAvgRobust, FedAvgRobustConfig(
        **BASE, defense="weak_dp", norm_bound=1e9, stddev=0.1), workload, data)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), fa, fr))
    assert max(diffs) > 1e-3


def test_gossip_reaches_consensus(workload):
    data = _data(n_clients=8)
    cfg = DecentralizedConfig(comm_round=12, epochs=1, batch_size=30, lr=0.05,
                              neighbor_num=4, frequency_of_the_test=100)
    g = DecentralizedGossip(workload, data, cfg)
    stacked = g.run()
    # all nodes should be close after repeated mixing (row-stochastic W)
    spread = jax.tree.leaves(jax.tree.map(
        lambda x: float(jnp.max(jnp.abs(x - x.mean(0, keepdims=True)))),
        stacked))
    assert max(spread) < 0.5


def test_ring_mesh_gossip_matches_dense(workload, devices):
    from fedml_tpu.parallel.mesh import make_mesh
    data = _data(n_clients=8)
    mesh = make_mesh(devices=devices, client_axis=8, model_axis=1)
    cfg = DecentralizedConfig(comm_round=3, epochs=1, batch_size=30, lr=0.05,
                              frequency_of_the_test=100)
    # dense version with the uniform ring matrix (self + both neighbors @ 1/3)
    W = np.zeros((8, 8), np.float32)
    for i in range(8):
        W[i, i] = W[i, (i - 1) % 8] = W[i, (i + 1) % 8] = 1 / 3
    g_dense = DecentralizedGossip(workload, data, cfg, topology=W)
    g_mesh = DecentralizedGossip(workload, data, cfg, mesh=mesh)
    rng = jax.random.key(0)
    sd = g_dense.run(rng=rng)
    sm = g_mesh.run(rng=rng)
    _tree_close(sd, sm, rtol=1e-4, atol=1e-5)


def test_hierarchical_single_group_equals_fedavg(workload):
    data = _data()
    fa, _ = _run(FedAvg, FedAvgConfig(**BASE), workload, data)
    fh, _ = _run(HierarchicalFedAvg, HierarchicalConfig(
        **BASE, group_num=1, group_comm_round=1), workload, data)
    _tree_close(fa, fh, rtol=1e-5, atol=1e-6)


def test_hierarchical_multi_group_runs(workload):
    data = _data(n_clients=10)
    cfg = HierarchicalConfig(comm_round=4, client_num_per_round=6, epochs=1,
                             batch_size=30, lr=0.2, frequency_of_the_test=2,
                             group_num=3, group_comm_round=2)
    algo = HierarchicalFedAvg(workload, data, cfg)
    algo.run()
    assert algo.history and np.isfinite(algo.history[-1]["train_acc"])


def test_hierarchical_vmapped_groups_match_sequential_replay(workload):
    """The batched [G, M, ...] group axis must equal a host-side python
    replay of the same per-group rng/cohort semantics (the group tier was a
    Python loop before; vmapping it must not change the math)."""
    from fedml_tpu.algorithms.hierarchical import make_grouped_round
    from fedml_tpu.core.pytree import tree_weighted_mean
    from fedml_tpu.data.stacking import gather_cohort
    from fedml_tpu.parallel.cohort import train_cohort

    data = _data(n_clients=9)
    cfg = HierarchicalConfig(comm_round=1, client_num_per_round=6, epochs=1,
                             batch_size=30, lr=0.2, group_num=3,
                             group_comm_round=2)
    algo = HierarchicalFedAvg(workload, data, cfg)
    p0 = algo.init_params(jax.random.key(3))
    groups = algo._group_clients(np.arange(6))
    cohorts = [gather_cohort(data.train, groups.get(g, []),
                             pad_to=cfg.client_num_per_round)
               for g in range(cfg.group_num)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *cohorts)
    rr = jax.random.key(5)
    batched = algo._grouped_round(p0, stacked, rr)

    # python replay with identical fold_in streams
    gp, gw = [], []
    for g in range(cfg.group_num):
        r = jax.random.fold_in(rr, g)
        p = p0
        w = np.asarray(cohorts[g]["num_samples"], np.float32)
        for _ in range(cfg.group_comm_round):
            r, rloc = jax.random.split(r)
            if w.sum() > 0:
                st, _ = train_cohort(algo._local_train, p, cohorts[g], rloc)
                p = tree_weighted_mean(st, cohorts[g]["num_samples"])
        gp.append(p)
        gw.append(w.sum())
    replay = tree_weighted_mean(gp, jnp.asarray(gw))
    _tree_close(batched, replay, rtol=1e-5, atol=1e-6)


def test_hierarchical_two_level_mesh_matches_vmapped(workload):
    """The [groups, clients] two-level mesh (group psum over ICI, global
    psum over DCN) must produce the SAME model as the single-chip vmapped
    path — same fold_in(group)/split rng streams, same client slot
    numbering, so simulation and pod execution are interchangeable."""
    from fedml_tpu.parallel.mesh import make_two_level_mesh

    data = _data(n_clients=8)
    cfg = HierarchicalConfig(comm_round=3, client_num_per_round=8, epochs=1,
                             batch_size=30, lr=0.2, group_num=2,
                             group_comm_round=2, frequency_of_the_test=100)
    mesh = make_two_level_mesh(group_axis=2, client_axis=4)
    single = HierarchicalFedAvg(workload, data, cfg)
    two = HierarchicalFedAvg(workload, data, cfg, mesh=mesh)
    p0 = single.init_params(jax.random.key(9))
    ps = single.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(4))
    pt = two.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(4))
    _tree_close(ps, pt, rtol=1e-4, atol=1e-5)


def test_hierarchical_empty_group_is_noop(workload):
    """A group that receives no sampled clients must pass params through
    (not poison the global mean with NaNs)."""
    data = _data(n_clients=6)
    cfg = HierarchicalConfig(comm_round=2, client_num_per_round=4, epochs=1,
                             batch_size=30, lr=0.2, group_num=5,
                             group_comm_round=1, frequency_of_the_test=1)
    algo = HierarchicalFedAvg(workload, data, cfg)
    # force most groups empty
    algo.group_indexes = np.zeros(6, dtype=np.int64)
    algo.run()
    assert np.isfinite(algo.history[-1]["train_acc"])
    assert np.isfinite(algo.history[-1]["train_loss"])
