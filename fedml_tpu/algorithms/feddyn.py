"""FedDyn (Acar et al. 2021, arXiv:2111.04263) — dynamic regularization
that makes the federated fixed point coincide with the CENTRALIZED
optimum under arbitrary client heterogeneity.

Beyond the reference's algorithm list: its heterogeneity answers are
FedProx's proximal pull (biased fixed point) and FedNova's normalization
(step-count skew only); SCAFFOLD (algorithms/scaffold.py) corrects drift
variance but not the E→∞ fixed-point bias.  FedDyn fixes the fixed point
itself: each client k keeps a linear correction λ_k so that at
convergence the sum of local first-order conditions telescopes into the
global one (the "exactness under client drift" test pins this — FedAvg
with many local epochs converges to the mean of client optima, FedDyn to
the true global optimum).

Algorithm 1 of the paper, in cohort-engine form (per-client persistent
state rides the stacked-pytree helpers shared with SCAFFOLD/Ditto):

    local:   θ_k ← argmin_θ  L_k(θ) − ⟨λ_k, θ⟩ + (α/2)‖θ − θ^t‖²
             (SGD: g = ∇L_k(θ) − λ_k + α(θ − θ^t), clip AFTER correction)
    state:   λ_k ← λ_k − α(θ_k − θ^t)            (sampled clients only)
    server:  h ← h − (α/N)·Σ_{k∈S}(θ_k − θ^t)
             θ^{t+1} = mean_{k∈S}(θ_k) − h/α      (UNIFORM mean, paper)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import (FedAvg, FedAvgConfig,
                                         gather_client_rows,
                                         scatter_client_rows,
                                         zeros_client_state)
from fedml_tpu.trainer.workload import Workload

Pytree = Any


@dataclasses.dataclass
class FedDynConfig(FedAvgConfig):
    feddyn_alpha: float = 0.01  # the paper's α (regularization strength)


def make_feddyn_local(workload: Workload, lr: float, epochs: int,
                      alpha: float):
    """``train(theta_ref, lam, data, rng) -> theta`` — the regularized
    local solver.  Starts from the round's global weights; the gradient
    carries the −λ_k linear term and the α(θ − θ^t) proximal term, with
    the workload's ``grad_clip_norm`` honored AFTER the correction (the
    corrected-then-clipped ordering every stateful trainer here uses).
    Fully-padded batches freeze the carry (ragged clients)."""
    import optax
    clip = (optax.clip_by_global_norm(workload.grad_clip_norm)
            if workload.grad_clip_norm is not None else None)
    grad_fn = jax.grad(lambda p, b, r: workload.loss_fn(p, b, r, True)[0])

    def train(theta_ref: Pytree, lam: Pytree, data: Dict[str, jax.Array],
              rng: jax.Array):
        num_steps = jax.tree.leaves(data)[0].shape[0]
        clip_state = clip.init(theta_ref) if clip is not None else None

        def step(carry, step_idx):
            theta, rng = carry
            rng, drng = jax.random.split(rng)
            batch = jax.tree.map(lambda x: x[step_idx % num_steps], data)
            grads = grad_fn(theta, batch, drng)
            grads = jax.tree.map(
                lambda g, li, t, tr: g - li + alpha * (t - tr),
                grads, lam, theta, theta_ref)
            if clip is not None:
                grads, _ = clip.update(grads, clip_state)
            gd = (jnp.sum(batch["mask"]) > 0).astype(jnp.float32)
            theta = jax.tree.map(lambda p, g: p - lr * gd * g,
                                 theta, grads)
            return (theta, rng), None

        (theta, _), _ = jax.lax.scan(step, (theta_ref, rng),
                                     jnp.arange(epochs * num_steps))
        return theta

    return train


class FedDyn(FedAvg):
    """FedAvg.run drives this via the replaced ``cohort_step`` (host-gather
    path — the stacked λ_k state is scattered back per round).  Client ids
    are re-derived from the seeded sampling chain, the SCAFFOLD pattern.

    ``mesh=`` shards the cohort's clients axis across devices (shard_map +
    psum; matches single-chip to float tolerance — parity-tested); the
    λ_k state stays host-resident either way.  Multi-process meshes
    ride the shared wrap (make_sharded_stateful_round: global input
    staging + replicated state outputs; every process mirrors the state)."""

    def __init__(self, workload, data, config: FedDynConfig, mesh=None,
                 sink=None):
        if config.client_optimizer != "sgd":
            raise ValueError(
                "feddyn's local solver is SGD on the dynamically "
                "regularized objective (Acar'21 Alg. 1); "
                "--client_optimizer sgd only")
        if getattr(workload, "stateful", False):
            raise ValueError(
                "feddyn does not support stateful (BatchNorm) workloads: "
                "the λ correction over running statistics is undefined — "
                "use a GroupNorm model (e.g. resnet18_gn)")
        if config.feddyn_alpha <= 0.0:
            raise ValueError("feddyn_alpha must be > 0 (the server step "
                             "divides by it)")
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        alpha = cfg.feddyn_alpha
        self._round_counter = 0
        self.h_state = None
        self.lam_locals = None  # stacked [client_num_in_total, ...]
        local = make_feddyn_local(workload, cfg.lr, cfg.epochs, alpha)

        def _core(params, cohort, rng, h, lam_cohort,
                  psum_axis=None, index_offset=0):
            """One FedDyn round over (a shard of) the cohort — the ONE body
            both execution paths share (the SCAFFOLD/FedNova shared-core
            pattern): single-chip calls it with no axis; the mesh path
            per-device with psum reductions and the shard's global slot
            offset for rng folding (parallel/cohort.py convention)."""
            def allsum(x):
                return (jax.lax.psum(x, psum_axis)
                        if psum_axis is not None else x)

            n = cohort["num_samples"].shape[0]
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(n) + index_offset)
            batches = {k: v for k, v in cohort.items()
                       if k != "num_samples"}
            thetas = jax.vmap(local, in_axes=(None, 0, 0, 0))(
                params, lam_cohort, batches, rngs)
            live = (cohort["num_samples"] > 0).astype(jnp.float32)
            m_live = jnp.maximum(allsum(jnp.sum(live)), 1.0)

            def _live_mean(y):
                return allsum(jnp.sum(
                    y * live.reshape((-1,) + (1,) * (y.ndim - 1)),
                    axis=0)) / m_live

            # λ_k ← λ_k − α(θ_k − θ^t); padded slots frozen
            new_lam = jax.tree.map(
                lambda li, y, x: jnp.where(
                    live.reshape((-1,) + (1,) * (y.ndim - 1)) > 0,
                    li - alpha * (y - x[None]), li),
                lam_cohort, thetas, params)
            # h ← h − (α/N)·Σ_{k∈S}(θ_k − θ^t)
            new_h = jax.tree.map(
                lambda hh, y, x: hh - alpha * (m_live / self.data.client_num)
                * _live_mean(y - x[None]),
                h, thetas, params)
            # θ^{t+1} = uniform mean of cohort models − h/α
            new_params = jax.tree.map(
                lambda y, hh: _live_mean(y) - hh / alpha, thetas, new_h)
            return new_params, new_lam, new_h

        if mesh is None:
            self._round_step = jax.jit(_core)
        else:
            from jax.sharding import PartitionSpec as P
            from fedml_tpu.parallel.cohort import make_sharded_stateful_round
            self._round_step = make_sharded_stateful_round(
                _core, mesh,
                in_specs=(P(), P("clients"), P(), P(), P("clients")),
                out_specs=(P(), P("clients"), P()))
        self.cohort_step = self._stateful_step

    def run(self, params=None, rng=None, checkpointer=None):
        # fresh runs restart the sampling-chain mirror AND the correction
        # state; a checkpoint resume restores both via _load_extra_state
        self._round_counter = 0
        self.h_state = None
        self.lam_locals = None
        return super().run(params=params, rng=rng, checkpointer=checkpointer)

    def _stateful_step(self, params, cohort, rng):
        if self.h_state is None:
            self.h_state = jax.tree.map(jnp.zeros_like, params)
            self.lam_locals = zeros_client_state(params,
                                                 self.data.client_num)
        # THE loop's own sampling hook (not sample_clients directly), so a
        # subclass overriding _sample_round cannot desync the state mirror
        ids = self._sample_round(self._round_counter)
        self._round_counter += 1
        lam_cohort = gather_client_rows(self.lam_locals, ids,
                                        cohort["num_samples"].shape[0])
        params, new_lam, self.h_state = self._round_step(
            params, cohort, rng, self.h_state, lam_cohort)
        self.lam_locals = scatter_client_rows(self.lam_locals, ids,
                                              new_lam)
        return params, {}

    # correction state rides the round checkpoint (async saves snapshot
    # the mutable numpy buffers — RoundCheckpointer.save)
    def _extra_state(self):
        return {"h_state": self.h_state, "lam_locals": self.lam_locals,
                "round_counter": self._round_counter}

    def _extra_state_template(self, params):
        return {"h_state": jax.tree.map(jnp.zeros_like, params),
                "lam_locals": zeros_client_state(params,
                                                 self.data.client_num),
                "round_counter": 0}

    def _load_extra_state(self, extra) -> None:
        self.h_state = extra["h_state"]
        # stacked state is host-resident by convention (fedavg.py)
        self.lam_locals = jax.tree.map(np.asarray, extra["lam_locals"])
        self._round_counter = int(extra["round_counter"])
