"""The shard spine bundle: plan + sharded fold + sharded admission, and
the wire helpers both actor ends speak.

Server side, `ShardSpine` is what `--model_shards S` hands
`FedAvgServerActor` (``shard_wire=``): it owns the per-round broadcast
slices (one encode-once `SharedPayload` fan-out PER SHARD — S payload
serializations per round, never one per receiver), the per-silo upload
assembly + admission, and the plan identity the round checkpoint
records (``extra_state`` hook) so a resume re-derives — and verifies —
the identical layout.

Silo side, `SiloShardAssembler` banks a round's inbound shard slices
until all S arrived (any order), joins them into the params tree the
train fn consumes, and splits the trained tree back into upload slices
— all driven by the plan spec riding shard 0's sync frame, so a silo
needs ZERO shard configuration (the secagg sync-frame discipline).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from fedml_tpu.shard_spine.admission import ShardAdmission
from fedml_tpu.shard_spine.agg import ShardedStreamingAggregator
from fedml_tpu.shard_spine.plan import (ShardPlan, SiloShardCodec,
                                        build_shard_plan)

log = logging.getLogger(__name__)


class ShardSpine:
    """Everything the sharded round needs, built once per federation."""

    def __init__(self, plan: ShardPlan, agg: ShardedStreamingAggregator,
                 admission: Optional[ShardAdmission]):
        self.plan = plan
        self.agg = agg
        self.admission = admission
        self._spec = plan.spec()

    @property
    def num_shards(self) -> int:
        return self.plan.num_shards

    # -- server round lifecycle ----------------------------------------------
    def round_start(self, host_params) -> None:
        if self.admission is not None:
            self.admission.round_start(host_params)

    def round_end(self) -> None:
        if self.admission is not None:
            self.admission.round_end()

    def broadcast_slices(self, host_params) -> List[dict]:
        """The round's per-shard broadcast payloads (host views — each
        becomes ONE `SharedPayload` for the whole cohort)."""
        import jax
        leaves = [np.asarray(x) for x in jax.tree.leaves(host_params)]
        return self.plan.split_leaves(leaves)

    def spec(self) -> dict:
        """The plan descriptor shard 0's sync frame ships (static
        across rounds — silos rebuild split/join from it alone)."""
        return self._spec

    def join(self, slices: List[dict]):
        """Slices -> full host tree (the health observatory's view of
        an admitted upload)."""
        import jax
        leaves = self.plan.join_slices(slices)
        return jax.tree.unflatten(self.agg._treedef, leaves)

    # -- checkpoint identity (extra_state hook) ------------------------------
    def checkpoint_state(self) -> Dict[str, np.ndarray]:
        """Fixed-shape record of the layout for the round checkpoint:
        a resume re-derives the plan from the same (template, S,
        threshold) and VERIFIES the fingerprint matches — restoring
        sharded state under a silently different layout is the one
        mistake this subsystem must make impossible."""
        return {"num_shards": np.asarray(self.plan.num_shards, np.int64),
                "plan_fp": np.asarray(self.plan.fingerprint(), np.int64)}

    def restore_checkpoint_state(self, state) -> None:
        want_s = int(np.asarray(state["num_shards"]))
        want_fp = int(np.asarray(state["plan_fp"]))
        if want_s != self.plan.num_shards:
            raise ValueError(
                f"checkpoint was written under --model_shards {want_s} "
                f"but this run uses {self.plan.num_shards}; resume with "
                f"the original shard count (the layout is part of the "
                f"checkpointed state)")
        if want_fp != self.plan.fingerprint():
            raise ValueError(
                "checkpoint records a different shard-plan fingerprint "
                "than this run re-derived (the model or split threshold "
                "changed); refusing to resume under a mismatched layout")

    # the journal round-mode tag: recovery refuses a journal written by
    # a different aggregation configuration (plain <-> sharded, or a
    # different S) instead of unflattening foreign fold state
    def journal_mode(self) -> str:
        return f"shard_mean[S={self.plan.num_shards}]"


def build_shard_spine(template, *, num_shards: int,
                      norm_clip: float = 0.0, noise_std: float = 0.0,
                      seed: int = 0, fused: str = "auto",
                      admission_on: bool = True,
                      max_num_samples: float = 1e6, norm_k: float = 6.0,
                      norm_window: int = 64, norm_min_history: int = 8,
                      trust=None, min_split_elems: int = 1024,
                      mesh="auto", sentry=None, device=None) -> ShardSpine:
    """Build the spine from the live template.

    ``fused``: ``"on"`` wires the Pallas finalize unconditionally
    (``interpret=True`` off-TPU — the parity/proof mode); ``"auto"``
    compiles it on TPU and keeps the XLA compose on CPU (an interpreted
    kernel is a correctness tool, not a speedup — the honest default);
    ``"off"`` keeps the XLA compose everywhere.

    ``mesh="auto"``: build a ``[1, S]`` model mesh when the host has at
    least S devices (each shard's fold state then lives on its own
    device); pass None to force placement-free, or a mesh to reuse one.
    """
    if fused not in ("auto", "on", "off"):
        raise ValueError(f"fused must be auto|on|off, got {fused!r}")
    import jax
    backend = jax.default_backend()
    use_fused = fused == "on" or (fused == "auto" and backend == "tpu")
    interpret = backend != "tpu"
    if mesh == "auto":
        from fedml_tpu.parallel.mesh import make_model_mesh
        mesh = make_model_mesh(num_shards)
        if mesh is None and num_shards > 1:
            log.info("--model_shards %d on a %d-device host: shards "
                     "share the default device (same math; per-device "
                     "memory split needs >= %d devices)",
                     num_shards, len(jax.devices()), num_shards)
    plan = build_shard_plan(template, num_shards,
                            min_split_elems=min_split_elems)
    agg = ShardedStreamingAggregator(
        plan, template, norm_clip=norm_clip, noise_std=noise_std,
        seed=seed, fused=use_fused, interpret=interpret, mesh=mesh,
        sentry=sentry, device=device)
    admission = None
    if admission_on:
        admission = ShardAdmission(
            plan, template, max_num_samples=max_num_samples,
            norm_k=norm_k, norm_window=norm_window,
            norm_min_history=norm_min_history, trust=trust)
    return ShardSpine(plan, agg, admission)


class SiloShardAssembler:
    """Client-side shard choreography: bank sync slices per round until
    complete, join for training, split the trained tree for upload."""

    def __init__(self):
        self._codec: Optional[SiloShardCodec] = None
        self._round: Optional[int] = None
        self._slices: Dict[int, dict] = {}
        self._meta: Dict[str, object] = {}

    def offer(self, round_idx, shard, num_shards, slice_payload,
              spec: Optional[dict], meta: Optional[dict] = None) -> bool:
        """Bank one sync slice; returns True when the round's model is
        complete.  ``spec`` rides shard 0's frame; ``meta`` (client_idx,
        EF ack, ...) is banked from whichever frame carries it."""
        if spec is not None:
            if self._codec is None \
                    or self._codec.fingerprint != ShardPlan.from_spec(
                        spec).fingerprint():
                self._codec = SiloShardCodec(spec)
        if self._codec is None:
            log.warning("shard slice arrived before any plan spec; "
                        "dropping it (shard 0's frame carries the spec)")
            return False
        if num_shards is not None \
                and int(num_shards) != self._codec.num_shards:
            log.warning("shard slice claims %s shards but the plan has "
                        "%d; dropping it", num_shards,
                        self._codec.num_shards)
            return False
        if round_idx != self._round:
            if self._round is not None and round_idx is not None \
                    and round_idx < self._round:
                # a STALE frame (chaos delay/dup of an older round) must
                # not destroy the current round's partial assembly —
                # only a NEWER round supersedes it
                log.info("dropping stale round-%s shard slice (current "
                         "round %s)", round_idx, self._round)
                return False
            self._round = round_idx
            self._slices = {}
            self._meta = {}
        if meta:
            self._meta.update(meta)
        try:
            shard = int(shard)
        except (TypeError, ValueError):
            shard = -1
        if not 0 <= shard < self._codec.num_shards:
            # a mislabeled frame banked out of range would make the
            # completion count lie and take() KeyError mid-handler —
            # drop it like the server-side ShardAdmission does
            log.warning("dropping shard slice with out-of-range index "
                        "%s (plan has %d shards)", shard,
                        self._codec.num_shards)
            return False
        self._slices[shard] = slice_payload
        return len(self._slices) == self._codec.num_shards

    def take(self):
        """The completed round's ``(params_tree, meta)``; clears the
        bank."""
        slices = [self._slices[s]
                  for s in range(self._codec.num_shards)]
        params = self._codec.join(slices)
        meta = dict(self._meta)
        self._slices = {}
        self._meta = {}
        return params, meta

    def split_upload(self, new_params) -> List[dict]:
        if self._codec is None:
            raise RuntimeError("split_upload before any sync: no plan "
                               "spec has arrived")
        host = _as_host(new_params)
        return self._codec.split(host)

    @property
    def num_shards(self) -> Optional[int]:
        return None if self._codec is None else self._codec.num_shards


def _as_host(tree):
    import jax
    return jax.tree.map(np.asarray, tree)
