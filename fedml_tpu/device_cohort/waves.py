"""Static device-sized waves: the unit of compiled cross-device training.

A mega-cohort round (1k-100k sampled clients) cannot train as one vmap —
the stacked cohort would not fit HBM, and a dynamic cohort shape would
re-jit every round.  `plan_waves` chops the sampled cohort into
fixed-size waves (the last one padded with weight-0 slots, the
`gather_cohort` convention), so every wave of every round hits ONE jit
cache entry; `make_wave_fn` compiles the wave: local training over the
stacked client axis (`parallel/cohort.train_cohort` — vmap on one chip,
shard_map over the mesh's ``clients`` axis), plus the wave SUMMARY the
host needs for admission/health — the weighted partial mean, the weight
total, and any per-client aux reductions — computed on device so the
host never walks the ``[wave, ...]`` stack.

Per-client rng = fold_in(round_rng, global cohort slot) via the wave's
``offset`` (a traced scalar, so chunking does not retrace): a
wave-chunked round trains bit-identically to a single-wave round, and
to the plain FedAvg cohort engine on the same seed.

`WaveAdmission` is the per-wave screen: structural fingerprint, finite
guard, and a rolling median+MAD norm-outlier screen over the wave
summary (the same statistics `robust/admission.py` runs per upload on
the live wire — reused here at wave granularity, because inside a
compiled wave there is no per-client payload to screen).  A rejected
wave contributes weight 0: its clients' work is discarded for the
round, which is the honest granularity of a compiled wave.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.core.pytree import acc_dtype
# new-vs-old jax shard_map/pcast compat lives with the cohort engine —
# THE one home for the convention (parallel/cohort.py)
from fedml_tpu.parallel.cohort import (compat_pcast_varying,
                                       compat_shard_map)
# per-wave screens reuse the live admission pipeline's statistics
# helpers so wave screening can never drift from upload screening
from fedml_tpu.robust.admission import (AdmissionVerdict, _all_finite,
                                        _leaves, _update_norm,
                                        norm_outlier_threshold,
                                        params_fingerprint)
from fedml_tpu.obs import telemetry

Pytree = Any


@dataclasses.dataclass(frozen=True)
class Wave:
    """One static-size slice of the round's sampled cohort.

    ``ids``: the LIVE client ids (length <= wave_size; `gather_cohort`
    pads the rest with weight-0 dummy slots).  ``offset``: this wave's
    first global cohort-slot index — the per-client rng fold anchor.
    """
    ids: np.ndarray
    offset: int

    @property
    def n_live(self) -> int:
        return len(self.ids)


def plan_waves(ids: Sequence[int], wave_size: int) -> List[Wave]:
    """Chop the sampled cohort into ``wave_size`` chunks (last padded by
    the gather).  Every wave is the SAME static shape, so the whole
    round — any cohort size — costs one jit cache entry."""
    if wave_size < 1:
        raise ValueError(f"wave_size must be >= 1, got {wave_size}")
    ids = np.asarray(ids, dtype=np.int64)
    return [Wave(ids=ids[lo:lo + wave_size], offset=lo)
            for lo in range(0, max(len(ids), 1), wave_size)]


def _wave_summary(stacked: Pytree, w: jax.Array, aux: Dict[str, jax.Array],
                  psum_axis: Optional[str] = None):
    """Device-side wave summary: weighted partial mean (acc-dtype
    accumulation, the `tree_weighted_mean` contract), weight total, and
    weighted sums of per-client aux arrays.  With ``psum_axis`` the
    reductions ride ICI (the shard_map path)."""
    def allsum(x):
        return jax.lax.psum(x, psum_axis) if psum_axis is not None else x

    total = allsum(jnp.sum(w))
    # all-pad waves (total 0) divide by the guard, not 0 — the engine
    # skips them by weight before the mean is ever read
    ratio = w / jnp.maximum(total, 1e-6)

    def _mean(x):
        acc = acc_dtype(x.dtype)
        r = ratio.reshape((-1,) + (1,) * (x.ndim - 1))
        return allsum(jnp.sum(x.astype(acc) * r.astype(acc),
                              axis=0)).astype(x.dtype)

    mean = jax.tree.map(_mean, stacked)
    aux_sums = {k: allsum(jnp.sum(
        v.astype(jnp.float32)
        * w.reshape((-1,) + (1,) * (v.ndim - 1)), axis=0))
        for k, v in aux.items()}
    return mean, total, aux_sums


def make_wave_fn(make_stacked: Callable, mesh: Optional[Mesh] = None):
    """Compile one wave: ``wave_fn(params, wave_data, rng, offset) ->
    (stacked_uploads, weights, wave_mean, wave_weight, aux_sums)``.

    ``make_stacked(params, wave_data, rng, offset) -> (stacked, aux)``
    is the jit-able per-wave trainer (typically `train_cohort` over a
    local trainer); ``aux`` maps names to per-client ``[wave, ...]``
    arrays that reduce to weighted sums (e.g. FedNova's tau terms).

    ``offset`` must be a traced scalar (pass ``jnp.int32(lo)``) so every
    wave of every round shares ONE jit cache entry.  On a mesh the wave
    shards over the ``clients`` axis (stacked outputs stay sharded, the
    summary is psum'd replicated); the stacked outputs are identical to
    the single-chip wave bit for bit (the `train_cohort` rng contract),
    so the host-ordered streaming fold downstream agrees too."""
    if mesh is None:
        @jax.jit
        def wave_fn(params, wave_data, rng, offset):
            stacked, aux = make_stacked(params, wave_data, rng, offset)
            w = wave_data["num_samples"].astype(jnp.float32)
            mean, total, aux_sums = _wave_summary(stacked, w, aux)
            return stacked, w, mean, total, aux_sums
        return wave_fn

    def _sharded(params, wave_data, rng, offset):
        # per-device: wave_data leaves are the local shard [W/D, ...];
        # params/rng arrive replicated — mark them device-varying so the
        # local-train scan carry typechecks (parallel/cohort.py idiom)
        params = compat_pcast_varying(params, ("clients",))
        rng = compat_pcast_varying(rng, ("clients",))
        local_c = wave_data["num_samples"].shape[0]
        local_off = offset + jax.lax.axis_index("clients") * local_c
        stacked, aux = make_stacked(params, wave_data, rng, local_off)
        w = wave_data["num_samples"].astype(jnp.float32)
        mean, total, aux_sums = _wave_summary(stacked, w, aux,
                                              psum_axis="clients")
        return stacked, w, mean, total, aux_sums

    sharded = compat_shard_map(
        _sharded, mesh=mesh,
        in_specs=(P(), P("clients"), P(), P()),
        out_specs=(P("clients"), P("clients"), P(), P(), P()))
    n_dev = mesh.shape["clients"]

    @jax.jit
    def wave_fn(params, wave_data, rng, offset):
        W = wave_data["num_samples"].shape[0]
        if W % n_dev:  # static shape — checked at trace time
            raise ValueError(
                f"wave size {W} not divisible by the mesh clients axis "
                f"({n_dev}); pick --wave_size as a multiple of the "
                f"device count")
        return sharded(params, wave_data, rng, offset)

    return wave_fn


def make_scaffold_wave_fn(scaffold_local, lr: float):
    """SCAFFOLD's wave (single-chip vmap; the control variates are
    host-resident stacked state, `algorithms/fedavg.py` convention):

    ``wave_fn(params, wave_data, rng, offset, c_global, c_cohort) ->
    (stacked_y, weights, wave_mean, wave_weight, new_c_cohort,
    c_delta_sum, live_count)``

    Padded slots (weight 0) freeze their aliased ``c`` rows and
    contribute nothing to the c-delta sum, exactly like the in-tree
    `algorithms/scaffold.Scaffold._core`."""

    @jax.jit
    def wave_fn(params, wave_data, rng, offset, c_global, c_cohort):
        n = wave_data["num_samples"].shape[0]
        # the train_cohort rng convention (fold_in(rng, global slot)),
        # restated because scaffold_local's extra per-client c_diff arg
        # doesn't fit train_cohort's (params, batch, rng) vmap — the
        # same restatement algorithms/scaffold.Scaffold._core makes,
        # and the engine's scaffold-vs-Scaffold parity test pins all
        # three spellings together (a drifting convention fails there)
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(n) + offset)
        batches = {k: v for k, v in wave_data.items() if k != "num_samples"}
        c_diffs = jax.tree.map(lambda cg, ci: cg[None] - ci,
                               c_global, c_cohort)
        ys, ks = jax.vmap(scaffold_local, in_axes=(None, 0, 0, 0))(
            params, batches, rngs, c_diffs)
        w = wave_data["num_samples"].astype(jnp.float32)
        live = (w > 0).astype(jnp.float32)
        k_safe = jnp.maximum(ks, 1.0)
        # c_i+ = c_i − c + (x − y_i)/(K·lr); frozen for padded slots
        new_c = jax.tree.map(
            lambda ci, cg, x, y: jnp.where(
                live.reshape((-1,) + (1,) * x.ndim) > 0,
                ci - cg[None] + (x[None] - y)
                / (k_safe.reshape((-1,) + (1,) * x.ndim) * lr),
                ci),
            c_cohort, c_global, params, ys)
        c_delta = jax.tree.map(
            lambda nci, ci: jnp.sum(
                (nci - ci) * live.reshape((-1,) + (1,) * (nci.ndim - 1)),
                axis=0),
            new_c, c_cohort)
        mean, total, _ = _wave_summary(ys, w, {})
        return ys, w, mean, total, new_c, c_delta, jnp.sum(live)

    return wave_fn


class WaveAdmission:
    """Per-wave admission: the structural fingerprint, finite guard, and
    rolling median+MAD norm screen of `robust.AdmissionPipeline`, run
    against each wave's weighted partial mean instead of per upload.

    Rejection reasons land in
    ``fedml_cohort_wave_rejected_total{reason}`` and in the in-process
    ``rejected`` mirror; there is no trust ledger — a wave index is a
    position in a freshly-sampled cohort, not a persistent identity, so
    striking it would quarantine an arbitrary slice of future cohorts.

    The norm history resets at ``round_start`` (unlike the live
    pipeline's cross-round silo history): wave means of ONE round are
    the exchangeable population — update norms drift round-over-round
    as training converges (and change regime outright when, e.g.,
    SCAFFOLD's control variates arm after round 0), so a cross-round
    history rejects honest waves on drift alone (observed, pinned).
    Consequence: the screen arms only in rounds with more than
    ``norm_min_history`` live waves — i.e. at the mega-cohort scale it
    exists for (100k clients / 256-wide waves = ~390 screened waves),
    while a 4-wave smoke run keeps structure/finite screening only.
    """

    REASONS = ("fingerprint", "nonfinite", "norm_outlier")

    def __init__(self, template, *, norm_k: float = 6.0,
                 norm_window: int = 64, norm_min_history: int = 8,
                 norm_screen: bool = True):
        if norm_window < 1 or norm_min_history < 1:
            raise ValueError("norm_window and norm_min_history must be >= 1")
        import collections
        self.fingerprint = params_fingerprint(template)
        self.norm_k = norm_k
        self.norm_min_history = norm_min_history
        self.norm_screen = norm_screen
        self._norms = collections.deque(maxlen=norm_window)
        reg = telemetry.get_registry()
        self._c_rejected = {r: reg.counter(
            "fedml_cohort_wave_rejected_total", reason=r)
            for r in self.REASONS}
        self.rejected: Dict[str, int] = {r: 0 for r in self.REASONS}
        self.admitted = 0
        # identity-keyed f64 host mirror of the round reference: one
        # conversion per round, not one per wave (AdmissionPipeline idiom)
        self._ref_cache: Tuple[object, Optional[list]] = (None, None)

    def round_start(self) -> None:
        """Open a round: clear the norm history (see class docstring —
        the wave population is per-round, a cross-round history rejects
        honest waves on convergence drift)."""
        self._norms.clear()

    def _reject(self, reason: str,
                norm: Optional[float] = None) -> AdmissionVerdict:
        self.rejected[reason] += 1
        self._c_rejected[reason].inc()
        return AdmissionVerdict(False, reason=reason, norm=norm)

    def norm_threshold(self) -> Optional[float]:
        return norm_outlier_threshold(self._norms, self.norm_k,
                                      self.norm_min_history)

    def screen(self, wave_mean, global_params) -> AdmissionVerdict:
        """Screen one wave's summary against the round's global.  Order
        matters: structure before any tree math (the pipeline's rule)."""
        try:
            fp_ok = params_fingerprint(wave_mean) == self.fingerprint
        except Exception:  # noqa: BLE001 — unhashable garbage summary
            fp_ok = False
        if not fp_ok:
            return self._reject("fingerprint")
        if not _all_finite(wave_mean):
            return self._reject("nonfinite")
        if self._ref_cache[0] is not global_params:
            # _leaves (not jax.tree.leaves): the canonical flatten order
            # _update_norm zips against
            self._ref_cache = (global_params,
                               [np.asarray(leaf, np.float64)
                                for leaf in _leaves(global_params)])
        norm = _update_norm(wave_mean, self._ref_cache[1])
        if self.norm_screen:
            thresh = self.norm_threshold()
            if thresh is not None and norm > thresh:
                return self._reject("norm_outlier", norm)
            self._norms.append(norm)
        self.admitted += 1
        return AdmissionVerdict(True, norm=norm)
