"""Federated GANs (FedGan, AsDGan) and FedSeg segmentation stack."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms import (
    FedGan, FedGanConfig, AsDGan, AsDGanConfig,
    SegmentationWorkload, evaluate_segmentation,
    segmentation_ce, segmentation_focal, confusion_matrix,
    metrics_from_confusion, FedAvg, FedAvgConfig)
from fedml_tpu.algorithms.fedseg import IGNORE_INDEX
from fedml_tpu.models import (
    Generator, Discriminator, CondGenerator, PatchDiscriminator,
    DeepLabV3Plus, UNet)
from fedml_tpu.data.stacking import FederatedData


@pytest.mark.slow
def test_fedgan_trains_and_samples():
    rng = np.random.RandomState(0)
    C, S, B = 2, 2, 8
    cohort = {"x": jnp.asarray(rng.rand(C, S, B, 16, 16, 1)
                               .astype(np.float32) * 2 - 1),
              "num_samples": jnp.asarray([16.0, 16.0])}
    gan = FedGan(Generator(out_channels=1, base_hw=4, widths=(16, 8), z_dim=16),
                 Discriminator(widths=(8, 16)),
                 FedGanConfig(rounds=2))
    out = gan.run(cohort)
    assert len(out["history"]) == 2
    imgs = gan.sample(out["params"], jax.random.key(1), 4)
    assert imgs.shape == (4, 16, 16, 1)
    assert float(jnp.abs(imgs).max()) <= 1.0


def test_asdgan_server_g_private_ds():
    rng = np.random.RandomState(1)
    C, S, B = 3, 2, 4
    data = {"a": jnp.asarray(rng.rand(C, S, B, 16, 16, 1)
                             .astype(np.float32)),
            "b": jnp.asarray(rng.rand(C, S, B, 16, 16, 1)
                             .astype(np.float32) * 2 - 1),
            "num_samples": jnp.asarray([8.0, 8.0, 8.0])}
    asd = AsDGan(CondGenerator(out_channels=1, width=8),
                 PatchDiscriminator(width=8),
                 AsDGanConfig(epochs=2))
    out = asd.run(data)
    assert len(out["history"]) == 2
    # discriminators stay per-client (never averaged)
    leaves = jax.tree.leaves(out["d_params"])
    assert leaves[0].shape[0] == C
    assert not np.allclose(np.asarray(leaves[-1][0]),
                           np.asarray(leaves[-1][1]))
    fake = asd.generate(out["g_params"], data["a"][0, 0])
    assert fake.shape == (B, 16, 16, 1)


def test_segmentation_losses_respect_ignore_index():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(2, 4, 4, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, (2, 4, 4)))
    y_ig = y.at[0].set(IGNORE_INDEX)
    # loss over half-ignored target equals loss over the valid half alone
    l_full = segmentation_ce(logits[1:], y[1:])
    l_ig = segmentation_ce(logits, y_ig)
    np.testing.assert_allclose(float(l_full), float(l_ig), rtol=1e-5)
    f = segmentation_focal(logits, y)
    assert np.isfinite(float(f)) and float(f) >= 0
    # focal <= alpha-scaled CE (since (1-pt)^gamma <= 1)
    assert float(f) <= 0.5 * float(segmentation_ce(logits, y)) + 1e-6


def test_confusion_matrix_and_metrics():
    pred = jnp.asarray([[0, 1], [2, 1]])
    targ = jnp.asarray([[0, 1], [2, 0]])
    cm = np.asarray(confusion_matrix(pred, targ, 3))
    assert cm.sum() == 4
    assert cm[0, 0] == 1 and cm[1, 1] == 1 and cm[2, 2] == 1
    assert cm[0, 1] == 1                        # truth 0 predicted 1
    m = metrics_from_confusion(cm)
    assert m["acc"] == 0.75
    # perfect prediction -> all metrics 1
    mp = metrics_from_confusion(np.diag([5, 3, 2]))
    for v in mp.values():
        np.testing.assert_allclose(v, 1.0)


@pytest.mark.slow
def test_fedseg_end_to_end_unet():
    rng = np.random.RandomState(0)
    C, S, B, H = 2, 2, 2, 16
    classes = 4
    train = {"x": rng.rand(C, S, B, H, H, 3).astype(np.float32),
             "y": rng.randint(0, classes, (C, S, B, H, H)).astype(np.int32),
             "mask": np.ones((C, S, B), np.float32),
             "num_samples": np.full((C,), S * B, np.float32)}
    data = FederatedData(client_num=C, class_num=classes, train=train)
    model = UNet(num_classes=classes, widths=(4, 8))
    wl = SegmentationWorkload(model, classes)
    fed = FedAvg(wl, data, FedAvgConfig(comm_round=2, client_num_per_round=2,
                                        epochs=1, lr=0.05,
                                        frequency_of_the_test=100))
    params = fed.run()
    keeper = evaluate_segmentation(
        wl, params,
        {k: jnp.asarray(train[k][0]) for k in ("x", "y", "mask")})
    assert 0.0 <= keeper.mIoU <= 1.0
    assert 0.0 <= keeper.accuracy <= 1.0


@pytest.mark.slow
def test_deeplab_shapes_both_backbones():
    x = jnp.asarray(np.random.RandomState(0).rand(1, 32, 32, 3), jnp.float32)
    for bb in ("xception", "resnet"):
        # compact twin: defaults are reference-sized (16 middle blocks,
        # width 1.0, ASPP 256) — too heavy for a CPU unit test
        net = DeepLabV3Plus(num_classes=5, backbone=bb, aspp_features=16,
                            middle_reps=2, width_mult=0.25)
        params = net.init(jax.random.key(0), x)["params"]
        out = jax.jit(lambda p, v: net.apply({"params": p}, v))(params, x)
        assert out.shape == (1, 32, 32, 5)


@pytest.mark.slow
def test_deeplab_reference_default_structure():
    """Default hyperparameters match the reference DeepLab: 16 Xception
    middle-flow blocks of 3 separable convs (xception.py:132-162), exit
    separable convs 1536/1536/2048, ASPP/decoder width 256
    (deeplabV3_plus.py:70-133)."""
    from fedml_tpu.models import AlignedXception
    net = DeepLabV3Plus(num_classes=3)
    assert net.aspp_features == 256
    assert net.middle_reps == 16 and net.width_mult == 1.0
    bb = AlignedXception()
    assert bb.middle_reps == 16 and bb.width_mult == 1.0
    x = jnp.asarray(np.random.RandomState(1).rand(1, 32, 32, 3), jnp.float32)
    params = bb.init(jax.random.key(0), x)["params"]
    # 3 entry blocks + 16 middle + exit block20 = 20 XceptionBlocks
    n_blocks = sum(1 for k in params if k.startswith("XceptionBlock"))
    assert n_blocks == 20
    middle = params["XceptionBlock_3"]
    n_seps = sum(1 for k in middle if k.startswith("SepConvNorm"))
    assert n_seps == 3  # reference middle blocks are reps=3


def test_perceptual_loss_taps_and_gradient():
    """perception_loss.py parity: four VGG16 feature taps, zero for
    identical inputs, differentiable and positive for different ones."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models import VGG16Features, perceptual_loss

    feat = VGG16Features()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 1), jnp.float32)
    params = feat.init(jax.random.key(0), jnp.repeat(x, 3, -1))["params"]
    taps = feat.apply({"params": params}, jnp.repeat(x, 3, -1))
    assert set(taps) == {"relu1_2", "relu2_2", "relu3_3", "relu4_3"}
    assert float(perceptual_loss(params, feat, x, x)) == 0.0
    y = x + 0.1
    val, grad = jax.value_and_grad(
        lambda a: perceptual_loss(params, feat, a, y))(x)
    assert float(val) > 0.0
    assert float(jnp.abs(grad).max()) > 0.0


def test_asdgan_l1_and_perceptual_terms():
    """AsDGan with the reference's reconstruction terms enabled: the G loss
    grows by the extra terms and training still runs; lambda=0 reproduces
    the pure-GAN objective."""
    import jax.numpy as jnp
    from fedml_tpu.algorithms.fedgan import AsDGan, AsDGanConfig
    from fedml_tpu.models import CondGenerator, PatchDiscriminator

    rng = np.random.RandomState(0)
    b = jnp.asarray(rng.rand(2, 2, 2, 16, 16, 1), jnp.float32)
    data = {"a": b + 0.1, "b": b, "num_samples": jnp.ones(2)}
    outs = {}
    for name, l1, lp in (("gan", 0.0, 0.0), ("full", 10.0, 1.0)):
        algo = AsDGan(CondGenerator(out_channels=1), PatchDiscriminator(),
                      AsDGanConfig(epochs=1, lambda_l1=l1,
                                   lambda_perceptual=lp, seed=0))
        outs[name] = algo.run(data)["history"][-1]
    assert outs["full"]["g_loss"] > outs["gan"]["g_loss"]
    assert np.isfinite(outs["full"]["g_loss"])
