"""FedProx (Li et al. 2020) — FedAvg with a proximal term on local training.

Parity note (SURVEY.md §2.2): the reference's distributed fedprox directory
is a FedAvg clone whose trainer contains NO mu term
(fedml_api/distributed/fedprox/MyModelTrainer.py:19-49) — the capability it
ships is "FedAvg with its own message pipeline".  We implement the *actual*
algorithm: local objective  F_k(w) + (mu/2)||w - w_global||^2, i.e. gradient
g + mu*(w - w_global) each local step — the same mu usage the reference does
implement inside FedNova's optimizer (standalone/fednova/fednova.py:133-136).
"""

from __future__ import annotations

import dataclasses

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import make_client_optimizer


@dataclasses.dataclass
class FedProxConfig(FedAvgConfig):
    mu: float = 0.1


class FedProx(FedAvg):
    def __init__(self, workload, data, config: FedProxConfig, mesh=None, sink=None):
        # the only delta vs FedAvg is the prox term inside local SGD, so it
        # rides FedAvg's machinery via the local_train seam — including the
        # HBM-resident device round and scanned multi-round dispatch
        opt = make_client_optimizer(config.client_optimizer, config.lr,
                                    config.wd)
        local_train = make_local_trainer(workload, opt, config.epochs,
                                         prox_mu=config.mu)
        super().__init__(workload, data, config, mesh=mesh, sink=sink,
                         local_train=local_train)
