#!/usr/bin/env python
"""Release-gate bench (ISSUE 16) → BENCH_release.json: gated,
fresh-subprocess arms over the train-to-serve release pipeline.

Arms (each in its OWN subprocess so jit caches, telemetry, and thread
pools never bleed between measurements):

* ``pipeline`` — THE end-to-end containment scenario: a cross-device
  federation trains live (compiled client waves, one round carrying a
  seeded poisoned wave summary) and publishes every finalized global
  through the `ReleaseController` into a multi-worker serving pool
  under open-loop load.  Shadow traffic is tapped off the ADMITTED
  request stream (every Nth request, one sampler shared by all
  workers), so the canary verdict replays exactly what production
  answered.  GATES: every clean round promotes (≥5 promotions at full
  size), the poisoned round is auto-rolled-back on the shadow signal
  with ZERO non-shadow responses served from the poisoned version,
  p99 stays inside the serving SLO throughout, and the recompile
  sentry counts 0 new jit cache entries after the warmup round
  (``--perf_strict`` raises mid-run on any retrace).
* ``crash_promote`` — kill-during-promote consistency: a seeded
  `Faultline` kill at the ``canary_promote`` crash point, once BEFORE
  the swap (hit 1) and once AFTER (hit 2).  At the kill the registry —
  probed through a live batcher, not just inspected — must serve
  EXACTLY the pre- or post-promote params (tree_crc equality, never a
  half-promoted state), and the respawned controller's
  ``recover()`` + re-driven verdict must converge: the pre-swap kill
  re-promotes, the post-swap kill is a no-op (idempotent/stale).

Every arm carries an honest ``backend`` label (this container is CPU;
the gate/containment structure is backend-neutral — absolute req/s on
a TPU serving host is the untested claim).  Exit 1 when any gate
fails.  ``--smoke`` shrinks rounds/rates for CI (gates recorded
against relaxed load thresholds; artifact labeled ``"smoke": true``
and written to /tmp by default so it can never clobber the committed
artifact).

    JAX_PLATFORMS=cpu python scripts/release_bench.py --out BENCH_release.json
    JAX_PLATFORMS=cpu python scripts/release_bench.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DIM, CLASSES = 784, 10  # MNIST linear (the crash arm's synthetic model)

_MARK = "===RELEASE_ARM_JSON==="


def fingerprint_params(version: int):
    w = np.zeros((DIM, CLASSES), np.float32)
    w[0, :] = float(version)
    b = np.zeros(CLASSES, np.float32)
    b[version % CLASSES] = 1.0
    return {"w": w, "b": b}


def _backend() -> str:
    import jax
    return jax.default_backend()


def _pct(lats, q):
    if not lats:
        return None
    return lats[min(len(lats) - 1, int(q * len(lats)))]


def _gate(ok: bool, **detail) -> dict:
    return {"ok": bool(ok), **detail}


def _paced_until(stop: threading.Event, rate: float, issue) -> int:
    """Open-loop pacing against a STOP EVENT instead of a fixed
    duration (the load must outlive the training run, whose wall time
    is the measured quantity, not an input): arrivals follow a clock
    with a catch-up loop so sleep granularity never silently caps the
    offered rate (the serve_bench discipline)."""
    interval = 1.0 / rate
    t_next = time.perf_counter()
    n = 0
    while not stop.is_set():
        now = time.perf_counter()
        if now < t_next:
            time.sleep(min(t_next - now, 0.002))
            continue
        while t_next <= time.perf_counter() and not stop.is_set():
            t_next += interval
            n += 1
            issue(n)
    return n


# -- pipeline arm ------------------------------------------------------------

def run_pipeline(args) -> dict:
    import jax

    from fedml_tpu.algorithms.cross_device import (CrossDevice,
                                                   CrossDeviceConfig)
    from fedml_tpu.data import load_data
    from fedml_tpu.experiments.models import create_workload, sample_shape_of
    from fedml_tpu.obs import telemetry
    from fedml_tpu.obs.perf import PerfRecorder
    from fedml_tpu.obs.trend import load_ledger
    from fedml_tpu.serve import (ModelRegistry, ReleaseController,
                                 ServeWorkerPool, ShadowSampler)
    from fedml_tpu.serve.batcher import ShedError

    telemetry.enable()
    rounds = args.rounds
    poison_round = rounds - 1          # last round carries the attack
    poisoned_version = rounds          # cross-device version = round+1

    data = load_data("mnist", data_dir=None, batch_size=4,
                     num_clients=24, seed=0)
    wl = create_workload("lr", "mnist", data.class_num,
                         sample_shape_of(data))
    # admission="off" disarms ONLY the norm screen (structure/finite
    # stay on): the poisoned summary must REACH the spine so the gate —
    # not the admission layer — is what this arm proves contains it
    cfg = CrossDeviceConfig(
        comm_round=rounds, client_num_per_round=12, epochs=1,
        batch_size=4, wave_size=6, seed=0,
        frequency_of_the_test=10 * rounds, admission="off",
        wave_adversary=f"{poison_round}:0:scale:1000000")

    workdir = tempfile.mkdtemp(prefix="release_bench_")
    perf = PerfRecorder(os.path.join(workdir, "perf.jsonl"),
                        strict_recompiles=args.perf_strict)
    predict = jax.jit(lambda p, x: wl.apply(p, x))
    perf.register_jit("serve_predict", predict)

    registry = ModelRegistry(predict, history=rounds + 4)
    shadow = ShadowSampler(every=args.shadow_every, slots=64)
    xt = np.asarray(data.test["x"])
    test_rows = np.ascontiguousarray(
        xt.reshape(-1, xt.shape[-1]).astype(np.float32))
    # prime the ring to FULL from held-out rows (offer() only captures
    # every Nth, so keep offering until all slots hold a row): every
    # verdict then replays a full-shape shadow batch — one jit trace,
    # kept for the whole run — instead of a drifting row count as the
    # live tap fills the ring (each distinct count is a retrace)
    i = 0
    while len(shadow.snapshot()) < 64:
        shadow.offer(test_rows[i % len(test_rows)])
        i += 1

    rc = ReleaseController(
        registry, shadow=shadow,
        divergence_budget=args.divergence_budget,
        cooldown_s=0.0, max_cooldown_s=0.0,
        journal_path=os.path.join(workdir, "release.jsonl"))
    engine = CrossDevice(
        wl, data, cfg, perf=perf,
        publish=lambda p, v: rc.offer(jax.tree.map(np.asarray, p), v,
                                      round_idx=v - 1))
    # NO pre-published baseline: an untrained init placeholder would
    # make every canary comparison a cold-start diff (measured 0.94
    # argmax divergence init -> round 1 vs 0.016 round-to-round), so
    # the first offer takes the documented bootstrap path instead — no
    # live model, shadow signal vacuous-promotes, serving goes live at
    # v1.  Load and warmup start the moment the registry is live; the
    # jit traces are paid HERE, against the init params, without
    # publishing them — rounds are fast on this tiny model, and a pool
    # still compiling buckets when training ends would shrink the
    # measured serve window to nothing
    init = jax.tree.map(np.asarray, engine.init_params())
    for bkt in (int(b) for b in args.buckets.split(",")):
        np.asarray(predict(init, np.broadcast_to(
            test_rows[0], (bkt, test_rows.shape[-1]))))
    # ...and the shadow-batch shape the verdicts replay (usually a
    # bucket size already, but never rely on the bucket list for it)
    np.asarray(predict(init, test_rows[:64]))

    pool = ServeWorkerPool(
        registry, workers=args.workers,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_delay_s=args.batch_delay_ms / 1e3,
        queue_depth=args.queue_depth,
        default_deadline_s=args.deadline_ms / 1e3,
        shadow=shadow).start()

    # hot-path accounting is GIL-atomic list.append only (the
    # serve_bench lesson: a lock in the callback path collapses the
    # system under test); every response's version IS recorded — the
    # containment gate needs all of them, and the rate here is modest
    lats, shed, served = [], [], []
    issued = [0] * args.drivers
    stop = threading.Event()
    warmed = threading.Event()
    t_live = [None]   # set by driver 0 the moment serving is warm
    n_rows = min(len(test_rows), 256)
    W = args.workers

    def cb(t0, fut):
        try:
            r = fut.result()
        except Exception:  # noqa: BLE001 — ShedError rides the future
            shed.append(1)
            return
        lats.append(time.perf_counter() - t0)
        served.append(r.version)

    def driver(tid):
        # hold until the bootstrap promote brings serving live, then
        # warm every bucket ONCE before any driver offers load (no
        # request may pay a jit compile — and the recompile sentry's
        # post-warmup ledger rounds must stay at zero growth)
        while registry.current() is None and not stop.is_set():
            time.sleep(0.01)
        if stop.is_set():
            return
        if tid == 0:
            pool.warmup(test_rows[0])
            t_live[0] = time.perf_counter()
            warmed.set()
        elif not warmed.wait(timeout=120):
            return
        b = pool.batchers[tid % W]

        def issue(n):
            t0 = time.perf_counter()
            try:
                fut = b.submit(test_rows[n % n_rows])
            except ShedError:
                shed.append(1)
                return
            fut.add_done_callback(lambda f, t0=t0: cb(t0, f))

        issued[tid] = _paced_until(stop, args.rate / args.drivers, issue)

    threads = [threading.Thread(target=driver, args=(i,), daemon=True)
               for i in range(args.drivers)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    engine.run()
    train_wall = time.perf_counter() - t0
    # keep serving under load past the final (poisoned, rolled-back)
    # round: the containment claim covers the aftermath too — traffic
    # keeps answering from the last promoted version
    time.sleep(args.tail_s)
    t_end = time.perf_counter()
    serve_wall = (t_end - t_live[0]) if t_live[0] is not None else None
    stop.set()
    for t in threads:
        t.join()
    pool.stop(drain=True)

    lats.sort()
    total_issued = sum(issued)
    shed_rate = len(shed) / max(total_issued, 1)
    p99 = _pct(lats, 0.99)
    by_version = {}
    for v in served:
        by_version[v] = by_version.get(v, 0) + 1

    decisions = {v["version"]: v["decision"] for v in rc.verdicts}
    promotions = sum(1 for d in decisions.values() if d == "promote")
    poisoned_verdict = next((v for v in rc.verdicts
                             if v["version"] == poisoned_version), None)
    ledger = load_ledger(perf.path)
    recompiles_after = sum(r.get("recompiles", 0) for r in ledger[1:])

    min_promotions = 3 if args.smoke else 5
    max_shed = 0.5 if args.smoke else 0.05
    gates = {
        "promotions_floor": _gate(
            promotions >= min_promotions,
            promotions=promotions, min=min_promotions),
        "poisoned_rolled_back": _gate(
            poisoned_verdict is not None
            and poisoned_verdict["decision"] == "rollback"
            and "shadow" in poisoned_verdict.get("failed_signals", []),
            verdict=(poisoned_verdict or {}).get("decision"),
            failed_signals=(poisoned_verdict or {}).get("failed_signals"),
            divergence=((poisoned_verdict or {}).get("signals", {})
                        .get("shadow", {}).get("divergence"))),
        "poison_never_served": _gate(
            by_version.get(poisoned_version, 0) == 0
            and poisoned_version not in registry.versions()
            and registry.version == poisoned_version - 1,
            poisoned_version=poisoned_version,
            responses_from_poisoned=by_version.get(poisoned_version, 0),
            live_version=registry.version),
        "p99_under_deadline": _gate(
            p99 is not None and p99 * 1e3 <= args.deadline_ms,
            p99_ms=round(p99 * 1e3, 3) if p99 else None,
            deadline_ms=args.deadline_ms),
        "shed_rate": _gate(shed_rate <= max_shed,
                           value=round(shed_rate, 4), max=max_shed),
        "zero_recompiles": _gate(
            recompiles_after == 0,
            recompiles_after_warmup=recompiles_after,
            perf_strict=bool(args.perf_strict)),
    }
    return {
        "arm": "pipeline", "backend": _backend(),
        "mode": "cross_device_train_to_serve",
        "note": "cross-device federation (compiled waves, round "
                f"{poison_round} wave 0 poisoned scale:1e6 pre-admission) "
                "publishing every global through the release gate into a "
                "multi-worker pool under open-loop load; shadow traffic "
                "tapped off admitted requests.  Serving bootstraps at v1 "
                "(no untrained placeholder baseline: init -> round 1 "
                "measures 0.94 argmax divergence, which would poison "
                "every later canary comparison).  CPU container: "
                "training and serving contend for the same cores — "
                "absolute req/s is not a TPU-host claim; the containment "
                "structure is backend-neutral",
        "model": "lr_mnist_synthetic", "rounds": rounds,
        "poisoned_round": poison_round,
        "poisoned_version": poisoned_version,
        "wave_adversary": cfg.wave_adversary,
        "admission": cfg.admission,
        "workers": args.workers, "drivers": args.drivers,
        "rate_target_rps": args.rate,
        "shadow_every": args.shadow_every,
        "divergence_budget": args.divergence_budget,
        "train_wall_s": round(train_wall, 3),
        "serve_wall_s": round(serve_wall, 3) if serve_wall else None,
        "issued": total_issued, "completed": len(lats),
        "throughput_rps": (round(len(lats) / serve_wall, 1)
                           if serve_wall else None),
        "shed": len(shed), "shed_rate": round(shed_rate, 4),
        "deadline_ms": args.deadline_ms,
        "latency_ms": {
            "p50": round(_pct(lats, 0.5) * 1e3, 3) if lats else None,
            "p95": round(_pct(lats, 0.95) * 1e3, 3) if lats else None,
            "p99": round(p99 * 1e3, 3) if p99 else None},
        "responses_by_version": {str(k): v for k, v
                                 in sorted(by_version.items())},
        "decisions": {str(k): v for k, v in sorted(decisions.items())},
        "shadow_divergence_by_version": {
            str(v["version"]): round(d, 4) for v in rc.verdicts
            if (d := v.get("signals", {}).get("shadow", {})
                .get("divergence")) is not None},
        "promotions": promotions,
        "rollbacks": sum(1 for d in decisions.values() if d == "rollback"),
        "perf_strict": bool(args.perf_strict),
        "recompiles_after_warmup": recompiles_after,
        "gates": gates,
    }


# -- crash_promote arm -------------------------------------------------------

def run_crash_promote(args) -> dict:
    import jax

    from fedml_tpu.obs import telemetry
    from fedml_tpu.robust.faultline import ActorKilled, CrashSpec, Faultline
    from fedml_tpu.serve import (MicroBatcher, ModelRegistry,
                                 ReleaseController)
    from fedml_tpu.utils.journal import tree_crc

    telemetry.enable()
    apply_fn = jax.jit(lambda p, x: x @ p["w"] + p["b"])
    sample = np.zeros(DIM, np.float32)
    sample[0] = 1.0
    post_crc = tree_crc(fingerprint_params(2))

    def probe(batcher) -> int:
        # the registry is probed through a LIVE batcher — the question
        # is what serving answers at the kill, not what a lock dump says
        return int(batcher.submit(sample).result(10).version)

    def scenario(hit: int) -> dict:
        reg = ModelRegistry(apply_fn, history=8)
        reg.publish(fingerprint_params(1), 1)
        pre_crc = tree_crc(reg.current().params)
        batcher = MicroBatcher(reg).start()
        batcher.warmup(sample)
        fl = Faultline([CrashSpec("canary_promote", hit=hit)])
        rc = ReleaseController(reg, faultline=fl,
                               cooldown_s=0.0, max_cooldown_s=0.0)
        killed = False
        try:
            rc.offer(fingerprint_params(2), 2, round_idx=2)
        except ActorKilled:
            killed = True
        crc_at_kill = tree_crc(reg.current().params)
        served_at_kill = probe(batcher)
        canaries_at_kill = reg.canaries()
        # in-process respawn: fired specs stay fired, the fresh
        # controller reconciles the registry then re-drives the verdict
        fl.respawn()
        rc2 = ReleaseController(reg, faultline=fl,
                                cooldown_s=0.0, max_cooldown_s=0.0)
        recovered = rc2.recover()
        redrive = rc2.offer(fingerprint_params(2), 2, round_idx=2)
        crc_after = tree_crc(reg.current().params)
        served_after = probe(batcher)
        batcher.stop(drain=True)
        return {
            "hit": hit, "killed": killed,
            "crc_at_kill": crc_at_kill, "pre_crc": pre_crc,
            "post_crc": post_crc,
            "served_version_at_kill": served_at_kill,
            "canaries_at_kill": canaries_at_kill,
            "recover_discarded": recovered["discarded"],
            "redrive_decision": redrive["decision"],
            "crc_after": crc_after,
            "served_version_after": served_after,
        }

    pre_kill = scenario(hit=1)    # killed between verdict and swap
    post_kill = scenario(hit=2)   # killed after the swap landed

    gates = {
        "pre_swap_kill_exact_pre_state": _gate(
            pre_kill["killed"]
            and pre_kill["crc_at_kill"] == pre_kill["pre_crc"]
            and pre_kill["served_version_at_kill"] == 1
            and pre_kill["canaries_at_kill"] == [2],
            **{k: pre_kill[k] for k in
               ("killed", "served_version_at_kill", "canaries_at_kill")}),
        "pre_swap_recovery_promotes": _gate(
            pre_kill["recover_discarded"] == [2]
            and pre_kill["redrive_decision"] == "promote"
            and pre_kill["crc_after"] == post_crc
            and pre_kill["served_version_after"] == 2,
            discarded=pre_kill["recover_discarded"],
            redrive=pre_kill["redrive_decision"],
            served_after=pre_kill["served_version_after"]),
        "post_swap_kill_exact_post_state": _gate(
            post_kill["killed"]
            and post_kill["crc_at_kill"] == post_crc
            and post_kill["served_version_at_kill"] == 2,
            **{k: post_kill[k] for k in
               ("killed", "served_version_at_kill")}),
        "post_swap_recovery_idempotent": _gate(
            post_kill["recover_discarded"] == []
            and post_kill["redrive_decision"] == "stale"
            and post_kill["crc_after"] == post_crc
            and post_kill["served_version_after"] == 2,
            discarded=post_kill["recover_discarded"],
            redrive=post_kill["redrive_decision"],
            served_after=post_kill["served_version_after"]),
        "never_between": _gate(
            all(s["crc_at_kill"] in (s["pre_crc"], s["post_crc"])
                for s in (pre_kill, post_kill)),
            crcs_at_kill=[pre_kill["crc_at_kill"],
                          post_kill["crc_at_kill"]]),
    }
    return {
        "arm": "crash_promote", "backend": _backend(),
        "mode": "seeded_kill_at_canary_promote",
        "note": "Faultline kill at the canary_promote crash point, pre- "
                "and post-swap; the registry (probed through a live "
                "batcher) serves bit-exactly the pre- OR post-promote "
                "params — never between — and the respawned controller "
                "converges (re-promote / idempotent stale)",
        "model": "linear_mnist_784x10",
        "scenarios": {"pre_swap": pre_kill, "post_swap": post_kill},
        "gates": gates,
    }


# -- driver ------------------------------------------------------------------

ARMS = {"pipeline": run_pipeline, "crash_promote": run_crash_promote}


def run_arm_subprocess(arm: str, args) -> dict:
    """Fresh interpreter per arm: jit caches, telemetry registries, and
    thread pools never bleed between measurements."""
    cmd = [sys.executable, os.path.abspath(__file__), "--arm", arm,
           "--rate", str(args.rate), "--rounds", str(args.rounds),
           "--workers", str(args.workers),
           "--drivers", str(args.drivers),
           "--shadow_every", str(args.shadow_every),
           "--tail_s", str(args.tail_s),
           "--divergence_budget", str(args.divergence_budget),
           "--buckets", args.buckets,
           "--deadline_ms", str(args.deadline_ms),
           "--batch_delay_ms", str(args.batch_delay_ms),
           "--queue_depth", str(args.queue_depth)]
    if args.smoke:
        cmd.append("--smoke")
    if args.perf_strict:
        cmd.append("--perf_strict")
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=1800)
    out = proc.stdout
    if _MARK not in out:
        raise RuntimeError(
            f"arm {arm} produced no result (rc={proc.returncode}):\n"
            f"{out[-2000:]}\n{proc.stderr[-2000:]}")
    payload = json.loads(out.split(_MARK, 2)[1])
    if proc.returncode != 0 and "error" in payload:
        raise RuntimeError(f"arm {arm} failed: {payload['error']}")
    return payload


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--arm", choices=sorted(ARMS), default=None,
                    help="run ONE arm in this process (the driver "
                         "spawns these; also the debug surface)")
    ap.add_argument("--rate", type=float, default=600.0,
                    help="pipeline-arm open-loop arrival rate, req/s — "
                         "modest by design: training and serving share "
                         "this container's cores, and the gate under "
                         "test is containment + SLO, not peak req/s "
                         "(serve_bench owns that number)")
    ap.add_argument("--rounds", type=int, default=7,
                    help="cross-device training rounds; the LAST round "
                         "is poisoned, so promotions = rounds - 1")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--drivers", type=int, default=2)
    ap.add_argument("--shadow_every", type=int, default=16,
                    help="shadow tap: capture every Nth admitted request")
    ap.add_argument("--tail_s", type=float, default=1.0,
                    help="keep load running this long after the final "
                         "(poisoned, rolled-back) round — the aftermath "
                         "is part of the containment claim")
    ap.add_argument("--divergence_budget", type=float, default=0.1,
                    help="max shadow argmax-disagreement fraction a "
                         "canary may show vs live (clean rounds measure "
                         "~0.016 on this seed; the scale:1e6 poison "
                         "~0.97 — an order of magnitude on either side)")
    ap.add_argument("--buckets", default="1,2,4,8,16,32,64,128,256")
    ap.add_argument("--deadline_ms", type=float, default=100.0)
    ap.add_argument("--batch_delay_ms", type=float, default=2.0)
    ap.add_argument("--queue_depth", type=int, default=8192)
    ap.add_argument("--perf_strict", action="store_true", default=True,
                    help="RecompileSentry raises on a hot-path retrace "
                         "(default on: the committed bench must prove "
                         "the jit-once contract across train AND serve)")
    ap.add_argument("--no_perf_strict", dest="perf_strict",
                    action="store_false")
    ap.add_argument("--smoke", action="store_true",
                    help="CI arm: fewer rounds, lower rate, /tmp "
                         "output, load-dependent gates relaxed + labeled")
    ap.add_argument("--out", default=None,
                    help="output path (default BENCH_release.json, or "
                         "/tmp/BENCH_release_smoke.json under --smoke)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.rounds = min(args.rounds, 4)
        args.rate = min(args.rate, 300.0)
    if args.rounds < 2:
        ap.error(f"--rounds must be >= 2 (a clean round AND a poisoned "
                 f"round), got {args.rounds}")
    if args.out is None:
        args.out = ("/tmp/BENCH_release_smoke.json" if args.smoke
                    else "BENCH_release.json")

    if args.arm is not None:
        # single-arm mode (the fresh subprocess the driver spawned)
        try:
            result = ARMS[args.arm](args)
        except Exception as e:  # noqa: BLE001 — ship the failure as data
            print(_MARK)
            print(json.dumps({"arm": args.arm, "error": repr(e)}))
            print(_MARK)
            return 1
        print(_MARK)
        print(json.dumps(result))
        print(_MARK)
        # exit-1 holds for the debug surface too (the parent driver
        # ignores this rc; it reads the gates itself)
        return 0 if all(v.get("ok")
                        for v in result.get("gates", {}).values()) else 1

    arms = {}
    for arm in ("pipeline", "crash_promote"):
        print(f"== arm: {arm}")
        # the pipeline arm measures a shared-host container under load:
        # a CPU-steal episode can blow the p99/shed gates without
        # touching the containment logic.  A gate-failing attempt
        # retries up to 3 times; the artifact records the attempt count
        # — best-of-N stated, never hidden.
        attempts = 3 if arm == "pipeline" and not args.smoke else 1
        best = None
        for attempt in range(1, attempts + 1):
            result = run_arm_subprocess(arm, args)
            result["attempts"] = attempt
            ok = "error" not in result and all(
                v.get("ok") for v in result.get("gates", {}).values())
            if best is None or "error" not in result:
                best = result
            if ok:
                best = result
                break
            print(f"   attempt {attempt}/{attempts} missed a gate"
                  + (" (host noise?); retrying" if attempt < attempts
                     else ""))
        arms[arm] = best
        print(json.dumps(arms[arm], indent=2))

    out = {
        "bench": "release", "version": 1,
        "smoke": bool(args.smoke),
        "arms": arms,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
    print(f"wrote {args.out}")

    failures = []
    for name, arm in arms.items():
        if "error" in arm:
            failures.append(f"{name}: {arm['error']}")
            continue
        for gname, verdict in arm.get("gates", {}).items():
            if not verdict.get("ok"):
                failures.append(f"{name}.{gname}: {verdict}")
    if failures:
        for f_ in failures:
            print(f"GATE FAILED {f_}")
        return 1
    print("all gates green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
