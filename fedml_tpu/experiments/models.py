"""Model × dataset factory — parity with the reference's ``create_model``
switch (``fedml_experiments/distributed/fedavg/main_fedavg.py:224-259``).

The reference pairs a model name with a dataset to pick both the
architecture and the trainer flavor (classification / next-word prediction /
tag prediction — FedAvgAPI.py:33-39).  Here the same switch returns a
``Workload`` (model + loss + metrics bundled), so every runner downstream is
algorithm-generic.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from fedml_tpu.data.stacking import FederatedData
from fedml_tpu.models import (
    CNNDropOut, CNNOriginalFedAvg, LogisticRegression, RNNOriginalFedAvg,
    RNNStackOverflow, TransformerLM, efficientnet, mobilenet, mobilenet_v3,
    resnet18_gn, resnet56, resnet110, vgg11, vgg13, vgg16)
from fedml_tpu.trainer.workload import (
    ClassificationWorkload, NWPWorkload, TagPredictionWorkload, Workload)

# next-word/char-prediction datasets -> NWP trainer flavor
_NWP_DATASETS = {"shakespeare", "fed_shakespeare", "stackoverflow_nwp"}


def create_workload(model_name: str, dataset: str, class_num: int,
                    sample_shape: Sequence[int],
                    compute_dtype: str = "",
                    attn_block_size: int = 0,
                    attn_flash: bool = False,
                    moe_experts: int = 0) -> Workload:
    """main_fedavg.py:224-259 switch, flax edition.

    ``compute_dtype="bfloat16"`` enables MXU-native mixed precision on the
    classification workloads (f32 master params, bf16 model compute).
    ``attn_block_size`` > 0 gives the transformer flash-style kv blocking
    (O(T*block) attention memory) for long-context train/eval;
    ``attn_flash`` swaps in the TPU pallas flash kernel instead."""
    import jax.numpy as jnp
    dtype = jnp.dtype(compute_dtype) if compute_dtype else None
    if (attn_block_size or attn_flash or moe_experts) \
            and model_name != "transformer":
        raise ValueError("--attn_block_size/--attn_flash/--moe_experts "
                         "only apply to --model transformer")
    if attn_block_size and attn_flash:
        raise ValueError("--attn_block_size and --attn_flash are mutually "
                         "exclusive attention backends; pick one")
    if dtype is not None and dataset == "stackoverflow_lr":
        raise ValueError(
            f"--compute_dtype is not wired into the tag-prediction "
            f"workload; dataset {dataset!r} would silently ignore it")
    if dataset in _NWP_DATASETS:
        if model_name == "transformer":
            # the attention member of the NLP family (no reference analog —
            # its zoo stops at LSTMs, rnn.py:18-22); per-position logits,
            # same NWPWorkload contract, ring-attention capable
            model = TransformerLM(vocab_size=class_num, dtype=dtype,
                                  block_size=attn_block_size or None,
                                  use_flash=attn_flash,
                                  moe_experts=moe_experts)
        elif dataset == "stackoverflow_nwp":
            model = RNNStackOverflow(dtype=dtype)          # rnn.py:39-70
        else:
            model = RNNOriginalFedAvg(vocab_size=class_num,
                                      dtype=dtype)          # rnn.py:4-36
        return NWPWorkload(model, compute_dtype=dtype)
    if dataset == "stackoverflow_lr":
        model = LogisticRegression(int(np.prod(sample_shape)), class_num)
        return TagPredictionWorkload(model)

    input_dim = int(np.prod(sample_shape))
    small = class_num <= 10
    factories = {
        "lr": lambda: LogisticRegression(input_dim, class_num),
        "cnn": lambda: CNNDropOut(only_digits=small),          # Reddi'20
        "cnn_fedavg": lambda: CNNOriginalFedAvg(only_digits=small),
        "resnet56": lambda: resnet56(class_num),
        "resnet110": lambda: resnet110(class_num),
        "resnet18_gn": lambda: resnet18_gn(class_num),
        "mobilenet": lambda: mobilenet(num_classes=class_num),
        "mobilenet_v3": lambda: mobilenet_v3(num_classes=class_num),
        "efficientnet": lambda: efficientnet("b0", num_classes=class_num),
        "vgg11": lambda: vgg11(num_classes=class_num),
        "vgg13": lambda: vgg13(num_classes=class_num),
        "vgg16": lambda: vgg16(num_classes=class_num),
    }
    if model_name not in factories:
        raise KeyError(f"unknown model {model_name!r}; "
                       f"have {sorted(factories)}")
    # grad-clip 1.0 parity with MyModelTrainer (classification only,
    # my_model_trainer_classification.py:44)
    return ClassificationWorkload(factories[model_name](),
                                  num_classes=class_num, grad_clip_norm=1.0,
                                  compute_dtype=dtype)


def sample_shape_of(data: FederatedData) -> tuple:
    return tuple(data.train["x"].shape[3:])
