"""Text encodings for the federated NLP datasets.

Re-specifies (TPU-side, numpy-only) the reference's two text stacks:

* Shakespeare char-level encoding — the 86-char TFF vocabulary with
  pad/bos/eos/oov giving VOCAB_SIZE 90
  (``fedml_api/data_preprocessing/shakespeare/language_utils.py:11-20`` and
  ``fed_shakespeare/utils.py:18-33``; sequence length 80 per McMahan'17,
  ``fed_shakespeare/utils.py:15``).
* StackOverflow word-level tokenizer — top-10k word vocab from a
  ``stackoverflow.word_count`` file, bos/eos/pad/oov framing at seq len 20
  (``stackoverflow_nwp/utils.py:26-85``), and the LR variant's 10k
  bag-of-words x / 500-tag multi-hot y
  (``stackoverflow_lr/utils.py:33-42,65-95``).

Outputs are int32/float32 numpy arrays ready for `stacking.stack_client_data`;
one-hot blow-ups happen on device, not here.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

# The TFF text-generation tutorial vocabulary (86 printable chars, ordered by
# frequency). language_utils.py:11-13 / fed_shakespeare/utils.py:19-21.
CHAR_VOCAB = list(
    'dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#\'/37;?bfjnrvzBFJNRVZ"&*.26:\naeimquyAEIMQUY]!%)-159\r'
)
SHAKESPEARE_SEQ_LEN = 80


class CharVocab:
    """fed_shakespeare token layout: [pad] + chars + [bos] + [eos], oov = size
    (fed_shakespeare/utils.py:24-33,47-52)."""

    def __init__(self, chars: Sequence[str] = CHAR_VOCAB):
        self.pad = 0
        self._ids = {c: i + 1 for i, c in enumerate(chars)}
        self.bos = len(chars) + 1
        self.eos = len(chars) + 2
        self.oov = len(chars) + 3
        self.vocab_size = len(chars) + 4  # 90 for the default vocab

    def char_id(self, c: str) -> int:
        return self._ids.get(c, self.oov)

    def encode_snippet(self, text: str, seq_len: int = SHAKESPEARE_SEQ_LEN
                       ) -> List[np.ndarray]:
        """<bos> text <eos>, chopped into (seq_len+1)-length windows, last
        window padded — mirrors fed_shakespeare/utils.py preprocess/to_ids.
        Each window yields (x, y) by the shift-by-one split done in
        utils.split (fed_shakespeare/utils.py:72-76)."""
        ids = [self.bos] + [self.char_id(c) for c in text] + [self.eos]
        out = []
        for i in range(0, len(ids), seq_len + 1):
            win = ids[i:i + seq_len + 1]
            if len(win) < 2:
                break
            win = win + [self.pad] * (seq_len + 1 - len(win))
            out.append(np.asarray(win, dtype=np.int32))
        return out


# LEAF's shakespeare variant indexes raw chars directly into the same 86-char
# string (oov = -1 from str.find; the reference one-hots at VOCAB_SIZE 90,
# language_utils.py:16-40). We clamp oov to the shared oov id instead.
def leaf_word_to_indices(word: str, vocab: Optional[CharVocab] = None
                         ) -> np.ndarray:
    vocab = vocab or CharVocab()
    return np.asarray([vocab.char_id(c) for c in word], dtype=np.int32)


class WordVocab:
    """StackOverflow word vocab: [pad] + top-k words + [bos] + [eos], hashed
    oov buckets after (stackoverflow_nwp/utils.py:33-41,60-66)."""

    def __init__(self, words: Sequence[str], num_oov_buckets: int = 1):
        self.pad = 0
        self._ids = {w: i + 1 for i, w in enumerate(words)}
        self.bos = len(words) + 1
        self.eos = len(words) + 2
        self.num_oov_buckets = num_oov_buckets
        self.vocab_size = len(words) + 3 + num_oov_buckets  # 10004 at k=10000

    @classmethod
    def from_word_count_file(cls, path: str, vocab_size: int = 10000,
                             num_oov_buckets: int = 1) -> "WordVocab":
        """`stackoverflow.word_count`: one "word count" line per word,
        most-frequent first (stackoverflow_nwp/utils.py:26-30)."""
        words = []
        with open(path) as f:
            for line in f:
                words.append(line.split()[0])
                if len(words) >= vocab_size:
                    break
        return cls(words, num_oov_buckets)

    def word_id(self, w: str) -> int:
        i = self._ids.get(w)
        if i is not None:
            return i
        # stable across processes (Python's hash() is salted per-interpreter)
        bucket = zlib.crc32(w.encode("utf8")) % self.num_oov_buckets
        return bucket + len(self._ids) + 3

    def encode_sentence(self, sentence: str, seq_len: int = 20) -> np.ndarray:
        """<bos> tokens [<eos>] <pad>... at length seq_len+1
        (stackoverflow_nwp/utils.py:68-82: eos only when the truncated
        sentence is shorter than seq_len)."""
        tokens = [self.word_id(w) for w in sentence.split(" ")[:seq_len]]
        if len(tokens) < seq_len:
            tokens = tokens + [self.eos]
        tokens = [self.bos] + tokens
        tokens += [self.pad] * (seq_len + 1 - len(tokens))
        return np.asarray(tokens[:seq_len + 1], dtype=np.int32)


def split_next_word(windows: np.ndarray) -> Dict[str, np.ndarray]:
    """[N, L+1] id windows -> x=[N, L], y=[N, L] shifted by one
    (fed_shakespeare/utils.py:72-76 splits off only the last column; the
    TFF-style LM target is the full shift, which the reference's RNN also
    uses — we keep the full shift so every position trains)."""
    return {"x": windows[:, :-1], "y": windows[:, 1:]}


def bag_of_words(sentences: Sequence[str], vocab: Dict[str, int],
                 normalize: bool = True) -> np.ndarray:
    """StackOverflow-LR x: 10k-dim token-frequency vector per example
    (stackoverflow_lr/utils.py:65-74: counts / num_tokens)."""
    out = np.zeros((len(sentences), len(vocab)), dtype=np.float32)
    for i, s in enumerate(sentences):
        toks = s.split(" ")
        for t in toks:
            j = vocab.get(t)
            if j is not None:
                out[i, j] += 1.0
        if normalize and toks:
            out[i] /= len(toks)
    return out


def multi_hot_tags(tag_lists: Sequence[str], tag_vocab: Dict[str, int],
                   sep: str = "|") -> np.ndarray:
    """StackOverflow-LR y: 500-dim multi-hot tag vector
    (stackoverflow_lr/utils.py:77-84)."""
    out = np.zeros((len(tag_lists), len(tag_vocab)), dtype=np.float32)
    for i, tags in enumerate(tag_lists):
        for t in tags.split(sep):
            j = tag_vocab.get(t)
            if j is not None:
                out[i, j] = 1.0
    return out


def load_tag_dict(path: str, tag_size: int = 500) -> Dict[str, int]:
    """`stackoverflow.tag_count` is a json {tag: count} ordered by frequency
    (stackoverflow_lr/utils.py:39-42)."""
    with open(path) as f:
        tags = json.load(f)
    return {t: i for i, t in enumerate(list(tags.keys())[:tag_size])}
