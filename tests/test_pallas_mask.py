"""Pallas fused quantize+mask kernel (interpret mode on CPU).

The contract under test is the SecAgg ring algebra: per-client masked
updates whose uint32 sum over the cohort equals the sum of the quantized
weighted updates EXACTLY (every pair's +PRG and -PRG cancel bit-for-bit),
and whose dequantized sum reproduces the weighted mean.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.secure.pallas_mask import (derive_pair_seeds,
                                          fused_quantize_mask)
from fedml_tpu.secure.secagg import dequantize, quantize

N = 4
SCALE, CLIP = 2.0**16, 2.0**14


def _tree(seed, shape=(300, 7)):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(*shape), jnp.float32),
            "b": jnp.asarray(rng.randn(11), jnp.float32)}


def _mask_all(updates, weights, key):
    return [fused_quantize_mask(updates[i], weights[i], i, key, N,
                                SCALE, CLIP, interpret=True)
            for i in range(N)]


def test_masks_cancel_exactly_in_ring_sum():
    key = jax.random.key(0)
    updates = [_tree(i) for i in range(N)]
    weights = np.random.RandomState(9).dirichlet(np.ones(N))
    masked = _mask_all(updates, weights, key)

    ring_sum = jax.tree.map(lambda *xs: sum(xs[1:], xs[0]), *masked)
    plain_sum = jax.tree.map(
        lambda *xs: sum(xs[1:], xs[0]),
        *[quantize(jax.tree.map(
            lambda x: x * jnp.float32(weights[i]), updates[i]),
            SCALE, CLIP) for i in range(N)])
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), ring_sum, plain_sum)

    # ... and the dequantized sum is the weighted mean (Σw = 1)
    want = jax.tree.map(lambda *xs: sum(w * np.asarray(x) for w, x in
                                        zip(weights, xs)), *updates)
    got = dequantize(ring_sum, SCALE)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), b, atol=N / SCALE * 2), got, want)


def test_single_update_is_masked():
    """One client's masked upload must NOT reveal its quantized update
    (the mask moves essentially every word)."""
    key = jax.random.key(1)
    upd = _tree(3)
    masked = fused_quantize_mask(upd, 1.0, 0, key, N, SCALE, CLIP,
                                 interpret=True)
    q = quantize(upd, SCALE, CLIP)
    frac_equal = np.mean(np.asarray(masked["w"]) == np.asarray(q["w"]))
    assert frac_equal < 0.01


def test_same_shape_leaves_get_distinct_masks():
    """Leaf-index seed separation: two identical leaves must carry
    different masks (mask reuse would leak their difference)."""
    key = jax.random.key(2)
    x = jnp.ones((256, 4), jnp.float32)
    tree = {"a": x, "b": x}
    masked = fused_quantize_mask(tree, 1.0, 0, key, N, SCALE, CLIP,
                                 interpret=True)
    assert not np.array_equal(np.asarray(masked["a"]),
                              np.asarray(masked["b"]))


def test_pair_seeds_symmetric():
    key = jax.random.key(5)
    s0 = derive_pair_seeds(key, jnp.asarray(0), N)
    s2 = derive_pair_seeds(key, jnp.asarray(2), N)
    # pair (0,2) agrees on both words of its 64-bit seed
    np.testing.assert_array_equal(np.asarray(s0[2]), np.asarray(s2[0]))


def test_aggregator_pallas_backend_weighted_mean():
    """SecureCohortAggregator(backend='pallas') end-to-end: masked stacked
    aggregation reproduces the plain weighted mean."""
    from fedml_tpu.secure import SecureCohortAggregator
    rng = np.random.RandomState(3)
    updates = {"w": jnp.asarray(rng.randn(N, 40, 5), jnp.float32)}
    n = jnp.asarray([10.0, 30.0, 20.0, 40.0])
    agg = SecureCohortAggregator(N, backend="pallas")
    got = agg.aggregate_stacked(updates, n, jax.random.key(7))
    w = np.asarray(n) / np.asarray(n).sum()
    want = (np.asarray(updates["w"]) * w[:, None, None]).sum(0)
    np.testing.assert_allclose(np.asarray(got["w"]), want,
                               atol=N / SCALE * 2)


def test_turboaggregate_pallas_backend_cli():
    """--secagg_backend pallas end-to-end through the CLI; result within
    noise of the xla backend (different mask streams, same cancellation)."""
    from fedml_tpu.experiments.main import main
    base = ["--algo", "turboaggregate", "--model", "lr", "--dataset",
            "mnist", "--client_num_in_total", "8", "--client_num_per_round",
            "4", "--group_num", "2", "--comm_round", "2", "--batch_size",
            "4", "--log_stdout", "false"]
    s_xla = main(base + ["--secagg_backend", "xla"])
    s_pal = main(base + ["--secagg_backend", "pallas"])
    # masks cancel in both: the dequantized aggregates differ only by
    # fixed-point rounding, so accuracies should be essentially equal
    assert abs(s_xla["train_acc"] - s_pal["train_acc"]) < 0.05, (s_xla, s_pal)
