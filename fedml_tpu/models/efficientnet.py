"""EfficientNet B0-B7 (parity: fedml_api/model/cv/efficientnet.py:138 +
efficientnet_utils.py) — Tan & Le'19 compound-scaled MBConv nets.

The reference carries ~900 LoC of utils (swish autograd hacks, TF-'same'
padding shims, url loaders); on TPU none of that survives: swish is
``nn.swish`` (XLA fuses it), 'SAME' padding is native, and pretrained-url
loading is out of scope.  What remains is the architecture itself:
stem -> 7 MBConv stages (compound-scaled) -> head -> pool -> classifier.

Drop-connect (stochastic depth) is applied per-sample during training like
the reference (efficientnet_utils.py drop_connect).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.norms import Norm, conv_kernel_init
from fedml_tpu.models.mobilenet import InvertedResidual

# (expand_ratio, channels, repeats, stride, kernel) — B0 baseline, Table 1.
_B0_BLOCKS = (
    (1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3))

# name -> (width_mult, depth_mult, dropout) (efficientnet_utils.py:
# efficientnet_params).
_SCALINGS = {
    "b0": (1.0, 1.0, 0.2), "b1": (1.0, 1.1, 0.2), "b2": (1.1, 1.2, 0.3),
    "b3": (1.2, 1.4, 0.3), "b4": (1.4, 1.8, 0.4), "b5": (1.6, 2.2, 0.4),
    "b6": (1.8, 2.6, 0.5), "b7": (2.0, 3.1, 0.5),
}


def _round_filters(ch: int, width_mult: float, divisor: int = 8) -> int:
    ch *= width_mult
    new = max(divisor, int(ch + divisor / 2) // divisor * divisor)
    if new < 0.9 * ch:
        new += divisor
    return int(new)


def _round_repeats(r: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * r))


class EfficientNet(nn.Module):
    num_classes: int = 1000
    width_mult: float = 1.0
    depth_mult: float = 1.0
    dropout: float = 0.2
    drop_connect: float = 0.2
    norm: str = "group"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(_round_filters(32, self.width_mult), (3, 3),
                    strides=(2, 2), padding="SAME", use_bias=False,
                    kernel_init=conv_kernel_init)(x)
        x = nn.swish(Norm(self.norm)(x, train))
        total = sum(_round_repeats(r, self.depth_mult)
                    for _, _, r, _, _ in _B0_BLOCKS)
        idx = 0
        for expand, ch, repeats, stride, kernel in _B0_BLOCKS:
            out_ch = _round_filters(ch, self.width_mult)
            for i in range(_round_repeats(repeats, self.depth_mult)):
                in_ch = x.shape[-1]
                x = InvertedResidual(
                    exp_ch=in_ch * expand, out_ch=out_ch, kernel=kernel,
                    stride=stride if i == 0 else 1, use_se=True,
                    use_hs=False, norm=self.norm, activation=nn.swish,
                    se_reduce_ch=max(1, in_ch // 4),
                    drop_rate=self.drop_connect * idx / total)(x, train)
                idx += 1
        x = nn.Conv(_round_filters(1280, self.width_mult), (1, 1),
                    use_bias=False, kernel_init=conv_kernel_init)(x)
        x = nn.swish(Norm(self.norm)(x, train))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def efficientnet(name: str = "b0", num_classes: int = 1000,
                 norm: str = "group") -> EfficientNet:
    """``EfficientNet.from_name('efficientnet-b0')`` parity
    (efficientnet.py:318-322)."""
    w, d, drop = _SCALINGS[name]
    return EfficientNet(num_classes=num_classes, width_mult=w, depth_mult=d,
                        dropout=drop, norm=norm)
