#!/usr/bin/env python
"""CLI for the wire-path microbench (fedml_tpu/utils/wirebench.py).

Measures, on the CPU container (honest host wall clock, no accelerator):

  a. broadcast serialize time vs cohort size — per-silo encode (seed
     path) vs encode-once ``send_many``;
  b. encode/decode copies per leaf (codec spy counts, not estimates);
  c. end-to-end round time of a real federation over the codec-roundtrip
     hub, seed path vs encode-once + incremental staging (plus a chaos
     arm with dup/reorder/corrupt faults and the admission screen armed).

Writes BENCH_wire.json and prints one summary JSON line.

  python scripts/wire_bench.py              # full: ~10MB model, N=1..8
  python scripts/wire_bench.py --smoke      # CI/chaos-suite sized
  python scripts/wire_bench.py --out /tmp/w.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="small model / short run (rides run_chaos.sh)")
    ap.add_argument("--out", default=None,
                    help="details artifact path ('' to skip writing); "
                         "default BENCH_wire.json for full runs, a /tmp "
                         "path for --smoke so CI-sized numbers can never "
                         "clobber the committed full-bench artifact")
    args = ap.parse_args()
    if args.out is None:
        args.out = ("/tmp/BENCH_wire_smoke.json" if args.smoke
                    else "BENCH_wire.json")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fedml_tpu.utils.wirebench import run

    details = run(out_path=args.out or None, smoke=args.smoke)
    ser = details["broadcast_serialize"]
    e2e = details["round_e2e"]
    n_max = str(max(ser["cohort_sizes"]))
    line = {
        "metric": "wire_encode_once_speedup_n%s" % n_max,
        "value": round(ser["speedup_at_n%s" % n_max], 2),
        "unit": "x",
        "backend": details["backend"],
        "model_mb": details["model_mb"],
        "round_speedup_e2e": round(e2e["round_speedup"], 3),
        "results_identical": e2e["results_identical"],
        "encode_copies_per_leaf":
            details["codec_copies"]["encode_copies_per_leaf"],
        "decode_leaves_sharing_frame_memory":
            details["codec_copies"]["decode_leaves_sharing_frame_memory"],
        "chaos_rounds_completed":
            e2e["encode_once_under_chaos"]["rounds"],
    }
    print(json.dumps(line), flush=True)
    # acceptance gates.  Functional (always hard): the two e2e paths
    # agree bit-for-bit and the chaos arm completed its rounds.  Timing
    # (hard on FULL runs only): one shared encode beats N=8 per-silo
    # encodes by >= 4x — on a --smoke run inside a loaded CI container a
    # wall-clock ratio dipping under the bar is a perf flake, not a
    # functional regression, and must not fail the chaos suite.
    # (chaos-arm completion is asserted inside bench_round_e2e itself —
    # an incomplete federation raises before we get here)
    ok = e2e["results_identical"]
    timing_ok = line["value"] >= 4.0
    if not timing_ok:
        sys.stderr.write("wire_bench: encode-once speedup "
                         f"{line['value']}x below the 4x bar"
                         + (" (smoke: advisory only)\n" if args.smoke
                            else " — acceptance gate FAILED\n"))
    if not ok:
        sys.stderr.write("wire_bench: FUNCTIONAL gate failed "
                         f"(identical={e2e['results_identical']})\n")
    return 0 if ok and (timing_ok or args.smoke) else 1


if __name__ == "__main__":
    sys.exit(main())
