"""Expert parallelism (ep): shard MoE expert tables over an ``experts``
mesh axis, GSPMD-style.

The SwitchFFN layer (models/moe.py) keeps its experts as explicit
``[E, ...]`` einsum operands precisely so that ep is a PLACEMENT, not an
algorithm: put the tables' leading axis on the mesh's ``experts``
dimension, jit the unchanged forward/training step, and XLA inserts the
dispatch/combine collectives (the token->expert einsum becomes an
all-to-all-shaped reduce across expert shards).  Same recipe as
tp_shard_params — pick a mesh, annotate shardings, let XLA work
(SURVEY.md §2.5: parallelism is a config knob).

Composability: the ``experts`` axis can be a second mesh dimension next to
``clients`` (dp x ep federated training) — each device then holds its
cohort shard AND its expert shard, exactly like dp x tp.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_expert_mesh(n_experts_axis: int,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D [experts] mesh (pure ep; make_dp_ep_mesh for the combined
    federated form)."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_experts_axis:
        raise ValueError(f"need {n_experts_axis} devices for the experts "
                         f"axis, have {len(devices)}")
    arr = np.asarray(devices[:n_experts_axis])
    return Mesh(arr, ("experts",))


def make_dp_ep_mesh(client_axis: int, expert_axis: int,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """[clients, experts] mesh for dp x ep federated MoE training: cohort
    rows sharded on ``clients`` (P("clients") data placement), expert
    tables on ``experts`` (ep_shard_params works unchanged — it only needs
    the axis name), everything under the PLAIN vmapped cohort step with
    GSPMD inserting both the client psums and the expert all-to-alls."""
    devices = list(devices if devices is not None else jax.devices())
    n = client_axis * expert_axis
    if len(devices) < n:
        raise ValueError(f"need {n} devices for a [{client_axis}, "
                         f"{expert_axis}] mesh, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(client_axis, expert_axis)
    return Mesh(arr, ("clients", "experts"))


def ep_shard_params(params: Any, mesh: Mesh, n_experts: int,
                    axis: str = "experts") -> Any:
    """Place MoE expert tables' leading [E] dim on the ``axis`` mesh axis;
    everything else replicated.

    Gated on BOTH the param path (inside a ``moe_*`` module — SwitchFFN's
    naming in TransformerLM) and the leading-dim size, so a coincidental
    E-sized leading dim elsewhere (a Dense kernel with in=E) never gets an
    expert sharding.  The router stays replicated: every token needs every
    router row."""
    n = mesh.shape[axis]
    if n_experts % n:
        raise ValueError(f"n_experts={n_experts} not divisible by the "
                         f"{axis} mesh axis ({n})")

    def place(path, x):
        keys = [str(getattr(p, "key", "")) for p in path]
        in_moe = any(k.startswith("moe_") for k in keys)
        is_router = any(k == "router" for k in keys)
        nd = getattr(x, "ndim", 0)
        if (in_moe and not is_router and nd >= 1
                and x.shape[0] == n_experts):
            spec = [axis] + [None] * (nd - 1)
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map_with_path(place, params)
