#!/usr/bin/env python
"""Secure-aggregation overhead bench (ISSUE 11): mask-agreement / unmask
cost on the PR 6 perf ledger at N=8 and N=32, flat vs grouped.

Each arm is a fresh subprocess running the real cross-silo federation
over the local hub with ``--perf`` on; the measurements are the ledger's
own ``mask_agreement`` / ``unmask`` phase medians (first round skipped —
it pays the jit compiles) plus the telemetry share-frame counters, so
the committed numbers are exactly what the flight recorder would show a
production run.  Grouped masking (--secagg grouped, E edges) must move
strictly fewer share frames than flat at the same N — the O(N²) →
O(N²/E) agreement-traffic claim, asserted here, not just stated.

CPU-container honest: ``backend`` is labeled and the wall times are
advisory context for the RATIOS (overhead share of round_s, grouped vs
flat frames), which is what the artifact exists to pin.

    python scripts/secagg_bench.py                 # full: N=8 + N=32
    python scripts/secagg_bench.py --smoke         # N=8 arms, /tmp output
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_arm(name, n_silos, rounds, secagg, edges, workdir):
    run_dir = os.path.join(workdir, name)
    ledger = os.path.join(run_dir, "perf.jsonl")
    cmd = [sys.executable, "-m", "fedml_tpu",
           "--algo", "cross_silo", "--model", "lr", "--dataset", "mnist",
           "--client_num_in_total", str(n_silos),
           "--client_num_per_round", str(n_silos),
           "--comm_round", str(rounds),
           "--frequency_of_the_test", str(rounds),
           "--batch_size", "4", "--log_stdout", "false",
           "--perf", "true", "--perf_strict", "true",
           "--telemetry", "true", "--run_dir", run_dir,
           "--perf_ledger", ledger]
    if secagg != "off":
        cmd += ["--secagg", secagg, "--agg_mode", "stream"]
    if edges:
        cmd += ["--edge_aggregators", str(edges)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    print(f"== arm {name}: N={n_silos} secagg={secagg} edges={edges}")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=1800)
    if proc.returncode != 0:
        raise SystemExit(f"arm {name} failed rc={proc.returncode}:\n"
                         f"{proc.stderr[-3000:]}")

    rows = [json.loads(l) for l in open(ledger) if l.strip()]
    steady = rows[1:] or rows  # skip the compile-paying first round
    tel = json.load(open(os.path.join(run_dir, "telemetry.json")))
    counters = tel.get("counters", {})
    hists = tel.get("histograms", {})

    def hist_mean(name):
        # one histogram per protocol endpoint (root, or each edge under
        # grouped masking) — pool them: the protocol's own instrument,
        # visible wherever the SecAggServer actually runs
        tot = cnt = 0.0
        for k, v in hists.items():
            if k.startswith(name):
                tot += v.get("sum", 0.0)
                cnt += v.get("count", 0)
        return (tot / cnt) if cnt else 0.0

    share_frames = sum(v for k, v in counters.items()
                       if k.startswith("fedml_secagg_share_frames_total"))
    envelopes = sum(v for k, v in counters.items()
                    if k.startswith("fedml_secagg_share_envelopes_total"))
    masked = sum(v for k, v in counters.items()
                 if k.startswith("fedml_secagg_masked_uploads_total"))
    recon = sum(v for k, v in counters.items()
                if k.startswith("fedml_secagg_unmask_reconstructions"))
    round_s = statistics.median(r["round_s"] for r in steady)
    agreement_s = hist_mean("fedml_secagg_agreement_seconds")
    unmask_s = hist_mean("fedml_secagg_unmask_seconds")
    med_phase = lambda key: statistics.median(  # noqa: E731
        r["phases"].get(key, 0.0) for r in steady)
    out = {
        "n_silos": n_silos, "rounds": rounds, "secagg": secagg,
        "edges": edges,
        "round_s_median": round_s,
        # wall span round-open -> roster flush / unmask-open -> finalize
        # (the protocol's own histograms): on the in-process hub the
        # agreement span OVERLAPS the cohort's serialized local training,
        # so it is an upper bound on protocol latency, not compute cost
        "mask_agreement_s_mean": agreement_s,
        "unmask_s_mean": unmask_s,
        # pure handler compute (the flat root's ledger phases): what the
        # protocol itself costs the server per round
        "mask_agreement_handler_s_median": med_phase("mask_agreement"),
        "unmask_handler_s_median": med_phase("unmask"),
        "secagg_overhead_frac": ((agreement_s + unmask_s) / round_s
                                 if round_s else None),
        "share_frames_total": share_frames,
        "share_envelopes_total": envelopes,
        "masked_uploads_total": masked,
        "unmask_reconstructions_total": recon,
        "recompiles": sum(r.get("recompiles", 0) for r in rows),
    }
    if secagg != "off":
        # the flat path's ledger must carry the new phases (grouped runs
        # the protocol at the EDGES, which have no perf recorder — their
        # cost shows in the histograms above)
        out["ledger_has_secagg_phases"] = all(
            "unmask" in r["phases"] for r in steady) if secagg == \
            "pairwise" else None
    print(f"   round {round_s * 1e3:.1f}ms  agreement "
          f"{agreement_s * 1e3:.1f}ms  unmask {unmask_s * 1e3:.1f}ms  "
          f"envelopes {envelopes:.0f}")
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="N=8 arms only; output under /tmp (never the "
                        "committed artifact)")
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    arms = [("n8_plain", 8, "off", 0), ("n8_flat", 8, "pairwise", 0),
            ("n8_grouped", 8, "grouped", 2)]
    if not args.smoke:
        arms += [("n32_flat", 32, "pairwise", 0),
                 ("n32_grouped", 32, "grouped", 4)]
    out_path = args.out or (
        os.path.join(tempfile.gettempdir(), "BENCH_secagg.json")
        if args.smoke else os.path.join(REPO, "BENCH_secagg.json"))

    import jax
    workdir = tempfile.mkdtemp(prefix="secagg_bench.")
    results = {}
    for name, n, secagg, edges in arms:
        results[name] = run_arm(name, n, args.rounds, secagg, edges,
                                workdir)

    # acceptance gates — the artifact's claims, verified before writing
    failures = []
    for name, r in results.items():
        if r["secagg"] != "off":
            if not (r["mask_agreement_s_mean"] > 0
                    and r["unmask_s_mean"] > 0):
                failures.append(f"{name}: secagg timing instruments "
                                f"recorded nothing")
            if r["masked_uploads_total"] < r["n_silos"]:
                failures.append(f"{name}: fewer masked uploads than silos")
            if r["recompiles"]:
                failures.append(f"{name}: {r['recompiles']} recompiles — "
                                f"the protocol is host-side by design")
            if r.get("ledger_has_secagg_phases") is False:
                failures.append(f"{name}: flat-path ledger lines missing "
                                f"the mask_agreement/unmask phases")
    for n in (8, 32):
        flat, grp = results.get(f"n{n}_flat"), results.get(f"n{n}_grouped")
        if flat and grp and \
                grp["share_envelopes_total"] >= flat["share_envelopes_total"]:
            failures.append(
                f"N={n}: grouped relayed {grp['share_envelopes_total']:.0f} "
                f"share envelopes vs flat {flat['share_envelopes_total']:.0f}"
                f" — the O(N²/E) agreement-traffic claim does not hold")
    if failures:
        for f in failures:
            print(f"FAIL {f}")
        return 1

    artifact = {
        "bench": "secagg_overhead",
        "backend": jax.default_backend(),
        "note": ("wall times are 1-core-CPU-container advisory context; "
                 "the pinned claims are the ratios (overhead share of "
                 "round_s, grouped-vs-flat share frames)"),
        "rounds_per_arm": args.rounds,
        "arms": results,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"== secagg bench OK -> {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
