#!/usr/bin/env python
"""Sustained-degradation survivability soak (ISSUE 19) → BENCH_degrade.json.

Three arms over the same deterministic 6-silo federation (silo 5 is a
NaN-spewing attacker the admission pipeline rejects, silo 6 is
persistently slow):

* **clean** — no chaos, wait policy: the convergence reference;
* **static** — flapping links (drop/dup/delay — never corrupt) on the
  silos 4-6 with the classic drop policy at the static
  ``round_timeout_s`` cap: what degradation costs WITHOUT the spine;
* **degrade** — the same chaos plus a correlated partition cutting
  silos 4 and 6 silo->server (uploads AND heartbeats) over a known
  round span, a mid-soak ``barrier_close`` process kill + in-process
  respawn, and the full degrade spine live: adaptive deadlines,
  quorum-aware closure with partition holds, fault attribution,
  participation debt.

Invariants (any failure exits 1, with the gate named):

  G1  zero network- or unknown-attributed trust strikes — the flaky
      links and deadline drops must NEVER look Byzantine (silo 5's
      payload strikes still land);
  G2  the adaptive deadline undercuts the static cap on >= 80% of warm
      rounds, and round wall-clock tracks it (holds excluded);
  G3  bounded starvation — no honest silo goes more than
      ``STARVE_BOUND`` rounds without an accepted upload;
  G4  the degraded arm's final global lands within ``CONV_TOL`` (L2)
      of the clean arm;
  G5  zero recompiles after warmup under strict sentry on every
      measured arm;
  G6  the killed round's resumed deadline equals the pre-kill one
      exactly — the deadline is a pure function of ledgered history;
  G7  the partition rounds produced >= 1 HOLD (the discrimination
      actually fired), and the kill actually landed.

Determinism: chaos and kills derive from --seed.  ``--smoke`` is the
CI twin (reduced rounds/windows, artifact labeled smoke=true —
``perf_trend.py --degrade_bench`` refuses to anchor the committed
trend line on it).

Usage:
  python scripts/degrade_soak.py [--smoke] [--seed N] [--out PATH]
"""

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from fedml_tpu.algorithms.cross_silo import (FailureDetector,  # noqa: E402
                                             FedAvgClientActor,
                                             FedAvgServerActor, MsgType)
from fedml_tpu.comm.chaos import (ChaosPlan, ChaosTransport,  # noqa: E402
                                  LinkChaos, Partition)
from fedml_tpu.comm.local import LocalHub  # noqa: E402
from fedml_tpu.core.stream_agg import StreamingAggregator  # noqa: E402
from fedml_tpu.obs.perf import PerfRecorder  # noqa: E402
from fedml_tpu.obs.trend import load_ledger  # noqa: E402
from fedml_tpu.robust import AdmissionPipeline, TrustTracker  # noqa: E402
from fedml_tpu.robust.degrade import ReliabilityTracker  # noqa: E402
from fedml_tpu.robust.faultline import (ActorKilled, CrashSpec,  # noqa: E402
                                        Faultline)
from fedml_tpu.utils.checkpoint import RoundCheckpointer  # noqa: E402
from fedml_tpu.utils.journal import RoundJournal  # noqa: E402

MAX_RESPAWNS = 5
N_SILOS = 6
ATTACKER = 5          # NaN upload every tasked round: payload strikes
SLOW = 6              # persistently slow but honest: must never strike
HONEST = (1, 2, 3, 4, SLOW)
FLAKY = (4, 5, 6)     # silos on bad links; 1-3 stay clean so the
#                       quorum floor of 3 is always reachable (liveness)
PARTITIONED = (4, 6)  # the correlated window cuts these silo->server
WARMUP_ROUNDS = 5
STARVE_BOUND = 6
CONV_TOL = 1.5
FRAC_THRESHOLD = 0.8
WALL_SLACK_S = 0.5


class Violation(Exception):
    pass


def _cfg(smoke):
    # the partition is ROUND-bounded (cut rounds in [a, b)), not
    # wall-clock: a cold-start stall on a chaos-dropped upload can eat
    # seconds, and a wall window would drift past the rounds it meant
    # to hit; round space is immune to that variance.  Two partition
    # rounds, not one — the hold needs EVERY missing silo non-ALIVE,
    # and a coincidental chaos drop of the (beating, alive) attacker's
    # upload in one round spoils that evidence; two rounds make the
    # spoiler a coincidence squared.
    if smoke:
        return dict(rounds=10, static_rounds=4, cap=3.0, slow_s=0.4,
                    part=(6, 8), kill_round=8, suspect_s=0.5)
    return dict(rounds=40, static_rounds=12, cap=5.0, slow_s=0.8,
                part=(12, 14), kill_round=18, suspect_s=0.75)


def _params(seed=3):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(6, 4).astype(np.float32),
                      "bias": rng.randn(4).astype(np.float32)}}


def _train_fn(silo, slow_s=0.0):
    """Deterministic per (silo, round) — identical params across arms;
    only the LATENCY differs (the slow silo sleeps, the attacker
    spews NaN)."""
    def fn(params, client_idx, round_idx):
        if silo == SLOW and slow_s > 0:
            time.sleep(slow_s)
        if silo == ATTACKER:
            return jax.tree.map(
                lambda v: np.full_like(np.asarray(v), np.nan), params), 10
        rng = np.random.RandomState(1000 * silo + int(round_idx or 0))
        return jax.tree.map(
            lambda v: v + rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
            params), 10 + silo
    return fn


def _l2(a, b):
    return float(np.sqrt(sum(
        float(np.sum((np.asarray(x, np.float64)
                      - np.asarray(y, np.float64)) ** 2))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))))


def _plan(seed, part=None):
    """Flapping links for silos 4-6 (both directions, never corrupt —
    every payload strike must trace to the attacker), plus the
    correlated round-bounded partition cutting silos 4 and 6
    silo->server (uploads AND round-tagged heartbeats: the detector
    evidence the verdict needs)."""
    flaky = dict(drop_prob=0.08, dup_prob=0.05, delay_prob=0.2,
                 max_delay_s=0.05)
    links = {}
    for s in FLAKY:
        links[(s, 0)] = LinkChaos(**flaky)
        links[(0, s)] = LinkChaos(**flaky)
    if part is not None:
        for s in PARTITIONED:
            links[(s, 0)] = LinkChaos(
                partition=Partition(after_round=part[0],
                                    until_round=part[1]), **flaky)
    return ChaosPlan(seed=seed, default=LinkChaos(), links=links,
                     immune_types=(MsgType.S2C_FINISH,
                                   MsgType.ROUND_TIMEOUT))


def _compose_extra(named):
    """Named (get, set) pairs folded into one extra_state hook (the
    main.py composition, inlined so the soak never imports the CLI)."""
    def get():
        return {name: g() for name, (g, _) in named}

    def set_(tree):
        for name, (_, s) in named:
            sub = tree.get(name) if hasattr(tree, "get") else None
            if sub is not None:
                s(sub)
    return (get, set_)


def _run(workdir, *, rounds, plan=None, cap=None, slow_s=0.0,
         degrade_cfg=None, suspect_s=None, fl=None, perf_path=None,
         ck=False, hb_s=None, deadline_trace=None):
    """One federation attempt: pump when chaos-free, threaded drive
    under a ChaosTransport wrap.  Returns (server, admission)."""
    init = _params(3)
    hub = LocalHub(codec_roundtrip=True)
    wrap = (lambda t: t) if plan is None \
        else (lambda t: ChaosTransport(t, plan))
    perf = None
    if perf_path:
        perf = PerfRecorder(perf_path, strict_recompiles=True,
                            rss_interval_s=10.0)
    stream = StreamingAggregator(init, method="mean", kind="params",
                                 norm_clip=1.0, seed=0,
                                 sentry=perf.sentry if perf else None)
    adm = AdmissionPipeline(
        init, kind="params",
        trust=TrustTracker(strikes_to_quarantine=1, quarantine_rounds=5,
                           probation_rounds=2))
    extra = (lambda: adm.trust.state_dict(N_SILOS),
             adm.trust.load_state_dict)
    degrade = None
    if degrade_cfg is not None:
        degrade = ReliabilityTracker(N_SILOS, **degrade_cfg)
        if deadline_trace is not None:
            orig = degrade.deadline_s

            def spy(expected, cap_s, _orig=orig, _t=deadline_trace):
                d = _orig(expected, cap_s)
                _t.append(d)
                return d
            degrade.deadline_s = spy
        extra = _compose_extra([
            ("trust", extra),
            ("degrade", (degrade.state_dict, degrade.load_state_dict))])
    kw = {}
    if cap is not None:
        kw = dict(straggler_policy="drop", round_timeout_s=cap,
                  min_silo_frac=0.5)
    if suspect_s is not None:
        # dead_after_s huge: partitioned silos go SUSPECT, never DEAD —
        # the spine must survive on suspicion evidence alone
        kw["failure_detector"] = FailureDetector(
            suspect_after_s=suspect_s, dead_after_s=3600.0)
    server = FedAvgServerActor(
        wrap(hub.transport(0)), init, N_SILOS, N_SILOS, rounds,
        checkpointer=(RoundCheckpointer(os.path.join(workdir, "ck"),
                                        save_every=1) if ck else None),
        journal=(RoundJournal(os.path.join(workdir, "j"),
                              snapshot_every=1) if ck else None),
        stream_agg=stream, admission=adm, extra_state=extra,
        degrade=degrade, faultline=fl, perf=perf, **kw)
    silos = [FedAvgClientActor(i, wrap(hub.transport(i)),
                               _train_fn(i, slow_s=slow_s),
                               heartbeat_interval_s=hb_s)
             for i in range(1, N_SILOS + 1)]
    server.register_handlers()
    for s in silos:
        s.register_handlers()
    try:
        if plan is not None:
            import threading
            threads = [threading.Thread(target=a.run, daemon=True)
                       for a in silos]
            for t in threads:
                t.start()
            server.start()
            server.transport.run()
            for t in threads:
                t.join(timeout=10)
        else:
            server.start()
            hub.pump()
    finally:
        if perf is not None:
            perf.close()
    return server, adm


def _merged_rows(perf_paths):
    """Per-round ledger rows across respawn attempts (a later attempt's
    re-run of a round wins); each attempt's first row is flagged — it
    pays the jit compiles and is excluded from wall tracking."""
    rows = {}
    for path in perf_paths:
        if not os.path.exists(path):
            continue
        for i, r in enumerate(load_ledger(path)):
            r = dict(r)
            r["_attempt_first"] = (i == 0)
            rows[int(r["round"])] = r
    return [rows[k] for k in sorted(rows)]


def _recompiles_after_warmup(perf_paths):
    total = 0
    for path in perf_paths:
        if not os.path.exists(path):
            continue
        rows = load_ledger(path)
        total += sum(int(r.get("recompiles") or 0) for r in rows[1:])
    return total


def _starvation(bench_rows):
    """Max consecutive rounds each honest silo went unfolded, from the
    per-round accepted sets on the degrade ledger."""
    worst = {}
    for silo in HONEST:
        since = mx = 0
        for row in bench_rows:
            if silo in row["accepted_silos"]:
                since = 0
            else:
                since += 1
            mx = max(mx, since)
        worst[str(silo)] = mx
    return worst


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI twin (artifact labeled smoke=true)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=str, default="",
                    help="write BENCH_degrade.json here")
    args = ap.parse_args(argv)
    cfg = _cfg(args.smoke)
    backend = jax.default_backend()

    # -- clean arm: the convergence reference ---------------------------
    print("[degrade_soak] arm clean ...", flush=True)
    with tempfile.TemporaryDirectory() as d:
        clean_srv, _ = _run(d, rounds=cfg["rounds"])
        clean_params, clean_rounds = clean_srv.params, clean_srv.round_idx

    # -- static arm: drop policy at the static cap ----------------------
    print("[degrade_soak] arm static ...", flush=True)
    with tempfile.TemporaryDirectory() as d:
        pp = os.path.join(d, "perf.jsonl")
        static_srv, _ = _run(d, rounds=cfg["static_rounds"],
                             plan=_plan(args.seed), cap=cfg["cap"],
                             slow_s=cfg["slow_s"], perf_path=pp)
        static_rows = [{"round": int(r["round"]),
                        "wall_s": round(float(r["round_s"]), 4)}
                       for r in _merged_rows([pp])]
        static_rc = _recompiles_after_warmup([pp])
        static_rounds_done = static_srv.round_idx

    # -- degrade arm: the spine under chaos + partition + kill ----------
    print("[degrade_soak] arm degrade ...", flush=True)
    degrade_cfg = dict(min_quorum=0.5, adaptive_deadline=True,
                       deadline_floor_s=0.3, deadline_quantile=0.9,
                       deadline_slack=1.5, partition_frac=0.3,
                       partition_max_holds=3, min_history=2)
    traces, perfs, failures = {}, [], []
    with tempfile.TemporaryDirectory() as d:

        def once(fl, attempt):
            trace = traces.setdefault(attempt, [])
            pp = os.path.join(d, f"a{attempt}-perf.jsonl")
            perfs.append(pp)
            # round-bounded partition: by the resumed round (>= the
            # kill round, past the partition span) the cut is inert,
            # so every attempt safely runs the SAME plan
            plan = _plan(args.seed, part=cfg["part"])
            return _run(d, rounds=cfg["rounds"], plan=plan,
                        cap=cfg["cap"], slow_s=cfg["slow_s"],
                        degrade_cfg=degrade_cfg,
                        suspect_s=cfg["suspect_s"], fl=fl, perf_path=pp,
                        ck=True, hb_s=0.25, deadline_trace=trace)

        fl = Faultline(crashes=[CrashSpec(point="barrier_close", hit=1,
                                          round_idx=cfg["kill_round"])],
                       seed=args.seed)
        for attempt in range(MAX_RESPAWNS + 1):
            try:
                deg_srv, deg_adm = once(fl, attempt)
                break
            except ActorKilled:
                fl.respawn()
        else:
            raise Violation(f"still crashing after {MAX_RESPAWNS} "
                            f"respawns")

        rows = _merged_rows(perfs)
        bench_rows = []
        for r in rows:
            dg = r.get("degrade") or {}
            bench_rows.append({
                "round": int(r["round"]),
                "wall_s": round(float(r["round_s"]), 4),
                "deadline_s": dg.get("deadline_s"),
                "accepted_silos": dg.get("accepted") or [],
                "accepted": len(dg.get("accepted") or []),
                "dropped": len(dg.get("dropped") or []),
                "holds": int(dg.get("holds") or 0),
                "attempt_first": bool(r.get("_attempt_first"))})
        deg_rc = _recompiles_after_warmup(perfs)
        sft = deg_adm.trust.strike_fault_totals()
        starve = _starvation(bench_rows)
        tracker = deg_srv.degrade

    # -- gates ----------------------------------------------------------
    warm = [r for r in bench_rows if r["round"] >= WARMUP_ROUNDS
            and isinstance(r["deadline_s"], (int, float))]
    under = sum(1 for r in warm if r["deadline_s"] < cfg["cap"])
    beat_frac = under / len(warm) if warm else 0.0
    nohold = [r for r in warm
              if not r["holds"] and not r["attempt_first"]]
    tracked = sum(1 for r in nohold
                  if r["wall_s"] <= r["deadline_s"] + WALL_SLACK_S)
    track_frac = tracked / len(nohold) if nohold else 0.0
    pre = traces.get(0, [None])[-1]
    post = traces.get(1, [None])[0]
    delta = _l2(deg_srv.params, clean_params)
    gates = {
        "zero_network_strikes": {
            "ok": sft.get("network", 0) == 0 and sft.get("unknown", 0) == 0,
            "network": sft.get("network", 0),
            "unknown": sft.get("unknown", 0)},
        "payload_strikes_land": {
            "ok": sft.get("payload", 0) >= 1, "payload": sft.get("payload", 0)},
        "adaptive_beats_static": {
            "ok": beat_frac >= FRAC_THRESHOLD, "frac": round(beat_frac, 3),
            "threshold": FRAC_THRESHOLD, "warm_rounds": len(warm)},
        "deadline_tracks_wall": {
            "ok": track_frac >= FRAC_THRESHOLD,
            "frac": round(track_frac, 3), "threshold": FRAC_THRESHOLD,
            "slack_s": WALL_SLACK_S, "rounds": len(nohold)},
        "bounded_starvation": {
            "ok": all(v <= STARVE_BOUND for v in starve.values()),
            "bound": STARVE_BOUND, "worst": max(starve.values())},
        "convergence_vs_clean": {
            "ok": delta <= CONV_TOL, "delta": round(delta, 4),
            "tolerance": CONV_TOL},
        "zero_recompiles": {
            "ok": static_rc == 0 and deg_rc == 0,
            "static": static_rc, "degrade": deg_rc},
        "resume_deadline_determinism": {
            "ok": (isinstance(pre, float) and isinstance(post, float)
                   and abs(pre - post) < 1e-9 and pre < cfg["cap"]),
            "pre": pre, "post": post},
        "partition_hold_exercised": {
            "ok": tracker.holds_total >= 1 and fl.kills >= 1,
            "holds": tracker.holds_total, "kills": fl.kills},
        "bounded_progress": {
            "ok": (deg_srv.round_idx == cfg["rounds"]
                   and clean_rounds == cfg["rounds"]
                   and static_rounds_done == cfg["static_rounds"]),
            "degrade_rounds": deg_srv.round_idx},
    }
    failures = [f"{name}: {v}" for name, v in gates.items() if not v["ok"]]

    bench = {
        "bench": "degrade", "version": 1, "smoke": bool(args.smoke),
        "seed": args.seed, "backend": backend, "n_silos": N_SILOS,
        "attacker_silo": ATTACKER, "slow_silo": SLOW,
        "rounds": cfg["rounds"], "round_timeout_s": cfg["cap"],
        "warmup_rounds": WARMUP_ROUNDS,
        "partition_rounds": list(cfg["part"]),
        "degrade_config": degrade_cfg,
        "arms": {
            "clean": {"backend": backend,
                      "rounds_completed": clean_rounds},
            "static": {"backend": backend,
                       "rounds_completed": static_rounds_done,
                       "rounds": static_rows,
                       "wall_p90_s": round(float(np.percentile(
                           [r["wall_s"] for r in static_rows], 90)), 4),
                       "recompiles_after_warmup": static_rc},
            "degrade": {
                "backend": backend,
                "rounds_completed": deg_srv.round_idx,
                "rounds": [{k: v for k, v in r.items()
                            if k != "attempt_first"}
                           for r in bench_rows],
                "wall_p90_s": round(float(np.percentile(
                    [r["wall_s"] for r in bench_rows], 90)), 4),
                "strike_fault_totals": sft,
                "max_rounds_since_accept": starve,
                "holds_total": tracker.holds_total,
                "drops_total": tracker.drops_total,
                "kill_round": cfg["kill_round"], "kills": fl.kills,
                "resume": {"round": cfg["kill_round"],
                           "deadline_pre_kill": pre,
                           "deadline_post_resume": post},
                "final_delta_vs_clean": round(delta, 4),
                "recompiles_after_warmup": deg_rc},
        },
        "gates": gates,
    }
    print(json.dumps(bench["gates"], indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(bench, f, indent=2)
        print(f"[degrade_soak] wrote {args.out}")
    if failures:
        for f in failures:
            print(f"[degrade_soak] GATE FAILED {f}", file=sys.stderr)
        return 1
    print(f"[degrade_soak] all {len(gates)} gates green "
          f"(delta vs clean {delta:.3f}, holds {tracker.holds_total}, "
          f"strikes {sft})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
