"""Byzantine-robust aggregation (core/byzantine.py) — beyond the
reference's clip+DP defenses.  Each rule: numpy-oracle correctness with
weight-0 padded slots, resistance to actually-poisoned updates inside a
full federated round, and the CLI surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.byzantine import (METHODS, coordinate_median,
                                      geometric_median, krum, krum_weights,
                                      make_byzantine_aggregate,
                                      trimmed_mean)


@pytest.fixture()
def stacked(rng):
    return {"a": jnp.asarray(rng.randn(7, 5, 3).astype(np.float32)),
            "b": jnp.asarray(rng.randn(7, 4).astype(np.float32))}


def _pad(tree, k):
    return jax.tree.map(
        lambda x: jnp.concatenate([x, jnp.zeros((k,) + x.shape[1:],
                                                x.dtype)]), tree)


def test_coordinate_median_oracle_and_padding(stacked):
    w = jnp.ones(7)
    got = coordinate_median(stacked, w)
    np.testing.assert_allclose(np.asarray(got["a"]),
                               np.median(np.asarray(stacked["a"]), axis=0),
                               rtol=1e-6)
    # weight-0 padded slots must not move the median
    got_pad = coordinate_median(_pad(stacked, 3),
                                jnp.concatenate([w, jnp.zeros(3)]))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), got, got_pad)


def test_trimmed_mean_oracle_and_padding(stacked):
    w = jnp.ones(7)
    got = trimmed_mean(stacked, w, trim_frac=0.2)  # k = floor(1.4) = 1
    a = np.sort(np.asarray(stacked["a"]), axis=0)[1:-1]
    np.testing.assert_allclose(np.asarray(got["a"]), a.mean(axis=0),
                               rtol=1e-5)
    got_pad = trimmed_mean(_pad(stacked, 2),
                           jnp.concatenate([w, jnp.zeros(2)]), 0.2)
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=1e-5), got, got_pad)


def test_krum_selects_the_cluster(rng):
    """6 honest updates in a tight cluster + 2 far outliers: Krum's pick
    must be an honest client, even with the outliers claiming huge
    sample weights."""
    honest = rng.randn(1, 10).astype(np.float32) + \
        0.01 * rng.randn(6, 10).astype(np.float32)
    evil = 50.0 + rng.randn(2, 10).astype(np.float32)
    tree = {"w": jnp.asarray(np.concatenate([honest, evil]))}
    w = jnp.asarray([1, 1, 1, 1, 1, 1, 100, 100], jnp.float32)
    sel = np.asarray(krum_weights(tree, w, f=2))
    assert sel[:6].sum() == pytest.approx(1.0)
    assert sel[6:].sum() == 0.0
    # multi-krum m=3 averages three honest updates
    sel3 = np.asarray(krum_weights(tree, w, f=2, m=3))
    assert (sel3 > 0).sum() == 3 and sel3[6:].sum() == 0.0
    got = np.asarray(krum(tree, w, f=2)["w"])
    assert np.abs(got - honest.mean(0)).max() < 1.0


def test_geometric_median_resists_outliers(rng):
    honest = rng.randn(1, 8).astype(np.float32) + \
        0.05 * rng.randn(5, 8).astype(np.float32)
    evil = 100.0 * np.ones((2, 8), np.float32)
    tree = {"w": jnp.asarray(np.concatenate([honest, evil]))}
    w = jnp.ones(7)
    gm = np.asarray(geometric_median(tree, w)["w"])
    mean = np.asarray(tree["w"]).mean(0)
    honest_center = honest.mean(0)
    assert np.abs(gm - honest_center).max() < 2.0          # stays home
    assert np.abs(mean - honest_center).max() > 20.0       # mean hijacked


@pytest.mark.parametrize("method", METHODS)
def test_defended_round_survives_poison(method, rng):
    """Full federated round via the cohort engine: 2 of 8 clients upload
    garbage (via a poisoned local dataset scale); every Byzantine rule
    must keep the global update bounded while plain FedAvg blows up."""
    import flax.linen as nn
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    class Linear(nn.Module):
        # plain Dense: the zoo's LogisticRegression keeps the reference's
        # sigmoid-on-logits quirk, which SATURATES under exploding inputs
        # and would neuter this data-poisoning attack
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(3)(x.reshape((x.shape[0], -1)))

    xs = [rng.randn(8, 6).astype(np.float32) for _ in range(8)]
    ys = [rng.randint(0, 3, 8).astype(np.int32) for _ in range(8)]
    for i in (6, 7):  # poisoned silos: exploding features
        xs[i] = xs[i] * 1e4
    cohort = {k: jnp.asarray(v)
              for k, v in stack_client_data(xs, ys, batch_size=4).items()}
    wl = ClassificationWorkload(Linear(), num_classes=3,
                                grad_clip_norm=None)
    local = make_local_trainer(wl, make_client_optimizer("sgd", 0.5),
                               epochs=1)
    params = wl.init(jax.random.key(0), jax.tree.map(
        lambda v: v[0, 0], {k: cohort[k] for k in ("x", "y", "mask")}))

    plain, _ = make_cohort_step(local)(params, cohort, jax.random.key(1))
    agg = make_byzantine_aggregate(method, trim_frac=0.25, byz_f=2,
                                   krum_m=3)
    defended, _ = make_cohort_step(local, aggregate=agg)(
        params, cohort, jax.random.key(1))

    norm = lambda t: float(jnp.sqrt(sum(
        jnp.sum((a - b) ** 2) for a, b in
        zip(jax.tree.leaves(t), jax.tree.leaves(params)))))
    assert norm(plain) > 50.0, "attack no longer effective; fix the test"
    assert norm(defended) < 10.0, (method, norm(defended))


def test_make_byzantine_aggregate_validates_params():
    with pytest.raises(ValueError, match="unknown byzantine"):
        make_byzantine_aggregate("median-ish")
    with pytest.raises(ValueError, match="trim_frac"):
        make_byzantine_aggregate("trimmed_mean", trim_frac=0.5)
    with pytest.raises(ValueError, match="krum_m"):
        make_byzantine_aggregate("multi_krum", krum_m=0)
    with pytest.raises(ValueError, match="byz_f"):
        make_byzantine_aggregate("krum", byz_f=-1)


def test_cli_byzantine_defense():
    from fedml_tpu.experiments.main import main
    out = main(["--algo", "fedavg_robust", "--defense", "krum",
                "--byz_f", "1", "--model", "lr", "--dataset", "mnist",
                "--client_num_in_total", "8", "--client_num_per_round", "4",
                "--comm_round", "2", "--batch_size", "8",
                "--log_stdout", "false"])
    assert np.isfinite(out["train_loss"])


def test_byzantine_rejects_mesh_and_pallas():
    from fedml_tpu.algorithms.fedavg_robust import (FedAvgRobust,
                                                    FedAvgRobustConfig)
    from fedml_tpu.data.registry import load_data
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload
    from fedml_tpu.parallel.mesh import make_mesh

    data = load_data("mnist", None, client_num=8, batch_size=8)
    wl = ClassificationWorkload(LogisticRegression(784, 10), num_classes=10)
    with pytest.raises(ValueError, match="full cohort"):
        FedAvgRobust(wl, data,
                     FedAvgRobustConfig(defense="krum",
                                        client_num_per_round=8),
                     mesh=make_mesh(client_axis=8))
    with pytest.raises(ValueError, match="own aggregate"):
        FedAvgRobust(wl, data, FedAvgRobustConfig(
            defense="trimmed_mean", defense_backend="pallas"))
    # multi-Krum selection bound: m <= n - f - 2, else the "defense"
    # degenerates to a plain mean over everyone including attackers
    with pytest.raises(ValueError, match="m <= n - f - 2"):
        FedAvgRobust(wl, data, FedAvgRobustConfig(
            defense="multi_krum", client_num_per_round=8, byz_f=2,
            krum_m=8))


@pytest.mark.parametrize("method", METHODS)
def test_every_method_is_padding_invariant(method, rng):
    """The property every rule must hold for the static-cohort defended
    round (robust/defense.py): weight-0 slots NEVER change the result,
    whatever garbage they hold — padded and unpadded cohorts agree."""
    agg = make_byzantine_aggregate(method, trim_frac=0.2, byz_f=1, krum_m=2)
    for trial in range(3):
        n, pad = 5, int(rng.randint(1, 4))
        tree = {"a": jnp.asarray(rng.randn(n, 3, 2).astype(np.float32)),
                "b": jnp.asarray(rng.randn(n, 4).astype(np.float32))}
        w = jnp.asarray(rng.rand(n).astype(np.float32) + 0.5)
        base = agg(tree, w)
        # padded slots carry large GARBAGE (not zeros) with weight 0
        garbage = jax.tree.map(
            lambda x: jnp.concatenate(
                [x, jnp.asarray(1e4 * rng.randn(
                    pad, *x.shape[1:]).astype(np.float32))]), tree)
        got = agg(garbage, jnp.concatenate([w, jnp.zeros(pad)]))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
            base, got)


def test_geometric_median_survives_all_zero_weights(rng):
    """The all-weights-zero cohort (every silo rejected/quarantined) used
    to divide by a zero weight sum and NaN out; now it falls back to the
    unweighted geometric median — finite and deterministic."""
    tree = {"w": jnp.asarray(rng.randn(5, 6).astype(np.float32))}
    out = geometric_median(tree, jnp.zeros(5))
    assert np.isfinite(np.asarray(out["w"])).all()
    # the guard must not perturb live cohorts: a single live client's
    # geometric median is that client's update
    solo = np.asarray(geometric_median(
        tree, jnp.asarray([0.0, 0.0, 0.0, 0.0, 1.0]))["w"])
    np.testing.assert_allclose(solo, np.asarray(tree["w"])[4], atol=1e-3)
