"""TPU-native secure aggregation: pairwise masking in Z_2^32 under jit.

The reference's TurboAggregate exchanges Lagrange-coded shares through MPI
messages between worker processes (TA_decentralized_worker.py); the finite-
field kernel lives in `fedml_tpu.secure.field` for the cross-silo path.  But
*on-pod*, the TPU-native construction is additive pairwise masking in the
ring Z_2^32 (the practical-SecAgg construction, Bonawitz et al. 2017):

- uint32 wraparound IS the ring arithmetic — no explicit mod anywhere;
- each ordered client pair (i < j) derives a shared mask from a common seed
  (key agreement on the host edge; `jax.random.fold_in` of a cohort key in
  simulation); client i adds it, client j subtracts it;
- the masked cohort sum — a plain `lax.psum`/`sum` in the jit round program
  — cancels every mask exactly, bit for bit.  The server learns only the
  sum, each individual update stays masked.

Quantization float→fixed-point mirrors the role of the reference's
``transform_tensor_to_finite`` step (TA model quantization) with an explicit
clip range and scale.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp

Pytree = Any

log = logging.getLogger(__name__)

# the uint32 ring holds signed fixed-point values in ±2^31; the COHORT SUM
# must stay inside that, not just each update
RING_CAPACITY = 2.0**31


def ring_budget_scale(num_clients: int, clip: float) -> float:
    """Largest power-of-two fixed-point scale whose worst-case cohort sum
    cannot wrap the uint32 ring: ``num_clients * clip * scale < 2^31``.

    Each masked contribution is clipped to ±clip BEFORE quantization, so
    N clients all saturating the clip sum to N*clip — the wrap boundary
    the per-update quantize range used to ignore (every aggregate beyond
    it silently flipped sign).  Deriving the scale from the cohort size
    makes the budget structural instead of a caller obligation."""
    if num_clients < 1:
        raise ValueError(f"num_clients must be >= 1, got {num_clients}")
    if clip <= 0:
        raise ValueError(f"clip must be > 0, got {clip}")
    import math
    scale = 2.0 ** math.floor(math.log2(RING_CAPACITY / (num_clients * clip)))
    while num_clients * clip * scale >= RING_CAPACITY:  # boundary guard
        scale /= 2.0
    if scale < 1.0:
        raise ValueError(
            f"no usable fixed-point scale: {num_clients} clients at "
            f"clip={clip} already exceed the uint32 ring capacity")
    return scale


def validate_ring_budget(num_clients: int, clip: float,
                         scale: float) -> None:
    """Fail loudly when a cohort sum can wrap the ring: the satellite bug
    (ISSUE 11) — quantize's fixed-point range is per-update, but N
    clipped updates sum to N*clip, and a wrapped sum dequantizes to a
    silently-corrupted aggregate (sign-flipped, not noisy)."""
    if num_clients * clip * scale >= RING_CAPACITY:
        raise ValueError(
            f"uint32 ring budget exceeded: num_clients={num_clients} * "
            f"clip={clip} * scale={scale} = "
            f"{num_clients * clip * scale:.3g} >= 2^31 — the cohort sum "
            f"can wrap and corrupt the aggregate.  Lower scale/clip or "
            f"pass scale=None to auto-derive it from the cohort size "
            f"(ring_budget_scale gives {ring_budget_scale(num_clients, clip)})")


def quantize(tree: Pytree, scale: float = 2.0**16,
             clip: float = 2.0**14) -> Pytree:
    """Fixed-point encode float pytree into uint32 ring elements.

    Values are clipped to ±clip then scaled; negatives wrap mod 2^32 (two's
    complement), so additions in uint32 implement signed fixed-point sums as
    long as the true sum stays within ±2^31/scale."""
    def enc(x):
        q = jnp.round(jnp.clip(x, -clip, clip) * scale).astype(jnp.int32)
        return q.astype(jnp.uint32)
    return jax.tree.map(enc, tree)


def dequantize(tree: Pytree, scale: float = 2.0**16) -> Pytree:
    def dec(q):
        return q.astype(jnp.uint32).astype(jnp.int32).astype(jnp.float32) / scale
    return jax.tree.map(dec, tree)


def _pair_key(base_key: jax.Array, i, j) -> jax.Array:
    """Shared key for ordered pair (min,max) — both ends derive the same."""
    lo = jnp.minimum(i, j)
    hi = jnp.maximum(i, j)
    return jax.random.fold_in(jax.random.fold_in(base_key, lo), hi)


def pairwise_masks(base_key: jax.Array, client_idx, num_clients: int,
                   tree: Pytree) -> Pytree:
    """Net mask for one client: +PRG(s_ij) for j>i, −PRG(s_ij) for j<i.

    Σ_i mask_i = 0 in uint32 exactly.  Shapes/dtypes follow ``tree``."""
    def mask_leaf(x):
        def one_pair(j, acc):
            key = _pair_key(base_key, client_idx, j)
            bits = jax.random.bits(key, x.shape, jnp.uint32)
            sign = jnp.where(j > client_idx, jnp.uint32(1),
                             jnp.uint32(0xFFFFFFFF))  # -1 in the ring
            use = (j != client_idx).astype(jnp.uint32)
            return acc + bits * sign * use
        # the zero init inherits client_idx's varying-axis type so the scan
        # carry matches under shard_map (client_idx is axis_index there)
        zero = jnp.zeros(x.shape, jnp.uint32) + \
            jnp.asarray(client_idx).astype(jnp.uint32) * jnp.uint32(0)
        return jax.lax.fori_loop(0, num_clients, one_pair, zero)
    return jax.tree.map(mask_leaf, tree)


class SecureCohortAggregator:
    """Drop-in secure replacement for plain weighted cohort aggregation.

    ``mask_update(update, n_i, client_idx)`` runs on/for each client:
    quantize(update * n_i) + pairwise mask.  ``unmask_sum(masked_sum,
    total_n)`` runs on the server: dequantize / Σn.  Works identically
    whether the sum is a stacked ``sum(axis=0)`` (single chip) or a
    ``lax.psum`` over the cohort mesh axis — masks cancel in either."""

    def __init__(self, num_clients: int, scale: Optional[float] = None,
                 clip: float = 2.0**14, backend: str = "xla"):
        """``backend="pallas"`` fuses quantize+mask into one VMEM pass per
        block with an in-kernel counter PRG (fedml_tpu.secure.pallas_mask)
        — O(D) HBM traffic instead of O(N·D).  The two backends use
        different PRG streams; every client of a cohort must use the same
        one or masks won't cancel.  Note the pallas stream is a 64-bit-keyed
        hash PRG (architecture demo), not the threefry PRF of the XLA path —
        see the pallas_mask module docstring before using it for real
        privacy.

        ``scale=None`` (default) derives the fixed-point scale from the
        cohort size so the worst-case cohort sum (every client's clipped
        contribution at ±clip) cannot wrap the uint32 ring; an explicit
        scale that CAN wrap is rejected at construction instead of
        corrupting an aggregate mid-federation (`validate_ring_budget`)."""
        if backend not in ("xla", "pallas"):
            raise ValueError(f"unknown secagg backend {backend!r}")
        if scale is None:
            scale = ring_budget_scale(num_clients, clip)
            log.debug("secagg: auto-derived scale %g for %d clients at "
                      "clip %g", scale, num_clients, clip)
        else:
            validate_ring_budget(num_clients, clip, scale)
        self.num_clients = num_clients
        self.scale = scale
        self.clip = clip
        self.backend = backend

    def mask_update(self, update: Pytree, weight, client_idx,
                    round_key: jax.Array) -> Pytree:
        """Quantize(update * weight) + pairwise mask.

        Ring-budget contract: the TRUE cohort sum of weighted values must
        stay within ±2^31/scale or the uint32 sum wraps and dequantizes
        wrong.  Pass NORMALIZED weights (Σweight = 1, as
        ``aggregate_stacked`` does) and the sum is the weighted mean with
        magnitude ≤ clip — safe for any cohort size.  Raw sample counts as
        weights put the budget on the caller (server divides by Σn)."""
        if self.backend == "pallas":
            from fedml_tpu.secure.pallas_mask import fused_quantize_mask
            return fused_quantize_mask(
                update, weight, client_idx, round_key, self.num_clients,
                self.scale, self.clip,
                interpret=jax.default_backend() != "tpu")
        weighted = jax.tree.map(
            lambda x: x * jnp.asarray(weight, x.dtype), update)
        q = quantize(weighted, self.scale, self.clip)
        masks = pairwise_masks(round_key, jnp.asarray(client_idx),
                               self.num_clients, q)
        return jax.tree.map(jnp.add, q, masks)

    def unmask_sum(self, masked_sum: Pytree, total_weight=1.0) -> Pytree:
        deq = dequantize(masked_sum, self.scale)
        return jax.tree.map(
            lambda x: x / jnp.maximum(
                jnp.asarray(total_weight, jnp.float32), 1e-12), deq)

    def aggregate_stacked(self, updates: Pytree, num_samples: jax.Array,
                          round_key: jax.Array) -> Pytree:
        """Single-chip simulation path: updates' leaves are [C, ...].

        Weights are normalized BEFORE masking so each client contributes
        w_i/Σw · update — the ring sum is the weighted mean itself, bounded
        by max|update| ≤ clip, which cannot wrap uint32 regardless of
        cohort size or sample counts."""
        total = jnp.maximum(jnp.sum(num_samples), 1e-12)
        w_norm = num_samples / total
        def per_client(c):
            upd = jax.tree.map(lambda x: x[c], updates)
            return self.mask_update(upd, w_norm[c], c, round_key)
        masked = jax.vmap(per_client)(jnp.arange(self.num_clients))
        summed = jax.tree.map(lambda x: jnp.sum(x, axis=0, dtype=jnp.uint32),
                              masked)
        return self.unmask_sum(summed, 1.0)
