"""Round critical-path observatory: overlap accounting over the server
receive path, reduced to one ``critical_path`` record per perf.jsonl
round line (ISSUE 17; the measurement layer ROADMAP item 4's ingest
offload will be benched on).

The flight recorder already measures *how long* each receive-path phase
ran (decode, admission, fold, journal, unmask, ...) but not *when* —
so a round where fold runs fully overlapped with the network looks
identical to one where the host serializes fold after the last upload.
`RoundCriticalPath` keeps the actual ``[t0, t1)`` interval of every
phase sample plus every upload-arrival timestamp, then sweeps the round
once at close:

* each elementary segment of the round's wall clock is attributed to
  exactly ONE constraint, so the attribution *partitions* the round —
  ``sum(attribution) == round_s`` by construction (the ``coverage``
  field states it; the ingest bench gates ``>= 0.95`` on every arm);
* a segment where phase work was active goes to the busiest-priority
  active bucket (fold > decode > admission > network);
* an idle segment is classified by where it falls against the round's
  arrival timeline: before the first upload it is ``network`` (the
  broadcast + remote train + upload are in flight — from the server's
  chair the wire is the constraint), between first and last arrival it
  is ``straggler`` (the quorum is trickling in), and after the last
  arrival it is ``barrier_wait`` (share reveals, barrier close);
* known compile wall time (the device observatory's per-round compile
  ledger) is carved OUT of the work buckets into ``compile`` without
  changing the total — compiles happen *inside* fold/decode work, so
  re-labeling keeps the partition a partition.

The ``binding`` constraint is simply the bucket with the largest share.
``fold_overlap_ratio`` is the fraction of fold busy time that ran while
uploads were still arriving — exactly the "aggregation hidden behind
the network" number the Smart-NIC analog (arXiv 2307.06561) optimizes;
1.0 means the host never stalled the wire to fold.

Cost contract: this module is armed by `PerfRecorder` only — no
recorder, no accumulator, and instrumented paths pay the one
``perf is None`` branch they always paid.  Stdlib only, like all of
``obs/``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from fedml_tpu.obs import telemetry

#: the closed attribution vocabulary — every second of a round lands in
#: exactly one of these (trend.validate_ledger rejects records naming
#: anything else, so dashboards never chase an invented constraint)
CONSTRAINTS = ("network", "decode", "admission", "fold", "barrier_wait",
               "straggler", "compile")

# perf-phase name -> constraint bucket.  Open vocabulary on the phase
# side (unknown phases default to "fold": host-side round work); the
# idle buckets (straggler / barrier_wait) are never mapped — they are
# derived from the arrival timeline, and "straggler_wait" (an idle
# *measurement*, not work) is excluded so it cannot double-count.
PHASE_BUCKETS: Dict[str, str] = {
    "decode": "decode",
    "broadcast_serialize": "network",
    "admission": "admission",
    "health": "admission",
    "fold": "fold", "staging": "fold", "journal": "fold",
    "aggregate": "fold", "defended_aggregate": "fold",
    "shard_finalize": "fold",
    "unmask": "fold", "mask_agreement": "fold",
    "checkpoint": "fold", "publish": "fold",
    # in the mega-cohort regime the wave *produces* uploads — it is the
    # wire analog (broadcast + local train + upload compressed into one
    # device dispatch), so it buckets as network: fold_overlap_ratio
    # then measures exactly "folds hidden behind wave production", the
    # same question the cross-silo arms ask of the real wire
    "wave": "network",
    "compile": "compile",
}
_EXCLUDED_PHASES = frozenset({"straggler_wait"})

# when several buckets are active in one instant (receive threads
# overlap), the segment goes to the first active bucket in this order —
# the one most likely to be the actual bottleneck
_WORK_PRIORITY = ("fold", "decode", "admission", "compile", "network")


def phase_bucket(name: str) -> Optional[str]:
    """Constraint bucket for a perf-phase name (None = excluded)."""
    if name in _EXCLUDED_PHASES:
        return None
    return PHASE_BUCKETS.get(name, "fold")


def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Merge overlapping intervals; returns disjoint sorted intervals."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [intervals[0]]
    for lo, hi in intervals[1:]:
        if lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _clip(intervals, lo: float, hi: float) -> List[Tuple[float, float]]:
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if min(b, hi) > max(a, lo)]


def _overlap(intervals, lo: float, hi: float) -> float:
    """Total length of ``intervals ∩ [lo, hi)`` (intervals disjoint)."""
    return sum(b - a for a, b in _clip(intervals, lo, hi))


class RoundCriticalPath:
    """Per-round interval accumulator + the closing attribution sweep.

    Receive threads call ``note(phase, seconds)`` (the sample ENDED now;
    its interval is ``[now - seconds, now)`` — the measure-then-note
    idiom every `PerfRecorder.add_phase` caller already follows) and
    ``note_arrival()`` once per upload landing off the wire.  The owner
    calls ``finalize(duration)`` once at round close."""

    __slots__ = ("_t0", "_clock", "_lock", "_samples", "_arrivals")

    def __init__(self, t0: Optional[float] = None, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock() if t0 is None else t0
        self._lock = threading.Lock()
        self._samples: Dict[str, List[Tuple[float, float]]] = {}
        self._arrivals: List[float] = []

    def note(self, phase: str, seconds: float,
             t1: Optional[float] = None) -> None:
        """Record a phase sample that ran for ``seconds`` ending at
        ``t1`` (now by default)."""
        bucket = phase_bucket(phase)
        if bucket is None or seconds <= 0.0:
            return
        if t1 is None:
            t1 = self._clock()
        with self._lock:
            self._samples.setdefault(bucket, []).append((t1 - seconds, t1))

    def note_arrival(self, t: Optional[float] = None) -> None:
        """Record one upload landing off the wire (the arrival timeline
        classifies the round's idle time: network → straggler →
        barrier_wait)."""
        if t is None:
            t = self._clock()
        with self._lock:
            self._arrivals.append(t)

    # -- the closing sweep ---------------------------------------------------
    def finalize(self, duration: Optional[float] = None,
                 compile_s: float = 0.0) -> dict:
        """Reduce the round into its ``critical_path`` record.

        ``duration`` pins the round's wall clock (the recorder passes
        its own ``round_s`` so the partition target and the ledger's
        headline number are the same measurement); ``compile_s`` is
        known compile wall time to carve out of the work buckets."""
        t0 = self._t0
        with self._lock:
            samples = {k: list(v) for k, v in self._samples.items()}
            arrivals = sorted(self._arrivals)
        t1 = t0 + duration if duration is not None else self._clock()
        duration = max(t1 - t0, 0.0)
        attribution = {c: 0.0 for c in CONSTRAINTS}
        busy = {b: _union(_clip(iv, t0, t1)) for b, iv in samples.items()}
        if duration > 0.0:
            # sweep every elementary segment between interval boundaries
            bounds = {t0, t1}
            for iv in busy.values():
                for a, b in iv:
                    bounds.add(a)
                    bounds.add(b)
            first = arrivals[0] if arrivals else None
            last = arrivals[-1] if arrivals else None
            for t in arrivals:
                if t0 < t < t1:
                    bounds.add(t)
            edges = sorted(b for b in bounds if t0 <= b <= t1)
            for lo, hi in zip(edges, edges[1:]):
                if hi <= lo:
                    continue
                mid = (lo + hi) / 2.0
                seg = hi - lo
                active = next(
                    (b for b in _WORK_PRIORITY
                     if any(a <= mid < e for a, e in busy.get(b, ()))),
                    None)
                if active is not None:
                    attribution[active] += seg
                elif first is None or mid < first:
                    attribution["network"] += seg
                elif mid < last:
                    attribution["straggler"] += seg
                else:
                    attribution["barrier_wait"] += seg
        # carve known compile time out of the work buckets (compiles run
        # INSIDE fold/decode work); the total is untouched
        carve = min(compile_s, sum(attribution[b]
                                   for b in ("fold", "decode", "network")))
        if carve > 0.0:
            for b in ("fold", "decode", "network"):
                take = min(carve, attribution[b])
                attribution[b] -= take
                attribution["compile"] += take
                carve -= take
                if carve <= 0.0:
                    break
        total = sum(attribution.values())
        fold_busy = sum(b - a for a, b in busy.get("fold", ()))
        overlap = (_overlap(busy.get("fold", ()), t0, arrivals[-1])
                   / fold_busy if fold_busy > 0.0 and arrivals else 0.0)
        binding = max(CONSTRAINTS, key=lambda c: attribution[c])
        rec = {
            "binding": binding,
            "attribution": {c: round(v, 6)
                            for c, v in attribution.items() if v > 0.0},
            "coverage": round(total / duration, 6) if duration > 0.0 else 1.0,
            "round_s": round(duration, 6),
            "uploads": len(arrivals),
            "fold_overlap_ratio": round(overlap, 6),
        }
        if arrivals:
            # "pure network time": t0 → last arrival.  The ingest bench's
            # wall-clock gate (round_s <= 1.15 x network time) reads this
            # — a pipelined round ends almost as soon as the wire does.
            rec["last_arrival_s"] = round(max(arrivals[-1] - t0, 0.0), 6)
        return rec


class IngestGauges:
    """The ``fedml_ingest_*`` family: per-round wire throughput, the
    fold-overlap ratio, per-constraint utilization, the upload counter,
    and — when the `--ingest_pipeline` path is on — the queue-depth
    gauge plus the enqueue/overflow counters (overflow labelled per
    shard so a hot shard's backpressure is visible on its own series).
    Handles are cached at construction (the registry may be the Null
    one — then every export is a no-op attribute call); the per-shard
    overflow counters are lazy because the shard count is a runtime
    fact, not a construction-time one."""

    __slots__ = ("_reg", "_g_bps", "_g_overlap", "_g_util", "_c_uploads",
                 "_g_depth", "_c_enqueued", "_c_overflow")

    def __init__(self, registry=None):
        reg = registry if registry is not None else telemetry.get_registry()
        self._reg = reg
        self._g_bps = reg.gauge("fedml_ingest_bytes_per_second_value")
        self._g_overlap = reg.gauge("fedml_ingest_fold_overlap_ratio")
        self._g_util = {
            c: reg.gauge("fedml_ingest_phase_utilization_ratio",
                         constraint=c)
            for c in CONSTRAINTS}
        self._c_uploads = reg.counter("fedml_ingest_uploads_total")
        self._g_depth = reg.gauge("fedml_ingest_queue_depth_value")
        self._c_enqueued = reg.counter("fedml_ingest_enqueued_total")
        self._c_overflow: Dict[int, object] = {}

    # -- pipeline queue instrumentation --------------------------------------
    def note_enqueued(self, depth: int) -> None:
        """One frame entered an ingest queue; ``depth`` is that queue's
        occupancy after the put."""
        self._c_enqueued.inc()
        self._g_depth.set(depth)

    def note_depth(self, depth: int) -> None:
        """Queue occupancy after a fold worker consumed a frame."""
        self._g_depth.set(depth)

    def note_overflow(self, shard: int) -> None:
        """One frame bounced off a full queue (it is dead-lettered by
        the pipeline, attributed as a network fault — never a strike)."""
        c = self._c_overflow.get(shard)
        if c is None:
            c = self._reg.counter("fedml_ingest_overflow_total",
                                  shard=str(shard))
            self._c_overflow[shard] = c
        c.inc()

    def export(self, record: dict, wire_bytes_in: float) -> None:
        round_s = record.get("round_s") or 0.0
        if round_s > 0.0:
            self._g_bps.set(wire_bytes_in / round_s)
            attribution = record.get("attribution") or {}
            for c, g in self._g_util.items():
                g.set(attribution.get(c, 0.0) / round_s)
        self._g_overlap.set(record.get("fold_overlap_ratio", 0.0))
        uploads = record.get("uploads", 0)
        if uploads:
            self._c_uploads.inc(uploads)


def validate_record(rec, path: str = "critical_path") -> List[str]:
    """Shape-check one ``critical_path`` record (trend gate + tests
    share this): returns problem strings, empty when valid."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return [f"{path}: not a dict"]
    binding = rec.get("binding")
    if binding not in CONSTRAINTS:
        problems.append(f"{path}: binding {binding!r} not in {CONSTRAINTS}")
    attribution = rec.get("attribution")
    if not isinstance(attribution, dict):
        problems.append(f"{path}: no attribution dict")
        attribution = {}
    for k, v in attribution.items():
        if k not in CONSTRAINTS:
            problems.append(f"{path}: unknown constraint {k!r}")
        if not isinstance(v, (int, float)) or v < 0:
            problems.append(f"{path}: attribution[{k!r}] = {v!r}")
    round_s = rec.get("round_s")
    if not isinstance(round_s, (int, float)) or round_s < 0:
        problems.append(f"{path}: round_s = {round_s!r}")
    coverage = rec.get("coverage")
    if not isinstance(coverage, (int, float)):
        problems.append(f"{path}: coverage = {coverage!r}")
    elif isinstance(round_s, (int, float)) and round_s > 0:
        total = sum(v for v in attribution.values()
                    if isinstance(v, (int, float)))
        if abs(total / round_s - coverage) > 0.01:
            problems.append(
                f"{path}: coverage {coverage} disagrees with "
                f"attribution sum {total:.6f} / round_s {round_s:.6f}")
    return problems
