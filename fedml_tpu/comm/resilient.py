"""Retry / backoff / dead-letter decorator for wire transports.

The reference's transports send exactly once and pray: a gRPC
``sendMessage`` that raises UNAVAILABLE, or an MQTT publish on a dead
socket, kills the federation (grpc_comm_manager.py:70-76 has no retry;
mqtt_comm_manager.py reconnects never).  `ResilientTransport` wraps any
`Transport` with the production posture:

* **bounded in-flight queue** — ``send_message`` enqueues and returns;
  a single daemon sender thread drains in FIFO order, so message order
  per sender is preserved and a slow wire never blocks the event loop.
  A full queue dead-letters the message instead of blocking (back
  pressure surfaces as an explicit signal, not a hang).
* **retries with exponential backoff + decorrelated jitter** — each
  attempt that raises is retried after ``base_backoff_s * mult^k``
  seconds, multiplied by a seeded jitter in ``[1-jitter, 1+jitter]``,
  capped at ``max_backoff_s``.
* **per-send deadline** — ``send_deadline_s`` bounds the TOTAL time
  (all attempts + backoffs) spent on one message.
* **reconnection** — between attempts the wrapper calls the inner
  transport's ``reconnect()`` (if it has one); gRPC drops its cached
  channel so the next attempt dials fresh, MQTT re-runs the
  CONNECT/SUBSCRIBE handshake.
* **dead-letter callback** — ``on_dead_letter(msg, exc)`` fires when a
  message exhausts its attempts/deadline or the queue is full; the
  default logs and drops (an FL upload is retried implicitly by the
  next round — losing one is degradation, not corruption).

Compose order: ``ResilientTransport(ChaosTransport(inner))`` retries
THROUGH injected faults (chaos drops are silent, so only transport
errors trigger retry); ``ChaosTransport(ResilientTransport(inner))``
injects faults the retry layer never sees.  Tests use the first form
against a flaky inner transport to prove retry recovers what one-shot
sends lose.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

_STOP = object()


class SendDeadlineExceeded(RuntimeError):
    """Raised (into the dead-letter path) when a send's total retry
    budget is exhausted."""


class SendQueueFull(RuntimeError):
    """Raised (into the dead-letter path) when the bounded in-flight
    queue rejects a message."""


@dataclasses.dataclass
class RetryPolicy:
    """Backoff schedule for one message."""
    max_attempts: int = 5
    base_backoff_s: float = 0.05
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter_frac: float = 0.2            # each backoff scaled by U[1-j, 1+j]
    send_deadline_s: Optional[float] = 30.0  # total budget per message

    def backoff(self, attempt: int, rng) -> float:
        raw = min(self.base_backoff_s * self.backoff_multiplier ** attempt,
                  self.max_backoff_s)
        if self.jitter_frac <= 0:
            return raw
        lo, hi = 1.0 - self.jitter_frac, 1.0 + self.jitter_frac
        return raw * float(rng.uniform(lo, hi))


class ResilientTransport(Transport):
    """Decorate ``inner`` with queued, retried, dead-lettered sends."""

    def __init__(self, inner: Transport, policy: Optional[RetryPolicy] = None,
                 max_in_flight: int = 256,
                 on_dead_letter: Optional[
                     Callable[[Message, Exception], None]] = None,
                 seed: int = 0,
                 fault_feed: Optional[Callable[[str, Message], None]] = None):
        # no super().__init__(): observers belong to the inner transport
        self.inner = inner
        self.policy = policy if policy is not None else RetryPolicy()
        self.on_dead_letter = on_dead_letter
        # fault_feed(reason, msg): the reliability tracker's attribution
        # feed (robust/degrade) — ALWAYS called on a dead letter, in
        # addition to on_dead_letter/log, so dead letters classify as
        # network faults (partition evidence, never a trust strike) even
        # when a caller installed its own drop handler
        self.fault_feed = fault_feed
        self._rng = np.random.RandomState(seed)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_in_flight)
        self._stopped = False
        self.sent_ok = 0
        self.retries = 0
        self.dead_letters = 0
        # telemetry mirrors of the attribute counters above (null no-ops
        # when telemetry is disabled); _m_retry increments exactly once
        # per retried attempt, in lockstep with self.retries
        reg = telemetry.get_registry()
        self._m_ok = reg.counter("fedml_comm_send_ok_total")
        self._m_retry = reg.counter("fedml_comm_send_retries_total")
        # fedml_comm_dead_letter_total{reason} registers LAZILY on the
        # first dead letter of each reason (the PR 6 no-fabricated-0
        # contract: a healthy run exports no dead-letter series at all)
        self._m_dead_by_reason: dict = {}
        self._sender = threading.Thread(target=self._drain, daemon=True,
                                        name="resilient-sender")
        self._sender.start()

    # -- observer passthrough ------------------------------------------------
    def add_observer(self, observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer) -> None:
        self.inner.remove_observer(observer)

    # -- send path -----------------------------------------------------------
    # send_many (inherited): each fan-out sibling is enqueued as its own
    # message, so per-link retry/backoff/dead-letter semantics are exactly
    # the single-send ones — one silo's flaky channel retries alone while
    # its siblings proceed.  The shared payload rides every sibling as an
    # already-encoded block, so retries never re-serialize the model bytes.

    def send_message(self, msg: Message) -> None:
        if self._stopped:
            # the sender thread is gone; an enqueue would vanish silently —
            # surface it like every other terminal send failure
            self._dead_letter(msg, RuntimeError(
                f"transport stopped; dropping {msg!r}"))
            return
        try:
            self._queue.put_nowait(msg)
        except queue.Full:
            self._dead_letter(msg, SendQueueFull(
                f"in-flight queue full ({self._queue.maxsize}); "
                f"dropping {msg!r}"))

    @staticmethod
    def _dead_letter_reason(exc: Exception) -> str:
        """The dead letter's labeled reason — a closed, low-cardinality
        set (each reason is one labeled series)."""
        if isinstance(exc, SendDeadlineExceeded):
            return "deadline"
        if isinstance(exc, SendQueueFull):
            return "queue_full"
        if isinstance(exc, RuntimeError) and "transport stopped" in str(exc):
            return "stopped"
        return "send_failed"

    def _dead_letter(self, msg: Message, exc: Exception) -> None:
        self.dead_letters += 1
        reason = self._dead_letter_reason(exc)
        c = self._m_dead_by_reason.get(reason)
        if c is None:
            c = telemetry.get_registry().counter(
                "fedml_comm_dead_letter_total", reason=reason)
            self._m_dead_by_reason[reason] = c
        c.inc()
        if self.fault_feed is not None:
            try:
                self.fault_feed(reason, msg)
            except Exception:  # noqa: BLE001 — attribution must not kill
                log.exception("dead-letter fault_feed raised")
        if self.on_dead_letter is not None:
            self.on_dead_letter(msg, exc)
        else:
            log.error("dead-lettering %r: %s", msg, exc)

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            self._send_with_retries(item)

    def _send_with_retries(self, msg: Message) -> None:
        deadline = (None if self.policy.send_deadline_s is None
                    else time.monotonic() + self.policy.send_deadline_s)
        last_exc: Optional[Exception] = None
        deadline_hit = False
        for attempt in range(self.policy.max_attempts):
            if self._stopped and attempt > 0:
                # graceful drain: a message already queued at stop() still
                # gets its FIRST attempt (a FINISH broadcast precedes the
                # server's own stop), but no backoff-retry loop may outlive
                # the transport
                return
            try:
                self.inner.send_message(msg)
                self.sent_ok += 1
                self._m_ok.inc()
                return
            except Exception as exc:  # noqa: BLE001 — any wire error retries
                if self._stopped:
                    return  # shutdown drain: one attempt, no backoff
                last_exc = exc
                if attempt + 1 >= self.policy.max_attempts:
                    break  # terminal attempt: no backoff/reconnect to pay
                pause = self.policy.backoff(attempt, self._rng)
                if deadline is not None and \
                        time.monotonic() + pause > deadline:
                    deadline_hit = True  # budget gone before the next try
                    break
                log.warning("send attempt %d/%d failed (%s); retrying in "
                            "%.3fs", attempt + 1, self.policy.max_attempts,
                            exc, pause)
                self.retries += 1
                self._m_retry.inc()
                time.sleep(pause)
                reconnect = getattr(self.inner, "reconnect", None)
                if reconnect is not None:
                    try:
                        reconnect()
                    except Exception as rexc:  # noqa: BLE001
                        log.warning("reconnect failed: %s", rexc)
        if deadline_hit and last_exc is not None:
            last_exc = SendDeadlineExceeded(
                f"{self.policy.send_deadline_s}s send budget exhausted "
                f"(last error: {last_exc})")
        self._dead_letter(msg, last_exc if last_exc is not None
                          else RuntimeError("send failed"))

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> None:
        self.inner.run()

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._queue.put(_STOP)
        self._sender.join(timeout=5)
        self.inner.stop()
