"""Hierarchical FL — two-tier client -> group (edge) -> global averaging.

Parity with fedml_api/standalone/hierarchical_fl/:
* random client->group assignment (trainer.py:12-18, ``group_method ==
  'random'``);
* per global round: the plain seeded sampler picks clients, which are routed
  to their groups (trainer.py:32-41);
* each group runs ``group_comm_round`` FedAvg rounds among its sampled
  clients (group.py:24-46), then groups average weighted by their sampled
  clients' sample counts (trainer.py:56-62).

TPU mapping (SURVEY.md §2.5): group tier = ICI within a pod slice, global
tier = DCN across slices.  Single-chip, the WHOLE two-tier round is one jit:
group cohorts are padded to one static [G, M, ...] bucket, each group's
``group_comm_round`` FedAvg rounds run as a `lax.scan`, and the G groups run
simultaneously under `vmap` — groups are a batch axis, not a Python loop.
On a mesh the groups iterate host-side over the client-sharded cohort step
(each group already parallel over its clients' devices).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List

import jax
import numpy as np

import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.data.stacking import gather_cohort
from fedml_tpu.parallel.cohort import train_cohort

logger = logging.getLogger(__name__)


def make_grouped_round(local_train, group_comm_round: int):
    """One jit for an entire hierarchical round: vmap over the group axis of
    a scanned multi-round FedAvg (group.py:24-46 per group, trainer.py:56-62
    across groups).

    ``grouped(params, cohorts, rng) -> new_params`` with cohort leaves
    [G, M, S, B, ...]; a group whose sampled-client weights are all zero
    (possible under random assignment) passes params through unchanged.
    """

    def group_run(params, cohort, rng):
        # guard the weights, not the mean: an all-padding (empty) group gets
        # uniform dummy weights so tree_weighted_mean stays finite (ints
        # included), then the result is discarded by the total>0 select
        total = jnp.sum(cohort["num_samples"].astype(jnp.float32))
        safe_w = jnp.where(total > 0, cohort["num_samples"],
                           jnp.ones_like(cohort["num_samples"]))

        def body(carry, _):
            p, r = carry
            r, rr = jax.random.split(r)
            stacked, _ = train_cohort(local_train, p, cohort, rr)
            p_new = tree_weighted_mean(stacked, safe_w)
            # empty group: no clients -> model unchanged
            p = jax.tree.map(
                lambda new, old: jnp.where(total > 0, new, old), p_new, p)
            return (p, r), None

        (p, _), _ = jax.lax.scan(body, (params, rng), None,
                                 length=group_comm_round)
        return p, total

    @jax.jit
    def grouped(params, cohorts, rng):
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(cohorts["num_samples"].shape[0]))
        group_params, group_w = jax.vmap(
            group_run, in_axes=(None, 0, 0))(params, cohorts, rngs)
        return tree_weighted_mean(group_params, group_w)

    return grouped


@dataclasses.dataclass
class HierarchicalConfig(FedAvgConfig):
    group_num: int = 2
    group_comm_round: int = 2
    group_method: str = "random"


class HierarchicalFedAvg(FedAvg):
    def __init__(self, workload, data, config: HierarchicalConfig, mesh=None, sink=None):
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        if cfg.group_method != "random":
            raise ValueError(f"unknown group_method {cfg.group_method!r}")
        rng = np.random.RandomState(cfg.seed)
        self.group_indexes = rng.randint(0, cfg.group_num, data.client_num)
        # single-chip: all groups train simultaneously (vmap'd group axis)
        self._grouped_round = (None if mesh is not None else
                               make_grouped_round(self._local_train,
                                                  cfg.group_comm_round))

    def _group_clients(self, ids: np.ndarray) -> Dict[int, List[int]]:
        groups: Dict[int, List[int]] = {}
        for cid in ids:
            groups.setdefault(int(self.group_indexes[cid]), []).append(int(cid))
        return groups

    def run(self, params=None, rng=None, checkpointer=None):
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        if params is None:
            rng, init_rng = jax.random.split(rng)
            params = self.workload.init(init_rng, jax.tree.map(
                lambda v: v[0, 0], {k: self.data.train[k]
                                    for k in ("x", "y", "mask")}))
        params, rng, start_round = self._maybe_resume(checkpointer, params, rng)

        from jax.sharding import PartitionSpec as P
        from fedml_tpu.parallel.mesh import stage_global
        params = stage_global(params, self.mesh)
        for global_round in range(start_round, cfg.comm_round):
            ids = sample_clients(global_round, self.data.client_num,
                                 cfg.client_num_per_round)
            groups = self._group_clients(np.asarray(ids))
            if self._grouped_round is not None:
                # one jit: [G, M, ...] cohorts, groups vmapped in parallel
                rng, rr = jax.random.split(rng)
                cohorts = [gather_cohort(self.data.train,
                                         groups.get(g, []),
                                         pad_to=cfg.client_num_per_round)
                           for g in range(cfg.group_num)]
                stacked = jax.tree.map(lambda *xs: jax.numpy.stack(xs),
                                       *cohorts)
                params = self._grouped_round(params, stacked, rr)
            else:
                # same rng derivation as the vmapped path (fold_in by group
                # index, split per group round) so one seed yields one model
                # regardless of topology
                rng, rr = jax.random.split(rng)
                group_params, group_weights = [], []
                for gidx in sorted(groups):
                    gids = groups[gidx]
                    w_group = params
                    cohort = gather_cohort(self.data.train, gids,
                                           pad_to=cfg.client_num_per_round)
                    cohort = stage_global(cohort, self.mesh, P("clients"))
                    r_g = jax.random.fold_in(rr, gidx)
                    for group_round in range(cfg.group_comm_round):
                        r_g, rloc = jax.random.split(r_g)
                        rloc = stage_global(rloc, self.mesh)
                        w_group, _ = self.cohort_step(w_group, cohort, rloc)
                    group_params.append(w_group)
                    group_weights.append(
                        float(self.data.train["num_samples"][gids].sum()))
                params = tree_weighted_mean(group_params,
                                            jax.numpy.asarray(group_weights))

            if (global_round % cfg.frequency_of_the_test == 0
                    or global_round == cfg.comm_round - 1):
                stats = self.evaluate_global(params)
                stats["round"] = global_round
                self.history.append(stats)
                logger.info("global round %d: %s", global_round, stats)
                if self.sink is not None:
                    self.sink.log(stats, step=global_round)
            if checkpointer is not None:
                checkpointer.maybe_save(
                    global_round,
                    self._ckpt_state(params, rng, global_round),
                    last_round=global_round == cfg.comm_round - 1)
        return params
