"""GAN model pair for the federated GAN algorithms.

Parity targets: the reference's GAN nets live in
``fedml_api/model/cv/{dadgan,asdgan,networks}.py`` — a conv
generator/discriminator family (DCGAN/pix2pix flavors) managed by a torch
``BaseModel`` with checkpoint save/load (base_model.py:161-178).  Here:

* ``Generator`` — noise z -> image via dense reshape + transposed-conv
  stack (the DCGAN shape used by FedGan);
* ``Discriminator`` — image -> real/fake logit via strided conv stack;
* ``CondGenerator`` — conditioning image A -> synthetic image B
  (encoder-decoder, the AsDGan server generator whose outputs ship to
  clients, AsDGanAggregator.forward_G);
* ``PatchDiscriminator`` — patch-logit map (the client-side D judging
  (A, B) pairs).

GroupNorm everywhere (jit-stable under tiny federated batches); NHWC.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.norms import Norm


class Generator(nn.Module):
    """z [B, z_dim] -> image [B, H, W, C]; H = 4 * 2^len(widths)."""
    out_channels: int = 1
    base_hw: int = 4
    widths: Sequence[int] = (64, 32)
    z_dim: int = 64

    @nn.compact
    def __call__(self, z, train: bool = False):
        B = z.shape[0]
        x = nn.Dense(self.base_hw * self.base_hw * self.widths[0])(z)
        x = x.reshape(B, self.base_hw, self.base_hw, self.widths[0])
        for w in self.widths:
            x = nn.ConvTranspose(w, (4, 4), strides=(2, 2), padding="SAME")(x)
            x = Norm("group")(x, train)
            x = nn.relu(x)
        x = nn.Conv(self.out_channels, (3, 3), padding="SAME")(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    """image -> single real/fake logit."""
    widths: Sequence[int] = (32, 64)

    @nn.compact
    def __call__(self, x, train: bool = False):
        for w in self.widths:
            x = nn.Conv(w, (4, 4), strides=(2, 2), padding="SAME")(x)
            x = Norm("group")(x, train)
            x = nn.leaky_relu(x, 0.2)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1)(x)


class CondGenerator(nn.Module):
    """A -> fake B (encoder-decoder with skip, pix2pix-lite)."""
    out_channels: int = 1
    width: int = 32

    @nn.compact
    def __call__(self, a, train: bool = False):
        e1 = nn.Conv(self.width, (4, 4), strides=(2, 2), padding="SAME")(a)
        e1 = nn.relu(Norm("group")(e1, train))
        e2 = nn.Conv(self.width * 2, (4, 4), strides=(2, 2), padding="SAME")(e1)
        e2 = nn.relu(Norm("group")(e2, train))
        d1 = nn.ConvTranspose(self.width, (4, 4), strides=(2, 2),
                              padding="SAME")(e2)
        d1 = nn.relu(Norm("group")(d1, train))
        d1 = jnp.concatenate([d1, e1], axis=-1)
        d2 = nn.ConvTranspose(self.width, (4, 4), strides=(2, 2),
                              padding="SAME")(d1)
        d2 = nn.relu(Norm("group")(d2, train))
        x = nn.Conv(self.out_channels, (3, 3), padding="SAME")(d2)
        return jnp.tanh(x)


class PatchDiscriminator(nn.Module):
    """(optionally A-conditioned) image -> patch logit map [B, h, w, 1]."""
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, mult in enumerate((1, 2)):
            x = nn.Conv(self.width * mult, (4, 4), strides=(2, 2),
                        padding="SAME")(x)
            if i:
                x = Norm("group")(x, train)
            x = nn.leaky_relu(x, 0.2)
        return nn.Conv(1, (3, 3), padding="SAME")(x)
