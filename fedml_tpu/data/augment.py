"""On-device, jittable data augmentation.

The reference augments on the host CPU through torchvision transforms —
RandomCrop(32, padding=4), RandomHorizontalFlip, Normalize, Cutout(16)
(``fedml_api/data_preprocessing/cifar10/data_loader.py:57-99``).  On TPU,
host-side per-image Python transforms would serialize the input pipeline; the
TPU-native design applies the same augmentations *inside the jit'd train step*
as vectorized gather/where ops keyed by a `jax.random` key, so they fuse with
the forward pass and cost ~zero HBM round-trips.

All functions take `x` of shape [..., H, W, C] (any leading batch dims) and a
key, and are shape-polymorphic under vmap.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def normalize(x: jnp.ndarray, mean: Sequence[float], std: Sequence[float]
              ) -> jnp.ndarray:
    """Channelwise (x - mean) / std (cifar10/data_loader.py:82-88)."""
    mean = jnp.asarray(mean, x.dtype)
    std = jnp.asarray(std, x.dtype)
    return (x - mean) / std


def random_flip(key: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
    """Horizontal flip with p=0.5, independently per image (leading dims)."""
    batch_shape = x.shape[:-3]
    flip = jax.random.bernoulli(key, 0.5, batch_shape)
    return jnp.where(flip[..., None, None, None], jnp.flip(x, axis=-2), x)


def _shifted_crop(x: jnp.ndarray, dy: jnp.ndarray, dx: jnp.ndarray,
                  pad: int) -> jnp.ndarray:
    """Crop an H×W window at offset (dy, dx) out of the zero-padded image.
    Implemented as a roll + static slice so shapes stay static under jit."""
    H, W = x.shape[-3], x.shape[-2]
    xp = jnp.pad(x, [(0, 0)] * (x.ndim - 3) + [(pad, pad), (pad, pad), (0, 0)])
    xp = jnp.roll(xp, shift=(-dy, -dx), axis=(-3, -2))
    return jax.lax.slice_in_dim(
        jax.lax.slice_in_dim(xp, 0, H, axis=x.ndim - 3), 0, W, axis=x.ndim - 2)


def random_crop(key: jax.Array, x: jnp.ndarray, padding: int = 4
                ) -> jnp.ndarray:
    """RandomCrop(H, padding) — pad `padding` on each side, crop back to H×W
    at a uniform offset, per image."""
    batch_shape = x.shape[:-3]
    kdy, kdx = jax.random.split(key)
    dy = jax.random.randint(kdy, batch_shape, 0, 2 * padding + 1)
    dx = jax.random.randint(kdx, batch_shape, 0, 2 * padding + 1)
    if batch_shape:
        flat_x = x.reshape((-1,) + x.shape[-3:])
        out = jax.vmap(lambda xi, yi, xi2: _shifted_crop(xi, yi, xi2, padding)
                       )(flat_x, dy.reshape(-1), dx.reshape(-1))
        return out.reshape(x.shape)
    return _shifted_crop(x, dy, dx, padding)


def cutout(key: jax.Array, x: jnp.ndarray, length: int = 16) -> jnp.ndarray:
    """Cutout: zero a length×length square at a uniform center, clipped to the
    image (cifar10/data_loader.py:57-76 — the mask is clipped, so edge squares
    are smaller, exactly as np.clip does there)."""
    H, W = x.shape[-3], x.shape[-2]
    batch_shape = x.shape[:-3]
    ky, kx = jax.random.split(key)
    cy = jax.random.randint(ky, batch_shape + (1, 1), 0, H)
    cx = jax.random.randint(kx, batch_shape + (1, 1), 0, W)
    rows = jnp.arange(H)[:, None]
    cols = jnp.arange(W)[None, :]
    inside = ((rows >= cy - length // 2) & (rows < cy + length // 2)
              & (cols >= cx - length // 2) & (cols < cx + length // 2))
    return x * (1.0 - inside[..., None].astype(x.dtype))


def cifar_train_augment(key: jax.Array, x: jnp.ndarray,
                        mean: Sequence[float], std: Sequence[float],
                        crop_padding: int = 4, cutout_length: int = 16
                        ) -> jnp.ndarray:
    """The full CIFAR train transform pipeline (crop → flip → normalize →
    cutout), one fused on-device pass.  Matches the order in
    cifar10/data_loader.py:79-92 (Cutout is appended after ToTensor/Normalize).
    """
    k1, k2, k3 = jax.random.split(key, 3)
    x = random_crop(k1, x, crop_padding)
    x = random_flip(k2, x)
    x = normalize(x, mean, std)
    return cutout(k3, x, cutout_length)


def center_crop(x: jnp.ndarray, size: int) -> jnp.ndarray:
    """CenterCrop(size) — the reference's fed_cifar100 *test* transform
    (fed_cifar100/utils.py:19-24)."""
    H, W = x.shape[-3], x.shape[-2]
    top, left = (H - size) // 2, (W - size) // 2
    out = jax.lax.slice_in_dim(x, top, top + size, axis=x.ndim - 3)
    return jax.lax.slice_in_dim(out, left, left + size, axis=x.ndim - 2)


def random_crop_to(key: jax.Array, x: jnp.ndarray, size: int) -> jnp.ndarray:
    """RandomCrop(size) with size < H — cuts a size×size window at a uniform
    offset (the fed_cifar100 24×24 train crop, fed_cifar100/utils.py:11-17).
    Output is smaller than the input, unlike `random_crop` which pads first."""
    H, W = x.shape[-3], x.shape[-2]
    batch_shape = x.shape[:-3]
    kdy, kdx = jax.random.split(key)
    dy = jax.random.randint(kdy, batch_shape, 0, H - size + 1)
    dx = jax.random.randint(kdx, batch_shape, 0, W - size + 1)

    def crop_one(xi, yi, xi2):
        rolled = jnp.roll(xi, shift=(-yi, -xi2), axis=(-3, -2))
        out = jax.lax.slice_in_dim(rolled, 0, size, axis=rolled.ndim - 3)
        return jax.lax.slice_in_dim(out, 0, size, axis=rolled.ndim - 2)

    if batch_shape:
        flat = x.reshape((-1,) + x.shape[-3:])
        out = jax.vmap(crop_one)(flat, dy.reshape(-1), dx.reshape(-1))
        return out.reshape(batch_shape + out.shape[1:])
    return crop_one(x, dy, dx)


def fed_cifar100_train_augment(key: jax.Array, x: jnp.ndarray,
                               mean: Sequence[float], std: Sequence[float],
                               crop_size: int = 24) -> jnp.ndarray:
    """fed_cifar100 train pipeline: RandomCrop(24) → flip → normalize
    (fed_cifar100/utils.py:11-17)."""
    k1, k2 = jax.random.split(key)
    x = random_crop_to(k1, x, crop_size)
    x = random_flip(k2, x)
    return normalize(x, mean, std)


def fed_cifar100_eval_transform(x: jnp.ndarray, mean: Sequence[float],
                                std: Sequence[float], crop_size: int = 24
                                ) -> jnp.ndarray:
    """fed_cifar100 test pipeline: CenterCrop(24) → normalize."""
    return normalize(center_crop(x, crop_size), mean, std)


# Channel stats from the reference (cifar10/data_loader.py:80-81 etc.)
CIFAR10_MEAN = (0.49139968, 0.48215827, 0.44653124)
CIFAR10_STD = (0.24703233, 0.24348505, 0.26158768)
CIFAR100_MEAN = (0.5071, 0.4865, 0.4409)
CIFAR100_STD = (0.2673, 0.2564, 0.2762)
CINIC10_MEAN = (0.47889522, 0.47227842, 0.43047404)
CINIC10_STD = (0.24205776, 0.23828046, 0.25874835)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)
