#!/usr/bin/env python
"""Perf regression gate + timing-trust lint for flight-recorder ledgers.

    python scripts/perf_trend.py --ledger RUN/perf.jsonl \
        --baseline PERF_demo.jsonl --lint_mfu 'BENCH_*.json'

Exit 0 = pass, 1 = named regression / lint violation, 2 = bad inputs —
wire it into CI beside the test tiers (scripts/test_fast.sh).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.obs.trend import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
