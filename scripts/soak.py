#!/usr/bin/env python
"""Seeded process-level fault-injection soak campaign (ISSUE 12).

Drives small in-process federations through a matrix of fault arms —
process kills at every registered crash point, link chaos, disk faults,
defenses under attack, the edge tree, async, and secagg — with an
in-process respawn harness (catch `ActorKilled`, cancel the corpse's
timers, rebuild the server from its checkpoint + journal on a fresh
transport endpoint) and an INVARIANT CHECKER:

  I1  never a mis-aggregated global — killed-then-resumed finals equal
      the uncrashed reference bit-for-bit on the defended-mean stream
      path (allclose on secagg, whose abort-only rounds may legally
      lose work but never publish a partial unmask);
  I2  bounded progress — every arm completes within its respawn budget
      (no deadlock, no crash loop);
  I3  trust monotone across crashes — a quarantined attacker's sentence
      survives every respawn (never released early by a restart);
  I4  every ledger still parses — perf.jsonl / health.jsonl / the
      journal all load after kills and injected disk faults.

Any violation exits 1 with the arm and invariant named.  Determinism:
all faults derive from --seed (the `ChaosTransport` / `Faultline`
replay contract), so a failing campaign re-runs identically.

Usage:
  python scripts/soak.py [--smoke] [--seed N] [--out PATH]
"""

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,  # noqa: E402
                                             FedAvgServerActor)
from fedml_tpu.comm.local import LocalHub  # noqa: E402
from fedml_tpu.core.stream_agg import StreamingAggregator  # noqa: E402
from fedml_tpu.robust.faultline import (CRASH_POINTS, ActorKilled,  # noqa: E402
                                        CrashSpec, DiskFaultInjector,
                                        DiskFaultSpec, Faultline,
                                        kill_actor)
from fedml_tpu.utils.checkpoint import RoundCheckpointer  # noqa: E402
from fedml_tpu.utils.journal import RoundJournal  # noqa: E402

MAX_RESPAWNS = 10


class Violation(Exception):
    def __init__(self, invariant, detail):
        super().__init__(f"{invariant}: {detail}")
        self.invariant = invariant


def _params(seed=3):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(6, 4).astype(np.float32),
                      "bias": rng.randn(4).astype(np.float32)}}


def _train_fn(silo, nan_silos=()):
    def fn(params, client_idx, round_idx):
        if silo in nan_silos:
            return jax.tree.map(
                lambda v: np.full_like(np.asarray(v), np.nan), params), 10
        rng = np.random.RandomState(1000 * silo + int(round_idx or 0))
        return jax.tree.map(
            lambda v: v + rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
            params), 10 + silo
    return fn


def _bit_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _check_ledgers(workdir):
    """I4: every artifact the run left must still parse."""
    from fedml_tpu.obs.trend import load_ledger
    for root, _, files in os.walk(workdir):
        for f in files:
            p = os.path.join(root, f)
            if f.endswith("perf.jsonl") or f.endswith("health.jsonl"):
                load_ledger(p)  # raises on mid-file corruption
            elif f == "journal.jsonl":
                RoundJournal(root).read_records()


def _run_sync(workdir, rounds=3, n=3, ck=True, jr=True, fl=None,
              nan_silos=(), admission=None, extra_state=None,
              perf_path=None, chaos_plan=None, straggler=None):
    """One sync federation attempt (pump or threaded drive)."""
    perf = None
    if perf_path:
        from fedml_tpu.obs.perf import PerfRecorder
        perf = PerfRecorder(perf_path, strict_recompiles=True,
                            rss_interval_s=10.0)
    init = _params(3)
    hub = LocalHub(codec_roundtrip=True)
    wrap = (lambda t: t)
    threaded = chaos_plan is not None
    if chaos_plan is not None:
        from fedml_tpu.comm.chaos import ChaosTransport
        wrap = lambda t: ChaosTransport(t, chaos_plan)  # noqa: E731
    stream = StreamingAggregator(init, method="mean", kind="params",
                                 norm_clip=1.0, seed=0,
                                 sentry=perf.sentry if perf else None)
    kw = {}
    if straggler:
        kw = dict(straggler_policy="drop", round_timeout_s=straggler,
                  min_silo_frac=0.5)
    server = FedAvgServerActor(
        wrap(hub.transport(0)), init, n, n, rounds,
        checkpointer=(RoundCheckpointer(os.path.join(workdir, "ck"),
                                        save_every=1) if ck else None),
        stream_agg=stream,
        journal=(RoundJournal(os.path.join(workdir, "j"),
                              snapshot_every=1) if jr else None),
        faultline=fl, admission=admission, extra_state=extra_state,
        perf=perf, **kw)
    silos = [FedAvgClientActor(i, wrap(hub.transport(i)),
                               _train_fn(i, nan_silos))
             for i in range(1, n + 1)]
    server.register_handlers()
    for s in silos:
        s.register_handlers()
    try:
        if threaded:
            import threading
            threads = [threading.Thread(target=a.run, daemon=True)
                       for a in silos]
            for t in threads:
                t.start()
            server.start()
            server.transport.run()
            for t in threads:
                t.join(timeout=10)
        else:
            server.start()
            hub.pump()
    finally:
        if perf is not None:
            perf.close()
    return server


def _respawn_loop(run_once, specs, seed, on_respawn=None):
    """The in-process kill -9 harness: one attempt per remaining spec,
    bounded by MAX_RESPAWNS (I2)."""
    fl = Faultline(crashes=specs, seed=seed)
    for attempt in range(MAX_RESPAWNS + 1):
        try:
            return run_once(fl, attempt), fl
        except ActorKilled as e:
            fl.respawn()
            if on_respawn is not None:
                on_respawn(e, attempt)
    raise Violation("I2_bounded_progress",
                    f"still crashing after {MAX_RESPAWNS} respawns")


# ---------------------------------------------------------------------------
# the arms
# ---------------------------------------------------------------------------

def arm_sync_kill_every_point(seed, smoke=False):
    """Kill the sync server at EVERY registered crash point (one per
    round across respawns); final global must be bit-identical to the
    uncrashed reference (I1) with ledgers parsing (I4)."""
    points = [p for p in CRASH_POINTS if p != "mid_unmask"]
    if smoke:
        points = points[:2]
    with tempfile.TemporaryDirectory() as ref_dir:
        ref = _run_sync(ref_dir, jr=False, ck=False).params
    with tempfile.TemporaryDirectory() as d:
        specs = [CrashSpec(point=p, hit=1, round_idx=i % 3)
                 for i, p in enumerate(points)]

        def once(fl, attempt):
            return _run_sync(
                d, fl=fl,
                perf_path=os.path.join(d, f"a{attempt}-perf.jsonl"))

        server, fl = _respawn_loop(once, specs, seed)
        if server.round_idx != 3:
            raise Violation("I2_bounded_progress",
                            f"finished at round {server.round_idx}")
        if not _bit_equal(server.params, ref):
            raise Violation("I1_misaggregation",
                            "resumed global != uncrashed reference")
        _check_ledgers(d)
        return {"kills": fl.kills, "respawns": fl.respawns,
                "points": points}


def arm_sync_link_chaos_plus_kill(seed, smoke=False):
    """Link chaos (dup + reorder + corrupt-free drop with the drop
    policy) composed with a process kill: the federation must complete
    (I2) with a finite global and parsing ledgers (I4).  Bit-identity
    is NOT asserted — the drop policy legally loses uploads."""
    from fedml_tpu.algorithms.cross_silo import MsgType
    from fedml_tpu.comm.chaos import ChaosPlan, LinkChaos
    plan = ChaosPlan(
        seed=seed,
        default=LinkChaos(drop_prob=0.05, dup_prob=0.1, reorder_prob=0.1,
                          max_delay_s=0.02),
        immune_types=(MsgType.S2C_FINISH, MsgType.ROUND_TIMEOUT))
    with tempfile.TemporaryDirectory() as d:
        specs = [CrashSpec(point="post_fold_pre_ack", hit=1, round_idx=1)]

        def once(fl, attempt):
            return _run_sync(d, fl=fl, chaos_plan=plan, straggler=2.0)

        server, fl = _respawn_loop(once, specs, seed)
        if server.round_idx != 3:
            raise Violation("I2_bounded_progress",
                            f"finished at round {server.round_idx}")
        if not all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(server.params)):
            raise Violation("I1_misaggregation", "non-finite global")
        _check_ledgers(d)
        return {"kills": fl.kills, "faults": "chaos+kill"}


def arm_trust_monotone_under_kills(seed, smoke=False):
    """A NaN-spewing attacker is quarantined; the server is killed twice
    mid-federation.  I3: every respawn restores the attacker's sentence
    — the trust state is monotone across crashes (never released early),
    pinned against the checkpointed extra_state."""
    from fedml_tpu.robust import AdmissionPipeline, TrustTracker

    def make_admission():
        return AdmissionPipeline(
            _params(3), kind="params",
            trust=TrustTracker(strikes_to_quarantine=1,
                               quarantine_rounds=5, probation_rounds=2))

    with tempfile.TemporaryDirectory() as d:
        state = {"adm": None, "sentence": None}

        def once(fl, attempt):
            adm = make_admission()
            state["adm"] = adm
            extra = (lambda: adm.trust.state_dict(3),
                     adm.trust.load_state_dict)
            server = _run_sync(d, rounds=5, fl=fl, nan_silos=(3,),
                               admission=adm, extra_state=extra)
            return server

        def on_respawn(e, attempt):
            pre = state["adm"].trust._quarantine_until.get(3)
            if state["sentence"] is None:
                state["sentence"] = pre
            elif pre is not None and state["sentence"] is not None \
                    and pre < state["sentence"]:
                raise Violation("I3_trust_monotone",
                                f"sentence shrank {state['sentence']} -> "
                                f"{pre}")

        specs = [CrashSpec(point="post_fold_pre_ack", hit=1, round_idx=1),
                 CrashSpec(point="barrier_close", hit=1, round_idx=3)]
        server, fl = _respawn_loop(once, specs, seed,
                                   on_respawn=on_respawn)
        if server.round_idx != 5:
            raise Violation("I2_bounded_progress",
                            f"finished at round {server.round_idx}")
        trust = state["adm"].trust
        until = trust._quarantine_until.get(3)
        probation = trust._probation_left.get(3)
        if until is None and probation is None \
                and trust.state(3, server.round_idx - 1) == "trusted" \
                and state["sentence"] is not None \
                and server.round_idx - 1 < state["sentence"]:
            raise Violation("I3_trust_monotone",
                            "attacker fully trusted before its original "
                            "sentence expired")
        return {"kills": fl.kills, "sentence_until": state["sentence"]}


def arm_edge_tree_root_kill(seed, smoke=False):
    """The edge topology with the ROOT killed mid-round: the root's
    journal restores the durably-folded edge frames and re-syncs only
    the missing edges (whose silos retrain deterministically) — final
    global bit-identical to the uncrashed tree (I1)."""
    from fedml_tpu.algorithms.hierarchical import EdgeAggregatorActor
    init = _params(3)

    def build(workdir, fl):
        hub = LocalHub(codec_roundtrip=True)
        root = FedAvgServerActor(
            hub.transport(0), init, 4, 2, 2,
            checkpointer=(RoundCheckpointer(
                os.path.join(workdir, "ck"), save_every=1)
                if workdir else None),
            stream_agg=StreamingAggregator(init, method="mean",
                                           kind="params", seed=0),
            journal=(RoundJournal(os.path.join(workdir, "j"),
                                  snapshot_every=1) if workdir else None),
            faultline=fl)
        edges = [EdgeAggregatorActor(
            e, hub.transport(e), {2 + g: g for g in block},
            cohort_total=4, client_num_in_total=4,
            stream_agg=StreamingAggregator(init, method="mean",
                                           kind="params", seed=0))
            for e, block in ((1, (1, 2)), (2, (3, 4)))]
        silos = [FedAvgClientActor(2 + g, hub.transport(2 + g),
                                   _train_fn(g),
                                   server_id=(1 if g <= 2 else 2))
                 for g in (1, 2, 3, 4)]
        root.register_handlers()
        for a in edges + silos:
            a.register_handlers()
        return hub, root

    hub, root = build(None, None)
    root.start()
    hub.pump()
    ref = root.params
    with tempfile.TemporaryDirectory() as d:
        specs = [CrashSpec(point="post_fold_pre_ack", hit=1, round_idx=0)]

        def once(fl, attempt):
            hub, root = build(d, fl)
            root.start()
            hub.pump()
            return root

        root2, fl = _respawn_loop(once, specs, seed)
        if root2.round_idx != 2:
            raise Violation("I2_bounded_progress",
                            f"finished at round {root2.round_idx}")
        if not _bit_equal(root2.params, ref):
            raise Violation("I1_misaggregation",
                            "edge-tree resumed global != reference")
        return {"kills": fl.kills}


def arm_async_kill(seed, smoke=False):
    """The async server killed mid-version resumes the SAME version
    (buffer + fold restored) and completes every version (I2) with a
    finite global."""
    from fedml_tpu.algorithms.async_fl import (AsyncFedServerActor,
                                               delta_encoder)
    init = _params(7)
    with tempfile.TemporaryDirectory() as d:

        def once(fl, attempt):
            hub = LocalHub(codec_roundtrip=True)
            srv = AsyncFedServerActor(
                hub.transport(0), init, 3, 3, num_versions=3,
                aggregation_goal=3,
                checkpointer=RoundCheckpointer(os.path.join(d, "ck"),
                                               save_every=1),
                stream_agg=StreamingAggregator(init, method="mean",
                                               kind="delta", seed=0),
                journal=RoundJournal(os.path.join(d, "j"),
                                     snapshot_every=1),
                faultline=fl)
            silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i),
                                       encode_upload=delta_encoder)
                     for i in (1, 2, 3)]
            srv.register_handlers()
            for s in silos:
                s.register_handlers()
            srv.start()
            hub.pump()
            return srv

        specs = [CrashSpec(point="post_fold_pre_ack", hit=2, round_idx=1),
                 CrashSpec(point="mid_checkpoint_write", hit=1,
                           round_idx=2)]
        srv, fl = _respawn_loop(once, specs, seed)
        if srv.version != 3:
            raise Violation("I2_bounded_progress",
                            f"finished at version {srv.version}")
        if not all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(srv.params)):
            raise Violation("I1_misaggregation", "non-finite global")
        _check_ledgers(d)
        return {"kills": fl.kills}


def arm_secagg_abort_only(seed, smoke=False):
    """Secagg with kills at mid_unmask and barrier_close: crashed rounds
    ABORT to the boundary (the journal marks them non-resumable) and the
    completed federation matches the clean secagg run — a partially
    unmasked sum never publishes (I1)."""
    from fedml_tpu.robust import AdmissionPipeline
    from fedml_tpu.secure.protocol import (SecAggClient, SecAggServer,
                                           masked_template)
    init = {"w": np.zeros(6, np.float32)}

    def run(workdir, fl):
        hub = LocalHub(codec_roundtrip=True)
        server = FedAvgServerActor(
            hub.transport(0), init, 4, 4, 2,
            admission=AdmissionPipeline(masked_template(init),
                                        kind="masked"),
            secagg=SecAggServer(threshold=0, clip=64.0, weight_cap=10.0),
            checkpointer=(RoundCheckpointer(
                os.path.join(workdir, "ck"), save_every=1)
                if workdir else None),
            journal=(RoundJournal(os.path.join(workdir, "j"))
                     if workdir else None),
            faultline=fl)
        server.register_handlers()
        for i in range(1, 5):
            def tf(i=i):
                def fn(params, client_idx, round_idx):
                    return jax.tree.map(
                        lambda v: np.asarray(v) + 0.1 * i, params), 4.0 + i
                return fn
            c = FedAvgClientActor(i, hub.transport(i), tf(),
                                  secagg=SecAggClient(i))
            c.register_handlers()
        server.start()
        hub.pump()
        return server

    ref = run(None, None).params
    with tempfile.TemporaryDirectory() as d:
        specs = [CrashSpec(point="mid_unmask", hit=1, round_idx=0),
                 CrashSpec(point="barrier_close", hit=1, round_idx=1)]
        server, fl = _respawn_loop(
            specs=specs, seed=seed,
            run_once=lambda fl, attempt: run(d, fl))
        if server.round_idx != 2:
            raise Violation("I2_bounded_progress",
                            f"finished at round {server.round_idx}")
        if not all(np.allclose(np.asarray(a), np.asarray(b))
                   for a, b in zip(jax.tree.leaves(server.params),
                                   jax.tree.leaves(ref))):
            raise Violation("I1_misaggregation",
                            "secagg resumed global != clean secagg run")
        jr = RoundJournal(os.path.join(d, "j"))
        kinds = {r["kind"] for r in jr.read_records()}
        if jr.recover() is not None and "abandon" not in kinds:
            raise Violation("I1_misaggregation",
                            "crashed secagg round neither closed nor "
                            "abandoned")
        return {"kills": fl.kills}


def arm_disk_faults(seed, smoke=False):
    """ENOSPC on the perf ledger, EIO on the health ledger, a TORN
    journal append, and a failed snapshot — all during a killed-and-
    resumed run: one warning each, the round loop survives, the ledger
    prefixes parse (I4), and recovery from the torn prefix stays
    bit-identical (I1)."""
    import errno
    from fedml_tpu.obs.health import HealthAccumulator
    with tempfile.TemporaryDirectory() as ref_dir:
        ref = _run_sync(ref_dir, jr=False, ck=False).params
    with tempfile.TemporaryDirectory() as d:
        inj = DiskFaultInjector([
            DiskFaultSpec(channel="perf_ledger", hit=2),
            DiskFaultSpec(channel="health_ledger", hit=1,
                          err=errno.EIO),
            DiskFaultSpec(channel="journal", hit=40, torn=True),
            DiskFaultSpec(channel="journal_snapshot", hit=30),
        ]).install()
        try:
            specs = [CrashSpec(point="barrier_close", hit=1,
                               round_idx=1)]

            def once(fl, attempt):
                # a health accumulator rides along so the health-ledger
                # channel sees real appends
                health = HealthAccumulator(
                    kind="params",
                    ledger_path=os.path.join(d, f"a{attempt}-health.jsonl"))
                init = _params(3)
                hub = LocalHub(codec_roundtrip=True)
                from fedml_tpu.obs.perf import PerfRecorder
                perf = PerfRecorder(
                    os.path.join(d, f"a{attempt}-perf.jsonl"),
                    rss_interval_s=10.0)
                server = FedAvgServerActor(
                    hub.transport(0), init, 3, 3, 3,
                    checkpointer=RoundCheckpointer(
                        os.path.join(d, "ck"), save_every=1),
                    stream_agg=StreamingAggregator(
                        init, method="mean", kind="params",
                        norm_clip=1.0, seed=0),
                    journal=RoundJournal(os.path.join(d, "j"),
                                         snapshot_every=1),
                    faultline=fl, perf=perf, health=health)
                silos = [FedAvgClientActor(i, hub.transport(i),
                                           _train_fn(i))
                         for i in (1, 2, 3)]
                server.register_handlers()
                for s in silos:
                    s.register_handlers()
                try:
                    server.start()
                    hub.pump()
                finally:
                    perf.close()
                return server

            server, fl = _respawn_loop(once, specs, seed)
        finally:
            inj.remove()
        if server.round_idx != 3:
            raise Violation("I2_bounded_progress",
                            f"finished at round {server.round_idx}")
        if not _bit_equal(server.params, ref):
            raise Violation("I1_misaggregation",
                            "global diverged under disk faults")
        if inj.injected < 2:
            raise Violation("I4_ledgers_parse",
                            f"only {inj.injected} disk faults landed — "
                            f"the arm did not exercise the seam")
        _check_ledgers(d)
        return {"kills": fl.kills, "disk_faults": inj.injected}


ARMS = {
    "sync_kill_every_point": arm_sync_kill_every_point,
    "sync_link_chaos_plus_kill": arm_sync_link_chaos_plus_kill,
    "trust_monotone_under_kills": arm_trust_monotone_under_kills,
    "edge_tree_root_kill": arm_edge_tree_root_kill,
    "async_kill": arm_async_kill,
    "secagg_abort_only": arm_secagg_abort_only,
    "disk_faults": arm_disk_faults,
}

SMOKE_ARMS = ("sync_kill_every_point", "secagg_abort_only", "disk_faults")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="reduced matrix for CI (3 arms, fewer points)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--arms", type=str, default="",
                    help="comma list to restrict (default: all)")
    ap.add_argument("--out", type=str, default="",
                    help="write the JSON summary here")
    args = ap.parse_args(argv)

    names = (args.arms.split(",") if args.arms
             else (SMOKE_ARMS if args.smoke else list(ARMS)))
    results, violations = {}, []
    for name in names:
        t0 = time.monotonic()
        print(f"[soak] arm {name} ...", flush=True)
        try:
            detail = ARMS[name](args.seed, smoke=args.smoke)
            results[name] = {"ok": True, "s": round(
                time.monotonic() - t0, 2), **detail}
            print(f"[soak]   ok ({results[name]['s']}s) {detail}")
        except Violation as v:
            results[name] = {"ok": False, "invariant": v.invariant,
                             "detail": str(v)}
            violations.append((name, v))
            print(f"[soak]   VIOLATION {v}", file=sys.stderr)
    summary = {"seed": args.seed, "smoke": args.smoke,
               "arms": results,
               "violations": [f"{n}: {v}" for n, v in violations]}
    print(json.dumps(summary, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
    if violations:
        print(f"[soak] {len(violations)} invariant violation(s)",
              file=sys.stderr)
        return 1
    print(f"[soak] {len(results)} arm(s), zero invariant violations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
