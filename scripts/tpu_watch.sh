#!/usr/bin/env bash
# Recurring tunnel probe (VERDICT r3 item 1: "check for the tunnel early
# and repeatedly — a cron-style retry during the session").  The moment
# the backend answers, fire the full capture; on a mid-capture wedge go
# back to probing and retry (stage 1 reruns are cache-warm and cheap).
# A sentinel file marks capture-in-progress so interactive work can
# avoid contaminating the timings on this small host.
cd "$(dirname "$0")/.."
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
SENTINEL=/tmp/tpu_capture_running
trap 'rm -f "$SENTINEL"' EXIT
while true; do
  if timeout 75 python -c "import jax, jax.numpy as jnp; \
jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8)))" \
      >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) alive — launching capture" >> "$LOG"
    touch "$SENTINEL"
    if bash scripts/tpu_capture.sh >> "$LOG" 2>&1; then
      rm -f "$SENTINEL"
      echo "$(date -u +%FT%TZ) capture COMPLETE" >> "$LOG"
      exit 0
    fi
    rm -f "$SENTINEL"
    # promote the freshest capture partial so a later wedged bench run
    # (or the driver's end-of-round commit of uncommitted work) still
    # carries the newest REAL on-chip measurements; the whole contract
    # lives in bench.promote_partial (safe-path interpreter: cwd is not
    # on sys.path, insert it)
    python -c "import sys; sys.path.insert(0, '.'); import bench; \
print(bench.promote_partial())" >> "$LOG" 2>&1
    echo "$(date -u +%FT%TZ) capture incomplete — back to probing" >> "$LOG"
  else
    echo "$(date -u +%FT%TZ) wedged" >> "$LOG"
  fi
  sleep 140
done
