#!/usr/bin/env bash
# Full TPU perf capture — run when the tunnel is alive and the machine is
# otherwise IDLE (concurrent work contaminates both the TPU timings and
# the torch CPU baseline; verify skill).  One command covers every
# VERDICT-r02 pending item:
#   1. bf16 comparison run   -> BENCH_DETAILS_bf16.json
#   2. resnet56 repeat runs  -> BENCH_R56_SPREAD.json (variance methodology)
#   3. clean full f32 bench  -> BENCH_DETAILS.json (honest FLOPs,
#      device_kind, per-round spread medians, flash + blockwise T=2048)
# Ordered so the committed artifact (BENCH_DETAILS.json) is written LAST
# by the canonical f32 run.  Aborts before touching anything if the
# backend probe fails.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== backend probe (120s watchdog) =="
timeout 120 python - <<'EOF'
import jax, jax.numpy as jnp
jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8)))
d = jax.devices()[0]
print("alive:", d.platform, getattr(d, "device_kind", "?"))
EOF

echo "== 1/3 bf16 comparison =="
BENCH_DTYPE=bfloat16 BENCH_SCALING=0 python bench.py
cp BENCH_DETAILS.json BENCH_DETAILS_bf16.json
echo "bf16 details -> BENCH_DETAILS_bf16.json"

echo "== 2/3 resnet56 repeat spreads (tunnel-jitter methodology) =="
python - <<'EOF'
import json
import bench
rows = []
for rep in range(3):
    round_s, flops, steps, spread = bench.bench_resnet56_cifar10(8)
    rows.append({"rep": rep, "round_s": round_s, "spread": spread,
                 "step_time_ms": 1e3 * round_s / steps})
    print("rep", rep, rows[-1])
with open("BENCH_R56_SPREAD.json", "w") as f:
    json.dump(rows, f, indent=2)
print("wrote BENCH_R56_SPREAD.json")
EOF

echo "== 3/3 full clean f32 bench (canonical BENCH_DETAILS.json) =="
BENCH_MODE=full python bench.py

echo "done — inspect BENCH_DETAILS.json / BENCH_DETAILS_bf16.json /"
echo "BENCH_R56_SPREAD.json, then commit the clean artifacts."
