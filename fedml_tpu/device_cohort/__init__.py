"""Wave scheduler for mega-cohort cross-device federation.

`waves.py` turns one round's sampled cohort (1k-100k lightweight
clients) into a sequence of static device-sized WAVES, each trained as
ONE compiled XLA program, with per-wave summaries for admission/health
and stacked outputs the streaming spine folds device-side — the bridge
between `parallel/cohort.py` (the compiled engine) and the live round
loop's O(model) aggregation (`core/stream_agg.py`).
"""

from fedml_tpu.device_cohort.waves import (Wave, WaveAdmission,
                                           make_scaffold_wave_fn,
                                           make_wave_fn, plan_waves)

__all__ = ["Wave", "WaveAdmission", "make_wave_fn",
           "make_scaffold_wave_fn", "plan_waves"]
