"""The unified experiment config — one typed tree replacing the reference's
per-entry argparse soup (``fedml_experiments/distributed/fedavg/
main_fedavg.py:46-112``) plus its launch satellites (``gpu_mapping.yaml``,
``mpi_host_file``, ``grpc_ipconfig.csv``).

Flag parity: every behavioral flag of the reference's ``add_args`` exists
here under the same name (model, dataset, data_dir, partition_method,
partition_alpha, client_num_in_total, client_num_per_round, batch_size,
client_optimizer, lr, wd, epochs, comm_round, frequency_of_the_test, ci).
GPU placement flags (gpu_server_num / gpu_num_per_server / gpu_mapping_*)
are replaced by mesh flags (``--mesh_clients``), and ``mpirun -np N
-hostfile`` is replaced by ``--coordinator_address/--num_processes/
--process_id`` feeding ``jax.distributed.initialize``
(fedml_tpu/parallel/mesh.py).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass
class ExperimentConfig:
    # ---- reference argparse parity (main_fedavg.py:46-112) -------------
    algo: str = "fedavg"
    model: str = "lr"
    dataset: str = "mnist"
    data_dir: Optional[str] = None       # None => hermetic synthetic twin
    partition_method: str = "hetero"
    partition_alpha: float = 0.5
    client_num_in_total: int = 1000
    client_num_per_round: int = 10
    batch_size: int = 10
    client_optimizer: str = "sgd"
    compute_dtype: str = ""              # "bfloat16": MXU mixed precision
    lr: float = 0.03
    wd: float = 0.001
    epochs: int = 1
    comm_round: int = 10
    frequency_of_the_test: int = 5
    rounds_per_dispatch: int = 1         # >1: lax.scan K rounds per dispatch
    ci: int = 0                          # short-circuit eval (CI mode flag)
    seed: int = 0

    # ---- server optimizer (FedOpt, fedopt/optrepo.py registry) ---------
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.9

    # ---- server-optimizer spine (fedml_tpu/server_opt, ISSUE 18) -------
    server_opt: str = "plain"         # LIVE server step over the
    #                                   streaming/sharded finalize:
    #                                   plain (bit-identical pre-seam
    #                                   assignment) | momentum | adam |
    #                                   fedac — the finalize output
    #                                   becomes a pseudo-gradient and
    #                                   the optimizer's one jitted step
    #                                   applies it (lr/momentum ride
    #                                   --server_lr/--server_momentum;
    #                                   fedac knobs ride --fedac_*)
    server_adam_beta1: float = 0.9    # server_opt adam first moment
    server_adam_beta2: float = 0.999  # server_opt adam second moment
    server_adam_eps: float = 1e-8     # server_opt adam denominator floor
    adaptive: bool = False            # health-driven adaptive round
    #                                   controller (server_opt/
    #                                   controller.py): steer cohort /
    #                                   epochs / wave pacing from the
    #                                   PR 8 drift alarms; every decision
    #                                   named on the perf-ledger line.
    #                                   Requires --health
    adapt_min_cohort: int = 2         # adaptive: cohort backoff floor
    adapt_patience: int = 2           # adaptive: calm rounds before
    #                                   levers decay back to baseline

    # ---- algorithm extras ----------------------------------------------
    mu: float = 0.1                      # FedProx proximal term
    ditto_lambda: float = 0.1            # Ditto: personalization pull λ
    personal_lr: float = 0.0             # Ditto: 0 → inherit --lr
    personal_epochs: int = 0             # Ditto: 0 → inherit --epochs
    feddyn_alpha: float = 0.01           # FedDyn: dynamic-reg strength α
    fedac_mu: float = 0.0                # FedAC: >0 derives (γ,α,β)
    fedac_gamma: float = 0.0             # FedAC explicit knobs (0 → lr)
    fedac_alpha: float = 1.0
    fedac_beta: float = 1.0
    dp_clip: float = 1.0                 # dp_fedavg: per-user L2 bound S
    dp_noise_multiplier: float = 1.0     # dp_fedavg: z (std = S·z/m)
    dp_delta: float = 1e-5               # dp_fedavg: δ for reported ε
    dp_accounting: str = "fixed_size"    # dp_fedavg: fixed_size | poisson
    gmf: float = 0.0                     # FedNova global momentum factor
    norm_bound: float = 5.0              # robust: clip threshold
    stddev: float = 0.025                # robust: weak-DP noise
    defense: str = "weak_dp"             # robust: clip/weak_dp/none or a
    #                                      Byzantine rule (coordinate_median,
    #                                      trimmed_mean, krum, multi_krum,
    #                                      geometric_median)
    trim_frac: float = 0.1               # trimmed_mean: cut per side
    byz_f: int = 0                       # krum: assumed Byzantine count
    krum_m: int = 1                      # multi_krum: updates averaged
    gm_iters: int = 8                    # geometric_median: Weiszfeld steps
    gm_eps: float = 1e-6                 # geometric_median: smoothing floor
    defense_backend: str = "xla"         # robust: "xla" | "pallas" (fused
    #                                      clip+noise+mean, core/pallas_agg)
    # robust: backdoor attack evaluation (poison_type pipeline,
    # FedAvgRobustAggregator.py:14-45, 270)
    backdoor: bool = False               # poison attacker shards + eval
    attacker_num: int = 1                # first K clients are attackers
    target_label: int = 9                # attack target ("truck" for cifar)
    poison_frac: float = 1.0             # fraction of attacker shard stamped
    trigger_size: int = 3                # pixel-trigger side length
    group_num: int = 2                   # hierarchical / turboaggregate
    group_comm_round: int = 2            # hierarchical
    drop_tolerance: int = 1              # turboaggregate
    secagg_backend: str = "xla"          # turboaggregate: "xla" | "pallas"
    neighbor_num: int = 2                # decentralized topology
    # cross-silo actor mode (distributed FedAvg over host transports;
    # reference: run_fedavg_distributed_pytorch.sh + grpc_ipconfig.csv)
    silo_backend: str = "local"          # "local" (in-process hub) | "grpc"
    node_id: int = 0                     # grpc: 0=server, 1..N=silos
    ip_config: str = ""                  # grpc: rank→IP csv (reference fmt)
    base_port: int = 50000               # grpc: port = base_port + node_id
    grpc_max_message_mb: int = 1000      # grpc: per-message size cap (sends
    #                                      warn loudly at 80% of it instead
    #                                      of a bare RESOURCE_EXHAUSTED)
    grpc_workers: int = 4                # grpc: inbound RPC thread pool —
    #                                      raise with the cohort on the
    #                                      server node
    straggler_policy: str = "wait"       # wait | drop | abort
    round_timeout_s: float = 0.0         # 0 = no straggler timer
    min_silo_frac: float = 0.5           # drop-policy quorum
    # decentralized online learning (standalone/decentralized main_dol.py)
    mode: str = "DOL"                    # "DOL" | "PUSHSUM" | "LOCAL"
    iteration_number: int = 100          # stream length T per client
    beta: float = 0.0                    # adversarial (kmeans) stream frac
    b_symmetric: bool = False            # undirected vs directed topology
    topology_neighbors_num_undirected: int = 4
    topology_neighbors_num_directed: int = 4
    time_varying: bool = False           # regenerate graph each iteration
    temperature: float = 3.0             # FedGKT KD temperature
    lambda_l1: float = 0.0               # AsDGan G reconstruction L1 term
    lambda_perceptual: float = 0.0       # AsDGan G VGG-feature term
    fednas_layers: int = 3               # DARTS search depth
    fednas_channels: int = 8             # DARTS init channels
    fednas_steps: int = 2                # DARTS cell steps

    # ---- TPU placement (replaces gpu_mapping / mpirun) -----------------
    mesh_clients: int = 0     # >0: shard the cohort over this many devices
    mesh_groups: int = 0      # >0 (hierarchical): [groups, clients] mesh
    mesh_sequence: int = 0    # >0 (fedavg + transformer): dp x sp
    #                           [clients, sequence] mesh with ring attention
    mesh_stages: int = 0      # >0 (cross_silo + transformer): silo-local
    #                           pipeline parallelism — transformer blocks
    #                           over this many stage devices (GPipe,
    #                           parallel/pipeline.py); composes with
    #                           --moe_experts (balance loss rides the
    #                           schedule's scan carry)
    pp_microbatches: int = 0  # GPipe microbatches (0 = mesh_stages)
    client_axis: str = "vmap"  # cohort engine: "vmap" (concurrent
    #                            clients, grouped convs) | "scan"
    #                            (sequential clients, dense convs) —
    #                            identical results, hardware-empirical
    #                            choice (bench BENCH_R56 grid)
    eval_chunk_clients: int = 1024  # evaluate_global clients per compiled
    #                                 call; bounds eval memory on large
    #                                 corpora (0 = one-shot vmap)
    attn_block_size: int = 0  # >0 (transformer): flash-style kv blocking —
    #                           O(T*block) attention memory for single-chip
    #                           train/eval at long context
    attn_flash: bool = False  # transformer: TPU pallas flash-attention
    #                           kernel (fails loudly off-TPU)
    moe_experts: int = 0      # >0 (transformer): Switch MoE FFN with this
    #                           many experts (models/moe.py); expert tables
    #                           are ep-shardable (parallel/expert.py)
    silo_idle_timeout_s: float = 0.0  # grpc silos: exit after this long
    #                                   with no traffic (0 = wait forever)
    # ---- fault tolerance (comm/resilient.py + cross_silo health) -------
    heartbeat_s: float = 0.0          # >0: silos send liveness beats at
    #                                   this interval (threaded/grpc modes)
    dead_after_s: float = 0.0         # >0: server failure detector — silos
    #                                   unheard this long are DEAD and
    #                                   excluded from the round quorum
    suspect_after_s: float = 0.0      # detector SUSPECT threshold
    #                                   (0 = dead_after_s / 2)
    retask_timeout_s: float = 0.0     # async_fl: re-task silos quiet this
    #                                   long (liveness under upload loss)
    silo_retries: int = 0             # >0: wrap the wire transport in
    #                                   ResilientTransport with this many
    #                                   send attempts (backoff + jitter +
    #                                   reconnect between attempts)
    # ---- sustained degradation (fedml_tpu/robust/degrade.py, ISSUE 19) -
    min_quorum: float = 0.0           # >0: quorum-aware closure — the
    #                                   deadline may close the round only
    #                                   once ceil(frac*expected) silos
    #                                   folded (raises the drop-policy
    #                                   quorum, never lowers it); needs
    #                                   --straggler_policy drop
    adaptive_deadline: bool = False   # arm the straggler timer from the
    #                                   observed per-silo completion
    #                                   quantile (p90 * slack) instead of
    #                                   the static --round_timeout_s
    #                                   (which stays the ceiling and the
    #                                   cold-start fallback)
    deadline_floor_s: float = 0.5     # adaptive deadline lower clamp
    deadline_quantile: float = 0.9    # completion quantile the deadline
    #                                   derives from
    deadline_slack: float = 1.5       # deadline = quantile * slack
    partition_frac: float = 0.0       # >0: a deadline miss of at least
    #                                   this cohort fraction WITH network
    #                                   evidence (dead-letters / detector
    #                                   suspects) HOLDS the round instead
    #                                   of folding a minority view
    partition_max_holds: int = 3      # holds before the round abandons
    #                                   loudly (global unchanged)
    wire_compression: str = "none"    # cross_silo uploads: none|topk|int8
    topk_frac: float = 0.1            # topk: fraction of entries kept
    error_feedback: bool = False      # carry the compression residual into
    #                                   the next round's delta (EF-SGD style;
    #                                   silo-local state, so gRPC silos must
    #                                   be persistent processes — they are)
    # ---- payload defense (fedml_tpu/robust: admission + defended agg) --
    robust_agg: str = "mean"          # cross_silo/async_fl LIVE aggregation
    #                                   rule: mean | coordinate_median |
    #                                   trimmed_mean | krum | multi_krum |
    #                                   geometric_median (rule knobs ride
    #                                   --trim_frac/--byz_f/--krum_m/
    #                                   --gm_iters/--gm_eps)
    norm_clip: float = 0.0            # >0: clip each upload's update norm
    #                                   (reference RobustAggregator parity)
    agg_noise_std: float = 0.0        # >0: weak-DP noise on the defended
    #                                   aggregate (reference parity)
    admission: str = "auto"           # upload admission screen: auto (on
    #                                   whenever any defense flag is set,
    #                                   or under --chaos_corrupt — an
    #                                   unscreened corrupted frame can
    #                                   crash the decoder) | on | off
    max_num_samples: float = 1e6      # admission: cap on the self-reported
    #                                   sample count (0 = uncapped)
    norm_screen_k: float = 6.0        # admission: reject norms beyond
    #                                   median + k * MAD of recent accepts
    norm_screen_window: int = 64      # admission: rolling norm history
    norm_screen_min_history: int = 8  # admission: norms banked before the
    #                                   outlier screen arms
    strikes_to_quarantine: int = 3    # TrustTracker: strikes before
    #                                   quarantine
    quarantine_rounds: int = 4        # TrustTracker: rounds served before
    #                                   probation
    probation_rounds: int = 2         # TrustTracker: clean rounds to
    #                                   restore full trust
    # ---- streaming aggregation (core/stream_agg.py, ROADMAP item 2) ----
    agg_mode: str = "stack"           # cross_silo/async_fl aggregation
    #                                   memory regime: stack (the
    #                                   [cohort,...] staged buffer — exact
    #                                   reference semantics, RSS linear in
    #                                   cohort) | stream (fold each
    #                                   admitted upload at arrival —
    #                                   O(model) state, RSS flat in
    #                                   cohort; mean is bit-identical to
    #                                   stack's DEFENDED-mean path; an
    #                                   undefended stack run differs in
    #                                   last-ulp summation order (sync)
    #                                   or per-delta staleness discounts
    #                                   (async) — README "Streaming
    #                                   aggregation"; robust rules see a
    #                                   bounded reservoir sample)
    stream_reservoir: int = 64        # stream + a robust rule: reservoir
    #                                   slots the rule sees at finalize
    #                                   (size to the adversary count, not
    #                                   the cohort; exact when cohort<=K)
    # ---- sharded global-model spine (fedml_tpu/shard_spine) ------------
    model_shards: int = 0             # >0 (cross_silo + --agg_mode
    #                                   stream): lay the global model
    #                                   out as S shards — broadcast and
    #                                   uploads ship per-shard slices
    #                                   (one encode per shard, screened
    #                                   per shard), the streaming fold
    #                                   state itself is sharded (each
    #                                   shard's accumulator is
    #                                   O(model/S), on its own device
    #                                   when >= S devices exist), and
    #                                   the defended finalize runs per
    #                                   shard.  1 = the sharded
    #                                   machinery with one shard
    #                                   (bit-identical to the
    #                                   replicated path — the parity
    #                                   pin); 0 = off
    fused_finalize: str = "auto"      # shard finalize backend: auto
    #                                   (fused Pallas kernel on TPU,
    #                                   XLA compose on CPU) | on (force
    #                                   the kernel; interpret mode off-
    #                                   TPU — the parity/proof mode) |
    #                                   off (XLA compose everywhere).
    #                                   One kernel launch per shard:
    #                                   division + weak-DP noise fused
    #                                   (sigma=0 bit-identical to XLA
    #                                   for f32 models).  Requires
    #                                   --model_shards >= 1
    edge_aggregators: int = 0         # >0: multi-level topology — this
    #                                   many EdgeAggregatorActor tiers
    #                                   between silos and the root; each
    #                                   edge folds its silos locally and
    #                                   ships ONE pre-reduced update per
    #                                   round (cross_silo local backend)
    # ---- zero-copy pipelined ingest (comm/ingest.py, ISSUE 20) ---------
    ingest_pipeline: bool = False     # opt-in receive path: the
    #                                   transport thread only validates
    #                                   frame headers and enqueues; one
    #                                   fold worker per shard runs
    #                                   decode → screen → fold in
    #                                   arrival order (bit-identical to
    #                                   the inline path).  cross_silo /
    #                                   async_fl servers and the
    #                                   cross_device wave loop; requires
    #                                   --agg_mode stream on the actor
    #                                   paths and refuses unproven
    #                                   combinations loudly (--wire_
    #                                   compression, grpc backend,
    #                                   --edge_aggregators, faultline)
    ingest_queue_depth: int = 64      # bounded per-shard ingest queue
    #                                   depth; overflow dead-letters
    #                                   through the degradation fault
    #                                   feed as a NETWORK fault — never
    #                                   a trust strike, never silent
    # ---- secure aggregation (secure/protocol.py, ROADMAP item 3) -------
    secagg: str = "off"               # cross_silo live secure aggregation:
    #                                   off | pairwise (one masking group =
    #                                   the whole cohort) | grouped
    #                                   (masking scoped per edge block —
    #                                   requires --edge_aggregators;
    #                                   TurboAggregate's grouped scheme,
    #                                   mask-agreement traffic O(N^2/E)).
    #                                   Uploads are quantized into the
    #                                   uint32 ring and pairwise+self
    #                                   masked; the server learns only the
    #                                   cohort sum.  Dropouts recover via
    #                                   t-of-N Shamir shares (unmask phase
    #                                   at barrier close).  Requires
    #                                   --agg_mode stream (the masked fold
    #                                   is ring addition at arrival; there
    #                                   is no stack path).
    secagg_threshold: int = 0         # t of t-of-N Shamir: shares needed
    #                                   to reconstruct a seed — the round
    #                                   survives up to N-t dropouts and
    #                                   fails LOUDLY beyond.  0 = majority
    #                                   (N//2+1, min 2)
    secagg_clip: float = 64.0         # per-coordinate clip before ring
    #                                   quantization; the fixed-point
    #                                   scale auto-derives from the group
    #                                   size so the cohort sum cannot
    #                                   wrap uint32
    adversary: str = ""               # seeded per-silo attacks over the
    #                                   real message path, e.g.
    #                                   "2:scale:20,3:sign_flip" (kinds:
    #                                   sign_flip scale gauss nan_bomb
    #                                   inflate backdoor)
    # ---- cross-device mega-cohort engine (algorithms/cross_device.py) --
    cross_device: bool = False        # train the round as compiled client
    #                                   WAVES (vmap single-chip, shard_map
    #                                   on a --mesh_clients mesh) with each
    #                                   wave's stacked updates folded
    #                                   device-side into the streaming
    #                                   spine at wave completion — 1k-100k
    #                                   sampled clients per round at
    #                                   O(model) server memory.  Shorthand
    #                                   for --algo cross_device (both
    #                                   spellings work; combining it with
    #                                   any other --algo fails loudly)
    wave_size: int = 0                # clients per compiled wave (static
    #                                   shape; last wave pads with
    #                                   weight-0 slots).  0 = auto:
    #                                   min(cohort, 256) rounded up to a
    #                                   mesh-axis multiple
    local_alg: str = "sgd"            # per-client trainer inside the
    #                                   compiled wave: sgd | fedprox
    #                                   (--mu) | scaffold (host-stacked
    #                                   control variates) | fednova
    #                                   (normalized averaging)
    sampler: str = "numpy"            # cross_device cohort sampler:
    #                                   numpy (reference-bit-exact
    #                                   RandomState chain — the baseline-
    #                                   comparable default) | jax (on-
    #                                   device permutation).  THE TWO
    #                                   DIVERGE; the choice is recorded
    #                                   in every metrics.jsonl row so
    #                                   curves are never silently
    #                                   cross-compared
    async_goal: int = 0               # async_fl: aggregate every K uploads
    #                                   (0 = n_silos // 2, FedBuff style)
    staleness_exponent: float = 0.5   # async_fl: (1+s)^-alpha discount
    async_server_lr: float = 1.0      # async_fl: server step on the mean
    completion_signal: str = ""       # write the final summary line here on
    #                                   completion (FIFO or file; parity with
    #                                   the reference's ./tmp/fedml pipe)
    platform: Optional[str] = None       # force jax platform (e.g. "cpu")
    host_device_count: int = 0           # virtual CPU devices (simulation)
    coordinator_address: Optional[str] = None  # multi-host bootstrap
    num_processes: int = 1
    process_id: int = 0

    # ---- observability (obs/ subsystem) --------------------------------
    run_dir: Optional[str] = None        # metrics.jsonl + summary.json here
    metrics_dir: Optional[str] = None    # alias for --run_dir (obs naming;
    #                                      wins when both are given)
    profile_dir: Optional[str] = None    # jax.profiler trace dir (XLA)
    trace_dir: Optional[str] = None      # distributed round spans land here
    #                                      (Perfetto trace_event JSON, one
    #                                      file per process; stitch with
    #                                      scripts/obs_report.py)
    telemetry: bool = False              # enable the counter/gauge/histogram
    #                                      registry; snapshot written to
    #                                      run_dir/telemetry.{json,prom}
    prom_port: int = 0                   # >0: serve live Prometheus text at
    #                                      :port/metrics (implies telemetry)
    metrics_port: int = 0                # alias for --prom_port (obs naming;
    #                                      setting BOTH to different ports is
    #                                      a config error, not a silent pick)
    perf: bool = False                   # performance flight recorder
    #                                      (obs/perf.py): one perf.jsonl
    #                                      ledger line per round/version —
    #                                      phase wall-times, wire bytes,
    #                                      peak host RSS, recompile sentry
    #                                      (cross_silo / async_fl server)
    perf_ledger: Optional[str] = None    # explicit ledger path (implies
    #                                      --perf; default run_dir/perf.jsonl)
    perf_strict: bool = False            # recompile sentry raises
    #                                      RecompileError instead of
    #                                      warning — the test/CI mode that
    #                                      makes a retracing hot function
    #                                      fail the run loudly (implies
    #                                      --perf)
    device_obs: bool = False             # device & compile observatory
    #                                      (obs/device.py): extend every
    #                                      perf.jsonl line with a device
    #                                      section — per-device memory
    #                                      watermarks (memory_stats, or
    #                                      the live-arrays CPU fallback),
    #                                      a named compile ledger (wall
    #                                      time per jit cache entry, and
    #                                      recompile warnings name the
    #                                      arg shape that changed), and
    #                                      an honest MFU gauge from XLA
    #                                      cost analysis (implies --perf;
    #                                      costs one extra cost-analysis
    #                                      compile per NEW jit cache
    #                                      entry, off the steady path)
    slo: str = ""                        # SLO threshold overrides for the
    #                                      serve deep health check, e.g.
    #                                      "round_duration_p95_seconds=10,
    #                                      serve_shed_rate=0.01" (names:
    #                                      obs/perf.DEFAULT_SLOS; includes
    #                                      the health_* drift-alarm
    #                                      thresholds of obs/health.py
    #                                      and the device-memory headroom
    #                                      objective of obs/device.py)
    health: bool = False                 # federation health observatory
    #                                      (obs/health.py): streaming
    #                                      per-round learning-health stats
    #                                      on the receive path — update-
    #                                      norm Welford moments, cosine
    #                                      alignment, per-silo fairness,
    #                                      drift alarms, one health.jsonl
    #                                      line per round/version
    #                                      (cross_silo / async_fl server)
    health_ledger: Optional[str] = None  # explicit health ledger path
    #                                      (implies --health; default
    #                                      run_dir/health.jsonl)
    log_stdout: bool = True
    # ---- chaos injection (comm/chaos.py over the local silo backend) ---
    # seeded per-message fault probabilities for --algo cross_silo
    # --silo_backend local; any non-zero value switches the local hub to
    # the threaded drive (delayed frames arrive on wall-clock timers)
    chaos_drop: float = 0.0              # drop prob (needs --straggler_policy
    #                                      drop + --round_timeout_s)
    chaos_delay: float = 0.0             # delay prob
    chaos_max_delay_s: float = 0.05      # delay bound (also reorder flush)
    chaos_dup: float = 0.0               # duplicate prob
    chaos_reorder: float = 0.0           # reorder (hold-back) prob
    chaos_corrupt: float = 0.0           # payload corruption prob (seeded
    #                                      bit-flip/NaN into model_params —
    #                                      the admission screen's sparring
    #                                      partner)
    chaos_seed: int = 0                  # fault-schedule seed

    # ---- crash consistency (utils/journal.py + robust/faultline.py) ----
    journal: bool = False            # durable round journal on the
    #                                  streaming-fold receive path: per-
    #                                  accept records appended crash-safe
    #                                  + periodic atomic fold-state
    #                                  snapshots, so a server killed
    #                                  MID-ROUND resumes the same round
    #                                  and re-tasks only silos whose
    #                                  uploads were not durably folded
    #                                  (bit-identical resume on the
    #                                  defended-mean stream path; secagg
    #                                  rounds are abort-only).  Requires
    #                                  --agg_mode stream (or --secagg);
    #                                  pair with --checkpoint_every 1 for
    #                                  mid-round recovery to engage
    journal_dir: Optional[str] = None  # explicit journal directory
    #                                  (implies --journal; default
    #                                  run_dir/journal; edges get
    #                                  journal/edge{e} subdirs)
    journal_snapshot_every: int = 4  # fold-state snapshot cadence in
    #                                  accepted folds (1 = every fold
    #                                  durable — tightest recovery window
    #                                  at one O(model) write per upload)

    # ---- checkpoint / resume (orbax round-level, SURVEY §5.4) ----------
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 10
    checkpoint_async: bool = False  # background orbax saves (training
    #                                 never blocks on I/O; durable at the
    #                                 next save/flush/close/read)
    checkpoint_keep_last_n: int = 0  # >0: retention GC — only the newest
    #                                  N round dirs survive (serve-while-
    #                                  train runs must not fill the disk
    #                                  the serving registry watches);
    #                                  0 = the checkpointer default (3)

    # ---- serving (fedml_tpu/serve: registry + batcher + HTTP frontend) -
    serve_port: int = 0             # >0 (cross_silo): serve the global
    #                                 model over HTTP while training —
    #                                 /predict /healthz /version /metrics
    serve_buckets: str = "1,2,4,8,16,32"  # micro-batch shape buckets
    #                                 (comma ints, strictly increasing;
    #                                 one jit compile per bucket)
    serve_deadline_ms: float = 50.0  # default per-request deadline; a
    #                                 request that waits this out in the
    #                                 queue is shed (429), not served late
    serve_queue_depth: int = 256    # admission control: submits beyond
    #                                 this many queued requests get 429
    serve_batch_delay_ms: float = 2.0  # micro-batch flush deadline: how
    #                                 long the oldest queued request may
    #                                 wait for batchmates
    serve_workers: int = 1          # >1: the multi-worker frontend
    #                                 (serve/pool.py) — N SO_REUSEPORT
    #                                 accept loops, each its own micro-
    #                                 batcher, over ONE shared registry;
    #                                 1 = the single ThreadingHTTPServer
    serve_best_effort_headroom: float = 0.5  # fraction of the queue
    #                                 depth best-effort requests may
    #                                 fill; past it (or while any SLO is
    #                                 breaching) best_effort sheds and
    #                                 interactive keeps the reserve

    # ---- release gate (fedml_tpu/serve/release: canary → promote) ------
    release_gate: bool = False      # gate every published global behind
    #                                 the canary release controller:
    #                                 shadow divergence + health alarms +
    #                                 held-out eval must all pass before
    #                                 the serving swap (requires
    #                                 --serve_port)
    release_shadow_every: int = 16  # shadow sampler: capture every Nth
    #                                 admitted /predict instance
    release_shadow_slots: int = 64  # shadow ring size (newest N kept)
    release_divergence_budget: float = 0.1  # max fraction of shadow rows
    #                                 where canary disagrees with live
    release_eval_tolerance: float = 0.02  # held-out eval may regress at
    #                                 most this much vs the last promoted
    release_cooldown_s: float = 5.0  # refuse new canaries this long
    #                                 after a rollback...
    release_backoff: float = 2.0    # ...growing exponentially per
    #                                 consecutive failure...
    release_max_cooldown_s: float = 60.0  # ...capped here
    wave_adversary: str = ""        # cross_device only: seeded poisoned
    #                                 wave summaries, injected pre-
    #                                 admission — "round:wave:kind[:param]"
    #                                 comma list (robust/adversary)


def build_parser() -> argparse.ArgumentParser:
    """Argparse surface generated from the dataclass — one flag per field,
    same names as the reference where a reference flag exists."""
    p = argparse.ArgumentParser(
        prog="python -m fedml_tpu",
        description="TPU-native federated learning experiments")
    for f in dataclasses.fields(ExperimentConfig):
        name = "--" + f.name
        default = f.default
        if f.type in ("Optional[str]", Optional[str]):
            p.add_argument(name, type=str, default=default)
        elif isinstance(default, bool):
            p.add_argument(name, type=lambda s: s.lower() in ("1", "true"),
                           default=default)
        elif isinstance(default, int):
            p.add_argument(name, type=int, default=default)
        elif isinstance(default, float):
            p.add_argument(name, type=float, default=default)
        else:
            p.add_argument(name, type=str, default=default)
    return p


def config_from_argv(argv=None) -> ExperimentConfig:
    args = build_parser().parse_args(argv)
    return ExperimentConfig(**vars(args))
