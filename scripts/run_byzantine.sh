#!/usr/bin/env bash
# Live-path Byzantine defense demo (ISSUE 4 acceptance): 1 attacker among
# 4 silos over the real local transport, three arms —
#
#   1. clean        — no attacker, plain mean (the reference trajectory);
#   2. undefended   — silo 2 runs a x50 scale attack, plain mean: the
#                     final eval loss demonstrably degrades;
#   3. defended     — same attack, --robust_agg trimmed_mean + the
#                     admission pipeline: final loss back within 10% of
#                     clean, the attacker ends QUARANTINED, and the
#                     telemetry accounts for every rejected upload.
#
# Usage: scripts/run_byzantine.sh [workdir]  (default: a fresh mktemp dir)
set -euo pipefail
cd "$(dirname "$0")/.."

DIR="${1:-$(mktemp -d /tmp/fedml_byzantine.XXXXXX)}"
mkdir -p "$DIR"
echo "== byzantine demo: artifacts under $DIR"

BASE=(--algo cross_silo --model lr --dataset mnist
      --client_num_in_total 4 --client_num_per_round 4 --comm_round 6
      --frequency_of_the_test 6 --batch_size 4 --log_stdout false)
ATTACK=(--adversary "2:scale:50")
DEFENSE=(--robust_agg trimmed_mean --trim_frac 0.3
         --norm_screen_min_history 3 --strikes_to_quarantine 2)

env JAX_PLATFORMS=cpu python -m fedml_tpu "${BASE[@]}" \
    --run_dir "$DIR/clean" > "$DIR/clean.json"
env JAX_PLATFORMS=cpu python -m fedml_tpu "${BASE[@]}" "${ATTACK[@]}" \
    --run_dir "$DIR/undefended" > "$DIR/undefended.json"
env JAX_PLATFORMS=cpu python -m fedml_tpu "${BASE[@]}" "${ATTACK[@]}" \
    "${DEFENSE[@]}" --telemetry true \
    --run_dir "$DIR/defended" > "$DIR/defended.json"

echo "== asserting the three-arm comparison + quarantine telemetry"
python - "$DIR" <<'EOF'
import json, sys
d = sys.argv[1]
loss = {arm: json.load(open(f"{d}/{arm}.json"))["test_loss"]
        for arm in ("clean", "undefended", "defended")}
print("final test_loss:", {k: round(v, 4) for k, v in loss.items()})
assert loss["undefended"] > loss["clean"] * 1.01, (
    "the scale attack failed to degrade the undefended mean")
assert loss["defended"] <= loss["clean"] * 1.10, (
    "the defended run strayed >10% from the clean trajectory")
tel = json.load(open(f"{d}/defended/telemetry.json"))
rejected = {k: v for k, v in tel["counters"].items()
            if k.startswith("fedml_robust_rejected_total")}
assert sum(rejected.values()) >= 1, "no upload was ever rejected"
assert tel["counters"]["fedml_robust_quarantine_events_total"] >= 1, (
    "the attacker was never quarantined")
assert tel["gauges"]["fedml_robust_quarantined_total"] >= 1, (
    "the attacker did not END the run quarantined")
print("rejections by reason:", rejected)
print("quarantine events:",
      tel["counters"]["fedml_robust_quarantine_events_total"])
EOF
echo "== byzantine demo OK ($DIR)"
