"""Straggler CHAOS via the first-class injection layer (comm/chaos.py):
seeded drops, delays, duplicates, and partitions against the cross-silo
drop policy and the async (FedBuff) server — liveness and progress must
survive every seed (VERDICT r3 item 7).

The reference's only straggler story is a barrier that hangs until
MPI.Abort (FedAvgServerManager.py:51, server_manager.py:64); these tests
assert the opposite contract: with seeded adversarial networking —
lossy/delayed/duplicated frames, silos partitioned away mid-federation —
the server still closes every round (drop policy) or version (async),
never wedges, and the surviving quorum's updates are the ones
aggregated.  Faults are injected by wrapping each actor's transport in a
`ChaosTransport`; the actors themselves are UNMODIFIED production code
(the original ad-hoc ``_ChaoticClientActor`` subclass is gone).

Determinism note: each case is seeded; 20 seeds per policy.  One silo is
immortal by construction (its links carry a quiet plan) — with EVERY
silo dead no quorum policy can terminate (that is the abort policy's
job, tested in test_comm.py).
"""

import threading

import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (
    FailureDetector, FedAvgClientActor, FedAvgServerActor, MsgType)
from fedml_tpu.comm.chaos import (ChaosPlan, ChaosTransport, LinkChaos,
                                  Partition)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message


def _params_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


def _add_train_fn(delta):
    def fn(params, client_idx, round_idx):
        import jax
        return jax.tree.map(lambda v: v + delta, params), 10
    return fn


def _run_federation(server, actors, timeout_s=30.0):
    threads = [threading.Thread(target=a.run, daemon=True) for a in actors]
    for th in threads:
        th.start()
    server.register_handlers()
    server.start()
    done = threading.Event()

    def _serve():
        server.transport.run()
        done.set()

    st = threading.Thread(target=_serve, daemon=True)
    st.start()
    # LIVENESS: the server loop must terminate on its own
    assert done.wait(timeout_s), "server wedged: FINISH never reached"
    for th in threads:
        th.join(timeout=5)


def _chaotic_silo_plan(seed, silo, death_round=None, window=None):
    """Fault schedule for one silo's transport: lossy/delayed/duplicated
    uplink, plus an optional death partition (everything the silo sends
    for rounds >= death_round is cut) and an optional wall-clock window
    partition (the mid-round network split)."""
    partition = (Partition(after_round=death_round, window_s=window)
                 if death_round is not None or window is not None else None)
    uplink = LinkChaos(drop_prob=0.12, delay_prob=0.3, max_delay_s=0.07,
                       dup_prob=0.1, reorder_prob=0.1, partition=partition)
    return ChaosPlan(seed=seed * 977 + silo,
                     links={(silo, 0): uplink},
                     immune_types=(MsgType.S2C_FINISH,))


def _chaotic_server_plan(seed, faulted_silos):
    """Downlink faults (sync broadcasts) toward the non-immortal silos.
    FINISH is immune: shutdown liveness is the transport layer's job
    (ResilientTransport), not the chaos suite's."""
    down = LinkChaos(drop_prob=0.08, delay_prob=0.2, max_delay_s=0.05,
                     dup_prob=0.08)
    return ChaosPlan(seed=seed * 31 + 7,
                     links={(0, s): down for s in faulted_silos},
                     immune_types=(MsgType.S2C_FINISH,))


@pytest.mark.parametrize("seed", range(20))
def test_chaos_drop_policy_survives_faulty_network(seed):
    """4 silos behind chaotic links (drops, delays, duplicates, reorders,
    a mid-run wall-clock partition, up to 2 death partitions at random
    rounds): every round still closes under the drop policy, the run
    never aborts, and the aggregate ends exactly at init + sum(per-round
    survivor-mean deltas) replayed from the server's own drop log."""
    rng = np.random.RandomState(1000 + seed)
    n_silos, n_rounds = 4, 4
    hub = LocalHub()
    init = _params_tree(seed)

    deaths = {}  # silo id -> death round
    dying = rng.choice(np.arange(2, n_silos + 1), size=2, replace=False)
    for silo in dying:
        if rng.rand() < 0.7:  # not every chosen silo actually dies
            deaths[int(silo)] = int(rng.randint(0, n_rounds))
    # silo 2 additionally suffers a transient mid-round partition window
    # (unless it is already dying — then the death partition dominates)
    windows = {2: (0.18, 0.45)}

    completed = []
    detector = FailureDetector(suspect_after_s=0.3, dead_after_s=0.6)
    server = FedAvgServerActor(
        ChaosTransport(hub.transport(0),
                       _chaotic_server_plan(seed, range(2, n_silos + 1))),
        init, client_num_in_total=n_silos,
        client_num_per_round=n_silos, num_rounds=n_rounds,
        on_round_done=lambda r, p: completed.append(r),
        straggler_policy="drop", round_timeout_s=0.25, min_silo_frac=0.2,
        failure_detector=detector)
    transports = {1: hub.transport(1)}  # silo 1 immortal: clean links
    for i in range(2, n_silos + 1):
        transports[i] = ChaosTransport(
            hub.transport(i),
            _chaotic_silo_plan(seed, i, death_round=deaths.get(i),
                               window=windows.get(i)))
    actors = [
        FedAvgClientActor(i, transports[i], _add_train_fn(float(i)),
                          heartbeat_interval_s=0.04)
        for i in range(1, n_silos + 1)]

    _run_federation(server, actors)

    assert not server.aborted
    assert server.round_idx == n_rounds
    assert completed == list(range(n_rounds))
    # chaos must have actually happened on the faulted links
    total_faults = sum(sum(t.faults.values())
                       for t in transports.values()
                       if isinstance(t, ChaosTransport))
    assert total_faults > 0, "chaos plan injected nothing"
    # progress check: replay the expected aggregate from the server's own
    # drop log (survivors of round r = all silos minus dropped)
    expected = np.asarray(init["dense"]["kernel"], np.float64)
    for r in range(n_rounds):
        dropped = set(server.dropped_silos.get(r, []))
        survivors = [i for i in range(1, n_silos + 1) if i not in dropped]
        assert survivors, "quorum closed a round with zero uploads"
        expected = expected + np.mean([float(i) for i in survivors])
    # a dead silo must actually be in the drop log from its death round
    for silo, death in deaths.items():
        for r in range(death, n_rounds):
            assert silo in server.dropped_silos.get(r, []), \
                f"dead silo {silo} missing from round-{r} drop log"
    np.testing.assert_allclose(
        np.asarray(server.params["dense"]["kernel"], np.float64),
        expected, rtol=1e-5)


@pytest.mark.parametrize("seed", range(20))
def test_chaos_async_server_survives_faulty_network(seed):
    """FedBuff server under injected chaos: lossy/delayed/duplicated
    uplinks plus up to 1 death partition (of 3 silos, goal 2) — versions
    keep closing from whoever is alive (the re-task watchdog refills the
    rotation when uploads are lost), FINISH arrives, staleness stays
    plausible."""
    from fedml_tpu.algorithms.async_fl import (AsyncFedServerActor,
                                               delta_encoder)

    rng = np.random.RandomState(2000 + seed)
    n_silos, versions, goal = 3, 4, 2
    hub = LocalHub()
    init = _params_tree(seed)

    death = ({int(rng.randint(2, n_silos + 1)): int(rng.randint(0, 2))}
             if rng.rand() < 0.5 else {})
    server = AsyncFedServerActor(
        hub.transport(0), init, client_num_in_total=8, n_silos=n_silos,
        num_versions=versions, aggregation_goal=goal,
        staleness_exponent=0.5, seed=seed, retask_timeout_s=0.3)
    transports = {1: hub.transport(1)}  # immortal silo
    for i in range(2, n_silos + 1):
        transports[i] = ChaosTransport(
            hub.transport(i),
            _chaotic_silo_plan(seed, i, death_round=death.get(i)))
    actors = [FedAvgClientActor(i, transports[i], _add_train_fn(float(i)),
                                encode_upload=delta_encoder)
              for i in range(1, n_silos + 1)]

    _run_federation(server, actors)

    assert server.version == versions
    # every consumed version had `goal` distinct uploads; duplicates and
    # drops change how many uploads were SEEN, not the liveness contract
    assert len(server.staleness_seen) >= versions * goal
    assert all(s >= 0 for s in server.staleness_seen)
    # the aggregate must have moved off init and stayed finite
    k = np.asarray(server.params["dense"]["kernel"])
    assert np.isfinite(k).all()
    assert float(np.abs(k - init["dense"]["kernel"]).max()) > 0.1


def test_chaos_transport_is_deterministic_per_seed():
    """Two runs of the same seeded plan over the same message sequence
    make identical fault decisions (the injection layer's contract)."""
    def run_once():
        hub = LocalHub()
        sink = hub.transport(0)
        got = []

        class Collect:
            def receive_message(self, msg_type, msg):
                got.append(msg.get("n"))

        sink.add_observer(Collect())
        chaos = ChaosTransport(
            hub.transport(1),
            ChaosPlan(seed=7, links={(1, 0): LinkChaos(
                drop_prob=0.3, dup_prob=0.2)}))
        for n in range(50):
            chaos.send_message(Message("m", 1, 0).add("n", n))
        hub.pump()
        return got, dict(chaos.faults)

    got_a, faults_a = run_once()
    got_b, faults_b = run_once()
    assert got_a == got_b
    assert faults_a == faults_b
    assert faults_a["drop"] > 0 and faults_a["dup"] > 0


def test_chaos_partition_window_and_immunity():
    """A wall-clock partition cuts matching traffic; immune types pass."""
    hub = LocalHub()
    sink = hub.transport(0)
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg_type)

    sink.add_observer(Collect())
    plan = ChaosPlan(seed=0, links={(1, 0): LinkChaos(
        partition=Partition(window_s=(0.0, 1e9)))},
        immune_types=("finish",))
    chaos = ChaosTransport(hub.transport(1), plan)
    chaos.send_message(Message("data", 1, 0))
    chaos.send_message(Message("finish", 1, 0))
    hub.pump()
    assert got == ["finish"]
    assert chaos.faults["partition"] == 1


def test_chaos_round_partition_models_silo_death():
    """after_round cuts only messages tagged with a round >= the death
    round — the declarative form of the old _ChaoticClientActor."""
    hub = LocalHub()
    sink = hub.transport(0)
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg.get(Message.ARG_ROUND))

    sink.add_observer(Collect())
    chaos = ChaosTransport(
        hub.transport(1),
        ChaosPlan(links={(1, 0): LinkChaos(
            partition=Partition(after_round=2))}))
    for r in range(5):
        chaos.send_message(
            Message("up", 1, 0).add(Message.ARG_ROUND, r))
    hub.pump()
    assert got == [0, 1]
    assert chaos.faults["partition"] == 3


@pytest.mark.slow
def test_chaos_real_training_converges_under_drop():
    """End-to-end: 3-silo LR federation on synthetic data behind chaotic
    links (delays + one death partition) still LEARNS (loss decreases)
    under the drop policy — the convergence half of the chaos contract."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.data.synthetic import mnist_learnable_twin
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    data = mnist_learnable_twin(num_clients=3, class_num=4, dim=16,
                                batch_size=8, noise=0.5, seed=0)
    wl = ClassificationWorkload(LogisticRegression(16, 4), num_classes=4)
    local = make_local_trainer(wl, make_client_optimizer("sgd", 0.3),
                               epochs=2)
    one = jax.tree.map(lambda v: v[0, 0], {k: data.train[k]
                                           for k in ("x", "y", "mask")})
    init = wl.init(jax.random.key(0), one)

    def loss_of(params):
        logits = wl.apply(params, jnp.asarray(data.train["x"][0, 0]))
        import optax
        return float(optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray(data.train["y"][0, 0])).mean())

    def train_fn(silo):
        def fn(params, client_idx, round_idx):
            batches = jax.tree.map(
                lambda v: jnp.asarray(v[silo - 1]),
                {k: data.train[k] for k in ("x", "y", "mask")})
            new_params, _ = local(params, batches,
                                  jax.random.fold_in(jax.random.key(1),
                                                     round_idx))
            n = int(data.train["num_samples"][silo - 1])
            return new_params, n
        return fn

    hub = LocalHub()
    # 10 rounds (the seed version's 6 left the loss just short of the
    # 0.7*l0 bar even in the no-chaos limit — the budget was too tight,
    # not the robustness)
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=3,
        client_num_per_round=3, num_rounds=10,
        straggler_policy="drop", round_timeout_s=1.0, min_silo_frac=0.3)
    transports = {
        1: hub.transport(1),
        2: ChaosTransport(hub.transport(2), ChaosPlan(
            seed=2, links={(2, 0): LinkChaos(delay_prob=0.5,
                                             max_delay_s=0.05)},
            immune_types=(MsgType.S2C_FINISH,))),
        3: ChaosTransport(hub.transport(3), ChaosPlan(
            seed=3, links={(3, 0): LinkChaos(
                partition=Partition(after_round=3))},
            immune_types=(MsgType.S2C_FINISH,))),
    }
    actors = [FedAvgClientActor(i, transports[i], train_fn(i))
              for i in (1, 2, 3)]
    l0 = loss_of(init)
    _run_federation(server, actors, timeout_s=120.0)

    assert not server.aborted and server.round_idx == 10
    assert all(3 in server.dropped_silos.get(r, []) for r in range(3, 10))
    assert loss_of(server.params) < 0.7 * l0
