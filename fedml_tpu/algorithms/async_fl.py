"""Asynchronous buffered federated aggregation (FedBuff-style) — beyond
the reference.

The reference's server is a strict barrier: every sampled client must
report before aggregation (check_whether_all_receive,
FedAvgServerManager.py:51), so one straggler stalls the world and its
only escape is MPI.Abort.  Our cross-silo layer already softens that
with wait/drop/abort policies; this module removes the barrier entirely,
the Nguyen et al. 2022 (FedBuff) way:

* silos train CONTINUOUSLY: upload a delta, immediately receive the
  current global + a fresh client assignment, keep going;
* the server buffers deltas and aggregates every ``aggregation_goal``
  uploads — a "version" — applying each delta against the CURRENT global
  with a staleness discount ``(1 + s)^-alpha`` where ``s`` is how many
  versions elapsed since the silo's base model.  The discount is applied
  OUTSIDE the sample-weight normalization: mixing ratios come from raw
  ``num_samples`` (summing to 1), and each delta is then scaled by its
  own discount — so a buffer of uniformly stale deltas is damped
  absolutely (the FedBuff behavior), not just relatively.  At zero
  staleness every discount is 1 and the update is plain weighted FedAvg;
* with ``aggregation_goal = n_silos``, ``alpha`` irrelevant (zero
  staleness) and ``server_lr = 1`` the first version reduces EXACTLY to
  a synchronous FedAvg round (the parity oracle in
  tests/test_async_fl.py).

Deltas ride the existing client actor's ``encode_upload`` hook (the same
seam wire compression uses), so the client side is unchanged
FedAvgClientActor choreography — INIT/SYNC in, MODEL out.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from fedml_tpu.comm.actors import SelfMessageTimer, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.algorithms.cross_silo import MsgType
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

# server self-message from the re-task watchdog timer (value continues
# the MsgType numbering in algorithms/cross_silo.py)
MSG_RETASK_TICK = 7


def delta_encoder(new_params, global_params):
    """Client-side upload transform: send the UPDATE, not the weights —
    the async server applies it to whatever global is current."""
    return jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                        new_params, global_params)


class AsyncFedServerActor(ServerManager):
    """Barrier-free aggregator: buffer ``aggregation_goal`` deltas, apply
    with staleness discounts, re-task exactly the silos whose uploads
    were consumed.

    ``num_versions`` plays comm_round's role: total aggregations before
    FINISH.  ``on_version(version, params)`` is the eval hook."""

    def __init__(self, transport: Transport, init_params,
                 client_num_in_total: int, n_silos: int,
                 num_versions: int, aggregation_goal: int,
                 staleness_exponent: float = 0.5, server_lr: float = 1.0,
                 on_version: Optional[Callable[[int, object], None]] = None,
                 seed: int = 0, checkpointer=None,
                 retask_timeout_s: Optional[float] = None):
        """``checkpointer``: a `RoundCheckpointer`; every applied version
        is saved per its ``save_every`` gating and ``start()`` resumes
        from the latest saved version — a crashed async server restarts
        mid-federation instead of from version 0.

        ``retask_timeout_s``: liveness watchdog.  The FedBuff tasking
        rule re-tasks only the silos whose uploads were CONSUMED — if a
        silo's upload is lost on the wire, that silo falls out of
        rotation, and once fewer than ``aggregation_goal`` silos remain
        active the server wedges.  With a watchdog, any silo quiet for
        this long is re-tasked with a fresh assignment against the
        current global (a duplicate from a silo that was merely slow is
        handled by the at-most-once buffer guard)."""
        super().__init__(0, transport)
        if not 1 <= aggregation_goal <= n_silos:
            raise ValueError(
                f"aggregation_goal must be in [1, n_silos={n_silos}], "
                f"got {aggregation_goal}")
        self.params = init_params
        self.client_num_in_total = client_num_in_total
        self.n_silos = n_silos
        self.num_versions = num_versions
        self.goal = aggregation_goal
        self.alpha = staleness_exponent
        self.server_lr = server_lr
        self.on_version = on_version
        self.version = 0
        self.staleness_seen: List[int] = []  # per consumed upload
        self._buffer: List[Tuple[object, float, float, int]] = []
        self._task_rng = np.random.RandomState(seed)
        self.checkpointer = checkpointer
        self.retask_timeout_s = retask_timeout_s
        self._last_heard: Dict[int, float] = {}
        self._retask_timer = SelfMessageTimer()
        # (silo, base_version) pairs already aggregated — the at-most-once
        # guard must survive buffer flushes, not just scan the live buffer
        self._consumed: set = set()
        self._finished = False
        # version observability: inter-aggregation gap + per-upload
        # staleness (null no-ops when telemetry is disabled)
        reg = telemetry.get_registry()
        self._h_version = reg.histogram(
            "fedml_async_version_duration_seconds")
        self._h_staleness = reg.histogram(
            "fedml_async_staleness_total", buckets=(0, 1, 2, 4, 8, 16, 32))
        self._version_t0: Optional[float] = None

    def register_handlers(self) -> None:
        self.register_handler(MsgType.C2S_MODEL, self._on_model)
        self.register_handler(MSG_RETASK_TICK, self._on_retask_tick)

    # -- tasking -----------------------------------------------------------
    def start(self) -> None:
        """Initial tasking: version-0 assignments use the same seeded
        sampler as the synchronous paths, so goal == n_silos reduces to
        the FedAvg round-0 cohort.  With a ``checkpointer`` holding a
        saved version, the server resumes from it and re-tasks every
        silo against the restored global."""
        if self.checkpointer is not None:
            step = self.checkpointer.latest_round()
            if step is not None:
                state = self.checkpointer.restore(
                    step, like=self._checkpoint_state())
                self.params = state["params"]
                self.version = int(np.asarray(state["version"]))
                log.info("resumed from checkpoint: continuing at version "
                         "%d of %d", self.version, self.num_versions)
        if self.version >= self.num_versions:
            for silo in range(1, self.n_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
            return
        ids = sample_clients(0, self.client_num_in_total, self.n_silos)
        now = time.monotonic()
        self._version_t0 = now
        # one root span for the initial tasking wave, so version-0 silo
        # train/upload spans stitch into a single trace instead of N
        # disconnected fragments
        with self._root_span("tasking", f"version{self.version}",
                             version=self.version):
            for silo, client_idx in enumerate(ids, start=1):
                self._last_heard[silo] = now
                self._task(silo, int(client_idx), MsgType.S2C_INIT)
        self._arm_retask_timer()

    # -- liveness watchdog --------------------------------------------------
    def _arm_retask_timer(self) -> None:
        if self.retask_timeout_s is None:
            return
        # fire only ENQUEUES a self-message; the re-task scan runs on the
        # transport's event loop like every other handler
        self._retask_timer.arm(self.retask_timeout_s,
                               lambda: self.send(MSG_RETASK_TICK, 0))

    def _cancel_retask_timer(self, join: bool = False) -> None:
        self._retask_timer.cancel(join=join)

    def _on_retask_tick(self, msg: Message) -> None:
        if self.version >= self.num_versions:
            return
        now = time.monotonic()
        # a silo with an upload sitting in the buffer is waiting on the
        # version to close, not lost — re-tasking it would only produce a
        # duplicate the at-most-once guard rejects
        buffered = {s for _, _, _, s, _ in self._buffer}
        for silo in range(1, self.n_silos + 1):
            if silo in buffered:
                continue
            quiet = now - self._last_heard.get(silo, now)
            if quiet >= self.retask_timeout_s:
                log.warning("silo %d quiet for %.1fs; re-tasking against "
                            "version %d", silo, quiet, self.version)
                self._last_heard[silo] = now  # one nudge per timeout window
                # watchdog ticks are self-messages with no inbound trace
                # context — root each nudge so its train/upload stitch
                with self._root_span("retask",
                                     f"retask-v{self.version}-s{silo}",
                                     silo=silo, version=self.version):
                    self._task(silo, self._next_client())
        self._arm_retask_timer()

    def _task(self, silo: int, client_idx: int, msg_type=MsgType.S2C_SYNC):
        host_params = jax.tree.map(np.asarray, self.params)
        self.send(msg_type, silo,
                  **{Message.ARG_MODEL_PARAMS: host_params,
                     Message.ARG_CLIENT_INDEX: client_idx,
                     Message.ARG_ROUND: self.version})

    def _next_client(self) -> int:
        return int(self._task_rng.randint(self.client_num_in_total))

    def _checkpoint_state(self) -> dict:
        """Version-state pytree (fixed shapes — doubles as the orbax
        restore template)."""
        return {"params": jax.tree.map(np.asarray, self.params),
                "version": np.asarray(self.version, np.int64)}

    # -- aggregation -------------------------------------------------------
    def _on_model(self, msg: Message) -> None:
        self._last_heard[msg.sender_id] = time.monotonic()
        if self.version >= self.num_versions:
            return  # late upload after FINISH
        base_version = int(msg.get(Message.ARG_ROUND))
        if (msg.sender_id, base_version) in self._consumed or \
                any(s == msg.sender_id and b == base_version
                    for _, _, _, s, b in self._buffer):
            # at-most-once guard: a duplicated frame (lossy wire re-send,
            # chaos dup, or a watchdog re-task racing a slow upload) must
            # not count the same update twice — whether its first copy is
            # still buffered or was already aggregated into a version
            log.warning("ignoring duplicate version-%d upload from silo %d",
                        base_version, msg.sender_id)
            return
        delta = msg.get(Message.ARG_MODEL_PARAMS)
        num_samples = float(msg.get(Message.ARG_NUM_SAMPLES))
        staleness = self.version - base_version
        discount = float(1.0 + staleness) ** (-self.alpha)
        self.staleness_seen.append(staleness)
        self._h_staleness.observe(staleness)
        self._buffer.append(
            (delta, num_samples, discount, msg.sender_id, base_version))
        if len(self._buffer) >= self.goal:
            self._apply_buffer()

    def _apply_buffer(self) -> None:
        now = time.monotonic()
        if self._version_t0 is not None:
            self._h_version.observe(now - self._version_t0)
        self._version_t0 = now
        deltas = [d for d, _, _, _, _ in self._buffer]
        samples = np.asarray([n for _, n, _, _, _ in self._buffer],
                             np.float64)
        discounts = np.asarray([c for _, _, c, _, _ in self._buffer],
                               np.float64)
        # Sample ratios sum to 1; the staleness discount multiplies each
        # term afterwards so stale buffers shrink the applied step itself.
        coeffs = discounts * samples / max(samples.sum(), 1e-12)
        # traced as a child of whichever upload's handling tripped the
        # goal, so the async trace shows which silo closed each version
        with self._span("aggregate", version=self.version,
                        buffered=len(deltas)):
            mean = jax.tree.map(
                lambda *leaves: sum(c * np.asarray(l, np.float64)
                                    for c, l in zip(coeffs, leaves)),
                *deltas)
            self.params = jax.tree.map(
                lambda p, d: (np.asarray(p, np.float64)
                              + self.server_lr * d).astype(
                                  np.asarray(p).dtype),
                self.params, mean)
        silos = [s for _, _, _, s, _ in self._buffer]
        self._consumed.update((s, b) for _, _, _, s, b in self._buffer)
        self._buffer.clear()
        self.version += 1
        if self.checkpointer is not None:
            self.checkpointer.maybe_save(
                self.version - 1, self._checkpoint_state(),
                last_round=self.version >= self.num_versions)
        if self.on_version is not None:
            self.on_version(self.version, self.params)
        if self.version >= self.num_versions:
            for silo in range(1, self.n_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
            return
        for silo in silos:  # only the consumed silos need new work
            self._task(silo, self._next_client())

    def finish(self) -> None:
        self._finished = True
        self._cancel_retask_timer(join=True)
        super().finish()
