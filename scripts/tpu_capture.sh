#!/usr/bin/env bash
# Full TPU perf capture — run when the tunnel is alive and the machine is
# otherwise IDLE (concurrent work contaminates both the TPU timings and
# the torch CPU baseline; verify skill).  One command covers every
# pending measurement item.
#
# Round-4 hardening: the tunnel was observed to answer the liveness probe
# and then wedge on the first heavy compile RPC.  So (a) stages run
# most-valuable-first — the canonical f32 bench leads because its
# programs are in the persistent compile cache from the last clean run
# (cache hits avoid exactly the long compile RPCs that trigger wedges);
# (b) every stage runs under its own `timeout` and a failed stage skips
# forward instead of aborting the capture; (c) bench.py itself carries a
# stall watchdog that emits partial artifacts (see bench.py _WATCH).
#
# Stages:
#   1. canonical full f32 bench -> BENCH_DETAILS.json (the committed
#      artifact: honest FLOPs, device_kind, spreads, flash+moe T=2048;
#      bench.py now leads with its own timing-sanity gate — a failed gate
#      exits 3 and quarantines the artifact)
#   2. MNIST-LR published row   -> MNIST_LR_TPU.json (VERDICT r4 item 8:
#      a published accuracy row reproduced end-to-end on the chip;
#      LR compiles are trivial, so this is the lowest-wedge-risk stage)
#   3. bf16 comparison          -> BENCH_DETAILS_bf16.json (BENCH_OUT —
#      never clobbers the canonical artifact)
#   4. resnet56 investigation   -> BENCH_R56_SPREAD.json (timing-sanity
#      gate, then spread repeats, {vmap,scan} x {f32,bf16} grid, E=20
#      published-config row; written incrementally, cell by cell)
#   5. profiler traces          -> profiles/ (local only, gitignored)
#   6. flagship accuracy run    -> FLAGSHIP_CURVE.json (the published
#      resnet56 config end-to-end; longest stage, so it goes last)
set -uo pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python - <<'EOF'
import jax, jax.numpy as jnp
jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8)))
d = jax.devices()[0]
print("alive:", d.platform, getattr(d, "device_kind", "?"))
EOF
}

echo "== backend probe (90s watchdog) =="
probe || { echo "backend unreachable — aborting capture"; exit 1; }

# any measurement stage that fails or goes partial (bench exit 3, timeout
# 124) marks the whole capture incomplete — the final exit code is what
# tpu_watch.sh keys on to keep retrying instead of declaring COMPLETE
FAILED=0

echo "== 1/6 canonical full f32 bench (cache-warm; BENCH_DETAILS.json) =="
timeout 5400 env BENCH_MODE=full BENCH_STALL_S=1500 python bench.py \
  || { echo "stage 1 FAILED or partial (rc=$?) — see BENCH_DETAILS.json.partial"; FAILED=1; }

probe || { echo "tunnel wedged after stage 1 — stopping"; exit 2; }
echo "== 2/6 MNIST-LR published accuracy row on-chip (MNIST_LR_TPU.json) =="
timeout 3600 python scripts/mnist_lr_tpu.py \
  || { echo "stage 2 FAILED or partial (rc=$?) — see MNIST_LR_TPU.json.partial"; FAILED=1; }

probe || { echo "tunnel wedged after stage 2 — stopping"; exit 2; }
echo "== 3/6 bf16 comparison (BENCH_DETAILS_bf16.json) =="
timeout 3600 env BENCH_DTYPE=bfloat16 BENCH_SCALING=0 BENCH_STALL_S=1500 \
  BENCH_OUT=BENCH_DETAILS_bf16.json python bench.py \
  || { echo "stage 3 FAILED or partial (rc=$?)"; FAILED=1; }

probe || { echo "tunnel wedged after stage 3 — stopping"; exit 2; }
echo "== 4/6 resnet56 investigation: spreads + client-axis x dtype grid =="
timeout 3600 python - <<'EOF' || { echo "stage 4 FAILED or partial (rc=$?)"; FAILED=1; }
import json
import os
import sys
import jax
import bench

def save(out):
    with open("BENCH_R56_SPREAD.json", "w") as f:
        json.dump(out, f, indent=2)

# resolve the attached chip's peak once; _mfu reads this module global.
# The measured matmul rate floors it (device_kind is untrusted, bench.py)
# unless an explicit BENCH_PEAK_TFLOPS pins the denominator
bench.PEAK_TFLOPS = bench._peak_for_device(jax.devices()[0])
# timing trust gate first — bench.run_timing_gate is THE gate (sanity
# probe + retry + matmul-peak plausibility cap), shared with bench.main
# so the two cannot drift; an untrusted timer makes every grid cell
# fiction, so bail with the evidence on disk
sanity, mm, failures = bench.run_timing_gate()
if not os.environ.get("BENCH_PEAK_TFLOPS"):
    bench.PEAK_TFLOPS = max(bench.PEAK_TFLOPS, mm["bf16"])
out = {"spread_reps": [], "grid": {},
       "device_kind": jax.devices()[0].device_kind,
       "timing_sanity": sanity,
       "measured_matmul_tflops": mm,
       "peak_tflops": bench.PEAK_TFLOPS}
if failures:
    out["timing_untrusted"] = failures
    with open("BENCH_R56_SPREAD.json.untrusted", "w") as f:
        json.dump(out, f, indent=2)
    print("timing untrusted:", failures)
    sys.exit(3)
for rep in range(3):
    round_s, flops, steps, spread = bench.bench_resnet56_cifar10(8)
    out["spread_reps"].append(
        {"rep": rep, "round_s": round_s, "spread": spread,
         "step_time_ms": 1e3 * round_s / steps})
    print("rep", rep, out["spread_reps"][-1], flush=True)
    save(out)

# vmap lowers per-client conv kernels to grouped convs (MXU sliver per
# group at 16/32/64 channels); scan keeps dense convs.  Grid pins which
# engine + dtype the flagship should ship with, and the E=20 row scales
# the winner to the published config (benchmark/README.md:105).
for axis in ("vmap", "scan"):
    for dtype in ("", "bfloat16"):
        os.environ["BENCH_DTYPE"] = dtype
        round_s, flops, steps, spread = bench.bench_resnet56_cifar10(
            6, client_axis=axis)
        key = f"{axis}_{dtype or 'f32'}"
        out["grid"][key] = {
            "round_s": round_s, "steps": steps,
            "step_time_ms": 1e3 * round_s / steps,
            "mfu": bench._mfu(flops, round_s), "spread": spread}
        print(key, out["grid"][key], flush=True)
        save(out)
os.environ["BENCH_DTYPE"] = ""

# published-config row: E=20 with the winning engine
best = min(out["grid"], key=lambda k: out["grid"][k]["round_s"])
axis, dtype = best.rsplit("_", 1)
os.environ["BENCH_DTYPE"] = "" if dtype == "f32" else dtype
round_s, flops, steps, spread = bench.bench_resnet56_cifar10(
    3, epochs=20, client_axis=axis)
out["e20_published_config"] = {
    "engine": best, "round_s": round_s, "steps": steps,
    "step_time_ms": 1e3 * round_s / steps,
    "mfu": bench._mfu(flops, round_s), "spread": spread}
os.environ["BENCH_DTYPE"] = ""
print("E=20:", out["e20_published_config"], flush=True)
save(out)
print("wrote BENCH_R56_SPREAD.json")
EOF

probe || { echo "tunnel wedged after stage 4 — stopping"; exit 2; }
echo "== 5/6 profiler traces (resnet56 + shakespeare rounds) =="
for cfg in "resnet56 cifar10" "rnn shakespeare"; do
  set -- $cfg
  if ! timeout 1800 python -m fedml_tpu --algo fedavg --model "$1" \
      --dataset "$2" \
      --client_num_in_total 10 --client_num_per_round 10 --comm_round 3 \
      --batch_size 64 --frequency_of_the_test 3 --log_stdout false \
      --profile_dir "profiles/$1"; then
    echo "profiled $1 run FAILED — profiles/$1 is empty/partial"
    FAILED=1
  fi
done

probe || { echo "tunnel wedged after stage 5 — stopping"; exit 2; }
echo "== 6/6 flagship accuracy (published resnet56 config, longest) =="
timeout 14400 python scripts/flagship_accuracy.py \
  || { echo "stage 6 FAILED or partial (rc=$?) — see FLAGSHIP_CURVE.json.partial"; FAILED=1; }

if [ "$FAILED" -ne 0 ]; then
  echo "capture INCOMPLETE — at least one measurement stage failed or went"
  echo "partial; tpu_watch.sh will retry (completed stages rerun cache-warm)"
  exit 3
fi
echo "done — inspect BENCH_DETAILS.json / BENCH_DETAILS_bf16.json /"
echo "BENCH_R56_SPREAD.json / FLAGSHIP_CURVE.json + profiles/, then commit"
echo "the clean artifacts (profiles/ stays local — gitignored)."
