"""Pallas TPU kernel: fused quantize + pairwise-mask for secure aggregation.

The hot op of on-pod SecAgg (`fedml_tpu.secure.secagg`) is per-client
``quantize(weight * update) + Σ_j ±PRG(s_ij)``.  The XLA path materialises
N-1 leaf-sized threefry mask arrays per client and sums them — O(N·D) HBM
traffic per client just for masks.  This kernel does the whole thing in ONE
VMEM pass per block: load the f32 block once, quantize on the VPU, generate
each pair's mask stream with a counter-based in-kernel PRG (murmur3
finalizer over the global element index — no HBM temporaries, no sequential
PRNG state), accumulate in uint32, and store the masked block.  HBM traffic
drops from O(N·D) to O(D).

Correctness requirement: pair (i, j) must generate IDENTICAL bits on both
ends so masks cancel in the cohort sum.  The PRG is ``hash(pair_seed,
element_index)`` with the symmetric pair seed from `derive_pair_seeds` —
stateless, so client i's +bits equal client j's −bits exactly by
construction, on any backend.

Security note: this stream is a murmur3-based counter PRG keyed by the
64-bit pair secret — weaker than the XLA path's threefry (a cryptographic
PRF with a 128-bit-state key schedule).  It demonstrates the fused-kernel
architecture; a production deployment should swap ``_murmur_fmix`` for a
few rounds of a real block cipher (the kernel structure is unchanged).

CPU/test fallback: ``interpret=True`` runs the same kernel semantics through
the Pallas interpreter (tests assert exact ring cancellation there); real
speed needs the TPU backend.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

_LANES = 128
_BLOCK_ROWS = 256          # 256x128 f32 block = 128 KiB in VMEM


def derive_pair_seeds(round_key: jax.Array, client_idx,
                      num_clients: int) -> jax.Array:
    """int32[num_clients, 2] symmetric pair seeds — BOTH words of the
    threefry pair key, so the in-kernel counter PRG is keyed with the full
    64 bits of pair secret; both ends derive the same values (fold_in of
    the sorted pair, matching secagg._pair_key)."""
    def one(j):
        lo = jnp.minimum(client_idx, j)
        hi = jnp.maximum(client_idx, j)
        key = jax.random.fold_in(jax.random.fold_in(round_key, lo), hi)
        return jax.random.key_data(key).astype(jnp.uint32)[:2].astype(
            jnp.int32)
    return jax.vmap(one)(jnp.arange(num_clients))


def _murmur_fmix(x: jax.Array) -> jax.Array:
    """murmur3's 32-bit finalizer — a full-avalanche uint32 hash on the VPU
    (counter-based PRG: hash(seed, index) needs no sequential state, so the
    two ends of a pair trivially generate identical streams)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _mask_kernel(seeds_ref, signs_ref, x_ref, o_ref, *, num_clients,
                 scale, clip):
    """One [BLOCK_ROWS, 128] block: quantize + accumulate all pair masks."""
    from jax.experimental import pallas as pl

    q = jnp.round(jnp.clip(x_ref[:], -clip, clip) * scale)
    acc = q.astype(jnp.int32).astype(jnp.uint32)
    # global element index (stable across the grid -> both pair ends agree)
    block = pl.program_id(0).astype(jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, acc.shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, acc.shape, 1)
    idx = (block * jnp.uint32(_BLOCK_ROWS) + rows) * jnp.uint32(_LANES) + cols
    idx_h = _murmur_fmix(idx * jnp.uint32(0x9E3779B9) + jnp.uint32(1))

    def body(j, acc):
        s0 = seeds_ref[j, 0].astype(jnp.uint32)
        s1 = seeds_ref[j, 1].astype(jnp.uint32)
        # both 32-bit key words enter the stream independently: full 64-bit
        # pair secret keys the counter PRG
        bits = _murmur_fmix(idx_h ^ _murmur_fmix(s0)
                            ^ _murmur_fmix(s1 ^ jnp.uint32(0x5BD1E995)))
        return acc + bits * signs_ref[j]

    acc = jax.lax.fori_loop(0, num_clients, body, acc)
    o_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("num_clients", "scale", "clip",
                                             "interpret"))
def _masked_flat(x2d, seeds, signs, *, num_clients, scale, clip, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = x2d.shape[0]
    grid = rows // _BLOCK_ROWS
    kernel = functools.partial(_mask_kernel, num_clients=num_clients,
                               scale=scale, clip=clip)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # seeds[N]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # signs[N]
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.uint32),
        interpret=interpret,
    )(seeds, signs, x2d)


def fused_quantize_mask(tree: Pytree, weight, client_idx,
                        round_key: jax.Array, num_clients: int,
                        scale: float = 2.0**16, clip: float = 2.0**14,
                        interpret: bool = False) -> Pytree:
    """Pallas-fused equivalent of
    ``secagg.quantize(weight*tree) + secagg.pairwise_masks(...)``.

    Same ring semantics (uint32 wraparound, +PRG for j>i, -PRG for j<i) but
    a DIFFERENT PRG stream than the XLA path — all clients of a cohort must
    use the same path for masks to cancel.
    """
    client_idx = jnp.asarray(client_idx)
    seeds = derive_pair_seeds(round_key, client_idx, num_clients)
    idx = jnp.arange(num_clients)
    signs = jnp.where(idx == client_idx, jnp.uint32(0),
                      jnp.where(idx > client_idx, jnp.uint32(1),
                                jnp.uint32(0xFFFFFFFF)))

    def leaf(leaf_id, x):
        w = jnp.asarray(weight, x.dtype)
        flat = (x * w).reshape(-1)
        block = _BLOCK_ROWS * _LANES
        pad = (-flat.size) % block
        x2d = jnp.pad(flat, (0, pad)).reshape(-1, _LANES)
        # distinct PRG stream per leaf (same-shape leaves must not share
        # masks); the offset is leaf-position-deterministic, so every
        # client derives the same per-leaf seeds and cancellation holds
        out = _masked_flat(x2d, seeds + jnp.int32(leaf_id * 31337), signs,
                           num_clients=num_clients,
                           scale=float(scale), clip=float(clip),
                           interpret=interpret)
        return out.reshape(-1)[:flat.size].reshape(x.shape)

    leaves, treedef = jax.tree.flatten(tree)
    return jax.tree.unflatten(
        treedef, [leaf(i, x) for i, x in enumerate(leaves)])
