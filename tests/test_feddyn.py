"""FedDyn dynamic regularization (algorithms/feddyn.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import FedAvg, FedAvgConfig
from fedml_tpu.algorithms.feddyn import FedDyn, FedDynConfig
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _overlapping_clients(n_clients=4, dim=6, per=32, seed=0):
    """Heterogeneous but NON-separable data (overlapping class clouds):
    the global optimum is finite, so 'converges to the centralized
    optimum' is a checkable statement."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(n_clients, dim) * 0.8
    xs = [(centers[c] + 1.5 * rng.randn(per, dim)).astype(np.float32)
          for c in range(n_clients)]
    ys = [np.full(per, c, np.int32) for c in range(n_clients)]
    return xs, ys


def _fed(xs, ys, batch=8, classes=4):
    train = stack_client_data(xs, ys, batch)
    return FederatedData(client_num=len(xs), class_num=classes,
                         train=train, test=train)


@pytest.fixture(scope="module")
def workload():
    return ClassificationWorkload(LogisticRegression(6, 4), num_classes=4,
                                  grad_clip_norm=None)


def test_feddyn_beats_fedavg_toward_centralized_optimum(workload):
    """The paper's claim: under client drift (one class per client, many
    local epochs) FedAvg's fixed point is biased; FedDyn's coincides with
    the centralized optimum.  At an equal round budget FedDyn must (a)
    reach lower global train loss than FedAvg and (b) land near the
    pooled-data optimum."""
    xs, ys = _overlapping_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=40, client_num_per_round=4, epochs=5,
               batch_size=8, lr=0.1, frequency_of_the_test=39)
    fa = FedAvg(workload, data, FedAvgConfig(**cfg))
    dyn = FedDyn(workload, data, FedDynConfig(feddyn_alpha=0.03, **cfg))
    fa.run(rng=jax.random.key(0))
    dyn.run(rng=jax.random.key(0))
    loss_fa = fa.history[-1]["train_loss"]
    loss_dyn = dyn.history[-1]["train_loss"]
    assert loss_dyn < loss_fa, (loss_dyn, loss_fa)

    # centralized optimum on the pooled data (full-batch adam to
    # convergence) — FedDyn should close most of FedAvg's gap to it
    import optax
    pooled_x = jnp.asarray(np.concatenate(xs))
    pooled_y = jnp.asarray(np.concatenate(ys))
    params = workload.init(jax.random.key(1), {
        "x": pooled_x[:1], "y": pooled_y[:1],
        "mask": jnp.ones((1,), jnp.float32)})
    batch = {"x": pooled_x, "y": pooled_y,
             "mask": jnp.ones(len(pooled_x), jnp.float32)}
    loss_fn = jax.jit(jax.value_and_grad(
        lambda p: workload.loss_fn(p, batch, jax.random.key(0), True)[0]))
    opt = optax.adam(0.05)
    opt_state = opt.init(params)
    for _ in range(3000):
        loss_c, g = loss_fn(params)
        updates, opt_state = opt.update(g, opt_state)
        params = optax.apply_updates(params, updates)
    loss_c = float(loss_c)
    assert loss_fa - loss_c > 0.05  # the drift bias is real in this setup
    assert loss_dyn - loss_c < 0.6 * (loss_fa - loss_c), \
        (loss_dyn, loss_fa, loss_c)


def test_state_updates_and_checkpoint_template(workload):
    xs, ys = _overlapping_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=3, client_num_per_round=2, epochs=2,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    dyn = FedDyn(workload, data, FedDynConfig(feddyn_alpha=0.05, **cfg))
    dyn.run(rng=jax.random.key(1))
    assert dyn.h_state is not None
    assert max(float(jnp.abs(x).max())
               for x in jax.tree.leaves(dyn.h_state)) > 0
    assert max(float(jnp.abs(x).max())
               for x in jax.tree.leaves(dyn.lam_locals)) > 0
    tmpl = dyn._extra_state_template(dyn.init_params(jax.random.key(0)))
    live = dyn._extra_state()
    assert jax.tree.structure(tmpl) == jax.tree.structure(live)


def test_unsampled_clients_keep_lambda(workload):
    """λ_k must change ONLY for sampled clients (cohort=1 per round, so
    after one round exactly one client's row is non-zero)."""
    xs, ys = _overlapping_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=1, client_num_per_round=1, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    dyn = FedDyn(workload, data, FedDynConfig(feddyn_alpha=0.05, **cfg))
    dyn.run(rng=jax.random.key(2))
    from fedml_tpu.core.sampling import sample_clients
    (sampled,) = sample_clients(0, data.client_num, 1)
    norms = np.asarray([
        sum(float(jnp.sum(jnp.abs(x[i])))
            for x in jax.tree.leaves(dyn.lam_locals))
        for i in range(data.client_num)])
    assert norms[sampled] > 0
    assert np.all(norms[np.arange(data.client_num) != sampled] == 0)


def test_rerun_resets_state(workload):
    xs, ys = _overlapping_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=2, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    dyn = FedDyn(workload, data, FedDynConfig(feddyn_alpha=0.05, **cfg))
    out1 = dyn.run(rng=jax.random.key(0))
    out2 = dyn.run(rng=jax.random.key(0))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 out1, out2)
    assert dyn._round_counter == 2


def test_rejects_unsupported_configs(workload):
    xs, ys = _overlapping_clients()
    data = _fed(xs, ys)
    base = dict(comm_round=1, client_num_per_round=2, epochs=1,
                batch_size=8, lr=0.1)
    with pytest.raises(ValueError, match="SGD"):
        FedDyn(workload, data,
               FedDynConfig(client_optimizer="adam", **base))
    with pytest.raises(ValueError, match="feddyn_alpha"):
        FedDyn(workload, data, FedDynConfig(feddyn_alpha=0.0, **base))
    stateful_wl = ClassificationWorkload(
        LogisticRegression(6, 4), num_classes=4, stateful=True)
    with pytest.raises(ValueError, match="stateful"):
        FedDyn(stateful_wl, data, FedDynConfig(**base))


def test_mesh_sharded_feddyn_equals_single_chip(workload):
    """The mesh path (shard_map + psum, rng folded by GLOBAL cohort slot)
    must match single-chip to float tolerance — params AND λ state —
    including a genuinely padded cohort (second case: 4 live clients in
    8 slots over 4 devices, so devices 2-3 hold ONLY padding: live-mask
    freeze + aliased client-0 slot under psum)."""
    from fedml_tpu.parallel.mesh import make_mesh
    for n_clients, m, axis in ((4, 4, 4), (4, 8, 4)):
        xs, ys = _overlapping_clients(n_clients=n_clients)
        data = _fed(xs, ys)
        cfg = dict(comm_round=2, client_num_per_round=m, epochs=2,
                   batch_size=8, lr=0.1, frequency_of_the_test=100)
        single = FedDyn(workload, data,
                        FedDynConfig(feddyn_alpha=0.05, **cfg))
        meshed = FedDyn(workload, data,
                        FedDynConfig(feddyn_alpha=0.05, **cfg),
                        mesh=make_mesh(client_axis=axis,
                                       devices=jax.devices()[:axis]))
        out_s = single.run(rng=jax.random.key(0))
        out_m = meshed.run(rng=jax.random.key(0))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
        for a, b in zip(jax.tree.leaves(single.lam_locals),
                        jax.tree.leaves(meshed.lam_locals)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


def test_cli_feddyn_end_to_end():
    from fedml_tpu.experiments.main import main
    summary = main(["--algo", "feddyn", "--model", "lr", "--dataset",
                    "mnist", "--client_num_in_total", "8",
                    "--client_num_per_round", "4", "--comm_round", "2",
                    "--frequency_of_the_test", "1", "--batch_size", "4",
                    "--feddyn_alpha", "0.05", "--log_stdout", "false"])
    assert np.isfinite(summary["train_loss"])
