"""Sustained-degradation survivability (ISSUE 19): the reliability
tracker's adaptive deadline / quorum-partition verdict / participation
debt, the closed fault-attribution vocabulary with its hard invariant
(only PAYLOAD verdicts may strike trust), the dead-letter attribution
feed, checkpointed determinism of every derivation, and the resume-path
straggler-timer audit.

Fast tier only — the full chaos + partition + kill soak rides
scripts/degrade_soak.py (committed as BENCH_degrade.json and re-derived
by ``perf_trend.py --degrade_bench``).
"""

import dataclasses

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.core.stream_agg import StreamingAggregator
from fedml_tpu.experiments.config import ExperimentConfig
from fedml_tpu.experiments.main import _degrade_setup
from fedml_tpu.robust import AdmissionPipeline, TrustTracker
from fedml_tpu.robust.degrade import (FaultClass, ReliabilityTracker,
                                      classify_admission_reason,
                                      merge_priority)
from fedml_tpu.robust.faultline import ActorKilled, CrashSpec, Faultline
from fedml_tpu.utils.checkpoint import RoundCheckpointer
from fedml_tpu.utils.journal import RoundJournal


def _params(seed=3):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


def _train_fn(silo):
    def fn(params, client_idx, round_idx):
        rng = np.random.RandomState(1000 * silo + int(round_idx or 0))
        return jax.tree.map(
            lambda v: v + rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
            params), 10 + silo
    return fn


def _tracker(n=4, **kw):
    base = dict(min_quorum=0.5, adaptive_deadline=True,
                deadline_floor_s=0.2, deadline_quantile=0.9,
                deadline_slack=1.5, partition_frac=0.5,
                partition_max_holds=2, min_history=2)
    base.update(kw)
    return ReliabilityTracker(n, **base)


# ---------------------------------------------------------------------------
# fault-attribution vocabulary + the strike invariant
# ---------------------------------------------------------------------------

class TestFaultAttribution:
    def test_vocabulary_is_closed(self):
        assert FaultClass.ALL == ("network", "payload", "unknown")
        t = _tracker()
        with pytest.raises(ValueError, match="closed"):
            t.note_fault("cosmic_ray")

    def test_admission_reasons_all_classify_payload(self):
        from fedml_tpu.robust.admission import REASONS
        for reason in REASONS:
            assert classify_admission_reason(reason) == FaultClass.PAYLOAD

    def test_only_payload_may_strike(self):
        """THE invariant: a network- or unknown-attributed verdict
        reaching TrustTracker.strike is a programming error, raised at
        the call site — a chaotic link must never walk an honest silo
        into Byzantine quarantine."""
        trust = TrustTracker(strikes_to_quarantine=1)
        for fault in (FaultClass.NETWORK, FaultClass.UNKNOWN):
            with pytest.raises(ValueError, match="only payload"):
                trust.strike(2, 0, "flaky_link", fault=fault)
        # the refused strikes left no trace: no quarantine, no counts
        assert trust.state(2, 1) == TrustTracker.TRUSTED
        assert trust.strike_fault_totals() == {"network": 0, "payload": 0,
                                               "unknown": 0}
        with pytest.raises(ValueError, match="closed"):
            trust.strike(2, 0, "bad", fault="gamma_burst")
        # a payload strike lands normally
        assert trust.strike(2, 0, "nonfinite") is True
        assert trust.state(2, 1) == TrustTracker.QUARANTINED
        assert trust.strike_fault_totals()["payload"] == 1

    def test_network_faults_route_to_tracker_not_trust(self):
        t = _tracker()
        t.round_start(0, {1, 2, 3, 4})
        t.note_drop(3)
        t.note_dead_letter("deadline", silo=2)
        led = t.as_ledger()
        assert led["faults"]["network"] == 2
        assert led["faults"]["payload"] == 0
        assert led["dead_letters"] == 1


class TestStrikeReasonsState:
    def test_roundtrip(self):
        trust = TrustTracker(strikes_to_quarantine=3)
        trust.strike(1, 0, "nonfinite")
        trust.strike(1, 1, "norm_outlier")
        trust.strike(3, 1, "fingerprint")
        state = trust.state_dict(4)
        sr = state["strike_reasons"]
        assert sr.shape == (4, len(FaultClass.ALL))
        fresh = TrustTracker(strikes_to_quarantine=3)
        fresh.load_state_dict(state)
        assert fresh.strike_fault_totals() == trust.strike_fault_totals()
        assert fresh.strike_fault_totals()["payload"] == 3

    def test_pre19_snapshot_restores_tolerantly(self, caplog):
        """A checkpoint written before the attribution matrix existed
        restores with a warning, never a refused resume."""
        trust = TrustTracker()
        trust.strike(2, 0, "nonfinite")
        state = dict(trust.state_dict(3))
        state.pop("strike_reasons")
        fresh = TrustTracker()
        with caplog.at_level("WARNING"):
            fresh.load_state_dict(state)
        assert "pre-19" in caplog.text
        assert fresh.strike_fault_totals()["payload"] == 0
        # the sentence itself still restored
        assert fresh._strikes == trust._strikes

    def test_foreign_shape_matrix_restores_tolerantly(self, caplog):
        trust = TrustTracker()
        state = dict(trust.state_dict(3))
        state["strike_reasons"] = np.zeros((3, 7), np.int64)
        with caplog.at_level("WARNING"):
            TrustTracker().load_state_dict(state)
        assert "fault vocabulary" in caplog.text


# ---------------------------------------------------------------------------
# adaptive deadline
# ---------------------------------------------------------------------------

class TestAdaptiveDeadline:
    def test_static_when_disabled_and_none_when_uncapped(self):
        t = _tracker(adaptive_deadline=False)
        assert t.deadline_s({1, 2}, 7.0) == 7.0
        assert _tracker().deadline_s({1, 2}, None) is None

    def test_cold_start_any_unmeasured_silo_falls_back_to_cap(self):
        """The bootstrap trap: a deadline derived from only the measured
        (fast) silos would drop an unmeasured slow-but-honest silo
        before it ever got a completion on record — and its late
        uploads, discarded as stale, could never grow its history.  Cap
        until EVERY expected silo has min_history observations."""
        t = _tracker(min_history=2)
        for _ in range(5):
            t.observe_completion(1, 0.1)
            t.observe_completion(2, 0.1)
        # silo 3 has one observation — still cold
        t.observe_completion(3, 0.9)
        assert t.deadline_s({1, 2, 3}, 10.0) == 10.0
        t.observe_completion(3, 0.9)
        d = t.deadline_s({1, 2, 3}, 10.0)
        assert d == pytest.approx(0.9 * 1.5)  # slowest silo's q90 * slack

    def test_clamps_to_floor_and_cap(self):
        t = _tracker(min_history=1, deadline_floor_s=0.5)
        t.observe_completion(1, 0.01)
        assert t.deadline_s({1}, 10.0) == 0.5
        t2 = _tracker(min_history=1)
        t2.observe_completion(1, 100.0)
        assert t2.deadline_s({1}, 3.0) == 3.0

    def test_bad_observations_ignored(self):
        t = _tracker(min_history=1)
        t.observe_completion(1, float("nan"))
        t.observe_completion(1, float("inf"))
        t.observe_completion(1, -0.5)
        t.observe_completion(99, 0.2)   # not this tracker's cohort
        assert t.deadline_s({1}, 5.0) == 5.0  # still cold: nothing stuck

    def test_derivation_is_pure_in_checkpointed_state(self):
        """The resume-determinism contract: restoring state_dict into a
        fresh tracker re-derives the crashed process's deadline
        EXACTLY (same floats in, same float out)."""
        rng = np.random.RandomState(7)
        t = _tracker(min_history=2)
        for silo in (1, 2, 3, 4):
            for lat in rng.uniform(0.05, 1.2, size=9):
                t.observe_completion(silo, float(lat))
        want = t.deadline_s({1, 2, 3, 4}, 30.0)
        assert want is not None and want < 30.0
        fresh = _tracker(min_history=2)
        fresh.load_state_dict(t.state_dict())
        assert fresh.deadline_s({1, 2, 3, 4}, 30.0) == want

    def test_suspicion_grows_with_silence(self):
        t = _tracker()
        assert t.suspicion(1, 10.0) == 0.0  # no history, nothing to suspect
        t.observe_completion(1, 0.5)
        assert t.suspicion(1, 0.5) < t.suspicion(1, 5.0)


# ---------------------------------------------------------------------------
# quorum-aware closure + partition discrimination
# ---------------------------------------------------------------------------

class TestQuorumPartition:
    def test_quorum_for(self):
        assert _tracker(min_quorum=0.0).quorum_for(10) is None
        assert _tracker(min_quorum=0.5).quorum_for(5) == 3
        assert _tracker(min_quorum=1.0).quorum_for(4) == 4

    def test_close_at_quorum_wait_below(self):
        t = _tracker(partition_frac=0.0)
        t.round_start(0, {1, 2, 3, 4})
        v = t.assess_timeout(0, {1, 2, 3, 4}, {1, 2}, quorum=2)
        assert v.action == "close" and v.missing == (3, 4)
        v = t.assess_timeout(0, {1, 2, 3, 4}, {1}, quorum=2)
        assert v.action == "wait"

    def test_correlated_miss_with_dead_letters_holds_then_abandons(self):
        t = _tracker(partition_frac=0.5, partition_max_holds=2)
        t.round_start(3, {1, 2, 3, 4})
        t.note_dead_letter("send_failed")
        verdicts = [t.assess_timeout(3, {1, 2, 3, 4}, {1, 2}, quorum=2)
                    for _ in range(3)]
        assert [v.action for v in verdicts] == ["hold", "hold", "abandon"]
        assert all(v.partition_suspected for v in verdicts)
        assert t.holds_total == 2

    def test_detector_states_are_evidence(self):
        """No dead letters, but every missing silo is non-ALIVE per the
        failure detector: still a partition."""
        t = _tracker(partition_frac=0.5)
        t.round_start(0, {1, 2, 3, 4})
        v = t.assess_timeout(0, {1, 2, 3, 4}, {1, 2}, quorum=2,
                             detector_states={3: "suspect", 4: "dead"})
        assert v.action == "hold" and v.partition_suspected

    def test_mass_miss_without_evidence_is_not_a_partition(self):
        """Silos alive, links clean, uploads simply absent: close under
        the quorum rule — holding would stall on non-network failures."""
        t = _tracker(partition_frac=0.5)
        t.round_start(0, {1, 2, 3, 4})
        v = t.assess_timeout(0, {1, 2, 3, 4}, {1, 2}, quorum=2,
                             detector_states={3: "alive", 4: "suspect"})
        assert v.action == "close" and not v.partition_suspected
        assert "without network evidence" in v.reason

    def test_hold_budget_and_evidence_are_per_round(self):
        t = _tracker(partition_frac=0.5, partition_max_holds=1)
        t.round_start(0, {1, 2})
        t.note_dead_letter("send_failed")
        assert t.assess_timeout(0, {1, 2}, set(), 1).action == "hold"
        assert t.assess_timeout(0, {1, 2}, set(), 1).action == "abandon"
        t.round_start(1, {1, 2})
        # fresh round: dead-letter evidence gone, budget reset
        v = t.assess_timeout(1, {1, 2}, {1}, quorum=1)
        assert v.action == "close" and not v.partition_suspected


# ---------------------------------------------------------------------------
# participation debt + priority re-tasking
# ---------------------------------------------------------------------------

class TestDebtPriority:
    def test_drop_accrues_accept_repays(self):
        t = _tracker()
        t.round_start(0, {1, 2, 3, 4})
        t.note_drop(2)
        t.note_drop(2)
        t.note_drop(3)
        assert t.debt(2) == 2 and t.max_debt() == 2
        assert t.priority([1, 2, 3, 4]) == [2, 3, 1, 4]
        assert t.priority_clients() == [2, 3]
        t.note_accept(2)
        assert t.debt(2) == 0
        assert t.drops_total == 3

    def test_merge_priority_deterministic_no_duplicates(self):
        assert merge_priority([5, 1, 2, 3], [2, 7], 4) == [2, 7, 5, 1]
        assert merge_priority([1, 2], [], 2) == [1, 2]  # zero debt: untouched
        assert merge_priority([1, 2, 3], [9, 9, 8], 2) == [9, 8]


# ---------------------------------------------------------------------------
# ledger + checkpointed state
# ---------------------------------------------------------------------------

class TestLedgerAndState:
    def test_ledger_schema(self):
        t = _tracker(min_history=1)
        t.round_start(5, {1, 2, 3})
        t.observe_completion(1, 0.4)
        t.note_accept(1)
        t.note_drop(3)
        t.deadline_s({1, 2, 3}, 9.0)
        led = t.as_ledger()
        assert led["accepted"] == [1] and led["dropped"] == [3]
        assert set(led) >= {"deadline_s", "holds", "dead_letters",
                            "debt_max", "faults"}

    def test_state_dict_roundtrip(self):
        t = _tracker()
        t.round_start(0, {1, 2, 3, 4})
        t.observe_completion(1, 0.3)
        t.observe_completion(1, 0.5)
        t.note_drop(4)
        t.note_dead_letter("deadline")
        t.assess_timeout(0, {1, 2, 3, 4}, {1}, quorum=1)
        state = t.state_dict()
        assert state["lat"].shape == (4, t.window)
        fresh = _tracker()
        fresh.load_state_dict(state)
        assert fresh.debt(4) == 1
        assert fresh.drops_total == t.drops_total
        assert fresh.holds_total == t.holds_total
        assert fresh._fault_counts == t._fault_counts
        assert list(fresh._lat[1]) == [0.3, 0.5]

    def test_foreign_shape_restores_tolerantly(self, caplog):
        fresh = _tracker(4)
        state = _tracker(7).state_dict()
        with caplog.at_level("WARNING"):
            fresh.load_state_dict(state)
        assert "starting reliability history fresh" in caplog.text

    def test_constructor_validates(self):
        with pytest.raises(ValueError, match="min_quorum"):
            ReliabilityTracker(3, min_quorum=1.5)
        with pytest.raises(ValueError, match="deadline_quantile"):
            ReliabilityTracker(3, deadline_quantile=0.0)


# ---------------------------------------------------------------------------
# dead-letter feed (comm/resilient -> tracker attribution)
# ---------------------------------------------------------------------------

class TestDeadLetterFeed:
    def test_dead_letter_feeds_tracker_never_trust(self):
        """A dead-lettered send books network evidence on the tracker
        (labeled fedml_comm_dead_letter_total{reason}) and leaves the
        trust ledger untouched."""
        import time

        from fedml_tpu.comm.message import Message
        from fedml_tpu.comm.resilient import ResilientTransport, RetryPolicy
        from fedml_tpu.comm.transport import Transport

        class _Down(Transport):
            def send_message(self, msg):
                raise ConnectionError("wire down")

            def run(self):
                pass

            def stop(self):
                pass

        t = _tracker()
        trust = TrustTracker(strikes_to_quarantine=1)
        t.round_start(0, {1, 2})
        rt = ResilientTransport(
            _Down(), RetryPolicy(max_attempts=1, send_deadline_s=5.0),
            fault_feed=lambda reason, msg: t.note_dead_letter(reason))
        try:
            rt.send_message(Message("m", 0, 1))
            deadline = time.monotonic() + 5.0
            while rt.dead_letters < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
        finally:
            rt.stop()
        assert rt.dead_letters == 1
        assert t._round_dead_letters == 1
        assert t._fault_counts["network"] == 1
        # the wire failure produced zero strikes anywhere
        assert trust.strike_fault_totals()["payload"] == 0
        # and the labeled counter carries the reason
        assert "send_failed" in rt._m_dead_by_reason


# ---------------------------------------------------------------------------
# config gates (experiments/main._degrade_setup)
# ---------------------------------------------------------------------------

class TestConfigGates:
    def _cfg(self, **kw):
        base = dict(straggler_policy="drop", round_timeout_s=5.0)
        base.update(kw)
        return ExperimentConfig(**base)

    def test_off_by_default(self):
        assert _degrade_setup(ExperimentConfig(), 4) is None

    def test_sync_happy_path(self):
        t = _degrade_setup(self._cfg(min_quorum=0.5, adaptive_deadline=True,
                                     partition_frac=0.3), 4)
        assert isinstance(t, ReliabilityTracker)
        assert t.quorum_for(4) == 2

    @pytest.mark.parametrize("kw,match", [
        (dict(min_quorum=1.5), "min_quorum"),
        (dict(min_quorum=0.5, straggler_policy="wait"), "drop"),
        (dict(adaptive_deadline=True, round_timeout_s=0.0),
         "round_timeout_s"),
        (dict(partition_frac=2.0), "partition_frac"),
        (dict(min_quorum=0.8, partition_frac=0.5), "quorum gap"),
    ])
    def test_misconfigurations_fail_loud(self, kw, match):
        with pytest.raises(ValueError, match=match):
            _degrade_setup(self._cfg(**kw), 4)

    def test_async_refuses_barrier_flags(self):
        with pytest.raises(ValueError, match="no barrier"):
            _degrade_setup(self._cfg(min_quorum=0.5), 4, mode="async")
        with pytest.raises(ValueError, match="retask_timeout_s"):
            _degrade_setup(self._cfg(adaptive_deadline=True,
                                     retask_timeout_s=0.0), 4,
                           mode="async")


# ---------------------------------------------------------------------------
# engine integration (LocalHub pump) + the resume-path timer audit
# ---------------------------------------------------------------------------

def _run_degrade(init, rounds, *, n=3, degrade=None, ck=None, jr=None,
                 fl=None, extra_state=None, arm_log=None,
                 timeout_s=300.0):
    hub = LocalHub(codec_roundtrip=True)
    stream = StreamingAggregator(init, method="mean", kind="params",
                                 norm_clip=1.0, seed=0)
    server = FedAvgServerActor(
        hub.transport(0), init, n, n, rounds, checkpointer=ck,
        journal=jr, faultline=fl, stream_agg=stream, degrade=degrade,
        extra_state=extra_state, straggler_policy="drop",
        round_timeout_s=timeout_s, min_silo_frac=0.5)
    if arm_log is not None:
        orig = server._timer.arm

        def spy(delay_s, fire, _orig=orig, _log=arm_log):
            _log.append((server.round_idx, delay_s))
            _orig(delay_s, fire)
        server._timer.arm = spy
    silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
             for i in range(1, n + 1)]
    server.register_handlers()
    for s in silos:
        s.register_handlers()
    server.start()
    if arm_log is not None:
        # the audit point: start() ran recovery + broadcast, nothing
        # else has pumped yet
        server._start_arms = list(arm_log)
    hub.pump()
    return server


class TestEngineIntegration:
    def test_degrade_ledger_and_adaptive_deadline_live(self, tmp_path):
        """Pump-mode federation with the spine on: the perf row carries
        the degrade ledger, and once every silo is measured the armed
        deadline adapts below the static cap."""
        from fedml_tpu.obs.perf import PerfRecorder
        from fedml_tpu.obs.trend import load_ledger
        init = _params(3)
        pp = str(tmp_path / "perf.jsonl")
        hub = LocalHub(codec_roundtrip=True)
        perf = PerfRecorder(pp, strict_recompiles=False)
        stream = StreamingAggregator(init, method="mean", kind="params",
                                     norm_clip=1.0, seed=0)
        degrade = ReliabilityTracker(
            3, min_quorum=0.5, adaptive_deadline=True,
            deadline_floor_s=1e-4, deadline_quantile=0.9,
            deadline_slack=1.5, partition_frac=0.3, min_history=1)
        server = FedAvgServerActor(
            hub.transport(0), init, 3, 3, 4, stream_agg=stream,
            degrade=degrade, perf=perf, straggler_policy="drop",
            round_timeout_s=300.0, min_silo_frac=0.5)
        silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
                 for i in range(1, 4)]
        server.register_handlers()
        for s in silos:
            s.register_handlers()
        server.start()
        hub.pump()
        perf.close()
        assert server.round_idx == 4
        rows = load_ledger(pp)
        assert len(rows) == 4
        for r in rows:
            dg = r["degrade"]
            assert dg["accepted"] == [1, 2, 3]
            assert dg["faults"]["payload"] == 0
        # round 0 is cold (cap); later rounds derive from history
        assert rows[0]["degrade"]["deadline_s"] == 300.0
        assert rows[-1]["degrade"]["deadline_s"] < 300.0

    def test_resumed_midround_rearms_exactly_one_timer(self, tmp_path):
        """The resume-path straggler-timer audit (ISSUE 19 satellite):
        a server resumed MID-ROUND from the journal re-arms exactly one
        ROUND_TIMEOUT timer for the re-tasked remainder — no stale
        pre-crash timer semantics, and never a drop-policy round with
        zero timers."""
        init = _params(3)
        fl = Faultline(crashes=[CrashSpec(point="post_fold_pre_ack",
                                          hit=1, round_idx=1)])
        with pytest.raises(ActorKilled):
            _run_degrade(init, 3,
                         ck=RoundCheckpointer(str(tmp_path / "ck"),
                                              save_every=1),
                         jr=RoundJournal(str(tmp_path / "j"),
                                         snapshot_every=1), fl=fl)
        arms = []
        resumed = _run_degrade(
            init, 3,
            ck=RoundCheckpointer(str(tmp_path / "ck"), save_every=1),
            jr=RoundJournal(str(tmp_path / "j"), snapshot_every=1),
            arm_log=arms)
        # start() = journal recovery + the resumed round's broadcast:
        # exactly ONE timer armed, for the resumed round
        assert resumed._start_arms == [(1, 300.0)]
        # and the federation then completed normally (one arm per round)
        assert resumed.round_idx == 3
        assert [r for r, _ in arms] == [1, 2]

    def test_resume_replays_latency_history(self, tmp_path):
        """The deadline's determinism across a crash rides the journal:
        accept records carry lat_s, and the resumed broadcast replays
        them into the tracker so the NEXT derivation sees the same
        history the crashed process had."""
        init = _params(3)

        def mk_degrade():
            return ReliabilityTracker(
                3, min_quorum=0.5, adaptive_deadline=True,
                deadline_floor_s=1e-4, deadline_quantile=0.9,
                deadline_slack=1.5, partition_frac=0.3, min_history=1)
        d1 = mk_degrade()
        fl = Faultline(crashes=[CrashSpec(point="post_fold_pre_ack",
                                          hit=2, round_idx=2)])
        with pytest.raises(ActorKilled):
            _run_degrade(init, 4, degrade=d1,
                         ck=RoundCheckpointer(str(tmp_path / "ck"),
                                              save_every=1),
                         jr=RoundJournal(str(tmp_path / "j"),
                                         snapshot_every=1), fl=fl,
                         extra_state=(d1.state_dict, d1.load_state_dict))
        d2 = mk_degrade()
        resumed = _run_degrade(
            init, 4, degrade=d2,
            ck=RoundCheckpointer(str(tmp_path / "ck"), save_every=1),
            jr=RoundJournal(str(tmp_path / "j"), snapshot_every=1),
            extra_state=(d2.state_dict, d2.load_state_dict))
        assert resumed.round_idx == 4
        # every silo's history covers every completed round: the
        # checkpointed matrix plus the journal replay left no gap
        for silo in (1, 2, 3):
            assert len(d2._lat[silo]) == 4

    def test_attacker_strikes_payload_honest_drop_does_not(self):
        """End-to-end attribution: a NaN attacker strikes (payload), and
        the strike totals show zero network/unknown — the invariant the
        soak pins at scale."""
        init = _params(3)
        hub = LocalHub(codec_roundtrip=True)
        stream = StreamingAggregator(init, method="mean", kind="params",
                                     norm_clip=1.0, seed=0)
        adm = AdmissionPipeline(init, kind="params",
                                trust=TrustTracker(strikes_to_quarantine=1))
        degrade = ReliabilityTracker(3, min_quorum=0.5, partition_frac=0.4)
        server = FedAvgServerActor(
            hub.transport(0), init, 3, 3, 2, stream_agg=stream,
            admission=adm, degrade=degrade, straggler_policy="drop",
            round_timeout_s=300.0, min_silo_frac=0.5)

        def nan_train(params, client_idx, round_idx):
            return jax.tree.map(
                lambda v: np.full_like(np.asarray(v), np.nan), params), 10

        silos = [FedAvgClientActor(1, hub.transport(1), _train_fn(1)),
                 FedAvgClientActor(2, hub.transport(2), _train_fn(2)),
                 FedAvgClientActor(3, hub.transport(3), nan_train)]
        server.register_handlers()
        for s in silos:
            s.register_handlers()
        server.start()
        hub.pump()
        sft = adm.trust.strike_fault_totals()
        assert sft["payload"] >= 1
        assert sft["network"] == 0 and sft["unknown"] == 0
        assert degrade._fault_counts["payload"] >= 1


# the CLI wiring sanity: every degrade flag the README documents exists
def test_config_has_degrade_fields():
    names = {f.name for f in dataclasses.fields(ExperimentConfig)}
    assert {"min_quorum", "adaptive_deadline", "deadline_floor_s",
            "deadline_quantile", "deadline_slack", "partition_frac",
            "partition_max_holds"} <= names
