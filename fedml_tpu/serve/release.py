"""Train-to-serve release gate: canary shadow eval, automated
promote/rollback, and poisoned-round containment (ISSUE 16).

The round loop and the serving registry used to meet with no quality
gate between them — ``publish()`` swapped every finalized global live,
so one Byzantine round that slipped past admission (or one corrupted
checkpoint) went straight to users.  This module closes ROADMAP's last
north-star gap: every finalized global enters the `ModelRegistry` as a
**canary** (in history, never the live slot) and `ReleaseController`
gates promotion on three independent signals:

* **shadow traffic** — a deterministic slice of live requests tapped by
  `ShadowSampler` (the `MicroBatcher` ``shadow=`` seam; every worker of
  a `ServeWorkerPool` feeds ONE shared sampler) is replayed against the
  canary and the serving version; the disagreement fraction must stay
  within ``divergence_budget``.  The canary answers shadow traffic ONLY
  — by construction it cannot serve a non-shadow response, because the
  live slot never moves until the verdict;
* **health observatory** — the PR 8 drift/norm alarms
  (`obs.health.HealthAccumulator.healthz`) for the round that produced
  the candidate must all be ok;
* **held-out eval** — ``eval_fn(params)`` (higher is better) must not
  regress below the last promoted score by more than
  ``eval_tolerance`` (monotone-regression tolerance).

Pass → ``registry.promote()``: one lock-guarded reference swap riding
the PR 15 decode swap barrier (decode sessions never straddle
versions).  Fail → the canary is discarded; serving never moved, which
IS the rollback to the last promoted version — and a cooldown with
exponential backoff refuses the next canary, so a flapping trainer
cannot thrash serving.  Every verdict lands in telemetry
(``fedml_release_*``) and the release journal (`utils.journal
.durable_append`, channel ``release_journal``) with the verdict, the
per-signal evidence, and the rolled-back/live version named.

Crash consistency: `robust.faultline` crash points ``canary_promote`` /
``canary_rollback`` fire BEFORE and AFTER each atomic registry mutation
(hit 1 = pre, hit 2 = post).  A server killed mid-promotion respawns
via ``recover()``: lingering canaries are discarded (a canary is never
half-promoted — the registry is exactly the pre- or post-verdict
state), and the train loop's next offer re-drives the gate.

Signals with no evidence pass VACUOUSLY (no shadow traffic captured,
no health record, no eval_fn): the gate degrades to availability, not
to blocking every release — but each vacuous pass is named in the
verdict so an operator can see which protections were actually live.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

Pytree = Any

SIGNALS = ("shadow", "health", "eval")

# rollback/refusal reasons (the rollback counter's label vocabulary)
ROLLBACK_REASONS = SIGNALS + ("cooldown",)


class ShadowSampler:
    """Deterministic every-Nth tap of live request traffic into a fixed
    ring — the shadow slice the gate replays against each canary.

    Hot-path cost is one C-level ``next()`` on an `itertools.count`
    (GIL-atomic, lock-free: the serve bench proved hot-path locks
    collapse throughput at 10k+ req/s) plus, on the sampled Nth request
    only, one row copy into the ring.  The slice is deterministic in the
    arrival sequence: the same submit order yields the same captured
    rows, so shadow verdicts replay."""

    def __init__(self, every: int = 16, slots: int = 64):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.every = int(every)
        self.slots = int(slots)
        self._n = itertools.count()
        self._ring: list = [None] * self.slots
        reg = telemetry.get_registry()
        self._c_sampled = reg.counter("fedml_release_shadow_requests_total")

    def offer(self, x) -> None:
        """One live request's instance; keeps every ``every``-th."""
        n = next(self._n)
        if n % self.every:
            return
        # np.array: an owned copy — the caller's buffer may be reused
        self._ring[(n // self.every) % self.slots] = np.array(x)
        self._c_sampled.inc()

    def snapshot(self) -> list:
        """The captured rows, ring order (stable for a fixed arrival
        sequence; partially-filled rings return only the filled slots)."""
        return [r for r in self._ring if r is not None]


def _divergence(y_live: np.ndarray, y_canary: np.ndarray) -> float:
    """Disagreement fraction between two models' outputs on the shadow
    slice.  Classification heads ([N, C], C > 1) compare argmax — the
    user-visible prediction; anything else compares values within a
    relative tolerance (regression outputs drift a little every honest
    round; a poisoned model blows far past it)."""
    if y_live.ndim >= 2 and y_live.shape[-1] > 1:
        a = np.argmax(y_live.reshape(y_live.shape[0], -1), axis=-1)
        b = np.argmax(y_canary.reshape(y_canary.shape[0], -1), axis=-1)
        return float(np.mean(a != b))
    flat_l = y_live.reshape(y_live.shape[0], -1).astype(np.float64)
    flat_c = y_canary.reshape(y_canary.shape[0], -1).astype(np.float64)
    tol = 1e-3 * (1.0 + np.abs(flat_l))
    row_diff = np.any(~np.isfinite(flat_c) | (np.abs(flat_l - flat_c)
                                              > tol), axis=-1)
    return float(np.mean(row_diff))


class ReleaseController:
    """The promote/rollback state machine between the train loop and the
    serving registry.  ``offer(params, version, round_idx)`` is the
    publish hook: canary-publish, evaluate the three signals, then
    promote or discard — never leaving a canary unresolved (except
    across a crash, which ``recover()`` cleans up).

    ``eval_fn(params) -> float`` scores the candidate on held-out data,
    higher is better.  ``health`` is an `obs.health.HealthAccumulator`
    (or anything with ``healthz()``).  ``clock`` is injectable for
    cooldown tests."""

    def __init__(self, registry, *, shadow: Optional[ShadowSampler] = None,
                 health=None, eval_fn: Optional[Callable] = None,
                 divergence_budget: float = 0.1,
                 eval_tolerance: float = 0.02,
                 cooldown_s: float = 5.0, backoff: float = 2.0,
                 max_cooldown_s: float = 60.0,
                 journal_path: Optional[str] = None,
                 faultline=None, clock: Callable[[], float] = time.monotonic):
        if not 0.0 <= divergence_budget <= 1.0:
            raise ValueError(f"divergence_budget must be in [0, 1], got "
                             f"{divergence_budget}")
        if cooldown_s < 0 or backoff < 1.0 or max_cooldown_s < cooldown_s:
            raise ValueError(
                f"cooldown_s >= 0, backoff >= 1, max_cooldown_s >= "
                f"cooldown_s required; got cooldown_s={cooldown_s}, "
                f"backoff={backoff}, max_cooldown_s={max_cooldown_s}")
        self.registry = registry
        self.shadow = shadow
        self.health = health
        self.eval_fn = eval_fn
        self.divergence_budget = float(divergence_budget)
        self.eval_tolerance = float(eval_tolerance)
        self.cooldown_s = float(cooldown_s)
        self.backoff = float(backoff)
        self.max_cooldown_s = float(max_cooldown_s)
        self.journal_path = journal_path
        self.faultline = faultline
        self.clock = clock
        self._lock = threading.Lock()
        self._cooldown_until = -float("inf")
        self._consecutive_failures = 0
        self._last_promoted_score: Optional[float] = None
        self.promotions = 0
        self.rollbacks = 0
        self.verdicts: list = []          # every offer's verdict dict
        self._journal_dead = False
        reg = telemetry.get_registry()
        self._c_canaries = reg.counter("fedml_release_canaries_total")
        self._c_promotions = reg.counter("fedml_release_promotions_total")
        self._c_rollbacks = {
            r: reg.counter("fedml_release_rollbacks_total", signal=r)
            for r in ROLLBACK_REASONS}
        self._g_divergence = reg.gauge(
            "fedml_release_shadow_divergence_ratio")
        self._g_eval = reg.gauge("fedml_release_eval_score_value")
        self._g_cooldown = reg.gauge("fedml_release_cooldown_seconds")
        self._h_verdict = reg.histogram("fedml_release_verdict_seconds")

    # -- crash points --------------------------------------------------------
    def _crash(self, point: str, round_idx) -> None:
        if self.faultline is not None:
            self.faultline.maybe_crash(point, round_idx=round_idx)

    # -- the three signals ---------------------------------------------------
    def _signal_shadow(self, version: int) -> dict:
        rows = self.shadow.snapshot() if self.shadow is not None else []
        serving = self.registry.current()
        if not rows or serving is None:
            return {"ok": True, "vacuous": True, "n": 0,
                    "divergence": None}
        canary = self.registry.get(version)
        x = np.stack([np.asarray(r) for r in rows])
        y_live = np.asarray(serving.apply_fn(serving.params, x))
        y_canary = np.asarray(canary.apply_fn(canary.params, x))
        div = _divergence(y_live, y_canary)
        self._g_divergence.set(div)
        return {"ok": div <= self.divergence_budget, "vacuous": False,
                "n": len(rows), "divergence": div,
                "budget": self.divergence_budget,
                "against": serving.version}

    def _signal_health(self, round_idx) -> dict:
        h = self.health.healthz() if self.health is not None else None
        if h is None or not h.get("alarms"):
            return {"ok": True, "vacuous": True, "round": None,
                    "alarms": {}}
        if round_idx is not None and h.get("round") != round_idx:
            # no record FOR THE PRODUCING ROUND: vacuous, but named — an
            # operator can see the observatory lagged the publish
            return {"ok": True, "vacuous": True, "round": h.get("round"),
                    "expected_round": round_idx, "alarms": {}}
        alarms = {name: bool(a.get("ok", True))
                  for name, a in h["alarms"].items()}
        return {"ok": all(alarms.values()), "vacuous": False,
                "round": h.get("round"), "alarms": alarms}

    def _signal_eval(self, params) -> dict:
        if self.eval_fn is None:
            return {"ok": True, "vacuous": True, "score": None}
        score = float(self.eval_fn(params))
        self._g_eval.set(score)
        baseline = self._last_promoted_score
        ok = (np.isfinite(score)
              and (baseline is None
                   or score >= baseline - self.eval_tolerance))
        return {"ok": bool(ok), "vacuous": False, "score": score,
                "baseline": baseline, "tolerance": self.eval_tolerance}

    # -- the gate ------------------------------------------------------------
    def offer(self, params: Pytree, version: int,
              round_idx=None) -> dict:
        """Gate one finalized global.  Returns the verdict dict (also
        appended to ``self.verdicts`` and the release journal)."""
        t0 = time.perf_counter()
        with self._lock:
            verdict = self._offer_locked(params, int(version), round_idx)
        self._h_verdict.observe(time.perf_counter() - t0)
        return verdict

    def _offer_locked(self, params, version: int, round_idx) -> dict:
        now = self.clock()
        base = {"version": version, "round": round_idx,
                "live_before": self.registry.version}
        if now < self._cooldown_until:
            verdict = {**base, "decision": "cooldown",
                       "cooldown_remaining_s":
                           round(self._cooldown_until - now, 3),
                       "live_version": self.registry.version}
            self._c_rollbacks["cooldown"].inc()
            log.warning("release: version %d REFUSED (cooldown, %.1fs "
                        "remaining)", version,
                        verdict["cooldown_remaining_s"])
            return self._record(verdict)
        if not self.registry.publish(params, version, canary=True):
            return self._record({**base, "decision": "stale",
                                 "live_version": self.registry.version})
        self._c_canaries.inc()
        signals = {"shadow": self._signal_shadow(version),
                   "health": self._signal_health(round_idx),
                   "eval": self._signal_eval(params)}
        failed = [s for s in SIGNALS if not signals[s]["ok"]]
        if not failed:
            self._crash("canary_promote", round_idx)   # hit N: pre
            self.registry.promote(version)
            self._crash("canary_promote", round_idx)   # hit N+1: post
            self.promotions += 1
            self._c_promotions.inc()
            if not signals["eval"]["vacuous"]:
                self._last_promoted_score = signals["eval"]["score"]
            self._consecutive_failures = 0
            self._cooldown_until = -float("inf")
            self._g_cooldown.set(0.0)
            verdict = {**base, "decision": "promote", "signals": signals,
                       "live_version": version}
            log.info("release: version %d PROMOTED (shadow n=%d "
                     "div=%s, health=%s, eval=%s)", version,
                     signals["shadow"]["n"],
                     signals["shadow"]["divergence"],
                     "vacuous" if signals["health"]["vacuous"] else "ok",
                     signals["eval"]["score"])
            return self._record(verdict)
        # fail → automatic rollback: discard the canary (the live slot
        # never moved, so serving is already the last promoted version)
        self._crash("canary_rollback", round_idx)      # hit N: pre
        self.registry.discard(version)
        self._crash("canary_rollback", round_idx)      # hit N+1: post
        self.rollbacks += 1
        for s in failed:
            self._c_rollbacks[s].inc()
        self._consecutive_failures += 1
        cooldown = min(
            self.cooldown_s
            * self.backoff ** (self._consecutive_failures - 1),
            self.max_cooldown_s)
        self._cooldown_until = self.clock() + cooldown
        self._g_cooldown.set(cooldown)
        verdict = {**base, "decision": "rollback", "signals": signals,
                   "failed_signals": failed,
                   "rolled_back_to": self.registry.version,
                   "live_version": self.registry.version,
                   "cooldown_s": cooldown,
                   "consecutive_failures": self._consecutive_failures}
        log.warning("release: version %d ROLLED BACK (failed signals "
                    "%s); serving stays on %s, cooldown %.1fs",
                    version, failed, self.registry.version, cooldown)
        return self._record(verdict)

    # -- crash recovery ------------------------------------------------------
    def recover(self) -> dict:
        """Respawn path: resolve any canary a crash left unvetted.  A
        canary is never half-promoted (the registry mutation is one
        atomic swap), so the registry is in exactly one of two states
        per canary: still-canary (verdict never landed — discard it;
        the trainer's next offer re-drives the gate) or promoted (the
        verdict completed before the crash — nothing to do)."""
        with self._lock:
            discarded = []
            for v in self.registry.canaries():
                self.registry.discard(v)
                discarded.append(v)
            report = {"decision": "recover", "discarded": discarded,
                      "live_version": self.registry.version}
            if discarded:
                log.warning("release: recovery discarded unresolved "
                            "canaries %s (live stays %s)", discarded,
                            self.registry.version)
            return self._record(report)

    # -- verdict record ------------------------------------------------------
    def _record(self, verdict: dict) -> dict:
        verdict = {"ts": time.time(), **verdict}
        self.verdicts.append(verdict)
        if self.journal_path and not self._journal_dead:
            from fedml_tpu.utils.journal import durable_append
            try:
                durable_append(self.journal_path,
                               json.dumps(verdict, sort_keys=True) + "\n",
                               channel="release_journal")
            except OSError as e:
                # the ledger contract everywhere else in obs/: warn once
                # and disable — a full disk must never block a verdict
                self._journal_dead = True
                log.warning("release journal disabled (%s); verdicts "
                            "stay in telemetry only", e)
        return verdict
