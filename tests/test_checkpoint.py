"""Checkpoint/resume (orbax) + torch pretrained import tests.

Kill-and-resume contract: a run interrupted at round k and resumed from its
checkpoint must be BIT-IDENTICAL to the uninterrupted run (VERDICT r1 #5) —
params, server optimizer state, round index, and RNG key all round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.algorithms.fedopt import FedOpt, FedOptConfig
from fedml_tpu.data.synthetic import synthetic_federated_dataset
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload
from fedml_tpu.utils.checkpoint import (RoundCheckpointer, _pack_keys,
                                        _unpack_keys)


def _setup():
    data = synthetic_federated_dataset(num_clients=8, samples_per_client=12,
                                       sample_shape=(6,), class_num=3,
                                       batch_size=4)
    wl = ClassificationWorkload(LogisticRegression(6, 3), num_classes=3,
                                grad_clip_norm=None)
    return wl, data


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _kwargs(rounds):
    return dict(comm_round=rounds, client_num_per_round=4, epochs=1,
                batch_size=4, lr=0.1, frequency_of_the_test=100, seed=0)


def test_prng_key_pack_roundtrip():
    key = jax.random.key(42)
    tree = {"rng": key, "x": jnp.ones(3)}
    packed = _pack_keys(tree)
    assert isinstance(packed["rng"], dict) and "__prng_data__" in packed["rng"]
    restored = _unpack_keys(packed)
    assert jnp.all(jax.random.key_data(restored["rng"])
                   == jax.random.key_data(key))


def test_fedavg_kill_and_resume_bit_identical(tmp_path):
    wl, data = _setup()
    # uninterrupted 4-round run
    straight = FedAvg(wl, data, FedAvgConfig(**_kwargs(4))).run()

    # interrupted: 2 rounds with checkpointing, then a FRESH object resumes
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    FedAvg(wl, data, FedAvgConfig(**_kwargs(2))).run(checkpointer=ck)
    assert ck.latest_round() == 1
    resumed = FedAvg(wl, data, FedAvgConfig(**_kwargs(4))).run(
        checkpointer=ck)
    _assert_trees_equal(straight, resumed)


def test_fedopt_resume_preserves_server_momentum(tmp_path):
    wl, data = _setup()
    cfg = dict(server_optimizer="sgd", server_lr=0.5, server_momentum=0.9)
    straight = FedOpt(wl, data, FedOptConfig(**cfg, **_kwargs(4))).run()

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    FedOpt(wl, data, FedOptConfig(**cfg, **_kwargs(2))).run(checkpointer=ck)
    resumed = FedOpt(wl, data, FedOptConfig(**cfg, **_kwargs(4))).run(
        checkpointer=ck)
    # with momentum 0.9 any server-state loss would diverge immediately;
    # bit-equality proves the optimizer state rode the checkpoint
    _assert_trees_equal(straight, resumed)


def test_fednova_resume_preserves_gmf_buffer(tmp_path):
    from fedml_tpu.algorithms.fednova import FedNova, FedNovaConfig
    wl, data = _setup()
    cfg = dict(gmf=0.9)
    straight = FedNova(wl, data, FedNovaConfig(**cfg, **_kwargs(4))).run()

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    FedNova(wl, data, FedNovaConfig(**cfg, **_kwargs(2))).run(checkpointer=ck)
    resumed = FedNova(wl, data, FedNovaConfig(**cfg, **_kwargs(4))).run(
        checkpointer=ck)
    _assert_trees_equal(straight, resumed)


def test_save_every_gating(tmp_path):
    wl, data = _setup()
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=3)
    FedAvg(wl, data, FedAvgConfig(**_kwargs(4))).run(checkpointer=ck)
    # rounds saved: idx 2 (every 3rd) and 3 (last round)
    assert ck.latest_round() == 3


def test_async_save_resumes_bit_identical(tmp_path):
    """async_save=True must not change resume semantics: reads flush the
    in-flight write first, so a resume right after a background save sees
    the same state a sync save would have produced."""
    wl, data = _setup()
    straight = FedAvg(wl, data, FedAvgConfig(**_kwargs(4))).run()

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1,
                           async_save=True)
    FedAvg(wl, data, FedAvgConfig(**_kwargs(2))).run(checkpointer=ck)
    assert ck.latest_round() == 1  # latest_round flushes pending writes
    resumed = FedAvg(wl, data, FedAvgConfig(**_kwargs(4))).run(
        checkpointer=ck)
    _assert_trees_equal(straight, resumed)
    ck.close()


def test_cli_checkpoint_flag(tmp_path):
    from fedml_tpu.experiments.main import main
    argv = ["--algo", "fedavg", "--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "8", "--client_num_per_round", "4",
            "--batch_size", "4", "--comm_round", "2", "--log_stdout",
            "false", "--checkpoint_dir", str(tmp_path / "ck"),
            "--checkpoint_every", "1"]
    main(argv)
    ck = RoundCheckpointer(str(tmp_path / "ck"))
    assert ck.latest_round() == 1
    # resume continues (round 2..3 of a 4-round config); fresh handle —
    # CheckpointManager instances cache their step list
    main([a if a != "2" else "4" for a in argv])
    assert RoundCheckpointer(str(tmp_path / "ck")).latest_round() == 3


# ---------------------------------------------------------------------------
# torch pretrained import (resnet.py:202-246 parity)
# ---------------------------------------------------------------------------

def _torch_cifar_resnet(layers=(1, 1, 1), num_classes=10):
    """Reference-shaped torch CIFAR ResNet (Bottleneck, 16/32/64 stages) —
    built here only to produce a structurally-faithful state_dict."""
    torch = pytest.importorskip("torch")
    nn = torch.nn

    class Bottleneck(nn.Module):
        expansion = 4

        def __init__(self, inplanes, planes, stride=1, downsample=None):
            super().__init__()
            self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(planes)
            self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(planes)
            self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
            self.bn3 = nn.BatchNorm2d(planes * 4)
            self.downsample = downsample

        def forward(self, x):
            identity = x
            out = torch.relu(self.bn1(self.conv1(x)))
            out = torch.relu(self.bn2(self.conv2(out)))
            out = self.bn3(self.conv3(out))
            if self.downsample is not None:
                identity = self.downsample(x)
            return torch.relu(out + identity)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 16, 3, padding=1, bias=False)
            self.bn1 = nn.BatchNorm2d(16)
            inplanes = 16
            for s, (planes, n) in enumerate(zip((16, 32, 64), layers)):
                blocks = []
                for i in range(n):
                    stride = 2 if (s > 0 and i == 0) else 1
                    down = None
                    if stride != 1 or inplanes != planes * 4:
                        down = nn.Sequential(
                            nn.Conv2d(inplanes, planes * 4, 1, stride,
                                      bias=False),
                            nn.BatchNorm2d(planes * 4))
                    blocks.append(Bottleneck(inplanes, planes, stride, down))
                    inplanes = planes * 4
                setattr(self, f"layer{s + 1}", nn.Sequential(*blocks))
            self.fc = nn.Linear(64 * 4, num_classes)

        def forward(self, x):
            x = torch.relu(self.bn1(self.conv1(x)))
            x = self.layer3(self.layer2(self.layer1(x)))
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    return Net()


@pytest.mark.slow
def test_torch_resnet_import_forward_parity(tmp_path):
    """Import a torch CIFAR-ResNet checkpoint and verify the flax model
    produces the SAME logits (33x33 input keeps XLA SAME padding symmetric,
    matching torch's pad=1 on strided convs)."""
    torch = pytest.importorskip("torch")
    from fedml_tpu.models.resnet import CifarResNet
    from fedml_tpu.utils.torch_import import (import_torch_state_dict,
                                              load_torch_checkpoint)

    torch.manual_seed(0)
    tnet = _torch_cifar_resnet(layers=(1, 1, 1))
    tnet.eval()
    # reference checkpoint format: {'state_dict': ...} with module. prefix
    sd = {"module." + k: v for k, v in tnet.state_dict().items()}
    path = str(tmp_path / "ckpt.pth")
    torch.save({"state_dict": sd}, path)

    model = CifarResNet(layers=(1, 1, 1), num_classes=10, norm="batch")
    x = np.random.RandomState(0).randn(2, 33, 33, 3).astype(np.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x))
    variables = import_torch_state_dict(dict(variables),
                                        load_torch_checkpoint(path))

    flax_out = model.apply(variables, jnp.asarray(x), train=False)
    with torch.no_grad():
        torch_out = tnet(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    np.testing.assert_allclose(np.asarray(flax_out), torch_out,
                               atol=2e-4, rtol=1e-3)


def test_import_rejects_architecture_mismatch(tmp_path):
    torch = pytest.importorskip("torch")
    from fedml_tpu.models.resnet import CifarResNet
    from fedml_tpu.utils.torch_import import import_torch_state_dict

    tnet = _torch_cifar_resnet(layers=(1, 1, 1))
    sd = {k: v.numpy() for k, v in tnet.state_dict().items()}
    model = CifarResNet(layers=(2, 2, 2), num_classes=10, norm="batch")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    with pytest.raises(ValueError, match="unit count"):
        import_torch_state_dict(dict(variables), sd)
