#!/usr/bin/env bash
# End-to-end observability demo (ISSUE 2 acceptance): a chaos-enabled
# 2-silo federated run with distributed tracing + telemetry on, then the
# merged run report — asserting every artifact actually materializes:
#
#   * a stitched multi-process Perfetto trace covering
#     broadcast -> train -> upload -> aggregate,
#   * a Prometheus text snapshot with link/chaos counters and
#     failure-detector gauges,
#   * an obs_report per-round timeline,
#   * a perf.jsonl flight-recorder ledger (ISSUE 6) that passes the
#     perf_trend gate honestly and FAILS it on a seeded regression,
#     with the mfu<=1.0 lint green over every committed BENCH artifact,
#   * a device & compile observatory section on every ledger line
#     (ISSUE 10): per-device memory watermarks, a NAMED compile ledger
#     with wall times, and an honest MFU <= 1.0 whose FLOPs/peak
#     provably come from the same table bench.py uses — plus a forced
#     recompile whose sentry verdict names the exact arg shape change,
#     and a seeded compile-time regression failing the trend gate.
#
# Usage: scripts/run_obs_demo.sh [workdir]  (default: a fresh mktemp dir)
set -euo pipefail
cd "$(dirname "$0")/.."

DIR="${1:-$(mktemp -d /tmp/fedml_obs_demo.XXXXXX)}"
RUN="$DIR/run" TRACE="$DIR/trace"
echo "== obs demo: artifacts under $DIR"

env JAX_PLATFORMS=cpu python -m fedml_tpu \
    --algo cross_silo --model lr --dataset mnist \
    --client_num_in_total 4 --client_num_per_round 2 --comm_round 3 \
    --frequency_of_the_test 1 --batch_size 4 --log_stdout false \
    --straggler_policy drop --round_timeout_s 2 --min_silo_frac 0.5 \
    --chaos_drop 0.05 --chaos_delay 0.3 --chaos_dup 0.1 \
    --chaos_reorder 0.1 --chaos_seed 7 \
    --heartbeat_s 0.2 --dead_after_s 5 \
    --run_dir "$RUN" --trace_dir "$TRACE" --telemetry true \
    --perf true --perf_strict true --device_obs true

REPORT="$DIR/report.txt"
env JAX_PLATFORMS=cpu python scripts/obs_report.py \
    --run_dir "$RUN" --trace_dir "$TRACE" \
    --merge_trace "$DIR/trace_merged.json" | tee "$REPORT"

echo "== asserting artifacts"
# the report renders a per-round timeline with every phase stitched in
grep -q "round timelines" "$REPORT"
for phase in broadcast train upload aggregate; do
    grep -q "$phase" "$REPORT"
done
# the Prometheus snapshot carries link counters, chaos fault counters,
# and failure-detector gauges
for series in fedml_comm_send_total fedml_chaos_faults_total \
              fedml_failure_detector_alive_total \
              fedml_round_duration_seconds_count; do
    grep -q "$series" "$RUN/telemetry.prom"
done
# the merged Perfetto trace is non-trivial valid trace_event JSON
python - "$DIR/trace_merged.json" <<'EOF'
import json, sys
events = json.load(open(sys.argv[1]))["traceEvents"]
names = {e["name"] for e in events}
assert {"round", "broadcast", "train", "upload", "aggregate"} <= names, names
print(f"merged trace OK: {len(events)} spans, phases {sorted(names)}")
EOF

echo "== asserting the flight recorder (perf.jsonl + trend gate)"
[ -s "$RUN/perf.jsonl" ]
# the report renders the ledger section
grep -q "perf ledger" "$REPORT"
# honest ledger: schema + recompile gate + mfu lint over every
# committed BENCH artifact all green (exit 0)
env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --ledger "$RUN/perf.jsonl" --baseline "$RUN/perf.jsonl" \
    --lint_mfu 'BENCH_*.json' 'MULTICHIP_*.json' SCALE_PROOF.json
# seeded +60% regression on the aggregate phase MUST fail the gate
# (non-zero exit, naming the phase) — proving the gate can actually
# catch what it exists to catch
python - "$RUN/perf.jsonl" "$DIR/perf_regressed.jsonl" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
for r in rows:
    for k in r.get("phases", {}):
        if k in ("aggregate", "defended_aggregate", "broadcast_serialize"):
            r["phases"][k] = r["phases"][k] * 1.6 + 0.05
with open(sys.argv[2], "w") as f:
    f.writelines(json.dumps(r) + "\n" for r in rows)
EOF
if env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --ledger "$DIR/perf_regressed.jsonl" --baseline "$RUN/perf.jsonl" \
    > "$DIR/trend_fail.txt"; then
    echo "ERROR: trend gate passed a seeded +60% regression"; exit 1
fi
grep -q "phase regression" "$DIR/trend_fail.txt"
echo "trend gate OK: honest ledger passes, seeded regression fails"

echo "== asserting the device & compile observatory (ISSUE 10)"
# every ledger line carries a device section: per-device memory
# watermarks (CPU-honest live_arrays source here), at least one NAMED
# compile-ledger entry with wall time, and an MFU <= 1.0 whose peak
# provably comes from the SAME table bench.py delegates to
env JAX_PLATFORMS=cpu python - "$RUN/perf.jsonl" <<'EOF'
import json, sys
import bench
from fedml_tpu.obs.device import (MFU_PROVENANCE, compiled_flops,
                                  peak_tflops_for_device)
assert bench._peak_for_device is peak_tflops_for_device
assert bench._compiled_flops is compiled_flops
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert rows, "no ledger lines"
compiles = []
for r in rows:
    d = r["device"]
    mem = d["memory"]
    assert mem is None or (mem and all(
        "bytes_in_use" in e and "source" in e for e in mem)), mem
    compiles += d["compiles"]
    mfu = d["mfu"]
    if mfu is not None:
        assert 0.0 <= mfu <= 1.0, f"impossible mfu {mfu}"
        import jax
        assert d["peak_tflops"] == peak_tflops_for_device(None) * len(
            jax.local_devices())
        assert d["mfu_provenance"] == MFU_PROVENANCE
assert compiles, "no named compile-ledger entry in the whole run"
assert all(e["fn"] and e["wall_s"] > 0 for e in compiles), compiles
names = sorted({e["fn"] for e in compiles})
print(f"device section OK: {len(rows)} rounds, compiles {names}, "
      f"mem source "
      f"{sorted({e['source'] for r in rows for e in r['device']['memory'] or []})}")
EOF
# the report renders the device observatory table
grep -q "device observatory" "$REPORT"
# a forced recompile (a REAL re-jit on a changed arg shape) fires a
# sentry verdict that NAMES the exact shape change
env JAX_PLATFORMS=cpu python - "$DIR/recompile_probe.jsonl" <<'EOF'
import sys
import jax, jax.numpy as jnp
from fedml_tpu.obs import telemetry
from fedml_tpu.obs.device import DeviceRecorder
from fedml_tpu.obs.perf import PerfRecorder, RecompileError
reg = telemetry.TelemetryRegistry()
rec = PerfRecorder(sys.argv[1], registry=reg, strict_recompiles=True,
                   device=DeviceRecorder(registry=reg))
f = rec.instrument_jit("hot_fn", jax.jit(lambda x: x * 2.0))
rec.round_start(0); f(jnp.ones((4,), jnp.float32)); rec.round_end(0)
rec.round_start(1); f(jnp.ones((8,), jnp.float32))
try:
    rec.round_end(1)
    raise SystemExit("ERROR: sentry did not fire on a forced re-jit")
except RecompileError as e:
    assert "float32[4] -> float32[8]" in str(e), str(e)
    print(f"sentry names the shape change: {e}")
finally:
    rec.close()
EOF
# a seeded 4x compile-time regression MUST fail the (device) trend gate
python - "$RUN/perf.jsonl" "$DIR/perf_compile_regressed.jsonl" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
for r in rows:
    for e in r["device"]["compiles"]:
        e["wall_s"] = e["wall_s"] * 4.0 + 0.2
with open(sys.argv[2], "w") as f:
    f.writelines(json.dumps(r) + "\n" for r in rows)
EOF
if env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --ledger "$DIR/perf_compile_regressed.jsonl" \
    --baseline "$RUN/perf.jsonl" > "$DIR/device_fail.txt"; then
    echo "ERROR: trend gate passed a seeded 4x compile regression"; exit 1
fi
grep -q "device compile regression" "$DIR/device_fail.txt"
echo "device gate OK: honest ledger passes, seeded compile regression fails"

echo "== streaming aggregation: one --agg_mode stream round, fold phase"
# the O(1)-memory fold path (ISSUE 7): uploads fold at arrival, so the
# ledger gains a 'fold' phase and never records a 'staging' one — and
# the same trend gate covers the new ledger shape
STREAM_RUN="$DIR/stream_run"
env JAX_PLATFORMS=cpu python -m fedml_tpu \
    --algo cross_silo --model lr --dataset mnist \
    --client_num_in_total 4 --client_num_per_round 2 --comm_round 3 \
    --frequency_of_the_test 1 --batch_size 4 --log_stdout false \
    --agg_mode stream --norm_clip 5.0 \
    --run_dir "$STREAM_RUN" --perf true --perf_strict true \
    --device_obs true
python - "$STREAM_RUN/perf.jsonl" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert rows, "stream run wrote no ledger lines"
for r in rows:
    assert r["phases"].get("fold", 0) > 0, \
        f"round {r['round']} ledger is missing the fold phase: {r['phases']}"
    assert "staging" not in r["phases"], \
        "stream mode must not stage a cohort buffer"
# the device observatory covers the stream hot path too: the per-arrival
# fold jit compiles exactly once, named in round 0's compile ledger
fold_compiles = [e["fn"] for r in rows for e in r["device"]["compiles"]
                 if e["fn"].startswith("stream_fold")]
assert fold_compiles == ["stream_fold[mean]"], fold_compiles
print(f"fold phase present in all {len(rows)} stream-round ledger lines; "
      f"stream fold compiled once, named in the device ledger")
EOF
env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --ledger "$STREAM_RUN/perf.jsonl" --baseline "$STREAM_RUN/perf.jsonl"
echo "stream ledger OK: fold phase recorded, trend gate green"

# sharded-spine smoke (fedml_tpu/shard_spine): per-device memory ~1/S,
# S=1 bit-parity, fused-finalize kernel named in the compile ledger
# with a non-null MFU, 0 recompiles under strict — the full gates of
# scripts/shard_bench.py at CI size (output to /tmp so the committed
# BENCH_shard.json keeps full-bench numbers)
env JAX_PLATFORMS=cpu python scripts/shard_bench.py --smoke
echo "shard spine smoke OK: per-device scaling + fused finalize gates green"

echo "== asserting the critical-path observatory (ISSUE 17)"
# every ledger line of the chaos run carries a critical_path record
# naming the round's binding constraint, with the attribution
# partitioning the round's wall clock — and the report renders it
python - "$RUN/perf.jsonl" <<'EOF'
import json, sys
from fedml_tpu.obs import critical_path as cpath
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert rows, "no ledger lines"
for r in rows:
    cp = r["critical_path"]
    assert cpath.validate_record(cp, path=f"round {r['round']}") == []
    assert cp["coverage"] >= 0.95, cp
bindings = sorted({r["critical_path"]["binding"] for r in rows})
print(f"critical_path on all {len(rows)} ledger lines; bindings {bindings}")
EOF
grep -q "critical path" "$REPORT"
grep -q "binding constraint" "$REPORT"
# ingest gauges land beside the rest of the telemetry snapshot
grep -q "fedml_ingest_uploads_total" "$RUN/telemetry.prom"
# full cost-contract smoke: four traffic arms + the disabled-mode pin
# (output to /tmp so the committed BENCH_ingest.json keeps full-bench
# numbers), then the committed artifact through the trend gate
env JAX_PLATFORMS=cpu python scripts/ingest_bench.py --smoke
env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --ingest_bench BENCH_ingest.json
echo "ingest smoke OK: critical-path records, gauges, and cost gates green"

echo "== asserting the server-optimizer spine (ISSUE 18)"
# structural pipe-cleaner for the convergence contract: both workloads,
# plain + optimizer arms, controller decisions on every ledger line,
# zero recompiles under --perf_strict (output to /tmp — the committed
# BENCH_opt.json keeps full-bench numbers), then the committed
# artifact through the trend gate, which re-derives the rounds-to-
# target and final-accuracy claims from the committed curves
env JAX_PLATFORMS=cpu python scripts/opt_bench.py --smoke
env JAX_PLATFORMS=cpu python scripts/perf_trend.py \
    --opt_bench BENCH_opt.json
echo "opt smoke OK: server-optimizer arms, pacing decisions, and convergence gates green"
echo "== obs demo OK ($DIR)"

echo "== asserting the zero-copy pipelined ingest (ISSUE 20)"
# pipelined vs inline twin at demo size: identical seeds and arrival
# order, so the per-round global_crc sequences must be bit-identical;
# the pipelined ledger must carry exactly one arena + one screen
# compile entry (re-staging never recompiles), and the pipeline gauges
# must land in the telemetry snapshot
ING_INLINE=$(mktemp -d /tmp/obs_ing_inline.XXXXXX)
ING_PIPED=$(mktemp -d /tmp/obs_ing_piped.XXXXXX)
for mode in "false:$ING_INLINE" "true:$ING_PIPED"; do
  env JAX_PLATFORMS=cpu python -m fedml_tpu \
      --model lr --dataset mnist --algo cross_silo --agg_mode stream \
      --comm_round 3 --client_num_per_round 4 --client_num_in_total 8 \
      --epochs 1 --batch_size 8 --admission on \
      --perf true --perf_strict true --telemetry true \
      --ingest_pipeline "${mode%%:*}" --run_dir "${mode#*:}" \
      --log_stdout false
done
python - "$ING_INLINE/perf.jsonl" "$ING_PIPED/perf.jsonl" <<'EOF2'
import json, sys
def rows(p):
    return [json.loads(l) for l in open(p) if l.strip()]
inline, piped = rows(sys.argv[1]), rows(sys.argv[2])
a = [(r["round"], r["global_crc"]) for r in inline]
b = [(r["round"], r["global_crc"]) for r in piped]
assert a == b and a, f"pipelined != inline: {a} vs {b}"
sizes = piped[-1]["jit_cache_sizes"]
assert sizes.get("ingest_arena") == 1 and sizes.get("ingest_screen") == 1, \
    sizes
assert all(r["recompiles"] == 0 for r in piped[1:]), piped
print(f"pipelined ingest bit-equal over {len(a)} rounds "
      f"(crc {a[-1][1]}); one arena + one screen compile, 0 recompiles")
EOF2
grep -q "fedml_ingest_enqueued_total" "$ING_PIPED/telemetry.prom"
grep -q "fedml_ingest_queue_depth_value" "$ING_PIPED/telemetry.prom"
echo "pipelined ingest smoke OK: bit-parity, compile pins, gauges green"
