"""Convergence-to-target validation against BASELINE.md accuracy rows.

The algebra tests (test_fedavg_oracle.py) prove the math; these prove
LEARNING: runs that hit the reference's published accuracy targets within
its round budgets (benchmark/README.md:12-14).

* synthetic(0.5, 0.5) LR FedAvg — the EXACT reference generator
  (generate_synthetic.py) — target >60 train acc within 200 rounds;
* MNIST-LR twin (hermetic learnable stand-in, power-law sizes, label skew)
  — reference target >75 train acc within 100+ rounds at the reference
  hyperparameters (1000 clients, 10/round, B=10, SGD lr=0.03, E=1);
* RNN char-LM (the shakespeare trainer flavor) on a deterministic
  next-token task — >90% token accuracy, proving the NLP family learns
  federatedly (mirrors the transformer learning test in
  test_ring_attention.py via the shared identity_lm_data fixture).

All are slow-marked: they run tens-to-hundreds of cohort rounds on CPU.
"""

import pytest

from fedml_tpu.algorithms import FedAvg, FedAvgConfig
from fedml_tpu.data.synthetic import load_synthetic, mnist_learnable_twin
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


@pytest.mark.slow
def test_synthetic_alpha_beta_lr_to_60():
    """benchmark/README.md:14 — synthetic(α,β) LR FedAvg: >60 train acc,
    30 clients, 10/round, B=10, SGD lr=0.01, E=1, <=200 rounds."""
    data = load_synthetic(alpha=0.5, beta=0.5, num_users=30, batch_size=10,
                          seed=0)
    wl = ClassificationWorkload(
        LogisticRegression(input_dim=60, output_dim=10), num_classes=10,
        grad_clip_norm=None)
    cfg = FedAvgConfig(comm_round=200, client_num_per_round=10, epochs=1,
                       batch_size=10, lr=0.01, frequency_of_the_test=1000,
                       seed=0)
    algo = FedAvg(wl, data, cfg)
    params = algo.run()
    acc = algo.evaluate_global(params)["train_acc"]
    assert acc > 0.60, f"synthetic(0.5,0.5) train acc {acc:.3f} <= 0.60"


@pytest.mark.slow
def test_rnn_charlm_federated_learning_to_target():
    """The RNN family LEARNS federatedly, not just runs (the shakespeare
    trainer flavor): a 2-layer LSTM char-LM on a deterministic
    next-token task (y_t = x_t) must reach >90% token accuracy — the same
    learning-proof pattern as the transformer test
    (test_ring_attention.py)."""
    from conftest import identity_lm_data
    from fedml_tpu.models import RNNOriginalFedAvg
    from fedml_tpu.trainer.workload import NWPWorkload

    model = RNNOriginalFedAvg(vocab_size=12, embedding_dim=8, hidden_size=32)
    data = identity_lm_data()
    cfg = FedAvgConfig(comm_round=100, client_num_per_round=4, epochs=2,
                       batch_size=8, lr=0.5, frequency_of_the_test=99)
    algo = FedAvg(NWPWorkload(model), data, cfg)
    algo.run()
    assert algo.history[-1]["train_acc"] > 0.9, algo.history[-1]


@pytest.mark.slow
def test_mnist_lr_to_75():
    """benchmark/README.md:12 — MNIST LR FedAvg: >75 train acc @ >100
    rounds, 1000 clients, 10/round, B=10, SGD lr=0.03, E=1 (hermetic
    learnable twin standing in for LEAF MNIST; twin noise calibrated so
    the >100-round budget is genuinely needed — 0.54 at round 30,
    0.86 at 119 — instead of saturating at 1.0 within 30 rounds)."""
    data = mnist_learnable_twin(num_clients=1000, batch_size=10, seed=0)
    wl = ClassificationWorkload(
        LogisticRegression(input_dim=784, output_dim=10), num_classes=10,
        grad_clip_norm=None)
    cfg = FedAvgConfig(comm_round=120, client_num_per_round=10, epochs=1,
                       batch_size=10, lr=0.03, frequency_of_the_test=1000,
                       seed=0)
    algo = FedAvg(wl, data, cfg)
    params = algo.run()
    acc = algo.evaluate_global(params)["train_acc"]
    assert acc > 0.75, f"MNIST-LR twin train acc {acc:.3f} <= 0.75"


REF_CURVES = "/root/reference/fedml_api/model/cv/pretrained/CIFAR10/resnet56"


@pytest.mark.skipif(not __import__("os").path.isdir(REF_CURVES),
                    reason="reference curves not mounted")
def test_reference_curve_reader_parses_published_cifar10():
    """The stored resnet56/CIFAR10 trajectory parses and matches
    BASELINE.md's expectations: ~top-1 >90 by the end, monotone learning
    shape (pretrained/CIFAR10/resnet56/train_metrics)."""
    import os
    from fedml_tpu.utils.reference_curves import (curve_is_learning,
                                                  load_reference_curve)
    curve = load_reference_curve(os.path.join(REF_CURVES, "train_metrics"))
    acc = [e["train_accTop1"] for e in curve]
    assert len(acc) > 50
    assert acc[-1] > 90.0
    assert curve_is_learning(acc, min_gain=10.0)


@pytest.mark.slow
def test_noniid_cifar_twin_learning_curve_shape():
    """A non-IID (Dirichlet-partitioned) CIFAR run whose accuracy series
    must show the same qualitative shape as the published reference curve
    (rising tail; VERDICT round-1 item 4). Small CNN stands in for resnet56
    so the run fits CPU; the partition/augment path is the real one."""
    import jax
    import flax.linen as nn
    from fedml_tpu.algorithms import FedAvg, FedAvgConfig
    from fedml_tpu.data import load_data
    from fedml_tpu.trainer.workload import ClassificationWorkload
    from fedml_tpu.utils.reference_curves import curve_is_learning

    data = load_data("cifar10", data_dir=None, batch_size=32, client_num=8,
                     partition_method="hetero", partition_alpha=0.5, seed=0)

    class SmallCNN(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.relu(nn.Conv(16, (3, 3), strides=2)(x))
            x = nn.relu(nn.Conv(32, (3, 3), strides=2)(x))
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(10)(x)

    wl = ClassificationWorkload(SmallCNN(), num_classes=10,
                                grad_clip_norm=None)
    cfg = FedAvgConfig(comm_round=30, client_num_per_round=4, epochs=1,
                       batch_size=32, lr=0.05, frequency_of_the_test=5,
                       seed=0)
    algo = FedAvg(wl, data, cfg)
    algo.run()
    accs = [h["train_acc"] for h in algo.history]
    assert curve_is_learning(accs, min_gain=0.05), accs


@pytest.mark.slow
def test_flagship_retention_proxy_on_learnable_cifar_twin():
    """Hermetic proxy of the flagship CIFAR10 row (benchmark/README.md:105
    — centralized 93.19 vs federated 87.12, retention 0.935): on the
    LDA(0.5)-partitioned MULTI-MODE learnable CIFAR twin (modes=4 gives
    each class four prototypes — intra-class variation that makes the
    non-IID gap REAL; the old single-prototype twin saturated at
    fed == cent == 1.0, a ratio that probed nothing), a conv net trained
    with the flagship choreography (10 clients, full participation) must

    * show the gap mid-training (measured: test acc 0.40 at round 10 vs
      centralized 1.00 — the federated run has real work to do), and
    * CLOSE it by the full budget: retention >= 0.94, above the
      published 0.935 ratio (measured 0.992 at pinning time).

    scripts/flagship_accuracy.py runs the full-size resnet56 version of
    this on TPU; this CI tier keeps partition/engine/optimizer real and
    shrinks only the model and round budget."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from fedml_tpu.algorithms.centralized import CentralizedTrainer
    from fedml_tpu.data.synthetic import (FLAGSHIP_TWIN_KWARGS,
                                          cifar_learnable_twin)

    data = cifar_learnable_twin(num_clients=10, samples_per_client=120,
                                partition_alpha=0.5, batch_size=32,
                                seed=0, **FLAGSHIP_TWIN_KWARGS)

    class SmallCNN(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.relu(nn.Conv(16, (3, 3), strides=2)(x))
            x = nn.relu(nn.Conv(32, (3, 3), strides=2)(x))
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(10)(x)

    wl = ClassificationWorkload(SmallCNN(), num_classes=10)
    rounds, epochs = 40, 2
    algo = FedAvg(wl, data, FedAvgConfig(
        comm_round=rounds, client_num_per_round=10, epochs=epochs,
        batch_size=32, lr=0.05, frequency_of_the_test=10, seed=0))
    algo.run()
    fed_acc = algo.history[-1]["test_acc"]
    mid_acc = next((h["test_acc"] for h in algo.history
                    if h["round"] == 10), None)
    assert mid_acc is not None, \
        ("eval cadence no longer covers round 10: "
         f"{[h['round'] for h in algo.history]}")

    trainer = CentralizedTrainer(wl, lr=0.05, epochs_per_call=1)
    pooled = {k: jnp.asarray(v) for k, v in data.train_global.items()}
    params_c = wl.init(jax.random.key(0),
                       jax.tree.map(lambda v: v[0], pooled))
    rng = jax.random.key(1)
    for _ in range(rounds * epochs):
        rng, r = jax.random.split(rng)
        params_c, _ = trainer.local_train(params_c, pooled, r)
    cent_acc = trainer.metrics(
        params_c, {k: jnp.asarray(v)
                   for k, v in data.test_global.items()})["acc"]

    assert cent_acc > 0.90, f"centralized twin too weak: {cent_acc}"
    # the proxy must PROBE the gap: mid-training the federated model is
    # far from centralized (else the task is trivially separable again)
    assert mid_acc < 0.7 * cent_acc, (mid_acc, cent_acc)
    retention = fed_acc / cent_acc
    assert retention >= 0.94, (fed_acc, cent_acc, retention)
