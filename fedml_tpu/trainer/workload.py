"""Client workload contract — the TPU-native ``ModelTrainer``.

The reference seam is the framework-neutral ``ModelTrainer`` ABC
(``fedml_core/trainer/model_trainer.py:4-37``: get/set params, train, test).
Here the seam is *functional*: a `Workload` bundles pure functions
(init / loss / metrics) over a flax model, so trainers can `jax.grad`,
`vmap` (stacked clients), and `shard_map` (mesh-sharded cohorts) it.

The three concrete workloads mirror the reference's three trainer flavors
(fedml_api/standalone/fedavg/my_model_trainer_{classification,nwp,
tag_prediction}.py):

* `ClassificationWorkload` — softmax CE, top-1 accuracy, grad-clip 1.0
  (my_model_trainer_classification.py:44).
* `NWPWorkload` — per-position softmax CE over sequence logits, ignoring
  padding-id targets (next-word/char prediction).
* `TagPredictionWorkload` — multi-label: BCE-with-logits, exact-match +
  precision/recall (my_model_trainer_tag_prediction.py; eval thresholds at
  0.5 like MyModelTrainer.test, MyModelTrainer.py:76-82).

Batches are dicts ``{"x": [B, ...], "y": [B, ...], "mask": [B]}``; the mask
makes padded cohort batches exact — a padded row contributes nothing to loss,
gradient, or metrics, so sample-weighted FedAvg stays bit-honest.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

Pytree = Any
Batch = Dict[str, jax.Array]


def make_client_optimizer(name: str, lr: float, wd: float = 0.0) -> optax.GradientTransformation:
    """Client optimizer parity (my_model_trainer_classification.py:27-31):
    "sgd" -> plain SGD(lr); anything else -> Adam(lr, weight_decay=wd,
    amsgrad=True).  Torch couples wd into the gradient before the moment
    updates, so add_decayed_weights precedes the amsgrad transform."""
    if name == "sgd":
        return optax.sgd(lr)
    return optax.chain(
        optax.add_decayed_weights(wd),
        optax.scale_by_amsgrad(),
        optax.scale(-lr),
    )


@dataclasses.dataclass(frozen=True)
class Workload:
    """Pure-function training contract.

    loss_fn(params, batch, rng, train) -> (scalar loss, aux dict).  For
    stateful models (BatchNorm running stats) aux carries ``"state"``: the
    updated non-trained collections, which the local trainer splices back
    into params after the optimizer step (local_sgd.py).  FedAvg then
    averages running stats along with weights — exactly what the reference's
    state_dict averaging does (FedAVGAggregator.py:72-80 iterates ALL
    state_dict keys, stats included).

    metric_fn(params, batch) -> dict of *summable* metrics
    (must include "correct", "loss_sum", "total").
    """
    model: Any  # flax linen module
    loss_fn: Callable[[Pytree, Batch, jax.Array, bool], tuple]
    metric_fn: Callable[[Pytree, Batch], Dict[str, jax.Array]]
    grad_clip_norm: Optional[float] = None
    stateful: bool = False  # params = full variables dict incl. batch_stats

    def init(self, rng: jax.Array, sample_batch: Batch) -> Pytree:
        variables = self.model.init(rng, sample_batch["x"])
        if self.stateful:
            return dict(variables)
        return variables["params"]

    def apply(self, params: Pytree, x: jax.Array, train: bool = False,
              rng: Optional[jax.Array] = None) -> jax.Array:
        kwargs = {}
        if rng is not None:
            kwargs["rngs"] = {"dropout": rng}
        variables = params if self.stateful else {"params": params}
        if self.stateful and train:
            out, _ = self.model.apply(variables, x, train=True,
                                      mutable=["batch_stats"], **kwargs)
            return out
        return self.model.apply(variables, x, train=train, **kwargs)


def _masked_mean(values: jax.Array, mask: jax.Array) -> jax.Array:
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(values * mask) / denom


def cast_floats(tree: Pytree, dtype) -> Pytree:
    """Cast floating leaves to ``dtype`` (ints/keys untouched)."""
    return jax.tree.map(
        lambda v: v.astype(dtype)
        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else v, tree)


def ClassificationWorkload(model, num_classes: int,
                           grad_clip_norm: Optional[float] = 1.0,
                           stateful: bool = False,
                           compute_dtype=None) -> Workload:
    """Softmax cross-entropy on logits, batch-mean over valid rows (the
    torch ``nn.CrossEntropyLoss()`` default reduction).  ``stateful=True``
    for BatchNorm models: params is the full variables dict and updated
    running stats ride the loss aux (see Workload docstring).

    ``compute_dtype=jnp.bfloat16`` enables mixed precision the TPU way
    (SURVEY.md "MXU" guidance): master params, gradients, and the optimizer
    stay f32; the forward/backward model compute — conv/matmul inputs AND
    weights — is cast to bf16, halving HBM traffic and doubling MXU rate.
    The CE loss is always computed in f32 (softmax is range-sensitive)."""

    def loss_fn(params, batch, rng, train):
        kwargs = {"rngs": {"dropout": rng}} if rng is not None else {}
        x = batch["x"]
        if compute_dtype is not None:
            if stateful:
                # keep BatchNorm running stats f32: their momentum update
                # adds increments far below bf16's 8-bit mantissa
                params = {k: (v if k == "batch_stats"
                              else cast_floats(v, compute_dtype))
                          for k, v in params.items()}
            else:
                params = cast_floats(params, compute_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                x = x.astype(compute_dtype)
        if stateful:
            logits, new_state = model.apply(
                params, x, train=train,
                mutable=["batch_stats"], **kwargs)
        else:
            logits = model.apply({"params": params}, x,
                                 train=train, **kwargs)
        logits = logits.astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
        loss = _masked_mean(ce, batch["mask"])
        aux = {"loss": loss}
        if stateful:
            new_state = dict(new_state)
            if compute_dtype is not None:
                # running stats rejoin the f32 master tree
                new_state = cast_floats(new_state, jnp.float32)
            aux["state"] = new_state
        return loss, aux

    def metric_fn(params, batch):
        variables = params if stateful else {"params": params}
        logits = model.apply(variables, batch["x"], train=False)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
        pred = jnp.argmax(logits, axis=-1)
        mask = batch["mask"]
        out = {
            "correct": jnp.sum((pred == batch["y"]) * mask),
            "loss_sum": jnp.sum(ce * mask),
            "total": jnp.sum(mask),
        }
        if num_classes > 5:
            # top-5 parity with the reference's accTop5 curves
            # (pretrained/*/train_metrics)
            top5 = jax.lax.top_k(logits, 5)[1]
            in5 = jnp.any(top5 == batch["y"][..., None], axis=-1)
            out["correct_top5"] = jnp.sum(in5 * mask)
        return out

    return Workload(model=model, loss_fn=loss_fn, metric_fn=metric_fn,
                    grad_clip_norm=grad_clip_norm, stateful=stateful)


def make_nwp_loss_metrics(forward, pad_id: int = 0):
    """THE single home of the NWP loss/metric semantics: per-position CE
    averaged over non-pad positions of valid rows, plus summable
    correct/loss_sum/total metrics (my_model_trainer_nwp.py semantics,
    where torch CE with [B, V, T] logits means per-position CE).

    ``forward(params, x, rng, train) -> (logits [B, T, V], extra_loss)``
    abstracts the model application — NWPWorkload's flax apply (with
    dtype casting and the MoE balance-loss capture riding ``extra_loss``)
    and the pipeline workload's GPipe forward (parallel/pipeline.py) both
    build on this, so the masking/metric math cannot drift between them.
    """

    def _position_mask(batch):
        tok_valid = (batch["y"] != pad_id).astype(jnp.float32)
        return tok_valid * batch["mask"][:, None]

    def loss_fn(params, batch, rng, train):
        logits, extra = forward(params, batch["x"], rng, train)
        logits = logits.astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
        m = _position_mask(batch)
        loss = jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0) + extra
        return loss, {"loss": loss}

    def metric_fn(params, batch):
        logits, _ = forward(params, batch["x"], None, False)
        logits = logits.astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
        pred = jnp.argmax(logits, axis=-1)
        m = _position_mask(batch)
        return {
            "correct": jnp.sum((pred == batch["y"]) * m),
            "loss_sum": jnp.sum(ce * m),
            "total": jnp.sum(m),
        }

    return loss_fn, metric_fn


def NWPWorkload(model, pad_id: int = 0,
                grad_clip_norm: Optional[float] = None,
                compute_dtype=None) -> Workload:
    """Next-word/char prediction over [B, T, V] logits
    (make_nwp_loss_metrics has the loss semantics).

    ``compute_dtype=jnp.bfloat16``: casts params for bf16 weight loads and
    f32 master/CE as in ClassificationWorkload — but flax RNN cells promote
    to their own ``dtype``, so the MODEL must also be built with
    ``dtype=bfloat16`` (RNNOriginalFedAvg/RNNStackOverflow take it;
    create_workload wires both) or the recurrent matmuls stay f32."""

    def forward(params, x, rng, train):
        if compute_dtype is not None:
            params = cast_floats(params, compute_dtype)
        if getattr(model, "moe_experts", 0) and train:
            # capture the Switch load-balance terms sown per MoE layer
            # (models/moe.py); plain applies elsewhere no-op the sow.
            # Switch eq. 4: each layer's aux SUMS into the loss at weight
            # alpha (not a mean — a deeper stack gets more total pressure)
            logits, sown = model.apply({"params": params}, x,
                                       train=train, mutable=["losses"])
            extra = model.moe_aux_weight * sum(
                jax.tree.leaves(sown.get("losses", {})))
            return logits, extra
        return model.apply({"params": params}, x, train=train), 0.0

    loss_fn, metric_fn = make_nwp_loss_metrics(forward, pad_id)
    return Workload(model=model, loss_fn=loss_fn, metric_fn=metric_fn,
                    grad_clip_norm=grad_clip_norm)


def TagPredictionWorkload(model, grad_clip_norm: Optional[float] = None) -> Workload:
    """Multi-label tag prediction (stackoverflow_lr): BCE-with-logits loss;
    eval thresholds sigmoid>0.5 with exact-match accuracy plus summed
    precision/recall (MyModelTrainer.test, MyModelTrainer.py:76-82)."""

    def loss_fn(params, batch, rng, train):
        logits = model.apply({"params": params}, batch["x"], train=train)
        bce = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, batch["y"]), axis=-1)
        loss = _masked_mean(bce, batch["mask"])
        return loss, {"loss": loss}

    def metric_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"], train=False)
        bce = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, batch["y"]), axis=-1)
        mask = batch["mask"]
        pred = (logits > 0.0).astype(jnp.float32)  # sigmoid(z) > .5 <=> z > 0
        y = batch["y"]
        exact = jnp.all(pred == y, axis=-1).astype(jnp.float32)
        tp = jnp.sum(y * pred, axis=-1)
        precision = tp / (jnp.sum(pred, axis=-1) + 1e-13)
        recall = tp / (jnp.sum(y, axis=-1) + 1e-13)
        return {
            "correct": jnp.sum(exact * mask),
            "loss_sum": jnp.sum(bce * mask),
            "total": jnp.sum(mask),
            "precision_sum": jnp.sum(precision * mask),
            "recall_sum": jnp.sum(recall * mask),
        }

    return Workload(model=model, loss_fn=loss_fn, metric_fn=metric_fn,
                    grad_clip_norm=grad_clip_norm)
