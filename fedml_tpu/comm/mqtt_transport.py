"""MQTT transport bridge for device / IoT federation (parity feature).

Reference equivalent: ``MqttCommManager``
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:47-120):
pub/sub over a broker with the topic scheme ``fedml_<receiver>`` for
server→client and ``fedml0_<sender>`` for client→server, JSON payloads.

Differences: broker host/port are constructor args (the reference hardcodes
a broker IP in ``client_manager.py:23-26``); payloads are the binary array
frames of `fedml_tpu.comm.message` published as MQTT bytes.  ``paho-mqtt``
is used when installed; without it the transport falls back to the
in-repo ``MiniMqttClient`` (comm/mqtt_client.py), which speaks the same
MQTT 3.1.1 wire protocol over a real TCP socket — so the transport is
fully functional in this sandbox against the in-repo loopback broker
(comm/mqtt_broker.py) or any external MQTT 3.1.1 daemon.

Validation: the fake-paho test (tests/test_comm.py) pins the topic
scheme + payload codec in isolation, and tests/test_mqtt_broker.py runs
the FULL cross-silo FedAvg choreography over real TCP MQTT framing
(MiniMqttClient ↔ MqttBroker) — the live-broker interop the reference
only ever ran manually (mqtt_comm_manager.py has no test).
"""

from __future__ import annotations

import logging
import queue

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

try:
    import paho.mqtt.client as _mqtt
    HAVE_MQTT = True
except ImportError:  # pragma: no cover - environment without paho-mqtt
    _mqtt = None
    HAVE_MQTT = False

_STOP = object()
_LOST = object()   # unexpected broker disconnect (MiniMqttClient)


class MqttTransport(Transport):
    def __init__(self, node_id: int, broker_host: str, broker_port: int = 1883,
                 topic_prefix: str = "fedml_tpu"):
        super().__init__()
        self.node_id = node_id
        self.topic_prefix = topic_prefix
        self.broker_host = broker_host
        self.broker_port = broker_port
        self._inbox: "queue.Queue" = queue.Queue()
        self._stopped = False
        self._m_torn = telemetry.get_registry().counter(
            "fedml_wire_torn_frames_total")
        cid = f"{topic_prefix}_{node_id}"
        if not HAVE_MQTT:
            # no paho: the in-repo MQTT 3.1.1 client speaks the same wire
            # protocol over a real socket (works against mqtt_broker.py or
            # any external 3.1.1 daemon).  An unexpected broker loss wakes
            # run() with ConnectionError instead of wedging the inbox.
            from fedml_tpu.comm.mqtt_client import MiniMqttClient
            self._client = MiniMqttClient(client_id=cid)
            self._client.on_disconnect = (
                lambda c, u, rc: self._inbox.put(_LOST))
        elif hasattr(_mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
            self._client = _mqtt.Client(_mqtt.CallbackAPIVersion.VERSION1,
                                        client_id=cid)
        else:
            self._client = _mqtt.Client(client_id=cid)
        self._client.on_message = self._on_message
        self._client.connect(broker_host, broker_port)
        self._client.subscribe(self._topic(node_id), qos=1)
        self._client.loop_start()

    def _topic(self, node_id: int) -> str:
        return f"{self.topic_prefix}/{node_id}"

    def _on_message(self, client, userdata, mqtt_msg) -> None:
        try:
            msg = Message.from_bytes(mqtt_msg.payload)
        except ValueError as exc:
            # a torn frame must not kill the broker callback thread: drop
            # it like a lost publish and let the round policy recover
            self._m_torn.inc()
            log.warning("node %d: dropping undecodable %d-byte frame from "
                        "%s: %s", self.node_id, len(mqtt_msg.payload),
                        mqtt_msg.topic, exc)
            return
        self._inbox.put(msg)

    def send_message(self, msg: Message) -> None:
        # shared-aware: a send_many sibling reuses the fan-out's encoded
        # block (one header encode + one memcpy per receiver)
        data = msg.to_bytes()
        self._obs_send(msg, len(data))
        self._client.publish(self._topic(msg.receiver_id), data, qos=1)

    def reconnect(self) -> None:
        """Tear down and re-run the CONNECT/SUBSCRIBE handshake against the
        same broker — the hook `ResilientTransport` invokes between retry
        attempts after a publish fails (broker restarted, TCP reset)."""
        if self._stopped:
            return
        try:
            self._client.loop_stop()
            self._client.disconnect()
        except Exception:  # noqa: BLE001 — the old session may be half-dead
            pass
        if hasattr(self._client, "_closing"):  # MiniMqttClient
            self._client._closing = False
        self._client.connect(self.broker_host, self.broker_port)
        self._client.subscribe(self._topic(self.node_id), qos=1)
        self._client.loop_start()

    def run(self) -> None:
        while True:
            item = self._inbox.get()
            if item is _STOP:
                return
            if item is _LOST:
                raise ConnectionError(
                    "MQTT broker connection lost (unexpected disconnect)")
            self._notify(item)

    def stop(self) -> None:
        if self._stopped:
            return  # idempotent: actor finish + fixture teardown both stop
        self._stopped = True
        self._inbox.put(_STOP)
        self._client.loop_stop()
        self._client.disconnect()
