#!/usr/bin/env python
"""Streaming-vs-stack aggregation memory/time bench (ISSUE 7 acceptance).

Simulates one server aggregating N admitted uploads per round at
N ∈ {64, 256, 1024} under both ``--agg_mode`` regimes:

* **stack** — the staged ``[cohort, ...]`` host buffer + one defended
  jit (the PR 5 path, buffer released at round close);
* **stream** — `core.stream_agg.StreamingAggregator`: each upload folds
  into O(model) running state at arrival, finalize is one division;
* **stream_reservoir** — the robust-rule regime: a bounded K-slot
  reservoir feeds ``trimmed_mean`` (memory O(K * model), flat in N).

Each (mode, N) arm runs in a FRESH SUBPROCESS so peak RSS is the arm's
own, not an artifact of allocator history: round 1 pays the compiles
(warmup), then the measured round tracks VmRSS with the PR 6
`RssSampler` plus an explicit sample after every arrival, against a
post-gc baseline taken between the rounds.

CPU-honest contract (bench.py / wirebench): numbers are host wall-clock
on whatever ``jax.default_backend()`` reports — labeled, never dressed
as accelerator throughput.  Upload *generation* time is excluded from
``round_s`` (a server receives uploads; it does not synthesize them).

Acceptance (parent process, exit 1 on failure):
  * stream peak RSS flat in N: peak(N=1024) <= 1.15 x peak(N=64);
  * stack marginal RSS ~linear in N (delta grows >= 4x from 64 to 1024
    at these sizes — the cohort buffer dominates);
  * ``mean`` checksums bit-identical between stream and stack arms.

  python scripts/stream_bench.py             # full: ~2MB model, writes
                                             # BENCH_stream.json
  python scripts/stream_bench.py --smoke     # CI-sized, /tmp output
"""

import argparse
import gc
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

MB = 1024 * 1024


def _template(model_mb: float):
    import numpy as np
    n = int(model_mb * MB / 4)
    return {"dense": {"kernel": np.ones((n // 2,), np.float32),
                      "bias": np.zeros((n - n // 2,), np.float32)},
            "step": np.int32(0)}


def _upload(tmpl, i: int):
    """Deterministic per-index upload — both arms regenerate the SAME
    stream, so a matching checksum proves the aggregates match."""
    import numpy as np
    rng = np.random.RandomState(1000 + i)
    return {"dense": {"kernel": tmpl["dense"]["kernel"]
                      + rng.standard_normal(
                          tmpl["dense"]["kernel"].shape).astype(np.float32),
                      "bias": tmpl["dense"]["bias"]
                      + rng.standard_normal(
                          tmpl["dense"]["bias"].shape).astype(np.float32)},
            "step": np.int32(i)}


def _weight(i: int) -> float:
    return float(10 * (i % 7 + 1))


def _checksum(tree) -> float:
    import jax
    import numpy as np
    return float(sum(np.abs(np.asarray(l)).astype(np.float64).sum()
                     for l in jax.tree.leaves(tree)))


def _run_child(mode: str, n: int, model_mb: float,
               reservoir_k: int) -> dict:
    """One arm: warmup round, then the measured round. Prints one JSON
    line on stdout."""
    import jax
    import numpy as np

    from fedml_tpu.obs.perf import RssSampler, read_rss_bytes

    tmpl = _template(model_mb)
    norm_clip = 0.0  # pure mean: the checksum-identity arm
    t_health = [0.0]

    if mode in ("stream", "stream_reservoir", "stream_health"):
        from fedml_tpu.core.stream_agg import StreamingAggregator
        agg = StreamingAggregator(
            tmpl,
            method="trimmed_mean" if mode == "stream_reservoir" else "mean",
            norm_clip=norm_clip, reservoir_k=reservoir_k, trim_frac=0.1)
        health = None
        if mode == "stream_health":
            # the ISSUE 9 acceptance arm: the health observatory rides
            # the same fold-at-arrival seam — worst case (norm=None, so
            # health pays its own norm pass beside the alignment dot)
            from fedml_tpu.obs.health import HealthAccumulator
            health = HealthAccumulator(kind="params", alarms=False)

        def round_fn(sample):
            agg.reset(tmpl)
            t_health[0] = 0.0
            if health is not None:
                t0 = time.perf_counter()
                health.round_start(0, tmpl, expected=range(1, n + 1))
                t_health[0] += time.perf_counter() - t0
            t_arr = 0.0
            for i in range(n):
                u = _upload(tmpl, i)
                if health is not None:
                    t0 = time.perf_counter()
                    health.observe_admitted(i + 1, u, _weight(i))
                    t_health[0] += time.perf_counter() - t0
                t0 = time.perf_counter()
                agg.fold(u, _weight(i))
                t_arr += time.perf_counter() - t0
                del u
                sample()
            t0 = time.perf_counter()
            out = agg.finalize(0)
            jax.block_until_ready(out)
            t_fin = time.perf_counter() - t0
            if health is not None:
                t0 = time.perf_counter()
                health.round_end(0, new_global=jax.tree.map(np.asarray, out))
                t_health[0] += time.perf_counter() - t0
            sample()
            return out, t_arr, t_fin
    else:
        from fedml_tpu.robust.defense import make_defended_aggregate
        fn = make_defended_aggregate("mean", norm_clip=norm_clip)

        def round_fn(sample):
            # the live server's staging path: the [cohort, ...] buffer
            # fills at arrival, one defended jit at the barrier, buffer
            # released at round close (PR 7's stack-mode contract)
            staging = jax.tree.map(
                lambda l: np.empty((n,) + np.shape(l),
                                   np.asarray(l).dtype), tmpl)
            leaves = jax.tree.leaves(staging)
            w = np.zeros(n, np.float32)
            t_arr = 0.0
            for i in range(n):
                u = _upload(tmpl, i)
                t0 = time.perf_counter()
                for buf, leaf in zip(leaves, jax.tree.leaves(u)):
                    buf[i] = np.asarray(leaf)
                w[i] = _weight(i)
                t_arr += time.perf_counter() - t0
                del u
                sample()
            t0 = time.perf_counter()
            out = fn(tmpl, staging, w, 0)
            jax.block_until_ready(out)
            t_fin = time.perf_counter() - t0
            sample()
            del staging, leaves
            return out, t_arr, t_fin

    # round 1: compiles + allocator warmup — never measured
    out, _, _ = round_fn(lambda: None)
    del out
    gc.collect()
    baseline = read_rss_bytes()
    sampler = RssSampler(interval_s=0.002).start()
    out, t_arr, t_fin = round_fn(sampler.sample)
    peak = sampler.peak_bytes
    sampler.stop()
    checksum = _checksum(out)
    cache = None
    if mode in ("stream", "stream_reservoir", "stream_health"):
        cache = agg._cache_size()
        assert cache == 1, f"fold jit recompiled: cache={cache}"
    line = {
        "mode": mode, "n": n, "model_mb": model_mb,
        "backend": jax.default_backend(),
        "reservoir_k": reservoir_k if mode == "stream_reservoir" else None,
        "baseline_rss_mb": round(baseline / MB, 1),
        "peak_rss_mb": round(peak / MB, 1),
        "peak_delta_mb": round((peak - baseline) / MB, 1),
        "arrival_s": round(t_arr, 4),
        "finalize_s": round(t_fin, 4),
        "round_s": round(t_arr + t_fin, 4),
        "checksum": checksum,
        "fold_jit_cache_size": cache,
    }
    if mode == "stream_health":
        line["health_s"] = round(t_health[0], 4)
        line["health_overhead_frac"] = round(
            t_health[0] / max(t_arr + t_fin + t_health[0], 1e-12), 4)
    return line


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized: tiny model, N in {8, 32}, /tmp out")
    ap.add_argument("--health", action="store_true",
                    help="ISSUE 9 acceptance arms: stream with the "
                         "health observatory folding at arrival vs "
                         "plain stream — peak RSS must stay flat "
                         "N=64->1024 and the aggregate stays checksum-"
                         "identical (health observes, never perturbs); "
                         "writes BENCH_health.json")
    ap.add_argument("--out", default=None,
                    help="artifact path ('' skips writing); default "
                         "BENCH_stream.json / BENCH_health.json, /tmp "
                         "for --smoke")
    ap.add_argument("--model_mb", type=float, default=None)
    ap.add_argument("--reservoir_k", type=int, default=64)
    ap.add_argument("--child", nargs=2, metavar=("MODE", "N"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    model_mb = args.model_mb or (0.25 if args.smoke else 2.0)
    if args.child:
        mode, n = args.child[0], int(args.child[1])
        print(json.dumps(_run_child(mode, n, model_mb, args.reservoir_k)))
        return 0

    if args.out is None:
        base = "BENCH_health.json" if args.health else "BENCH_stream.json"
        args.out = (f"/tmp/{base[:-5]}_smoke.json" if args.smoke else base)
    sizes = [8, 32] if args.smoke else [64, 256, 1024]
    modes = (("stream", "stream_health") if args.health
             else ("stack", "stream", "stream_reservoir"))
    arms = {}
    for mode in modes:
        for n in sizes:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--child", mode, str(n),
                   "--model_mb", str(model_mb),
                   "--reservoir_k", str(args.reservoir_k)]
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=1800)
            if out.returncode != 0:
                print(out.stdout, out.stderr, file=sys.stderr)
                raise RuntimeError(f"arm {mode}/N={n} failed")
            arms[(mode, n)] = json.loads(out.stdout.strip().splitlines()[-1])
            a = arms[(mode, n)]
            print(f"  {mode:>17} N={n:<5} peak {a['peak_rss_mb']:>8.1f}MB "
                  f"(Δ {a['peak_delta_mb']:>7.1f}MB)  round "
                  f"{a['round_s']:.3f}s", file=sys.stderr)

    lo, hi = sizes[0], sizes[-1]
    if args.health:
        health_flat = (arms[("stream_health", hi)]["peak_rss_mb"]
                       / max(arms[("stream_health", lo)]["peak_rss_mb"],
                             1e-9))
        # the observatory adds O(model) f64 state, never O(cohort):
        # its peak must track the plain stream arm within the same band
        vs_stream = (arms[("stream_health", hi)]["peak_rss_mb"]
                     / max(arms[("stream", hi)]["peak_rss_mb"], 1e-9))
        checksums_equal = all(
            arms[("stream_health", n)]["checksum"]
            == arms[("stream", n)]["checksum"] for n in sizes)
        # per-upload health cost must scale LINEARLY in N (O(model) work
        # per arrival, no cohort-sized state to rescan): the hi arm's
        # per-upload health time stays within noise of the lo arm's
        per_upload = {n: arms[("stream_health", n)]["health_s"] / n
                      for n in sizes}
        health_linear = per_upload[hi] <= per_upload[lo] * 2.0
        acceptance = {
            "health_peak_ratio_hi_over_lo": round(health_flat, 3),
            "health_flat_leq_1_15x": health_flat <= 1.15,
            "health_vs_stream_peak_ratio": round(vs_stream, 3),
            "health_within_1_15x_of_stream": vs_stream <= 1.15,
            "checksums_identical_health_on_vs_off": checksums_equal,
            "health_per_upload_s": {str(n): round(per_upload[n], 6)
                                    for n in sizes},
            "health_per_upload_flat_in_n": health_linear,
            # NOTE: the "<5% of round_s" acceptance is measured against
            # the LIVE perf.jsonl ledger (run_health_demo.sh), where
            # round_s includes training — this bench isolates the bare
            # server aggregation, so the fraction here is the honest
            # aggregation-only overhead, not the round-level one
            "max_health_overhead_frac_of_bare_aggregation": max(
                arms[("stream_health", n)]["health_overhead_frac"]
                for n in sizes),
        }
        details = {
            "backend": arms[("stream", lo)]["backend"],
            "note": ("CPU-container wall-clock + VmRSS watermark bench — "
                     "the health observatory folding per-upload stats at "
                     "arrival beside the stream aggregate; upload "
                     "generation excluded, not a training-throughput "
                     "claim"),
            "smoke": bool(args.smoke),
            "model_mb": model_mb,
            "cohort_sizes": sizes,
            "arms": {f"{m}_n{n}": arms[(m, n)] for (m, n) in arms},
            "acceptance": acceptance,
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(details, f, indent=1)
                f.write("\n")
        print(json.dumps({"bench": "health_obs", "out": args.out or None,
                          **acceptance}))
        ok = (acceptance["health_flat_leq_1_15x"]
              and acceptance["health_within_1_15x_of_stream"]
              and acceptance["health_per_upload_flat_in_n"]
              and checksums_equal)
        return 0 if ok else 1

    stream_flat = (arms[("stream", hi)]["peak_rss_mb"]
                   / max(arms[("stream", lo)]["peak_rss_mb"], 1e-9))
    reservoir_flat = (arms[("stream_reservoir", hi)]["peak_rss_mb"]
                      / max(arms[("stream_reservoir", lo)]["peak_rss_mb"],
                            1e-9))
    stack_delta_growth = (arms[("stack", hi)]["peak_delta_mb"]
                          / max(arms[("stack", lo)]["peak_delta_mb"], 1e-9))
    checksums_equal = all(
        arms[("stream", n)]["checksum"] == arms[("stack", n)]["checksum"]
        for n in sizes)
    acceptance = {
        "stream_peak_ratio_hi_over_lo": round(stream_flat, 3),
        "stream_flat_leq_1_15x": stream_flat <= 1.15,
        "reservoir_peak_ratio_hi_over_lo": round(reservoir_flat, 3),
        "stack_peak_delta_growth": round(stack_delta_growth, 2),
        "stack_grows_with_cohort": stack_delta_growth >= (2.0 if args.smoke
                                                          else 4.0),
        "mean_checksums_identical_stream_vs_stack": checksums_equal,
    }
    details = {
        "backend": arms[("stream", lo)]["backend"],
        "note": ("CPU-container wall-clock + VmRSS watermark bench (host "
                 "perf_counter, /proc polling; no accelerator) — server "
                 "aggregation memory/time only, upload generation "
                 "excluded, not a training-throughput claim"),
        "smoke": bool(args.smoke),
        "model_mb": model_mb,
        "cohort_sizes": sizes,
        "arms": {f"{m}_n{n}": arms[(m, n)]
                 for (m, n) in arms},
        "acceptance": acceptance,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(details, f, indent=1)
            f.write("\n")
    print(json.dumps({"bench": "stream_agg", "out": args.out or None,
                      **acceptance}))
    ok = (acceptance["stream_flat_leq_1_15x"]
          and acceptance["stack_grows_with_cohort"]
          and checksums_equal)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
