"""Cohort padding invariance, across the algorithm zoo.

THE core static-shape contract (SURVEY.md §7 hard part (a)): cohorts are
padded to a static size with weight-0 slots, and padded slots must be
bit-invisible — identical final params whether the configured cohort is
exactly the client count or far larger (every extra slot is padding).
Pinned for FedNova since round 2 (test_fednova_detail); this sweep pins it
for every cohort-engine algorithm, including the stateful ones whose
per-client state gather/scatter must also ignore padded slots."""

import jax
import numpy as np
import pytest

from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _data(n_clients=3, dim=6, per=12, seed=0):
    rng = np.random.RandomState(seed)
    xs = [rng.randn(per, dim).astype(np.float32) for _ in range(n_clients)]
    ys = [rng.randint(0, 4, per).astype(np.int32) for _ in range(n_clients)]
    train = stack_client_data(xs, ys, 4)
    return FederatedData(client_num=n_clients, class_num=4, train=train,
                         test=train)


def _wl():
    return ClassificationWorkload(LogisticRegression(6, 4), num_classes=4,
                                  grad_clip_norm=None)


def _make(algo_name, data, m):
    base = dict(comm_round=3, client_num_per_round=m, epochs=2,
                batch_size=4, lr=0.1, frequency_of_the_test=100)
    if algo_name == "fedavg":
        from fedml_tpu.algorithms import FedAvg, FedAvgConfig
        return FedAvg(_wl(), data, FedAvgConfig(**base))
    if algo_name == "fedprox":
        from fedml_tpu.algorithms import FedProx, FedProxConfig
        return FedProx(_wl(), data, FedProxConfig(mu=0.1, **base))
    if algo_name == "fedopt":
        from fedml_tpu.algorithms import FedOpt, FedOptConfig
        return FedOpt(_wl(), data, FedOptConfig(
            server_optimizer="adam", server_lr=0.01, **base))
    if algo_name == "fednova":
        from fedml_tpu.algorithms import FedNova, FedNovaConfig
        return FedNova(_wl(), data, FedNovaConfig(**base))
    if algo_name == "scaffold":
        from fedml_tpu.algorithms import Scaffold, ScaffoldConfig
        return Scaffold(_wl(), data, ScaffoldConfig(**base))
    if algo_name == "feddyn":
        from fedml_tpu.algorithms import FedDyn, FedDynConfig
        return FedDyn(_wl(), data, FedDynConfig(feddyn_alpha=0.05, **base))
    if algo_name == "ditto":
        from fedml_tpu.algorithms import Ditto, DittoConfig
        return Ditto(_wl(), data, DittoConfig(ditto_lambda=0.1, **base))
    if algo_name == "dp_fedavg":
        from fedml_tpu.algorithms import DPFedAvg, DPFedAvgConfig
        return DPFedAvg(_wl(), data, DPFedAvgConfig(
            dp_clip=0.5, dp_noise_multiplier=1.0, **base))
    if algo_name == "fedac":
        from fedml_tpu.algorithms import FedAC, FedACConfig
        return FedAC(_wl(), data, FedACConfig(fedac_mu=0.1, **base))
    if algo_name == "fedavg_robust":
        from fedml_tpu.algorithms import FedAvgRobust, FedAvgRobustConfig
        return FedAvgRobust(_wl(), data, FedAvgRobustConfig(
            defense="norm_diff_clipping", norm_bound=1.0, **base))
    raise KeyError(algo_name)


ALGOS = ("fedavg", "fedprox", "fedopt", "fednova", "scaffold", "feddyn",
         "ditto", "dp_fedavg", "fedac", "fedavg_robust")


@pytest.mark.parametrize("algo_name", ALGOS)
def test_padded_cohort_slots_are_invisible(algo_name):
    """m = N (no padding) vs m = 2N (half the cohort is weight-0 padding):
    same clients, same rng chain, so the final global params must match to
    float tolerance (the padded slots' rng streams exist but their
    contributions are masked everywhere)."""
    data = _data()
    n = data.client_num
    exact = _make(algo_name, data, n)
    padded = _make(algo_name, data, 2 * n)
    out_a = exact.run(rng=jax.random.key(7))
    out_b = padded.run(rng=jax.random.key(7))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6),
        out_a, out_b)
