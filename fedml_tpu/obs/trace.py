"""Distributed round tracing: spans whose context rides Message headers.

The reference (and our PR 1 fault layer) had no way to see WHERE a
federated round spends its time: a stalled round could be a dead silo, a
retry storm, or a first-call jit compile.  This tracer stitches one
round into a single cross-process trace — server ``round`` span →
``broadcast`` → per-silo ``recv``/``train``/``upload`` → server
``aggregate`` — by carrying ``(trace_id, span_id)`` in a reserved plain
header key of every `Message` (`CTX_KEY`, mirrored as
``Message.ARG_TRACE``).  Export is Chrome/Perfetto ``trace_event`` JSON
(one file per process; `obs/report.py` merges them), viewable in
``ui.perfetto.dev`` alongside the ``jax.profiler`` XLA traces
``profiler_trace`` already captures.

Cost contract: tracing is a process-global opt-in (`enable()`); when
disabled ``get_tracer()`` is ``None`` and instrumented paths pay exactly
one branch per message, no allocations, no threads.

Duplicate tolerance: a chaotic wire can deliver one frame twice.  Spans
created with ``deterministic=True`` derive their span id from
``(trace_id, parent_id, name, node)``, and the tracer records the FIRST
span per id — so a duplicated delivery collapses to one span instead of
forking the trace.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Optional

# the Message param key trace context travels under (a plain {"t","s"}
# dict, so it rides the JSON header of the binary codec untouched).
# comm/message.py mirrors this as Message.ARG_TRACE — kept literal here
# so this module stays import-cycle-free (stdlib only).
CTX_KEY = "_trace"

# the ONE null context instrumented call sites reuse when tracing is
# disabled: nullcontext is reentrant and stateless, so sharing a single
# instance makes the disabled path literally allocation-free (the
# zero-allocation pin in tests/test_critical_path.py holds it to that)
NULL_CONTEXT = contextlib.nullcontext()

_USE_CURRENT = object()  # start_span default: parent = the active span
_tracer_ids = itertools.count()


class SpanContext:
    """The propagated identity of a span: (trace_id, span_id), plus —
    when extracted from a message — the unique id ``inject()`` stamped on
    that SEND.  The msg_id is what separates "the wire duplicated one
    frame" (same msg_id → recv spans dedupe) from "two messages rode the
    same parent span" (distinct msg_ids → distinct spans)."""
    __slots__ = ("trace_id", "span_id", "msg_id")

    def __init__(self, trace_id: str, span_id: str,
                 msg_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.msg_id = msg_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id}, {self.msg_id})"


class Span:
    """One timed operation.  ``end()`` records it (idempotent)."""
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "node",
                 "args", "t0", "tid", "_tracer", "_ended")

    def __init__(self, tracer: "SpanTracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str], node, args: dict,
                 t0: float):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.node = node
        self.args = args
        self.t0 = t0
        self.tid = threading.get_ident()
        self._tracer = tracer
        self._ended = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self._tracer._record(self, self._tracer._clock() - self.t0)


class SpanTracer:
    """Collects spans; exports Chrome ``trace_event`` JSON.

    ``node`` labels spans that don't pass their own (in-process actors
    pass their node id per span, so one tracer serves a whole local
    federation).  ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, node="proc0", clock=time.time):
        self.node = node
        self._clock = clock
        self._lock = threading.Lock()
        self._spans: dict = {}              # span_id -> record (first wins)
        self._order: list = []              # span ids in record order
        self._seq = itertools.count()
        self._local = threading.local()
        # per-tracer nonce keeps generated ids unique across processes
        # (grpc silos) and across tracer instances within one process
        self._nonce = f"{os.getpid():x}.{next(_tracer_ids)}"

    # -- id generation -------------------------------------------------------
    def new_trace_id(self, hint: str = "") -> str:
        return f"{self._nonce}-{hint or next(self._seq)}"

    # -- current-span stack (thread-local) -----------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_context(self) -> Optional[SpanContext]:
        stack = self._stack()
        return stack[-1].context if stack else None

    # -- span lifecycle ------------------------------------------------------
    def start_span(self, name: str, parent=_USE_CURRENT,
                   trace_id: Optional[str] = None, node=None,
                   span_id: Optional[str] = None, deterministic: bool = False,
                   **args) -> Span:
        """``parent`` accepts a Span, a SpanContext, or None (root); the
        default is the thread's active span.  ``deterministic=True``
        derives the span id from (trace_id, parent, name, node) so a
        duplicated message re-handled on the same node dedupes."""
        if parent is _USE_CURRENT:
            parent = self.current_context()
        elif isinstance(parent, Span):
            parent = parent.context
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None \
                else self.new_trace_id()
        parent_id = parent.span_id if parent is not None else None
        if node is None:
            node = self.node
        if span_id is None:
            if deterministic:
                # include the parent context's message id (present when
                # the parent was extracted off a wire message): dedupes
                # duplicated deliveries of ONE frame without collapsing
                # distinct frames that share a parent span
                msg_id = getattr(parent, "msg_id", None) or ""
                span_id = deterministic_span_id(
                    trace_id, parent_id or "", msg_id, name, str(node))
            else:
                span_id = f"{self._nonce}.{next(self._seq)}"
        return Span(self, name, trace_id, span_id, parent_id, node, args,
                    self._clock())

    @contextlib.contextmanager
    def span(self, name: str, **kw):
        """Start a span, make it the thread's current (so sends inside it
        propagate its context), end it on exit."""
        sp = self.start_span(name, **kw)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            sp.end()

    def record_span(self, name: str, dur_s: float,
                    t0: Optional[float] = None, parent=None,
                    trace_id: Optional[str] = None, node=None,
                    **args) -> None:
        """Record an already-finished span retroactively: the hot-path
        form for schedulers that know a phase's duration only after it
        ran (serve queue wait, batch execution, decode steps) — one call
        per event, no context-manager entry on the critical path.
        ``t0`` defaults to ``now - dur_s`` on this tracer's clock; pass
        a Span/SpanContext as ``parent`` to hang it under a request."""
        if isinstance(parent, Span):
            parent = parent.context
        if t0 is None:
            t0 = self._clock() - dur_s
        sp = self.start_span(name, parent=parent, trace_id=trace_id,
                             node=node, **args)
        sp.t0 = t0
        sp._ended = True
        self._record(sp, dur_s)

    def _record(self, span: Span, dur_s: float) -> None:
        rec = {"name": span.name, "trace_id": span.trace_id,
               "span_id": span.span_id, "parent_id": span.parent_id,
               "node": span.node, "ts": span.t0, "dur": dur_s,
               "tid": span.tid, "args": span.args}
        with self._lock:
            if span.span_id not in self._spans:   # dedupe: first wins
                self._spans[span.span_id] = rec
                self._order.append(span.span_id)

    # -- export --------------------------------------------------------------
    @property
    def spans(self) -> list:
        """Recorded span dicts, in record order (test/report surface)."""
        with self._lock:
            return [dict(self._spans[i]) for i in self._order]

    def to_trace_events(self) -> list:
        """Chrome ``trace_event`` list: one complete ("X") event per span
        plus ``process_name`` metadata naming each node's track."""
        events, nodes = [], {}
        for rec in self.spans:
            pid = _node_pid(rec["node"])
            nodes.setdefault(pid, rec["node"])
            events.append({
                "name": rec["name"], "cat": "fedml", "ph": "X",
                "ts": int(rec["ts"] * 1e6), "dur": int(rec["dur"] * 1e6),
                "pid": pid, "tid": rec["tid"] % 1_000_000,
                "args": {"trace_id": rec["trace_id"],
                         "span_id": rec["span_id"],
                         "parent_id": rec["parent_id"],
                         "node": str(rec["node"]), **rec["args"]}})
        for pid, node in sorted(nodes.items()):
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": f"node {node}"}})
        return events

    def export(self, path: str) -> None:
        """Write ``{"traceEvents": [...]}`` atomically (tmp + replace)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": self.to_trace_events(),
                       "displayTimeUnit": "ms"}, f)
        os.replace(tmp, path)


def _node_pid(node) -> int:
    """Stable small integer per node label (Perfetto tracks are per-pid)."""
    try:
        return int(node)
    except (TypeError, ValueError):
        digest = hashlib.blake2s(str(node).encode(), digest_size=2).digest()
        return 1000 + int.from_bytes(digest, "big")


def deterministic_span_id(*parts: str) -> str:
    return hashlib.blake2s("|".join(parts).encode(),
                           digest_size=8).hexdigest()


# -- Message header propagation ---------------------------------------------

_msg_seq = itertools.count()


def inject(msg, ctx: SpanContext) -> None:
    """Attach ``ctx`` to an outgoing message (plain JSON-header param),
    stamping a unique per-send message id: a chaotic wire can deliver
    this one frame twice, and the id is how the receiver's span dedupe
    tells that apart from two genuinely distinct sends."""
    msg.add(CTX_KEY, {"t": ctx.trace_id, "s": ctx.span_id,
                      "m": f"{os.getpid():x}.{next(_msg_seq)}"})


def extract(msg) -> Optional[SpanContext]:
    """Read the propagated context off an inbound message, if any."""
    d = msg.get(CTX_KEY)
    if isinstance(d, dict) and "t" in d and "s" in d:
        return SpanContext(d["t"], d["s"], d.get("m"))
    return None


# -- process-global tracer ---------------------------------------------------

_tracer: Optional[SpanTracer] = None


def get_tracer() -> Optional[SpanTracer]:
    """``None`` unless `enable()` ran — instrumented paths branch on
    exactly this."""
    return _tracer


def enable(node="proc0", clock=time.time) -> SpanTracer:
    global _tracer
    if _tracer is None:
        _tracer = SpanTracer(node=node, clock=clock)
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None
