"""fedml_tpu.server_opt — the server-optimizer spine (ISSUE 18).

* `optimizer` — the pluggable pseudo-gradient step over the streaming
  and sharded finalize (plain | momentum | adam | fedac), with
  checkpoint/journal-riding O(model) state and the PR 14-style
  mismatch refusals;
* `controller` — the health-driven adaptive round controller steering
  cohort/epochs/wave pacing from the PR 8 drift alarms.
"""

from fedml_tpu.server_opt.controller import AdaptiveController, Decision
from fedml_tpu.server_opt.optimizer import (SERVER_OPT_NAMES,
                                            ServerOptConfigError,
                                            ServerOptMismatchError,
                                            ServerOptimizer)

__all__ = ["AdaptiveController", "Decision", "SERVER_OPT_NAMES",
           "ServerOptConfigError", "ServerOptMismatchError",
           "ServerOptimizer"]
