"""Decoder-only transformer LM — the attention member of the NLP family.

The reference's NLP zoo stops at LSTMs (fedml_api/model/nlp/rnn.py:4-70);
this model is the modern drop-in for the same next-word/char-prediction
workloads ([B, T] tokens in, [B, T, V] per-position logits out — the
NWPWorkload contract), and the carrier for the framework's long-context
story: pass ``ring_axis`` (inside a shard_map over a ``sequence`` mesh axis,
see fedml_tpu.parallel.ring_attention) and the same parameters run with the
sequence sharded across devices and exact ring attention over ICI.

Architecture: pre-LN blocks (LN → causal MHA → residual, LN → GELU MLP →
residual), learned positional embeddings, final LN → vocab head.  ``dtype``
enables bf16 mixed precision the same way as the rest of the zoo (params
stay f32; softmax/logits accumulate f32).

Incremental decode (the serving hot path, ISSUE 15): pass ``cache`` (built
by `init_decode_cache`) and per-slot ``positions`` to run ONE token per
slot against per-layer KV caches carried as explicit state — the model
returns ``(logits [B, V], new_cache)`` instead of re-running the whole
prefix every token.  The cache is plain pytree state (no flax mutable
collections), so the serving scheduler jits one step over a fixed
``[slots]`` batch and donates the cache in place; per-slot positions mean
every slot may sit at a DIFFERENT sequence index, which is exactly what
continuous batching needs (a finished slot restarts at position 0 and the
``kv_idx <= position`` mask hides the previous occupant's stale rows).
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.parallel.ring_attention import (
    blockwise_attention, full_attention, ring_attention)


def _auto_block(t: int, threshold: int, max_block: int = 512,
                min_block: int = 64) -> Optional[int]:
    """Largest kv-block size in [min_block, max_block] dividing ``t``, or
    None when ``t <= threshold`` (dense is fine) or no usable divisor
    exists (a sub-64 block would make the scan slower than it saves —
    realistic sequence lengths have power-of-two factors)."""
    if t <= threshold:
        return None
    for b in range(min(max_block, t), min_block - 1, -1):
        if t % b == 0:
            return b
    return None


def _pallas_flash(q, k, v):
    """TPU-fused flash attention (jax.experimental.pallas.ops.tpu) for the
    dense causal case — one VMEM-tiled kernel instead of XLA-scheduled
    matmul+softmax.  TPU backend only; q/k/v are [B, T, H, d]."""
    import jax
    if jax.default_backend() != "tpu":
        raise RuntimeError(
            "use_flash=True needs a TPU backend (the pallas flash kernel "
            "does not run on CPU); use block_size= for a backend-neutral "
            "memory-efficient path")
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)
    # kernel layout is [B, H, T, d]
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), causal=True,
                          sm_scale=1.0 / (q.shape[-1] ** 0.5))
    return out.transpose(0, 2, 1, 3)


class CausalSelfAttention(nn.Module):
    n_heads: int
    d_model: int
    dtype: object = None
    block_size: Optional[int] = None  # flash-style kv blocking (single-chip
    #                                   long context); None = dense scores
    use_flash: bool = False  # TPU pallas flash kernel (dense causal only)
    # dense attention materializes [B, H, T, T] scores; past this length
    # switch to blockwise automatically (exact same math) so long-context
    # eval/init can't OOM just because no backend flag was passed
    auto_block_len: int = 1024

    @nn.compact
    def __call__(self, x, positions, ring_axis: Optional[str] = None,
                 cache: Optional[dict] = None):
        d_head = self.d_model // self.n_heads
        q = nn.DenseGeneral((self.n_heads, d_head), dtype=self.dtype,
                            name="query")(x)
        k = nn.DenseGeneral((self.n_heads, d_head), dtype=self.dtype,
                            name="key")(x)
        v = nn.DenseGeneral((self.n_heads, d_head), dtype=self.dtype,
                            name="value")(x)
        t = x.shape[1]
        new_cache = None
        if cache is not None:
            # incremental decode: x is [B, 1, D], positions is [B] — the
            # per-slot write index.  Scatter this token's k/v into the
            # cache row, attend the single query against the whole cache
            # with a per-slot causal mask (kv_idx <= position): rows past
            # the slot's own position — including a previous occupant's
            # stale entries after slot reuse — are masked out, so a slot
            # restarting at position 0 is bit-equivalent to a fresh cache.
            k_cache, v_cache = cache["k"], cache["v"]   # [B, Tc, H, d]
            tc = k_cache.shape[1]
            write = (jnp.arange(tc)[None, :]
                     == positions[:, None])[:, :, None, None]
            k_cache = jnp.where(write, k.astype(k_cache.dtype), k_cache)
            v_cache = jnp.where(write, v.astype(v_cache.dtype), v_cache)
            new_cache = {"k": k_cache, "v": v_cache}
            scale = 1.0 / math.sqrt(d_head)
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k_cache,
                preferred_element_type=jnp.float32) * scale
            mask = (jnp.arange(tc)[None, None, None, :]
                    <= positions[:, None, None, None])
            scores = jnp.where(mask, scores, -1e30)
            p = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", p,
                             v_cache.astype(jnp.float32))
        elif ring_axis is not None:
            out = ring_attention(q, k, v, positions, positions, ring_axis)
        elif self.use_flash:
            out = _pallas_flash(q, k, v)
        elif self.block_size is not None:
            out = blockwise_attention(q, k, v, positions, positions,
                                      self.block_size)
        elif (blk := _auto_block(t, self.auto_block_len)) is not None:
            out = blockwise_attention(q, k, v, positions, positions, blk)
        else:
            out = full_attention(q, k, v, positions, positions)
        out = out.astype(x.dtype)
        out = nn.DenseGeneral(self.d_model, axis=(-2, -1),
                              dtype=self.dtype, name="out")(out)
        return (out, new_cache) if cache is not None else out


def init_decode_cache(model: "TransformerLM", slots: int, cache_len: int,
                      dtype=jnp.float32) -> dict:
    """Fresh per-layer KV cache for incremental decode: one
    ``{"attn_i": {"k", "v"}}`` entry per layer, each ``[slots, cache_len,
    n_heads, d_head]``.  Zeros are fine as the initial value — the
    per-slot ``kv_idx <= position`` mask in `CausalSelfAttention` never
    reads a row the slot's own steps have not written."""
    if cache_len > model.max_len:
        raise ValueError(
            f"cache_len {cache_len} exceeds the model's max_len "
            f"{model.max_len}: the positional embedding table has no row "
            f"for those positions; shrink the cache or grow max_len")
    d_head = model.d_model // model.n_heads
    shape = (slots, cache_len, model.n_heads, d_head)
    return {f"attn_{i}": {"k": jnp.zeros(shape, dtype),
                          "v": jnp.zeros(shape, dtype)}
            for i in range(model.n_layers)}


class TransformerLM(nn.Module):
    """Per-position next-token logits, causal.

    ``positions`` are global token indices (default ``arange(T)``); under
    sequence parallelism each shard passes its own offset block so the
    positional embedding and causal mask stay globally correct.

    Incremental decode: with ``cache`` (from `init_decode_cache`),
    ``input_seq`` is ONE token per slot (``[B]`` ints), ``positions`` the
    per-slot sequence index (``[B]`` ints), and the call returns
    ``(logits [B, vocab], new_cache)`` — the prediction for position
    ``positions + 1`` given everything the cache holds up to and
    including this token."""
    vocab_size: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 2048
    dropout_rate: float = 0.0
    dtype: object = None
    block_size: Optional[int] = None  # see CausalSelfAttention
    use_flash: bool = False           # see CausalSelfAttention
    auto_block_len: int = 1024        # see CausalSelfAttention
    moe_experts: int = 0        # >0: Switch MoE FFN with this many experts
    #                             (models/moe.py) — the ep-shardable form;
    #                             NWPWorkload adds the sown balance loss
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01      # Switch paper's alpha
    pad_id: int = 0       # pad token id; MoE routing excludes pad positions
    #                       (they would otherwise eat expert capacity)

    @nn.compact
    def __call__(self, input_seq, train: bool = False, positions=None,
                 ring_axis: Optional[str] = None,
                 cache: Optional[dict] = None):
        decode = cache is not None
        if decode:
            if positions is None:
                raise ValueError(
                    "decode (cache=) needs per-slot positions: each slot "
                    "sits at its own sequence index")
            if ring_axis is not None:
                raise ValueError(
                    "decode (cache=) is single-chip attention over the kv "
                    "cache; ring_axis does not compose with it")
            tokens = input_seq.reshape(-1)          # [B] one token/slot
            seq_for_mask = tokens[:, None]          # [B, 1] (MoE pad mask)
            x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="tok_embed")(tokens)[:, None, :]
            x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                             name="pos_embed")(positions)[:, None, :]
        else:
            _, t = input_seq.shape
            if positions is None:
                positions = jnp.arange(t)
            seq_for_mask = input_seq
            x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="tok_embed")(input_seq)
            x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                             name="pos_embed")(positions)[None, :, :]
        new_cache = {} if decode else None
        for i in range(self.n_layers):
            h = nn.LayerNorm(dtype=self.dtype)(x)
            attn = CausalSelfAttention(self.n_heads, self.d_model,
                                       dtype=self.dtype,
                                       block_size=self.block_size,
                                       use_flash=self.use_flash,
                                       auto_block_len=self.auto_block_len,
                                       name=f"attn_{i}")
            if decode:
                h, new_cache[f"attn_{i}"] = attn(
                    h, positions, cache=cache[f"attn_{i}"])
            else:
                h = attn(h, positions, ring_axis)
            if self.dropout_rate:
                h = nn.Dropout(self.dropout_rate,
                               deterministic=decode or not train)(h)
            x = x + h
            h = nn.LayerNorm(dtype=self.dtype)(x)
            if self.moe_experts:
                from fedml_tpu.models.moe import SwitchFFN
                h = SwitchFFN(self.moe_experts, self.d_model, self.d_ff,
                              capacity_factor=self.moe_capacity_factor,
                              dtype=self.dtype, name=f"moe_{i}")(
                    h, mask=(seq_for_mask != self.pad_id))
            else:
                h = nn.Dense(self.d_ff, dtype=self.dtype)(h)
                h = nn.gelu(h)
                h = nn.Dense(self.d_model, dtype=self.dtype)(h)
            if self.dropout_rate:
                h = nn.Dropout(self.dropout_rate,
                               deterministic=decode or not train)(h)
            x = x + h
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          name="lm_head")(x)
        return (logits[:, 0, :], new_cache) if decode else logits
