"""Fused robust aggregation (core/pallas_agg.py) vs the XLA compose path.

Runs through the Pallas interpreter on CPU; the kernel semantics are
backend-independent, so interpreter parity here implies TPU parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.pallas_agg import make_fused_robust_aggregate
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.robust import clip_update


def _stacked_params(rng, n=6):
    """A params-like tree with a 'batch_stats'-keyed branch (never clipped),
    an INTEGER leaf (the torch-style BN step counter — int leaves must take
    the same weighted-mean-truncate path in both backends), and a ragged
    mix of leaf shapes."""
    mk = lambda *s: jnp.asarray(rng.randn(n, *s).astype(np.float32))
    return {
        "params": {
            "dense": {"kernel": mk(17, 33), "bias": mk(33)},
            "conv": {"kernel": mk(3, 3, 2, 8)},
        },
        "batch_stats": {"bn": {"mean": mk(8), "var": jnp.abs(mk(8)),
                               "num_batches_tracked": jnp.asarray(
                                   rng.randint(0, 100, (n, 1)), jnp.int32)}},
    }


def _globals_like(stacked):
    return jax.tree.map(lambda x: x[0] * 0.5, stacked)


@pytest.mark.parametrize("norm_bound", [None, 0.7])
def test_fused_matches_xla_compose(rng, norm_bound):
    """σ=0: fused kernel == vmap(clip_update) then tree_weighted_mean."""
    stacked = _stacked_params(rng)
    g = _globals_like(stacked)
    w = jnp.asarray([4.0, 1.0, 0.0, 2.5, 3.0, 1.5])  # incl. a padded client

    fused = make_fused_robust_aggregate(norm_bound=norm_bound, noise_std=0.0,
                                        interpret=True)
    got = fused(stacked, w, g, jax.random.key(0))

    if norm_bound is None:
        want = tree_weighted_mean(stacked, w)
    else:
        clipped = jax.vmap(clip_update, in_axes=(0, None, None))(
            stacked, g, norm_bound)
        want = tree_weighted_mean(clipped, w)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
                 got, want)


def test_fused_noise_statistics(rng):
    """σ>0: output = σ=0 output + Σ r_i σ n_i with n_i ~ N(0,1); the summed
    noise std must be σ·sqrt(Σ r_i²) within sampling tolerance."""
    n = 4
    big = jnp.asarray(rng.randn(n, 64, 128).astype(np.float32))
    stacked = {"w": big}
    g = jax.tree.map(lambda x: x[0] * 0.0, stacked)
    w = jnp.ones((n,))
    sigma = 0.5

    base = make_fused_robust_aggregate(norm_bound=None, noise_std=0.0,
                                       interpret=True)(
        stacked, w, g, jax.random.key(1))
    noised = make_fused_robust_aggregate(norm_bound=None, noise_std=sigma,
                                         interpret=True)(
        stacked, w, g, jax.random.key(1))
    delta = np.asarray(noised["w"] - base["w"]).ravel()
    want_std = sigma * np.sqrt(n * (1 / n) ** 2)
    assert abs(delta.mean()) < 0.01
    np.testing.assert_allclose(delta.std(), want_std, rtol=0.05)


def test_fused_noise_keyed_by_rng(rng):
    """Different round rng ⇒ different noise; same rng ⇒ identical."""
    stacked = {"w": jnp.asarray(rng.randn(3, 32, 128).astype(np.float32))}
    g = jax.tree.map(lambda x: x[0] * 0.0, stacked)
    w = jnp.ones((3,))
    f = make_fused_robust_aggregate(noise_std=0.1, interpret=True)
    a = f(stacked, w, g, jax.random.key(5))
    b = f(stacked, w, g, jax.random.key(5))
    c = f(stacked, w, g, jax.random.key(6))
    np.testing.assert_array_equal(a["w"], b["w"])
    assert not np.allclose(a["w"], c["w"])


def test_fedavg_robust_pallas_backend(rng):
    """End-to-end: FedAvgRobust with defense_backend='pallas' runs a round
    and defends like the XLA backend (params move, stay finite)."""
    from fedml_tpu.algorithms import FedAvgRobust, FedAvgRobustConfig
    from fedml_tpu.data.stacking import FederatedData, stack_client_data
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload

    xs = [rng.randn(8, 6).astype(np.float32) for _ in range(4)]
    ys = [rng.randint(0, 3, 8).astype(np.int32) for _ in range(4)]
    train = stack_client_data(xs, ys, batch_size=4)
    data = FederatedData(client_num=4, class_num=3, train=train, test=train)
    wl = ClassificationWorkload(LogisticRegression(6, 3), num_classes=3,
                                grad_clip_norm=None)
    cfg = FedAvgRobustConfig(comm_round=2, client_num_per_round=4, epochs=1,
                             batch_size=4, lr=0.5, defense="weak_dp",
                             norm_bound=1.0, stddev=0.01,
                             defense_backend="pallas",
                             frequency_of_the_test=100)
    algo = FedAvgRobust(wl, data, cfg)
    p0 = algo.init_params(jax.random.key(0))
    p1 = algo.run(params=jax.tree.map(jnp.copy, p0))
    leaves = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), p0, p1))
    assert max(leaves) > 0
    assert all(np.isfinite(x).all() for x in jax.tree.leaves(p1))
