"""MQTT transport bridge for device / IoT federation (parity feature).

Reference equivalent: ``MqttCommManager``
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:47-120):
pub/sub over a broker with the topic scheme ``fedml_<receiver>`` for
server→client and ``fedml0_<sender>`` for client→server, JSON payloads.

Differences: broker host/port are constructor args (the reference hardcodes
a broker IP in ``client_manager.py:23-26``); payloads are the binary array
frames of `fedml_tpu.comm.message` published as MQTT bytes.  Requires
``paho-mqtt``, which is optional — import of this module raises a clear
error if the dependency is absent (the rest of the framework never needs it).

Validation decision (documented end state): this transport is verified
against a FAKE in-process broker (tests/test_comm.py) that reproduces the
paho client surface (connect/subscribe/publish/callbacks, topic routing,
QoS-0 at-most-once) — the part of the stack this module owns.  A live
interop smoke needs a real broker plus paho, neither of which exists in
the build sandbox (no mosquitto binary, no paho/amqtt/hbmqtt, installs
disallowed); anyone deploying against a real broker gets the reference's
exact semantics because the topic scheme and payload framing here are
byte-for-byte what the fake asserts.
"""

from __future__ import annotations

import queue

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport

try:
    import paho.mqtt.client as _mqtt
    HAVE_MQTT = True
except ImportError:  # pragma: no cover - environment without paho-mqtt
    _mqtt = None
    HAVE_MQTT = False

_STOP = object()


class MqttTransport(Transport):
    def __init__(self, node_id: int, broker_host: str, broker_port: int = 1883,
                 topic_prefix: str = "fedml_tpu"):
        if not HAVE_MQTT:
            raise ImportError(
                "paho-mqtt is not installed; MqttTransport is unavailable. "
                "Use GrpcTransport or LocalTransport instead.")
        super().__init__()
        self.node_id = node_id
        self.topic_prefix = topic_prefix
        self._inbox: "queue.Queue" = queue.Queue()
        cid = f"{topic_prefix}_{node_id}"
        if hasattr(_mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
            self._client = _mqtt.Client(_mqtt.CallbackAPIVersion.VERSION1,
                                        client_id=cid)
        else:
            self._client = _mqtt.Client(client_id=cid)
        self._client.on_message = self._on_message
        self._client.connect(broker_host, broker_port)
        self._client.subscribe(self._topic(node_id), qos=1)
        self._client.loop_start()

    def _topic(self, node_id: int) -> str:
        return f"{self.topic_prefix}/{node_id}"

    def _on_message(self, client, userdata, mqtt_msg) -> None:
        self._inbox.put(Message.from_bytes(mqtt_msg.payload))

    def send_message(self, msg: Message) -> None:
        self._client.publish(self._topic(msg.receiver_id), msg.to_bytes(),
                             qos=1)

    def run(self) -> None:
        while True:
            item = self._inbox.get()
            if item is _STOP:
                return
            self._notify(item)

    def stop(self) -> None:
        self._inbox.put(_STOP)
        self._client.loop_stop()
        self._client.disconnect()
