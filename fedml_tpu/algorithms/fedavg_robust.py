"""FedAvg-Robust — defense hooks at aggregation time.

Parity with fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:
norm-diff clipping and weak-DP Gaussian noise applied to each client update
before averaging (:133, :179-207; defense math in
fedml_core/robustness/robust_aggregation.py).

Here the defenses are the cohort engine's ``transform_update`` hook, so the
whole defended round (local training + clip + noise + aggregation) remains
one jit — on a mesh the defense runs shard-local before the psum.

Beyond the reference, ``defense`` also accepts the Byzantine-tolerant
aggregation rules of core/byzantine.py (coordinate_median, trimmed_mean,
krum, multi_krum, geometric_median), which replace the aggregate itself.
"""

from __future__ import annotations

import dataclasses
import logging

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.core.byzantine import METHODS as BYZ_METHODS
from fedml_tpu.core.byzantine import make_byzantine_aggregate
from fedml_tpu.core.pallas_agg import make_fused_robust_aggregate
from fedml_tpu.core.robust import add_gaussian_noise, clip_update
from fedml_tpu.parallel.cohort import make_cohort_step
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import make_client_optimizer

log = logging.getLogger(__name__)


@dataclasses.dataclass
class FedAvgRobustConfig(FedAvgConfig):
    defense: str = "weak_dp"     # clip/DP (reference parity) or a
    #                              Byzantine rule (core/byzantine.py)
    norm_bound: float = 5.0
    stddev: float = 0.025        # reference default for weak DP
    defense_backend: str = "xla"  # "xla" | "pallas" (fused kernel,
    #                                core/pallas_agg.py; single-chip only)
    trim_frac: float = 0.1       # trimmed_mean: fraction cut per side
    byz_f: int = 0               # krum: assumed Byzantine count
    krum_m: int = 1              # multi_krum: how many updates to average
    gm_iters: int = 8            # geometric_median: Weiszfeld iterations
    gm_eps: float = 1e-6         # geometric_median: smoothing floor


class FedAvgRobust(FedAvg):
    DEFENSES = ("norm_diff_clipping", "weak_dp", "none") + BYZ_METHODS

    def __init__(self, workload, data, config: FedAvgRobustConfig, mesh=None, sink=None):
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        if cfg.defense not in self.DEFENSES:
            raise ValueError(f"unknown defense {cfg.defense!r}; "
                             f"available: {self.DEFENSES}")
        if cfg.defense_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown defense_backend {cfg.defense_backend!r}; "
                f"available: ('xla', 'pallas')")

        opt = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
        local_train = make_local_trainer(workload, opt, cfg.epochs)

        if cfg.defense in BYZ_METHODS:
            # Byzantine rules replace the AGGREGATE (they need the whole
            # cohort: per-coordinate sorts / the pairwise distance matmul),
            # so they ride the single-chip vmap engine; the mesh path's
            # aggregation is a fixed psum and would need an all-gather
            if mesh is not None:
                raise ValueError(
                    f"defense {cfg.defense!r} needs the full cohort on one "
                    "chip (sorts / pairwise distances); drop --mesh_clients")
            if cfg.defense_backend == "pallas":
                raise ValueError(
                    "defense_backend='pallas' fuses clip+noise+mean; "
                    f"Byzantine rule {cfg.defense!r} has its own aggregate "
                    "— use the xla backend")
            if cfg.defense in ("krum", "multi_krum"):
                m = cfg.krum_m if cfg.defense == "multi_krum" else 1
                # the bound is on the LIVE cohort: sample_clients caps the
                # cohort at the dataset's client count, so a small dataset
                # shrinks n below the configured cohort size
                n = min(cfg.client_num_per_round, data.client_num)
                max_m = n - cfg.byz_f - 2
                if m > max_m:
                    raise ValueError(
                        f"multi-Krum needs m <= n - f - 2 = "
                        f"{n} - {cfg.byz_f} - 2 = "
                        f"{max_m}, got m={m}: selecting that many updates "
                        "can include Byzantine ones, silently degenerating "
                        "to a plain mean")
                if n < 2 * cfg.byz_f + 3:
                    # Blanchard et al. 2017 Prop. 1: the (alpha, f)-Byzantine
                    # resilience of Krum additionally needs n >= 2f + 3; below
                    # it the selection can be steered by a near-majority of
                    # attackers.  Warn rather than abort — the rule still runs
                    # and small cohorts are common in tests/simulation.
                    log.warning(
                        "krum robustness guarantee needs n >= 2f + 3 "
                        "(n=%d, f=%d): selection may be defeatable by a "
                        "coordinated near-majority of Byzantine silos",
                        n, cfg.byz_f)
            agg = make_byzantine_aggregate(
                cfg.defense, trim_frac=cfg.trim_frac, byz_f=cfg.byz_f,
                krum_m=cfg.krum_m, gm_iters=cfg.gm_iters, gm_eps=cfg.gm_eps)
            self.cohort_step = make_cohort_step(
                local_train, aggregate=agg,
                client_axis=cfg.client_axis)
            return

        if cfg.defense_backend == "pallas" and cfg.defense != "none":
            # fused clip+noise+mean: one VMEM pass, no transformed [N, D]
            # copies in HBM (core/pallas_agg.py).  The clip norm is global
            # across the cohort, so this path is single-chip; mesh-sharded
            # runs use the XLA transform hook.
            if mesh is not None:
                raise ValueError("defense_backend='pallas' does not shard "
                                 "over a mesh; drop --mesh_clients or use "
                                 "the xla backend")
            import jax
            fused = make_fused_robust_aggregate(
                norm_bound=(cfg.norm_bound if cfg.defense in
                            ("norm_diff_clipping", "weak_dp") else None),
                noise_std=(cfg.stddev if cfg.defense == "weak_dp" else 0.0),
                interpret=jax.default_backend() != "tpu")
            self.cohort_step = make_cohort_step(
                local_train, aggregate=fused,
                client_axis=cfg.client_axis)
            return

        def transform(client_params, global_params, rng):
            p = client_params
            if cfg.defense in ("norm_diff_clipping", "weak_dp"):
                p = clip_update(p, global_params, cfg.norm_bound)
            if cfg.defense == "weak_dp":
                p = add_gaussian_noise(p, rng, cfg.stddev)
            return p

        self.cohort_step = make_cohort_step(
            local_train, mesh=mesh,
            transform_update=None if cfg.defense == "none" else transform,
            client_axis=cfg.client_axis)
