"""Capture the committed device-observatory baseline (BENCH_device.json)
— the first point on the perf trend line ROADMAP item 5b asks for.

Runs a short live cross-silo round loop on the CPU backend with
``--device_obs`` (the REAL instrument, not a synthetic ledger), then
distills the ``perf.jsonl`` device sections into one committed artifact:
per-round wall times, the named compile ledger, the device-memory
watermark, and the per-round MFU — labeled ``backend: "cpu"`` so nobody
quotes it as an accelerator number, with the timing-trust rules applied
(any mfu > 1.0 marks the artifact ``timing_untrusted`` and exits
nonzero instead of committing fiction; the per-round ``mfu`` keys ride
the same ``perf_trend.py --lint_mfu`` scan as every BENCH artifact).

Usage: python scripts/device_baseline.py [--out BENCH_device.json]
       [--rounds 4] [--keep_run DIR]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_live_rounds(run_dir: str, rounds: int) -> list:
    cmd = [sys.executable, "-m", "fedml_tpu",
           "--algo", "cross_silo", "--model", "lr", "--dataset", "mnist",
           "--client_num_in_total", "4", "--client_num_per_round", "2",
           "--comm_round", str(rounds), "--frequency_of_the_test", "1",
           "--batch_size", "4", "--log_stdout", "false",
           "--norm_clip", "5.0",
           "--run_dir", run_dir, "--telemetry", "true",
           "--perf", "true", "--perf_strict", "true",
           "--device_obs", "true"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(cmd, check=True, cwd=REPO, env=env)
    with open(os.path.join(run_dir, "perf.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def distill(rows: list) -> dict:
    # the gate's OWN aggregations (trend.device_compile_seconds /
    # device_mem_peak_bytes) compute the two numbers the note below
    # calls "the device-gate baselines" — reusing them is the same
    # drift-proofing as bench delegating its peak table to obs/device
    sys.path.insert(0, REPO)
    from fedml_tpu.obs import trend

    devs = [r.get("device") or {} for r in rows]
    compiles = [e for d in devs for e in d.get("compiles") or []]
    mem_sources = {e.get("source") for d in devs
                   for e in d.get("memory") or [] if e.get("source")}
    steady = rows[1:] or rows  # round 0 pays the compiles
    round_s = sorted(r["round_s"] for r in steady
                     if r.get("round_s") is not None)
    art = {
        "metric": "device_observatory_baseline",
        "backend": next((d.get("backend") for d in devs if d.get("backend")),
                        None),
        "captured_at": time.time(),
        "rounds": len(rows),
        "round_s_median": (round_s[len(round_s) // 2] if round_s else None),
        "compile_total_s": round(trend.device_compile_seconds(rows) or 0.0,
                                 6),
        "compile_ledger": compiles,
        "device_mem": {"peak_bytes": trend.device_mem_peak_bytes(rows),
                       "sources": sorted(mem_sources)},
        "peak_tflops": next((d.get("peak_tflops") for d in devs
                             if d.get("peak_tflops")), None),
        "peak_source": next((d.get("peak_source") for d in devs
                             if d.get("peak_source")), None),
        "mfu_provenance": next((d.get("mfu_provenance") for d in devs
                                if d.get("mfu_provenance")), None),
        # per-round detail keeps the literal "mfu" key so the
        # perf_trend --lint_mfu scan covers this artifact like any BENCH
        "rounds_detail": [
            {"round": r.get("round"), "round_s": r.get("round_s"),
             "mfu": (r.get("device") or {}).get("mfu"),
             "flops": (r.get("device") or {}).get("flops"),
             "compiles": len((r.get("device") or {}).get("compiles") or [])}
            for r in rows],
        "note": ("CPU-honest trend anchor captured by the live device "
                 "observatory (scripts/device_baseline.py): gate future "
                 "perf PRs with scripts/perf_trend.py against a fresh "
                 "capture — compile_total_s and device_mem.peak_bytes "
                 "are the device-gate baselines.  NOT an accelerator "
                 "number; the MFU denominator on cpu is the conservative "
                 "accelerator-class table default."),
    }
    mfus = [d.get("mfu") for d in devs if isinstance(d.get("mfu"),
                                                    (int, float))]
    if mfus:
        art["mfu_median"] = sorted(mfus)[len(mfus) // 2]
        if max(mfus) > 1.0:
            # the round-4 lesson, applied to the live instrument: an
            # impossible MFU documents a timing/peak failure — the
            # artifact must refuse itself, never be committed as perf
            art["timing_untrusted"] = (
                f"max per-round mfu {max(mfus):.3g} > 1.0 — physically "
                f"impossible; baseline not trustworthy")
    return art


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="device_baseline",
        description="Capture BENCH_device.json from a live --device_obs "
                    "round loop (CPU-honest trend anchor)")
    p.add_argument("--out", default=os.path.join(REPO, "BENCH_device.json"))
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--keep_run", default=None,
                   help="keep the live run dir here (default: temp dir)")
    args = p.parse_args(argv)
    run_dir = args.keep_run or tempfile.mkdtemp(prefix="fedml_devbase.")
    rows = run_live_rounds(run_dir, args.rounds)
    if not rows:
        print("device_baseline: live run wrote no ledger lines")
        return 2
    art = distill(rows)
    with open(args.out, "w") as f:
        json.dump(art, f, indent=2)
    print(json.dumps({k: art[k] for k in
                      ("metric", "backend", "rounds", "round_s_median",
                       "compile_total_s", "mfu_median")
                      if k in art}))
    if art.get("timing_untrusted"):
        print(f"device_baseline: {art['timing_untrusted']}", file=sys.stderr)
        return 3
    print(f"device_baseline: wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
