#!/usr/bin/env bash
# Live secure-aggregation demo (ISSUE 11 acceptance): the real cross-silo
# transport speaking the pairwise-masked SecAgg protocol, three asserted
# arms —
#
#   1. parity      — a clean --secagg pairwise federation publishes a
#                    global within quantization tolerance of the
#                    plaintext defended-mean arm (checkpointed params
#                    compared leaf-for-leaf, not just eval metrics);
#   2. chaos kill  — a silo dies mid-round (its upload is lost after the
#                    mask agreement): the drop policy closes the
#                    barrier, and the unmask phase reconstructs the dead
#                    silo's pairwise secret from surviving Shamir shares
#                    (asserted via the reconstruction counter, labeled
#                    pair_key);
#   3. privacy     — the wire probe: every upload frame is uint32 ring
#                    words, and no individual plaintext update appears
#                    in ANY decoded frame (pytest-driven live probe);
#
# plus the observability contract: mask_agreement/unmask phases on every
# perf-ledger line under --perf_strict, the health ledger NAMING its
# suppressed fields, the trend gate green on both ledgers, and the
# committed BENCH_secagg.json present and self-consistent.
#
# Usage: scripts/run_secagg_demo.sh [workdir]  (default: a fresh mktemp dir)
set -euo pipefail
cd "$(dirname "$0")/.."

DIR="${1:-$(mktemp -d /tmp/fedml_secagg.XXXXXX)}"
mkdir -p "$DIR"
echo "== secagg demo: artifacts under $DIR"

BASE=(--algo cross_silo --model lr --dataset mnist
      --client_num_in_total 4 --client_num_per_round 4 --comm_round 3
      --frequency_of_the_test 3 --batch_size 4 --log_stdout false
      --checkpoint_every 1)
SECAGG=(--secagg pairwise --agg_mode stream)

echo "== arm 1: plaintext mean vs masked (--secagg pairwise) parity"
env JAX_PLATFORMS=cpu python -m fedml_tpu "${BASE[@]}" \
    --checkpoint_dir "$DIR/ckpt_plain" \
    --run_dir "$DIR/plain" > "$DIR/plain.json"
env JAX_PLATFORMS=cpu python -m fedml_tpu "${BASE[@]}" "${SECAGG[@]}" \
    --checkpoint_dir "$DIR/ckpt_secagg" \
    --perf true --perf_strict true --health true --telemetry true \
    --run_dir "$DIR/secagg" > "$DIR/secagg.json"

python - "$DIR" <<'EOF'
import json, sys
import numpy as np
from fedml_tpu.utils.checkpoint import RoundCheckpointer
from fedml_tpu.robust.admission import _leaves
d = sys.argv[1]

# published globals leaf-for-leaf: quantization is the ONLY divergence
a = RoundCheckpointer(f"{d}/ckpt_plain")
b = RoundCheckpointer(f"{d}/ckpt_secagg")
sa, sb = a.latest_round(), b.latest_round()
assert sa == sb, (sa, sb)
pa = a.restore(sa)["params"]
pb = b.restore(sb)["params"]
diff = max(float(np.max(np.abs(np.asarray(x, np.float64)
                              - np.asarray(y, np.float64))))
           for x, y in zip(_leaves(pa), _leaves(pb)))
print(f"max |plain - masked| over the published global: {diff:.3g}")
assert diff < 5e-4, f"masked global strayed beyond quantization: {diff}"

la = json.load(open(f"{d}/plain.json"))["test_loss"]
lb = json.load(open(f"{d}/secagg.json"))["test_loss"]
assert abs(la - lb) < 1e-3, (la, lb)

# observability: every ledger line carries the protocol phases, the
# recompile sentry stayed silent under strict mode, and the health
# ledger NAMES its suppressed fields instead of zeroing them
perf = [json.loads(l) for l in open(f"{d}/secagg/perf.jsonl")]
assert perf and all("mask_agreement" in r["phases"]
                    and "unmask" in r["phases"] for r in perf), \
    sorted(perf[0]["phases"])
assert all(r["recompiles"] == 0 for r in perf)
health = [json.loads(l) for l in open(f"{d}/secagg/health.jsonl")]
assert all(r.get("suppressed", {}).get("reason")
           == "secagg_pairwise_masking" for r in health)
assert all(r["norm"]["count"] == 0 and r["accepted"] == 4 for r in health)
tel = json.load(open(f"{d}/secagg/telemetry.json"))
masked = sum(v for k, v in tel["counters"].items()
             if k.startswith("fedml_secagg_masked_uploads_total"))
assert masked == 12, masked  # 4 silos x 3 rounds, every upload masked
print("arm 1 OK: parity + ledger phases + named health suppression")
EOF

echo "== trend gate over the masked arm's ledgers"
python scripts/perf_trend.py --ledger "$DIR/secagg/perf.jsonl" \
    --health_ledger "$DIR/secagg/health.jsonl"

echo "== arm 2: chaos-killed silo mid-round, recovered via shares"
env JAX_PLATFORMS=cpu python -m fedml_tpu --algo cross_silo --model lr \
    --dataset mnist --client_num_in_total 5 --client_num_per_round 5 \
    --comm_round 4 --frequency_of_the_test 4 --batch_size 4 \
    --log_stdout false "${SECAGG[@]}" \
    --chaos_drop 0.05 --chaos_seed 1 \
    --straggler_policy drop --round_timeout_s 2 --min_silo_frac 0.4 \
    --telemetry true --run_dir "$DIR/chaos" > "$DIR/chaos.json"

python - "$DIR" <<'EOF'
import json, sys
d = sys.argv[1]
summary = json.load(open(f"{d}/chaos.json"))
assert "test_loss" in summary and summary["test_loss"] == summary["test_loss"]
tel = json.load(open(f"{d}/chaos/telemetry.json"))
recon = {k: v for k, v in tel["counters"].items()
         if k.startswith("fedml_secagg_unmask_reconstructions_total")}
pair = sum(v for k, v in recon.items() if 'kind="pair_key"' in k)
selfm = sum(v for k, v in recon.items() if 'kind="self_mask"' in k)
assert pair >= 1, (
    f"no dead silo's pairwise secret was ever reconstructed: {recon}")
assert selfm >= 1, recon
print(f"arm 2 OK: federation survived chaos; reconstructions: "
      f"self_mask={selfm:.0f}, pair_key={pair:.0f} (dropout recovery)")
EOF

echo "== arm 3: privacy probe — no plaintext update on any wire frame"
env JAX_PLATFORMS=cpu python -m pytest tests/test_secagg_live.py -q \
    -p no:cacheprovider \
    -k "privacy or plaintext or cancellation" \
    | tail -2

echo "== committed BENCH_secagg.json self-consistency"
python - <<'EOF'
import json
b = json.load(open("BENCH_secagg.json"))
arms = b["arms"]
for n in (8, 32):
    flat, grp = arms[f"n{n}_flat"], arms[f"n{n}_grouped"]
    assert grp["share_envelopes_total"] < flat["share_envelopes_total"], n
    assert flat["masked_uploads_total"] >= flat["n_silos"], n
    assert flat["recompiles"] == 0 and grp["recompiles"] == 0, n
print("BENCH_secagg.json OK:",
      {f"n{n}": {"flat_env": arms[f"n{n}_flat"]["share_envelopes_total"],
                 "grouped_env": arms[f"n{n}_grouped"]["share_envelopes_total"]}
       for n in (8, 32)})
EOF

echo "== secagg demo OK ($DIR)"
