"""FedOpt — server-side adaptive optimization (Reddi et al. 2020).

Parity with fedml_api/distributed/fedopt/FedOptAggregator.py:
the server averages client params, forms the pseudo-gradient
Δ = w_old − w_avg (``set_model_global_grads``, FedOptAggregator.py:108-122:
``parameter.grad = parameter.data - new_parameter.data``), and applies a
torch server optimizer.  The reference resolves optimizers by reflection over
``torch.optim.Optimizer.__subclasses__()`` (utils/optrepo.py:12); here the
registry maps names to optax transforms.

TPU design: the server step is pure — (w_old, w_avg, opt_state) →
(w_new, opt_state') — and jits together with the cohort step, so a whole
FedOpt round (local SGD on the cohort + psum aggregation + Adam server step)
is still one compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import optax

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.core.pytree import tree_sub

Pytree = Any

# name -> factory(lr, momentum) (parity surface of OptRepo: the torch
# optimizers the reference's experiments actually use)
SERVER_OPTIMIZERS = {
    "sgd": lambda lr, momentum: optax.sgd(lr, momentum=momentum or None),
    "adam": lambda lr, momentum: optax.adam(lr),
    "adagrad": lambda lr, momentum: optax.adagrad(lr),
    "adamw": lambda lr, momentum: optax.adamw(lr),
    "rmsprop": lambda lr, momentum: optax.rmsprop(lr, momentum=momentum),
    "yogi": lambda lr, momentum: optax.yogi(lr),
}


@dataclasses.dataclass
class FedOptConfig(FedAvgConfig):
    """Adds the server flags of main_fedopt.py:54-62."""
    server_optimizer: str = "sgd"
    server_lr: float = 0.1
    server_momentum: float = 0.0


class FedOpt(FedAvg):
    """FedAvg + server optimizer on the pseudo-gradient."""

    def __init__(self, workload, data, config: FedOptConfig, mesh=None, sink=None):
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        try:
            factory = SERVER_OPTIMIZERS[config.server_optimizer]
        except KeyError:
            raise ValueError(
                f"unknown server optimizer {config.server_optimizer!r}; "
                f"available: {sorted(SERVER_OPTIMIZERS)}") from None
        self.server_opt = factory(config.server_lr, config.server_momentum)
        self.server_opt_state = None

        base_step = self.cohort_step

        @jax.jit
        def step(global_params, cohort_data, rng, opt_state):
            w_avg, metrics = base_step(global_params, cohort_data, rng)
            delta = tree_sub(global_params, w_avg)  # pseudo-gradient
            updates, opt_state = self.server_opt.update(
                delta, opt_state, global_params)
            new_params = optax.apply_updates(global_params, updates)
            return new_params, metrics, opt_state

        self._fedopt_step = step
        # FedAvg.run drives self.cohort_step(params, cohort, rng)
        self.cohort_step = self._stateful_step

    def _stateful_step(self, params, cohort, rng):
        if self.server_opt_state is None:
            self.server_opt_state = self.server_opt.init(params)
        params, metrics, self.server_opt_state = self._fedopt_step(
            params, cohort, rng, self.server_opt_state)
        return params, metrics

    # server optimizer state (momentum / Adam moments) rides the round
    # checkpoint so a resumed run continues the same trajectory
    def _extra_state(self):
        return {"server_opt_state": self.server_opt_state}

    def _extra_state_template(self, params):
        return {"server_opt_state": self.server_opt.init(params)}

    def _load_extra_state(self, extra) -> None:
        self.server_opt_state = extra["server_opt_state"]
