"""Federated long-context training: dp × sp in ONE compiled program.

The reference caps sequences at one process's memory (its largest NLP model
is a 2-layer LSTM on 80-token windows, fedml_api/model/nlp/rnn.py:18-22;
SURVEY.md §5.7).  Here a cohort trains over a 2-D ``[clients, sequence]``
mesh: the cohort is data-parallel over the ``clients`` axis exactly as in
the cohort engine (fedml_tpu/parallel/cohort.py), while INSIDE each client's
local SGD the transformer's sequence axis is sharded over ``sequence`` with
exact ring attention (fedml_tpu/parallel/ring_attention.py).  One shard_map,
two collectives families: ring `ppermute` + loss/grad `psum` over
``sequence`` within a client, weighted aggregation `psum` over ``clients``
across the cohort.

SPMD correctness notes (the two easy-to-get-wrong pieces):

* the per-position CE is normalized by GLOBAL psum'd counts, so every
  sequence shard computes the identical loss value;
* each shard's backward produces only its PARTIAL gradient (its own logits'
  contribution), so the local trainer psums gradients over ``sequence``
  before the optimizer step (``grad_reduce`` hook, trainer/local_sgd.py) —
  all shards then take identical optimizer steps and parameters stay in
  sync without any explicit broadcast.

Parity test: dp×sp on the 8-device mesh == single-chip vmap cohort with
dense attention (tests/test_ring_attention.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.cohort import (compat_pcast_varying,
                                       compat_shard_map, train_cohort)
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import Workload


def make_sp_nwp_workload(model, axis_name: str = "sequence",
                         pad_id: int = 0,
                         grad_clip_norm: Optional[float] = None) -> Workload:
    """Next-token workload over a sequence-sharded model.

    ``model`` is a TransformerLM (anything taking ``positions``/
    ``ring_axis``).  ``loss_fn`` runs INSIDE a shard_map over ``axis_name``:
    the batch's token dim is the local shard, global positions come from the
    mesh coordinate, and sums/counts psum over the axis so the loss (and
    therefore the optimizer trajectory) is identical on every shard.

    ``init`` runs dense (outside the mesh) — fine for initialization since
    no [T, T] scores materialize there; at truly init-bound lengths,
    initialize at a shorter T (parameters are length-independent).

    Dropout caveat: per-shard dropout rngs would decorrelate across the
    sequence axis; keep ``dropout_rate=0`` for sp runs (the default).
    """

    def _position_mask(batch):
        tok_valid = (batch["y"] != pad_id).astype(jnp.float32)
        return tok_valid * batch["mask"][:, None]

    def _logits(params, batch, train):
        t_local = batch["x"].shape[-1]
        pos = (jax.lax.axis_index(axis_name) * t_local
               + jnp.arange(t_local))
        out = model.apply({"params": params}, batch["x"], train=train,
                          positions=pos, ring_axis=axis_name)
        return out.astype(jnp.float32)

    def loss_fn(params, batch, rng, train):
        logits = _logits(params, batch, train)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                             batch["y"])
        m = _position_mask(batch)
        total = jax.lax.psum(jnp.sum(ce * m), axis_name)
        count = jax.lax.psum(jnp.sum(m), axis_name)
        loss = total / jnp.maximum(count, 1.0)
        return loss, {"loss": loss}

    def metric_fn(params, batch):
        logits = _logits(params, batch, train=False)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                             batch["y"])
        pred = jnp.argmax(logits, axis=-1)
        m = _position_mask(batch)
        return {
            "correct": jax.lax.psum(jnp.sum((pred == batch["y"]) * m),
                                    axis_name),
            "loss_sum": jax.lax.psum(jnp.sum(ce * m), axis_name),
            "total": jax.lax.psum(jnp.sum(m), axis_name),
        }

    return Workload(model=model, loss_fn=loss_fn, metric_fn=metric_fn,
                    grad_clip_norm=grad_clip_norm)


def make_sp_mesh(n_clients: int, n_sequence: int, devices=None) -> Mesh:
    """[clients, sequence] grid.  Lay devices so the sequence axis (the
    latency-critical ring) rides contiguous ICI neighbors."""
    import numpy as np
    devs = list(devices if devices is not None else jax.devices())
    if n_clients * n_sequence != len(devs):
        raise ValueError(f"mesh {n_clients}x{n_sequence} != "
                         f"{len(devs)} devices")
    return Mesh(np.asarray(devs).reshape(n_clients, n_sequence),
                ("clients", "sequence"))


def make_sp_cohort_step(workload: Workload,
                        optimizer: optax.GradientTransformation,
                        epochs: int, mesh: Mesh,
                        axis_name: str = "sequence"):
    """One federated round over the [clients, sequence] mesh.

    ``step(params, cohort_data, rng) -> (new_params, metrics)``; cohort
    leaves [C, S, B, ...] with the token dim of x/y sharded over
    ``axis_name`` and clients over ``clients``.  The aggregation psums over
    BOTH axes with the sequence copies divided out, which also proves the
    fully-replicated out_spec (same trick as the two-level hierarchical
    mesh, algorithms/hierarchical.py).
    """
    from fedml_tpu.parallel.cohort import compat_is_legacy_shard_map
    if compat_is_legacy_shard_map():
        # fail-loud, not train-wrong: grad_reduce psums INSIDE the
        # mapped backward pass, and the legacy experimental shard_map
        # transposes that psum incorrectly without the replication
        # tracking pcast feeds — observed 3.4e-3 param drift vs the
        # dense oracle, i.e. silently wrong training
        raise RuntimeError(
            "sequence-parallel training (make_sp_cohort_step) requires "
            "a jax with jax.shard_map: the legacy experimental "
            "shard_map mis-transposes the gradient psum and trains "
            "silently wrong — upgrade jax (single-chip and "
            "--attn_block_size paths work everywhere)")
    local_train = make_local_trainer(
        workload, optimizer, epochs,
        grad_reduce=lambda g: jax.lax.psum(g, axis_name))
    n_cli = mesh.shape["clients"]
    n_seq = mesh.shape[axis_name]

    def _sharded(params, data, rng):
        params = compat_pcast_varying(params, ("clients", axis_name))
        rng = compat_pcast_varying(rng, ("clients", axis_name))
        local_c = data["num_samples"].shape[0]
        offset = jax.lax.axis_index("clients") * local_c
        stacked, metrics = train_cohort(local_train, params, data, rng,
                                        index_offset=offset)
        w = data["num_samples"].astype(jnp.float32)
        total = jax.lax.psum(jnp.sum(w), "clients")
        ratio = w / jnp.maximum(total, 1.0) / n_seq
        new_global = jax.tree.map(
            lambda x: jax.lax.psum(jnp.sum(
                x.astype(jnp.float32)
                * ratio.reshape((-1,) + (1,) * (x.ndim - 1)), axis=0),
                ("clients", axis_name)).astype(x.dtype),
            stacked)
        # per-step losses are already psum'd over the sequence axis inside
        # the loss, so divide out nothing — just prove invariance
        metrics = jax.tree.map(
            lambda x: jax.lax.psum(x, axis_name) / n_seq, metrics)
        return new_global, metrics

    data_spec = {"x": P("clients", None, None, axis_name),
                 "y": P("clients", None, None, axis_name),
                 "mask": P("clients"),
                 "num_samples": P("clients")}
    sharded = compat_shard_map(_sharded, mesh=mesh,
                               in_specs=(P(), data_spec, P()),
                               out_specs=(P(), P("clients")))

    @jax.jit
    def step(params, cohort_data, rng):
        C = cohort_data["num_samples"].shape[0]
        T = cohort_data["x"].shape[-1]
        if C % n_cli:
            raise ValueError(f"cohort size {C} not divisible by the mesh "
                             f"clients axis ({n_cli})")
        if T % n_seq:
            raise ValueError(f"sequence length {T} not divisible by the "
                             f"mesh sequence axis ({n_seq})")
        return sharded(params, cohort_data, rng)

    return step
