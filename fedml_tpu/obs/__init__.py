"""Observability subsystem: distributed round tracing, a telemetry
registry, and the run-report merger.

Three pillars, all stdlib-only (the `MetricsSink` dependency posture):

    fedml_tpu.obs.trace      span tracer; context propagates through
                             Message headers; Perfetto trace_event export
    fedml_tpu.obs.telemetry  thread-safe counter/gauge/histogram registry;
                             Prometheus text exposition + JSON snapshots
    fedml_tpu.obs.report     merges metrics.jsonl + telemetry snapshot +
                             trace into a per-round timeline report
                             (CLI: scripts/obs_report.py)

Both trace and telemetry are process-global opt-ins (``enable()``);
disabled they are a null tracer / null registry and instrumented hot
paths pay a single branch per event.  Enable BEFORE constructing
transports/actors — instrumented constructors cache their metric handles.

Four further pillars ride on those:

    fedml_tpu.obs.perf       performance flight recorder: per-round
                             perf.jsonl ledger (phase wall-times, RSS
                             watermark, recompile sentry) + SLO
                             evaluator over the telemetry registry
    fedml_tpu.obs.device     device & compile observatory: per-device
                             memory watermarks, named compile ledger
                             (wall time per jit cache entry), achieved
                             FLOP/s + honest MFU from XLA cost
                             analysis — rides the PerfRecorder round
                             cadence as each line's ``device`` section
    fedml_tpu.obs.health     federation health observatory: streaming
                             learning-health statistics on the receive
                             path (update-norm moments, cosine
                             alignment, per-silo fairness, drift
                             alarms) + health.jsonl ledger
    fedml_tpu.obs.trend      perf regression gate (phases + device
                             compile-time/memory) + health-ledger
                             schema gate + mfu<=1.0 timing-trust lint
                             (CLI: scripts/perf_trend.py)
"""

from fedml_tpu.obs.device import DeviceRecorder
from fedml_tpu.obs.health import HealthAccumulator
from fedml_tpu.obs.perf import (PerfRecorder, RecompileError,
                                RecompileSentry, RssSampler, SloEvaluator)
from fedml_tpu.obs.telemetry import (NullRegistry, TelemetryRegistry,
                                     start_http_server)
from fedml_tpu.obs.trace import Span, SpanContext, SpanTracer

__all__ = ["NullRegistry", "TelemetryRegistry", "start_http_server",
           "Span", "SpanContext", "SpanTracer",
           "DeviceRecorder", "HealthAccumulator", "PerfRecorder",
           "RecompileError", "RecompileSentry", "RssSampler",
           "SloEvaluator"]
