"""Import reference PyTorch checkpoints into flax params.

Parity target: the reference's pretrained-weight loading —
``fedml_api/model/cv/resnet.py:202-246`` (``torch.load`` a ``{'state_dict':
...}`` checkpoint, strip the DataParallel ``module.`` prefix, load) and the
GAN BaseModel save/load (``cv/base_model.py:161-178,277-296``).

Approach: both frameworks create sub-modules in forward/definition order, so
a torch ``state_dict`` (insertion-ordered) and a flax params tree (dict
insertion order = creation order) enumerate the SAME sequence of units
(conv / norm / dense).  The converter zips the two walks, transposing
layouts (torch conv OIHW -> flax HWIO, dense [out,in] -> [in,out]) and
routing BatchNorm running stats into the ``batch_stats`` collection.  This
is structural, not name-based, so it works for any reference model whose
module order matches its flax re-implementation (ResNets, CNNs, GANs).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

Pytree = Any


def strip_module_prefix(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """DataParallel saves keys as ``module.*`` (resnet.py:213-217); strip
    only the leading prefix (a mid-key 'module.' belongs to a real
    attribute name)."""
    return {(k[len("module."):] if k.startswith("module.") else k): v
            for k, v in state_dict.items()}


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """torch.load -> numpy state_dict (handles the reference's
    ``{'state_dict': ...}`` wrapper)."""
    import torch
    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    sd = ckpt.get("state_dict", ckpt) if isinstance(ckpt, dict) else ckpt
    return {k: v.detach().cpu().numpy()
            for k, v in strip_module_prefix(sd).items()
            if hasattr(v, "detach")}


def _torch_units(sd: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
    """Group consecutive same-prefix entries into per-module units."""
    units: List[Dict[str, np.ndarray]] = []
    prev_prefix = None
    for k, v in sd.items():
        if k.endswith("num_batches_tracked"):
            continue
        prefix, name = k.rsplit(".", 1) if "." in k else ("", k)
        if prefix != prev_prefix:
            units.append({})
            prev_prefix = prefix
        units[-1][name] = np.asarray(v)
    return units


_TYPE_RANK = {"Conv": 0, "ConvTranspose": 0, "Norm": 1}


def _elem_key(name: str):
    """Reconstruct creation order from flax auto-names (the params dict is
    ALPHABETICALLY sorted, so 'Bottleneck_0' would sort before the stem
    'Conv_0').  Within one module the torch-mirroring nets here create
    Conv_i immediately followed by Norm_i, with container blocks after the
    stem and explicitly-named heads ('fc') last — so order by (index,
    conv<norm<container), non-indexed names last.  Any model where this
    heuristic misfires fails the count/shape validation loudly."""
    prefix, _, idx = name.rpartition("_")
    if prefix and idx.isdigit():
        return (0, int(idx), _TYPE_RANK.get(prefix, 2), prefix)
    return (1, 0, 0, name)


def _path_key(path: Tuple[str, ...]):
    return tuple(_elem_key(p) for p in path)


def _flax_units(params: Pytree, path: Tuple[str, ...] = ()
                ) -> List[Tuple[Tuple[str, ...], Dict]]:
    """Leaf modules (dicts holding 'kernel' or 'scale'/'bias') in creation
    order (see _elem_key)."""
    out = []
    if isinstance(params, dict):
        if "kernel" in params or "scale" in params or (
                set(params) <= {"bias"} and params):
            return [(path, params)]
        for k, v in params.items():
            out.extend(_flax_units(v, path + (k,)))
        if not path:  # sort once, at the root
            out.sort(key=lambda pu: _path_key(pu[0]))
    return out


def _get_path(tree: Pytree, path: Tuple[str, ...]):
    for p in path:
        if not isinstance(tree, dict) or p not in tree:
            return None
        tree = tree[p]
    return tree


def import_torch_state_dict(variables: Pytree,
                            state_dict: Dict[str, np.ndarray]) -> Pytree:
    """Fill a flax variables dict (``{"params": ..., "batch_stats": ...}``
    or bare params) from an ordered torch state_dict.  Returns a new tree;
    raises on any unit-count or shape mismatch (silent partial loads are
    how wrong-checkpoint bugs hide)."""
    import jax

    full = "params" in variables
    params = jax.tree.map(np.asarray, variables["params"] if full
                          else variables)
    stats = jax.tree.map(np.asarray, variables.get("batch_stats", {})) \
        if full else {}

    t_units = _torch_units(state_dict)
    f_units = _flax_units(params)
    if len(t_units) != len(f_units):
        raise ValueError(
            f"unit count mismatch: torch has {len(t_units)} modules, flax "
            f"has {len(f_units)} — architectures differ")

    for (path, leaf), tu in zip(f_units, t_units):
        where = "/".join(path)
        if "kernel" in leaf:
            w = tu.get("weight")
            if w is None:
                raise ValueError(f"{where}: torch unit has no weight")
            if leaf["kernel"].ndim == 4:          # conv OIHW -> HWIO
                w = w.transpose(2, 3, 1, 0)
            elif leaf["kernel"].ndim == 2:        # dense [out,in] -> [in,out]
                w = w.T
            if w.shape != leaf["kernel"].shape:
                raise ValueError(f"{where}: kernel shape {leaf['kernel'].shape}"
                                 f" vs torch {w.shape}")
            leaf["kernel"] = w.astype(leaf["kernel"].dtype)
            if "bias" in leaf and "bias" in tu:
                leaf["bias"] = tu["bias"].astype(leaf["bias"].dtype)
        else:                                     # norm affine
            if "scale" in leaf and "weight" in tu:
                if tu["weight"].shape != leaf["scale"].shape:
                    raise ValueError(f"{where}: scale shape mismatch")
                leaf["scale"] = tu["weight"].astype(leaf["scale"].dtype)
            if "bias" in leaf and "bias" in tu:
                leaf["bias"] = tu["bias"].astype(leaf["bias"].dtype)
            if "running_mean" in tu:
                st = _get_path(stats, path)
                if st is not None:
                    st["mean"] = tu["running_mean"].astype(st["mean"].dtype)
                    st["var"] = tu["running_var"].astype(st["var"].dtype)

    out = {"params": params, **({"batch_stats": stats} if stats else {})} \
        if full else params
    return jax.tree.map(lambda x: x, out)  # fresh copy


def load_pretrained_resnet(path: str, depth: int = 56,
                           num_classes: int = 10) -> Tuple[Any, Pytree]:
    """``resnet56(class_num, pretrained=True, path=...)`` parity
    (resnet.py:202-222): returns (flax model, variables) with the torch
    checkpoint's weights, BatchNorm running stats included."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models import resnet56, resnet110
    model = (resnet56 if depth == 56 else resnet110)(num_classes,
                                                     norm="batch")
    dummy = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), dummy)
    return model, import_torch_state_dict(
        dict(variables), load_torch_checkpoint(path))
