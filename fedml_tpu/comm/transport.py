"""Transport SPI — the seam between algorithm choreography and the wire.

Reference equivalent: ``BaseCommunicationManager``
(fedml_core/distributed/communication/base_com_manager.py:7-27) and
``Observer`` (observer.py:4-8).  Same contract, two differences:

- `run()` is explicit and blocking (the reference hides a 0.3 s polling loop
  inside ``handle_receive_message``, mpi/com_manager.py:71-81; our transports
  block on queues/sockets — no idle polling).
- transports declare a ``flavor``: ``"p2p"`` for host-edge message passing
  (local / tcp-grpc / mqtt) — on-pod "transport" does not exist as an object
  at all, it is `lax.psum` inside the jit program.
"""

from __future__ import annotations

import abc
from typing import Protocol, runtime_checkable

from fedml_tpu.comm.message import Message


@runtime_checkable
class Observer(Protocol):
    def receive_message(self, msg_type, msg: Message) -> None: ...


class Transport(abc.ABC):
    """Abstract p2p transport: deliver Messages between numbered nodes."""

    flavor = "p2p"

    def __init__(self):
        self._observers: list[Observer] = []

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        # idempotent: teardown paths (actor finish + test fixture cleanup)
        # may both remove; the second call is a no-op, not a ValueError
        if observer in self._observers:
            self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        for obs in self._observers:
            obs.receive_message(msg.type, msg)

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        """Deliver msg to msg.receiver_id (asynchronously)."""

    @abc.abstractmethod
    def run(self) -> None:
        """Block dispatching inbound messages to observers until stopped."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Unblock run() and release resources.  Implementations MUST be
        idempotent: overlapping teardown paths (straggler-policy abort,
        actor ``finish()``, test fixtures) may each call ``stop()``."""
