"""Update compression for the cross-silo wire (WAN bandwidth).

The reference ships updates as JSON float lists (fedavg/utils.py:7-16 —
~4x bloat); our binary codec (comm/message.py) removes the encoding
overhead, and this module removes information redundancy on top of it for
bandwidth-limited silos.  Two classic schemes over the UPDATE (delta to the
global model, which is sparse-able and small-ranged; raw weights are
neither):

* ``topk`` — keep the k largest-|x| entries per leaf (Aji & Heafield 2017
  style sparsification): indices (int32) + values, ~2k/n of the dense
  bytes (each kept entry costs an index word plus a value word).
* ``int8`` — per-leaf symmetric linear quantization to uint8 with an f32
  scale: 4x smaller, max error scale/2.

Both are LOSSY; the cross-silo runner applies them to uploads only (the
down-link broadcast stays exact so silos never drift from the true global
model).  Error-feedback accumulation (keeping the residual client-side and
adding it to the next round's delta) composes naturally with the silo
train_fn closure but is deliberately not built in here — cross-round client
state contradicts the reference's stateless-client contract
(FedAVGTrainer re-pointed per round, FedAVGTrainer.py:25-29).

Pure numpy on purpose: compression runs host-side at the wire boundary,
never inside a jit.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

Pytree = Any

SCHEMES = ("none", "topk", "int8")


def compress_update(tree: Pytree, scheme: str, topk_frac: float = 0.1):
    """tree -> wire-able payload (still a pytree of arrays, so it rides the
    binary message codec unchanged)."""
    if scheme == "none":
        return {"scheme": "none", "tree": tree}
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    if scheme == "topk":
        comp = []
        for x in leaves:
            x = np.asarray(x)
            if not np.issubdtype(x.dtype, np.floating) or x.size < 16:
                comp.append({"dense": x})
                continue
            flat = x.reshape(-1)
            k = max(1, int(round(topk_frac * flat.size)))
            idx = np.argpartition(np.abs(flat), -k)[-k:].astype(np.int32)
            comp.append({"idx": idx, "val": flat[idx],
                         "shape": np.asarray(x.shape, np.int64),
                         "dtype": str(x.dtype)})
        return {"scheme": "topk", "leaves": comp,
                "treedef": _treedef_token(treedef, tree)}
    if scheme == "int8":
        comp = []
        for x in leaves:
            x = np.asarray(x)
            if not np.issubdtype(x.dtype, np.floating) or x.size < 16:
                comp.append({"dense": x})
                continue
            amax = float(np.max(np.abs(x)))
            scale = amax / 127.0 if amax > 0 else 1.0
            q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
            comp.append({"q": q, "scale": np.float32(scale),
                         "dtype": str(x.dtype)})
        return {"scheme": "int8", "leaves": comp,
                "treedef": _treedef_token(treedef, tree)}
    raise ValueError(f"unknown compression scheme {scheme!r}; "
                     f"available: {SCHEMES}")


def decompress_update(payload, like: Pytree) -> Pytree:
    """Inverse of compress_update; ``like`` supplies the tree structure
    (the server always knows the model skeleton)."""
    import jax
    scheme = payload["scheme"]
    if scheme == "none":
        return payload["tree"]
    like_leaves, treedef = jax.tree.flatten(like)
    if payload["treedef"] != _treedef_token(treedef, like):
        raise ValueError(
            "compressed payload tree structure does not match the "
            "receiver's model skeleton — sender/receiver model mismatch")
    out = []
    for d, ref in zip(payload["leaves"], like_leaves):
        if "dense" in d:
            out.append(np.asarray(d["dense"]))
        elif scheme == "topk":
            flat = np.zeros(int(np.prod(d["shape"])), dtype=d["dtype"])
            flat[np.asarray(d["idx"])] = np.asarray(d["val"])
            out.append(flat.reshape(tuple(int(s) for s in d["shape"])))
        else:  # int8
            out.append((np.asarray(d["q"], np.float32)
                        * float(d["scale"])).astype(d["dtype"]))
    return jax.tree.unflatten(treedef, out)


def _treedef_token(treedef, tree) -> str:
    """A cheap structural fingerprint carried on the wire so a mismatched
    decompress fails loudly instead of mis-zipping leaves."""
    return str(treedef)


def wire_bytes(payload) -> int:
    """Approximate payload size (for tests/metrics): summed array bytes."""
    import jax
    return sum(np.asarray(x).nbytes
               for x in jax.tree.leaves(payload)
               if hasattr(np.asarray(x), "nbytes"))
