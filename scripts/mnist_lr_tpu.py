"""On-chip reproduction of a published benchmark row (VERDICT r4 item 8).

benchmark/README.md:12 row: logistic regression on MNIST — 1000 clients,
10 per round, B=10, SGD lr=0.03, E=1, target >75 train accuracy past 100
rounds.  The CPU tier already proves this config learns
(tests/test_convergence.py::test_mnist_lr_to_75 on the hermetic learnable
twin); this script runs the SAME config end-to-end on the attached TPU
and writes the full accuracy curve + wall-clock to MNIST_LR_TPU.json —
the committed artifact closing the loop from SURVEY §6 on the chip side.

Every eval lands incrementally in MNIST_LR_TPU.json.partial so a tunnel
wedge mid-run still leaves the curve measured so far on disk (the same
hardening as scripts/flagship_accuracy.py).

Usage: `python scripts/mnist_lr_tpu.py` (TPU; minutes at measured round
rates).  `--platform cpu --rounds 8` is the wiring sanity run.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="tpu", choices=["cpu", "tpu"])
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--eval_every", type=int, default=10)
    ap.add_argument("--json_out", default="MNIST_LR_TPU.json")
    args = ap.parse_args()

    import jax
    if args.platform != "tpu":
        # pin before any backend query (a wedged tunnel blocks forever)
        jax.config.update("jax_platforms", args.platform)

    from fedml_tpu.algorithms import FedAvg, FedAvgConfig
    from fedml_tpu.data.synthetic import mnist_learnable_twin
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload

    config = {"model": "lr", "dataset": "mnist_learnable_twin",
              "clients": args.clients, "clients_per_round": 10,
              "batch_size": 10, "lr": 0.03, "epochs": 1,
              "rounds": args.rounds,
              "reference_row": "benchmark/README.md:12 — >75 train acc "
                               "past 100 rounds"}
    data = mnist_learnable_twin(num_clients=args.clients, batch_size=10,
                                seed=0)
    wl = ClassificationWorkload(
        LogisticRegression(input_dim=784, output_dim=10), num_classes=10,
        grad_clip_norm=None)
    curve = []

    class Sink:
        """Append every eval to <out>.partial as it lands — a wedge
        mid-run still leaves the curve measured so far on disk."""

        def log(self, metrics, step=None):
            if "train_acc" not in metrics:
                return
            curve.append({"round": step,
                          "train_acc": metrics.get("train_acc"),
                          "test_acc": metrics.get("test_acc")})
            with open(args.json_out + ".partial", "w") as f:
                json.dump({"partial": True, "config": config,
                           "curve_so_far": curve}, f, indent=1)

    cfg = FedAvgConfig(comm_round=args.rounds, client_num_per_round=10,
                       epochs=1, batch_size=10, lr=0.03,
                       frequency_of_the_test=args.eval_every, seed=0)
    algo = FedAvg(wl, data, cfg, sink=Sink())
    dev = jax.devices()[0]
    t0 = time.time()
    params = algo.run()
    wall_s = time.time() - t0
    final = algo.evaluate_global(params)
    out = {"platform": dev.platform,
           "device_kind": str(getattr(dev, "device_kind", "unknown")),
           "captured_at": time.time(), "config": config,
           "wall_clock_s": wall_s,
           "final_train_acc": float(final["train_acc"]),
           "final_test_acc": float(final["test_acc"]),
           "target_met": bool(final["train_acc"] > 0.75),
           "curve": curve}
    with open(args.json_out, "w") as f:
        json.dump(out, f, indent=2)
    try:
        os.remove(args.json_out + ".partial")
    except OSError:
        pass
    print(json.dumps({"final_train_acc": out["final_train_acc"],
                      "target_met": out["target_met"],
                      "wall_clock_s": round(wall_s, 1)}))
    # the >75 target is published for a >100-round budget
    # (benchmark/README.md:12); a short --rounds wiring sanity run is
    # EXPECTED to miss it on the calibrated twin (0.54 at round 30) and
    # must not read as a failure
    if not out["target_met"] and args.rounds >= 100:
        sys.exit(4)


if __name__ == "__main__":
    main()
