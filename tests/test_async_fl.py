"""Asynchronous buffered aggregation (algorithms/async_fl.py, FedBuff
style) — barrier-free federation beyond the reference's strict
all-receive server."""

import jax
import numpy as np
import pytest

from fedml_tpu.experiments.main import main

_BASE = ["--model", "lr", "--dataset", "mnist",
         "--client_num_in_total", "8", "--client_num_per_round", "4",
         "--batch_size", "16", "--epochs", "1", "--lr", "0.1",
         "--frequency_of_the_test", "1", "--log_stdout", "false"]


def test_goal_equals_cohort_reduces_to_fedavg_round():
    """aggregation_goal == n_silos, zero staleness, server_lr 1: the first
    version IS a synchronous FedAvg round — identical evaluation metrics
    (same seeded cohort, same local-SGD rng chain, same weighted mean)."""
    argv = _BASE + ["--comm_round", "1", "--batch_size", "64"]
    fed = main(["--algo", "fedavg"] + argv)
    asy = main(["--algo", "async_fl", "--async_goal", "4"] + argv)
    np.testing.assert_allclose(asy["train_acc"], fed["train_acc"],
                               rtol=1e-6)
    np.testing.assert_allclose(asy["train_loss"], fed["train_loss"],
                               rtol=1e-5)
    assert asy["mean_staleness"] == 0.0


def test_async_goal_below_cohort_trains_with_staleness():
    """goal < n_silos: versions advance without the full cohort, stale
    deltas really occur (discounted, not dropped), and the model still
    learns."""
    out = main(["--algo", "async_fl", "--async_goal", "2",
                "--comm_round", "8"] + _BASE)
    first = main(["--algo", "async_fl", "--async_goal", "2",
                  "--comm_round", "1"] + _BASE)
    assert out["version"] == 8
    assert out["mean_staleness"] > 0.0  # re-tasked silos mixed with v0 uploads
    assert out["train_loss"] < first["train_loss"]


def test_server_validates_goal_and_ignores_late_uploads():
    from fedml_tpu.algorithms.async_fl import AsyncFedServerActor
    from fedml_tpu.comm.local import LocalHub
    from fedml_tpu.comm.message import Message
    from fedml_tpu.algorithms.cross_silo import MsgType

    hub = LocalHub()
    with pytest.raises(ValueError, match="aggregation_goal"):
        AsyncFedServerActor(hub.transport(0), {"w": np.zeros(2)}, 8, 4,
                            num_versions=2, aggregation_goal=5)

    hub2 = LocalHub()
    for i in (1, 2):  # sink endpoints for the server's task/finish sends
        hub2.transport(i)
    server = AsyncFedServerActor(hub2.transport(0), {"w": np.zeros(2)},
                                 8, 2, num_versions=1, aggregation_goal=1,
                                 server_lr=1.0, staleness_exponent=0.0)
    server.register_handlers()
    msg = Message(MsgType.C2S_MODEL, 1, 0)
    msg.add(Message.ARG_MODEL_PARAMS, {"w": np.ones(2, np.float32)})
    msg.add(Message.ARG_NUM_SAMPLES, 4)
    msg.add(Message.ARG_ROUND, 0)
    server._on_model(msg)
    np.testing.assert_allclose(server.params["w"], 1.0)  # delta applied
    assert server.version == 1  # reached num_versions -> finished
    late = Message(MsgType.C2S_MODEL, 2, 0)
    late.add(Message.ARG_MODEL_PARAMS, {"w": 5 * np.ones(2, np.float32)})
    late.add(Message.ARG_NUM_SAMPLES, 4)
    late.add(Message.ARG_ROUND, 0)
    server._on_model(late)  # after FINISH: must be a no-op
    np.testing.assert_allclose(server.params["w"], 1.0)


def _make_two_silo_server(alpha):
    from fedml_tpu.algorithms.async_fl import AsyncFedServerActor
    from fedml_tpu.comm.local import LocalHub
    from fedml_tpu.comm.message import Message
    from fedml_tpu.algorithms.cross_silo import MsgType

    hub = LocalHub()
    for i in (1, 2):  # sink endpoints for the server's task sends
        hub.transport(i)
    server = AsyncFedServerActor(hub.transport(0), {"w": np.zeros(1)},
                                 8, 2, num_versions=2, aggregation_goal=2,
                                 server_lr=1.0, staleness_exponent=alpha)
    server.register_handlers()
    server.version = 1  # pretend one aggregation happened

    def upload(sender, value, base_version, num_samples=10):
        m = Message(MsgType.C2S_MODEL, sender, 0)
        m.add(Message.ARG_MODEL_PARAMS, {"w": np.asarray([value],
                                                         np.float32)})
        m.add(Message.ARG_NUM_SAMPLES, num_samples)
        m.add(Message.ARG_ROUND, base_version)
        server._on_model(m)

    return server, upload


def test_staleness_discount_weighting():
    """The discount acts OUTSIDE the sample-weight normalization: mixing
    ratios come from raw num_samples, then each delta is scaled by its own
    (1+s)^-alpha — so staleness shrinks the applied step absolutely."""
    server, upload = _make_two_silo_server(alpha=1.0)
    upload(1, 3.0, 1)   # fresh: ratio 0.5, discount 1
    upload(2, 9.0, 0)   # stale s=1, alpha=1: ratio 0.5, discount 0.5
    # applied = 0.5*1*3 + 0.5*0.5*9 = 3.75  (old relative-only scheme: 5.0)
    np.testing.assert_allclose(server.params["w"], 3.75)
    assert list(server.staleness_seen) == [0, 1]


def test_uniformly_stale_buffer_is_damped_absolutely():
    """A buffer of uniformly stale deltas must be applied at reduced
    strength, not full strength (the FedBuff discount must not cancel in
    the normalization)."""
    server, upload = _make_two_silo_server(alpha=1.0)
    upload(1, 4.0, 0)   # both s=1 -> discount 0.5 each
    upload(2, 8.0, 0)
    # applied = 0.5 * mean(4, 8) = 3.0; undamped would be 6.0
    np.testing.assert_allclose(server.params["w"], 3.0)

    # zero staleness at alpha>0 stays exact weighted FedAvg (parity case)
    server2, upload2 = _make_two_silo_server(alpha=1.0)
    upload2(1, 4.0, 1, num_samples=30)
    upload2(2, 8.0, 1, num_samples=10)
    np.testing.assert_allclose(server2.params["w"], 5.0)  # (30*4+10*8)/40
