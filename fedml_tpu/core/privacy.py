"""Differential-privacy accounting: Rényi-DP (RDP) moments accountant.

The reference ships "weak DP" — per-update Gaussian noise with NO privacy
accounting (``fedml_core/robustness/robust_aggregation.py:51-55``; the
stddev is a bare config knob and no (ε, δ) is ever computed or reported).
This module provides the real thing for ``--algo dp_fedavg``
(algorithms/dp_fedavg.py): the subsampled-Gaussian RDP bound composed
over rounds and converted to (ε, δ), so every run reports the privacy it
actually spent.

Math (host-side numpy — accounting is not a TPU workload):

* Gaussian mechanism with L2 sensitivity 1 and noise multiplier z has
  RDP ``ε(α) = α / (2 z²)`` (Mironov 2017, arXiv:1702.07476).
* Under Poisson subsampling with rate q, the integer-order bound
  (Mironov, Talwar & Zhang 2019, arXiv:1908.10530 — the tf-privacy
  accountant formula) is

      ε(α) = 1/(α−1) · log Σ_{j=0..α} C(α,j)(1−q)^{α−j} q^j e^{j(j−1)/(2z²)}

  computed in log space (lgamma binomials + logaddexp) so large orders
  don't overflow.
* RDP composes additively over rounds; conversion to (ε, δ) takes
  ``min_α [ ε(α) + log(1/δ)/(α−1) ]``.

Caveat (documented, standard practice): cohort sampling here is
fixed-size without replacement (core/sampling.sample_clients), accounted
as Poisson sampling with q = cohort/N — the approximation every
production DP-FL accountant makes.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence, Tuple

import numpy as np

# α=2..63 densely (small ε regimes resolve there) plus sparse large
# orders for tiny q / large z
DEFAULT_ORDERS: Tuple[int, ...] = tuple(range(2, 64)) + (
    80, 96, 128, 192, 256, 512)


def rdp_subsampled_gaussian(q: float, noise_multiplier: float,
                            orders: Sequence[int] = DEFAULT_ORDERS
                            ) -> np.ndarray:
    """Per-step RDP ε(α) of the Poisson-subsampled Gaussian mechanism.

    ``q=1`` reduces exactly to the unsubsampled Gaussian ``α/(2z²)``
    (unit-tested); ``q=0`` spends nothing; ``z=0`` is non-private (inf).
    Orders must be integers ≥ 2 (the integer-order bound).
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate q must be in [0, 1], got {q}")
    orders = np.asarray(list(orders))
    if orders.ndim != 1 or np.any(orders < 2) or \
            np.any(orders != orders.astype(int)):
        raise ValueError("orders must be integers >= 2")
    if noise_multiplier <= 0.0:
        return np.full(orders.shape, np.inf)
    if q == 0.0:
        return np.zeros(orders.shape)
    z2 = float(noise_multiplier) ** 2
    if q == 1.0:
        return orders / (2.0 * z2)
    out = np.empty(len(orders))
    log_q, log_1q = math.log(q), math.log1p(-q)
    for i, a in enumerate(int(o) for o in orders):
        # log-space sum of C(a,j)(1-q)^(a-j) q^j exp(j(j-1)/(2 z²))
        terms = [math.lgamma(a + 1) - math.lgamma(j + 1)
                 - math.lgamma(a - j + 1)
                 + (a - j) * log_1q + j * log_q
                 + j * (j - 1) / (2.0 * z2)
                 for j in range(a + 1)]
        out[i] = float(np.logaddexp.reduce(terms)) / (a - 1)
    return out


def eps_from_rdp(rdp: np.ndarray, orders: Sequence[int],
                 delta: float) -> float:
    """(ε, δ) from composed RDP: ``min_α [ε(α) + log(1/δ)/(α−1)]``
    (Mironov 2017 Prop. 3)."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    orders = np.asarray(list(orders), dtype=np.float64)
    eps = np.asarray(rdp) + math.log(1.0 / delta) / (orders - 1.0)
    return float(np.min(eps))


class RdpAccountant:
    """Tracks privacy spent by repeated subsampled-Gaussian rounds.

    One instance per training run: ``step(n)`` after n rounds,
    ``epsilon()`` any time (cheap — the per-step RDP vector is computed
    once and composition is a scalar multiply)."""

    def __init__(self, q: float, noise_multiplier: float, delta: float,
                 orders: Iterable[int] = DEFAULT_ORDERS):
        self.q = float(q)
        self.noise_multiplier = float(noise_multiplier)
        self.delta = float(delta)
        self.orders = tuple(int(o) for o in orders)
        self._per_step = rdp_subsampled_gaussian(
            self.q, self.noise_multiplier, self.orders)
        self.steps = 0

    def step(self, n: int = 1) -> None:
        self.steps += int(n)

    def epsilon(self) -> float:
        if self.steps == 0:
            return 0.0
        return eps_from_rdp(self._per_step * self.steps, self.orders,
                            self.delta)
