"""Local training as one compiled `lax.scan` — the client-side hot loop.

Reference equivalent: the per-client epochs x batches Python loop of
``MyModelTrainer.train`` (fedml_api/distributed/fedavg/MyModelTrainer.py:19-49).
Here the whole local run is a single scan over ``epochs * steps`` so XLA
fuses optimizer updates into the backward pass and the function is
`vmap`-able over a stacked client axis (the cohort engine's trick).

Parity details preserved:
* a *fresh* optimizer per local-training call (the reference constructs the
  optimizer inside ``train`` each round, so Adam moments never persist
  across rounds);
* optional global-norm grad clipping at 1.0 (classification trainer,
  my_model_trainer_classification.py:44);
* batch-mean loss over valid (non-padded) samples only.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.obs import telemetry
from fedml_tpu.trainer.workload import Workload

Pytree = Any


def instrument_train_fn(train_fn, epochs: int = 1, registry=None):
    """Wrap a (typically jit'd) ``train(params, data, rng)`` callable with
    trainer telemetry:

    * ``fedml_trainer_compile_seconds`` — the FIRST call's wall time (jit
      trace + XLA compile + run; the "why is round 0 slow" histogram);
    * ``fedml_trainer_train_seconds`` — every later call's wall time
      (blocked until ready, so async dispatch doesn't hide the work);
    * ``fedml_trainer_examples_total`` — valid (mask=1) examples consumed,
      so examples/sec falls out of the snapshot as
      ``examples_total / train_seconds_sum``.  Pass the trainer's
      ``epochs``: the scan revisits every batch each epoch, so one call
      consumes ``epochs * mask.sum()`` examples.

    The wrapper forwards the underlying jit's ``_cache_size`` probe, so
    the flight recorder's `RecompileSentry` (obs/perf.py) can register
    the instrumented function directly and catch a retracing trainer.
    Under ``--device_obs`` the device observatory's wrapper
    (`obs.device.DeviceRecorder.instrument`, applied via
    ``PerfRecorder.instrument_jit``) composes INSIDE this one — it sees
    raw calls for compile/FLOPs accounting while this wrapper keeps the
    blocked-wall-time trainer histograms; both forward the probe.

    With telemetry disabled this returns ``train_fn`` unchanged — zero
    wrapper, zero cost."""
    reg = registry if registry is not None else telemetry.get_registry()
    if not reg.enabled:
        return train_fn
    import threading

    h_compile = reg.histogram("fedml_trainer_compile_seconds")
    h_train = reg.histogram("fedml_trainer_train_seconds")
    c_examples = reg.counter("fedml_trainer_examples_total")
    # claimed under a lock: concurrent silo threads (the chaos CLI's
    # threaded drive) may both make their first call during the one jit
    # compile — exactly one sample may land in the compile histogram
    state = {"first": True}
    state_lock = threading.Lock()
    epochs = max(int(epochs), 1)

    def instrumented(params, data, rng):
        t0 = time.perf_counter()
        out = train_fn(params, data, rng)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        with state_lock:
            first, state["first"] = state["first"], False
        (h_compile if first else h_train).observe(dt)
        mask = data.get("mask") if isinstance(data, dict) else None
        if mask is not None:
            import numpy as np
            c_examples.inc(epochs * float(np.asarray(mask).sum()))
        return out

    cache_size = getattr(train_fn, "_cache_size", None)
    if cache_size is not None:
        instrumented._cache_size = cache_size
    return instrumented


def make_local_trainer(workload: Workload,
                       optimizer: optax.GradientTransformation,
                       epochs: int, prox_mu: float = 0.0,
                       grad_reduce=None, scan_unroll: int = 1):
    """Returns ``train(params, data, rng) -> (new_params, metrics)``.

    ``data`` leaves are [S, B, ...] (S batches of size B) with ``mask``
    [S, B]; the scan runs epochs*S steps, revisiting the same batches each
    epoch in order (the reference's DataLoader order is fixed per round).

    ``prox_mu`` adds the FedProx proximal gradient mu*(w - w_global) each
    step (w_global = the params this call started from).  NOTE the reference's
    *distributed fedprox* omits this term entirely (SURVEY.md §2.2 caveat —
    its trainer is vanilla SGD); we implement the actual algorithm (Li et al.
    2020), matching the mu usage in the reference's FedNova optimizer
    (fednova.py:133-136).

    ``grad_reduce(grads) -> grads`` runs right after the backward pass,
    before prox/clip/optimizer.  Sequence-parallel training uses it to
    `psum` the per-shard partial gradients over the ``sequence`` mesh axis
    (each shard's backward only sees its own logits' contribution to the
    psum'd loss; parallel/sequence.py).

    ``scan_unroll`` is forwarded to the step `lax.scan` — the default 1
    keeps the compiled program small; bench FLOPs twins pass the full trip
    count so XLA cost analysis (which counts a scan body once) sees every
    step (bench.py _honest_flops)."""
    clip = (optax.clip_by_global_norm(workload.grad_clip_norm)
            if workload.grad_clip_norm is not None else None)
    stateful = workload.stateful

    # Gradients are taken over the TRAINED collection only.  For stateful
    # workloads the non-trained collections (BatchNorm running stats) ride
    # the scan carry beside the optimizer state — never differentiated,
    # never seen by the optimizer — and the updated stats come back through
    # the loss aux ("state", workload.py).
    if stateful:
        def _loss(trained, state, batch, rng):
            return workload.loss_fn({"params": trained, **state}, batch, rng,
                                    True)
    else:
        def _loss(trained, state, batch, rng):
            return workload.loss_fn(trained, batch, rng, True)
    grad_fn = jax.value_and_grad(_loss, has_aux=True)

    def train(params: Pytree, data: Dict[str, jax.Array], rng: jax.Array
              ) -> Tuple[Pytree, Dict[str, jax.Array]]:
        if stateful:
            trained = params["params"]
            state = {k: v for k, v in params.items() if k != "params"}
        else:
            trained, state = params, {}
        init_trained = trained
        opt_state = optimizer.init(trained)
        clip_state = clip.init(trained) if clip is not None else None
        num_steps = jax.tree.leaves(data)[0].shape[0]

        def step(carry, step_idx):
            trained, state, opt_state, rng = carry
            rng, dropout_rng = jax.random.split(rng)
            batch = jax.tree.map(lambda x: x[step_idx % num_steps], data)
            (loss, aux), grads = grad_fn(trained, state, batch, dropout_rng)
            if grad_reduce is not None:
                grads = grad_reduce(grads)
            if prox_mu:
                grads = jax.tree.map(lambda g, p, p0: g + prox_mu * (p - p0),
                                     grads, trained, init_trained)
            if clip is not None:
                grads, _ = clip.update(grads, clip_state)
            updates, new_opt_state = optimizer.update(grads, opt_state, trained)
            new_trained = optax.apply_updates(trained, updates)
            new_state = aux["state"] if stateful else state
            # skip the update entirely for fully-padded batches (grads are 0
            # there anyway for SGD, but Adam's eps would still drift params)
            got_data = jnp.sum(batch["mask"]) > 0
            keep = lambda n, o: jax.tree.map(
                lambda a, b: jnp.where(got_data, a, b), n, o)
            return (keep(new_trained, trained), keep(new_state, state),
                    keep(new_opt_state, opt_state), rng), loss

        total_steps = epochs * num_steps
        (trained, state, _, _), losses = jax.lax.scan(
            step, (trained, state, opt_state, rng), jnp.arange(total_steps),
            unroll=scan_unroll)
        out = {"params": trained, **state} if stateful else trained
        return out, {"train_loss_per_step": losses}

    return train


def make_evaluator(workload: Workload):
    """Returns ``evaluate(params, data) -> summed metrics`` over [S, B, ...]
    batch stacks.  Mirrors ``MyModelTrainer.test`` (MyModelTrainer.py:51-90)
    but runs as one scan; metrics are sums so they aggregate exactly across
    clients/devices with a plain psum."""

    def evaluate(params: Pytree, data: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        def step(carry, batch):
            m = workload.metric_fn(params, batch)
            return jax.tree.map(jnp.add, carry, m), None

        first = jax.tree.map(lambda x: x[0], data)
        init = jax.tree.map(jnp.zeros_like, workload.metric_fn(params, first))
        out, _ = jax.lax.scan(step, init, data)
        return out

    return evaluate
