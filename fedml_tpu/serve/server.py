"""HTTP serving frontend: ThreadingHTTPServer over the micro-batcher.

Stdlib-only (the `telemetry.start_http_server` posture — one daemon
thread per connection, fine for the CPU/silo edge; a TPU pod fronts this
with a real LB).  Endpoints:

* ``POST /predict`` — body ``{"x": [...], "deadline_ms": 50,
  "tier": "interactive"}``; the instance rides the micro-batcher and
  the answer carries the model version that produced it: ``{"y": [...],
  "version": 12}``.  Shed requests answer **429** (deadline/queue-full/
  slo_degraded — retry later), a registry with no model yet answers
  **503**.  The per-request deadline (body field or ``X-Deadline-Ms``
  header) propagates into the batcher, so a request that waited out its
  budget in the queue is shed there instead of dispatched late; the
  admission tier (body field or ``X-Tier``) selects who sheds first
  under load — best_effort gives way before interactive (see
  `batcher.TierGate`).
* ``GET /healthz`` — 200 with ``{"status": "ok", "version": ...,
  "queue_depth": ...}`` once a model is live, 503 before (a load
  balancer keeps the instance out of rotation until the first publish).
  ``GET /healthz?deep=1`` additionally runs the SLO evaluator
  (`obs/perf.SloEvaluator` — round-duration p95, shed rate, torn-frame
  rate, quarantine rate over the telemetry registry): 200 while every
  SLO holds, **503 with the per-SLO verdict** on breach, so an LB can
  rotate out an instance that is up but violating its objectives.
* ``GET /version`` — the live/pinned version and known history (the
  bench asserts this ADVANCES across hot swaps).
* ``GET /metrics`` — Prometheus text from the process telemetry
  registry (the PR 2 exposition, `fedml_serve_*` series included).

Request spans: with tracing enabled each /predict records a
``serve_request`` span, so serving latency lands in the same Perfetto
timeline as the training rounds it interleaves with.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Optional

import numpy as np

from fedml_tpu.obs import telemetry, trace
from fedml_tpu.serve.batcher import (TIERS, BadInstanceError, MicroBatcher,
                                     ShedError)
from fedml_tpu.serve.registry import ModelRegistry

log = logging.getLogger(__name__)


class ServeFrontend:
    """Own the HTTP server lifecycle around a (registry, batcher) pair.

    ``port=0`` binds an ephemeral port (tests); read ``.port`` after
    ``start()``.  ``stop()`` closes the listener, then drains the
    batcher — in-flight requests still answer."""

    def __init__(self, registry: ModelRegistry, batcher: MicroBatcher,
                 port: int = 0, host: str = "127.0.0.1", slo=None,
                 health=None):
        """``slo``: a `fedml_tpu.obs.perf.SloEvaluator`; when set,
        ``/healthz?deep=1`` evaluates it (deep probes without one answer
        the shallow payload plus ``"deep": "unconfigured"``).

        ``health``: a `fedml_tpu.obs.health.HealthAccumulator`; when
        set, deep probes also carry the last round's learning-health
        verdict (`HealthAccumulator.healthz` — round, drift alarms,
        upload accounting) so an operator reading a 503 sees WHICH
        alarm tripped, not just that one did."""
        self.registry = registry
        self.batcher = batcher
        self.slo = slo
        self.health = health
        self._host = host
        self._requested_port = port
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._server is None:
            return self._requested_port
        return self._server.server_address[1]

    def start(self) -> "ServeFrontend":
        if self._server is not None:
            return self
        handler = _make_handler(self.registry, self.batcher, self.slo,
                                self.health)
        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._requested_port), handler)
        self._server.daemon_threads = True
        self.batcher.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"serve-http-{self.port}")
        self._thread.start()
        log.info("serving /predict on %s:%d", self._host, self.port)
        return self

    def stop(self, drain: bool = True) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
        self.batcher.stop(drain=drain)


def _make_handler(registry: ModelRegistry, batcher: MicroBatcher,
                  slo=None, health=None, pool=None,
                  worker_id: Optional[int] = None):
    """``pool``/``worker_id``: set by `ServeWorkerPool` — health
    payloads then carry the answering worker's id and every worker's
    queue depth, so one probe through any worker sees the whole pool."""
    class _Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # keep-alive: the load generator
        # reuses connections, without this every request pays a TCP dial
        disable_nagle_algorithm = True  # headers+body go out as separate
        # small writes; with Nagle on, loopback keep-alive traffic stalls
        # on the peer's ~40ms delayed ACK and p50 jumps 10x

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # split the query off before matching: LB health probes
            # commonly append cache-busting params (/healthz?probe=1);
            # the one query parameter that IS meaningful is healthz's
            # deep=1
            path, _, query = self.path.partition("?")
            path = path.rstrip("/")
            if path == "/healthz":
                m = registry.current()
                if m is None:
                    self._reply(503, {"status": "no_model"})
                    return
                body = {"status": "ok", "version": m.version,
                        "queue_depth": batcher.depth()}
                if pool is not None:
                    body["worker"] = worker_id
                    body["workers"] = pool.workers
                    body["queue_depths"] = pool.queue_depths()
                deep = "deep=1" in query.split("&")
                if deep and slo is None:
                    body["deep"] = "unconfigured"
                elif deep:
                    # query path: read the objectives without ticking the
                    # breach counters — those count once per round (the
                    # runner's evaluate()), not once per LB probe
                    results = slo.evaluate(count_breaches=False)
                    ok = all(v["ok"] for v in results.values())
                    body["slo"] = results
                    if health is not None:
                        # the learning-health verdict beside the SLO
                        # numbers: which drift alarm tripped, last round
                        verdict = health.healthz()
                        if verdict is not None:
                            body["health"] = verdict
                    if not ok:
                        body["status"] = "slo_breach"
                        self._reply(503, body)
                        return
                self._reply(200, body)
            elif path == "/version":
                body = {"version": registry.version,
                        "pinned": registry.pinned,
                        "history": registry.versions()}
                canaries = getattr(registry, "canaries", None)
                if canaries is not None:
                    # release-gated registries: name what is in shadow
                    # evaluation so an operator sees the pending canary
                    body["canaries"] = canaries()
                self._reply(200, body)
            elif path == "/metrics":
                body = telemetry.get_registry().render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": "not_found", "path": self.path})

        def do_POST(self):
            # ALWAYS consume the body first: on HTTP/1.1 keep-alive an
            # unread body would be parsed as the NEXT request line and
            # desync the connection
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError:
                n = 0
            body = self.rfile.read(n)
            if self.path.split("?", 1)[0].rstrip("/") != "/predict":
                self._reply(404, {"error": "not_found", "path": self.path})
                return
            try:
                req = json.loads(body or b"{}")
                x = np.asarray(req["x"], dtype=np.float32)
                deadline_ms = req.get("deadline_ms",
                                      self.headers.get("X-Deadline-Ms"))
                deadline_s = (float(deadline_ms) / 1e3
                              if deadline_ms is not None else None)
                tier = req.get("tier", self.headers.get("X-Tier",
                                                        "interactive"))
                if tier not in TIERS:
                    raise ValueError(f"unknown tier {tier!r}; expected "
                                     f"one of {TIERS}")
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": "bad_request", "detail": str(e)})
                return
            # the context-manager form makes the request span the
            # thread's CURRENT span, so the batcher's submit sees it and
            # the queue/batch/respond spans hang under this request
            tracer = trace.get_tracer()
            ctx = (tracer.span("serve_request", parent=None,
                               version=registry.version)
                   if tracer is not None else trace.NULL_CONTEXT)
            with ctx as span:
                try:
                    result = batcher.predict(x, deadline_s=deadline_s,
                                             tier=tier)
                    t_resp = time.perf_counter()
                    self._reply(200,
                                {"y": np.asarray(result.y).tolist(),
                                 "version": result.version})
                    if tracer is not None:
                        tracer.record_span(
                            "serve_respond",
                            time.perf_counter() - t_resp, parent=span)
                except ShedError as e:
                    self._reply(503 if e.reason == "no_model" else 429,
                                {"error": "shed", "reason": e.reason,
                                 "tier": tier})
                except FuturesTimeout:
                    # the batcher never answered: a server-side stall,
                    # not a client error — 503 so LBs retry/fail over
                    # instead of blaming the request
                    self._reply(503, {"error": "timeout"})
                except BadInstanceError as e:
                    # the one prediction failure that IS the client's
                    # fault
                    self._reply(400, {"error": "bad_instance",
                                      "detail": str(e)})
                except Exception as e:  # noqa: BLE001 — model/params
                    # fault: a 4xx here would stop LBs retrying a
                    # broken instance
                    self._reply(500, {"error": "predict_failed",
                                      "detail": str(e)})

        def log_message(self, *args):  # no per-request stderr spam
            pass

    return _Handler
