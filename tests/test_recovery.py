"""Crash-recoverable federation: a server killed mid-federation restarts
from its RoundCheckpointer and completes (ISSUE 1 acceptance), the
failure detector shrinks the quorum for dead silos and runs the rejoin
protocol, and the straggler timer never outlives the federation.

The reference loses the entire federation on any server fault (no
checkpoint on the FL path, SURVEY.md §5.4; its only exit is MPI.Abort).
"""

import threading

import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (
    FailureDetector, FedAvgClientActor, FedAvgServerActor, MsgType)
from fedml_tpu.comm.chaos import (ChaosPlan, ChaosTransport, LinkChaos,
                                  Partition)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message
from fedml_tpu.utils.checkpoint import RoundCheckpointer


def _params_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


def _add_train_fn(delta):
    def fn(params, client_idx, round_idx):
        import jax
        return jax.tree.map(lambda v: v + delta, params), 10
    return fn


class _Crash(Exception):
    """Stands in for kill -9: raised out of the server's event loop so no
    FINISH, no cleanup — only what the checkpointer already persisted
    survives."""


def _run_fedavg(init, num_rounds, ck=None, crash_after=None):
    """One pump-mode federation (3 silos, deterministic +i training).
    ``crash_after``: raise _Crash out of the round-done hook after that
    round completes — AFTER the checkpoint save, like a process killed
    between rounds."""
    hub = LocalHub()
    completed = []

    def on_done(r, p):
        completed.append(r)
        if crash_after is not None and r >= crash_after:
            raise _Crash()

    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=3,
        client_num_per_round=3, num_rounds=num_rounds,
        on_round_done=on_done, checkpointer=ck)
    clients = [FedAvgClientActor(i, hub.transport(i), _add_train_fn(float(i)))
               for i in (1, 2, 3)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    if crash_after is not None:
        with pytest.raises(_Crash):
            server.start()
            hub.pump()
    else:
        server.start()
        hub.pump()
    return server, completed


def test_fedavg_server_crash_and_resume_completes(tmp_path):
    """Kill the server after round 2 of 5; a FRESH server restarted on
    the same checkpoint directory resumes at round 3, completes rounds
    3-4, and lands on exactly the params of an uninterrupted run."""
    init = _params_tree(3)
    straight, comp = _run_fedavg(init, 5)
    assert comp == [0, 1, 2, 3, 4]

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    crashed, comp1 = _run_fedavg(init, 5, ck=ck, crash_after=2)
    assert comp1 == [0, 1, 2]
    assert ck.latest_round() == 2

    resumed, comp2 = _run_fedavg(init, 5, ck=RoundCheckpointer(
        str(tmp_path / "ck")))
    assert comp2 == [3, 4], "resume must continue, not restart"
    assert resumed.round_idx == 5
    np.testing.assert_allclose(
        np.asarray(resumed.params["dense"]["kernel"]),
        np.asarray(straight.params["dense"]["kernel"]), rtol=1e-6)


def test_fedavg_resume_of_completed_run_just_finishes(tmp_path):
    """Restarting a server whose checkpoint already holds the final round
    dismisses the silos immediately instead of re-running anything."""
    init = _params_tree(4)
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    done, comp = _run_fedavg(init, 3, ck=ck)
    assert comp == [0, 1, 2]

    again, comp2 = _run_fedavg(init, 3, ck=RoundCheckpointer(
        str(tmp_path / "ck")))
    assert comp2 == []
    assert again.round_idx == 3
    np.testing.assert_allclose(
        np.asarray(again.params["dense"]["kernel"]),
        np.asarray(done.params["dense"]["kernel"]), rtol=1e-6)


def test_async_server_crash_and_resume_completes(tmp_path):
    """FedBuff server killed after version 2 of 5 resumes from its
    checkpoint and closes the remaining versions."""
    from fedml_tpu.algorithms.async_fl import (AsyncFedServerActor,
                                               delta_encoder)

    init = _params_tree(5)

    def run(ck=None, crash_after=None):
        hub = LocalHub()
        versions_seen = []

        def on_version(v, p):
            versions_seen.append(v)
            if crash_after is not None and v >= crash_after:
                raise _Crash()

        server = AsyncFedServerActor(
            hub.transport(0), init, client_num_in_total=6, n_silos=3,
            num_versions=5, aggregation_goal=3, seed=0,
            on_version=on_version, checkpointer=ck)
        clients = [FedAvgClientActor(i, hub.transport(i),
                                     _add_train_fn(float(i)),
                                     encode_upload=delta_encoder)
                   for i in (1, 2, 3)]
        server.register_handlers()
        for c in clients:
            c.register_handlers()
        if crash_after is not None:
            with pytest.raises(_Crash):
                server.start()
                hub.pump()
        else:
            server.start()
            hub.pump()
        return server, versions_seen

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    crashed, seen1 = run(ck=ck, crash_after=2)
    assert seen1 == [1, 2]
    assert ck.latest_round() == 1  # step = version - 1

    resumed, seen2 = run(ck=RoundCheckpointer(str(tmp_path / "ck")))
    assert seen2 == [3, 4, 5], "resume must continue from version 2"
    assert resumed.version == 5
    k = np.asarray(resumed.params["dense"]["kernel"])
    assert np.isfinite(k).all()
    assert float(np.abs(k - init["dense"]["kernel"]).max()) > 0.1


def test_async_duplicate_upload_rejected_even_after_flush():
    """At-most-once guard: a duplicated frame whose first copy was
    already aggregated (buffer flushed) must STILL be rejected — the
    consumed set outlives the buffer."""
    from fedml_tpu.algorithms.async_fl import AsyncFedServerActor

    hub = LocalHub()
    init = _params_tree(10)
    server = AsyncFedServerActor(
        hub.transport(0), init, client_num_in_total=4, n_silos=2,
        num_versions=3, aggregation_goal=1, seed=0)
    hub.transport(1), hub.transport(2)  # endpoints for tasking sends
    server.register_handlers()
    server.start()
    hub.pump()

    def upload():
        return (Message(MsgType.C2S_MODEL, 1, 0)
                .add(Message.ARG_MODEL_PARAMS,
                     {"dense": {"kernel": np.ones((4, 3), np.float32),
                                "bias": np.ones(3, np.float32)}})
                .add(Message.ARG_NUM_SAMPLES, 10)
                .add(Message.ARG_ROUND, 0))

    hub.route(upload())
    hub.pump()
    assert server.version == 1  # goal=1: first copy applied immediately
    hub.route(upload())  # wire duplicate of the SAME (silo, base_version)
    hub.pump()
    assert server.version == 1, "duplicate applied twice after flush"
    assert len(server.staleness_seen) == 1


def _ef_federation(init, num_rounds, ck=None, crash_after=None,
                   restore_ef=True):
    """Cross-silo federation with topk wire compression + deferred error
    feedback, mirroring the run_cross_silo wiring: one process-shared
    `ErrorFeedback` (the local backend), encode applies+records, the
    server ack (ARG_ACCEPTED on the next sync) resolves, and — when
    ``restore_ef`` — the EF state rides the server checkpoint via the
    extra_state hook."""
    import jax

    from fedml_tpu.comm.compress import (ErrorFeedback, compress_update,
                                         decompress_update)

    ef = ErrorFeedback()
    n_silos = 3
    assert init["dense"]["kernel"].size >= 16, \
        "leaves under 16 entries ride compress_update's dense (lossless) " \
        "path — the EF residual would be identically zero"

    def make_train_fn(silo):
        def fn(params, client_idx, round_idx):
            # deterministic per (silo, round) so an uninterrupted and a
            # resumed run see IDENTICAL deltas; varied magnitudes so topk
            # keeps different coordinates each round (residuals matter)
            rs = np.random.RandomState(silo * 1000 + round_idx)
            new = jax.tree.map(
                lambda v: v + rs.randn(*v.shape).astype(v.dtype), params)
            return new, 10
        return fn

    def make_encode(silo):
        def enc(new_params, global_params):
            delta = jax.tree.map(np.subtract, new_params, global_params)
            delta = ef.apply(silo, delta)
            payload = compress_update(delta, "topk", topk_frac=0.25)
            ef.record(silo, delta, decompress_update(payload, delta))
            return payload
        return enc

    def decode(payload, global_params):
        host = jax.tree.map(np.asarray, global_params)
        return jax.tree.map(np.add, host,
                            decompress_update(payload, host))

    extra = None
    if restore_ef:
        template = jax.tree.map(lambda v: np.zeros_like(np.asarray(v)),
                                init)
        extra = (lambda: ef.state_dict(range(1, n_silos + 1), template),
                 ef.load_state_dict)

    hub = LocalHub()
    completed = []

    def on_done(r, p):
        completed.append(r)
        if crash_after is not None and r >= crash_after:
            raise _Crash()

    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=n_silos,
        client_num_per_round=n_silos, num_rounds=num_rounds,
        on_round_done=on_done, decode_upload=decode, checkpointer=ck,
        extra_state=extra)
    clients = [
        FedAvgClientActor(i, hub.transport(i), make_train_fn(i),
                          encode_upload=make_encode(i),
                          on_accepted=lambda acc, i=i: ef.resolve(i, acc))
        for i in range(1, n_silos + 1)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    if crash_after is not None:
        with pytest.raises(_Crash):
            server.start()
            hub.pump()
    else:
        server.start()
        hub.pump()
    return server, completed


def test_error_feedback_resume_is_bit_identical(tmp_path):
    """ISSUE 3 satellite/acceptance: EF residuals are cross-round state —
    a checkpoint without them makes a resumed --error_feedback run
    diverge.  With the extra_state hook, kill-after-round-2 + resume
    lands on EXACTLY (bit-identical, not allclose) the uninterrupted
    run's params; without it, the divergence the bug caused is visible."""
    rng = np.random.RandomState(11)
    init = {"dense": {"kernel": rng.randn(8, 6).astype(np.float32),
                      "bias": rng.randn(6).astype(np.float32)}}
    straight, comp = _ef_federation(init, 5)
    assert comp == [0, 1, 2, 3, 4]

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    _, comp1 = _ef_federation(init, 5, ck=ck, crash_after=2)
    assert comp1 == [0, 1, 2]

    resumed, comp2 = _ef_federation(
        init, 5, ck=RoundCheckpointer(str(tmp_path / "ck")))
    assert comp2 == [3, 4]
    for key in ("kernel", "bias"):
        np.testing.assert_array_equal(
            np.asarray(resumed.params["dense"][key]),
            np.asarray(straight.params["dense"][key]),
            err_msg="EF resume is not bit-identical")

    # the regression the fix closes: checkpoints carrying only the old
    # (params, round, accepted) tuple — no EF state — silently diverge
    ck2 = RoundCheckpointer(str(tmp_path / "ck2"), save_every=1)
    _ef_federation(init, 5, ck=ck2, crash_after=2, restore_ef=False)
    diverged, _ = _ef_federation(
        init, 5, ck=RoundCheckpointer(str(tmp_path / "ck2")),
        restore_ef=False)
    assert np.abs(np.asarray(diverged.params["dense"]["kernel"])
                  - np.asarray(straight.params["dense"]["kernel"])).max() \
        > 0, "EF state did not matter — the test lost its teeth"

    # schema drift must not crash: a pre-EF checkpoint (no "extra" leaf)
    # resumed with EF configured falls back to an untemplated restore
    # and completes (resuming beats crashing)
    ck3 = RoundCheckpointer(str(tmp_path / "ck3"), save_every=1)
    _ef_federation(init, 5, ck=ck3, crash_after=2, restore_ef=False)
    upgraded, comp3 = _ef_federation(
        init, 5, ck=RoundCheckpointer(str(tmp_path / "ck3")))
    assert comp3 == [3, 4]
    assert np.isfinite(
        np.asarray(upgraded.params["dense"]["kernel"])).all()


def _route_timeout(hub, round_idx):
    hub.route(Message(MsgType.ROUND_TIMEOUT, 0, 0)
              .add(Message.ARG_ROUND, round_idx))


def test_failure_detector_shrinks_quorum_and_rejoins():
    """Deterministic (fake-clock, pump-mode) walk through the detector
    lifecycle: a silo dies → first dropped by timeout, then declared DEAD
    and excluded at broadcast (the round closes WITHOUT a timeout), then
    rejoins via a heartbeat and is re-included the next round."""
    t = [0.0]
    detector = FailureDetector(suspect_after_s=0.5, dead_after_s=1.0,
                               clock=lambda: t[0])
    hub = LocalHub()
    init = _params_tree(6)
    completed = []
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=3,
        client_num_per_round=3, num_rounds=5,
        on_round_done=lambda r, p: completed.append(r),
        straggler_policy="drop", round_timeout_s=30.0, min_silo_frac=0.3,
        failure_detector=detector)

    # silo 3 "dies" after round 0 (everything it sends for rounds >= 1 is
    # cut); silo 2 goes quiet from round 3 to keep later rounds open
    t3 = ChaosTransport(hub.transport(3), ChaosPlan(links={
        (3, 0): LinkChaos(partition=Partition(after_round=1))}))
    t2 = ChaosTransport(hub.transport(2), ChaosPlan(links={
        (2, 0): LinkChaos(partition=Partition(after_round=3))}))
    trained_rounds = {1: [], 2: [], 3: []}

    def spy_train(silo):
        inner = _add_train_fn(float(silo))

        def fn(params, client_idx, round_idx):
            trained_rounds[silo].append(round_idx)
            return inner(params, client_idx, round_idx)
        return fn

    clients = [FedAvgClientActor(1, hub.transport(1), spy_train(1)),
               FedAvgClientActor(2, t2, spy_train(2)),
               FedAvgClientActor(3, t3, spy_train(3))]
    server.register_handlers()
    for c in clients:
        c.register_handlers()

    server.start()
    hub.pump()
    # round 0 closed with everyone; round 1 is open: silo 3's upload was cut
    assert completed == [0]
    assert sorted(server._received) == [1, 2]

    # silos 1 and 2 keep beating; silo 3 has been silent past dead_after_s
    t[0] = 1.5
    hub.route(Message(MsgType.C2S_HEARTBEAT, 1, 0))
    hub.route(Message(MsgType.C2S_HEARTBEAT, 2, 0))
    _route_timeout(hub, 1)
    hub.pump()
    # the timeout dropped silo 3 from round 1; at the round-2 broadcast
    # the detector declared it DEAD and EXCLUDED it, so round 2 closed on
    # silos {1,2} alone — no timeout injection was needed (the quorum
    # shrank instead of re-paying the timeout).  Round 3 is open because
    # silo 2 went quiet.
    assert completed == [0, 1, 2]
    assert server.dropped_silos[1] == [3]
    assert server.dropped_silos[2] == [3]
    assert detector.state(3) == FailureDetector.DEAD
    assert server.round_idx == 3

    # silo 3 comes back: its heartbeat is a REJOIN — the server must ship
    # it the current global + round index immediately
    t[0] = 2.0
    hub.route(Message(MsgType.C2S_HEARTBEAT, 3, 0))
    hub.pump()
    assert detector.state(3) == FailureDetector.ALIVE
    assert trained_rounds[3][-1] == 3, \
        "rejoined silo never received the current round's sync"
    # its round-3 upload was cut by the partition anyway; close round 3 by
    # timeout (drops silo 2, whose uploads are now cut too)
    _route_timeout(hub, 3)
    hub.pump()
    assert completed == [0, 1, 2, 3]
    assert server.dropped_silos[3] == [2, 3]
    # round 4: the rejoined silo is back in the EXPECTED set
    assert 3 in server._expected
    _route_timeout(hub, 4)
    hub.pump()
    assert completed == [0, 1, 2, 3, 4]
    assert server.round_idx == 5


def test_straggler_timer_never_outlives_federation():
    """Satellite: finish()/abort joins the straggler timer thread — after
    the federation ends no Timer may still be pending (leaked-thread
    warning under -W error)."""
    hub = LocalHub()
    init = _params_tree(7)
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=2,
        client_num_per_round=2, num_rounds=2,
        straggler_policy="drop", round_timeout_s=30.0, min_silo_frac=0.5)
    clients = [FedAvgClientActor(i, hub.transport(i), _add_train_fn(1.0))
               for i in (1, 2)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    server.start()
    assert server._timer.pending  # armed during the open round
    hub.pump()
    assert server.round_idx == 2
    assert not server._timer.pending
    live_timers = [th for th in threading.enumerate()
                   if isinstance(th, threading.Timer)]
    assert not live_timers, f"leaked straggler timers: {live_timers}"


def test_abort_path_cancels_timer_and_stops_transport():
    hub = LocalHub()
    server = FedAvgServerActor(
        hub.transport(0), _params_tree(8), client_num_in_total=2,
        client_num_per_round=2, num_rounds=3,
        straggler_policy="abort", round_timeout_s=30.0)
    hub.transport(1), hub.transport(2)  # endpoints exist, nobody listens
    server.register_handlers()
    server.start()
    # nobody answers; fire the timeout by hand (pump mode)
    _route_timeout(hub, 0)
    hub.pump()
    assert server.aborted
    assert not server._timer.pending and server._finished
    assert not [th for th in threading.enumerate()
                if isinstance(th, threading.Timer)]
    server.finish()  # double-finish tolerated (stop() is idempotent)


@pytest.mark.slow
def test_threaded_chaos_crash_recovery_end_to_end(tmp_path):
    """The full acceptance story in one run: threaded federation behind
    chaotic links (drops/delays/dups + one death partition), drop-policy
    server with checkpointing crashes after round 2, a restarted server
    resumes from the checkpoint and the federation completes."""
    init = _params_tree(9)
    n_silos, n_rounds = 3, 6
    ck_dir = str(tmp_path / "ck")

    def build(hub, ck, crash_after=None):
        completed = []

        def on_done(r, p):
            completed.append(r)
            if crash_after is not None and r >= crash_after:
                raise _Crash()

        server = FedAvgServerActor(
            hub.transport(0), init, client_num_in_total=n_silos,
            client_num_per_round=n_silos, num_rounds=n_rounds,
            on_round_done=on_done, straggler_policy="drop",
            round_timeout_s=0.4, min_silo_frac=0.3, checkpointer=ck)
        transports = {1: hub.transport(1)}
        for i in (2, 3):
            transports[i] = ChaosTransport(hub.transport(i), ChaosPlan(
                seed=i, links={(i, 0): LinkChaos(
                    drop_prob=0.1, delay_prob=0.3, max_delay_s=0.1,
                    dup_prob=0.1,
                    partition=(Partition(after_round=4) if i == 3
                               else None))},
                immune_types=(MsgType.S2C_FINISH,)))
        actors = [FedAvgClientActor(i, transports[i],
                                    _add_train_fn(float(i)))
                  for i in range(1, n_silos + 1)]
        return server, actors, completed

    def run_threaded(server, actors, expect_crash):
        threads = [threading.Thread(target=a.run, daemon=True)
                   for a in actors]
        for th in threads:
            th.start()
        server.register_handlers()
        outcome = {}

        def _serve():
            try:
                server.start()
                server.transport.run()
                outcome["done"] = True
            except _Crash:
                outcome["crashed"] = True

        st = threading.Thread(target=_serve, daemon=True)
        st.start()
        st.join(timeout=60)
        assert not st.is_alive(), "server wedged"
        if expect_crash:
            assert outcome.get("crashed"), "crash hook never fired"

    ck = RoundCheckpointer(ck_dir, save_every=1)
    server1, actors1, completed1 = build(LocalHub(), ck, crash_after=2)
    run_threaded(server1, actors1, expect_crash=True)
    assert completed1[-1] >= 2 and ck.latest_round() >= 2

    hub2 = LocalHub()
    server2, actors2, completed2 = build(hub2, RoundCheckpointer(ck_dir))
    run_threaded(server2, actors2, expect_crash=False)
    assert server2.round_idx == n_rounds
    assert completed2[0] == ck.latest_round() + 1 or not completed2
    assert np.isfinite(
        np.asarray(server2.params["dense"]["kernel"])).all()
