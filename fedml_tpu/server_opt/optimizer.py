"""The server-optimizer seam (ISSUE 18) — a pluggable step over the
streaming AND sharded finalize.

The live spine's ``StreamingAggregator.finalize()`` produces the
cohort's weighted-mean model; today the server actors assign it to the
global wholesale.  The seam reinterprets that output as a
pseudo-gradient (the FedOpt contract, Reddi et al. 2020,
FedOptAggregator.py:108-122)

    Δ = w_global − finalize(round)

and lets a ``ServerOptimizer`` apply it:

    plain     — the finalized tree verbatim, ZERO arithmetic.  Not
                ``w − 1.0·Δ``: a float round-trip through the delta is
                not bit-identical (``a − (a − b) ≠ b`` in f32), and the
                plain mode's whole job is the bit-identity parity pin
                against the pre-seam finalize.
    momentum  — optax-sgd trace: ``t ← Δ + m·t;  w ← w − lr·t``.
    adam      — optax-adam moments (b1/b2/eps, eps_root=0, count
                incremented before bias correction) on Δ.
    fedac     — FedAC (Yuan & Ma 2020, arXiv:2006.08950) Algorithm 1 at
                server granularity: the global IS the output iterate
                x^ag, the coupled x sequence is optimizer state, and the
                round's pseudo-gradient stands in for the local
                gradient:

                    x^md  = x/β + (1 − 1/β)·x^ag
                    x^ag' = x^md − lr·Δ
                    x'    = (1 − 1/α)·x + x^md/α − γ·Δ

                ``(α=1, β=1, γ=lr)`` collapses the recurrence onto the
                plain SGD step — the parity hook against
                ``algorithms/fedac.py``'s local form.  ``fedac_mu > 0``
                derives (γ, α, β) via the same Lemma-1 coupling
                (``fedac.fedac_coupling``).

Contracts the seam inherits from the spine it sits on:

* O(model) state, eagerly zero-initialized at construction so the
  checkpoint/extra-state template has fixed shapes from round 0 (the
  orbax ``restore(like=)`` requirement).
* One jitted step, registered with the RecompileSentry under
  ``server_opt[<name>]`` — the jit-once pin holds across rounds.
* ``state_dict``/``load_state_dict`` ride the PR 12 journal and round
  checkpoints; restore is bit-exact and REFUSES a snapshot written
  under a different optimizer or a different shard plan (the PR 14
  mode-mismatch refusal, mirrored — ``ServerOptMismatchError``).
* Under the PR 14 sharded spine the step always sees the FULL joined
  tree (the sharded finalize joins host-side before the seam); only
  the serialized state lays out shard-major along the leaf→shard plan,
  so per-shard checkpoint shards stay O(model/S).
"""

from __future__ import annotations

import json
import time
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

SERVER_OPT_NAMES = ("plain", "momentum", "adam", "fedac")


class ServerOptConfigError(ValueError):
    """A --server_opt / --adaptive flag combination that would silently
    mislabel a run — refused at config time with the reason."""


class ServerOptMismatchError(ValueError):
    """A checkpoint/journal snapshot written under a DIFFERENT server
    optimizer (or shard plan) than the one restoring it — restoring
    would continue a foreign trajectory; refused loudly instead (the
    PR 14 shard-fingerprint refusal, mirrored)."""


def _tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(lambda x, y: x - y.astype(x.dtype), a, b)


def _global_norm(tree: Pytree):
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in jax.tree.leaves(tree)))


class ServerOptimizer:
    """One pseudo-gradient step per round over the finalize seam.

    ``apply(params, finalized, round_idx)`` — the sync seam: the
    pseudo-gradient ``Δ = params − finalized`` forms INSIDE the jitted
    step.  ``apply_delta(params, delta, round_idx)`` — the async seam:
    the caller supplies Δ directly (async_fl's staleness discount
    scales the GRADIENT, so stale buffers move the momentum less).

    Both mutate ``self.state`` (the O(model) slots) and return the new
    global.  ``plain`` short-circuits ``apply`` to the finalized tree
    itself and ``apply_delta`` to the exact SGD step — no moments, no
    state.
    """

    def __init__(self, name: str, template: Pytree, *,
                 lr: float = 1.0, momentum: float = 0.9,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8,
                 fedac_mu: float = 0.0, fedac_gamma: float = 0.0,
                 fedac_alpha: float = 1.0, fedac_beta: float = 1.0,
                 local_steps: int = 1,
                 plan=None, sentry=None, device=None):
        if name not in SERVER_OPT_NAMES:
            raise ServerOptConfigError(
                f"unknown --server_opt {name!r}; "
                f"have {list(SERVER_OPT_NAMES)}")
        self.name = name
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.beta1, self.beta2, self.eps = float(beta1), float(beta2), \
            float(eps)
        if name == "fedac":
            if fedac_mu > 0.0:
                from fedml_tpu.algorithms.fedac import fedac_coupling
                gamma, alpha, beta = fedac_coupling(
                    self.lr, fedac_mu, max(int(local_steps), 1))
            else:
                gamma = fedac_gamma or self.lr
                alpha, beta = fedac_alpha, fedac_beta
            if alpha < 1.0 or beta < 1.0:
                raise ServerOptConfigError(
                    f"--server_opt fedac needs alpha >= 1 and beta >= 1 "
                    f"(got alpha={alpha:g}, beta={beta:g}); with "
                    f"--fedac_mu the coupling needs mu <= 1/lr")
            self.coupling = {"gamma": float(gamma), "alpha": float(alpha),
                             "beta": float(beta)}
        else:
            self.coupling = None
        self.plan = plan
        self._treedef = jax.tree.structure(template)
        self._template_leaves = [np.asarray(l)
                                 for l in jax.tree.leaves(template)]
        # the hyperparameter fingerprint a restore must match: same
        # optimizer NAME and same step rule — a momentum trace restored
        # under a different decay is a silent trajectory fork
        self.fp = zlib.crc32(json.dumps(
            {"name": name, "lr": self.lr, "momentum": self.momentum,
             "beta1": self.beta1, "beta2": self.beta2, "eps": self.eps,
             "coupling": self.coupling}, sort_keys=True).encode())
        self.step_count = 0
        self.state = self._init_state(template)
        self._build_steps()

        from fedml_tpu.obs import telemetry as _tel
        reg = _tel.get_registry()
        self._m_steps = reg.counter("fedml_srvopt_steps_total")
        self._m_delta = reg.gauge("fedml_srvopt_delta_norm_value")
        self._m_update = reg.gauge("fedml_srvopt_update_norm_value")
        self._m_secs = reg.histogram(
            "fedml_srvopt_step_seconds",
            buckets=(.0005, .002, .01, .05, .2, 1., 5.))
        if device is not None and self._step_jit is not None:
            self._step_jit = device.instrument(
                f"srvopt_step[{name}]", self._step_jit, sentry=sentry,
                sentry_name=f"server_opt[{name}]")
            self._delta_step_jit = device.instrument(
                f"srvopt_delta_step[{name}]", self._delta_step_jit)
        if sentry is not None:
            sentry.register(f"server_opt[{name}]", self)

    # -- state ----------------------------------------------------------------

    def _init_state(self, template: Pytree) -> dict:
        z = lambda: jax.tree.map(  # noqa: E731
            lambda l: jnp.zeros(np.shape(l), jnp.asarray(l).dtype),
            template)
        if self.name == "plain":
            return {}
        if self.name == "momentum":
            return {"trace": z()}
        if self.name == "adam":
            return {"mu": z(), "nu": z(),
                    "count": jnp.zeros((), jnp.int32)}
        # fedac: the coupled x sequence starts AT the global (x^0 =
        # x^ag,0 — fedac.py's fresh-run convention)
        return {"x": jax.tree.map(
            lambda l: jnp.asarray(l), template)}

    # -- the jitted step ------------------------------------------------------

    def _build_steps(self):
        name, lr = self.name, self.lr
        if name == "plain":
            self._step_jit = None

            @jax.jit
            def plain_delta(w, delta):
                new = jax.tree.map(lambda wi, di: wi - lr
                                   * di.astype(wi.dtype), w, delta)
                return new, _global_norm(delta), _global_norm(
                    _tree_sub(new, w))
            self._delta_step_jit = plain_delta
            return

        if name == "momentum":
            m = self.momentum

            def step(w, delta, state):
                t = jax.tree.map(lambda d, ti: d.astype(ti.dtype)
                                 + m * ti, delta, state["trace"])
                new = jax.tree.map(lambda wi, ti: wi
                                   - lr * ti.astype(wi.dtype), w, t)
                return new, {"trace": t}
        elif name == "adam":
            b1, b2, eps = self.beta1, self.beta2, self.eps

            def step(w, delta, state):
                count = state["count"] + 1
                mu = jax.tree.map(
                    lambda mi, d: b1 * mi + (1.0 - b1)
                    * d.astype(mi.dtype), state["mu"], delta)
                nu = jax.tree.map(
                    lambda ni, d: b2 * ni + (1.0 - b2)
                    * jnp.square(d.astype(ni.dtype)), state["nu"], delta)
                c = count.astype(jnp.float32)
                bc1 = 1.0 - jnp.power(jnp.float32(b1), c)
                bc2 = 1.0 - jnp.power(jnp.float32(b2), c)
                new = jax.tree.map(
                    lambda wi, mi, ni: wi - (lr * (mi / bc1)
                                             / (jnp.sqrt(ni / bc2) + eps)
                                             ).astype(wi.dtype),
                    w, mu, nu)
                return new, {"mu": mu, "nu": nu, "count": count}
        else:  # fedac
            gamma = self.coupling["gamma"]
            alpha, beta = self.coupling["alpha"], self.coupling["beta"]

            def step(w_ag, delta, state):
                x = state["x"]
                x_md = jax.tree.map(
                    lambda xi, ai: xi / beta + (1.0 - 1.0 / beta) * ai,
                    x, w_ag)
                new_ag = jax.tree.map(
                    lambda m_, d: m_ - lr * d.astype(m_.dtype),
                    x_md, delta)
                new_x = jax.tree.map(
                    lambda xi, m_, d: (1.0 - 1.0 / alpha) * xi
                    + m_ / alpha - gamma * d.astype(xi.dtype),
                    x, x_md, delta)
                return new_ag, {"x": new_x}

        @jax.jit
        def from_finalized(w, finalized, state):
            delta = _tree_sub(w, finalized)
            new, state = step(w, delta, state)
            return new, state, _global_norm(delta), _global_norm(
                _tree_sub(new, w))

        @jax.jit
        def from_delta(w, delta, state):
            new, state = step(w, delta, state)
            return new, state, _global_norm(delta), _global_norm(
                _tree_sub(new, w))

        self._step_jit = from_finalized
        self._delta_step_jit = from_delta

    # -- recompile-sentry probe (PerfRecorder.register_jit contract) ----------

    def _cache_size(self) -> int:
        n = 0
        for fn in (self._step_jit, self._delta_step_jit):
            if fn is not None:
                n += int(fn._cache_size())
        return n

    # -- the seam -------------------------------------------------------------

    def apply(self, params: Pytree, finalized: Pytree,
              round_idx: int = 0) -> Pytree:
        """The sync finalize seam.  ``plain`` returns the finalized tree
        ITSELF (bit-identity — no delta round-trip)."""
        self.step_count += 1
        self._m_steps.inc()
        if self.name == "plain":
            return finalized
        t0 = time.perf_counter()
        new, self.state, dn, un = self._step_jit(params, finalized,
                                                 self.state)
        self._m_delta.set(float(dn))
        self._m_update.set(float(un))
        self._m_secs.observe(time.perf_counter() - t0)
        return new

    def apply_delta(self, params: Pytree, delta: Pytree,
                    round_idx: int = 0) -> Pytree:
        """The async seam: Δ supplied by the caller (already
        staleness-discounted).  ``plain`` is the exact SGD step
        ``w − lr·Δ``."""
        self.step_count += 1
        self._m_steps.inc()
        t0 = time.perf_counter()
        if self.name == "plain":
            new, dn, un = self._delta_step_jit(params, delta)
        else:
            new, self.state, dn, un = self._delta_step_jit(
                params, delta, self.state)
        self._m_delta.set(float(dn))
        self._m_update.set(float(un))
        self._m_secs.observe(time.perf_counter() - t0)
        return new

    # -- checkpoint / journal (bit-exact, refusal-guarded) --------------------

    def _tree_slots(self):
        return [k for k in ("trace", "mu", "nu", "x") if k in self.state]

    def _split_flat(self, leaves):
        """Ordered leaf list → one flat host list laid out shard-major
        in sorted-slice-key order along the plan (the
        ShardedStreamingAggregator.state_dict layout, so per-shard
        checkpoint shards stay O(model/S))."""
        flat = []
        for body in self.plan.split_leaves(leaves):
            (_, d), = body.items()
            for k in sorted(d):
                flat.append(np.asarray(d[k]))
        return flat

    def _join_flat(self, flat):
        proto = self.plan.split_leaves(self._template_leaves)
        it = iter(flat)
        for body in proto:
            (_, d), = body.items()
            for k in sorted(d):
                d[k] = np.asarray(next(it))
        return self.plan.join_slices(proto)

    def state_dict(self) -> dict:
        """Host snapshot: every slot's leaves as numpy in their own
        dtype (bit-exact round trip), stamped with the optimizer
        identity/fingerprint (and the shard-plan fingerprint when
        sharded).  All leaves are numpy arrays (scalars travel as 0-d
        arrays — orbax rejects bare numpy scalars) — never strings —
        so the dict rides orbax checkpoints unmodified (the optimizer
        NAME travels as its index into ``SERVER_OPT_NAMES``)."""
        out = {"opt_id": np.asarray(SERVER_OPT_NAMES.index(self.name),
                                    np.int32),
               "fp": np.asarray(self.fp, np.int64),
               "step": np.asarray(self.step_count, np.int64)}
        if self.plan is not None:
            out["shard_fp"] = np.asarray(self.plan.fingerprint(),
                                         np.int64)
        for slot in self._tree_slots():
            leaves = [np.asarray(l)
                      for l in jax.tree.leaves(self.state[slot])]
            out[slot] = (self._split_flat(leaves)
                         if self.plan is not None else leaves)
        if "count" in self.state:
            out["count"] = np.asarray(self.state["count"], np.int32)
        return out

    def load_state_dict(self, state: dict) -> None:
        opt_id = int(np.asarray(state.get("opt_id", -1)))
        got = (SERVER_OPT_NAMES[opt_id]
               if 0 <= opt_id < len(SERVER_OPT_NAMES) else f"#{opt_id}")
        if got != self.name:
            raise ServerOptMismatchError(
                f"checkpoint was written under --server_opt {got!r} but "
                f"this run is --server_opt {self.name!r}; restoring its "
                f"optimizer state would continue a foreign trajectory — "
                f"restart from scratch or rerun with --server_opt {got}")
        if int(np.asarray(state.get("fp", -1))) != int(self.fp):
            raise ServerOptMismatchError(
                f"server_opt[{self.name}] checkpoint hyperparameters "
                f"differ from this run's (fingerprint "
                f"{state.get('fp')!r} != {self.fp}) — the restored "
                f"moments would step under a different rule")
        snap_fp = state.get("shard_fp")
        if self.plan is not None:
            if snap_fp is None:
                raise ServerOptMismatchError(
                    "server_opt snapshot carries no shard-plan "
                    "fingerprint (it was written by the replicated "
                    "path); the sharded spine refuses to restore it")
            if int(snap_fp) != int(self.plan.fingerprint()):
                raise ServerOptMismatchError(
                    "server_opt snapshot was written under a DIFFERENT "
                    "shard plan (fingerprint mismatch — --model_shards "
                    "or the model changed); restoring it would place "
                    "optimizer state into the wrong slots")
        elif snap_fp is not None:
            raise ServerOptMismatchError(
                "server_opt snapshot is laid out along a shard plan but "
                "this run is replicated; refusing the restore")
        for slot in self._tree_slots():
            leaves = state[slot]
            if self.plan is not None:
                leaves = self._join_flat(leaves)
            self.state[slot] = jax.tree.unflatten(
                self._treedef, [jnp.asarray(np.asarray(l))
                                for l in leaves])
        if "count" in self.state:
            self.state["count"] = jnp.asarray(int(np.asarray(
                state["count"])), jnp.int32)
        self.step_count = int(np.asarray(state.get("step", 0)))

    # extra-state template for orbax restore(like=): fixed shapes,
    # zero-filled, same layout as state_dict
    def state_template(self) -> dict:
        out = {"opt_id": np.asarray(SERVER_OPT_NAMES.index(self.name),
                                    np.int32),
               "fp": np.asarray(self.fp, np.int64),
               "step": np.asarray(0, np.int64)}
        if self.plan is not None:
            out["shard_fp"] = np.asarray(self.plan.fingerprint(),
                                         np.int64)
        zeros = [np.zeros(l.shape, l.dtype) for l in self._template_leaves]
        for slot in self._tree_slots():
            out[slot] = (self._split_flat(zeros)
                         if self.plan is not None else list(zeros))
        if "count" in self.state:
            out["count"] = np.asarray(0, np.int32)
        return out
