from fedml_tpu.experiments.config import ExperimentConfig, build_parser
from fedml_tpu.experiments.main import main, RUNNERS

__all__ = ["ExperimentConfig", "build_parser", "main", "RUNNERS"]
