"""Transport SPI — the seam between algorithm choreography and the wire.

Reference equivalent: ``BaseCommunicationManager``
(fedml_core/distributed/communication/base_com_manager.py:7-27) and
``Observer`` (observer.py:4-8).  Same contract, two differences:

- `run()` is explicit and blocking (the reference hides a 0.3 s polling loop
  inside ``handle_receive_message``, mpi/com_manager.py:71-81; our transports
  block on queues/sockets — no idle polling).
- transports declare a ``flavor``: ``"p2p"`` for host-edge message passing
  (local / tcp-grpc / mqtt) — on-pod "transport" does not exist as an object
  at all, it is `lax.psum` inside the jit program.
"""

from __future__ import annotations

import abc
from typing import Protocol, runtime_checkable

from fedml_tpu.comm.message import Message
from fedml_tpu.obs import telemetry


@runtime_checkable
class Observer(Protocol):
    def receive_message(self, msg_type, msg: Message) -> None: ...


class Transport(abc.ABC):
    """Abstract p2p transport: deliver Messages between numbered nodes.

    Telemetry: every concrete transport inherits per-link send/recv
    counters (``fedml_comm_{send,recv,send_bytes}_total``, labeled
    ``link="src->dst"``).  Handles come from the process registry at
    construction; with telemetry disabled the registry is the null
    object and each hot-path site pays one branch (``_reg.enabled``),
    no allocations.  Subclasses call ``_obs_send(msg[, nbytes])`` where
    they serialize/send; recv is counted centrally in ``_notify``.
    """

    flavor = "p2p"

    def __init__(self):
        self._observers: list[Observer] = []
        self._reg = telemetry.get_registry()
        self._link_cache: dict = {}  # (name, src, dst) -> counter

    def _obs_send(self, msg: Message, nbytes: int = 0) -> None:
        if not self._reg.enabled:
            return
        telemetry.link_counter(self._reg, self._link_cache,
                               "fedml_comm_send_total",
                               msg.sender_id, msg.receiver_id).inc()
        if nbytes:
            telemetry.link_counter(self._reg, self._link_cache,
                                   "fedml_comm_send_bytes_total",
                                   msg.sender_id, msg.receiver_id
                                   ).inc(nbytes)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        # idempotent: teardown paths (actor finish + test fixture cleanup)
        # may both remove; the second call is a no-op, not a ValueError
        if observer in self._observers:
            self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        if self._reg.enabled:
            telemetry.link_counter(self._reg, self._link_cache,
                                   "fedml_comm_recv_total",
                                   msg.sender_id, msg.receiver_id).inc()
        for obs in self._observers:
            obs.receive_message(msg.type, msg)

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        """Deliver msg to msg.receiver_id (asynchronously)."""

    def send_many(self, messages: list) -> None:
        """Deliver a fan-out built by `message.build_fanout`: N messages
        sharing ONE already-serialized payload (`SharedPayload`), so the
        expensive model-bytes encode ran exactly once no matter how many
        silos the broadcast reaches.

        The default delegates to ``send_message`` per receiver — which is
        the correct semantics for every flavor AND every wrapper:
        `ResilientTransport` queues/retries each link independently,
        `ChaosTransport` draws each link's fault schedule exactly as for
        a single send (replay seeds stay valid), and wire transports'
        ``to_bytes`` transparently reuses the shared block.  Override
        only to exploit a wire that can address multiple receivers in
        one operation."""
        for msg in messages:
            self.send_message(msg)

    @abc.abstractmethod
    def run(self) -> None:
        """Block dispatching inbound messages to observers until stopped."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Unblock run() and release resources.  Implementations MUST be
        idempotent: overlapping teardown paths (straggler-policy abort,
        actor ``finish()``, test fixtures) may each call ``stop()``."""
