"""Sequence/context parallelism: ring attention over a ``sequence`` mesh
axis must exactly reproduce dense causal attention (the long-context design
the reference lacks entirely, SURVEY.md §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models import TransformerLM
from fedml_tpu.parallel.ring_attention import (
    full_attention, make_sequence_mesh, make_sequence_parallel_apply,
    ring_attention)


def _qkv(rng, b=2, t=32, h=2, d=8):
    q = rng.randn(b, t, h, d).astype(np.float32)
    k = rng.randn(b, t, h, d).astype(np.float32)
    v = rng.randn(b, t, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _dense_reference(q, k, v, causal):
    """Plain softmax attention in numpy-ish jnp, no online accumulation."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d * 1.0)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


@pytest.mark.parametrize("causal", [True, False])
def test_full_attention_matches_dense_softmax(rng, causal):
    q, k, v = _qkv(np.random.RandomState(0))
    pos = jnp.arange(q.shape[1])
    got = full_attention(q, k, v, pos, pos, causal=causal)
    want = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(devices, causal):
    """Sharded ring == dense, on the 8-device mesh."""
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(np.random.RandomState(1), t=32)
    pos = jnp.arange(32)
    want = full_attention(q, k, v, pos, pos, causal=causal)

    mesh = make_sequence_mesh(8)

    def _sharded(q, k, v, pos):
        return ring_attention(q, k, v, pos, pos, "sequence", causal=causal)

    from fedml_tpu.parallel.cohort import compat_shard_map
    fn = jax.jit(compat_shard_map(
        _sharded, mesh=mesh,
        in_specs=(P(None, "sequence"), P(None, "sequence"),
                  P(None, "sequence"), P("sequence")),
        out_specs=P(None, "sequence")))
    got = fn(q, k, v, pos)
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_attention_matches_full(rng, causal):
    """Flash-style kv-block scan == dense, including gradients."""
    from fedml_tpu.parallel.ring_attention import blockwise_attention

    q, k, v = _qkv(np.random.RandomState(5), t=32)
    pos = jnp.arange(32)
    want = full_attention(q, k, v, pos, pos, causal=causal)
    got = blockwise_attention(q, k, v, pos, pos, block_size=8, causal=causal)
    np.testing.assert_allclose(got, want, atol=1e-5)

    def loss_block(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, pos, pos, 8,
                                           causal=causal) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, pos, pos,
                                      causal=causal) ** 2)

    g_block = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_block, g_full):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_transformer_blockwise_matches_dense():
    """TransformerLM(block_size=...) forward == dense TransformerLM with the
    same params."""
    dense = TransformerLM(vocab_size=40, d_model=32, n_heads=2, n_layers=2,
                          d_ff=64, max_len=64)
    blocked = TransformerLM(vocab_size=40, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_len=64, block_size=8)
    toks = jnp.asarray(np.random.RandomState(6).randint(0, 40, (2, 32)),
                       jnp.int32)
    params = dense.init(jax.random.key(0), toks)["params"]
    np.testing.assert_allclose(blocked.apply({"params": params}, toks),
                               dense.apply({"params": params}, toks),
                               atol=1e-4)


def test_transformer_tp_sharded_matches_dense(devices):
    """GSPMD dp×tp on the transformer: with q/k/v DenseGeneral kernels
    head-sharded over a model axis, the jitted forward equals the
    replicated one (XLA inserts the tensor-parallel collectives)."""
    from fedml_tpu.parallel.mesh import make_mesh, tp_shard_params

    model = TransformerLM(vocab_size=40, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=32)
    toks = jnp.asarray(np.random.RandomState(9).randint(0, 40, (4, 32)),
                       jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    want = model.apply({"params": params}, toks)

    mesh = make_mesh(client_axis=4, model_axis=2)
    params_tp = tp_shard_params(params, mesh, min_size=512)
    # every large 3-D DenseGeneral kernel must shard its HEADS dim (size 2
    # here) — in-projections at dim 1, the out-projection at dim 0 — so
    # the column/row-parallel pair needs one psum, not a reshard
    n_sharded = 0
    for p in jax.tree.leaves(params_tp):
        if getattr(p, "ndim", 0) != 3:
            continue
        spec = p.sharding.spec
        sharded_dims = [i for i, s in enumerate(spec) if s == "model"]
        assert sharded_dims, (p.shape, spec)
        assert p.shape[sharded_dims[0]] == 2, (p.shape, spec)
        n_sharded += 1
    assert n_sharded >= 4  # q, k, v, out
    got = jax.jit(lambda p, x: model.apply({"params": p}, x))(params_tp, toks)
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("t", [1024, 2000])  # 2000: largest divisor is 500
def test_transformer_auto_blockwise_past_threshold(t):
    """With no backend flag, sequences past auto_block_len silently switch
    to blockwise — including lengths not divisible by 512 (the block is
    the largest 64-512 divisor of T) — with exact parity vs dense."""
    dense = TransformerLM(vocab_size=20, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32, max_len=2048, auto_block_len=1 << 30)
    auto = TransformerLM(vocab_size=20, d_model=16, n_heads=2, n_layers=1,
                         d_ff=32, max_len=2048, auto_block_len=512)
    toks = jnp.asarray(np.random.RandomState(8).randint(0, 20, (1, t)),
                       jnp.int32)
    params = dense.init(jax.random.key(0), toks)["params"]
    np.testing.assert_allclose(auto.apply({"params": params}, toks),
                               dense.apply({"params": params}, toks),
                               atol=1e-4)


def test_auto_block_divisor_choice():
    from fedml_tpu.models.transformer import _auto_block
    assert _auto_block(1024, 1 << 30) is None          # under threshold
    assert _auto_block(2048, 1024) == 512
    assert _auto_block(2000, 1024) == 500
    assert _auto_block(1031, 1024) is None             # prime: stay dense


def test_transformer_flash_backend_rejects_cpu():
    """use_flash is the TPU pallas kernel; off-TPU it must fail loudly with
    guidance, never fall back silently (a silent fallback would fake a
    flash benchmark)."""
    model = TransformerLM(vocab_size=16, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=16, use_flash=True)
    toks = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(RuntimeError, match="needs a TPU backend"):
        model.init(jax.random.key(0), toks)


def test_transformer_sequence_parallel_parity(devices):
    """The FULL model (embeddings, LN, MLP, attention, head) under a
    sequence-sharded shard_map equals the single-device forward."""
    model = TransformerLM(vocab_size=50, d_model=32, n_heads=2, n_layers=2,
                         d_ff=64, max_len=64)
    b, t = 2, 32
    toks = jnp.asarray(np.random.RandomState(2).randint(0, 50, (b, t)),
                       jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    want = model.apply({"params": params}, toks)

    mesh = make_sequence_mesh(8)
    sp_apply = make_sequence_parallel_apply(model, mesh)
    got = sp_apply(params, toks)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_transformer_is_causal():
    """Changing tokens at positions > t must not change logits at t."""
    model = TransformerLM(vocab_size=50, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=64)
    rng = np.random.RandomState(3)
    toks = jnp.asarray(rng.randint(0, 50, (1, 16)), jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]
    out = model.apply({"params": params}, toks)
    toks2 = toks.at[0, 10:].set((toks[0, 10:] + 1) % 50)
    out2 = model.apply({"params": params}, toks2)
    np.testing.assert_allclose(out[0, :10], out2[0, :10], atol=1e-5)
    assert not np.allclose(out[0, 10:], out2[0, 10:])


@pytest.mark.skipif(
    not hasattr(__import__("jax"), "shard_map"),
    reason="sequence-parallel training requires jax.shard_map (the "
           "legacy fallback mis-transposes the gradient psum; "
           "make_sp_cohort_step refuses loudly there)")
def test_sp_cohort_step_matches_dense_cohort(devices):
    """Federated long-context: the dp×sp [4 clients, 2 sequence] mesh round
    (ring attention + psum'd loss/grads within each client, weighted psum
    aggregation across clients) == the single-chip vmap cohort with dense
    attention."""
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.parallel.sequence import (
        make_sp_cohort_step, make_sp_mesh, make_sp_nwp_workload)
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import NWPWorkload, make_client_optimizer

    model = TransformerLM(vocab_size=30, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=16)
    rng = np.random.RandomState(7)
    xs = [rng.randint(1, 30, (6, 16)).astype(np.int32) for _ in range(4)]
    ys = [np.concatenate([x[:, 1:], x[:, :1]], axis=1) for x in xs]
    stacked = {k: jnp.asarray(v)
               for k, v in stack_client_data(xs, ys, batch_size=3).items()}

    dense_wl = NWPWorkload(model)
    params = dense_wl.init(jax.random.key(0), jax.tree.map(
        lambda v: v[0, 0], {k: stacked[k] for k in ("x", "y", "mask")}))

    opt = make_client_optimizer("sgd", 0.1)
    dense_step = make_cohort_step(make_local_trainer(dense_wl, opt, 1))
    want, want_metrics = dense_step(params, stacked, jax.random.key(1))

    sp_wl = make_sp_nwp_workload(model)
    sp_step = make_sp_cohort_step(sp_wl, opt, epochs=1,
                                  mesh=make_sp_mesh(4, 2))
    got, got_metrics = sp_step(params, stacked, jax.random.key(1))

    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4),
                 got, want)
    np.testing.assert_allclose(got_metrics["train_loss_per_step"],
                               want_metrics["train_loss_per_step"],
                               atol=1e-4)


@pytest.mark.slow
def test_transformer_federated_learning_to_target():
    """The attention path LEARNS, not just runs: federated training on a
    deterministic next-token task (y_t = x_t) must reach >90% token accuracy
    — the convergence-suite pattern applied to the transformer family."""
    from conftest import identity_lm_data
    from fedml_tpu.algorithms import FedAvg, FedAvgConfig
    from fedml_tpu.trainer.workload import NWPWorkload

    model = TransformerLM(vocab_size=12, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=16)
    data = identity_lm_data()
    cfg = FedAvgConfig(comm_round=30, client_num_per_round=4, epochs=2,
                       batch_size=8, lr=0.3, frequency_of_the_test=29)
    algo = FedAvg(NWPWorkload(model), data, cfg)
    algo.run()
    assert algo.history[-1]["train_acc"] > 0.9, algo.history[-1]


def test_transformer_nwp_federated_round(devices):
    """Transformer drives the NWP workload through a full FedAvg cohort
    step (vmap'd clients + weighted aggregation) — loss finite, params move."""
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import NWPWorkload, make_client_optimizer

    model = TransformerLM(vocab_size=30, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=32)
    wl = NWPWorkload(model)
    rng = np.random.RandomState(4)
    xs = [rng.randint(1, 30, (6, 16)).astype(np.int32) for _ in range(4)]
    ys = [np.concatenate([x[:, 1:], x[:, :1]], axis=1) for x in xs]
    stacked = {k: jnp.asarray(v)
               for k, v in stack_client_data(xs, ys, batch_size=3).items()}
    params = wl.init(jax.random.key(0), jax.tree.map(
        lambda v: v[0, 0], {k: stacked[k] for k in ("x", "y", "mask")}))
    step = make_cohort_step(
        make_local_trainer(wl, make_client_optimizer("sgd", 0.1), epochs=1))
    new_params, metrics = step(params, stacked, jax.random.key(1))
    assert np.isfinite(float(metrics["train_loss_per_step"].mean()))
    delta = max(jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), params, new_params)))
    assert delta > 0
