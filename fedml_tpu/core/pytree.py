"""Pytree parameter math — the aggregation kernel of the framework.

In the reference, model weights travel as ``state_dict`` objects and the
server aggregates them key-by-key in a Python loop
(``fedml_api/distributed/fedavg/FedAVGAggregator.py:58-87``).  Here model
parameters are JAX pytrees; aggregation is a pure, jit-able function that XLA
fuses into a handful of kernels, and under `shard_map` the same function runs
*sharded*: each mesh participant contributes its local weighted sum and a
`lax.psum` completes the global mean over ICI.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    """a - b, elementwise. The FedOpt pseudo-gradient is tree_sub(w_old, w_agg)."""
    return jax.tree.map(jnp.subtract, a, b)


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_global_norm(tree: Pytree) -> jax.Array:
    """L2 norm of the concatenation of all leaves.

    Equivalent of the reference's ``vectorize_weight(...).norm()``
    (``fedml_core/robustness/robust_aggregation.py:4-12``) without ever
    materializing the flat vector.
    """
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_vector_norm(a: Pytree, b: Pytree) -> jax.Array:
    """|| a - b ||_2 over all leaves (norm of the update difference)."""
    return tree_global_norm(tree_sub(a, b))


def acc_dtype(dtype):
    """The weighted-mean accumulator dtype contract: float leaves
    accumulate in their own dtype, ints in f32 (exact for step
    counters).  Shared by `tree_weighted_mean`, the stack-mode scan
    mean (`robust/defense.py`), and the streaming fold
    (`core/stream_agg.py`) — all three must agree or stream-vs-stack
    bit-identity breaks."""
    return dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.float32


def tree_weighted_mean(trees: Sequence[Pytree] | Pytree, weights: jax.Array) -> Pytree:
    """Sample-weighted average of client parameter pytrees.

    Re-implements the aggregation math of
    ``FedAVGAggregator.aggregate`` (FedAVGAggregator.py:58-87):
    ``sum_i (n_i / sum_j n_j) * w_i`` per parameter.

    Accepts either a list of pytrees or a single *stacked* pytree whose
    leaves carry a leading ``[num_clients, ...]`` axis (the cohort-engine
    layout).  ``weights`` are raw sample counts; normalization happens here,
    so callers pass ``n_i`` directly as the reference does.
    """
    weights = jnp.asarray(weights, dtype=jnp.float32)
    norm = weights / jnp.sum(weights)
    if isinstance(trees, (list, tuple)):
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    else:
        stacked = trees

    def _avg(x):
        # accumulate in f32 (exact for int leaves like step counters, and
        # full-precision normalization for bf16 params), cast back at the end
        # — matching the reference where float-averaged int tensors are cast
        # back on load_state_dict
        acc = acc_dtype(x.dtype)
        w = norm.reshape((-1,) + (1,) * (x.ndim - 1)).astype(jnp.float32)
        out = jnp.sum(x.astype(acc) * w.astype(acc), axis=0)
        return out.astype(x.dtype)

    return jax.tree.map(_avg, stacked)


def tree_weighted_psum_mean(local_tree: Pytree, local_weight: jax.Array,
                            axis_name: str) -> Pytree:
    """Distributed weighted mean across a mesh axis.

    Each participant holds one client's (or client-shard's partial) parameters
    and weight; the global mean is computed with two `lax.psum`s over ICI.
    This single call replaces the reference's entire upload / barrier /
    aggregate message round-trip (FedAvgServerManager.py:45-82).
    """
    w = local_weight.astype(jnp.float32)
    total = jax.lax.psum(w, axis_name)
    ratio = w / total  # normalize in f32 even for bf16 parameter trees
    return jax.tree.map(
        lambda x: jax.lax.psum(x * ratio.astype(x.dtype), axis_name),
        local_tree,
    )


class HostMirror:
    """Identity-keyed memo of a pytree's device→host copy.

    The server actors read the global's host form several times per round
    (broadcast payload, checkpoint state, staging refill, serve publish);
    this keeps ONE ``np.asarray`` transfer per distinct params value —
    the mirror invalidates when the params OBJECT is replaced, which is
    how every aggregation path produces a new global.  Do not mutate a
    mirrored tree's leaves in place.
    """

    __slots__ = ("_key", "_host")

    def __init__(self):
        self._key = self._host = None

    def get(self, params: Pytree) -> Pytree:
        if self._host is None or self._key is not params:
            import numpy as np
            self._key = params
            self._host = jax.tree.map(np.asarray, params)
        return self._host
