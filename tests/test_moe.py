"""Switch MoE (models/moe.py) + expert parallelism (parallel/expert.py).

The reference has no MoE; ep is here because the framework treats every
parallelism as a placement knob (SURVEY.md §2.5).  Core claims: the
routed layer computes what it says (capacity drops ride the residual),
the balance loss reaches the optimizer, and GSPMD expert sharding is
numerically invisible — forward AND gradients — on the 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models import SwitchFFN, TransformerLM
from fedml_tpu.parallel.expert import (ep_shard_params, make_dp_ep_mesh,
                                       make_expert_mesh)
from fedml_tpu.trainer.workload import NWPWorkload


@pytest.fixture(scope="module")
def lm_setup():
    lm = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=2,
                      d_ff=64, max_len=16, moe_experts=8)
    toks = jnp.asarray(np.random.RandomState(0).randint(1, 32, (4, 16)),
                       jnp.int32)
    params = lm.init(jax.random.key(0), toks)["params"]
    return lm, toks, params


def test_switch_ffn_routes_and_drops():
    """Tiny capacity with 64 tokens (one routing group): most tokens are
    dropped and must come back EXACTLY zero (they ride the transformer
    residual); kept tokens must be nonzero."""
    ffn = SwitchFFN(n_experts=2, d_model=8, d_ff=16, capacity_factor=0.04)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 64, 8), jnp.float32)
    params = ffn.init(jax.random.key(0), x)["params"]
    y = ffn.apply({"params": params}, x)
    assert y.shape == x.shape
    row_norm = np.asarray(jnp.abs(y[0]).sum(axis=-1))
    kept = (row_norm > 0).sum()
    # one 64-token group: cap = ceil(0.04*64/2) = 2/expert -> <= 4 kept
    assert 1 <= kept <= 4, kept


def test_switch_ffn_pads_excluded():
    """Masked (pad) positions must return exactly zero, must not shift or
    consume real tokens' expert capacity, and must not enter the balance
    statistics — real-token outputs and the sown aux are identical with
    and without trailing pads."""
    ffn = SwitchFFN(n_experts=4, d_model=8, d_ff=16, capacity_factor=4.0)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(1, 16, 8), jnp.float32)
    params = ffn.init(jax.random.key(0), x)["params"]
    mask = jnp.asarray([[1.0] * 8 + [0.0] * 8])

    y_all, sown_all = ffn.apply({"params": params}, x,
                                mutable=["losses"])
    y_mask, sown_mask = ffn.apply({"params": params}, x, mask,
                                  mutable=["losses"])
    # pads come back zero; real tokens unaffected by the pads' presence
    # (capacity_factor=4 ensures zero drops in both runs)
    np.testing.assert_array_equal(np.asarray(y_mask[0, 8:]), 0.0)
    np.testing.assert_allclose(np.asarray(y_mask[0, :8]),
                               np.asarray(y_all[0, :8]), rtol=1e-6)
    # aux over real tokens only == aux of the unpadded prefix
    _, sown_prefix = ffn.apply({"params": params}, x[:, :8],
                               mutable=["losses"])
    aux_mask = float(jax.tree.leaves(sown_mask["losses"])[0])
    aux_prefix = float(jax.tree.leaves(sown_prefix["losses"])[0])
    aux_all = float(jax.tree.leaves(sown_all["losses"])[0])
    assert abs(aux_mask - aux_prefix) < 1e-5
    assert abs(aux_mask - aux_all) > 1e-6  # pads DID move the unmasked aux


def test_switch_ffn_grouped_routing_bounds_dispatch():
    """group_size splits routing: with G groups the dispatch tensor is
    [G, g, E, C] (linear in tokens).  Outputs stay exact for the kept
    tokens; per-group capacity means drop behavior is LOCAL to a group."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 64, 8), jnp.float32)
    big = SwitchFFN(n_experts=4, d_model=8, d_ff=16, capacity_factor=4.0,
                    group_size=128)
    small = SwitchFFN(n_experts=4, d_model=8, d_ff=16, capacity_factor=4.0,
                      group_size=32)
    params = big.init(jax.random.key(0), x)["params"]
    # no-drop regime: group choice cannot change the math
    np.testing.assert_allclose(
        np.asarray(big.apply({"params": params}, x)),
        np.asarray(small.apply({"params": params}, x)), rtol=1e-5,
        atol=1e-6)
    with pytest.raises(ValueError, match="must divide"):
        SwitchFFN(n_experts=4, d_model=8, d_ff=16, group_size=48).apply(
            {"params": params}, x)


def test_balance_loss_reaches_training(lm_setup):
    """The sown load-balance terms must change the training loss (plain
    CE vs CE + alpha*aux) and produce router gradients."""
    lm, toks, params = lm_setup
    wl = NWPWorkload(lm)
    batch = {"x": toks, "y": jnp.roll(toks, -1, axis=1),
             "mask": jnp.ones(4, jnp.float32)}
    loss, _ = wl.loss_fn(params, batch, None, True)

    lm0 = lm.copy(moe_aux_weight=0.0)
    loss0, _ = NWPWorkload(lm0).loss_fn(params, batch, None, True)
    assert float(loss) > float(loss0)  # aux is nonnegative and active

    g = jax.grad(lambda p: wl.loss_fn(p, batch, None, True)[0])(params)
    assert float(jnp.abs(g["moe_0"]["router"]["kernel"]).max()) > 0


def test_ep_sharding_placement(lm_setup, devices):
    """Expert tables land on the experts axis; the router and every
    non-MoE leaf stay replicated (every token needs every router row)."""
    from jax.sharding import PartitionSpec as P
    lm, toks, params = lm_setup
    mesh = make_expert_mesh(8, devices=devices)
    placed = ep_shard_params(params, mesh, 8)
    assert placed["moe_0"]["w1"].sharding.spec == P("experts", None, None)
    assert placed["moe_1"]["w2"].sharding.spec == P("experts", None, None)
    assert placed["moe_0"]["b1"].sharding.spec == P("experts", None)
    assert placed["moe_0"]["router"]["kernel"].sharding.spec == P()
    assert placed["tok_embed"]["embedding"].sharding.spec == P()


def test_ep_matches_single_chip(lm_setup, devices):
    """GSPMD ep: forward and gradients with experts sharded over 8 devices
    must equal the unsharded computation — XLA's inserted dispatch/combine
    collectives change layout, not math."""
    lm, toks, params = lm_setup
    wl = NWPWorkload(lm)
    batch = {"x": toks, "y": jnp.roll(toks, -1, axis=1),
             "mask": jnp.ones(4, jnp.float32)}
    mesh = make_expert_mesh(8, devices=devices)
    params_ep = ep_shard_params(params, mesh, 8)

    fwd = jax.jit(lambda p, x: lm.apply({"params": p}, x))
    np.testing.assert_allclose(np.asarray(fwd(params, toks)),
                               np.asarray(fwd(params_ep, toks)),
                               rtol=1e-5, atol=2e-5)
    grad = jax.jit(jax.grad(lambda p: wl.loss_fn(p, batch, None, True)[0]))
    g, g_ep = grad(params), grad(params_ep)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=2e-5), g, g_ep)


def test_ep_shard_rejects_indivisible(lm_setup, devices):
    lm, toks, params = lm_setup
    mesh = make_expert_mesh(8, devices=devices)
    with pytest.raises(ValueError, match="not divisible"):
        ep_shard_params(params, mesh, 12)


def test_dp_ep_cohort_round_matches_single_chip(devices):
    """dp x ep: the FULL federated round on a [clients=2, experts=4] mesh
    — cohort rows on clients, expert tables on experts, plain vmapped
    cohort step under GSPMD — must equal the unsharded round."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import make_client_optimizer

    lm = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1,
                      d_ff=64, max_len=8, moe_experts=4)
    wl = NWPWorkload(lm)
    rng = np.random.RandomState(0)
    xs = [rng.randint(1, 32, (4, 8)).astype(np.int32) for _ in range(4)]
    ys = [np.concatenate([x[:, 1:], x[:, :1]], axis=1) for x in xs]
    cohort = {k: jnp.asarray(v)
              for k, v in stack_client_data(xs, ys, batch_size=2).items()}
    params = wl.init(jax.random.key(0), jax.tree.map(
        lambda v: v[0, 0], {k: cohort[k] for k in ("x", "y", "mask")}))
    step = make_cohort_step(
        make_local_trainer(wl, make_client_optimizer("sgd", 0.1), epochs=1))
    want, _ = step(params, cohort, jax.random.key(5))

    mesh = make_dp_ep_mesh(2, 4, devices=devices)
    params_s = ep_shard_params(params, mesh, 4)
    cohort_s = jax.tree.map(
        lambda v: jax.device_put(v, NamedSharding(mesh, P("clients"))),
        cohort)
    got, _ = step(params_s, cohort_s, jax.random.key(5))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=2e-5), want, got)


def test_moe_lm_learns_federatedly():
    """The MoE transformer rides the standard federated machinery: a few
    FedAvg rounds on the identity-LM task must cut the loss."""
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import make_client_optimizer

    lm = TransformerLM(vocab_size=16, d_model=32, n_heads=2, n_layers=1,
                      d_ff=64, max_len=8, moe_experts=4)
    wl = NWPWorkload(lm)
    rng = np.random.RandomState(0)
    xs = [rng.randint(1, 16, (8, 8)).astype(np.int32) for _ in range(4)]
    ys = [x.copy() for x in xs]  # identity task
    cohort = {k: jnp.asarray(v)
              for k, v in stack_client_data(xs, ys, batch_size=4).items()}
    params = wl.init(jax.random.key(0), jax.tree.map(
        lambda v: v[0, 0], {k: cohort[k] for k in ("x", "y", "mask")}))
    step = make_cohort_step(
        make_local_trainer(wl, make_client_optimizer("sgd", 0.3), epochs=1))
    losses = []
    for r in range(6):
        params, m = step(params, cohort, jax.random.key(r))
        losses.append(float(m["train_loss_per_step"].mean()))
    assert losses[-1] < losses[0] * 0.7, losses
