"""Classical vertical FL — guest (labels) + hosts (feature shards).

Reference choreography (``fedml_api/distributed/classical_vertical_fl/``):
per round the guest takes one minibatch, computes its own logits, ADDS the
hosts' logits for the same rows, computes sigmoid-BCE loss against its
labels, takes d(loss)/d(total logits) and sends that gradient back to every
host; each party then backprops through its local classifier + feature
extractor (guest_trainer.py:73-126, host_trainer.py; vfl_api.py:16-41).
Batches advance cyclically (batch_idx wraps, guest_trainer.py:75-83).

TPU-native design: the logits-sum boundary is a *linear* point of the chain
rule, so the whole multi-party step differentiates as ONE jit program —
``jax.grad`` over sum(party_logits) produces exactly the gradients the wire
protocol ships (d total_logits is broadcast to every party, then each party
VJPs it locally).  Party feature shards can additionally be sharded over a
mesh axis via pjit PartitionSpec (feature-dim TP, SURVEY.md §2.5).  The
standalone fixture semantics (vfl_fixture.py) are `VerticalFL.fit`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

Pytree = Any


@dataclasses.dataclass
class VFLConfig:
    rounds: int = 100            # reference drives by comm rounds, 1 batch each
    batch_size: int = 256
    lr: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 0.01   # DenseModel SGD defaults (vfl_models_standalone.py:13)
    frequency_of_the_test: int = 10


def _cyclic_batch(rnd: int, batch_size: int, n: int) -> np.ndarray:
    """Always-full cyclic minibatch (guest_trainer.py:75-83 wraps batch_idx
    so every round serves batch_size rows).  Full batches keep the jit'd
    step at ONE static shape — ragged tails would recompile per size."""
    return np.arange(rnd * batch_size, rnd * batch_size + batch_size) % max(1, n)


class VerticalFL:
    """``party_models``: one flax module per party (guest first); each maps
    its feature shard to a [B, 1] logit contribution."""

    def __init__(self, party_models: Sequence[Any], cfg: VFLConfig):
        self.party_models = list(party_models)
        self.cfg = cfg
        self.opt = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay),
            optax.sgd(cfg.lr, momentum=cfg.momentum))
        self._build()

    def _build(self):
        def total_logits(params_list, xs):
            out = 0.0
            for model, p, x in zip(self.party_models, params_list, xs):
                out = out + model.apply({"params": p}, x)
            return out

        def loss_fn(params_list, xs, y):
            logits = total_logits(params_list, xs)
            # guest loss: sigmoid BCE (criterion = BCEWithLogitsLoss).
            # Labels may arrive as {-1,+1} (NUS-WIDE neg_label=-1) or {0,1};
            # binarize so BCE targets are always valid probabilities.
            y01 = (y > 0).astype(logits.dtype)
            return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, y01))

        def step(params_list, opt_states, xs, y):
            loss, grads = jax.value_and_grad(loss_fn)(params_list, xs, y)
            new_params, new_opts = [], []
            for p, s, g in zip(params_list, opt_states, grads):
                u, s = self.opt.update(g, s, p)
                new_params.append(optax.apply_updates(p, u))
                new_opts.append(s)
            return new_params, new_opts, loss

        self._step = jax.jit(step)
        self._predict = jax.jit(total_logits)

    def init(self, rng: jax.Array, xs: Sequence[np.ndarray]):
        rngs = jax.random.split(rng, len(self.party_models))
        params = [m.init(r, jnp.asarray(x[:1]))["params"]
                  for m, r, x in zip(self.party_models, rngs, xs)]
        return params, [self.opt.init(p) for p in params]

    def fit(self, train: Sequence[np.ndarray], test: Sequence[np.ndarray],
            rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        """train/test: [Xa, Xb, ..., y] (the loaders' contract,
        lending_club_dataset.py:162)."""
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(0)
        xs_all, y_all = train[:-1], np.asarray(train[-1], np.float32)
        params, opt_states = self.init(rng, xs_all)
        n = len(y_all)
        history: List[Dict[str, float]] = []
        for rnd in range(cfg.rounds):
            idx = _cyclic_batch(rnd, cfg.batch_size, n)
            xs = [jnp.asarray(x[idx]) for x in xs_all]
            y = jnp.asarray(y_all[idx])
            params, opt_states, loss = self._step(params, opt_states, xs, y)
            if (rnd + 1) % cfg.frequency_of_the_test == 0 or rnd == cfg.rounds - 1:
                m = self.evaluate(params, test)
                m.update({"round": rnd, "train_loss": float(loss)})
                history.append(m)
        return {"params": params, "history": history}

    def evaluate(self, params, test: Sequence[np.ndarray]) -> Dict[str, float]:
        xs = [jnp.asarray(x) for x in test[:-1]]
        y = np.asarray(test[-1], np.float32)
        logits = np.asarray(self._predict(params, xs))
        pred = (logits > 0).astype(np.float32)
        # the reference evaluates accuracy/auc on 0/1-ized labels
        y01 = (y > 0).astype(np.float32)
        return {"test_acc": float((pred == y01).mean())}


# ---------------------------------------------------------------------------
# Explicit message-protocol parity (cross-silo wire): the guest/host split.

class VFLHost:
    """Host party: logits up, gradient down (host_trainer semantics)."""

    def __init__(self, model, x: np.ndarray, cfg: VFLConfig):
        self.model = model
        self.x = x
        self.cfg = cfg
        self.opt = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay),
            optax.sgd(cfg.lr, momentum=cfg.momentum))

        def fwd(p, x):
            return model.apply({"params": p}, x)

        def bwd(p, opt_state, x, g_logits):
            _, vjp = jax.vjp(lambda q: fwd(q, x), p)
            (g_p,) = vjp(g_logits)
            u, opt_state = self.opt.update(g_p, opt_state, p)
            return optax.apply_updates(p, u), opt_state

        self._fwd = jax.jit(fwd)
        self._bwd = jax.jit(bwd)

    def init(self, rng):
        self.params = self.model.init(rng, jnp.asarray(self.x[:1]))["params"]
        self.opt_state = self.opt.init(self.params)

    def compute_logits(self, idx: np.ndarray) -> np.ndarray:
        self._batch = jnp.asarray(self.x[idx])
        return np.asarray(self._fwd(self.params, self._batch))

    def apply_gradients(self, g_logits: np.ndarray) -> None:
        self.params, self.opt_state = self._bwd(
            self.params, self.opt_state, self._batch,
            jnp.asarray(g_logits))


class VFLGuest(VFLHost):
    """Guest = host + labels + loss; produces the gradient it sends to all
    hosts (guest_trainer.py:94-105: d loss / d total_logits)."""

    def __init__(self, model, x: np.ndarray, y: np.ndarray, cfg: VFLConfig):
        super().__init__(model, x, cfg)
        self.y = np.asarray(y, np.float32)

        def loss_and_grad(logits_total, y):
            def f(l):
                y01 = (y > 0).astype(l.dtype)
                return jnp.mean(optax.sigmoid_binary_cross_entropy(l, y01))
            return jax.value_and_grad(f)(logits_total)

        self._loss_and_grad = jax.jit(loss_and_grad)

    def guest_step(self, host_logits: List[np.ndarray], idx: np.ndarray
                   ) -> np.ndarray:
        guest_logits = self.compute_logits(idx)
        total = jnp.asarray(sum(host_logits, guest_logits))
        loss, g = self._loss_and_grad(total, jnp.asarray(self.y[idx]))
        self.last_loss = float(loss)
        g = np.asarray(g)
        self.apply_gradients(g)       # guest backprops its own stack too
        return g                      # broadcast to hosts


def run_vfl_protocol(guest: VFLGuest, hosts: List[VFLHost],
                     rounds: int, batch_size: int,
                     rng: Optional[jax.Array] = None) -> List[float]:
    """Drives the wire choreography end-to-end (vfl_api.py:16-41).  Returns
    per-round guest losses.  Numerically identical to `VerticalFL.fit` —
    the test suite asserts it."""
    rng = rng if rng is not None else jax.random.key(0)
    rngs = jax.random.split(rng, len(hosts) + 1)
    guest.init(rngs[0])
    for h, r in zip(hosts, rngs[1:]):
        h.init(r)
    n = len(guest.y)
    losses = []
    for rnd in range(rounds):
        idx = _cyclic_batch(rnd, batch_size, n)
        host_logits = [h.compute_logits(idx) for h in hosts]
        g = guest.guest_step(host_logits, idx)
        for h in hosts:
            h.apply_gradients(g)
        losses.append(guest.last_loss)
    return losses
