"""Cross-silo / host-edge communication layer.

On-pod, fedml_tpu has no message passing at all — aggregation is a collective
inside one jit program (`fedml_tpu.parallel.cohort`).  This package is the
*edge* of the system: the place where true cross-silo federation (separate
hosts, separate trust domains, WAN links) still needs an explicit
message-passing protocol, as in the reference's
``fedml_core/distributed/communication`` stack.

Differences from the reference, by design:

- Payloads are **binary array frames**, not JSON-encoded nested float lists.
  The reference serializes every weight tensor through
  ``transform_tensor_to_list`` → json (fedml_api/distributed/fedavg/utils.py:7-16),
  a multi-x size and decode overhead; here pytrees are framed as a compact
  JSON header plus raw ``ndarray`` bytes (`fedml_tpu.comm.message`).
- The in-process transport is a first-class, deterministic test fixture —
  the reference references a MOCK backend that does not exist in its tree
  (fedml_core/distributed/client/client_manager.py:7).
- The gRPC backend uses grpc's generic bytes-in/bytes-out RPC, no codegen
  (the reference ships protoc-generated stubs of a string-payload proto,
  gRPC/proto/grpc_comm_manager.proto:3-16).
"""

from fedml_tpu.comm.message import Message, SharedPayload, build_fanout
from fedml_tpu.comm.transport import Observer, Transport
from fedml_tpu.comm.local import LocalHub, LocalTransport
from fedml_tpu.comm.actors import NodeManager, ClientManager, ServerManager
from fedml_tpu.comm.chaos import (ChaosPlan, ChaosTransport, LinkChaos,
                                  Partition)
from fedml_tpu.comm.resilient import ResilientTransport, RetryPolicy

__all__ = [
    "Message", "SharedPayload", "build_fanout",
    "Observer", "Transport", "LocalHub", "LocalTransport",
    "NodeManager", "ClientManager", "ServerManager",
    "ChaosPlan", "ChaosTransport", "LinkChaos", "Partition",
    "ResilientTransport", "RetryPolicy",
]
