"""FedAC — Federated Accelerated SGD (Yuan & Ma 2020, arXiv:2006.08950;
PAPERS.md).  Principled Nesterov acceleration of FedAvg: provably better
communication/convergence trade-off on strongly convex objectives, and in
practice faster on ill-conditioned problems at the SAME rounds budget
(pinned by test_fedac's conditioning test).

Beyond the reference's algorithm list — its only server-side optimizer
machinery is FedOpt's pseudo-gradient (FedOptAggregator.py:93-122), which
accelerates the SERVER update only; FedAC couples acceleration through
the LOCAL steps themselves.

Algorithm 1 of the paper, cohort-engine form.  The server state is a
coupled pair (x, x^ag); each round both are broadcast, every client runs
K local steps of

    x^md = (1/β)·x + (1 − 1/β)·x^ag
    g    = ∇F_i(x^md; ξ)
    x^ag ← x^md − η·g
    x    ← (1 − 1/α)·x + (1/α)·x^md − γ·g

and the server sample-weight-averages both sequences (the paper averages
uniformly over full participation; the weighted mean is the standard FL
extension and reduces to it on equal shards).  The explicit knobs
``(α=1, β=1, γ=η)`` collapse both sequences onto plain local SGD —
bit-identical FedAvg (parity-tested).  FedAC-I coupling (Lemma 1 of the
paper): given η ≤ 1/L and strong convexity μ ≤ 1/η,

    γ = max(sqrt(η / (μ·K)), η),   α = 1/(γμ),   β = α + 1.

``fedac_mu > 0`` derives (γ, α, β) this way from ``lr`` and the local
step count; otherwise the explicit knobs are used.  The model is
evaluated/reported at x^ag (the paper's output iterate); the x sequence
rides the checkpoint as server state.
"""

from __future__ import annotations

import dataclasses
import warnings
import zlib
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.server_opt import ServerOptMismatchError
from fedml_tpu.trainer.workload import Workload

Pytree = Any


@dataclasses.dataclass
class FedACConfig(FedAvgConfig):
    fedac_mu: float = 0.0     # >0: derive (gamma, alpha, beta) (FedAC-I)
    fedac_gamma: float = 0.0  # explicit knobs (0 -> gamma = lr)
    fedac_alpha: float = 1.0
    fedac_beta: float = 1.0


def fedac_coupling(lr: float, mu: float, k_steps: int):
    """FedAC-I hyperparameter coupling (arXiv:2006.08950 Lemma 1)."""
    import math
    gamma = max(math.sqrt(lr / (mu * max(k_steps, 1))), lr)
    alpha = 1.0 / (gamma * mu)
    beta = alpha + 1.0
    return gamma, alpha, beta


def make_fedac_local(workload: Workload, lr: float, epochs: int,
                     gamma: float, alpha: float, beta: float):
    """``train(x, x_ag, data, rng) -> (x', x_ag')`` — K coupled local
    steps.  Fully-padded batches freeze BOTH sequences (the x^ag ← x^md
    assignment must not fire on masked steps, or ragged clients would
    drift)."""
    import optax
    clip = (optax.clip_by_global_norm(workload.grad_clip_norm)
            if workload.grad_clip_norm is not None else None)
    grad_fn = jax.grad(lambda p, b, r: workload.loss_fn(p, b, r, True)[0])

    def train(x: Pytree, x_ag: Pytree, data: Dict[str, jax.Array],
              rng: jax.Array):
        num_steps = jax.tree.leaves(data)[0].shape[0]
        clip_state = clip.init(x) if clip is not None else None

        def step(carry, step_idx):
            x, x_ag, rng = carry
            rng, drng = jax.random.split(rng)
            batch = jax.tree.map(lambda v: v[step_idx % num_steps], data)
            x_md = jax.tree.map(
                lambda xi, ai: xi / beta + (1.0 - 1.0 / beta) * ai,
                x, x_ag)
            grads = grad_fn(x_md, batch, drng)
            if clip is not None:
                grads, _ = clip.update(grads, clip_state)
            live = jnp.sum(batch["mask"]) > 0
            new_ag = jax.tree.map(lambda m, g: m - lr * g, x_md, grads)
            new_x = jax.tree.map(
                lambda xi, m, g: (1.0 - 1.0 / alpha) * xi + m / alpha
                - gamma * g, x, x_md, grads)
            x_ag = jax.tree.map(
                lambda n, o: jnp.where(live, n, o), new_ag, x_ag)
            x = jax.tree.map(lambda n, o: jnp.where(live, n, o), new_x, x)
            return (x, x_ag, rng), None

        (x, x_ag, _), _ = jax.lax.scan(step, (x, x_ag, rng),
                                       jnp.arange(epochs * num_steps))
        return x, x_ag

    return train


class FedAC(FedAvg):
    """``run()``'s params ARE x^ag (the reported iterate); the coupled x
    sequence is server state riding ``_extra_state``.  FedAvg.run drives
    this via the replaced ``cohort_step`` (host-gather path).

    ``mesh=`` shards the cohort's clients axis (shared round body +
    shard_map/psum; matches single-chip to float tolerance —
    parity-tested); multi-process meshes ride the shared wrap's global
    input staging (the x sequence is replicated server state)."""

    def __init__(self, workload, data, config: FedACConfig, mesh=None,
                 sink=None):
        if config.client_optimizer != "sgd":
            raise ValueError(
                "fedac's local update IS the accelerated rule (Yuan&Ma'20 "
                "Alg. 1); --client_optimizer sgd only")
        if getattr(workload, "stateful", False):
            raise ValueError(
                "fedac does not support stateful (BatchNorm) workloads: "
                "the coupled sequences over running statistics are "
                "undefined — use a GroupNorm model (e.g. resnet18_gn)")
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        steps = int(self.data.train["x"].shape[1])  # S batches per epoch
        if cfg.fedac_mu > 0.0:
            gamma, alpha, beta = fedac_coupling(cfg.lr, cfg.fedac_mu,
                                                cfg.epochs * steps)
        else:
            gamma = cfg.fedac_gamma or cfg.lr
            alpha, beta = cfg.fedac_alpha, cfg.fedac_beta
        if alpha < 1.0 or beta < 1.0:
            hint = ""
            if cfg.fedac_mu > 0.0:
                hint = (f" — derived from --fedac_mu {cfg.fedac_mu}: the "
                        f"coupling needs mu <= 1/lr (= {1.0 / cfg.lr:g}); "
                        "lower --fedac_mu or raise --lr")
            raise ValueError(f"fedac needs alpha >= 1 and beta >= 1 "
                             f"(got alpha={alpha:g}, beta={beta:g}){hint}")
        self.coupling = {"gamma": gamma, "alpha": alpha, "beta": beta}
        # identifies the coupling this x sequence belongs to; x is only
        # meaningful relative to (gamma, alpha, beta, lr) — restoring it
        # under different coupling silently de-accelerates the run
        self._opt_tag = np.asarray(zlib.crc32(
            f"fedac:{gamma!r}:{alpha!r}:{beta!r}:{cfg.lr!r}".encode()),
            np.int64)
        self._x_state = None  # the coupled x sequence (params == x^ag)
        local = make_fedac_local(workload, cfg.lr, cfg.epochs, gamma,
                                 alpha, beta)

        def _core(x_ag, cohort, rng, x, psum_axis=None, index_offset=0):
            """One FedAC round over (a shard of) the cohort — the shared
            round body (SCAFFOLD/FedDyn/FedNova pattern); rng folds by
            GLOBAL cohort slot (parallel/cohort.py convention)."""
            def allsum(v):
                return (jax.lax.psum(v, psum_axis)
                        if psum_axis is not None else v)

            n = cohort["num_samples"].shape[0]
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(n) + index_offset)
            batches = {k: v for k, v in cohort.items()
                       if k != "num_samples"}
            xs, ags = jax.vmap(local, in_axes=(None, None, 0, 0))(
                x, x_ag, batches, rngs)
            w = cohort["num_samples"].astype(jnp.float32)
            ratio = w / jnp.maximum(allsum(jnp.sum(w)), 1.0)

            def _mean(stacked):
                return jax.tree.map(
                    lambda s: allsum(jnp.sum(
                        s * ratio.reshape((-1,) + (1,) * (s.ndim - 1)),
                        axis=0)), stacked)

            return _mean(ags), _mean(xs)

        if mesh is None:
            self._round_step = jax.jit(_core)
        else:
            from jax.sharding import PartitionSpec as P
            from fedml_tpu.parallel.cohort import make_sharded_stateful_round
            self._round_step = make_sharded_stateful_round(
                _core, mesh,
                in_specs=(P(), P("clients"), P(), P()),
                out_specs=(P(), P()))
        self.cohort_step = self._coupled_step

    def run(self, params=None, rng=None, checkpointer=None):
        self._x_state = None  # x^0 = x^ag,0 (fresh runs re-couple)
        return super().run(params=params, rng=rng,
                           checkpointer=checkpointer)

    def _coupled_step(self, params, cohort, rng):
        if self._x_state is None:
            self._x_state = jax.tree.map(jnp.copy, params)
        new_ag, self._x_state = self._round_step(params, cohort, rng,
                                                 self._x_state)
        return new_ag, {}

    # the x sequence rides the round checkpoint beside params (= x^ag)
    def _extra_state(self):
        return {"x_state": self._x_state, "opt_tag": self._opt_tag}

    def _extra_state_template(self, params):
        return {"x_state": jax.tree.map(jnp.zeros_like, params),
                "opt_tag": np.asarray(0, np.int64)}

    def _load_extra_state(self, extra) -> None:
        tag = extra.get("opt_tag")
        if tag is None:
            warnings.warn(
                "fedac: restoring a pre-tag x-sequence snapshot (no "
                "opt_tag recorded) — cannot verify it matches this "
                "run's (gamma, alpha, beta, lr) coupling", stacklevel=2)
        elif int(tag) != int(self._opt_tag):
            raise ServerOptMismatchError(
                f"fedac: snapshot's coupling tag {int(tag)} != this "
                f"run's {int(self._opt_tag)} (gamma="
                f"{self.coupling['gamma']:g}, alpha="
                f"{self.coupling['alpha']:g}, beta="
                f"{self.coupling['beta']:g}, lr={self.cfg.lr:g}); the x "
                f"sequence is only meaningful under the coupling that "
                f"produced it — rerun with the snapshot's --fedac_* / "
                f"--lr flags or start fresh")
        self._x_state = extra["x_state"]
