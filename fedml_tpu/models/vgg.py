"""VGG 11/13/16 with optional norm (parity: fedml_api/model/cv/vgg.py:13-133).

The reference offers plain and BN variants (``vgg11/13/16`` and
``vgg11_bn/13_bn/16_bn``); here one ``norm`` switch covers all six
("none" = plain, "batch"/"group" = normalized).  The reference classifier is
the torchvision triple-Dense head (512*7*7 -> 4096 -> 4096 -> classes,
vgg.py:20-28) which assumes 224x224 inputs; for small inputs (CIFAR) the
features already pool to 1x1 and the head degrades gracefully because we
flatten whatever spatial extent remains.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn

from fedml_tpu.models.norms import Norm, conv_kernel_init

# torchvision configs (vgg.py:63-69): numbers = conv widths, "M" = maxpool.
_CFGS = {
    "A": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "B": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"),
    "D": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence
    num_classes: int = 1000
    norm: str = "none"
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME",
                            kernel_init=conv_kernel_init)(x)
                if self.norm != "none":
                    x = Norm(self.norm)(x, train)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def vgg11(num_classes: int = 1000, norm: str = "none") -> VGG:
    return VGG(cfg=_CFGS["A"], num_classes=num_classes, norm=norm)


def vgg13(num_classes: int = 1000, norm: str = "none") -> VGG:
    return VGG(cfg=_CFGS["B"], num_classes=num_classes, norm=norm)


def vgg16(num_classes: int = 1000, norm: str = "none") -> VGG:
    return VGG(cfg=_CFGS["D"], num_classes=num_classes, norm=norm)
