"""Typed message envelope with a binary pytree codec.

Reference equivalent: ``fedml_core/distributed/communication/message.py:5-74``
— a dict of params with ``msg_type/sender/receiver`` plus arbitrary keys, and
model weights carried under ``"model_params"``.  The reference serializes to
JSON with weights converted tensor→nested-python-list
(fedml_api/distributed/fedavg/utils.py:7-16), which both bloats the wire size
~4x and costs a slow float-by-float decode.

Here a message serializes to one frame::

    [4-byte header length][JSON header][raw buffer 0][raw buffer 1]...

Array-valued params (numpy arrays, JAX arrays, and arbitrary pytrees of them)
are flattened; the header records the treedef, dtypes, and shapes; buffers are
the arrays' raw bytes.  Scalars/strings/lists of plain python stay in the
JSON header.  Decode is zero-copy ``np.frombuffer`` per leaf.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict

import numpy as np

_HDR = struct.Struct("<I")


class Message:
    """Key-value message envelope (type, sender, receiver, params)."""

    # canonical param keys, mirroring the reference's Message constants
    # (message.py:9-24) so algorithm choreography reads the same
    ARG_TYPE = "msg_type"
    ARG_SENDER = "sender"
    ARG_RECEIVER = "receiver"
    ARG_MODEL_PARAMS = "model_params"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_ROUND = "round_idx"
    ARG_ACCEPTED = "accepted_silos"  # silo ids aggregated last round (EF ack)
    # span context (obs/trace.py CTX_KEY): a {"t","s"} dict riding the
    # plain JSON header, so one federated round stitches into a single
    # cross-process trace
    ARG_TRACE = "_trace"

    def __init__(self, msg_type: int | str = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.params: Dict[str, Any] = {
            self.ARG_TYPE: msg_type,
            self.ARG_SENDER: sender_id,
            self.ARG_RECEIVER: receiver_id,
        }

    # -- accessors (reference message.py:26-60) ------------------------------
    @property
    def type(self):
        return self.params[self.ARG_TYPE]

    @property
    def sender_id(self) -> int:
        return self.params[self.ARG_SENDER]

    @property
    def receiver_id(self) -> int:
        return self.params[self.ARG_RECEIVER]

    def add(self, key: str, value: Any) -> "Message":
        self.params[key] = value
        return self

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def __repr__(self):
        keys = [k for k in self.params
                if k not in (self.ARG_TYPE, self.ARG_SENDER, self.ARG_RECEIVER)]
        return (f"Message(type={self.type}, {self.sender_id}->"
                f"{self.receiver_id}, params={keys})")

    # -- binary codec --------------------------------------------------------
    def to_bytes(self) -> bytes:
        header: Dict[str, Any] = {"plain": {}, "arrays": {}}
        buffers = []
        for key, value in self.params.items():
            leaves, spec = _flatten_arrays(value)
            if leaves is None:
                header["plain"][key] = value
            else:
                descr = []
                for leaf in leaves:
                    src = np.asarray(leaf)
                    arr = np.ascontiguousarray(src)
                    # ascontiguousarray promotes 0-d to shape (1,) — record
                    # the ORIGINAL shape so 0-d leaves round-trip exactly
                    descr.append({"dtype": arr.dtype.str, "shape": src.shape,
                                  "idx": len(buffers)})
                    buffers.append(arr)
                header["arrays"][key] = {"spec": spec, "leaves": descr}
        hdr = json.dumps(header).encode()
        parts = [_HDR.pack(len(hdr)), hdr]
        for arr in buffers:
            parts.append(_HDR.pack(arr.nbytes))
            parts.append(arr.tobytes())
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Message":
        (hlen,) = _HDR.unpack_from(data, 0)
        header = json.loads(data[_HDR.size:_HDR.size + hlen])
        offset = _HDR.size + hlen
        buffers = []
        while offset < len(data):
            (n,) = _HDR.unpack_from(data, offset)
            offset += _HDR.size
            buffers.append(data[offset:offset + n])
            offset += n
        msg = cls.__new__(cls)
        msg.params = dict(header["plain"])
        for key, info in header["arrays"].items():
            leaves = []
            for d in info["leaves"]:
                arr = np.frombuffer(buffers[d["idx"]], dtype=np.dtype(d["dtype"]))
                leaves.append(arr.reshape(d["shape"]))
            msg.params[key] = _unflatten_arrays(info["spec"], leaves)
        return msg


def _is_array(x) -> bool:
    if isinstance(x, (np.ndarray, np.generic)):  # includes 0-d numpy scalars
        return True
    return hasattr(x, "__array__") and hasattr(x, "dtype") and hasattr(x, "shape")


def _flatten_arrays(value):
    """Flatten a pytree-of-arrays into (leaves, json-able spec).

    Returns (None, None) when the value contains no arrays — it then travels
    in the JSON header verbatim.  Supports dict/list/tuple nests of arrays,
    the shapes model params (nested dicts) and stacked batches take.
    """
    if _is_array(value):
        return [value], {"k": "leaf"}
    if isinstance(value, dict):
        if not any(_contains_array(v) for v in value.values()):
            return None, None
        keys = sorted(value.keys())
        leaves, specs = [], []
        for k in keys:
            sub_leaves, sub_spec = _flatten_arrays(value[k])
            if sub_leaves is None:  # plain sub-value inside an array dict
                sub_leaves, sub_spec = [], {"k": "plain", "v": value[k]}
            leaves.extend(sub_leaves)
            specs.append(sub_spec)
        return leaves, {"k": "dict", "keys": keys, "children": specs}
    if isinstance(value, (list, tuple)):
        if not any(_contains_array(v) for v in value):
            return None, None
        leaves, specs = [], []
        for v in value:
            sub_leaves, sub_spec = _flatten_arrays(v)
            if sub_leaves is None:
                sub_leaves, sub_spec = [], {"k": "plain", "v": v}
            leaves.extend(sub_leaves)
            specs.append(sub_spec)
        kind = "tuple" if isinstance(value, tuple) else "list"
        return leaves, {"k": kind, "children": specs}
    return None, None


def _contains_array(value) -> bool:
    if _is_array(value):
        return True
    if isinstance(value, dict):
        return any(_contains_array(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_contains_array(v) for v in value)
    return False


def _unflatten_arrays(spec, leaves, _pos=None):
    if _pos is None:
        _pos = [0]
    kind = spec["k"]
    if kind == "leaf":
        out = leaves[_pos[0]]
        _pos[0] += 1
        return out
    if kind == "plain":
        return spec["v"]
    if kind == "dict":
        return {k: _unflatten_arrays(c, leaves, _pos)
                for k, c in zip(spec["keys"], spec["children"])}
    children = [_unflatten_arrays(c, leaves, _pos) for c in spec["children"]]
    return tuple(children) if kind == "tuple" else children
