"""342k-client scale proof (SURVEY hard part (f); VERDICT r3 item 5).

Generates a stackoverflow_nwp-shaped synthetic corpus (vocab 10000 + 3
special + 1 oov, seq 20 — mirroring the reference layout in
fedml_api/data_preprocessing/stackoverflow_nwp/data_loader.py) at the
reference's FULL client count (342,477 train clients), staged directly
into the memmap format (data/stacking.py save/load_stacked_memmap) in
client chunks so host RAM never holds the corpus, then runs federated
rounds of the standard FedAvg engine with cohort sampling — the cohort
gather fancy-indexes the memmap, so per-round RAM is one cohort.

Writes SCALE_PROOF.json: corpus size on disk, staging wall time, peak
host RSS, per-round wall times.  Run on an idle machine:

    python scripts/scale_proof.py --clients 342477 --rounds 10 \
        --per_round 50 [--out_dir /tmp/so_scale] [--small_model]
"""

import argparse
import json
import math
import os
import resource
import sys
import time

import numpy as np
from numpy.lib.format import open_memmap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEQ = 20            # reference nwp sequence length
VOCAB = 10000 + 3 + 1  # vocab + pad/bos/eos + oov (RNNStackOverflow)
PAD, BOS, EOS = 0, 1, 2


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def generate(out_dir: str, n_clients: int, batch_size: int,
             max_samples: int, seed: int, chunk: int = 8192) -> dict:
    """Stream the corpus into memmapped .npy files, ``chunk`` clients at
    a time — peak RAM is O(chunk), not O(n_clients)."""
    os.makedirs(out_dir, exist_ok=True)
    steps = math.ceil(max_samples / batch_size)
    cap = steps * batch_size
    shapes = {
        "x": ((n_clients, steps, batch_size, SEQ), np.int32),
        "y": ((n_clients, steps, batch_size, SEQ), np.int32),
        "mask": ((n_clients, steps, batch_size), np.float32),
        "num_samples": ((n_clients,), np.float32),
    }
    mm = {k: open_memmap(os.path.join(out_dir, f"{k}.npy"), mode="w+",
                         dtype=dt, shape=sh)
          for k, (sh, dt) in shapes.items()}
    t0 = time.time()
    for lo in range(0, n_clients, chunk):
        hi = min(lo + chunk, n_clients)
        c = hi - lo
        rng = np.random.RandomState(seed + lo)
        # long-tail per-client example counts (the reference SO corpus is
        # heavily skewed); clip to the padded capacity
        counts = np.clip(rng.lognormal(2.5, 1.0, c).astype(np.int64),
                         1, cap)
        toks = rng.randint(3, VOCAB, size=(c, cap, SEQ)).astype(np.int32)
        toks[:, :, 0] = BOS
        sample_idx = np.arange(cap)[None, :]
        live = (sample_idx < counts[:, None])  # [c, cap]
        toks *= live[:, :, None]
        ys = np.concatenate(
            [toks[:, :, 1:], np.full((c, cap, 1), EOS, np.int32)], axis=2)
        ys *= live[:, :, None]
        mm["x"][lo:hi] = toks.reshape(c, steps, batch_size, SEQ)
        mm["y"][lo:hi] = ys.reshape(c, steps, batch_size, SEQ)
        mm["mask"][lo:hi] = live.astype(np.float32).reshape(
            c, steps, batch_size)
        mm["num_samples"][lo:hi] = counts.astype(np.float32)
    for v in mm.values():
        v.flush()
    staging_s = time.time() - t0
    disk_gb = sum(os.path.getsize(os.path.join(out_dir, f"{k}.npy"))
                  for k in shapes) / 1e9
    return {"staging_wall_s": round(staging_s, 1),
            "corpus_disk_gb": round(disk_gb, 2),
            "rss_after_staging_gb": round(rss_gb(), 2),
            "steps_per_client": steps, "batch_size": batch_size}


def train(out_dir: str, n_clients: int, rounds: int, per_round: int,
          batch_size: int, small_model: bool, platform: str) -> dict:
    import jax
    # NEVER query the backend before pinning the platform: a wedged TPU
    # tunnel blocks jax.default_backend() forever (verify-skill gotcha).
    if platform != "tpu":
        jax.config.update("jax_platforms", platform)
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.data.stacking import FederatedData, load_stacked_memmap
    from fedml_tpu.models.rnn import RNNStackOverflow
    from fedml_tpu.trainer.workload import NWPWorkload

    stacked = load_stacked_memmap(out_dir)
    assert stacked["x"].shape[0] == n_clients
    data = FederatedData(client_num=n_clients, class_num=VOCAB,
                         train=stacked)
    model = (RNNStackOverflow(embedding_size=32, latent_size=64)
             if small_model else RNNStackOverflow())
    wl = NWPWorkload(model)
    algo = FedAvg(wl, data, FedAvgConfig(
        comm_round=rounds, client_num_per_round=per_round,
        batch_size=batch_size, epochs=1, lr=0.3,
        frequency_of_the_test=10**9))
    # throughput/staging proof: skip the metrics sweep entirely (round 0
    # always evals; a full-corpus LSTM eval would dominate the timing —
    # chunked eval exists for real runs, FedAvgConfig.eval_chunk_clients)
    algo.evaluate_global = lambda p: {}

    round_times = []
    t_last = time.time()
    orig_step = algo.cohort_step

    def timed_step(*a, **kw):
        nonlocal t_last
        out = orig_step(*a, **kw)
        jax.block_until_ready(out[0])
        now = time.time()
        round_times.append(now - t_last)
        t_last = now
        return out

    algo.cohort_step = timed_step
    t0 = time.time()
    algo.run()
    total = time.time() - t0
    rts = np.asarray(round_times[1:] or round_times)  # drop compile round
    return {"rounds": rounds, "clients_per_round": per_round,
            "model": "RNNStackOverflow" + ("(small)" if small_model else ""),
            "platform": jax.default_backend(),
            "total_wall_s": round(total, 1),
            "round_wall_s_median": round(float(np.median(rts)), 3),
            "round_wall_s_max": round(float(rts.max()), 3),
            "first_round_incl_compile_s": round(round_times[0], 1),
            "peak_rss_gb": round(rss_gb(), 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=342477)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--per_round", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=16)
    ap.add_argument("--max_samples", type=int, default=48)
    ap.add_argument("--out_dir", default="/tmp/so_scale")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--small_model", action="store_true",
                    help="reduced embed/latent for CPU-bound hosts")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="tpu touches the live backend — only pass it "
                         "when the tunnel is known-good")
    ap.add_argument("--skip_generate", action="store_true",
                    help="reuse an existing staged corpus in out_dir")
    ap.add_argument("--json_out", default="SCALE_PROOF.json")
    args = ap.parse_args()

    report = {"n_clients": args.clients,
              "reference_anchor":
                  "stackoverflow_nwp 342,477 train clients "
                  "(fedml_api/data_preprocessing/stackoverflow_nwp/)"}
    if not args.skip_generate:
        report["staging"] = generate(args.out_dir, args.clients,
                                     args.batch_size, args.max_samples,
                                     args.seed)
        print("staged:", json.dumps(report["staging"]))
    report["training"] = train(args.out_dir, args.clients, args.rounds,
                               args.per_round, args.batch_size,
                               args.small_model, args.platform)
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
