"""RDP moments accountant (core/privacy.py) — the math the reference's
"weak DP" never does (robust_aggregation.py:51-55 has no accounting)."""

import math

import numpy as np
import pytest

from fedml_tpu.core.privacy import (RdpAccountant, eps_from_rdp,
                                    rdp_subsampled_gaussian)


def test_q1_reduces_to_plain_gaussian_rdp():
    """q=1 must give the unsubsampled Gaussian's exact RDP α/(2z²) —
    the j=α term is the only survivor of the binomial sum."""
    orders = (2, 3, 8, 32, 256)
    for z in (0.5, 1.0, 2.7):
        got = rdp_subsampled_gaussian(1.0, z, orders)
        want = np.asarray(orders) / (2.0 * z * z)
        np.testing.assert_allclose(got, want, rtol=1e-12)


def test_alpha2_closed_form():
    """α=2 collapses to log(1 + q²(e^{1/z²} − 1)) (the three binomial
    terms sum to 1 + q²(e^{1/z²}−1))."""
    for q, z in ((0.01, 1.1), (0.3, 0.8), (0.9, 2.0)):
        got = rdp_subsampled_gaussian(q, z, (2,))[0]
        want = math.log(1.0 + q * q * (math.exp(1.0 / (z * z)) - 1.0))
        assert got == pytest.approx(want, rel=1e-10)


def test_subsampling_strictly_helps():
    orders = tuple(range(2, 32))
    full = rdp_subsampled_gaussian(1.0, 1.1, orders)
    sub = rdp_subsampled_gaussian(0.05, 1.1, orders)
    assert np.all(sub < full)


def test_edge_cases():
    orders = (2, 4, 8)
    assert np.all(np.isinf(rdp_subsampled_gaussian(0.1, 0.0, orders)))
    np.testing.assert_array_equal(
        rdp_subsampled_gaussian(0.0, 1.0, orders), np.zeros(3))
    with pytest.raises(ValueError, match="q must be"):
        rdp_subsampled_gaussian(1.5, 1.0, orders)
    with pytest.raises(ValueError, match="orders"):
        rdp_subsampled_gaussian(0.5, 1.0, (1,))
    with pytest.raises(ValueError, match="delta"):
        eps_from_rdp(np.ones(3), orders, 2.0)


def test_eps_conversion_matches_hand_computation():
    """One unsubsampled Gaussian step: ε = min_α [α/(2z²) + ln(1/δ)/(α−1)]
    — compute the minimum by brute force and compare."""
    z, delta = 1.0, 1e-5
    acct = RdpAccountant(1.0, z, delta)
    acct.step()
    alphas = np.arange(2, 1025, dtype=np.float64)
    want = np.min(alphas / (2 * z * z)
                  + math.log(1 / delta) / (alphas - 1))
    # DEFAULT_ORDERS is sparser than the brute-force grid — equal when the
    # argmin lands on a shared order, never better
    assert acct.epsilon() == pytest.approx(want, rel=5e-2)
    assert acct.epsilon() >= want - 1e-12


def test_composition_monotonicity():
    acct = RdpAccountant(0.02, 1.1, 1e-5)
    eps = []
    for _ in range(4):
        acct.step(25)
        eps.append(acct.epsilon())
    assert all(b > a for a, b in zip(eps, eps[1:]))
    # more noise -> less privacy spent at the same step count
    quieter = RdpAccountant(0.02, 2.2, 1e-5)
    quieter.step(100)
    assert quieter.epsilon() < eps[-1]
    # fresh accountant spends nothing
    assert RdpAccountant(0.02, 1.1, 1e-5).epsilon() == 0.0


def test_mnist_dpsgd_regime_ballpark():
    """The classic DP-SGD MNIST regime (q=256/60000, z=1.1, 60 epochs,
    δ=1e-5) lands at ε ≈ 3 in every published accountant; assert a
    generous window as a regression guard against formula typos."""
    q = 256 / 60000
    steps = 60 * (60000 // 256)
    acct = RdpAccountant(q, 1.1, 1e-5)
    acct.step(steps)
    assert 1.5 < acct.epsilon() < 4.5, acct.epsilon()


def test_fixed_size_wor_q1_is_replace_one_gaussian():
    """γ=1 (full participation): the WOR bound must equal the plain
    Gaussian RDP at replace-one sensitivity, α/(2·(z/2)²)."""
    from fedml_tpu.core.privacy import rdp_fixed_size_wor
    orders = (2, 3, 8, 32)
    z = 1.4
    got = rdp_fixed_size_wor(1.0, z, orders)
    want = np.asarray(orders) / (2.0 * (z / 2.0) ** 2)
    np.testing.assert_allclose(got, want)


def test_fixed_size_wor_pins_against_poisson_approximation():
    """VERDICT r4 item 7: the fixed-size bound APPLIES to the sampler
    dp_fedavg actually uses and must be CONSERVATIVE relative to the
    Poisson approximation at the same (q, z) — never optimistic.  Both
    stay finite and positive, and the WOR bound never exceeds its own
    unsubsampled replace-one clamp."""
    from fedml_tpu.core.privacy import (rdp_fixed_size_wor,
                                        rdp_subsampled_gaussian)
    orders = tuple(range(2, 40))
    for q, z in ((0.01, 1.1), (0.1, 1.0), (0.3, 2.0)):
        wor = rdp_fixed_size_wor(q, z, orders)
        poi = rdp_subsampled_gaussian(q, z, orders)
        assert np.all(np.isfinite(wor)) and np.all(wor > 0)
        # replace-one sensitivity doubling makes WOR epsilon the larger
        assert np.all(wor >= poi), (q, z)
        clamp = np.asarray(orders) / (2.0 * (z / 2.0) ** 2)
        assert np.all(wor <= clamp + 1e-12)
    # converted epsilons order the same way
    a_p = RdpAccountant(0.05, 1.2, 1e-5)
    a_f = RdpAccountant(0.05, 1.2, 1e-5, sampling="fixed_size_wor")
    a_p.step(50)
    a_f.step(50)
    assert a_f.epsilon() > a_p.epsilon() > 0


def test_fixed_size_wor_edges_and_validation():
    from fedml_tpu.core.privacy import rdp_fixed_size_wor
    assert np.all(rdp_fixed_size_wor(0.0, 1.0) == 0.0)
    assert np.all(np.isinf(rdp_fixed_size_wor(0.1, 0.0)))
    with pytest.raises(ValueError):
        rdp_fixed_size_wor(1.5, 1.0)
    with pytest.raises(ValueError):
        RdpAccountant(0.1, 1.0, 1e-5, sampling="bogus")
