from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
from fedml_tpu.models.norms import Norm
from fedml_tpu.models.resnet import (
    CifarResNet, ImageNetResNet, resnet56, resnet110, resnet18_gn)
from fedml_tpu.models.vgg import (VGG, vgg11, vgg13, vgg16, VGG16Features,
                                  perceptual_loss)
from fedml_tpu.models.mobilenet import (
    MobileNetV1, MobileNetV3, mobilenet, mobilenet_v3)
from fedml_tpu.models.efficientnet import EfficientNet, efficientnet
from fedml_tpu.models.resnet_gkt import GKTClientResNet, GKTServerResNet
from fedml_tpu.models.vfl import (
    VFLFeatureExtractor, VFLClassifier, VFLPartyNet)
from fedml_tpu.models.darts import (
    DARTSSearchNetwork, DARTSEvalNetwork, Genotype, PRIMITIVES,
    init_alphas, parse_genotype,
)
from fedml_tpu.models.gan import (
    Generator, Discriminator, CondGenerator, PatchDiscriminator)
from fedml_tpu.models.segmentation import (
    DeepLabV3Plus, UNet, AlignedXception, ResNetBackbone, ASPP)
from fedml_tpu.models.transformer import TransformerLM, CausalSelfAttention
from fedml_tpu.models.moe import SwitchFFN
