"""Streaming O(1)-memory defended aggregation (ROADMAP item 2).

The stack-then-reduce path (`robust/defense.make_defended_aggregate`
over a ``[cohort, ...]`` host buffer) makes server peak RSS linear in
cohort size — the scaling wall between today's ~8-silo cross-silo path
and the 1k–100k sampled clients of the cross-device north star.
Following "Performance Improvement of FL Server using Smart NIC"
(arXiv 2307.06561), aggregation belongs in the *receive path*: this
module folds each admitted upload into O(model) running state at
arrival, so the barrier-close does one finalize instead of an O(cohort)
reduction, and nothing model-sized is ever held per silo.

Two regimes, chosen by the aggregation rule:

* ``mean`` — an exact streaming fold.  One jit (donate-in-place on the
  accumulator) computes ``acc += clip(update, reference) * w`` per
  arrival; ``finalize`` divides by the folded weight total and adds the
  per-round weak-DP noise.  The fold is arithmetically the SAME
  sequential reduction the stack path's `lax.scan` mean runs over the
  cohort axis, so when uploads fold in slot order the two modes agree
  **bit for bit** (weight-0 slots — dropped stragglers, quarantined or
  rejected silos — contribute an exact ``+0.0`` to the stack scan and
  are simply never folded here).  Memory: O(model), flat in cohort.

* ``krum / coordinate_median / trimmed_mean / multi_krum /
  geometric_median`` — order statistics need a population, so exact
  streaming is impossible.  The trade (documented, bounded): a
  **reservoir** of ``reservoir_k`` slots (Vitter's Algorithm R, seeded)
  holds a uniform sample of the round's admitted uploads; ``finalize``
  runs the unchanged `core/byzantine.py` rule over the static
  ``[K, ...]`` reservoir via `make_defended_aggregate`.  For cohorts
  ``<= K`` the rule sees every upload (exact up to slot order); beyond
  that it sees a uniform K-subsample — the breakdown point degrades
  from f/N to f/K in expectation, so size K to the assumed adversary
  count, not the cohort.  Memory: O(K * model), flat in cohort.

The same object serves three sites: the sync server's admission-accept
path, the async server's delta buffer (``kind="delta"``: clip reference
is zeros), and the edge aggregators of the live multi-level topology
(`algorithms/hierarchical.EdgeAggregatorActor`), which fold their silos'
uploads locally and ship one pre-reduced ``(mean, weight, count)`` edge
to the root.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.pytree import acc_dtype
from fedml_tpu.core.robust import add_gaussian_noise, clip_update
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

STREAM_MODES = ("stream", "stack")


def zeros_acc_like(reference):
    """A fresh fold accumulator for ``reference``: same shapes, leaves
    in `acc_dtype` (floats accumulate in their own dtype, ints in f32).
    Shared with the sharded spine (`fedml_tpu.shard_spine.agg`) — the
    accumulator-dtype contract must stay one definition or the
    sharded-vs-replicated bit-identity pins break."""
    return jax.tree.map(
        lambda r: jnp.zeros(jnp.shape(r), acc_dtype(jnp.asarray(r).dtype)),
        reference)


class StreamingAggregator:
    """O(model)-memory fold-at-arrival defended aggregation.

    Round protocol::

        agg.reset(global_params)          # round open (broadcast)
        agg.fold(upload, num_samples)     # per admitted upload, at arrival
        new_global = agg.finalize(step)   # barrier close

    ``template``: the global params at construction — fixes every shape
    so the fold jit compiles exactly once (``_cache_size() == 1`` across
    rounds is the acceptance pin; register with a `RecompileSentry` via
    ``sentry=``).  ``kind="params"`` clips each upload against the
    round's reference global (the sync servers' semantics);
    ``kind="delta"`` clips against zeros and pads the reservoir with
    zero deltas (the async server's semantics).

    ``donate="auto"``: donate the accumulator buffer to each fold so XLA
    reuses it in place — O(model) steady state with zero per-fold
    allocation off-CPU; CPU backends warn-and-ignore donation, so auto
    keeps it off there (same contract as `make_defended_aggregate`).

    ``device``: a `fedml_tpu.obs.device.DeviceRecorder`; when set, the
    hot fold/finalize jits run behind the observatory's wrappers — each
    compile lands in the round's named compile ledger and every call's
    cost-analysis FLOPs feed the live MFU gauge.  The wrappers forward
    ``_cache_size``, so the jit-once pin holds unchanged.
    """

    def __init__(self, template, *, method: str = "mean",
                 kind: str = "params", norm_clip: float = 0.0,
                 noise_std: float = 0.0, seed: int = 0,
                 reservoir_k: int = 64, trim_frac: float = 0.1,
                 byz_f: int = 0, krum_m: int = 1, gm_iters: int = 8,
                 gm_eps: float = 1e-6, donate="auto", sentry=None,
                 device=None):
        from fedml_tpu.robust.defense import (ROBUST_AGG_METHODS,
                                              make_defended_aggregate)
        if method not in ROBUST_AGG_METHODS:
            raise ValueError(f"unknown streaming aggregation method "
                             f"{method!r}; available: {ROBUST_AGG_METHODS}")
        if kind not in ("params", "delta"):
            raise ValueError(f"kind must be 'params' or 'delta', got {kind!r}")
        if reservoir_k < 1:
            raise ValueError(f"reservoir_k must be >= 1, got {reservoir_k}")
        if norm_clip < 0 or noise_std < 0:
            raise ValueError(f"norm_clip/noise_std must be >= 0, got "
                             f"{norm_clip}/{noise_std}")
        self.method = method
        self.kind = kind
        self.norm_clip = norm_clip
        self.noise_std = noise_std
        self.reservoir_k = reservoir_k
        # the template's structure, kept for state_dict/load_state_dict:
        # a crash-resumed fold rebuilds its trees from flat snapshot
        # leaves without the caller re-supplying the round reference
        self._treedef = jax.tree.structure(template)
        # defended = the label contract obs/perf.py documents: the
        # finalize span is "defended_aggregate" only when a defense
        # actually runs (clip, noise, or a Byzantine rule)
        self.defended = (method != "mean" or norm_clip > 0 or noise_std > 0)
        reg = telemetry.get_registry()
        self._c_folds = reg.counter("fedml_stream_folds_total")
        self._c_evict = reg.counter("fedml_stream_evictions_total")
        self._g_reservoir = reg.gauge("fedml_stream_reservoir_fill_total")
        self._h_finalize = reg.histogram("fedml_stream_finalize_seconds")

        # per-round state
        self._reference = None          # device global (clip reference)
        self._acc = None                # running weighted sum (mean mode)
        self._wsum = None               # running weight total (device f32)
        self.count = 0                  # uploads folded this round
        self.weight_total = 0.0         # host f64 fold-order weight sum:
        #                                 readable AFTER finalize (the
        #                                 device _wsum is donated away
        #                                 there) — the edge frame's
        #                                 num_samples and the health
        #                                 observatory both read it
        self._seen = 0                  # reservoir: uploads offered
        self._res_leaves: Optional[list] = None   # [K, ...] host buffers
        self._res_def = None
        self._res_weights: Optional[np.ndarray] = None
        self._res_rng = np.random.RandomState(seed)

        if method == "mean":
            if donate == "auto":
                donate = jax.default_backend() != "cpu"

            def _fold(acc, wsum, upload, weight, reference):
                if norm_clip > 0:
                    upload = clip_update(upload, reference, norm_clip)
                weight = jnp.asarray(weight, jnp.float32)
                acc = jax.tree.map(
                    lambda a, u: a + u.astype(a.dtype)
                    * weight.astype(a.dtype), acc, upload)
                return acc, wsum + weight

            def _finalize(acc, wsum, reference, step):
                out = jax.tree.map(
                    lambda a, r: (a / wsum.astype(a.dtype)).astype(r.dtype),
                    acc, reference)
                if noise_std > 0:
                    key = jax.random.fold_in(jax.random.key(seed),
                                             jnp.asarray(step, jnp.uint32))
                    out = add_gaussian_noise(out, key, noise_std)
                return out

            def _fold_wave(acc, wsum, stacked, weights, reference):
                # the WAVE fold (cross-device engine): a sequential
                # lax.scan over the wave's slot axis running EXACTLY the
                # per-upload fold body per slot — so a wave-chunked
                # round, a single-wave round, and per-upload folds of
                # the same updates in slot order all land bit-identical
                # accumulators (weight-0 padded slots contribute an
                # exact +0.0, the stack-scan convention)
                def body(carry, xs):
                    a, ws = carry
                    upload, weight = xs
                    if norm_clip > 0:
                        upload = clip_update(upload, reference, norm_clip)
                    a = jax.tree.map(
                        lambda ai, ui: ai + ui.astype(ai.dtype)
                        * weight.astype(ai.dtype), a, upload)
                    return (a, ws + weight), None
                (acc, wsum), _ = jax.lax.scan(
                    body, (acc, wsum), (stacked, weights))
                return acc, wsum

            self._fold_fn = jax.jit(
                _fold, donate_argnums=(0, 1) if donate else ())
            self._fold_wave_fn = jax.jit(
                _fold_wave, donate_argnums=(0, 1) if donate else ())
            self._finalize_fn = jax.jit(_finalize)
            if device is not None:
                # per-arrival hot path: every fold call feeds the
                # compile ledger + FLOPs accounting (wrapper forwards
                # the _cache_size probe, so the jit-once pin holds).
                # Signatures note under the SENTRY's registration name
                # (stream_agg[...], the aggregator itself below) so a
                # firing verdict can name the shape that changed; the
                # mean finalize has a different arg shape and is not the
                # sentry-monitored cache, so it feeds no signatures.
                self._fold_fn = device.instrument(
                    f"stream_fold[{method}]", self._fold_fn, sentry=sentry,
                    sentry_name=f"stream_agg[{method}]")
                self._fold_wave_fn = device.instrument(
                    f"stream_fold_wave[{method}]", self._fold_wave_fn,
                    sentry=sentry, sentry_name=f"stream_agg[{method}]")
                self._finalize_fn = device.instrument(
                    f"stream_finalize[{method}]", self._finalize_fn)
            self._hot_jit = self._fold_fn
        else:
            # order-statistic rules fold per upload into the reservoir
            # only — a pre-summed wave has no per-client population
            self._fold_wave_fn = None
            # reservoir regime: the bounded stack IS the memory bound;
            # the finalize reuses the one-jit defended aggregate over the
            # static [K, ...] shape, so clip + rule + noise stay one
            # compile across rounds exactly like stack mode
            self._finalize_fn = make_defended_aggregate(
                method, trim_frac=trim_frac, byz_f=byz_f, krum_m=krum_m,
                gm_iters=gm_iters, gm_eps=gm_eps, norm_clip=norm_clip,
                noise_std=noise_std, seed=seed, donate=donate)
            if device is not None:
                # the reservoir finalize IS the sentry-monitored cache
                # (self._hot_jit): signatures land under the registered
                # stream_agg name so its verdicts carry the diff too
                self._finalize_fn = device.instrument(
                    f"stream_finalize[{method}]", self._finalize_fn,
                    sentry=sentry, sentry_name=f"stream_agg[{method}]")
            self._hot_jit = self._finalize_fn
        if sentry is not None:
            sentry.register(f"stream_agg[{method}]", self)

    # -- recompile-sentry probe (PerfRecorder.register_jit contract) ----------
    def _cache_size(self) -> int:
        n = int(self._hot_jit._cache_size())
        if self._fold_wave_fn is not None \
                and self._fold_wave_fn is not self._hot_jit:
            # the wave fold is part of the same monitored hot family: an
            # uncalled jit contributes 0, so per-upload-only rounds keep
            # the historical cache==1 pin and wave-only rounds read 1 too
            n += int(self._fold_wave_fn._cache_size())
        return n

    # -- crash consistency (utils/journal.py) --------------------------------
    @property
    def reference(self):
        """The round's clip reference (None between rounds) — the edge
        actors' resume path reads the restored round global here."""
        return self._reference

    def state_dict(self, include_reference: bool = False) -> dict:
        """Host snapshot of the MEAN fold state — the payload of the
        round journal's periodic durable snapshot.  Bit-exact contract:
        the accumulator leaves round-trip through numpy in their own
        ``acc_dtype``, ``wsum`` stays f32, so a restored fold continues
        the exact sequential reduction the uncrashed run would have.
        Reservoir (order-statistic) rules refuse: the Algorithm-R draw
        stream is not part of the durable contract — those rounds are
        abort-only (journal ``resumable=False``)."""
        if self.method != "mean":
            raise RuntimeError(
                f"state_dict: only the streaming MEAN fold snapshots; "
                f"{self.method!r} rounds are abort-only on crash")
        out = {
            "acc": (None if self._acc is None else
                    [np.asarray(l) for l in jax.tree.leaves(self._acc)]),
            "wsum": (np.float32(0.0) if self._wsum is None
                     else np.asarray(self._wsum, np.float32)[()]),
            "count": int(self.count),
            "weight_total": float(self.weight_total)}
        if include_reference:
            # edge actors snapshot the reference too: a respawned edge
            # has no live root sync to re-learn the round global from
            out["reference"] = [np.asarray(l)
                                for l in jax.tree.leaves(self._reference)]
        return out

    def load_state_dict(self, state: dict) -> None:
        """Restore a `state_dict` snapshot mid-round.  When the snapshot
        carries a ``reference`` the round is re-opened from it; otherwise
        the caller must have ``reset()`` the round first (the sync
        server restores the reference from its checkpointed global)."""
        if self.method != "mean":
            raise RuntimeError("load_state_dict: reservoir rounds are "
                               "abort-only; nothing to restore")
        if state.get("reference") is not None:
            self.reset(jax.tree.unflatten(
                self._treedef,
                [jnp.asarray(a) for a in state["reference"]]))
        if self._reference is None:
            raise RuntimeError("load_state_dict before reset(): the "
                               "round's clip reference is not set and "
                               "the snapshot carries none")
        if state.get("acc") is not None:
            self._acc = jax.tree.unflatten(
                jax.tree.structure(self._reference),
                [jnp.asarray(a) for a in state["acc"]])
            self._wsum = jnp.float32(state["wsum"])
        self.count = int(state["count"])
        self.weight_total = float(state["weight_total"])

    # -- round lifecycle -----------------------------------------------------
    def reset(self, reference) -> None:
        """Open a round against ``reference`` (the current global).  The
        reference is normalized to device arrays ONCE here — numpy
        round-0 globals and later jax outputs must key one jit entry,
        not two (the PR 5 double-compile class).  ``kind="delta"``
        replaces it with a cached zeros tree: async deltas clip against
        zero (clipping a delta against zero IS norm-clipping the delta)
        and pad with zero updates."""
        if self.kind == "delta":
            if self._reference is None:
                self._reference = jax.tree.map(
                    lambda r: jnp.zeros_like(jnp.asarray(r)), reference)
        else:
            self._reference = jax.tree.map(jnp.asarray, reference)
        self._acc = self._wsum = None
        self.count = 0
        self.weight_total = 0.0
        self._seen = 0
        if self._res_weights is not None:
            self._res_weights[:] = 0.0
        self._g_reservoir.set(0)

    def _pad_template(self):
        """What an unfolded reservoir slot holds: the reference — the
        current global for params kind (the zero diff every rule masks
        out), zeros for delta kind (reset already zeroed it)."""
        return jax.tree.map(np.asarray, self._reference)

    def _ensure_reservoir(self) -> None:
        if self._res_leaves is not None:
            return
        pad = self._pad_template()
        self._res_def = jax.tree.structure(pad)
        k = self.reservoir_k
        self._res_stack = jax.tree.map(
            lambda l: np.empty((k,) + np.shape(l), np.asarray(l).dtype), pad)
        self._res_leaves = jax.tree.leaves(self._res_stack)
        for buf, leaf in zip(self._res_leaves, jax.tree.leaves(pad)):
            buf[:] = np.asarray(leaf)
        self._res_weights = np.zeros(k, np.float32)

    def fold(self, upload, weight) -> None:
        """Fold one ADMITTED upload at arrival.  O(model) work, O(model)
        (mean) or O(K*model) (reservoir) standing memory — never a
        function of how many silos the round samples."""
        if self._reference is None:
            raise RuntimeError("fold() before reset(): the round's clip "
                               "reference is not set")
        if self.method != "mean":
            # validate BEFORE counting or drawing: a malformed upload
            # must fail loudly on every arrival, not only when it wins
            # an Algorithm-R slot (the mean fold's jit raises on its own
            # structure mismatch)
            self._ensure_reservoir()
            if jax.tree.structure(upload) != self._res_def:
                raise ValueError("upload does not match the aggregation "
                                 "template (treedef mismatch)")
        self._c_folds.inc()
        self.count += 1
        self.weight_total += float(weight)
        if self.method == "mean":
            if self._acc is None:
                self._acc = zeros_acc_like(self._reference)
                self._wsum = jnp.float32(0.0)
            self._acc, self._wsum = self._fold_fn(
                self._acc, self._wsum, upload, np.float32(weight),
                self._reference)
            return
        # reservoir regime (Algorithm R): the first K admitted uploads
        # fill slots; upload i > K replaces a uniform slot with
        # probability K/i — every admitted upload is in the reservoir
        # with equal probability K/n at round close
        self._seen += 1
        if self._seen <= self.reservoir_k:
            slot = self._seen - 1
        else:
            slot = int(self._res_rng.randint(self._seen))
            if slot >= self.reservoir_k:
                self._c_evict.inc()  # the arriving upload is the eviction
                return
            self._c_evict.inc()
        for buf, leaf in zip(self._res_leaves, jax.tree.leaves(upload)):
            buf[slot] = np.asarray(leaf)
        self._res_weights[slot] = np.float32(weight)
        self._g_reservoir.set(int((self._res_weights > 0).sum()))

    def fold_wave(self, stacked, weights) -> None:
        """Fold one compiled WAVE's stacked client updates at wave
        completion (the cross-device engine's seam): a device-side
        sequential scan over the ``[wave, ...]`` slot axis running the
        per-upload fold body per slot, so the fold order is the global
        cohort-slot order regardless of wave boundaries — wave-chunked,
        single-wave, and per-upload folds of the same updates land
        BIT-IDENTICAL accumulators.  Weight-0 padded slots contribute an
        exact ``+0.0`` (and do not count as folds); a wave of ALL pad
        slots folds as weight 0 instead of perturbing the normalizer.
        Standing memory stays O(model) — the wave stack is the caller's
        static device buffer, never banked here."""
        if self._reference is None:
            raise RuntimeError("fold_wave() before reset(): the round's "
                               "clip reference is not set")
        if self.method != "mean":
            raise RuntimeError(
                f"fold_wave: only the streaming MEAN folds pre-stacked "
                f"waves; order-statistic rules ({self.method!r}) need the "
                f"per-client population — fold() each upload into the "
                f"reservoir instead")
        w_host = np.asarray(weights, np.float32)
        live = int((w_host > 0).sum())
        if self._acc is None:
            self._acc = zeros_acc_like(self._reference)
            self._wsum = jnp.float32(0.0)
        self._acc, self._wsum = self._fold_wave_fn(
            self._acc, self._wsum, stacked,
            jnp.asarray(weights, jnp.float32), self._reference)
        self._c_folds.inc(live)
        self.count += live
        # slot-order sequential host adds — the per-upload path's exact
        # weight_total arithmetic (np.sum's pairwise order would differ)
        for w in w_host:
            self.weight_total += float(w)

    def finalize(self, step):
        """Close the round: the streamed mean (or the reservoir's robust
        rule) against the reset-time reference, noise folded by ``step``.
        Callers must guard the zero-fold round (skip aggregation) —
        same contract as `make_defended_aggregate` weights."""
        if self.count == 0:
            raise RuntimeError("finalize() with no folded uploads; the "
                               "caller must skip aggregation on an empty "
                               "round")
        import time
        t0 = time.perf_counter()
        if self.method == "mean":
            out = self._finalize_fn(self._acc, self._wsum, self._reference,
                                    step)
            # the accumulator was (possibly) donated; drop our handle so
            # a stale buffer is never folded into the next round
            self._acc = self._wsum = None
        else:
            out = self._finalize_fn(self._reference, self._res_stack,
                                    self._res_weights.copy(), step)
        self._h_finalize.observe(time.perf_counter() - t0)
        return out
