"""TFF-exported HDF5 federated datasets.

Covers the four h5-backed loaders of the reference (all use the group layout
``examples/<client_id>/<field>``):

* FederatedEMNIST — fields ``pixels`` [n,28,28] float, ``label`` int;
  3400 clients (``FederatedEMNIST/data_loader.py:15-49``).
* fed_cifar100 — ``image`` [n,32,32,3] uint8, ``label``; 500 train /
  100 test clients; train preprocessing = RandomCrop(24)+flip+normalize,
  test = CenterCrop(24) (``fed_cifar100/data_loader.py:17-51``,
  ``fed_cifar100/utils.py:8-24``).  We keep images at 32×32 here and do the
  24×24 crop on-device (`augment.fed_cifar100_train_augment` for train,
  `augment.fed_cifar100_eval_transform` for test).
* fed_shakespeare — ``snippets`` byte strings; 715 clients; char-encoded to
  80-token windows (``fed_shakespeare/data_loader.py:16-60``).
* stackoverflow nwp/lr — ``tokens``/``title``/``tags`` byte strings; 342,477
  clients; nwp = next-word ids at seq len 20, lr = 10k bag-of-words +
  500-tag multi-hot (``stackoverflow_nwp/dataset.py:20-49``,
  ``stackoverflow_lr/dataset.py:21-59``).

Every loader accepts ``max_clients`` because materializing 342k clients is a
host-memory decision, not a format one; and every loader has a hermetic
``fake_*_h5`` twin that writes a tiny format-identical file for tests.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .stacking import FederatedData, stack_client_data, batch_global
from .text import (CharVocab, WordVocab, SHAKESPEARE_SEQ_LEN,
                   bag_of_words, multi_hot_tags, split_next_word)

_EXAMPLES = "examples"

FEMNIST_TRAIN_FILE = "fed_emnist_train.h5"
FEMNIST_TEST_FILE = "fed_emnist_test.h5"
FED_CIFAR100_TRAIN_FILE = "fed_cifar100_train.h5"
FED_CIFAR100_TEST_FILE = "fed_cifar100_test.h5"
FED_SHAKESPEARE_TRAIN_FILE = "shakespeare_train.h5"
FED_SHAKESPEARE_TEST_FILE = "shakespeare_test.h5"
STACKOVERFLOW_TRAIN_FILE = "stackoverflow_train.h5"
STACKOVERFLOW_TEST_FILE = "stackoverflow_test.h5"


def _h5():
    import h5py
    return h5py


def _client_ids(h5file, max_clients: Optional[int]) -> List[str]:
    ids = list(h5file[_EXAMPLES].keys())
    return ids[:max_clients] if max_clients else ids


def _per_client_arrays(path: str, fields: Sequence[str],
                       max_clients: Optional[int]) -> List[Dict[str, np.ndarray]]:
    with _h5().File(path, "r") as f:
        out = []
        for cid in _client_ids(f, max_clients):
            g = f[_EXAMPLES][cid]
            out.append({k: np.asarray(g[k][()]) for k in fields})
    return out


def _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size, class_num
              ) -> FederatedData:
    train = stack_client_data(xs_tr, ys_tr, batch_size)
    test = stack_client_data(xs_te, ys_te, batch_size)
    cat = lambda parts: np.concatenate([p for p in parts if len(p)])
    return FederatedData(
        client_num=len(xs_tr), class_num=class_num, train=train, test=test,
        train_global=batch_global(cat(xs_tr), cat(ys_tr), batch_size),
        test_global=batch_global(cat(xs_te), cat(ys_te), batch_size))


def load_federated_emnist(data_dir: str, batch_size: int = 20,
                          max_clients: Optional[int] = None) -> FederatedData:
    """62-class FEMNIST; pixels already in [0,1] floats (TFF export)."""
    def read(path):
        xs, ys = [], []
        for g in _per_client_arrays(path, ("pixels", "label"), max_clients):
            xs.append(g["pixels"].reshape(-1, 28, 28, 1).astype(np.float32))
            ys.append(g["label"].reshape(-1).astype(np.int32))
        return xs, ys

    xs_tr, ys_tr = read(os.path.join(data_dir, FEMNIST_TRAIN_FILE))
    xs_te, ys_te = read(os.path.join(data_dir, FEMNIST_TEST_FILE))
    return _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size, class_num=62)


def load_fed_cifar100(data_dir: str, batch_size: int = 20,
                      max_clients: Optional[int] = None) -> FederatedData:
    """100-class fed CIFAR; stored uint8 HWC — we scale to [0,1] float32 and
    leave crop/flip/normalize to the on-device augment pipeline (the
    reference bakes them into the loader, fed_cifar100/utils.py:28-37)."""
    def read(path):
        xs, ys = [], []
        for g in _per_client_arrays(path, ("image", "label"), max_clients):
            xs.append(g["image"].reshape(-1, 32, 32, 3)
                      .astype(np.float32) / 255.0)
            ys.append(g["label"].reshape(-1).astype(np.int32))
        return xs, ys

    xs_tr, ys_tr = read(os.path.join(data_dir, FED_CIFAR100_TRAIN_FILE))
    xs_te, ys_te = read(os.path.join(data_dir, FED_CIFAR100_TEST_FILE))
    return _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size, class_num=100)


def load_fed_shakespeare(data_dir: str, batch_size: int = 4,
                         max_clients: Optional[int] = None) -> FederatedData:
    """Char LM over 90-symbol vocab; each snippet becomes 81-wide windows
    split into (x, y) by shift-by-one."""
    vocab = CharVocab()

    def read(path):
        xs, ys = [], []
        for g in _per_client_arrays(path, ("snippets",), max_clients):
            wins = []
            for snip in g["snippets"].reshape(-1):
                text = snip.decode("utf8") if isinstance(snip, bytes) else str(snip)
                wins.extend(vocab.encode_snippet(text))
            w = (np.stack(wins) if wins
                 else np.zeros((0, SHAKESPEARE_SEQ_LEN + 1), np.int32))
            d = split_next_word(w)
            xs.append(d["x"])
            ys.append(d["y"])
        return xs, ys

    xs_tr, ys_tr = read(os.path.join(data_dir, FED_SHAKESPEARE_TRAIN_FILE))
    xs_te, ys_te = read(os.path.join(data_dir, FED_SHAKESPEARE_TEST_FILE))
    return _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size,
                     class_num=vocab.vocab_size)


def load_stackoverflow_nwp(data_dir: str, batch_size: int = 16,
                           max_clients: Optional[int] = 1000,
                           vocab_size: int = 10000,
                           seq_len: int = 20) -> FederatedData:
    """Next-word prediction: each sentence -> 21 ids, split into x/y by
    shift (stackoverflow_nwp/utils.py:56-95).  max_clients defaults to 1000 —
    loading all 342k clients' text eagerly is a deliberate opt-in."""
    vocab = WordVocab.from_word_count_file(
        os.path.join(data_dir, "stackoverflow.word_count"), vocab_size)

    def read(path):
        xs, ys = [], []
        for g in _per_client_arrays(path, ("tokens",), max_clients):
            rows = [vocab.encode_sentence(
                        t.decode("utf8") if isinstance(t, bytes) else str(t),
                        seq_len)
                    for t in g["tokens"].reshape(-1)]
            w = (np.stack(rows) if rows
                 else np.zeros((0, seq_len + 1), np.int32))
            d = split_next_word(w)
            xs.append(d["x"])
            ys.append(d["y"])
        return xs, ys

    xs_tr, ys_tr = read(os.path.join(data_dir, STACKOVERFLOW_TRAIN_FILE))
    xs_te, ys_te = read(os.path.join(data_dir, STACKOVERFLOW_TEST_FILE))
    return _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size,
                     class_num=vocab.vocab_size)


def load_stackoverflow_lr(data_dir: str, batch_size: int = 10,
                          max_clients: Optional[int] = 1000,
                          vocab_size: int = 10000, tag_size: int = 500
                          ) -> FederatedData:
    """Tag prediction: x = normalized 10k BoW over tokens+title, y = 500-dim
    multi-hot tags (stackoverflow_lr/dataset.py:55-63)."""
    from .text import load_tag_dict
    words = WordVocab.from_word_count_file(
        os.path.join(data_dir, "stackoverflow.word_count"), vocab_size)
    word_dict = {w: i for i, w in enumerate(words._ids)}  # 0-based BoW index
    tag_dict = load_tag_dict(
        os.path.join(data_dir, "stackoverflow.tag_count"), tag_size)

    def read(path):
        xs, ys = [], []
        for g in _per_client_arrays(path, ("tokens", "title", "tags"),
                                    max_clients):
            dec = lambda a: [v.decode("utf8") if isinstance(v, bytes)
                             else str(v) for v in a.reshape(-1)]
            sents = [" ".join(p) for p in zip(dec(g["tokens"]),
                                              dec(g["title"]))]
            xs.append(bag_of_words(sents, word_dict))
            ys.append(multi_hot_tags(dec(g["tags"]), tag_dict))
        return xs, ys

    xs_tr, ys_tr = read(os.path.join(data_dir, STACKOVERFLOW_TRAIN_FILE))
    xs_te, ys_te = read(os.path.join(data_dir, STACKOVERFLOW_TEST_FILE))
    return _assemble(xs_tr, ys_tr, xs_te, ys_te, batch_size,
                     class_num=tag_size)


# ---------------------------------------------------------------------------
# Hermetic fixtures: format-identical tiny h5 files for tests / air-gapped CI.

def fake_femnist_h5(data_dir: str, num_clients: int = 4,
                    samples: int = 12, seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    for fname, n in ((FEMNIST_TRAIN_FILE, samples),
                     (FEMNIST_TEST_FILE, max(2, samples // 4))):
        with _h5().File(os.path.join(data_dir, fname), "w") as f:
            for c in range(num_clients):
                g = f.create_group(f"{_EXAMPLES}/f{c:04d}")
                g.create_dataset("pixels", data=rng.rand(n, 28, 28)
                                 .astype(np.float32))
                g.create_dataset("label", data=rng.randint(0, 62, (n, 1)))


def fake_fed_cifar100_h5(data_dir: str, num_clients: int = 4,
                         samples: int = 10, seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    for fname, n in ((FED_CIFAR100_TRAIN_FILE, samples),
                     (FED_CIFAR100_TEST_FILE, max(2, samples // 4))):
        with _h5().File(os.path.join(data_dir, fname), "w") as f:
            for c in range(num_clients):
                g = f.create_group(f"{_EXAMPLES}/c{c:04d}")
                g.create_dataset("image", data=rng.randint(
                    0, 256, (n, 32, 32, 3), dtype=np.uint8))
                g.create_dataset("label", data=rng.randint(0, 100, (n, 1)))


def fake_fed_shakespeare_h5(data_dir: str, num_clients: int = 3,
                            seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    words = ["to be or not to be ", "all the world's a stage ",
             "once more unto the breach "]
    for fname in (FED_SHAKESPEARE_TRAIN_FILE, FED_SHAKESPEARE_TEST_FILE):
        with _h5().File(os.path.join(data_dir, fname), "w") as f:
            for c in range(num_clients):
                g = f.create_group(f"{_EXAMPLES}/s{c:04d}")
                snips = [(words[rng.randint(len(words))] * rng.randint(3, 9))
                         .encode("utf8") for _ in range(rng.randint(1, 4))]
                g.create_dataset("snippets", data=snips)


def fake_stackoverflow_h5(data_dir: str, num_clients: int = 3,
                          vocab_size: int = 50, tag_size: int = 8,
                          seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    vocab = [f"word{i}" for i in range(vocab_size)]
    tags = [f"tag{i}" for i in range(tag_size)]
    with open(os.path.join(data_dir, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(vocab):
            f.write(f"{w} {vocab_size - i}\n")
    import json
    with open(os.path.join(data_dir, "stackoverflow.tag_count"), "w") as f:
        json.dump({t: tag_size - i for i, t in enumerate(tags)}, f)
    for fname in (STACKOVERFLOW_TRAIN_FILE, STACKOVERFLOW_TEST_FILE):
        with _h5().File(os.path.join(data_dir, fname), "w") as f:
            for c in range(num_clients):
                g = f.create_group(f"{_EXAMPLES}/u{c:06d}")
                n = rng.randint(2, 6)
                sent = lambda: " ".join(
                    vocab[rng.randint(vocab_size)]
                    for _ in range(rng.randint(3, 15))).encode("utf8")
                g.create_dataset("tokens", data=[sent() for _ in range(n)])
                g.create_dataset("title", data=[sent() for _ in range(n)])
                g.create_dataset("tags", data=[
                    "|".join(tags[rng.randint(tag_size)]
                             for _ in range(rng.randint(1, 3))).encode("utf8")
                    for _ in range(n)])
