"""Run-report merger: metrics.jsonl + telemetry snapshot + round traces
→ one human-readable per-round timeline (CLI: ``scripts/obs_report.py``).

The three observability streams land in different files with different
shapes (wandb-style events, Prometheus-style series, Perfetto-style
spans).  Debugging a slow or faulty federation needs them TOGETHER:
"round 3 took 9s" (trace) next to "silo 2 retried 14 sends" (telemetry)
next to "test_acc dropped" (metrics).  This module reads whatever subset
exists and renders it; every section degrades to absence, so the report
works on a crashed run (atomic summary.json + whatever trace files were
exported) as well as a finished one.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Dict, List, Optional

# -- loaders (each tolerates absence) ----------------------------------------


def load_jsonl(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn final line of a crashed run
    return out


def load_json(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_trace_events(trace_dir: Optional[str],
                      include_meta: bool = False) -> List[dict]:
    """Merge every process's exported span file in ``trace_dir`` (the
    multi-process stitch: each gRPC silo exports its own).  Span ("X")
    events only by default; ``include_meta`` keeps the ``process_name``
    metadata Perfetto uses to label node tracks."""
    if not trace_dir:
        return []
    events: List[dict] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "*.json"))):
        try:
            data = load_json(path)
        except json.JSONDecodeError:
            continue
        if isinstance(data, dict):
            data = data.get("traceEvents", [])
        if isinstance(data, list):
            events.extend(e for e in data if isinstance(e, dict))
    keep = ("X", "M") if include_meta else ("X",)
    # dedupe across files — the same invariant trace.py enforces
    # in-process: one event per span id.  This also makes the loader
    # idempotent when a --merge_trace output was written INTO trace_dir
    # (it would otherwise re-glob and double every span), and collapses
    # duplicate process_name metadata from multiple exporters.
    seen, uniq = set(), []
    for e in events:
        if e.get("ph") not in keep:
            continue
        if e["ph"] == "M":
            key = ("M", e.get("pid"), e.get("name"),
                   json.dumps(e.get("args"), sort_keys=True))
        else:
            span_id = (e.get("args") or {}).get("span_id")
            key = ("X", span_id) if span_id is not None else ("X", id(e))
        if key in seen:
            continue
        seen.add(key)
        uniq.append(e)
    return uniq


def merge_traces(trace_dir: str, out_path: str) -> Optional[int]:
    """Write one combined Perfetto file from all per-process exports;
    returns the span count (load it at ui.perfetto.dev).  A missing or
    empty trace dir returns None WITHOUT writing: a zero-span merged
    file would read as "traced, and nothing happened" when the truth is
    "nothing was traced"."""
    events = load_trace_events(trace_dir, include_meta=True)
    if not any(e["ph"] == "X" for e in events):
        return None
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return sum(1 for e in events if e["ph"] == "X")


# -- round timeline ----------------------------------------------------------


def group_round_traces(events: List[dict]) -> List[dict]:
    """Group span events by trace id; one entry per federated round (or
    async version), ordered by start time."""
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid is not None:
            by_trace.setdefault(tid, []).append(e)
    rounds = []
    for tid, evs in by_trace.items():
        evs.sort(key=lambda e: e.get("ts", 0))
        t0 = min(e["ts"] for e in evs)
        t1 = max(e["ts"] + e.get("dur", 0) for e in evs)
        root = next((e for e in evs
                     if not (e.get("args") or {}).get("parent_id")), evs[0])
        rounds.append({"trace_id": tid, "t0": t0, "total_s": (t1 - t0) / 1e6,
                       "root": root, "events": evs})
    rounds.sort(key=lambda r: r["t0"])
    return rounds


def _timeline_lines(trace: dict) -> List[str]:
    """Indented span tree for one round: depth from the parent chain,
    siblings ordered by start time."""
    evs = trace["events"]
    by_id = {(e.get("args") or {}).get("span_id"): e for e in evs}
    children: Dict[Optional[str], List[dict]] = {}
    for e in evs:
        args = e.get("args") or {}
        parent = args.get("parent_id")
        if parent not in by_id:
            parent = None  # orphan (e.g. exporter missing one process)
        children.setdefault(parent, []).append(e)
    lines: List[str] = []

    def walk(parent_id: Optional[str], depth: int) -> None:
        for e in sorted(children.get(parent_id, []),
                        key=lambda x: x.get("ts", 0)):
            args = e.get("args") or {}
            rel_ms = (e["ts"] - trace["t0"]) / 1e3
            lines.append(f"  {'  ' * depth}{e['name']:<12s} "
                        f"node={args.get('node', '?'):<4} "
                        f"+{rel_ms:8.1f}ms  {e.get('dur', 0) / 1e6:8.4f}s")
            walk(args.get("span_id"), depth + 1)

    walk(None, 0)
    return lines


# -- perf ledger section -----------------------------------------------------


def _perf_lines(rows: List[dict]) -> List[str]:
    """Per-round flight-recorder table from ``perf.jsonl`` rows (phase
    breakdown in ms + RSS watermark + recompile count), plus a summary
    line.  Phases are columns, union across rounds — a round missing a
    phase (checkpoint gated off) renders '-'."""
    phases = sorted({p for r in rows for p in (r.get("phases") or {})})
    out = ["  " + "  ".join(
        [f"{'round':>6s}", f"{'total_ms':>9s}"]
        + [f"{p[:14]:>14s}" for p in phases]
        + [f"{'rss_peak_mb':>11s}", f"{'recomp':>6s}"])]
    for r in rows:
        ph = r.get("phases") or {}
        rss = (r.get("rss") or {}).get("peak_bytes")
        cells = [f"{str(r.get('round', '?')):>6s}",
                 f"{r['round_s'] * 1e3:9.1f}" if r.get("round_s") is not None
                 else f"{'-':>9s}"]
        cells += [f"{ph[p] * 1e3:14.2f}" if p in ph else f"{'-':>14s}"
                  for p in phases]
        cells.append(f"{rss / 2 ** 20:11.1f}" if rss is not None
                     else f"{'-':>11s}")
        cells.append(f"{r.get('recompiles', 0):>6d}")
        out.append("  " + "  ".join(cells))
    late = [r for r in rows[1:] if r.get("recompiles")]
    rss_peaks = [(r.get("rss") or {}).get("peak_bytes") for r in rows]
    rss_peaks = [b for b in rss_peaks if b is not None]
    out.append(
        f"  {len(rows)} round(s); "
        + (f"peak RSS {max(rss_peaks) / 2 ** 20:.1f} MiB; "
           if rss_peaks else "no RSS watermark (no /proc); ")
        + (f"RECOMPILES after the baseline round in "
           f"{len(late)} round(s) — a hot function is retracing"
           if late else "recompiles after the baseline round: 0"))
    return out


# -- device observatory section ----------------------------------------------


def _device_lines(rows: List[dict]) -> List[str]:
    """Per-round device table from the perf ledger's ``device`` sections
    (obs/device.py): memory in-use/watermark (summed across devices),
    compile-ledger entries, achieved FLOP/s and MFU — plus a summary
    naming every compile with its wall time.  Rounds without a device
    section render '-' (the observatory is additive)."""
    def mb(v):
        return f"{v / 2 ** 20:10.1f}" if v is not None else f"{'-':>10s}"

    out = ["  " + "  ".join(
        [f"{'round':>6s}", f"{'mem_mb':>10s}", f"{'mem_peak_mb':>11s}",
         f"{'devs':>4s}", f"{'compiles':>8s}", f"{'compile_ms':>10s}",
         f"{'mfu':>9s}"])]
    all_compiles: List[dict] = []
    backend = None
    sources = set()
    for r in rows:
        dev = r.get("device")
        if not isinstance(dev, dict):
            continue
        backend = dev.get("backend") or backend
        mem = dev.get("memory") or []
        in_use = [e.get("bytes_in_use") for e in mem]
        in_use = [b for b in in_use if b is not None]
        peaks = [e.get("round_peak_bytes") or e.get("peak_bytes")
                 or e.get("bytes_in_use") for e in mem]
        peaks = [b for b in peaks if b is not None]
        sources.update(e.get("source") for e in mem if e.get("source"))
        comps = dev.get("compiles") or []
        all_compiles.extend(comps)
        compile_s = sum(float(e.get("wall_s") or 0.0) for e in comps)
        mfu = dev.get("mfu")
        out.append("  " + "  ".join(
            [f"{str(r.get('round', '?')):>6s}",
             mb(sum(in_use) if in_use else None),
             mb(max(peaks) if peaks else None)[:11].rjust(11),
             f"{len(mem) if mem else 0:>4d}",
             f"{len(comps):>8d}",
             f"{compile_s * 1e3:10.1f}" if comps else f"{'-':>10s}",
             f"{mfu:9.2e}" if isinstance(mfu, (int, float))
             else f"{'-':>9s}"]))
    head = f"  backend {backend or '?'}"
    if sources:
        head += f"; memory via {'/'.join(sorted(sources))}"
    head += (f"; {len(all_compiles)} compile(s) totalling "
             f"{sum(float(e.get('wall_s') or 0.0) for e in all_compiles) * 1e3:.1f}ms"
             if all_compiles else "; no compiles ledgered")
    out.append(head)
    for e in all_compiles:
        out.append(f"    compile {e.get('fn', '?'):<28s} "
                   f"{float(e.get('wall_s') or 0.0) * 1e3:8.1f}ms  "
                   f"{e.get('signature', '')[:48]}")
    return out


# -- critical-path section ---------------------------------------------------


def _critical_path_lines(rows: List[dict]) -> List[str]:
    """Per-round binding-constraint table from the perf ledger's
    ``critical_path`` records (obs/critical_path.py): what the round was
    actually waiting on, the wall-clock attribution shares, coverage,
    and the fold-overlap ratio — plus a summary naming the dominant
    constraint across the run."""
    out = ["  " + "  ".join(
        [f"{'round':>6s}", f"{'binding':>12s}", f"{'uploads':>7s}",
         f"{'coverage':>8s}", f"{'fold_ovl':>8s}",
         "attribution (top shares)"])]
    tally: dict = {}
    for r in rows:
        cp = r.get("critical_path")
        if not isinstance(cp, dict):
            continue
        binding = str(cp.get("binding", "?"))
        tally[binding] = tally.get(binding, 0) + 1
        attr = cp.get("attribution") or {}
        round_s = cp.get("round_s") or 0.0
        top = sorted(attr.items(), key=lambda kv: -kv[1])[:3]
        shares = "  ".join(
            f"{k}={v * 1e3:.1f}ms"
            + (f" ({v / round_s:.0%})" if round_s else "")
            for k, v in top)
        ovl = cp.get("fold_overlap_ratio")
        out.append("  " + "  ".join(
            [f"{str(r.get('round', '?')):>6s}", f"{binding:>12s}",
             f"{cp.get('uploads', 0):>7d}",
             f"{cp.get('coverage', 0.0):8.3f}",
             f"{ovl:8.2f}" if isinstance(ovl, (int, float))
             else f"{'-':>8s}", shares]))
    if tally:
        dominant = max(tally.items(), key=lambda kv: kv[1])
        out.append(f"  binding constraint: {dominant[0]} in "
                   f"{dominant[1]}/{sum(tally.values())} round(s) "
                   f"({', '.join(f'{k}={v}' for k, v in sorted(tally.items()))})")
    return out


# -- health ledger section ---------------------------------------------------


def _health_lines(rows: List[dict]) -> List[str]:
    """Per-round learning-health table from ``health.jsonl`` rows, plus
    a per-edge rollup table when the run carried the multi-level
    topology, plus an alarm summary line."""
    def num(v, spec="8.4f", width=8):
        return f"{v:{spec}}" if isinstance(v, (int, float)) \
            else f"{'-':>{width}s}"

    out = ["  " + "  ".join(
        [f"{'round':>6s}", f"{'up':>4s}", f"{'acc':>4s}", f"{'rej':>4s}",
         f"{'drop':>4s}", f"{'norm_mean':>10s}", f"{'norm_cv':>8s}",
         f"{'align':>8s}", f"{'gdelta':>9s}", "alarms"])]
    fired_total = 0
    for r in rows:
        norm = r.get("norm") or {}
        align = r.get("alignment") or {}
        alarms = r.get("alarms") or {}
        fired = sorted(a for a, v in alarms.items() if not v.get("ok"))
        fired_total += len(fired)
        mean = norm.get("mean")
        std = norm.get("std")
        cv = (std / mean) if mean and std is not None else None
        out.append("  " + "  ".join(
            [f"{str(r.get('round', '?')):>6s}",
             f"{r.get('uploads', 0):>4d}", f"{r.get('accepted', 0):>4d}",
             f"{r.get('rejected', 0):>4d}", f"{r.get('dropped', 0):>4d}",
             num(mean, "10.4f", 10), num(cv, "8.3f", 8),
             num((align.get("mean")), "8.4f", 8),
             num(r.get("global_delta_norm"), "9.4f", 9),
             ",".join(fired) if fired else "-"]))
    edge_rows = [r for r in rows if r.get("edges")]
    if edge_rows:
        out.append("  per-edge rollup (latest round with edge frames):")
        last = edge_rows[-1]
        out.append("  " + "  ".join(
            [f"{'edge':>6s}", f"{'up':>4s}", f"{'acc':>4s}",
             f"{'weight':>9s}", f"{'norm_mean':>10s}", f"{'align':>8s}",
             f"{'gdelta':>9s}"]))
        for e, s in sorted(last["edges"].items(),
                           key=lambda kv: (len(kv[0]), kv[0])):
            norm = s.get("norm") or {}
            align = s.get("alignment") or {}
            out.append("  " + "  ".join(
                [f"{e:>6s}", f"{s.get('uploads', 0):>4d}",
                 f"{s.get('accepted', 0):>4d}",
                 num(s.get("weight"), "9.1f", 9),
                 num(norm.get("mean"), "10.4f", 10),
                 num(align.get("mean"), "8.4f", 8),
                 num(s.get("global_delta_norm"), "9.4f", 9)]))
        rollup = last.get("edge_rollup") or {}
        if rollup.get("count"):
            out.append(f"  edge rollup (merged moments): "
                       f"count={rollup['count']} "
                       f"mean={rollup['mean']:.4f} std={rollup['std']:.4f}")
    out.append(
        f"  {len(rows)} round(s); "
        + (f"DRIFT ALARMS fired {fired_total} time(s) — see the alarms "
           f"column" if fired_total
           else "drift alarms: none fired"))
    return out


# -- renderer ----------------------------------------------------------------

_ROUND_KEYS = ("round", "version", "step")


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_report(run_dir: Optional[str] = None,
                  trace_dir: Optional[str] = None,
                  perf_ledger: Optional[str] = None,
                  health_ledger: Optional[str] = None) -> str:
    """``perf_ledger`` / ``health_ledger``: explicit ledger paths for
    runs that wrote them outside ``run_dir`` (the ``--perf_ledger`` /
    ``--health_ledger`` flags); default to ``run_dir/{perf,health}.jsonl``."""
    out: List[str] = ["=" * 64, "fedml_tpu run report", "=" * 64]
    summary = load_json(os.path.join(run_dir, "summary.json")) \
        if run_dir else None
    events = load_jsonl(os.path.join(run_dir, "metrics.jsonl")) \
        if run_dir else []
    telemetry = load_json(os.path.join(run_dir, "telemetry.json")) \
        if run_dir else None

    if summary:
        cfg = summary.get("config") or {}
        head = " ".join(f"{k}={cfg[k]}" for k in
                        ("algo", "model", "dataset", "client_num_per_round",
                         "comm_round") if k in cfg)
        if head:
            out += ["", f"run: {head}"]
        final = summary.get("final")
        if isinstance(final, dict) and final:
            out += ["final: " + "  ".join(f"{k}={_fmt(v)}"
                                          for k, v in sorted(final.items())
                                          if isinstance(v, (int, float)))]

    round_rows = [e for e in events
                  if any(k in e for k in _ROUND_KEYS)
                  and any(isinstance(v, (int, float))
                          for k, v in e.items() if not k.startswith("_"))]
    if round_rows:
        out += ["", "-- rounds (metrics.jsonl) " + "-" * 37]
        cols = sorted({k for e in round_rows for k, v in e.items()
                       if isinstance(v, (int, float))
                       and not k.startswith("_")},
                      key=lambda k: (k not in _ROUND_KEYS, k))
        out.append("  " + "  ".join(f"{c:>12s}" for c in cols))
        for e in round_rows:
            out.append("  " + "  ".join(
                f"{_fmt(e[c]) if c in e else '-':>12s}" for c in cols))

    perf_path = perf_ledger or (os.path.join(run_dir, "perf.jsonl")
                                if run_dir else None)
    perf_rows = load_jsonl(perf_path) if perf_path else []
    health_path = health_ledger or (os.path.join(run_dir, "health.jsonl")
                                    if run_dir else None)
    health_rows = load_jsonl(health_path) if health_path else []

    if run_dir and not round_rows and (perf_rows or health_rows):
        # perf-/health-only run (no per-round metrics.jsonl rows — eval
        # logging off or a crashed sink): say so explicitly, so the
        # absent rounds table reads as "not recorded", never as "the
        # run had no rounds" while the ledgers below clearly show them
        out += ["", "(no per-round metrics.jsonl rows — perf/health-only "
                    "run; rounds appear in the ledger sections below)"]

    if perf_rows:
        out += ["", "-- perf ledger (perf.jsonl, phase ms) " + "-" * 25]
        out += _perf_lines(perf_rows)
        if any(isinstance(r.get("critical_path"), dict) for r in perf_rows):
            out += ["", "-- critical path (perf.jsonl critical_path "
                        "section) " + "-" * 15]
            out += _critical_path_lines(perf_rows)
        if any(isinstance(r.get("device"), dict) for r in perf_rows):
            out += ["", "-- device observatory (perf.jsonl device "
                        "section) " + "-" * 17]
            out += _device_lines(perf_rows)
    elif perf_ledger:
        # an EXPLICITLY named ledger that renders nothing must say so —
        # an instrumented run silently reporting as uninstrumented is
        # the blindness this subsystem exists to end
        out += ["", f"-- perf ledger: no rows at {perf_ledger} "
                    f"(missing or empty)"]

    if health_rows:
        out += ["", "-- learning health (health.jsonl) " + "-" * 29]
        out += _health_lines(health_rows)
    elif health_ledger:
        out += ["", f"-- health ledger: no rows at {health_ledger} "
                    f"(missing or empty)"]

    traces = group_round_traces(load_trace_events(trace_dir))
    if traces:
        out += ["", "-- round timelines (trace) " + "-" * 36]
        for tr in traces:
            label = tr["root"]["name"]
            args = tr["root"].get("args") or {}
            for key in _ROUND_KEYS:
                if key in args:
                    label = f"{label} {key}={args[key]}"
                    break
            out.append(f"{label}  [trace {tr['trace_id']}]  "
                       f"total {tr['total_s']:.4f}s")
            out += _timeline_lines(tr)

    if telemetry:
        out += ["", "-- telemetry " + "-" * 50]
        for kind in ("counters", "gauges"):
            for series, value in sorted((telemetry.get(kind) or {}).items()):
                out.append(f"  {series:<56s} {_fmt(value)}")
        for series, h in sorted((telemetry.get("histograms") or {}).items()):
            if not h.get("count"):
                continue
            out.append(f"  {series:<56s} count={h['count']} "
                       f"mean={_fmt(h['mean'])} min={_fmt(h['min'])} "
                       f"max={_fmt(h['max'])}")
        counters = telemetry.get("counters") or {}
        hists = telemetry.get("histograms") or {}
        examples = counters.get("fedml_trainer_examples_total")
        train_s = sum(h["sum"] for name, h in hists.items()
                      if name.startswith(("fedml_trainer_train_seconds",
                                          "fedml_trainer_compile_seconds")))
        if examples and train_s:
            out += ["", f"  derived: examples/sec ≈ "
                        f"{examples / train_s:,.1f} "
                        f"({_fmt(examples)} examples / "
                        f"{train_s:.3f}s in-trainer)"]

    if len(out) == 3:
        out.append("(no artifacts found — pass --run_dir and/or "
                   "--trace_dir of an instrumented run)")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="obs_report",
        description="Merge metrics.jsonl + telemetry + round traces into "
                    "a per-round timeline report")
    p.add_argument("--run_dir", "--metrics_dir", dest="run_dir", default=None,
                   help="directory holding metrics.jsonl / summary.json / "
                        "telemetry.json")
    p.add_argument("--trace_dir", default=None,
                   help="directory holding per-process *.json span exports")
    p.add_argument("--merge_trace", default=None, metavar="OUT",
                   help="also write one combined Perfetto JSON here")
    p.add_argument("--perf_ledger", default=None,
                   help="explicit perf.jsonl path for runs that wrote it "
                        "outside --run_dir (default: run_dir/perf.jsonl)")
    p.add_argument("--health_ledger", default=None,
                   help="explicit health.jsonl path for runs that wrote it "
                        "outside --run_dir (default: run_dir/health.jsonl)")
    args = p.parse_args(argv)
    if args.merge_trace:
        if not args.trace_dir:
            print("--merge_trace: no --trace_dir given; nothing to merge")
        else:
            n = merge_traces(args.trace_dir, args.merge_trace)
            if n is None:
                print(f"--merge_trace: no span exports under "
                      f"{args.trace_dir!r} (missing or empty trace dir); "
                      f"nothing written")
            else:
                print(f"merged {n} span events -> {args.merge_trace}")
    print(render_report(args.run_dir, args.trace_dir,
                        perf_ledger=args.perf_ledger,
                        health_ledger=args.health_ledger), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
