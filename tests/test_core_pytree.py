"""Aggregation math vs. a plain-numpy oracle (the reference's key-by-key loop,
FedAVGAggregator.py:58-87)."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core import (
    tree_weighted_mean, tree_global_norm, tree_sub,
)
from fedml_tpu.core.pytree import tree_weighted_psum_mean
from fedml_tpu.core.robust import clip_update, add_gaussian_noise


def _random_tree(rng, scale=1.0):
    return {
        "dense": {"w": rng.randn(4, 3).astype(np.float32) * scale,
                  "b": rng.randn(3).astype(np.float32) * scale},
        "out": rng.randn(3, 2).astype(np.float32) * scale,
    }


def _numpy_weighted_mean(trees, ns):
    total = sum(ns)
    out = jax.tree.map(lambda *xs: sum(x * (n / total) for x, n in zip(xs, ns)), *trees)
    return out


def test_weighted_mean_matches_reference_loop(rng):
    trees = [_random_tree(rng) for _ in range(5)]
    ns = [3, 10, 1, 7, 4]
    got = tree_weighted_mean(trees, jnp.array(ns))
    want = _numpy_weighted_mean(trees, ns)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), got, want)


def test_weighted_mean_stacked_layout(rng):
    trees = [_random_tree(rng) for _ in range(4)]
    ns = jnp.array([1.0, 2.0, 3.0, 4.0])
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    got = tree_weighted_mean(stacked, ns)
    want = tree_weighted_mean(trees, ns)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), got, want)


def test_weighted_mean_is_jittable(rng):
    trees = [_random_tree(rng) for _ in range(3)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    f = jax.jit(tree_weighted_mean)
    got = f(stacked, jnp.array([1.0, 1.0, 2.0]))
    want = tree_weighted_mean(stacked, jnp.array([1.0, 1.0, 2.0]))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), got, want)


def test_global_norm(rng):
    t = _random_tree(rng)
    flat = np.concatenate([np.ravel(x) for x in jax.tree.leaves(t)])
    np.testing.assert_allclose(tree_global_norm(t), np.linalg.norm(flat), rtol=1e-5, atol=1e-6)


def test_psum_mean_matches_local_mean(rng, devices):
    """Distributed weighted mean over an 8-device mesh == the list version."""
    from jax.sharding import Mesh, PartitionSpec as P
    from fedml_tpu.parallel.cohort import compat_shard_map as shard_map

    trees = [_random_tree(rng) for _ in range(8)]
    ns = np.array([5., 1., 2., 8., 3., 4., 6., 7.], np.float32)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    mesh = Mesh(np.array(devices), ("clients",))

    @jax.jit
    def run(stacked, ns):
        def per_device(tree_slice, n):
            local = jax.tree.map(lambda x: x[0], tree_slice)
            return tree_weighted_psum_mean(local, n[0], "clients")
        return shard_map(
            per_device, mesh=mesh,
            in_specs=(P("clients"), P("clients")),
            out_specs=P())(stacked, ns)

    got = run(stacked, ns)
    want = tree_weighted_mean(trees, ns)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                 got, want)


def test_clip_update_norm_bound(rng):
    g = _random_tree(rng)
    c = _random_tree(rng, scale=10.0)
    clipped = clip_update(c, g, norm_bound=1.0)
    diff_norm = tree_global_norm(tree_sub(clipped, g))
    assert float(diff_norm) <= 1.0 + 1e-4
    # inside the bound: untouched
    near = jax.tree.map(lambda x: x + 1e-4, g)
    kept = clip_update(near, g, norm_bound=1.0)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), kept, near)


def test_add_noise_stddev(rng):
    t = {"w": jnp.zeros((200, 200))}
    noised = add_gaussian_noise(t, jax.random.key(0), stddev=0.5)
    assert abs(float(jnp.std(noised["w"])) - 0.5) < 0.02
