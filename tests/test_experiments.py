"""Experiments/CLI layer tests.

The reference's equivalent coverage is its CI shell scripts
(``CI-script-fedavg.sh:33-38``: smoke-run every dataset×model combo from the
shell, then assert on the wandb summary).  Here the CLI is a function
(`fedml_tpu.experiments.main.main`), so the smoke runs are in-process and
the "wandb summary" assertions read the run_dir artifacts.
"""

import json
import os

import numpy as np
import pytest

from fedml_tpu.experiments.config import build_parser, ExperimentConfig
from fedml_tpu.experiments.main import RUNNERS, main
from fedml_tpu.utils.metrics import MetricsSink

# every behavioral flag of the reference argparse surface
# (main_fedavg.py:46-112) that carries over by name
REFERENCE_FLAGS = [
    "model", "dataset", "data_dir", "partition_method", "partition_alpha",
    "client_num_in_total", "client_num_per_round", "batch_size",
    "client_optimizer", "lr", "wd", "epochs", "comm_round",
    "frequency_of_the_test", "ci",
]

_BASE = ["--client_num_in_total", "8", "--client_num_per_round", "4",
         "--comm_round", "2", "--frequency_of_the_test", "1",
         "--batch_size", "4", "--log_stdout", "false"]


def test_parser_reference_flag_parity():
    parser = build_parser()
    opts = {a.dest for a in parser._actions}
    missing = [f for f in REFERENCE_FLAGS if f not in opts]
    assert not missing, f"CLI lost reference flags: {missing}"


def test_all_algorithms_registered():
    expected = {"fedavg", "fedprox", "fedopt", "fednova", "fedavg_robust",
                "hierarchical", "centralized", "decentralized",
                "turboaggregate", "fednas", "fedgkt", "fedgan", "asdgan",
                "fedseg", "split_nn", "vfl", "cross_silo"}
    assert expected <= set(RUNNERS), sorted(expected - set(RUNNERS))


def test_cli_fedavg_end_to_end(tmp_path):
    run_dir = str(tmp_path / "run")
    summary = main(["--algo", "fedavg", "--model", "lr",
                    "--dataset", "mnist", "--run_dir", run_dir] + _BASE)
    assert "train_acc" in summary and "test_acc" in summary
    # wandb-equivalent artifacts (CI-script-fedavg.sh:43-48 reads the
    # wandb summary; our CI reads summary.json)
    with open(os.path.join(run_dir, "summary.json")) as f:
        persisted = json.load(f)
    assert persisted["final"]["train_acc"] == summary["train_acc"]
    events = [json.loads(l) for l in
              open(os.path.join(run_dir, "metrics.jsonl"))]
    rounds = [e["step"] for e in events if "round" in e and "step" in e]
    assert rounds == [0, 1]


def test_cli_mesh_equals_single_chip(devices):
    """The CLI's --mesh_clients path must reproduce the single-chip run
    bit-comparably (same cohort rng convention, psum vs vmap aggregation)."""
    argv = ["--algo", "fedavg", "--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "16", "--client_num_per_round", "8"] \
        + _BASE[4:]
    single = main(argv)
    sharded = main(argv + ["--mesh_clients", "8"])
    np.testing.assert_allclose(single["train_acc"], sharded["train_acc"],
                               rtol=1e-6)
    np.testing.assert_allclose(single["train_loss"], sharded["train_loss"],
                               rtol=1e-5)


def test_cli_ci_mode_restricts_eval(tmp_path):
    run_dir = str(tmp_path / "ci")
    summary = main(["--algo", "fedavg", "--model", "lr", "--dataset",
                    "mnist", "--comm_round", "6", "--ci", "1",
                    "--run_dir", run_dir] + _BASE[:4] + _BASE[8:])
    assert summary["round"] == 5
    events = [json.loads(l) for l in
              open(os.path.join(run_dir, "metrics.jsonl"))]
    evaluated = [e["round"] for e in events if "train_acc" in e]
    assert evaluated == [0, 5]  # round 0 + final only


@pytest.mark.parametrize("algo", ["fedopt", "centralized", "vfl"])
def test_cli_fast_algos(algo):
    summary = main(["--algo", algo, "--model", "lr", "--dataset", "mnist"]
                   + _BASE)
    assert summary


# big-model compiles dominate these CLI combos on CPU -> slow tier
_HEAVY_ALGOS = {"fednas", "fedgkt", "fedseg", "asdgan", "fedgan"}


@pytest.mark.parametrize(
    "algo", [pytest.param(a, marks=pytest.mark.slow)
             if a in _HEAVY_ALGOS else a for a in sorted(RUNNERS)])
def test_cli_every_algorithm(algo, tmp_path):
    """Every algorithm × the CLI runs end-to-end on hermetic data (the
    reference CI's per-combo smoke strategy)."""
    special = {
        "fednas": ["--dataset", "femnist", "--fednas_layers", "2",
                   "--fednas_channels", "4"],
        "fedgkt": ["--dataset", "femnist"],
        "fedgan": ["--dataset", "femnist"],
        "asdgan": ["--dataset", "femnist"],
        "fedseg": ["--dataset", "femnist"],
        "hierarchical": ["--group_num", "2", "--group_comm_round", "1"],
        "decentralized_online": ["--iteration_number", "30", "--lr", "0.3",
                                 "--wd", "0"],
        "turboaggregate": ["--group_num", "2"],
    }
    argv = (["--algo", algo, "--model", "lr", "--dataset", "mnist"]
            + _BASE + special.get(algo, [])
            + ["--run_dir", str(tmp_path / algo)])
    summary = main(argv)
    assert isinstance(summary, dict) and summary
    assert os.path.exists(tmp_path / algo / "summary.json")


def test_cli_cross_silo_matches_fedavg(tmp_path):
    """The actor-choreography path (local hub, wire codec on) must land at
    the same aggregate as the in-jit fedavg cohort for one full-batch
    round: same seeded sampling, same local SGD, same weighted mean."""
    argv = ["--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "4", "--client_num_per_round", "4",
            "--comm_round", "1", "--frequency_of_the_test", "1",
            "--batch_size", "64", "--epochs", "1", "--log_stdout", "false"]
    silo = main(["--algo", "cross_silo"] + argv)
    fed = main(["--algo", "fedavg"] + argv)
    np.testing.assert_allclose(silo["train_acc"], fed["train_acc"], rtol=1e-6)
    np.testing.assert_allclose(silo["train_loss"], fed["train_loss"],
                               rtol=1e-5)


@pytest.mark.slow
def test_cli_cross_silo_pipeline_stages(tmp_path):
    """--mesh_stages: cross-silo federation where every silo trains its
    transformer through the 2-stage GPipe pipeline (CPU devices stand in
    for the stage chips).  Must run end-to-end AND compose with
    --moe_experts (the ep x pp balance-loss path)."""
    argv = ["--algo", "cross_silo", "--model", "transformer",
            "--dataset", "shakespeare", "--mesh_stages", "2",
            "--client_num_in_total", "4", "--client_num_per_round", "2",
            "--comm_round", "1", "--frequency_of_the_test", "1",
            "--batch_size", "4", "--epochs", "1", "--log_stdout", "false"]
    out = main(argv)
    assert np.isfinite(out["train_loss"])
    import jax as _jax
    if hasattr(_jax, "shard_map"):
        out_moe = main(argv + ["--moe_experts", "2"])
        assert np.isfinite(out_moe["train_loss"])
    else:
        # legacy toolchain: the MoE schedule refuses loudly by contract
        with pytest.raises(RuntimeError, match="jax.shard_map"):
            main(argv + ["--moe_experts", "2"])


def test_cli_mesh_stages_rejected_outside_cross_silo():
    with pytest.raises(ValueError, match="mesh_stages"):
        main(["--algo", "fedavg", "--model", "transformer", "--dataset",
              "shakespeare", "--mesh_stages", "2"] + _BASE)


@pytest.mark.slow
def test_cli_cross_silo_grpc_loopback(tmp_path):
    """True multi-process federation: server + 2 silo processes over gRPC
    on 127.0.0.1 (the reference's localhost-MPI strategy, SURVEY.md §4.3,
    with grpc_ipconfig.csv-style peers)."""
    import subprocess
    import sys
    base = ["--algo", "cross_silo", "--silo_backend", "grpc",
            "--platform", "cpu", "--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "8", "--client_num_per_round", "2",
            "--comm_round", "2", "--frequency_of_the_test", "1",
            "--batch_size", "4", "--base_port", "52310",
            "--log_stdout", "false"]
    base += ["--silo_idle_timeout_s", "120"]  # no leaked silos on failure
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    silos = [subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu", "--node_id", str(i)] + base,
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for i in (1, 2)]
    try:
        # no sleep: the server's INIT broadcast uses wait_for_ready, so it
        # blocks until each silo's grpc server binds
        server = subprocess.run(
            [sys.executable, "-m", "fedml_tpu", "--node_id", "0"] + base,
            cwd=repo, env=env, capture_output=True, text=True, timeout=240)
        for p in silos:
            p.wait(timeout=60)
    finally:
        for p in silos:
            if p.poll() is None:
                p.kill()
    assert server.returncode == 0, server.stdout + server.stderr
    assert '"train_acc"' in server.stdout


def test_completion_signal_file(tmp_path):
    """--completion_signal writes the final summary line (the reference's
    sweep-orchestration named-pipe contract, fedavg/utils.py:19-27)."""
    sig = tmp_path / "done"
    summary = main(["--algo", "fedavg", "--model", "lr", "--dataset",
                    "mnist", "--completion_signal", str(sig)] + _BASE)
    line = json.loads(sig.read_text())
    assert line["algo"] == "fedavg"
    assert line["train_acc"] == summary["train_acc"]


def test_metrics_sink(tmp_path):
    with MetricsSink(str(tmp_path)) as sink:
        sink.log({"acc": 0.5}, step=0)
        sink.log({"acc": np.float32(0.75), "loss": 1.0}, step=1)
    assert sink.summary["acc"] == 0.75
    with open(tmp_path / "summary.json") as f:
        assert json.load(f)["acc"] == 0.75
    lines = open(tmp_path / "metrics.jsonl").read().splitlines()
    assert len(lines) == 2 and json.loads(lines[0])["acc"] == 0.5


def test_config_dataclass_roundtrip():
    cfg = ExperimentConfig(algo="fedprox", mu=0.5)
    assert cfg.mu == 0.5 and cfg.algo == "fedprox"


@pytest.mark.slow
def test_multiprocess_distributed_matches_single(tmp_path):
    """Two OS processes x 4 virtual CPU devices each, wired by
    jax.distributed.initialize, must reproduce the single-process 8-device
    run bit-comparably (the mpirun -np N replacement, LAUNCH.md)."""
    import subprocess
    import sys

    driver = tmp_path / "mp_driver.py"
    driver.write_text(
        "import sys, json\n"
        "sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "pid = int(sys.argv[1])\n"
        "from fedml_tpu.parallel.mesh import init_distributed\n"
        "init_distributed('127.0.0.1:29891', 2, pid)\n"
        "from fedml_tpu.experiments.main import main\n"
        "s = main(['--algo', 'fedavg', '--model', 'lr', '--dataset',"
        " 'mnist', '--client_num_in_total', '16',"
        " '--client_num_per_round', '8', '--comm_round', '2',"
        " '--batch_size', '4', '--frequency_of_the_test', '1',"
        " '--mesh_clients', '8', '--log_stdout', 'false'])\n"
        "print('RESULT', json.dumps(s))\n" % os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-u", str(driver), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(2)]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[-1][len("RESULT "):]))
    assert results[0]["train_acc"] == results[1]["train_acc"]

    # single-process 8-virtual-device reference (this pytest process)
    single = main(["--algo", "fedavg", "--model", "lr", "--dataset",
                   "mnist", "--client_num_in_total", "16",
                   "--client_num_per_round", "8", "--comm_round", "2",
                   "--batch_size", "4", "--frequency_of_the_test", "1",
                   "--mesh_clients", "8", "--log_stdout", "false"])
    np.testing.assert_allclose(results[0]["train_acc"],
                               single["train_acc"], rtol=1e-6)
    np.testing.assert_allclose(results[0]["train_loss"],
                               single["train_loss"], rtol=1e-5)


@pytest.mark.parametrize("dataset", [
    "shakespeare",
    pytest.param("stackoverflow_nwp", marks=pytest.mark.slow),
    "stackoverflow_lr", "fed_cifar100", "cinic10"])
def test_cli_dataset_axis(dataset, tmp_path):
    """The dataset axis end-to-end through the CLI (this path held
    a latent logits-shape bug precisely because only --dataset mnist was
    smoke-tested)."""
    argv = ["--algo", "fedavg", "--dataset", dataset,
            "--client_num_in_total", "4", "--client_num_per_round", "2",
            "--comm_round", "1", "--batch_size", "4", "--epochs", "1",
            "--frequency_of_the_test", "1", "--log_stdout", "false",
            "--run_dir", str(tmp_path / dataset)]
    summary = main(argv)
    assert np.isfinite(summary.get("train_loss", np.inf))


def test_cli_profiler_trace(tmp_path):
    """--profile_dir captures a jax profiler trace alongside the run
    (SURVEY §5.1 observability; the reference has no profiling at all)."""
    prof = tmp_path / "trace"
    main(["--algo", "fedavg", "--model", "lr", "--dataset", "mnist",
          "--profile_dir", str(prof)] + _BASE)
    captured = list(prof.rglob("*.pb")) + list(prof.rglob("*.json.gz"))
    assert captured, f"no trace artifacts under {prof}"


def test_flagship_partial_sink_checkpoints_curve(tmp_path):
    """scripts/flagship_accuracy.py's PartialSink must leave the measured
    curve on disk after EVERY eval — a wedged tunnel mid-flagship-run
    still yields an artifact (round-4 hardening)."""
    import importlib.util
    import json as _json
    import os as _os
    spec = importlib.util.spec_from_file_location(
        "flagship_accuracy",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "scripts", "flagship_accuracy.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.data.synthetic import synthetic_federated_dataset
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload

    path = str(tmp_path / "CURVE.json.partial")
    sink = mod.PartialSink(path, {"rounds": 4})
    data = synthetic_federated_dataset(num_clients=6, samples_per_client=12,
                                       sample_shape=(5,), class_num=3,
                                       batch_size=4)
    wl = ClassificationWorkload(LogisticRegression(5, 3), num_classes=3,
                                grad_clip_norm=None)
    cfg = FedAvgConfig(comm_round=4, client_num_per_round=3, epochs=1,
                       batch_size=4, lr=0.1, frequency_of_the_test=2, seed=0)
    FedAvg(wl, data, cfg, sink=sink).run()
    part = _json.loads(open(path).read())
    assert part["partial"] is True
    curve = part["federated_curve_so_far"]
    # evals at rounds 0, 2, 3 (every 2 + final)
    assert [c["round"] for c in curve] == [0, 2, 3]
    assert all(c["train_acc"] is not None for c in curve)


@pytest.mark.parametrize("algo,extra", [
    ("scaffold", []),
    ("feddyn", ["--feddyn_alpha", "0.05"]),
    ("ditto", ["--ditto_lambda", "0.1"]),
    ("fedac", ["--fedac_mu", "0.1"]),
    ("dp_fedavg", ["--dp_clip", "0.5", "--dp_noise_multiplier", "1.0"]),
])
def test_cli_stateful_mesh_equals_single_chip(devices, algo, extra):
    """--mesh_clients on the stateful/coupled algorithms (whose mesh paths
    are the shared sharded round bodies) must reproduce the single-chip
    CLI run to float tolerance — covering the experiments/main.py wiring,
    not just the library API."""
    argv = ["--algo", algo, "--model", "lr", "--dataset", "mnist"] \
        + _BASE + extra
    single = main(argv)
    sharded = main(argv + ["--mesh_clients", "4"])
    np.testing.assert_allclose(single["train_loss"], sharded["train_loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(single["train_acc"], sharded["train_acc"],
                               rtol=1e-5)


def test_top_level_api_lazy_exports():
    """`import fedml_tpu` must stay cheap (no jax import at package
    import time — platform selection must still be possible afterwards),
    while the curated names resolve lazily and point at the real
    objects."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # fresh interpreter: importing the package must not pull in jax
    code = (f"import sys; sys.path.insert(0, {repo!r}); "
            "import fedml_tpu; "
            "assert 'jax' not in sys.modules, 'package import pulled jax'; "
            "print('lazy-ok')")
    proc = subprocess.run([sys.executable, "-S", "-c", code],
                          capture_output=True, text=True)
    assert "lazy-ok" in proc.stdout, proc.stderr

    import fedml_tpu
    from fedml_tpu.algorithms import FedAvg
    assert fedml_tpu.FedAvg is FedAvg
    assert "FedAvg" in dir(fedml_tpu)
    with pytest.raises(AttributeError):
        fedml_tpu.not_a_symbol
