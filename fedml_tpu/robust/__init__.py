"""Payload defense for the live distributed path.

PR 1 hardened the *transport* (drop/delay/dup/reorder + retry + crash
recovery); this package hardens the *payload*: every upload entering a
distributed server actor passes the admission pipeline (structural
fingerprint, finite guard, sample-count cap, robust norm-outlier
screen) and repeated offenders are quarantined by the `TrustTracker`;
the accepted cohort is then aggregated by one jit-compiled defended
aggregate (norm clipping + weak-DP noise, reference parity, composed
with the Byzantine rules of `core/byzantine.py`).  `adversary.py` is
the attack half — seeded malicious silo behaviors riding the real
message path, symmetric to `comm/chaos.py` on the wire.
"""

from fedml_tpu.robust.admission import (AdmissionPipeline, AdmissionVerdict,
                                        TrustTracker, params_fingerprint)
from fedml_tpu.robust.adversary import (ATTACK_KINDS, Attack,
                                        make_backdoor_shard_transform,
                                        make_malicious_train_fn,
                                        parse_adversary_spec)
from fedml_tpu.robust.defense import ROBUST_AGG_METHODS, make_defended_aggregate
from fedml_tpu.robust.faultline import (CRASH_POINTS, DISK_CHANNELS,
                                        ActorKilled, CrashSpec,
                                        DiskFaultInjector, DiskFaultSpec,
                                        Faultline, kill_actor)

__all__ = [
    "AdmissionPipeline", "AdmissionVerdict", "TrustTracker",
    "params_fingerprint", "make_defended_aggregate", "ROBUST_AGG_METHODS",
    "Attack", "ATTACK_KINDS", "parse_adversary_spec",
    "make_malicious_train_fn", "make_backdoor_shard_transform",
    "CRASH_POINTS", "DISK_CHANNELS", "ActorKilled", "CrashSpec",
    "DiskFaultInjector", "DiskFaultSpec", "Faultline", "kill_actor",
]
