from fedml_tpu.data.stacking import (
    stack_client_data, gather_cohort, batch_global, FederatedData,
)
