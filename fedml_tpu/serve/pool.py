"""Multi-worker HTTP serving: N accept loops over ONE model registry.

One `ThreadingHTTPServer` peaks ~1.5k req/s on this stack (BENCH_serve
v1): the ceiling is the single accept/dispatch path, not the model math.
This module scales the frontend out the SO_REUSEPORT way: N workers,
each a full (listener, `MicroBatcher`) pair, all bound to the SAME port
— the kernel load-balances incoming connections across the listening
sockets, and each worker batches independently against the one shared
`ModelRegistry` snapshot, so hot-swap/pin/rollback semantics are
EXACTLY the single-frontend ones (every worker's next batch reads the
same registry slot; a publish is one atomic reference swap visible to
all of them).

Where SO_REUSEPORT is unavailable (or ``reuseport=False``), the pool
falls back to ONE shared listening socket that every worker's server
accepts from — ``accept(2)`` is thread-safe, so the workers form a
classic shared-accept pool; less kernel-level balancing, same
correctness.

Telemetry is worker-labeled (``fedml_serve_*{worker="i"}``): one hot
worker shows up as itself, not averaged into the pool.  ``/healthz``
carries the answering worker's id plus every worker's queue depth, and
``/healthz?deep=1`` runs the shared `SloEvaluator` — whose
``serve_queue_utilization_ratio`` objective reads the WORST worker's
queue gauge — so an LB probe through ANY worker sees pool-wide health.
"""

from __future__ import annotations

import http.server
import logging
import socket
import threading
from typing import Callable, List, Optional

from fedml_tpu.obs import telemetry
from fedml_tpu.serve.batcher import MicroBatcher, TierGate
from fedml_tpu.serve.registry import ModelRegistry
from fedml_tpu.serve.server import _make_handler

log = logging.getLogger(__name__)


def _reuseport_available() -> bool:
    return hasattr(socket, "SO_REUSEPORT")


def _bind_listener(host: str, port: int, reuseport: bool) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        if reuseport:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(128)
    except BaseException:
        s.close()
        raise
    return s


class _WorkerServer(http.server.ThreadingHTTPServer):
    """An HTTPServer over a PRE-BOUND socket (ours came from
    `_bind_listener`, possibly shared between workers)."""

    def __init__(self, sock: socket.socket, handler, owns_socket: bool):
        # bind_and_activate=False: the listener already exists
        super().__init__(sock.getsockname(), handler,
                         bind_and_activate=False)
        self.socket.close()          # the placeholder from __init__
        self.socket = sock
        self.server_address = sock.getsockname()
        self._owns_socket = owns_socket
        self.daemon_threads = True

    def server_close(self):
        if self._owns_socket:
            super().server_close()
        # a SHARED socket is closed once, by the pool


class ServeWorkerPool:
    """N HTTP workers × 1 registry: the production serving frontend.

    ``batcher_factory(worker_idx) -> MicroBatcher`` builds each worker's
    batcher (default: `MicroBatcher` over ``registry`` with
    ``batcher_kw``, worker-labeled).  ``slo``/``health`` back deep
    health checks exactly as on `ServeFrontend`; the pool wraps ``slo``
    in ONE shared `TierGate` so all workers' tiered admission reads one
    cached verdict.  ``port=0`` binds an ephemeral port (tests); read
    ``.port`` after ``start()``.
    """

    def __init__(self, registry: ModelRegistry, port: int = 0,
                 host: str = "127.0.0.1", workers: int = 2,
                 batcher_factory: Optional[Callable[[int],
                                                    MicroBatcher]] = None,
                 slo=None, health=None, reuseport: Optional[bool] = None,
                 **batcher_kw):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.registry = registry
        self.workers = workers
        self.slo = slo
        self.health = health
        self._host = host
        self._requested_port = port
        self._reuseport = (_reuseport_available() if reuseport is None
                           else bool(reuseport))
        gate = TierGate(slo) if slo is not None else None
        if batcher_factory is None:
            def batcher_factory(i: int) -> MicroBatcher:
                return MicroBatcher(registry, worker=str(i), slo=gate,
                                    **batcher_kw)
        else:
            if slo is not None:
                # fail loudly: the pool cannot inject the gate into a
                # caller-built batcher, and silently dropping it would
                # let deep-healthz answer 503 while best-effort traffic
                # is never shed — the exact shedding/health disagreement
                # the design forbids.  Wire TierGate(slo) (or the
                # evaluator itself) into the factory's batchers instead.
                raise ValueError(
                    "slo= and batcher_factory= together: pass the "
                    "SloEvaluator (or a shared TierGate) to the "
                    "factory's own MicroBatcher(slo=...) so tiered "
                    "shedding reads the same verdicts as deep-healthz")
            if batcher_kw:
                raise ValueError("pass batcher options through the "
                                 "factory when batcher_factory is "
                                 "given, not as extra kwargs "
                                 f"{sorted(batcher_kw)}")
        self._factory = batcher_factory
        self.batchers: List[MicroBatcher] = []
        self._servers: List[_WorkerServer] = []
        self._threads: List[threading.Thread] = []
        self._sockets: List[socket.socket] = []
        reg = telemetry.get_registry()
        self._g_workers = reg.gauge("fedml_serve_workers_value")

    @property
    def port(self) -> int:
        if not self._sockets:
            return self._requested_port
        return self._sockets[0].getsockname()[1]

    def queue_depths(self) -> List[int]:
        return [b.depth() for b in self.batchers]

    def start(self) -> "ServeWorkerPool":
        if self._servers:
            return self
        first = _bind_listener(self._host, self._requested_port,
                               self._reuseport)
        self._sockets.append(first)
        port = first.getsockname()[1]
        if self._reuseport:
            # one listener per worker, kernel-balanced
            for _ in range(1, self.workers):
                self._sockets.append(
                    _bind_listener(self._host, port, True))
            per_worker = self._sockets
            owns = [True] * self.workers
        else:
            # shared-accept fallback: every worker accepts from the one
            # listener; the pool owns (and closes) it once.  The socket
            # must be NON-BLOCKING: every worker's selector wakes on one
            # incoming connection and all of them race to accept() — the
            # losers must get BlockingIOError (socketserver swallows it)
            # instead of parking in accept() forever, which would wedge
            # serve_forever past shutdown().  Accepted connections come
            # back blocking (CPython restores default blocking-ness), so
            # request handling is unchanged.
            first.setblocking(False)
            per_worker = [first] * self.workers
            owns = [False] * self.workers
        for i in range(self.workers):
            batcher = self._factory(i)
            batcher.start()
            self.batchers.append(batcher)
            handler = _make_handler(self.registry, batcher, self.slo,
                                    self.health, pool=self, worker_id=i)
            server = _WorkerServer(per_worker[i], handler, owns[i])
            self._servers.append(server)
            t = threading.Thread(target=server.serve_forever, daemon=True,
                                 name=f"serve-worker-{i}-{port}")
            t.start()
            self._threads.append(t)
        self._g_workers.set(self.workers)
        log.info("serve pool: %d workers on %s:%d (%s)", self.workers,
                 self._host, port,
                 "SO_REUSEPORT" if self._reuseport else "shared accept")
        return self

    def warmup(self, sample_x) -> int:
        """Compile every bucket on every worker's batcher (each batcher
        jits through the shared apply_fn, so after the first worker the
        rest hit the jit cache).  Returns total buckets warmed."""
        return sum(b.warmup(sample_x) for b in self.batchers)

    def stop(self, drain: bool = True) -> None:
        for server in self._servers:
            server.shutdown()
            server.server_close()
        if not self._reuseport and self._sockets:
            self._sockets[0].close()
        self._servers = []
        self._threads = []
        self._sockets = []
        for b in self.batchers:
            b.stop(drain=drain)
        self.batchers = []
