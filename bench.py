"""Benchmark suite: honest rounds/sec + step-time + FLOPs + MFU.

Configs (BASELINE.md):
* femnist_cnn  — the cross-device headline (2-conv CNN, 10 clients/round,
  B=20, E=1, benchmark/README.md:54).  Comparable with BENCH_r01.
* resnet56_cifar10 — the flagship cross-silo config (10 clients, B=64,
  benchmark/README.md:105; the published config trains E=20 local epochs —
  we measure one epoch-round and report per-epoch numbers).
* cohort scaling — femnist_cnn at 10/32/64/128 clients per round: does the
  chip saturate as the cohort grows?
* multi-device — the same cohort step sharded over a mesh when >1 device
  is visible (skipped on single-chip hosts).

FLOPs come from XLA cost analysis of TWIN compiled programs
(``_honest_flops``): cost analysis counts a ``lax.scan`` body ONCE
regardless of trip count (verified empirically; the round-2 artifact
under-reported the scanned-dispatch MFU by exactly its trip count this
way), so per-round FLOPs are extrapolated from two rounds differing only
in local-step count, with recurrent cells unrolled in the cost twin.
MFU = achieved FLOP/s ÷ peak; peak comes from the detected device kind
(bf16 peak — the computation runs f32 unless BENCH_DTYPE=bfloat16, so
reported MFU is conservative), overridable via BENCH_PEAK_TFLOPS, and
raised to the measured bf16 matmul throughput when that exceeds the
table value (``bench_matmul_peak`` — the tunnel's device_kind string is
not trustworthy evidence of the attached silicon).  Cost twins compile
on the host CPU backend (``_twin_device_ctx``): they are never executed,
and keeping their fresh multi-minute compiles off the tunnel removes the
RPC most likely to wedge it.

stdout carries ONE JSON line (driver contract): the femnist_cnn rounds/s
with vs_baseline = measured sequential-torch-CPU round time ratio (the
reference's standalone simulator loop, fedavg_api.py:52-66 — an
architectural baseline, not a hardware-parity one; see BENCH_DETAILS.json
for the honest per-config breakdown, which is also written per-run).
When the accelerator backend is unreachable (wedged tunnel) NOTHING is
measured: the line carries ``skipped`` + the committed last-known-good
TPU figures marked ``stale`` — never a CPU number dressed as a
comparison, and BENCH_DETAILS.json is never overwritten.  An explicit
``BENCH_PLATFORM=cpu`` run writes BENCH_DETAILS_cpu.json instead.

Env knobs: BENCH_ROUNDS (default 20), BENCH_MODE=quick|full,
BENCH_SCALING=0 to skip the curve, BENCH_PLATFORM to force a jax platform.
"""

import json
import os
import sys
import time

import numpy as np

# The bf16 peak table (matched as a substring of jax's device_kind —
# the round-2 cohort-scaling numbers exceeded the blanket v5e assumption
# at 128 clients, so the attached chip's kind must be recorded, not
# assumed) and the XLA cost-analysis probe now LIVE in the device
# observatory (fedml_tpu/obs/device.py) and are aliased here: the
# offline bench and the live per-round fedml_dev_*/mfu gauges read ONE
# table and ONE accounting, so they can never disagree — the same
# drift-proofing as the _max_mfu -> trend.max_mfu delegation below
# (tests/test_device_obs.py pins all three by identity).
from fedml_tpu.obs.device import PEAK_TFLOPS_BY_KIND as _PEAK_BY_KIND
from fedml_tpu.obs.device import compiled_flops as _compiled_flops
from fedml_tpu.obs.device import peak_tflops_for_device as _peak_for_device

# device-independent default (env override or v5e); main() re-resolves
# from the attached chip's device_kind through the same parse path
PEAK_TFLOPS = _peak_for_device(None)


def _compute_dtype():
    """BENCH_DTYPE=bfloat16 runs model compute in bf16 (mixed precision:
    f32 master params/opt, bf16 conv/matmul), the MXU-native mode."""
    name = os.environ.get("BENCH_DTYPE")
    if not name:
        return None
    import jax.numpy as jnp
    return jnp.dtype(name)


def _now():
    return time.time()


def _twin_device_ctx():
    """Context that places the FLOPs cost twins on the host CPU backend.

    Twins are only COMPILED (cost analysis), never executed, so they do
    not need the accelerator at all — and compiling them on CPU keeps the
    single most wedge-prone RPC off the tunnel: round 4 observed the
    backend answer the liveness probe and then wedge inside the fresh
    multi-minute resnet56 twin compile, killing the whole capture.  FLOP
    counts are a property of the HLO, not the backend, and the twin
    subtraction (F2-F1) cancels residual backend-specific overhead.
    BENCH_TWIN_DEVICE=default restores on-device twins; falls back to the
    default backend when no CPU backend is registered."""
    import contextlib
    import jax
    if os.environ.get("BENCH_TWIN_DEVICE", "cpu") != "cpu":
        return contextlib.nullcontext()
    try:
        return jax.default_device(jax.local_devices(backend="cpu")[0])
    except Exception:
        return contextlib.nullcontext()


def _honest_flops(model, classes, lr, epochs, batch_size, xs, ys,
                  clients_per_round, workload=None):
    """Per-round FLOPs that count every local step: (flops, total_steps).

    XLA cost analysis counts a `lax.scan`/while body ONCE regardless of
    trip count, and the local trainer runs its whole epochs*S-step run as
    one scan (local_sgd.py) — so the full program's own number misses the
    steps loop entirely.  Instead compile two TWIN rounds whose step scan
    is fully UNROLLED (scan_unroll=S, so every step is present in the HLO
    that cost analysis sees) at S=1 and S=2 batches, and extrapolate:

        F(round) = F1 + (epochs*S - 1) * (F2 - F1)

    F2 - F1 is exactly one step body (batch gather + fwd/bwd + optimizer);
    F1 carries the per-round overhead (aggregation, weighing) once.  A
    model whose SINGLE step hides another scan (the LSTM recurrence) needs
    _rnn_round_flops instead — unrolling 80 cells makes a twin that takes
    minutes to compile, so the recurrent cost is extrapolated over
    sequence length too.  Twins always use the plain vmap cohort step:
    mesh collectives add negligible FLOPs.
    """
    import jax
    from fedml_tpu.data.stacking import gather_cohort

    def f_for(nb):
        need = nb * batch_size
        xs_t, ys_t = [], []
        for x, y in zip(xs[:clients_per_round], ys[:clients_per_round]):
            reps = max(1, -(-need // len(x)))
            xs_t.append(np.concatenate([x] * reps)[:need])
            ys_t.append(np.concatenate([y] * reps)[:need])
        with _twin_device_ctx():
            step, params, stacked = _build_step(
                model, classes, lr, 1, batch_size, xs_t, ys_t,
                workload=workload, scan_unroll=nb)
            cohort = gather_cohort(stacked, np.arange(clients_per_round),
                                   pad_to=clients_per_round)
            _beat()  # each unrolled twin is its own (long) compile
            return _compiled_flops(step, params, cohort, jax.random.key(0))

    f1, f2 = f_for(1), f_for(2)
    total_steps = epochs * max(1, -(-max(len(x) for x in xs) // batch_size))
    flops = f1 + (total_steps - 1) * max(f2 - f1, 0.0)
    return flops, total_steps


def _rnn_round_flops(dtype, clients_per_round, n_steps, seq_len=80,
                     batch=4, vocab=90, t_lo=4, t_hi=8):
    """Exact per-round FLOPs for the LSTM config: (flops, n_steps).

    The recurrence is a second scan INSIDE the training step, so the
    _honest_flops twins alone still count the T-step cell chain once.
    Unrolling all ``seq_len`` cells makes a twin that takes minutes to
    compile; instead, per-step cost is affine in T (embed + cell + logits
    + loss are all per-position; the optimizer update is T-independent),
    so three SMALL fully-unrolled twins pin both lines:

        A = (S=1, T=t_lo)   B = (S=2, T=t_lo)   C = (S=1, T=t_hi)
        per_token = (C - A) / (t_hi - t_lo)
        step(T)   = (B - A) + (T - t_lo) * per_token
        round     = (2A - B) + n_steps * step(seq_len)

    where 2A - B is the per-round overhead (aggregation) and B - A one
    t_lo-length step.  All scans (steps and cells) are unrolled in the
    twins so cost analysis sees every body."""
    import jax
    from fedml_tpu.data.stacking import gather_cohort
    from fedml_tpu.models import RNNOriginalFedAvg
    from fedml_tpu.trainer.workload import NWPWorkload

    def f_at(nb, t):
        rng = np.random.RandomState(0)
        xs = [rng.randint(1, vocab, (nb * batch, t)).astype(np.int32)
              for _ in range(clients_per_round)]
        ys = [np.concatenate([x[:, 1:], x[:, :1]], axis=1) for x in xs]
        wl = NWPWorkload(
            RNNOriginalFedAvg(vocab_size=vocab, dtype=dtype, unroll=t),
            compute_dtype=dtype)
        with _twin_device_ctx():
            step, params, stacked = _build_step(
                None, vocab, 0.8, 1, batch, xs, ys, workload=wl,
                scan_unroll=nb)
            cohort = gather_cohort(stacked, np.arange(clients_per_round),
                                   pad_to=clients_per_round)
            _beat()  # each unrolled twin is its own (long) compile
            return _compiled_flops(step, params, cohort, jax.random.key(0))

    a, b, c = f_at(1, t_lo), f_at(2, t_lo), f_at(1, t_hi)
    per_token = max(c - a, 0.0) / (t_hi - t_lo)
    step_t = max(b - a, 0.0) + (seq_len - t_lo) * per_token
    return max(2 * a - b, 0.0) + n_steps * step_t, n_steps


def _synth_clients(n_clients, samples, shape, classes, seed=0):
    rng = np.random.RandomState(seed)
    xs = [rng.randn(samples, *shape).astype(np.float32)
          for _ in range(n_clients)]
    ys = [rng.randint(0, classes, samples).astype(np.int32)
          for _ in range(n_clients)]
    return xs, ys


def _build_step(model, classes, lr, epochs, batch_size, xs, ys, mesh=None,
                workload=None, scan_unroll=1, client_axis="vmap"):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.data.stacking import stack_client_data, gather_cohort
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    stacked = stack_client_data(xs, ys, batch_size)
    if workload is None:
        workload = ClassificationWorkload(model, num_classes=classes,
                                          compute_dtype=_compute_dtype())
    local = make_local_trainer(workload,
                               make_client_optimizer("sgd", lr), epochs,
                               scan_unroll=scan_unroll)
    step = make_cohort_step(local, mesh=mesh, client_axis=client_axis)
    params = workload.init(jax.random.key(0), jax.tree.map(
        lambda v: jnp.asarray(v[0, 0]),
        {k: stacked[k] for k in ("x", "y", "mask")}))
    return step, params, stacked


_SPREAD_MIN_ROUND_S = 0.02  # per-round blocking is noise below this


def _round_spread(run_round, params, rounds):
    """Per-round BLOCKED wall times -> {median, p10, p90, max} seconds.

    The amortized loop hides run-to-run jitter (the round-2 artifact showed
    an unexplained 2x step-time spread on resnet56 through the TPU tunnel);
    blocking per round costs one host sync each, negligible once a round is
    >= _SPREAD_MIN_ROUND_S, and pins whether an outlier mean comes from a
    fat tail or a level shift."""
    import jax
    times = []
    for i in range(rounds):
        _beat()
        t0 = _now()
        params, _ = run_round(params, i)
        jax.block_until_ready(params)
        times.append(_now() - t0)
    ts = np.asarray(times)
    return {"mean": float(ts.mean()), "median": float(np.median(ts)),
            "p10": float(np.percentile(ts, 10)),
            "p90": float(np.percentile(ts, 90)),
            "max": float(ts.max()), "n": len(ts)}


def _measure(step, params, stacked, clients_per_round, total_clients,
             rounds, spread=False):
    """Compile once, then time `rounds` rounds; returns round_s (amortized)
    or (round_s, spread_stats) when ``spread``.  (FLOPs come separately
    from _honest_flops — the full program's cost analysis counts its scan
    bodies once and is NOT a per-round number.)"""
    import jax
    from fedml_tpu.core.sampling import sample_clients
    from fedml_tpu.data.stacking import gather_cohort

    def round_args(i):
        ids = sample_clients(i, total_clients, clients_per_round)
        return (gather_cohort(stacked, ids, pad_to=clients_per_round),
                jax.random.key(i))

    cohort, rng = round_args(0)
    params, _ = step(params, cohort, rng)          # warmup/compile
    jax.block_until_ready(params)
    _beat()
    probe_s = 0.0
    if spread:  # one POST-compile round estimates the per-round cost
        cohort, rng = round_args(0)
        t0 = _now()
        params, _ = step(params, cohort, rng)
        jax.block_until_ready(params)
        probe_s = _now() - t0
    if spread and probe_s >= _SPREAD_MIN_ROUND_S:
        # slow config: ONE blocked loop yields both the amortized mean and
        # the per-round spread (blocking costs a host sync per round —
        # negligible at this scale, and no duplicated measurement)
        def run_round(p, i):
            cohort, rng = round_args(1 + i)
            return step(p, cohort, rng)
        stats = _round_spread(run_round, params, max(rounds, 5))
        return stats["mean"], stats
    t0 = _now()
    for i in range(1, rounds + 1):
        cohort, rng = round_args(i)
        params, _ = step(params, cohort, rng)
    jax.block_until_ready(params)
    round_s = (_now() - t0) / rounds
    return (round_s, None) if spread else round_s


# the FEMNIST headline config, shared by the dispatch and scanned benches so
# the two rounds/s numbers always measure the same workload
# (benchmark/README.md:54: 2-conv CNN, B=20, E=1, sgd lr=0.1, 62 classes)
FEMNIST_CLASSES = 62
FEMNIST_LR = 0.1
FEMNIST_EPOCHS = 1
FEMNIST_BATCH = 20


def _femnist_data(clients_per_round):
    samples = int(os.environ.get("BENCH_FEMNIST_SAMPLES", "200"))
    return _synth_clients(max(128, clients_per_round), samples,
                          (28, 28, 1), FEMNIST_CLASSES)


def bench_femnist_cnn(rounds, clients_per_round=10, mesh=None,
                      on_device=True, flops_base=None):
    """benchmark/README.md:54 config on synthetic FEMNIST-shaped data.
    Returns (round_s, flops_per_round, steps_per_round).

    ``on_device`` (single-chip only): HBM-resident dataset + in-jit cohort
    gather (make_device_round) — the production fast path; False measures
    the host-gather + re-upload path for comparison.  ``flops_base`` is an
    optional (flops, steps, base_clients) from a previous call — per-round
    FLOPs are linear in cohort size (per-client training and aggregation
    both scale with clients), so the scaling curve reuses one twin
    measurement instead of recompiling twins per cohort size."""
    from fedml_tpu.models import CNNOriginalFedAvg
    xs, ys = _femnist_data(clients_per_round)
    model = CNNOriginalFedAvg(only_digits=False)
    if flops_base is None:
        flops, steps = _honest_flops(
            model, FEMNIST_CLASSES, FEMNIST_LR, FEMNIST_EPOCHS,
            FEMNIST_BATCH, xs, ys, clients_per_round)
    else:
        f0, steps, base_clients = flops_base
        flops = f0 * clients_per_round / base_clients
    if on_device and mesh is None:
        round_s = _measure_device(
            model, FEMNIST_CLASSES, FEMNIST_LR, FEMNIST_EPOCHS,
            FEMNIST_BATCH, xs, ys, clients_per_round, rounds)
        return round_s, flops, steps
    step, params, stacked = _build_step(
        model, FEMNIST_CLASSES, lr=FEMNIST_LR, epochs=FEMNIST_EPOCHS,
        batch_size=FEMNIST_BATCH, xs=xs, ys=ys, mesh=mesh)
    round_s = _measure(step, params, stacked, clients_per_round, len(xs),
                       rounds)
    return round_s, flops, steps


def _device_setup(model, classes, lr, epochs, batch_size, xs, ys):
    """Shared HBM-resident staging for the device-round / scanned benches:
    (local_train, params, stacked_dev)."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.data.stacking import stack_client_data
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    stacked = stack_client_data(xs, ys, batch_size)
    workload = ClassificationWorkload(model, num_classes=classes,
                                      compute_dtype=_compute_dtype())
    local = make_local_trainer(workload,
                               make_client_optimizer("sgd", lr), epochs)
    params = workload.init(jax.random.key(0), jax.tree.map(
        lambda v: jnp.asarray(v[0, 0]),
        {k: stacked[k] for k in ("x", "y", "mask")}))
    stacked_dev = {k: jnp.asarray(v) for k, v in stacked.items()}
    return local, params, stacked_dev


def _measure_device(model, classes, lr, epochs, batch_size, xs, ys,
                    clients_per_round, rounds):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.core.sampling import sample_clients
    from fedml_tpu.parallel.cohort import make_device_round

    local, params, stacked_dev = _device_setup(
        model, classes, lr, epochs, batch_size, xs, ys)
    round_fn = make_device_round(local, clients_per_round)
    live = jnp.ones(clients_per_round, jnp.float32)

    def ids_for(i):
        ids = sample_clients(i, len(xs), clients_per_round)
        return jnp.asarray(ids.astype(np.int32))

    args0 = (params, stacked_dev, ids_for(0), live, jax.random.key(0))
    params, _ = round_fn(*args0)
    jax.block_until_ready(params)
    _beat()
    t0 = _now()
    for i in range(1, rounds + 1):
        params, _ = round_fn(params, stacked_dev, ids_for(i), live,
                             jax.random.key(i))
    jax.block_until_ready(params)
    return (_now() - t0) / rounds


def bench_femnist_cnn_scanned(rounds, clients_per_round=10, k=20):
    """The dispatch-amortised fast path: lax.scan over K rounds per device
    dispatch (make_scanned_rounds).  At sub-ms round times the host loop is
    latency-bound — this measures the true on-chip round rate.  Returns
    round_s only; per-round FLOPs are the dispatch config's (identical
    hyperparameters by construction — shared FEMNIST_* constants)."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.core.sampling import sample_clients
    from fedml_tpu.models import CNNOriginalFedAvg
    from fedml_tpu.parallel.cohort import make_scanned_rounds

    xs, ys = _femnist_data(clients_per_round)
    # identical workload/hparams to the dispatch headline (shared FEMNIST_*
    # constants) so the two numbers compare only the dispatch model
    local, params, stacked_dev = _device_setup(
        CNNOriginalFedAvg(only_digits=False), FEMNIST_CLASSES, FEMNIST_LR,
        FEMNIST_EPOCHS, FEMNIST_BATCH, xs, ys)
    rounds_fn = make_scanned_rounds(local, clients_per_round)

    def ids_for(chunk):
        ids = np.stack([sample_clients(chunk * k + i, len(xs),
                                       clients_per_round)
                        for i in range(k)]).astype(np.int32)
        return jnp.asarray(ids), jnp.ones((k, clients_per_round), jnp.float32)

    ids, live = ids_for(0)
    args0 = (params, stacked_dev, ids, live, jax.random.key(0))
    params, _ = rounds_fn(*args0)     # warmup/compile
    jax.block_until_ready(params)
    _beat()
    n_chunks = max(1, rounds // k)
    t0 = _now()
    for c in range(1, n_chunks + 1):
        ids, live = ids_for(c)
        params, _ = rounds_fn(params, stacked_dev, ids, live,
                              jax.random.key(c))
    jax.block_until_ready(params)
    return (_now() - t0) / (n_chunks * k)


def bench_resnet56_cifar10(rounds, mesh=None, samples=512, epochs=1,
                           client_axis=None):
    """Flagship cross-silo config (benchmark/README.md:105): 10 clients,
    B=64; ``epochs`` local epochs measured (published runs use E=20 of
    5000 samples — pass epochs=20 for the exact config).  Returns
    (round_s, flops, steps).

    ``client_axis`` ("vmap" | "scan", env BENCH_R56_CLIENT_AXIS):
    concurrent clients lower per-client conv kernels to GROUPED convs —
    at 16/32/64 channels each group fills a sliver of the 128-wide MXU
    tile, the leading suspect for the ~1% committed MFU; "scan" trains
    clients sequentially with dense convs.  tpu_capture.sh measures both.
    """
    from fedml_tpu.models import resnet56
    client_axis = client_axis or os.environ.get(
        "BENCH_R56_CLIENT_AXIS", "vmap")
    xs, ys = _synth_clients(10, samples, (32, 32, 3), 10)
    flops, steps = _honest_flops(resnet56(10), 10, 0.001, epochs, 64,
                                 xs, ys, 10)
    step, params, stacked = _build_step(
        resnet56(10), 10, lr=0.001, epochs=epochs, batch_size=64, xs=xs,
        ys=ys, mesh=mesh, client_axis=client_axis)
    round_s, spread = _measure(step, params, stacked, 10, 10, rounds,
                               spread=True)
    return round_s, flops, steps, spread


def bench_shakespeare_rnn(rounds, clients_per_round=10):
    """The NLP family config (benchmark/README.md shakespeare row): 2-layer
    LSTM(256) char LM, B=4, seq 80 — recurrence compiles to lax.scan.
    Returns (round_s, flops, steps).

    The FLOPs come from _rnn_round_flops (cell scan extrapolated over
    sequence length): without it, cost analysis counts the 80-step cell
    scan once and the honest per-step cost is off by ~T (the round-2
    artifact's 0.14% "MFU" was this accounting artifact, not a slow
    kernel)."""
    from fedml_tpu.experiments.models import create_workload

    rng = np.random.RandomState(0)
    samples = int(os.environ.get("BENCH_RNN_SAMPLES", "32"))
    xs = [rng.randint(1, 90, (samples, 80)).astype(np.int32)
          for _ in range(max(32, clients_per_round))]
    ys = [np.concatenate([x[:, 1:], x[:, :1]], axis=1) for x in xs]
    # create_workload owns the model-dtype/workload-dtype coupling
    wl = create_workload("rnn", "shakespeare", 90, (80,),
                         compute_dtype=os.environ.get("BENCH_DTYPE", ""))
    n_steps = max(1, -(-samples // 4))
    flops, steps = _rnn_round_flops(_compute_dtype(), clients_per_round,
                                    n_steps)
    step, params, stacked = _build_step(
        None, 90, lr=0.8, epochs=1, batch_size=4, xs=xs, ys=ys, workload=wl)
    round_s = _measure(step, params, stacked, clients_per_round, len(xs),
                       rounds)
    return round_s, flops, steps


def bench_longcontext_transformer(steps=10, seq_len=2048, batch=2,
                                  block=256, use_flash=False,
                                  moe_experts=0):
    """Long-context single-chip training step (the capability the
    reference's LSTM zoo caps at 80 tokens): TransformerLM grad step at
    ``seq_len`` with flash-style kv blocking (or the pallas flash kernel
    when ``use_flash``).  ``moe_experts`` swaps the FFN for the Switch
    MoE layer (models/moe.py) — the routed-capacity timing point.
    Returns (step_s, tokens_per_s)."""
    import jax
    import jax.numpy as jnp
    import optax
    from fedml_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=256, d_model=256, n_heads=8,
                          n_layers=2, d_ff=1024, max_len=seq_len,
                          block_size=None if use_flash else block,
                          use_flash=use_flash,
                          moe_experts=moe_experts,
                          dtype=_compute_dtype())
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, 256, (batch, seq_len)), jnp.int32)
    params = model.init(jax.random.key(0), toks)["params"]

    def loss_fn(p, x):
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        y = jnp.concatenate([x[:, 1:], x[:, :1]], axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    grad = jax.jit(jax.grad(loss_fn))
    g = grad(params, toks)
    jax.block_until_ready(g)
    t0 = _now()
    for _ in range(steps):
        g = grad(params, toks)
    jax.block_until_ready(g)
    step_s = (_now() - t0) / steps
    return step_s, batch * seq_len / step_s


def bench_robust_backends(rounds, clients_per_round=10):
    """Defended FedAvg round (clip + weak-DP), XLA transform hook vs the
    fused Pallas aggregation kernel (core/pallas_agg.py) — same model and
    hparams as the femnist headline so the delta is the defense path."""
    import jax
    from fedml_tpu.core.pallas_agg import make_fused_robust_aggregate
    from fedml_tpu.core.robust import add_gaussian_noise, clip_update
    from fedml_tpu.models import CNNOriginalFedAvg
    from fedml_tpu.parallel.cohort import make_cohort_step
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    xs, ys = _femnist_data(clients_per_round)
    workload = ClassificationWorkload(CNNOriginalFedAvg(only_digits=False),
                                      num_classes=FEMNIST_CLASSES,
                                      compute_dtype=_compute_dtype())
    local = make_local_trainer(
        workload, make_client_optimizer("sgd", FEMNIST_LR), FEMNIST_EPOCHS)

    def transform(client_params, global_params, rng):
        p = clip_update(client_params, global_params, 5.0)
        return add_gaussian_noise(p, rng, 0.025)

    fused = make_fused_robust_aggregate(
        norm_bound=5.0, noise_std=0.025,
        interpret=jax.default_backend() != "tpu")
    from fedml_tpu.data.stacking import stack_client_data
    import jax.numpy as jnp
    stacked = stack_client_data(xs, ys, FEMNIST_BATCH)
    params = workload.init(jax.random.key(0), jax.tree.map(
        lambda v: jnp.asarray(v[0, 0]),
        {k: stacked[k] for k in ("x", "y", "mask")}))
    out = {}
    for name, step in (
            ("xla", make_cohort_step(local, transform_update=transform)),
            ("pallas", make_cohort_step(local, aggregate=fused))):
        out[name] = _measure(step, params, stacked, clients_per_round,
                             len(xs), rounds)
    return out


def bench_matmul_peak(n=4096, iters=24):
    """Empirical MXU throughput floor: achieved TF/s on a chained dense
    [n,n]x[n,n] matmul, bf16 and f32.

    Round-4 motivation: with the honest per-trip FLOPs accounting in
    place, the femnist configs still read MFU > 1.0 against the
    device_kind table peak ("TPU v5 lite" -> 197 TF/s bf16), and a hand
    count of the CNN's conv/fc MACs CONFIRMS the per-round FLOPs number
    — so the peak assumption, not the accounting, is what's broken (the
    tunnel's device_kind string is not trustworthy evidence of the
    attached silicon).  A plain matmul can't exceed the chip's real peak,
    so its achieved rate is a hard lower bound; when it beats the table
    value, MFU is quoted against it instead."""
    import jax
    import jax.numpy as jnp

    out = {}
    rng = np.random.RandomState(0)
    # ~N(0,1) columns keep the chained product's scale stable (no
    # overflow-to-inf values in the timing loop)
    b0 = (rng.randn(n, n) / np.sqrt(n)).astype(np.float32)
    for name, dt in (("bf16", jnp.bfloat16), ("f32", jnp.float32)):
        a = jnp.asarray(rng.randn(n, n).astype(np.float32), dtype=dt)
        b = jnp.asarray(b0, dtype=dt)
        f = jax.jit(lambda x, y: x @ y)
        r = f(a, b)
        jax.block_until_ready(r)
        _beat()
        t0 = _now()
        for _ in range(iters):
            r = f(r, b)
        jax.block_until_ready(r)
        out[name] = 2.0 * n ** 3 * iters / (_now() - t0) / 1e12
    return out


_LINEARITY_BAND = (1.7, 2.3)
# no announced TPU exceeds 918 TF/s bf16 dense (v6e); a measured "peak"
# beyond 2x that is timer failure, not silicon
_PEAK_SANITY_CAP_TFLOPS = 1836.0


def bench_timing_sanity(n=4096, iters=16):
    """Host-timing trust gate: evidence that timed loops measure real device
    execution.  Round-4 verdict: femnist MFU read 1.14/3.08 — physically
    impossible — implying ``block_until_ready`` through the experimental
    tunnel may not synchronize; every headline number hangs on that
    primitive, so prove it before measuring anything.

    Three checks on a chained [n,n] matmul (bf16 on accelerators; the
    multiplier's spectral radius is ~1/2, so the chain neither overflows
    nor folds to a constant):

    * sync:      t_block(R) vs t_sync(R), where t_sync ends at a host
                 ``float()`` readback of a scalar REDUCED FROM THE RESULT —
                 a synchronization that cannot be faked (the scalar depends
                 on every chained matmul).  A broken block_until_ready
                 shows t_block << t_sync.
    * linearity: t_sync(2R)/t_sync(R) ~ 2 within _LINEARITY_BAND — a timer
                 blind to device work reads near-constant instead.  The
                 iteration count auto-grows until the timed work dwarfs
                 the measured constant readback/dispatch overhead (tens
                 of ms through the tunnel), so a REAL backend with a
                 slow control path cannot fail the band spuriously.
    * checksum:  the readback scalar must be finite, and its existence
                 means XLA could not dead-code the timed work.

    All three must hold for ``trusted``; main() quarantines the whole
    capture (exit 3, nothing promoted to a committed artifact name) when
    they don't.  Returns the evidence dict either way.
    """
    import jax
    import jax.numpy as jnp

    dt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
    rng = np.random.RandomState(0)
    b = jnp.asarray((rng.randn(n, n) / (2.0 * np.sqrt(n))).astype(
        np.float32), dt)
    a = jnp.asarray(rng.randn(n, n).astype(np.float32), dt)
    f = jax.jit(lambda x, y: x @ y)
    summ = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def chain(k):
        x = a
        for _ in range(k):
            x = f(x, b)
        return x

    float(summ(chain(2)))  # compile both programs outside the timings

    def t_block(k):
        _beat()
        t0 = _now()
        jax.block_until_ready(chain(k))
        return _now() - t0

    def t_sync(k):
        _beat()
        t0 = _now()
        s = float(summ(chain(k)))
        return _now() - t0, s

    # constant per-call overhead estimate (dispatch + readback RTT —
    # through the tunnel this can be tens of ms): one near-zero-work
    # readback.  The linearity test compares t(2R)/t(R); with constant
    # overhead r it reads (2W+r)/(W+r), so W must dwarf r or a REAL
    # backend fails the band — grow iters until the timed work does.
    t0 = _now()
    float(summ(a))
    rtt = _now() - t0
    target = max(0.05, 20.0 * rtt)

    def measured(k, reps=3):
        # min-of-N before ANY decision: load spikes are strictly
        # additive noise, so min estimates the true time; a single
        # inflated sample must neither end growth early nor skew the
        # band ratio (observed on this 1-core host: min-of-2 left the
        # ratio brushing the band edges under the watcher's probes)
        t1, c = t_sync(k)
        for _ in range(reps - 1):
            t1 = min(t1, t_sync(k)[0])
        return t1, c

    ts1, checksum = measured(iters, reps=2)
    while ts1 < target and iters < 1024:
        # jump straight to the projected count (step-doubling would
        # re-time the chain log-many times, each paying the tunnel RTT)
        est = max(ts1 - rtt, 1e-6) / iters
        need = max((target - rtt) / est, 2.0 * iters)
        iters = int(min(1024, 2.0 ** np.ceil(np.log2(need))))
        ts1, checksum = measured(iters, reps=2)
    ts1 = min(ts1, measured(iters, reps=1)[0])  # 3rd sample at final size
    growth_capped = ts1 < target
    tb = min(t_block(iters), t_block(iters))
    ts2, _ = measured(2 * iters, reps=3)
    ratio = ts2 / max(ts1, 1e-9)
    sync_ratio = ts1 / max(tb, 1e-9)
    failures = []
    if not (_LINEARITY_BAND[0] <= ratio <= _LINEARITY_BAND[1]):
        msg = (f"linearity: t_sync(2R)/t_sync(R)={ratio:.2f} outside "
               f"{list(_LINEARITY_BAND)} — the timer is not measuring "
               "the device work")
        if growth_capped:
            msg += (f" [iters capped at {iters} before timed work "
                    f"dwarfed the {rtt * 1e3:.0f} ms per-call overhead; "
                    "this failure may be overhead-domination, not a "
                    "broken timer]")
        failures.append(msg)
    if sync_ratio > 1.5:
        failures.append(
            f"sync: readback-synced loop is {sync_ratio:.2f}x the "
            "block_until_ready loop — block_until_ready does not "
            "synchronize on this backend")
    if not np.isfinite(checksum):
        failures.append(f"checksum not finite ({checksum})")
    return {"n": n, "iters_R": iters, "t_block_R_s": tb, "t_sync_R_s": ts1,
            "t_sync_2R_s": ts2, "linearity_ratio": ratio,
            "sync_ratio": sync_ratio, "checksum": checksum,
            "readback_rtt_s": rtt, "growth_capped": growth_capped,
            "band": list(_LINEARITY_BAND), "trusted": not failures,
            "failures": failures,
            "tflops_readback_verified": 2.0 * n ** 3 * iters / ts1 / 1e12}


def run_timing_gate(on_cpu: bool = False):
    """THE timing-trust gate, shared by main() and the capture script's
    resnet56 grid stage so the two cannot drift (the same one-place
    principle as promote_partial): sanity probe with one retry — a
    transient host-load spike must not burn a live tunnel window — then
    the matmul-peak plausibility cap.  Returns ``(sanity, mm, failures)``;
    ``failures`` empty means the capture may proceed, ``mm`` is None on
    explicit-CPU runs."""
    kw = {"n": 512, "iters": 4} if on_cpu else {}
    _beat("timing sanity (linearity + readback sync)")
    sanity = bench_timing_sanity(**kw)
    if not sanity["trusted"]:
        _beat("timing sanity (retry)")
        sanity = bench_timing_sanity(**kw)
        sanity["retried"] = True
    failures = list(sanity["failures"])
    mm = None
    if not on_cpu:
        _beat("matmul peak probe")
        mm = bench_matmul_peak()
        if mm["bf16"] > _PEAK_SANITY_CAP_TFLOPS:
            failures.append(
                f"measured bf16 matmul {mm['bf16']:.0f} TF/s exceeds any "
                f"announced TPU peak (cap {_PEAK_SANITY_CAP_TFLOPS:.0f}) — "
                "timer failure, not silicon")
    return sanity, mm, failures


def bench_agg_kernels_flagship(iters=30, clients=10, workload=None,
                               sample_shape=(8, 32, 32, 3)):
    """Do the Pallas kernels earn their keep at flagship sizes?  (Round-4
    verdict item 6: the committed femnist-size reading was 1.05x — decide
    with flagship-size bf16 measurements, then justify or demote.)

    Aggregation-only microbenches at resnet56 parameter size (~0.85M
    params x 10 clients, the published CIFAR10 cross-silo shape):

    * robust aggregate (clip + weak-DP + weighted mean): fused Pallas
      kernel (core/pallas_agg.py) vs the XLA compose
      ``tree_weighted_mean(vmap(clip+noise))`` — f32 and bf16 stacked
      updates (bf16 halves the HBM traffic the kernel exists to save).
    * SecAgg quantize+mask: ``SecureCohortAggregator.mask_update`` with
      backend="pallas" (secure/pallas_mask.py) vs "xla" — f32, the
      quantization domain.

    Returns {row: {xla_ms, pallas_ms, speedup}}.  TPU-only in main():
    the interpreter path is not a perf number — but ``workload``/
    ``sample_shape`` are injectable so the wiring (tree shapes, fused
    kernel API, SecureCohortAggregator surface) is unit-testable on CPU
    at toy size (tests/test_bench_unit.py); a wiring break discovered
    mid-capture would cost a live tunnel window.
    """
    import jax
    import jax.numpy as jnp
    from fedml_tpu.core.pallas_agg import make_fused_robust_aggregate
    from fedml_tpu.core.pytree import tree_weighted_mean
    from fedml_tpu.core.robust import add_gaussian_noise, clip_update
    from fedml_tpu.models import resnet56
    from fedml_tpu.secure.secagg import SecureCohortAggregator
    from fedml_tpu.trainer.workload import ClassificationWorkload

    wl = workload or ClassificationWorkload(resnet56(10), num_classes=10)
    batch = {"x": jnp.zeros(sample_shape, jnp.float32),
             "y": jnp.zeros((sample_shape[0],), jnp.int32),
             "mask": jnp.ones((sample_shape[0],), jnp.float32)}
    params = wl.init(jax.random.key(0), batch)
    weights = jnp.ones((clients,), jnp.float32)
    interpret = jax.default_backend() != "tpu"
    fused = make_fused_robust_aggregate(5.0, 0.025, interpret=interpret)

    def stack(dt):
        # distinct per-client offsets so nothing collapses to a broadcast
        return jax.tree.map(
            lambda p: (p[None].astype(dt)
                       + (jnp.arange(1, clients + 1, dtype=jnp.float32)
                          * 1e-3).astype(dt).reshape(
                              (clients,) + (1,) * p.ndim)),
            params)

    def xla_agg(stacked, g, rng):
        def per_client(c, k):
            return add_gaussian_noise(clip_update(c, g, 5.0), k, 0.025)
        return tree_weighted_mean(
            jax.vmap(per_client)(stacked, jax.random.split(rng, clients)),
            weights)

    def timed_ms(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        _beat()
        t0 = _now()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return 1e3 * (_now() - t0) / iters

    rows = {}
    rng = jax.random.key(0)
    for name, dt in (("robust_agg_r56_f32", jnp.float32),
                     ("robust_agg_r56_bf16", jnp.bfloat16)):
        stacked = stack(dt)
        g = jax.tree.map(lambda p: p.astype(dt), params)
        xla_ms = timed_ms(jax.jit(xla_agg), stacked, g, rng)
        pal_ms = timed_ms(
            jax.jit(lambda s, gg, r: fused(s, weights, gg, r)),
            stacked, g, rng)
        rows[name] = {"xla_ms": xla_ms, "pallas_ms": pal_ms,
                      "speedup": xla_ms / pal_ms}

    stacked32 = stack(jnp.float32)
    one_update = jax.tree.map(lambda v: v[0], stacked32)
    for name, backend in (("secagg_mask_r56_f32", "pallas"),):
        agg_x = SecureCohortAggregator(clients, backend="xla")
        agg_p = SecureCohortAggregator(clients, backend=backend)
        xla_ms = timed_ms(
            jax.jit(lambda u, k: agg_x.mask_update(u, 1.0, 0, k)),
            one_update, rng)
        pal_ms = timed_ms(
            jax.jit(lambda u, k: agg_p.mask_update(u, 1.0, 0, k)),
            one_update, rng)
        rows[name] = {"xla_ms": xla_ms, "pallas_ms": pal_ms,
                      "speedup": xla_ms / pal_ms}
    return rows


def bench_twin_backend_delta(cpu_flops, clients_per_round=10):
    """Advisor r4 (bench.py _twin_device_ctx): cost-analysis FLOPs are a
    property of the post-optimization HLO, which is backend-specific —
    compile the femnist twins on the DEVICE backend too and record the
    relative per-round delta vs the CPU-twin number the headline already
    uses (``cpu_flops``, from bench_femnist_cnn's identical
    model/constants/data), so a divergence is detectable instead of
    silent.  Returns {cpu_flops, device_flops, rel_delta}."""
    from fedml_tpu.models import CNNOriginalFedAvg

    xs, ys = _femnist_data(clients_per_round)
    model = CNNOriginalFedAvg(only_digits=False)
    old = os.environ.get("BENCH_TWIN_DEVICE")
    os.environ["BENCH_TWIN_DEVICE"] = "default"
    try:
        dev_f, _ = _honest_flops(
            model, FEMNIST_CLASSES, FEMNIST_LR, FEMNIST_EPOCHS,
            FEMNIST_BATCH, xs, ys, clients_per_round)
    finally:
        if old is None:
            os.environ.pop("BENCH_TWIN_DEVICE", None)
        else:
            os.environ["BENCH_TWIN_DEVICE"] = old
    return {"cpu_flops": cpu_flops, "device_flops": dev_f,
            "rel_delta": abs(dev_f - cpu_flops) / max(cpu_flops, 1.0)}


def bench_torch_baseline(clients_per_round=10, batch_size=20):
    """The reference's standalone simulator loop (sequential clients,
    fedavg_api.py:52-66) in torch on this host's CPU — an architectural
    comparison point, not a hardware-parity baseline."""
    try:
        import torch
        import torch.nn as nn
    except Exception:
        return None

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(1, 32, 5, padding=2)
            self.c2 = nn.Conv2d(32, 64, 5, padding=2)
            self.f1 = nn.Linear(3136, 512)
            self.f2 = nn.Linear(512, 62)
            self.pool = nn.MaxPool2d(2, 2)

        def forward(self, x):
            x = self.pool(torch.relu(self.c1(x)))
            x = self.pool(torch.relu(self.c2(x)))
            return self.f2(torch.relu(self.f1(x.flatten(1))))

    torch.manual_seed(0)
    model = CNN()
    crit = nn.CrossEntropyLoss()
    # same samples/client as the jax side (BENCH_FEMNIST_SAMPLES) so the
    # vs_baseline ratio always compares identical workloads
    samples = int(os.environ.get("BENCH_FEMNIST_SAMPLES", "200"))
    xs, ys = _synth_clients(clients_per_round, samples, (28, 28, 1), 62)
    t0 = _now()
    for c in range(clients_per_round):
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        x = torch.from_numpy(xs[c]).permute(0, 3, 1, 2)
        y = torch.from_numpy(ys[c]).long()
        for s in range(0, len(x), batch_size):
            opt.zero_grad()
            loss = crit(model(x[s:s + batch_size]), y[s:s + batch_size])
            loss.backward()
            opt.step()
    return _now() - t0


def _mfu(flops, seconds):
    if not flops or not seconds:
        return 0.0
    return (flops / seconds) / (PEAK_TFLOPS * 1e12)


def _max_mfu(details) -> float:
    """Largest MFU anywhere in a details artifact.  The promotion contract
    keys on this: mfu > 1.0 is physically impossible, so such an artifact
    documents a timing failure, not performance.

    Delegates to `fedml_tpu.obs.trend.max_mfu` — the same recursive scan
    `scripts/perf_trend.py --lint_mfu` runs over committed artifacts — so
    the promotion/carry refusal contract and the CI lint can never
    disagree about what an artifact claims (a nested scaling-curve cell
    counts in both or neither)."""
    from fedml_tpu.obs.trend import max_mfu
    return max_mfu(details)


def _quarantine(reason: str):
    """Timing cannot be trusted: write the evidence to <out>.untrusted —
    the committed artifact names stay untouched — emit one honest JSON
    line, and exit 3 so tpu_capture.sh/tpu_watch.sh retry the capture
    instead of declaring it complete (round-4 verdict item 1: no artifact
    whose timing fails the self-check may be promoted)."""
    d = dict(_WATCH.get("details") or {})
    out = _WATCH.get("out")
    d["timing_untrusted"] = reason
    d["captured_at"] = time.time()
    if out:
        with open(_repo_path(out + ".untrusted"), "w") as f:
            json.dump(d, f, indent=2)
        if _WATCH.get("checkpointed"):
            # an untrusted run must not leave a promotable checkpoint —
            # but only delete a .partial THIS run wrote; an earlier run's
            # unpromoted trusted measurements are not ours to destroy
            try:
                os.remove(_repo_path(out + ".partial"))
            except OSError:
                pass
    print(json.dumps({
        "metric": "fedavg_round_time_femnist_cnn", "value": None,
        "unit": "rounds/sec", "timing_untrusted": reason,
        "skipped": "timing self-check failed; nothing measured this run "
                   "is trustworthy"}), flush=True)
    sys.exit(3)


def _backend_alive(timeout_s: float = 120.0) -> bool:
    """Probe the default jax backend in a SUBPROCESS with a timeout: the
    TPU tunnel can wedge such that the first device op blocks forever
    (verify skill, 'tunnel can wedge') — a hung bench leaves the round
    with no BENCH artifact at all, which is worse than CPU numbers."""
    import subprocess
    code = ("import jax, jax.numpy as jnp; "
            "jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8))); "
            "print('alive')")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and b"alive" in proc.stdout


def _repo_path(name):
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


# ---------------------------------------------------------------------------
# Mid-run wedge protection.  Round 4 observed the failure mode directly: the
# 120 s _backend_alive probe PASSED, then the first heavy compile RPC blocked
# in recvfrom forever (tunnel wedged between probe and compile).  A hung
# bench is the worst outcome for the round — no artifact at all, and every
# config measured before the wedge is lost.  So: a heartbeat (_beat) marks
# progress; completed configs are checkpointed to <out>.partial as they
# land; a daemon watchdog hard-exits with an honest partial JSON line if the
# heartbeat stalls.  BENCH_STALL_S overrides the threshold (0 disables).
_WATCH = {"beat": 0.0, "stage": "init", "details": None, "out": None,
          "torch_s": None, "done_line": None, "checkpointed": False}


def _beat(stage=None):
    _WATCH["beat"] = time.monotonic()
    if stage is not None:
        _WATCH["stage"] = stage


def _checkpoint_partial():
    """Persist measured-so-far configs; removed again on clean completion."""
    _beat()
    d, out = _WATCH.get("details"), _WATCH.get("out")
    if not d or not out:
        return
    part = dict(d)
    part["partial_next_stage"] = _WATCH["stage"]
    part["captured_at"] = time.time()  # freshness key (_emit_skipped)
    with open(_repo_path(out + ".partial"), "w") as f:
        json.dump(part, f, indent=2)
    _WATCH["checkpointed"] = True  # this run owns the .partial now


def _emit_stalled():
    """Watchdog path: write the partial artifact + ONE honest JSON line from
    whatever finished before the wedge, then hard-exit NONZERO (the main
    thread is unrecoverable — blocked inside a C++ RPC that ignores
    signals).  Exit 3 distinguishes partial-from-wedge from success so
    tpu_capture.sh / tpu_watch.sh keep retrying the canonical artifact
    instead of declaring the capture complete."""
    _checkpoint_partial()
    d = _WATCH.get("details") or {}
    stage = _WATCH.get("stage")
    cfgs = d.get("configs", {})
    disp = cfgs.get("femnist_cnn_c10", {}).get("rounds_per_s")
    scan = cfgs.get("femnist_cnn_c10_scan20", {}).get("rounds_per_s")
    if (disp or scan) and _max_mfu(d) > 1.0:
        # same contract as promote_partial/_emit_skipped: configs whose
        # MFU exceeds 1.0 are timing fiction — never quote them as the
        # round's evidence line (the .partial stays on disk for forensics;
        # promotion refuses it)
        sys.stderr.write(
            f"bench watchdog: stalled in {stage!r}; measured configs "
            f"report mfu {_max_mfu(d):.2f} > 1.0 — timing untrusted, "
            "values not quoted\n")
        _emit_skipped(partial_stage=stage)
        os._exit(3)
    if disp or scan:
        best = max(filter(None, (disp, scan)))
        line = {"metric": "fedavg_round_time_femnist_cnn",
                "value": round(best, 3), "unit": "rounds/sec",
                "platform": d.get("platform"),
                "device_kind": d.get("device_kind"),
                "partial": "tunnel wedged mid-run during stage "
                           f"{stage!r}; these values WERE measured this "
                           "run on the live chip before the wedge",
                "rounds_per_s_dispatch": disp and round(disp, 3),
                "rounds_per_s_scan20": scan and round(scan, 3)}
        if _WATCH.get("torch_s"):
            line["vs_baseline"] = round(_WATCH["torch_s"] * best, 3)
        if "mfu" in cfgs.get("femnist_cnn_c10", {}):
            line["mfu_femnist"] = round(cfgs["femnist_cnn_c10"]["mfu"], 4)
        print(json.dumps(line), flush=True)
    else:
        sys.stderr.write(f"bench watchdog: stalled in {stage!r} with "
                         "nothing measured yet\n")
        _emit_skipped(partial_stage=stage)
    os._exit(3)


def _start_watchdog():
    import threading
    stall = float(os.environ.get("BENCH_STALL_S", "900"))
    if not stall:
        return
    _beat()

    def run():
        while True:
            time.sleep(10)
            if time.monotonic() - _WATCH["beat"] > stall:
                _emit_stalled()

    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


def _emit_skipped(partial_stage=None):
    """Backend unreachable: measure NOTHING.  Emit a skipped marker plus
    the best committed prior evidence, clearly labeled — never CPU numbers
    dressed as a comparison (round-2 verdict), and never a vs_baseline.

    Carried value: the FRESHER of a committed BENCH_PARTIAL_LATEST.json
    (real on-chip measurements from a partial capture, labeled partial)
    and the last clean BENCH_DETAILS.json (labeled stale) — compared by
    their ``captured_at`` stamps, so an old committed partial can never
    outrank a newer clean artifact."""
    line = {"metric": "fedavg_round_time_femnist_cnn", "value": None,
            "unit": "rounds/sec", "stale": True,
            "skipped": "accelerator backend unreachable (wedged tunnel?); "
                       "nothing measured this run"}
    if partial_stage is not None:
        line["skipped"] = ("tunnel answered the liveness probe, then "
                           f"wedged during {partial_stage!r} before any "
                           "config completed; nothing measured this run")

    refused = []

    def _load(name):
        try:
            with open(_repo_path(name)) as f:
                last = json.load(f)
        except Exception:
            return None
        if last.get("platform") in (None, "cpu"):
            return None
        if last.get("timing_untrusted") or _max_mfu(last) > 1.0:
            # the round-4 lesson: an artifact whose own MFU exceeds 1.0
            # documents a timing failure — its rounds/s must not be
            # carried forward as evidence either.  Say so, or a null
            # line reads like "never measured" instead of "retracted".
            why = (f"timing_untrusted ({last['timing_untrusted']})"
                   if last.get("timing_untrusted")
                   else f"max mfu {_max_mfu(last):.2f} > 1.0")
            refused.append(
                f"{name}: {why} — retracted under the timing trust "
                "contract; re-capture staged (scripts/tpu_capture.sh)")
            return None
        cfgs = last.get("configs", {})
        scan = cfgs.get("femnist_cnn_c10_scan20", {}).get("rounds_per_s")
        disp = cfgs.get("femnist_cnn_c10", {}).get("rounds_per_s")
        value = max(filter(None, (scan, disp)), default=None)
        if value is None:
            return None
        return {"platform": last.get("platform"), "value": value,
                "captured_at": float(last.get("captured_at", 0.0)),
                "rounds_per_s_dispatch": disp, "rounds_per_s_scan20": scan}

    partial, clean = (_load("BENCH_PARTIAL_LATEST.json"),
                      _load("BENCH_DETAILS.json"))
    if partial is not None and (
            clean is None
            or partial["captured_at"] > clean["captured_at"]):
        line["value"] = partial.pop("value")
        partial.pop("captured_at")
        partial["source"] = (
            "committed BENCH_PARTIAL_LATEST.json — REAL on-chip "
            "measurements from a PARTIAL capture newer than the last "
            "clean run (tunnel wedged before the full suite completed)")
        line["partial_capture"] = partial
        line["stale"] = False  # real measurement, just incomplete
        line["partial"] = True
    elif clean is not None:
        line["value"] = clean.pop("value")
        clean.pop("captured_at")
        clean["source"] = ("committed BENCH_DETAILS.json — STALE, from a "
                           "previous clean TPU run, not this one")
        line["last_good_tpu"] = clean
    if line["value"] is None and refused:
        line["committed_artifacts_refused"] = refused
    # an unreachable accelerator must not mean an EMPTY artifact (the
    # round-5 trajectory was all nulls): run the CPU wire/aggregation
    # microbench so the emitted JSON always carries a real measured
    # number — clearly labeled backend "cpu", never dressed as a TPU
    # figure (the headline metric above stays null/stale, honestly).
    # ONLY from the pre-flight path (partial_stage None): there jax has
    # never initialized a backend, so pinning the platform to cpu is
    # safe.  The watchdog's mid-run stall path already holds a live
    # (wedged) accelerator backend — a jit here would dispatch into the
    # wedge and hang the very thread that must os._exit(3).
    if partial_stage is None and not _accelerator_backend_live():
        try:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from fedml_tpu.utils.wirebench import cpu_fallback_bench
            line["cpu_fallback"] = cpu_fallback_bench()
        except Exception as exc:  # noqa: BLE001 — fallback must never mask
            line["cpu_fallback"] = {"backend": "cpu",
                                    "error": str(exc)[:160]}
    print(json.dumps(line))


def _accelerator_backend_live() -> bool:
    """True when this process already initialized a non-CPU jax backend
    (private API; absence reads as 'no live backend')."""
    try:
        from jax._src import xla_bridge
        return any(p != "cpu" for p in getattr(xla_bridge, "_backends", {}))
    except Exception:  # noqa: BLE001
        return False


def promote_partial() -> str:
    """Promote a fresher BENCH_DETAILS.json.partial to
    BENCH_PARTIAL_LATEST.json — the committed partial-capture artifact
    ``_emit_skipped`` prefers over the stale clean run.  Owns the WHOLE
    promotion contract in one place (filenames, ``captured_at``
    freshness, platform/config-shape guards) so the watcher can't drift
    from the bench; called by scripts/tpu_watch.sh after an incomplete
    capture.  Atomic replace; a missing/corrupt destination counts as
    age 0 (self-healing).  Returns a one-line outcome for the watcher's
    log."""
    src = _repo_path("BENCH_DETAILS.json.partial")
    dst = _repo_path("BENCH_PARTIAL_LATEST.json")
    if not os.path.exists(src):
        return "promotion: no capture partial present"
    try:
        with open(src) as f:
            new = json.load(f)
    except Exception as e:
        return f"promotion: partial unreadable ({e})"
    if new.get("platform") in (None, "cpu") or not any(
            c.get("rounds_per_s")
            for c in new.get("configs", {}).values()):
        return "promotion: partial has no on-chip measurements; skipped"
    if new.get("timing_untrusted"):
        return "promotion: partial is marked timing_untrusted; refused"
    if _max_mfu(new) > 1.0:
        return (f"promotion: partial reports mfu {_max_mfu(new):.2f} > 1.0 "
                "— physically impossible, timing untrusted; refused")
    old_ts = 0.0
    try:
        with open(dst) as f:
            old_ts = float(json.load(f).get("captured_at", 0.0))
    except Exception:
        pass  # missing or corrupt dst self-heals: treat as age 0
    if float(new.get("captured_at", 0.0)) <= old_ts:
        return "promotion: committed partial is at least as fresh; kept"
    tmp = dst + ".tmp"
    with open(tmp, "w") as f:
        json.dump(new, f, indent=2)
    os.replace(tmp, dst)
    return "promotion: partial -> BENCH_PARTIAL_LATEST.json"


def main():
    if not os.environ.get("BENCH_PLATFORM") and not _backend_alive():
        _emit_skipped()
        return
    if os.environ.get("BENCH_PLATFORM"):
        import jax
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import jax

    # persistent compilation cache (the CLI's helper; gates itself on the
    # resolved backend): keeps TPU bench reruns inside the driver budget —
    # warm compiles don't change any measured number (warmup dispatch is
    # excluded from timing loops)
    from fedml_tpu.experiments.main import enable_compile_cache
    enable_compile_cache()

    _start_watchdog()
    _beat("backend attach")
    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    if on_cpu:
        # explicit BENCH_PLATFORM=cpu developer run: shrink so it terminates
        # (a CNN round is ~7-14 s on CPU) — results go to
        # BENCH_DETAILS_cpu.json, never over the TPU artifact
        os.environ.setdefault("BENCH_FEMNIST_SAMPLES", "20")
        os.environ.setdefault("BENCH_SCALING", "0")
    global PEAK_TFLOPS
    PEAK_TFLOPS = _peak_for_device(dev)

    rounds = int(os.environ.get("BENCH_ROUNDS", "20"))
    full = os.environ.get("BENCH_MODE", "quick") == "full"
    details = {"platform": dev.platform,
               "captured_at": time.time(),  # freshness key (_emit_skipped)
               "device_kind": str(getattr(dev, "device_kind", "unknown")),
               "n_devices": len(jax.devices()),
               "peak_tflops_assumed": PEAK_TFLOPS,
               "femnist_samples_per_client": int(os.environ.get(
                   "BENCH_FEMNIST_SAMPLES", "200")),
               "flops_accounting": (
                   "twin-program extrapolation (_honest_flops): scan "
                   "bodies counted per trip, LSTM recurrence unrolled in "
                   "the cost twin"),
               "configs": {}}
    out_name = os.environ.get(
        "BENCH_OUT",
        "BENCH_DETAILS_cpu.json" if on_cpu else "BENCH_DETAILS.json")
    _WATCH.update(details=details, out=out_name)

    # 0) torch CPU baseline FIRST (needs no accelerator; measuring it
    # before any TPU RPC means a mid-run wedge still yields vs_baseline)
    _beat("torch baseline")
    torch_s = bench_torch_baseline()
    _WATCH["torch_s"] = torch_s
    details["torch_cpu_sequential_round_s"] = torch_s

    # 0a/0b) timing trust gate FIRST (round-4 verdict item 1): linearity +
    # readback-sync + checksum, then the matmul-peak plausibility cap.  A
    # failed gate quarantines the whole run — without it, a
    # non-synchronizing block_until_ready turns every number below into
    # dispatch-rate fiction (the round-4 MFU-3.08 artifact).  The peak
    # measurement doubles as the empirical MFU denominator floor: a plain
    # matmul bounds the real chip peak from below, so when it exceeds the
    # device_kind table value (untrustworthy through the tunnel), MFU is
    # quoted against it.
    sanity, mm, gate_failures = run_timing_gate(on_cpu)
    details["timing_sanity"] = sanity
    peak_src = ("BENCH_PEAK_TFLOPS env override"
                if os.environ.get("BENCH_PEAK_TFLOPS")
                else "device_kind table")
    if mm is not None:
        details["measured_matmul_tflops"] = mm
    if gate_failures:
        _quarantine("; ".join(gate_failures))
    if mm is not None:
        # an explicit BENCH_PEAK_TFLOPS pins the MFU denominator; only the
        # untrusted device_kind table value gets raised by measurement
        if (mm["bf16"] > PEAK_TFLOPS
                and not os.environ.get("BENCH_PEAK_TFLOPS")):
            PEAK_TFLOPS = mm["bf16"]
            peak_src = ("measured bf16 matmul throughput (exceeds the "
                        "device_kind table peak — kind string untrusted)")
    details["peak_tflops_used"] = PEAK_TFLOPS
    details["peak_tflops_source"] = peak_src
    # which backend compiled the FLOPs cost twins (advisor r4: record it so
    # a backend-dependent cost-analysis divergence is attributable)
    details["twin_backend"] = (
        "cpu" if os.environ.get("BENCH_TWIN_DEVICE", "cpu") == "cpu"
        else dev.platform)

    # 1) cross-device headline
    _beat("femnist_cnn_c10 (honest-FLOPs twins + device rounds)")
    round_s, flops, steps = bench_femnist_cnn(rounds)
    details["configs"]["femnist_cnn_c10"] = {
        "round_s": round_s, "rounds_per_s": 1.0 / round_s,
        "steps_per_round": steps,
        "flops_per_round": flops, "mfu": _mfu(flops, round_s)}

    # 1b) dispatch-amortised headline (scan K rounds per dispatch);
    # identical hyperparameters to 1), so per-round FLOPs are shared
    _checkpoint_partial()
    _beat("femnist_cnn_c10_scan20")
    scan_round_s = bench_femnist_cnn_scanned(
        4 if on_cpu else max(rounds, 20), k=2 if on_cpu else 20)
    details["configs"]["femnist_cnn_c10_scan20"] = {
        "round_s": scan_round_s, "rounds_per_s": 1.0 / scan_round_s,
        "steps_per_round": steps,
        "flops_per_round": flops, "mfu": _mfu(flops, scan_round_s)}

    # 1c) twin backend cross-check (advisor r4): femnist twins compiled on
    # the device backend vs the CPU twins the headline used — small
    # compiles, and running AFTER the headline means a wedge here cannot
    # lose the measured configs
    _checkpoint_partial()
    _beat("twin backend cross-check (femnist twins on device)")
    if not on_cpu and os.environ.get("BENCH_TWIN_XCHECK", "1") != "0":
        details["twin_backend_delta"] = bench_twin_backend_delta(flops)

    # 2) NLP family: shakespeare char-LM (skipped on explicit-CPU runs).
    # Config ORDER from here on is by compile risk, not importance: the
    # tunnel's observed failure mode is wedging on heavy FRESH compile
    # RPCs, so small-program configs (rnn/robust/scaling) run first and
    # the big fresh compiles (resnet56, transformer) run LAST — a short
    # alive-window still yields a full partial of everything light.
    _checkpoint_partial()
    _beat("shakespeare_rnn_c10_b4")
    if not on_cpu:
        rnn_s, rnn_fl, rnn_steps = bench_shakespeare_rnn(
            max(3, rounds // 4))
        details["configs"]["shakespeare_rnn_c10_b4"] = {
            "round_s": rnn_s, "rounds_per_s": 1.0 / rnn_s,
            "steps_per_round": rnn_steps,
            "flops_per_round": rnn_fl, "mfu": _mfu(rnn_fl, rnn_s)}

    # 2c) defended aggregation: XLA transform hook vs fused Pallas kernel
    # (skipped on CPU: the interpreter path is not a perf number)
    _checkpoint_partial()
    _beat("fedavg_robust_weakdp_c10")
    if not on_cpu:
        rb = bench_robust_backends(max(3, rounds // 4))
        details["configs"]["fedavg_robust_weakdp_c10"] = {
            "round_s_xla": rb["xla"], "round_s_pallas": rb["pallas"],
            "pallas_speedup": rb["xla"] / rb["pallas"]}

    # 2d) pallas kernels at flagship size in bf16 (round-4 verdict item 6:
    # measure, then justify or demote) — aggregation-only programs, cheap
    # compiles, so they stay in the light-compile block
    _checkpoint_partial()
    _beat("pallas_kernels_flagship (r56-size agg + secagg mask)")
    if not on_cpu:
        details["configs"]["pallas_kernels_flagship"] = \
            bench_agg_kernels_flagship()

    # 3) cohort scaling curve (FLOPs scale linearly from the c=10 twins)
    _checkpoint_partial()
    if os.environ.get("BENCH_SCALING", "1") != "0":
        curve = {}
        details["cohort_scaling"] = curve
        for c in (10, 32, 64, 128):
            _beat(f"cohort_scaling c={c}")
            rs, fl, _ = bench_femnist_cnn(max(3, rounds // 4),
                                          clients_per_round=c,
                                          flops_base=(flops, steps, 10))
            curve[str(c)] = {"rounds_per_s": 1.0 / rs,
                             "mfu": _mfu(fl, rs)}
            _checkpoint_partial()

    # 4) flagship cross-silo — the FIRST heavy fresh compile (skipped on
    # explicit-CPU runs: resnet56 training steps take tens of seconds per
    # round there)
    _checkpoint_partial()
    _beat("resnet56_cifar10_c10_b64")
    if not on_cpu:
        r56_rounds = max(3, rounds // 4)
        samples = int(os.environ.get("BENCH_R56_SAMPLES",
                                     "5000" if full else "512"))
        round_s56, flops56, steps56, spread56 = bench_resnet56_cifar10(
            r56_rounds, samples=samples)
        cfg56 = {
            "round_s": round_s56, "samples_per_client": samples,
            "steps_per_round": steps56,
            # per vmapped step (10 clients' B=64 batches advance together)
            "step_time_ms": 1e3 * round_s56 / max(steps56, 1),
            "flops_per_round": flops56, "mfu": _mfu(flops56, round_s56)}
        if spread56 is not None:
            # per-round blocked medians pin the tunnel-jitter question: a
            # tight p10..p90 around the median with a fat max = host/tunnel
            # spikes, not a real level shift (round-2 "2x variance" item)
            cfg56["round_s_spread"] = spread56
            cfg56["step_time_ms_median"] = (
                1e3 * spread56["median"] / max(steps56, 1))
        details["configs"]["resnet56_cifar10_c10_b64"] = cfg56
    else:
        details["configs"]["resnet56_cifar10_c10_b64"] = {"mfu": 0.0,
                                                          "skipped": "cpu"}

    # 5) long-context transformer grad step (blockwise kv scan; the
    # reference has no comparable capability) — more heavy fresh
    # compiles, so it stays behind resnet56.  CPU: skipped.  The
    # flash/moe variants only run in BENCH_MODE=full (each a second
    # multi-minute XLA compile on the tunnel-attached chip).
    _checkpoint_partial()
    _beat("transformer_T2048_blockwise")
    if not on_cpu:
        lc_s, lc_tok = bench_longcontext_transformer()
        details["configs"]["transformer_T2048_blockwise"] = {
            "step_s": lc_s, "tokens_per_s": lc_tok}
        if full:
            # each variant is its own multi-minute XLA compile — separate
            # heartbeats so a slow-but-live compile isn't called a wedge
            _checkpoint_partial()
            _beat("transformer_T2048_flash")
            try:
                fl_s, fl_tok = bench_longcontext_transformer(use_flash=True)
                details["configs"]["transformer_T2048_flash"] = {
                    "step_s": fl_s, "tokens_per_s": fl_tok}
            except Exception as e:  # pallas kernel unavailable here
                details["configs"]["transformer_T2048_flash"] = {
                    "skipped": str(e)[:120]}
            # routed-FFN capability point: the SAME T=2048 config with a
            # Switch MoE FFN (8 experts) — directly comparable tokens/s
            # against transformer_T2048_blockwise (grouped routing keeps
            # dispatch linear in T)
            _checkpoint_partial()
            _beat("transformer_T2048_moe8")
            moe_s, moe_tok = bench_longcontext_transformer(moe_experts=8)
            details["configs"]["transformer_T2048_moe8"] = {
                "step_s": moe_s, "tokens_per_s": moe_tok}

    # 6) multi-device (skipped on 1-chip hosts)
    _beat("multi-device mesh")
    if len(jax.devices()) >= 2:
        from fedml_tpu.parallel.mesh import make_mesh
        n = len(jax.devices())
        mesh = make_mesh(client_axis=n)
        rs, fl, _ = bench_femnist_cnn(max(3, rounds // 4),
                                      clients_per_round=max(16, n),
                                      mesh=mesh,
                                      flops_base=(flops, steps, 10))
        details["configs"][f"femnist_cnn_mesh{n}"] = {
            "rounds_per_s": 1.0 / rs, "mfu": _mfu(fl, rs)}

    # sanity: MFU needs achieved-flops <= peak; XLA cost_analysis can
    # overcount (it models the unfused HLO), so flag near/over-peak values
    # instead of reporting them as utilization
    suspect = []
    for name, c in list(details["configs"].items()) + [
            (f"scaling_{k}", v)
            for k, v in details.get("cohort_scaling", {}).items()]:
        if c.get("mfu", 0.0) > 0.95:
            suspect.append(name)
    if suspect:
        details["mfu_warning"] = (
            "mfu > 0.95 for " + ", ".join(suspect) + " — XLA cost-analysis "
            "flops likely overcount vs the fused executable; treat these "
            "as upper bounds, trust round_s/step_time_ms")

    # primary line.  Explicit-CPU runs write a separate details file so the
    # committed TPU artifact is never clobbered (verify-skill
    # artifact-hygiene rule); their vs_baseline is still honest — torch CPU
    # vs jax CPU on the same host is a same-platform comparison.  (The
    # torch baseline itself was measured FIRST, before any TPU RPC.)
    details["vs_baseline_meaning"] = (
        "ratio vs the reference's SEQUENTIAL standalone simulator loop "
        "(fedavg_api.py:52-66) in torch on THIS HOST'S CPU — an "
        "architectural comparison (one-program cohort vs per-client "
        "Python loop), NOT a GPU-hardware claim; the 8xV100 wall-clock "
        "north star (BASELINE.md) remains unmeasured from both sides")
    # hard promotion contract (round-4 verdict item 1): an artifact whose
    # best MFU exceeds 1.0 documents a timing failure and must never reach
    # a committed name — quarantine it instead (exit 3 => capture retried)
    if _max_mfu(details) > 1.0:
        _quarantine(
            f"max mfu {_max_mfu(details):.2f} > 1.0 — achieved FLOP/s "
            "above the measured peak is physically impossible")
    with open(_repo_path(out_name), "w") as f:
        json.dump(details, f, indent=2)
    try:  # clean run: the incremental checkpoint is superseded
        os.remove(_repo_path(out_name + ".partial"))
    except OSError:
        pass
    if out_name == "BENCH_DETAILS.json" and not on_cpu:
        # a clean full TPU artifact supersedes any committed partial
        # capture (else _emit_skipped would keep preferring older partials)
        try:
            os.remove(_repo_path("BENCH_PARTIAL_LATEST.json"))
        except OSError:
            pass
    best_round_s = min(round_s, scan_round_s)
    line = {
        "metric": "fedavg_round_time_femnist_cnn",
        "value": round(1.0 / best_round_s, 3),
        "unit": "rounds/sec",
        "platform": details["platform"],
        "device_kind": details["device_kind"],
        "vs_baseline": round((torch_s or best_round_s) / best_round_s, 3),
        "rounds_per_s_dispatch": round(1.0 / round_s, 3),
        "rounds_per_s_scan20": round(1.0 / scan_round_s, 3),
        "mfu_femnist": round(details["configs"]["femnist_cnn_c10"]["mfu"], 4),
        "mfu_resnet56": round(
            details["configs"]["resnet56_cifar10_c10_b64"]["mfu"], 4),
    }
    if on_cpu:
        line["note"] = ("explicit BENCH_PLATFORM=cpu run; vs_baseline is a "
                        "same-host torch-vs-jax CPU comparison, not a TPU "
                        "number")
    print(json.dumps(line))


if __name__ == "__main__":
    main()
