"""Wire compression for cross-silo uploads (comm/compress.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.comm.compress import (compress_update, decompress_update,
                                     wire_bytes)
from fedml_tpu.comm.message import Message


def _delta_tree(rng):
    return {"dense": {"kernel": rng.randn(64, 32).astype(np.float32),
                      "bias": rng.randn(32).astype(np.float32)},
            "emb": rng.randn(128, 16).astype(np.float32),
            "step": np.int32(3)}  # small/int leaf: carried dense


def test_none_roundtrip_exact(rng):
    tree = _delta_tree(rng)
    out = decompress_update(compress_update(tree, "none"), tree)
    jax.tree.map(np.testing.assert_array_equal, tree, out)


def test_topk_keeps_largest_and_shrinks(rng):
    tree = _delta_tree(rng)
    payload = compress_update(tree, "topk", topk_frac=0.1)
    out = decompress_update(payload, tree)
    # reconstruction is exact at the kept entries, zero elsewhere
    for key in ("kernel", "bias"):
        a = tree["dense"][key].reshape(-1)
        b = np.asarray(out["dense"][key]).reshape(-1)
        kept = b != 0
        np.testing.assert_array_equal(b[kept], a[kept])
        k = max(1, round(0.1 * a.size))
        assert kept.sum() <= k
        # the kept entries are the k largest by |.|
        thresh = np.sort(np.abs(a))[-k]
        assert np.all(np.abs(a[kept]) >= thresh - 1e-12)
    # ~10x smaller on the wire (idx+val vs dense), int leaf still exact
    assert wire_bytes(payload) < 0.3 * wire_bytes({"t": tree})
    assert out["step"] == tree["step"]


def test_int8_error_bound(rng):
    tree = _delta_tree(rng)
    out = decompress_update(compress_update(tree, "int8"), tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype == np.int32:
            np.testing.assert_array_equal(a, b)
            continue
        scale = np.max(np.abs(a)) / 127.0
        assert np.max(np.abs(a - b)) <= scale / 2 + 1e-7


def test_payload_rides_message_codec(rng):
    """Compressed payloads are pytrees of arrays — they must survive the
    binary wire codec unchanged."""
    tree = _delta_tree(rng)
    payload = compress_update(tree, "topk", topk_frac=0.2)
    msg = Message(1, 1, 0).add("p", payload)
    got = Message.from_bytes(msg.to_bytes()).get("p")
    out = decompress_update(got, tree)
    ref = decompress_update(payload, tree)
    jax.tree.map(np.testing.assert_array_equal, ref, out)


def test_structure_mismatch_fails_loudly(rng):
    tree = _delta_tree(rng)
    payload = compress_update(tree, "int8")
    with pytest.raises(ValueError, match="does not match"):
        decompress_update(payload, {"other": tree["emb"]})


def test_server_detects_scheme_mismatch(rng):
    """Both mismatch directions fail loudly at the receive boundary, not
    deep inside aggregation."""
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor, MsgType)
    from fedml_tpu.comm.local import LocalHub

    tree = {"w": rng.randn(8).astype(np.float32)}

    def train_fn(params, client_idx, round_idx):
        return params, 1

    # silo compressed, server plain
    hub = LocalHub()
    server = FedAvgServerActor(hub.transport(0), tree, 1, 1, 1)
    silo = FedAvgClientActor(
        1, hub.transport(1), train_fn,
        encode_upload=lambda new, g: compress_update(new, "int8"))
    server.register_handlers()
    silo.register_handlers()
    server.start()
    with pytest.raises(ValueError, match="server has no"):
        hub.pump()

    # server compressed, silo plain
    hub2 = LocalHub()
    server2 = FedAvgServerActor(
        hub2.transport(0), tree, 1, 1, 1,
        decode_upload=lambda p, g: decompress_update(p, g))
    silo2 = FedAvgClientActor(1, hub2.transport(1), train_fn)
    server2.register_handlers()
    silo2.register_handlers()
    server2.start()
    with pytest.raises(ValueError, match="sent plain parameters"):
        hub2.pump()


def test_unknown_scheme():
    with pytest.raises(ValueError, match="unknown compression scheme"):
        compress_update({}, "gzip")


def test_error_feedback_recovers_aggressive_topk():
    """EF-SGD property: at an aggressive top-k fraction over multiple
    rounds, carrying the dropped residual forward must track the
    uncompressed run more closely than plain top-k (same seeds)."""
    from fedml_tpu.experiments.main import main
    argv = ["--algo", "cross_silo", "--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "4", "--client_num_per_round", "4",
            "--comm_round", "8", "--frequency_of_the_test", "7",
            "--batch_size", "16", "--epochs", "1", "--lr", "0.1",
            "--log_stdout", "false"]
    plain = main(argv)
    topk = ["--wire_compression", "topk", "--topk_frac", "0.02"]
    noef = main(argv + topk)
    ef = main(argv + topk + ["--error_feedback", "true"])
    gap_noef = abs(noef["train_loss"] - plain["train_loss"])
    gap_ef = abs(ef["train_loss"] - plain["train_loss"])
    assert gap_ef < gap_noef, (gap_ef, gap_noef)


@pytest.mark.parametrize("scheme", ["int8", "topk"])
def test_nonfinite_update_fails_loudly(rng, scheme):
    """A NaN/Inf leaf must raise, not silently quantize to garbage (int8's
    scale goes non-finite; topk argpartitions over NaN)."""
    bad = {"w": np.asarray(rng.randn(64), np.float32)}
    bad["w"][7] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        compress_update(bad, scheme)
    bad["w"][7] = np.inf
    with pytest.raises(ValueError, match="non-finite"):
        compress_update(bad, scheme)


def test_error_feedback_drop_carries_full_delta(rng):
    """Ack-aware EF (round-2 advisor): a dropped upload must carry its
    FULL delta into the next round's residual; an accepted upload carries
    only delta - sent; no ack field (legacy server) behaves as accepted."""
    from fedml_tpu.comm.compress import ErrorFeedback
    ef = ErrorFeedback()
    delta = {"w": np.asarray(rng.randn(64), np.float32)}

    def one_round(silo, accepted):
        d = ef.apply(silo, delta)
        payload = compress_update(d, "topk", topk_frac=0.1)
        sent = decompress_update(payload, d)
        ef.record(silo, d, sent)
        ef.resolve(silo, accepted)
        return d, sent

    # accepted: residual = delta - sent (the classic EF update)
    d, sent = one_round(1, np.asarray([1, 2], np.int32))
    np.testing.assert_allclose(ef._residual[1]["w"], d["w"] - sent["w"])
    # dropped: the FULL augmented delta carries forward
    d2, _ = one_round(1, np.asarray([2], np.int32))
    np.testing.assert_allclose(ef._residual[1]["w"], d2["w"])
    # and the next round's delta starts from it
    np.testing.assert_allclose(ef.apply(1, delta)["w"], delta["w"] + d2["w"])
    # legacy server (no ack field): assume accepted
    d3, sent3 = one_round(1, None)
    np.testing.assert_allclose(ef._residual[1]["w"], d3["w"] - sent3["w"])
    # resolve without a pending record is a no-op
    ef.resolve(99, np.asarray([1], np.int32))


@pytest.mark.parametrize("scheme", ["int8", "topk"])
def test_cli_cross_silo_with_compression(scheme):
    """End-to-end: compressed-upload federation still learns (loss finite,
    close to the uncompressed run for one full-batch round)."""
    from fedml_tpu.experiments.main import main
    argv = ["--algo", "cross_silo", "--model", "lr", "--dataset", "mnist",
            "--client_num_in_total", "4", "--client_num_per_round", "4",
            "--comm_round", "1", "--frequency_of_the_test", "1",
            "--batch_size", "64", "--epochs", "1", "--log_stdout", "false"]
    plain = main(argv)
    comp = main(argv + ["--wire_compression", scheme,
                        "--topk_frac", "0.5"])
    assert np.isfinite(comp["train_loss"])
    # int8 quantizes a small delta: accuracies should be near-identical;
    # topk at 50% keeps the dominant directions
    assert abs(comp["train_acc"] - plain["train_acc"]) < 0.15
    # observability: compressed runs report received upload bytes
    assert comp["upload_bytes"] > 0
    assert "upload_bytes" not in plain
