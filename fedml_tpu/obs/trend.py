"""Perf trend gate over flight-recorder ledgers + the timing-trust lint
(CLI: ``scripts/perf_trend.py``).

Three checks, each CI-usable (non-zero exit on failure, every verdict
names the phase/artifact that tripped it):

* **phase regression** — per-phase medians of the current ``perf.jsonl``
  vs a baseline ledger; a phase beyond ``noise_frac`` AND ``min_abs_s``
  (both must trip — a 2ms phase doubling is noise, a 2s phase doubling
  is not) is a named regression.
* **recompile gate** — any ledger round after the first with
  ``recompiles > 0`` fails: the flight recorder's sentry counted a hot
  function retracing (the PR 5 double-compile class).
* **mfu lint** — every mfu value in every given JSON artifact must be
  ≤ 1.0 *or explicitly retracted* (a ``timing_untrusted`` mark on the
  artifact, or an ``mfu_retracted`` key beside the offending cell).
  The BENCH_DETAILS mfu-1.57 retraction becomes an automatic check,
  not an archaeology finding.
* **health ledger schema** (``--health_ledger``) — the learning-health
  ledger (`obs/health.py`) must carry round/upload accounting, norm
  moments, alignment, and alarm verdicts on every line; a malformed
  ledger fails HERE, not in the reader that trusts it later.

``max_mfu`` here is the single source of truth for "largest MFU
anywhere in an artifact" (recursive — nested scaling curves included);
``bench._max_mfu`` delegates to it, so the promotion/carry refusal
contract and this lint can never disagree about what an artifact
claims.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import statistics
from typing import Dict, Iterator, List, Optional, Tuple

# markers that make an mfu > 1.0 value an acknowledged retraction
# instead of a lint violation: artifact-level timing_untrusted (the
# bench quarantine path writes it), or a sibling mfu_retracted note on
# the offending cell/any enclosing dict
RETRACTION_KEYS = ("timing_untrusted", "mfu_retracted")


# ---------------------------------------------------------------------------
# mfu lint
# ---------------------------------------------------------------------------

def iter_mfu(obj, path: str = "",
             retracted: bool = False) -> Iterator[Tuple[str, float, bool]]:
    """Yield ``(json_path, value, retracted)`` for every numeric ``mfu``
    key anywhere in ``obj``.  ``retracted`` is sticky downward: a
    retraction marker on any enclosing dict covers its whole subtree."""
    if isinstance(obj, dict):
        here = retracted or any(obj.get(k) for k in RETRACTION_KEYS)
        for k, v in obj.items():
            if k == "mfu" and isinstance(v, (int, float)):
                yield f"{path}/mfu", float(v), here
            else:
                yield from iter_mfu(v, f"{path}/{k}", here)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            yield from iter_mfu(v, f"{path}[{i}]", retracted)


def max_mfu(details) -> float:
    """Largest MFU anywhere in an artifact (recursive; retraction
    markers do NOT hide values here — an artifact carrying an impossible
    number stays refusable as evidence even after it owns up to it)."""
    return max((v for _, v, _ in iter_mfu(details)), default=0.0)


def lint_mfu_artifacts(paths: List[str]) -> List[str]:
    """Violations: unreadable artifacts and unretracted mfu > 1.0 cells.
    Empty list == lint green."""
    violations: List[str] = []
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            violations.append(f"{path}: unreadable ({e})")
            continue
        for jpath, value, retracted in iter_mfu(data):
            if value > 1.0 and not retracted:
                violations.append(
                    f"{path}:{jpath} = {value:.3g} > 1.0 — physically "
                    f"impossible and not marked retracted (add "
                    f"timing_untrusted or mfu_retracted, or re-capture)")
    return violations


# ---------------------------------------------------------------------------
# ledger loading + phase statistics
# ---------------------------------------------------------------------------

def load_ledger(path: str) -> List[dict]:
    """Read a ``perf.jsonl`` ledger; a torn final line (crashed run) is
    skipped, any other malformed line fails loudly."""
    rows: List[dict] = []
    with open(path) as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                continue  # torn tail of a crashed run
            raise ValueError(f"{path}:{i + 1}: malformed ledger line")
    return rows


def validate_ledger(rows: List[dict]) -> List[str]:
    """Schema check: every line carries round/phases/recompiles (and an
    RSS watermark where the platform provides one)."""
    problems = []
    if not rows:
        return ["ledger is empty"]
    for i, row in enumerate(rows):
        for key in ("round", "phases", "recompiles", "wire"):
            if key not in row:
                problems.append(f"line {i + 1}: missing {key!r}")
        if "rss" in row and row["rss"] is not None \
                and "peak_bytes" not in row["rss"]:
            problems.append(f"line {i + 1}: rss without peak_bytes")
    return problems


def validate_health_ledger(rows: List[dict]) -> List[str]:
    """Schema check for ``health.jsonl`` (obs/health.py): every line
    carries the round/upload accounting, the Welford norm summary, the
    alignment summary, and the alarm verdicts — so a malformed ledger
    fails the GATE, never the reader that trusts it later.  (Torn tails
    are `load_ledger`'s job; edge-actor summaries riding inside frames
    are never ledgered directly and are not validated here.)"""
    problems = []
    if not rows:
        return ["health ledger is empty"]
    for i, row in enumerate(rows):
        for key in ("round", "uploads", "accepted", "rejected", "norm",
                    "alignment", "alarms", "silos"):
            if key not in row:
                problems.append(f"line {i + 1}: missing {key!r}")
        norm = row.get("norm")
        if isinstance(norm, dict):
            for key in ("count", "mean", "std", "min", "max"):
                if key not in norm:
                    problems.append(f"line {i + 1}: norm without {key!r}")
        elif "norm" in row:
            problems.append(f"line {i + 1}: norm is not a summary dict")
        alarms = row.get("alarms")
        if isinstance(alarms, dict):
            for name, v in alarms.items():
                if not isinstance(v, dict) or "ok" not in v \
                        or "threshold" not in v:
                    problems.append(f"line {i + 1}: alarm {name!r} without "
                                    f"ok/threshold verdict")
        elif "alarms" in row:
            problems.append(f"line {i + 1}: alarms is not a verdict dict")
        acc = row.get("accepted")
        ups = row.get("uploads")
        if isinstance(acc, int) and isinstance(ups, int) and acc > ups:
            problems.append(f"line {i + 1}: accepted {acc} > uploads {ups}")
    return problems


def phase_medians(rows: List[dict],
                  skip_first: bool = True) -> Dict[str, float]:
    """Median per-phase seconds across the ledger (plus ``round_s``).
    The first round is skipped by default: it pays the jit compiles and
    would poison both sides of a comparison — even (especially) when it
    is the ONLY round, since a one-round smoke gated against a
    steady-state baseline would read its compile cost as a regression.
    A single-round ledger therefore yields no medians."""
    if skip_first:
        rows = rows[1:]
    acc: Dict[str, List[float]] = {}
    for row in rows:
        for name, dt in (row.get("phases") or {}).items():
            acc.setdefault(name, []).append(float(dt))
        if row.get("round_s") is not None:
            acc.setdefault("round_s", []).append(float(row["round_s"]))
    return {name: statistics.median(vals) for name, vals in acc.items()}


def check_recompiles(rows: List[dict]) -> List[str]:
    """Rounds after the ledger's first line with recompiles > 0."""
    return [f"round {row.get('round')}: {row['recompiles']} recompile(s) "
            f"after the baseline round "
            f"({row.get('recompiled', {})})"
            for row in rows[1:] if row.get("recompiles")]


def compare_ledgers(current: List[dict], baseline: List[dict],
                    noise_frac: float = 0.25,
                    min_abs_s: float = 0.005) -> List[dict]:
    """Per-phase regressions of ``current`` vs ``baseline`` medians.
    A phase regresses when it exceeds the baseline by BOTH the relative
    noise band and the absolute floor."""
    cur = phase_medians(current)
    base = phase_medians(baseline)
    out = []
    for name in sorted(base):
        b, c = base[name], cur.get(name)
        if c is None:
            continue  # phase absent this run (e.g. checkpointing off)
        if c > b * (1.0 + noise_frac) and (c - b) > min_abs_s:
            out.append({"phase": name, "baseline_s": b, "current_s": c,
                        "ratio": (c / b) if b else float("inf")})
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _expand(patterns: List[str]) -> List[str]:
    paths: List[str] = []
    for pat in patterns:
        # a pattern matching nothing passes through verbatim — the lint
        # then reports it unreadable, loudly
        paths.extend(sorted(_glob.glob(pat)) or [pat])
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_trend",
        description="Perf regression gate over flight-recorder ledgers "
                    "(+ the mfu<=1.0 timing-trust lint). Exit 0 = pass, "
                    "1 = regression/lint failure, 2 = missing inputs.")
    p.add_argument("--ledger", default=None,
                   help="current run's perf.jsonl")
    p.add_argument("--baseline", default=None,
                   help="baseline perf.jsonl to gate against (optional: "
                        "without it only schema + recompile checks run)")
    p.add_argument("--noise", type=float, default=0.25,
                   help="relative noise band a phase must exceed to count "
                        "as a regression (default 0.25 = +25%%)")
    p.add_argument("--min_abs_ms", type=float, default=5.0,
                   help="absolute floor (ms) a regression must also exceed")
    p.add_argument("--lint_mfu", nargs="*", default=None, metavar="GLOB",
                   help="JSON artifacts (globs ok) to lint for "
                        "unretracted mfu > 1.0")
    p.add_argument("--no_recompile_gate", action="store_true",
                   help="skip the recompiles-after-round-0 gate")
    p.add_argument("--health_ledger", default=None,
                   help="health.jsonl to schema-validate (obs/health.py): "
                        "a malformed health ledger fails the gate, not "
                        "the reader that trusts it later")
    args = p.parse_args(argv)
    if args.ledger is None and not args.lint_mfu \
            and args.health_ledger is None:
        p.print_usage()
        print("perf_trend: nothing to do (pass --ledger, --health_ledger "
              "and/or --lint_mfu)")
        return 2

    failures: List[str] = []

    if args.ledger is not None:
        try:
            rows = load_ledger(args.ledger)
        except (OSError, ValueError) as e:
            print(f"perf_trend: cannot read ledger: {e}")
            return 2
        problems = validate_ledger(rows)
        failures += [f"ledger schema: {x}" for x in problems]
        if not problems:
            print(f"ledger: {len(rows)} rounds, phases "
                  f"{sorted({k for r in rows for k in r['phases']})}")
        if not args.no_recompile_gate:
            failures += [f"recompile gate: {x}"
                         for x in check_recompiles(rows)]
        if args.baseline is not None:
            try:
                base = load_ledger(args.baseline)
            except (OSError, ValueError) as e:
                print(f"perf_trend: cannot read baseline: {e}")
                return 2
            if len(rows) < 2:
                # the only round pays the jit compiles; gating it against
                # a steady-state baseline would flag compile cost as a
                # regression — say so instead of a hollow "no regression"
                print("phase gate: ledger has no steady-state rounds "
                      "after the compile-paying first round — nothing "
                      "to compare (run >= 2 rounds for a gateable "
                      "ledger)")
            else:
                regressions = compare_ledgers(
                    rows, base, noise_frac=args.noise,
                    min_abs_s=args.min_abs_ms / 1e3)
                for r in regressions:
                    failures.append(
                        f"phase regression: {r['phase']} "
                        f"{r['baseline_s'] * 1e3:.1f}ms -> "
                        f"{r['current_s'] * 1e3:.1f}ms "
                        f"({r['ratio']:.2f}x, band +{args.noise:.0%})")
                if not regressions:
                    print(f"phase gate: no regression vs {args.baseline} "
                          f"(band +{args.noise:.0%}, floor "
                          f"{args.min_abs_ms:.1f}ms)")

    if args.health_ledger is not None:
        try:
            health_rows = load_ledger(args.health_ledger)
        except (OSError, ValueError) as e:
            print(f"perf_trend: cannot read health ledger: {e}")
            return 2
        problems = validate_health_ledger(health_rows)
        failures += [f"health ledger schema: {x}" for x in problems]
        if not problems:
            alarms = sum(1 for r in health_rows
                         for v in (r.get("alarms") or {}).values()
                         if not v.get("ok"))
            print(f"health ledger: {len(health_rows)} rounds, schema OK, "
                  f"{alarms} alarm verdict(s) fired")

    if args.lint_mfu:
        paths = _expand(args.lint_mfu)
        violations = lint_mfu_artifacts(paths)
        failures += [f"mfu lint: {v}" for v in violations]
        if not violations:
            print(f"mfu lint: {len(paths)} artifact(s) green "
                  f"(every mfu <= 1.0 or explicitly retracted)")

    if failures:
        for f_ in failures:
            print(f"FAIL {f_}")
        print(f"perf_trend: {len(failures)} failure(s)")
        return 1
    print("perf_trend: PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
